"""Benchmark suite: one JSON line per BASELINE.json config.

The headline driver benchmark stays in bench.py (single JSON line); this
suite stands up the five configs BASELINE.json names so throughput AND
accuracy claims are reproducible on real hardware:

  1 exact     single-ruleset exact hit-count throughput + oracle equality
  2 cms       exact -> count-min sketch width x depth sweep (error, recall)
  3 hll       per-rule unique-source HLL relative error
  4 multifw   multi-firewall batched match: flat vs stacked (vmap) paths
  5 topk      streaming top-K talkers precision vs exact

Run all: ``python bench_suite.py``; one: ``python bench_suite.py cms``.
Each config prints exactly one JSON line on stdout.
"""

from __future__ import annotations

import functools
import json
import sys
import time

import numpy as np


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def _setup(n_acls=4, rules_per_acl=64, seed=0, firewalls=1):
    from ruleset_analysis_tpu.hostside import aclparse, pack, synth

    rulesets = [
        aclparse.parse_asa_config(
            synth.synth_config(n_acls=n_acls, rules_per_acl=rules_per_acl, seed=seed + i),
            f"fw{i}",
        )
        for i in range(firewalls)
    ]
    return pack.pack_rulesets(rulesets)


def _tuples(packed, n, seed=0):
    from ruleset_analysis_tpu.hostside import synth

    return synth.synth_tuples(packed, n, seed=seed)


def _time_steps(step, state, rules, feeds, iters, valid_per_feed):
    """Counts-validated timed loop (shared sync discipline with bench.py)."""
    from ruleset_analysis_tpu.runtime.timing import timed_validated_steps

    state, _ = step(state, rules, feeds[0])  # warmup/compile
    state, dt, delta, expect = timed_validated_steps(
        step, state, rules, feeds, valid_per_feed, iters
    )
    if delta != expect:
        raise AssertionError(
            f"timed window did not execute: counts moved {delta}, expected {expect}"
        )
    return state, dt


# ---------------------------------------------------------------------------


def bench_exact() -> dict:
    """Config #1: single-ruleset exact hit-count; correctness vs oracle."""
    import jax
    import jax.numpy as jnp

    from ruleset_analysis_tpu.config import AnalysisConfig, SketchConfig
    from ruleset_analysis_tpu.hostside import pack
    from ruleset_analysis_tpu.models import pipeline
    from ruleset_analysis_tpu.ops.match import match_keys

    packed = _setup()
    b = 1 << 20
    cfg = AnalysisConfig(batch_size=b, sketch=SketchConfig(cms_width=1 << 14, cms_depth=4))
    state = pipeline.init_state(packed.n_keys, cfg)
    rules = pipeline.ship_ruleset(packed)
    feeds_np = [np.ascontiguousarray(_tuples(packed, b, seed=i).T) for i in range(2)]
    valid_per_feed = [int(f[pack.T_VALID].sum()) for f in feeds_np]
    feeds = [jnp.asarray(f) for f in feeds_np]
    step = jax.jit(
        functools.partial(
            pipeline.analysis_step,
            n_keys=packed.n_keys,
            topk_k=cfg.sketch.topk_chunk_candidates,
        ),
        donate_argnums=(0,),
    )
    iters = 20
    state, dt = _time_steps(step, state, rules, feeds, iters, valid_per_feed)

    # correctness: a fresh state stepped over a small batch must hold
    # exactly the bincount of the device-matched keys (oracle equality of
    # the match itself is pinned by tests/)
    t = _tuples(packed, 4096, seed=99)
    cols = {
        "acl": jnp.asarray(t[:, pack.T_ACL]), "proto": jnp.asarray(t[:, pack.T_PROTO]),
        "src": jnp.asarray(t[:, pack.T_SRC]), "sport": jnp.asarray(t[:, pack.T_SPORT]),
        "dst": jnp.asarray(t[:, pack.T_DST]), "dport": jnp.asarray(t[:, pack.T_DPORT]),
    }
    keys = np.asarray(match_keys(cols, rules.rules, rules.deny_key))
    check_state = pipeline.init_state(packed.n_keys, cfg)
    check_state, _ = pipeline.analysis_step(
        check_state, rules, jnp.asarray(np.ascontiguousarray(t.T)),
        n_keys=packed.n_keys, topk_k=cfg.sketch.topk_chunk_candidates,
    )
    want = np.bincount(keys[t[:, pack.T_VALID] == 1], minlength=packed.n_keys)
    exact_ok = bool((np.asarray(check_state.counts_lo) == want.astype(np.uint32)).all())
    lines_per_sec = iters * b / dt
    return {
        "metric": "config1_exact_hitcount_lines_per_sec_per_chip",
        "value": round(lines_per_sec / len(jax.devices()), 1),
        "unit": "lines/sec/chip",
        "vs_baseline": round(lines_per_sec / len(jax.devices()) / (1e9 / 60 / 8), 4),
        "detail": {"batch": b, "iters": iters, "rules_rows": int(packed.rules.shape[0]),
                   "exact_path_ok": exact_ok},
    }


def bench_cms() -> dict:
    """Config #2: CMS width x depth sweep — one-sided error + unused recall."""
    import jax.numpy as jnp

    from ruleset_analysis_tpu.ops import cms as cms_ops

    rng = np.random.default_rng(0)
    n_keys = 4096
    # zipf-ish key stream: heavy head, long tail, plus keys that never occur
    raw = rng.zipf(1.3, size=1 << 20).astype(np.uint64)
    keys = (raw % (n_keys // 2)).astype(np.uint32)  # half the keyspace never hit
    exact = np.bincount(keys, minlength=n_keys).astype(np.uint64)
    valid = np.ones_like(keys)

    sweep = []
    for width in (1 << 10, 1 << 12, 1 << 14, 1 << 16):
        for depth in (2, 4, 6):
            cms = cms_ops.cms_init(width, depth)
            cms = cms_ops.cms_update(cms, jnp.asarray(keys), jnp.asarray(valid))
            est = cms_ops.cms_query_np(np.asarray(cms), np.arange(n_keys, dtype=np.uint32))
            over = est.astype(np.int64) - exact.astype(np.int64)
            assert (over >= 0).all(), "CMS one-sided error violated"
            # unused-rule recall: of truly-zero keys, fraction estimated zero
            zero = exact == 0
            recall = float((est[zero] == 0).mean())
            sweep.append({
                "width": width, "depth": depth,
                "recall_unused": round(recall, 4),
                "mean_overcount": round(float(over.mean()), 2),
                "p99_overcount": round(float(np.percentile(over, 99)), 1),
            })
            log(f"cms w={width} d={depth} recall={recall:.4f} mean_over={over.mean():.2f}")
    best = [s for s in sweep if s["recall_unused"] >= 0.99]
    return {
        "metric": "config2_cms_unused_recall_at_16k_x4",
        "value": next(s["recall_unused"] for s in sweep if s["width"] == 1 << 14 and s["depth"] == 4),
        "unit": "recall",
        "vs_baseline": round(
            next(s["recall_unused"] for s in sweep if s["width"] == 1 << 14 and s["depth"] == 4) / 0.99, 4
        ),
        "detail": {"stream": int(keys.size), "n_keys": n_keys, "sweep": sweep,
                   "configs_meeting_99pct": len(best)},
    }


def bench_hll() -> dict:
    """Config #3: per-rule unique-source HLL relative error."""
    import jax.numpy as jnp

    from ruleset_analysis_tpu.ops import hll as hll_ops

    rng = np.random.default_rng(1)
    n_keys = 256
    p = 8
    # per-key unique-source populations spanning 4 decades
    true_cards = np.unique(np.round(np.logspace(1, 5, n_keys)).astype(np.int64))
    n_keys = len(true_cards)
    regs = hll_ops.hll_init(n_keys, p)
    batch = 1 << 20
    keys_all, src_all = [], []
    for k, card in enumerate(true_cards):
        pool = rng.integers(0, 1 << 32, size=card, dtype=np.uint32)
        draws = pool[rng.integers(0, card, size=min(4 * card, 1 << 18))]
        keys_all.append(np.full(draws.size, k, dtype=np.uint32))
        src_all.append(draws)
    keys = np.concatenate(keys_all)
    srcs = np.concatenate(src_all)
    order = rng.permutation(keys.size)
    keys, srcs = keys[order], srcs[order]
    for i in range(0, keys.size, batch):
        regs = hll_ops.hll_update(
            regs, jnp.asarray(keys[i:i + batch]), jnp.asarray(srcs[i:i + batch]),
            jnp.ones(keys[i:i + batch].size, dtype=np.uint32),
        )
    est = hll_ops.hll_estimate_np(np.asarray(regs))
    # true uniques actually seen (sampling may miss some of the pool)
    true_seen = np.array([
        len(np.unique(srcs[keys == k])) for k in range(n_keys)
    ])
    rel = np.abs(est - true_seen) / np.maximum(true_seen, 1)
    theory = 1.04 / np.sqrt(1 << p)
    return {
        "metric": "config3_hll_median_rel_error",
        "value": round(float(np.median(rel)), 4),
        "unit": "relative_error",
        "vs_baseline": round(theory / max(float(np.median(rel)), 1e-9), 4),
        "detail": {"p": p, "m": 1 << p, "theory_rse": round(theory, 4),
                   "p90_rel_error": round(float(np.percentile(rel, 90)), 4),
                   "n_keys": int(n_keys), "stream": int(keys.size)},
    }


def bench_multifw() -> dict:
    """Config #4: multi-firewall batched match — flat vs stacked layouts,
    measured through the PRODUCTION stream driver (runtime/stream.py with
    ``layout=...``), not a hand-fed step loop: the number includes the
    GroupBuffer bucketing, host->device transfer, sharded steps, and
    candidate draining that a real run pays."""
    import jax

    from ruleset_analysis_tpu.config import AnalysisConfig, SketchConfig
    from ruleset_analysis_tpu.hostside import pack
    from ruleset_analysis_tpu.runtime.stream import run_stream_packed

    # Large rulesets: the regime where per-line match cost dominates and
    # slab-grouping pays (small rulesets are sketch-bound, where grouping
    # only adds lane padding).
    firewalls = 8
    packed = _setup(n_acls=2, rules_per_acl=1024, firewalls=firewalls)
    g = packed.n_acls
    batch = 1 << 20
    n_batches = 6
    total = batch * n_batches
    feeds = [
        np.ascontiguousarray(_tuples(packed, batch, seed=i).T) for i in range(2)
    ]
    log(f"multifw: {firewalls} firewalls, {g} ACL groups, "
        f"{packed.rules.shape[0]} flat rows, {total} lines/run via stream driver")

    def arrays():
        for i in range(n_batches):
            yield feeds[i % len(feeds)]

    def run(layout: str) -> float:
        cfg = AnalysisConfig(
            batch_size=batch,
            sketch=SketchConfig(cms_width=1 << 14, cms_depth=4),
            layout=layout,
        )
        # each run_stream_packed call builds a fresh jit wrapper, so a
        # cold full run (same shapes) populates the persistent XLA
        # compilation cache (enable_persistent_cache in main) and only
        # the second, timed run reflects steady state
        run_stream_packed(packed, arrays(), cfg)
        t0 = time.perf_counter()
        rep = run_stream_packed(packed, arrays(), cfg)
        dt = time.perf_counter() - t0
        assert rep.totals["lines_total"] >= total
        return total / dt

    flat_lps = run("flat")
    stacked_lps = run("stacked")

    return {
        "metric": "config4_multifw_stacked_lines_per_sec_per_chip",
        "value": round(stacked_lps / len(jax.devices()), 1),
        "unit": "lines/sec/chip",
        "vs_baseline": round(stacked_lps / max(flat_lps, 1.0), 4),  # speedup vs flat
        "detail": {
            "firewalls": firewalls, "groups": g,
            "flat_rows": int(packed.rules.shape[0]),
            "slab_rows": pack.stacked_slab_rows(packed),
            "flat_stream_lines_per_sec": round(flat_lps, 1),
            "stacked_stream_lines_per_sec": round(stacked_lps, 1),
            "measured": "production stream driver (run_stream_packed)",
        },
    }


def bench_topk() -> dict:
    """Config #5: streaming top-K talkers precision vs exact.

    Also sweeps the scatter-bound FLIP variants (talk_cms_depth=1 halves
    fusion.7; sample_shift=3 kills 7/8 of fusions 8+9 — DESIGN.md §8):
    their ACCURACY halves are platform-independent, so the flip decision
    only needs the TPU timing half from bench.py's step_variants A/B.
    """
    import jax.numpy as jnp

    from ruleset_analysis_tpu.ops import cms as cms_ops
    from ruleset_analysis_tpu.ops import topk as topk_ops

    rng = np.random.default_rng(2)
    n_chunks, chunk = 32, 1 << 16
    k = 10
    acls = rng.integers(0, 4, size=n_chunks * chunk).astype(np.uint32)
    # zipf sources: the heavy hitters we must recover
    src = (rng.zipf(1.2, size=n_chunks * chunk) % 50000).astype(np.uint32)
    valid = np.ones(chunk, dtype=np.uint32)
    import collections

    exact_tops = {}
    for a in range(4):
        cnt = collections.Counter(src[acls == a].tolist())
        exact_tops[a] = {s for s, _ in cnt.most_common(k)}

    def precision(depth: int, shift: int) -> list[float]:
        talk = cms_ops.cms_init(1 << 14, depth)
        tracker = topk_ops.TopKTracker(capacity=4096)
        for c in range(n_chunks):
            sl = slice(c * chunk, (c + 1) * chunk)
            talk, ca, cs, ce = topk_ops.talker_chunk_update(
                talk, jnp.asarray(acls[sl]), jnp.asarray(src[sl]),
                jnp.asarray(valid), 64, salt=c, sample_shift=shift,
            )
            tracker.offer_chunk(np.asarray(ca), np.asarray(cs), np.asarray(ce))
        return [
            len(exact_tops[a] & {s for s, _ in tracker.top(a, k)}) / k
            for a in range(4)
        ]

    variants = {}
    for depth, shift in [(4, 0), (4, 3), (2, 0), (1, 0), (2, 3), (1, 3)]:
        ps = precision(depth, shift)
        variants[f"d{depth}_shift{shift}"] = {
            "talk_cms_depth": depth,
            "sample_shift": shift,
            "precision_at_10": round(float(np.mean(ps)), 4),
            "per_acl": [round(p, 3) for p in ps],
        }
        log(f"topk d={depth} shift={shift}: precision@{k}="
            f"{variants[f'd{depth}_shift{shift}']['precision_at_10']:.2f}")

    headline = variants["d4_shift0"]["precision_at_10"]
    return {
        "metric": "config5_topk_precision_at_10",
        "value": headline,
        "unit": "precision",
        "vs_baseline": round(headline / 0.9, 4),
        "detail": {"chunks": n_chunks, "chunk": chunk,
                   "per_acl": variants["d4_shift0"]["per_acl"],
                   # accuracy half of every pending scatter-lever flip
                   "flip_variants": variants},
    }


def bench_pallas() -> dict:
    """Match-kernel shootout: XLA-fused vs pallas vs pallas_fused.

    pallas_fused also does the exact-counts work (match + in-VMEM count
    histograms, ops/pallas_fused.py), so its fair comparison is against
    XLA match + segment_counts — the fused column measures match+counts
    for all three, deciding whether the batch-sized counts scatter
    (fusion.5 in the committed trace) is worth a kernel.
    """
    import jax
    import jax.numpy as jnp

    from ruleset_analysis_tpu.hostside import pack
    from ruleset_analysis_tpu.ops import pallas_fused, pallas_match
    from ruleset_analysis_tpu.ops.counts import segment_counts
    from ruleset_analysis_tpu.ops.match import first_match_rows, match_keys

    on_tpu = jax.devices()[0].platform == "tpu"
    # CPU runs execute pallas via the interpreter (parity smoke only);
    # full-size timing there would burn the whole config timeout
    b = 1 << 20 if on_tpu else 1 << 17
    n_timing_iters = 10 if on_tpu else 2
    results = {}
    from ruleset_analysis_tpu.models import pipeline

    for tag, rules_per_acl in (("small", 64), ("large", 1024)):
        packed = _setup(n_acls=4, rules_per_acl=rules_per_acl)
        t = _tuples(packed, b, seed=0)
        cols = {
            k: jnp.asarray(t[:, i])
            for k, i in zip(["acl", "proto", "src", "sport", "dst", "dport"], range(6))
        }
        # block-padded exactly as the pipeline ships it (the scan path of
        # first_match_rows asserts rule_block alignment)
        shipped = pipeline.ship_ruleset(packed, match_impl="pallas")
        rules, fm = shipped.rules, shipped.rules_fm

        def run(fn, *args):
            # sync via a 4-byte readback of the LAST output: device
            # programs execute FIFO, so its completion bounds the loop
            # (block_until_ready is unreliable on the tunnel plugin)
            out = fn(*args)
            np.asarray(out[:1])
            t0 = time.perf_counter()
            n = n_timing_iters
            for _ in range(n):
                out = fn(*args)
            np.asarray(out[:1])
            return (time.perf_counter() - t0) / n

        xla_fn = jax.jit(lambda c: first_match_rows(c, rules))
        pl_fn = jax.jit(lambda c: pallas_match.first_match_rows_pallas(c, fm))
        got = np.asarray(pl_fn(cols))
        want = np.asarray(xla_fn(cols))
        assert (got == want).all(), f"pallas/xla mismatch ({tag})"
        dt_x, dt_p = run(xla_fn, cols), run(pl_fn, cols)

        # match+counts leg: XLA match_keys + segment_counts vs the fused
        # kernel (keys AND per-key count delta in one pallas_call)
        deny = shipped.deny_key
        valid = jnp.ones(b, dtype=jnp.uint32)
        n_keys = packed.n_keys

        def xla_mc(c):
            keys = match_keys(c, rules, deny)
            return segment_counts(keys, valid, n_keys)

        def fused_mc(c):
            _keys, delta = pallas_fused.match_keys_and_counts_pallas(
                c, valid, rules, fm, deny, n_keys
            )
            return delta

        xla_mc_fn, fused_mc_fn = jax.jit(xla_mc), jax.jit(fused_mc)
        d_want = np.asarray(xla_mc_fn(cols))
        d_got = np.asarray(fused_mc_fn(cols))
        assert (d_got == d_want).all(), f"fused counts mismatch ({tag})"
        dt_xc, dt_f = run(xla_mc_fn, cols), run(fused_mc_fn, cols)

        results[tag] = {
            "rows": int(rules.shape[0]),
            "xla_mlines_per_sec": round(b / dt_x / 1e6, 1),
            "pallas_mlines_per_sec": round(b / dt_p / 1e6, 1),
            "pallas_speedup": round(dt_x / dt_p, 3),
            "xla_match_counts_mlines_per_sec": round(b / dt_xc / 1e6, 1),
            "fused_match_counts_mlines_per_sec": round(b / dt_f / 1e6, 1),
            "fused_speedup": round(dt_xc / dt_f, 3),
        }
        log(
            f"pallas[{tag}]: xla {b/dt_x/1e6:.1f}M vs pallas {b/dt_p/1e6:.1f}M"
            f" | match+counts: xla {b/dt_xc/1e6:.1f}M vs fused {b/dt_f/1e6:.1f}M lines/s"
        )
    results["platform"] = "tpu" if on_tpu else "cpu"
    results["batch"] = b
    results["timing_iters"] = n_timing_iters
    # a CPU run exercises parity only (pallas via the interpreter at 1/8
    # batch) — its timings must never be read as TPU numbers
    return {
        "metric": "pallas_match_speedup_vs_xla_large_ruleset",
        "value": results["large"]["pallas_speedup"],
        "unit": "speedup",
        "vs_baseline": results["large"]["pallas_speedup"],
        "detail": results,
    }


def bench_stage() -> dict:
    """Device-step stage attribution: where do the milliseconds go?

    The round-4 headline runs at ~4% of the u32 VPU roofline, so the step
    is NOT bounded by the predicate math — this config times each piece of
    the fused step in isolation (match kernel, exact-counts scatter, a
    one-hot-matmul counts alternative, HLL scatter-max, talker update,
    full step) to show which register update to attack next.  Timing
    discipline matches runtime/timing.py: the warmup dispatch is closed
    by a host fetch before the clock starts, every iteration's carry
    depends on the previous one (no pipelined elision of the chain), the
    window closes with a host fetch of the carry, and every stage
    validates the fetched value against an independently computed
    expectation — a window whose work did not run fails loudly instead of
    reporting a plausible number (the round-2 9x-roofline lesson).
    """
    import jax
    import jax.numpy as jnp

    from ruleset_analysis_tpu.config import AnalysisConfig, SketchConfig
    from ruleset_analysis_tpu.hostside import pack as pack_mod
    from ruleset_analysis_tpu.models import pipeline
    from ruleset_analysis_tpu.ops import cms as cms_ops
    from ruleset_analysis_tpu.ops import counts as count_ops
    from ruleset_analysis_tpu.ops import hll as hll_ops
    from ruleset_analysis_tpu.ops import topk as topk_ops
    from ruleset_analysis_tpu.ops.match import match_keys
    from ruleset_analysis_tpu.runtime.timing import timed_validated_steps

    on_tpu = jax.devices()[0].platform == "tpu"
    b = 1 << 20 if on_tpu else 1 << 16
    iters = 20 if on_tpu else 5
    packed = _setup(n_acls=4, rules_per_acl=64)
    n_keys = packed.n_keys
    tup = _tuples(packed, b, seed=3)
    wire = jnp.asarray(pack_mod.compact_batch(np.ascontiguousarray(tup.T)))
    rules = pipeline.ship_ruleset(packed)
    cols, valid = pipeline.batch_cols(wire)
    keys0 = jax.block_until_ready(
        match_keys(cols, rules.rules, rules.deny_key)
    )
    src, acl = cols["src"], cols["acl"]
    n_valid = int(jax.device_get(valid.astype(jnp.uint32).sum()))
    # exact host-side expectations (device sums are u32 and wrap mod 2^32)
    keys_sum = int(np.asarray(jax.device_get(keys0), dtype=np.uint64).sum() % (1 << 32))

    u32 = jnp.uint32
    M = 1 << 32

    def timed(name, init_carry, one_iter, validate):
        """Chained-carry window: warmup closed by a fetch, then timed."""
        f = jax.jit(one_iter)
        jax.device_get(jax.tree_util.tree_leaves(f(init_carry))[0])
        carry = init_carry
        t0 = time.perf_counter()
        for _ in range(iters):
            carry = f(carry)
        final = jax.device_get(carry)  # closes the window
        dt = time.perf_counter() - t0
        validate(final)
        ms = dt / iters * 1e3
        log(f"stage {name}: {ms:.2f} ms/iter")
        return round(ms, 3)

    def expect_scalar(expected, what):
        def check(final):
            got = int(np.asarray(final).reshape(())) 
            if got != expected:
                raise AssertionError(
                    f"stage window invalid: {what} carry {got} != {expected}"
                )
        return check

    results = {}

    # match kernel only: each iteration folds the (recomputed) key sum
    # into the carry, so iteration i+1 cannot issue before i finished
    results["match_ms"] = timed(
        "match",
        u32(0),
        lambda c: c + match_keys(cols, rules.rules, rules.deny_key).sum(dtype=u32),
        expect_scalar(iters * keys_sum % M, "match key-sum"),
    )

    # exact-counts scatter-add ([B] -> [n_keys]); per-iter sum == n_valid
    results["counts_scatter_ms"] = timed(
        "counts-scatter",
        u32(0),
        lambda c: c + count_ops.segment_counts(keys0, valid, n_keys).sum(dtype=u32),
        expect_scalar(iters * n_valid % M, "counts total"),
    )

    # one-hot matmul alternative: the SHIPPED formulation
    # (ops/counts.segment_counts_matmul — what counts_impl="matmul"
    # actually runs), so a measured default flip prices production code
    def counts_matmul(keys):
        return count_ops.segment_counts_matmul(keys, valid, n_keys)

    results["counts_matmul_ms"] = timed(
        "counts-matmul",
        u32(0),
        lambda c: c + counts_matmul(keys0).sum(dtype=u32),
        expect_scalar(iters * n_valid % M, "matmul counts total"),
    )

    # compare-and-reduce alternative: the SHIPPED formulation
    # (ops/counts.segment_counts_reduce, counts_impl="reduce")
    def counts_reduce(keys):
        return count_ops.segment_counts_reduce(keys, valid, n_keys)

    results["counts_reduce_ms"] = timed(
        "counts-reduce",
        u32(0),
        lambda c: c + counts_reduce(keys0).sum(dtype=u32),
        expect_scalar(iters * n_valid % M, "reduce counts total"),
    )

    # parity: every counts formulation must produce the exact scatter counts
    c_sc = jax.device_get(count_ops.segment_counts(keys0, valid, n_keys))
    c_mm = jax.device_get(counts_matmul(keys0))
    c_rd = jax.device_get(counts_reduce(keys0))
    if not np.array_equal(c_sc, c_mm):
        raise AssertionError("one-hot matmul counts != scatter counts")
    if not np.array_equal(c_sc, c_rd):
        raise AssertionError("compare-reduce counts != scatter counts")

    # HLL scatter-max ([B] -> [n_keys, m]).  Max-updates are idempotent,
    # so iterations past the first change nothing; the carry chain still
    # forces each scatter to execute, and the fixed point is the check.
    hll0 = hll_ops.hll_init(n_keys, 8)
    hll1 = jax.device_get(hll_ops.hll_update(hll0, keys0, src, valid))

    def check_hll(final):
        if not np.array_equal(final, hll1):
            raise AssertionError("stage window invalid: hll != 1-step fixed point")

    results["hll_ms"] = timed(
        "hll", hll0, lambda h: hll_ops.hll_update(h, keys0, src, valid), check_hll
    )

    # talker update INCLUDING candidate extraction: the candidates must be
    # live outputs of the chain or XLA dead-code-eliminates the per-chunk
    # top-k selection that the real step pays for
    sk = SketchConfig()
    tcms = cms_ops.cms_init(sk.cms_width, sk.talk_cms_depth)
    d1 = int(np.asarray(jax.device_get(
        topk_ops.talker_chunk_update(tcms, acl, src, valid, 10, salt=0)[0]
    ), dtype=np.uint64).sum())

    def step_talk(carry):
        t, acc = carry
        new, _ca, _cs, ce = topk_ops.talker_chunk_update(t, acl, src, valid, 10, salt=0)
        return new, acc + ce.sum(dtype=u32)

    # candidate estimates evolve with the accumulating cms, so the acc
    # expectation comes from an untimed replay of the same chain; the cms
    # sum (additive: iters x one-step delta) is the independent anchor
    pre = jax.jit(step_talk)
    c = (tcms, u32(0))
    for _ in range(iters):
        c = pre(c)
    expected_acc = int(np.asarray(jax.device_get(c[1])).reshape(()))

    def check_talk(final):
        t_final, acc = final
        got = int(np.asarray(t_final, dtype=np.uint64).sum())
        if got != iters * d1:
            raise AssertionError(
                f"stage window invalid: talker sum {got} != {iters * d1}"
            )
        got_acc = int(np.asarray(acc).reshape(()))
        if got_acc != expected_acc:
            raise AssertionError(
                f"stage window invalid: candidate sum {got_acc} != {expected_acc}"
            )

    results["talker_ms"] = timed("talker", (tcms, u32(0)), step_talk, check_talk)

    # full fused step, via the SHARED counts-validated helper
    import functools

    full_step = jax.jit(
        functools.partial(pipeline.analysis_step, n_keys=n_keys, topk_k=10, salt=0),
        donate_argnums=(0,),  # same discipline as bench_exact: no state copy
    )
    state = pipeline.init_state(n_keys, AnalysisConfig(sketch=sk))
    state, _ = full_step(state, rules, wire)  # warmup
    pipeline.counts_total(state)  # close warmup with the counts fetch
    state, dt, delta, expect = timed_validated_steps(
        full_step, state, rules, [wire], [n_valid], iters
    )
    if delta != expect:
        raise AssertionError(f"full-step window invalid: {delta} != {expect}")
    results["full_step_ms"] = round(dt / iters * 1e3, 3)
    log(f"stage full: {results['full_step_ms']:.2f} ms/iter")

    results["unattributed_ms"] = round(
        results["full_step_ms"] - (
            results["match_ms"] + results["counts_scatter_ms"]
            + results["hll_ms"] + results["talker_ms"]
        ), 3,
    )
    results["batch"] = b
    results["iters"] = iters
    results["n_keys"] = n_keys
    results["platform"] = "tpu" if on_tpu else "cpu"
    results["counts_matmul_speedup"] = round(
        results["counts_scatter_ms"] / max(results["counts_matmul_ms"], 1e-9), 2
    )
    return {
        "metric": "stage_full_step_ms",
        "value": results["full_step_ms"],
        "unit": "ms",
        "vs_baseline": 0.0,
        "detail": results,
    }


def bench_recall() -> dict:
    """Sketch-only recall certification at 1e8 lines (VERDICT r3 #7).

    The BASELINE.md accuracy north star ("exact counts replaced by CMS,
    >=99% unused-ACL recall vs the exact run") demonstrated at the scale
    where CMS load factors actually stress: ~1e8 packed lines (1e6 on the
    CPU fallback so the config still completes anywhere) over a 1k-key
    ruleset, swept across CMS widths.  One exact run is the ground truth;
    each geometry then runs sketch-only through the production stream
    driver, giving the committed recall CURVE plus the recommended
    geometry per ruleset size.
    """
    import jax

    from ruleset_analysis_tpu.config import AnalysisConfig, SketchConfig
    from ruleset_analysis_tpu.hostside.oracle import unused_rule_recall
    from ruleset_analysis_tpu.models.pipeline import register_bytes
    from ruleset_analysis_tpu.runtime.stream import run_stream_packed

    import os

    on_tpu = jax.devices()[0].platform == "tpu"
    # RA_RECALL_KEYS ~ fleet size of the key universe (VERDICT r4 #6:
    # certify at 10k+ keys, not just the 1k of the r4 run); RA_RECALL_LAYOUT
    # runs the sweep through the stacked (per-ACL slab) path instead of flat.
    want_keys = int(os.environ.get("RA_RECALL_KEYS", "1024"))
    n_acls_ = 16 if want_keys >= 4096 else 8
    packed = _setup(n_acls=n_acls_, rules_per_acl=max(want_keys // n_acls_, 8))
    layout = os.environ.get("RA_RECALL_LAYOUT", "flat")
    chunk = 1 << 20
    # RA_RECALL_CHUNKS overrides the scale (e.g. a deliberate 1e8-line CPU
    # certification run: accuracy is platform-independent, only slower)
    n_chunks_ = int(
        os.environ.get("RA_RECALL_CHUNKS", "0")
    ) or (96 if on_tpu else 1)
    feeds = [np.ascontiguousarray(_tuples(packed, chunk, seed=100 + i).T)
             for i in range(2)]
    total = n_chunks_ * chunk
    log(f"recall: {packed.n_keys} keys, {total} lines, tpu={on_tpu}")

    def arrays():
        for i in range(n_chunks_):
            yield feeds[i % len(feeds)]

    def cfg_for(width: int, depth: int, exact: bool) -> AnalysisConfig:
        return AnalysisConfig(
            batch_size=chunk,
            sketch=SketchConfig(cms_width=width, cms_depth=depth, hll_p=8),
            exact_counts=exact,
            layout=layout,
        )

    t0 = time.perf_counter()
    rep_exact = run_stream_packed(packed, arrays(), cfg_for(1 << 14, 4, True))
    t_exact = time.perf_counter() - t0
    exact_unused = rep_exact.unused

    sweep = []
    # depth is part of the sweep (VERDICT r4 #6): depth 2 halves the
    # register traffic, depth 6 tests whether extra rows buy recall at
    # fleet key counts where width collisions concentrate
    for width, depth in [
        (1 << 12, 4), (1 << 14, 2), (1 << 14, 4), (1 << 14, 6), (1 << 16, 4),
    ]:
        cfg = cfg_for(width, depth, False)
        t0 = time.perf_counter()
        rep = run_stream_packed(packed, arrays(), cfg)
        dt = time.perf_counter() - t0
        recall = unused_rule_recall(exact_unused, rep.unused)
        # CMS error is one-sided: a rule with real hits can never estimate
        # zero, so false "unused" claims must be structurally absent
        false_unused = [k for k in rep.unused if k not in set(exact_unused)]
        rb = sum(register_bytes(packed.n_keys, cfg).values())
        sweep.append({
            "width": width, "depth": depth,
            "recall_unused": round(recall, 4),
            "false_unused": len(false_unused),
            "register_bytes": rb,
            "lines_per_sec": round(total / dt, 1),
        })
        log(f"recall w={width} d={depth}: {recall:.4f} "
            f"({total / dt:.0f} lines/s)")
    meets = [s for s in sweep if s["recall_unused"] >= 0.99]
    recommended = min(meets, key=lambda s: s["register_bytes"]) if meets else None
    # headline geometry pinned to (2^14, depth 4) — the same row every
    # round, so cross-round artifact comparisons track ONE config even as
    # the sweep grows more depths
    headline = next(
        s for s in sweep if s["width"] == 1 << 14 and s["depth"] == 4
    )
    return {
        "metric": f"recall_sketch_only_unused_vs_exact_{total // 1_000_000}M_lines",
        "value": headline["recall_unused"],
        "unit": "recall",
        "vs_baseline": round(headline["recall_unused"] / 0.99, 4),
        "detail": {
            "lines": total,
            "n_keys": packed.n_keys,
            "exact_unused": len(exact_unused),
            "exact_run_sec": round(t_exact, 1),
            "exact_lines_per_sec": round(total / t_exact, 1),
            "sweep": sweep,
            # smallest geometry meeting the >=99% north star for this
            # ruleset size — the documented recommendation
            "recommended_geometry": recommended,
            "layout": layout,
            "platform": "tpu" if on_tpu else "cpu",
        },
    }


def bench_e2e() -> dict:
    """Full system: raw syslog text file -> report (host parse + device).

    The north-star metric is END-TO-END lines/min, so this measures the
    whole path the CLI takes: native C++ parse of raw bytes, packing,
    device analysis, report assembly.  The host parse runs on one CPU
    core here; on multi-core v5e hosts it scales per-process/per-core.

    One-time jit/compile cost is measured SEPARATELY (VERDICT r5 Weak #1:
    committed artifacts ranged 113k-873k lines/s purely on how much of
    the run was compile): a tiny warmup run with the identical batch
    geometry fills the persistent XLA cache first, so the headline
    ``value`` is the sustained rate and ``compile_warmup_sec`` prices the
    one-time cost explicitly in the emitted JSON.
    """
    import os
    import tempfile

    from ruleset_analysis_tpu.config import AnalysisConfig, SketchConfig
    from ruleset_analysis_tpu.hostside import fastparse, synth
    from ruleset_analysis_tpu.runtime.compcache import enable_persistent_cache
    from ruleset_analysis_tpu.runtime.stream import run_stream_file

    packed = _setup()
    n = 2_000_000
    n_warm = 10_000
    log(f"rendering {n} syslog lines...")
    tuples = _tuples(packed, n, seed=0)
    lines = synth.render_syslog(packed, tuples, seed=1)
    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "bench.log")
        with open(path, "w", encoding="utf-8") as f:
            f.write("\n".join(lines) + "\n")
        warm_path = os.path.join(d, "warm.log")
        with open(warm_path, "w", encoding="utf-8") as f:
            f.write("\n".join(lines[:n_warm]) + "\n")
        del lines
        size_mb = os.path.getsize(path) / 1e6
        cfg = AnalysisConfig(
            batch_size=1 << 19,
            sketch=SketchConfig(cms_width=1 << 14, cms_depth=4, hll_p=8),
        )
        # the warm run only de-compiles the measured run where compiled
        # programs persist across the two fresh jit wrappers — i.e. when
        # the persistent cache is active (always on TPU, the platform
        # whose committed e2e artifacts motivated this split; the CPU
        # cache is unsafe on some jaxlibs and stays off by default, and
        # there the emitted persistent_cache:false flags that the
        # sustained rate still includes a compile share)
        cache = enable_persistent_cache()
        t0 = time.perf_counter()
        run_stream_file(packed, warm_path, cfg, native=None)
        warm_sec = time.perf_counter() - t0
        rep = run_stream_file(packed, path, cfg, native=None)  # auto-select
    lps = rep.totals["lines_per_sec"]
    return {
        "metric": "e2e_text_to_report_lines_per_sec",
        "value": lps,
        "unit": "lines/sec",
        "vs_baseline": round(lps / (1e9 / 60 / 8), 4),  # vs north-star/chip
        "detail": {
            "lines": n,
            "file_mb": round(size_mb, 1),
            "native_parse": fastparse.available(),
            "host_cores": os.cpu_count(),
            "totals": rep.totals,
            # one-time cost, priced separately from the sustained rate
            # (dominated by jit trace + XLA compile; includes n_warm
            # lines of real work, negligible at the sustained rate)
            "compile_warmup_sec": round(warm_sec, 3),
            "warmup_lines": n_warm,
            "persistent_cache": bool(cache),
        },
    }


def bench_convert() -> dict:
    """One-time text->wire conversion throughput at w ∈ {1, 4, 8}.

    VERDICT r4 #7: the wire tier's "convert once" cost was only measured
    single-process.  This is pure host work (native parse + row packing,
    no device), so the numbers are valid on any host; the TPU host's
    core count is what matters at fleet scale.  Emits GB/min and the
    projected wall time for the north-star volume (1e9 lines).
    """
    import os
    import tempfile

    from ruleset_analysis_tpu.hostside import fastparse, synth
    from ruleset_analysis_tpu.hostside import wire as wire_mod

    packed = _setup()
    n = 2_000_000
    log(f"rendering {n} syslog lines...")
    tuples = _tuples(packed, n, seed=0)
    lines = synth.render_syslog(packed, tuples, seed=1)
    workers = [1, 4, 8]
    runs = {}
    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "bench.log")
        with open(path, "w", encoding="utf-8") as f:
            f.write("\n".join(lines) + "\n")
        del lines
        size_mb = os.path.getsize(path) / 1e6
        for w in workers:
            out = os.path.join(d, f"bench-w{w}.rawire")
            t0 = time.perf_counter()
            stats = wire_mod.convert_logs(
                packed, [path], out,
                batch_size=1 << 18, block_rows=1 << 18,
                feed_workers=0 if w == 1 else w,
            )
            dt = time.perf_counter() - t0
            runs[f"w{w}"] = {
                "lines_per_sec": round(n / dt, 1),
                "elapsed_sec": round(dt, 3),
                "gb_per_min": round(size_mb / 1e3 / dt * 60, 3),
                "parser": stats["parser"],
            }
            log(f"w={w}: {runs[f'w{w}']['lines_per_sec']:.0f} lines/s")
        # byte-identity across worker counts is pinned by
        # tests/test_wirefile.py::test_convert_feed_workers_byte_identical
    best = max(runs.values(), key=lambda r: r["lines_per_sec"])
    return {
        "metric": "wire_convert_lines_per_sec",
        "value": best["lines_per_sec"],
        "unit": "lines/sec",
        # convert is a ONE-TIME cost; vs_baseline rates it against the
        # north-star per-minute line volume (1e9/min): 1.0 means convert
        # keeps up with the analysis stream in real time on this host
        "vs_baseline": round(best["lines_per_sec"] / (1e9 / 60), 4),
        "detail": {
            "lines": n,
            "file_mb": round(size_mb, 1),
            "native_parse": fastparse.available(),
            "host_cores": os.cpu_count(),
            "runs": runs,
            "north_star_1e9_lines_convert_min": round(
                1e9 / best["lines_per_sec"] / 60, 1
            ),
        },
    }


def bench_v6() -> dict:
    """IPv6 step cost: the lexicographic limb predicate vs the v4 step.

    DESIGN.md's v6 extension predicts ~1.5x step cost (3x the
    address-compare FLOPs on a step whose match is ~22% of time); this
    config measures the actual per-line ratio on device, over a unified
    ruleset with comparable expanded row counts per family, plus a
    device-vs-host correctness check of the v6 counts path.
    """
    import jax
    import jax.numpy as jnp

    from ruleset_analysis_tpu.config import AnalysisConfig, SketchConfig
    from ruleset_analysis_tpu.hostside import aclparse, pack, synth
    from ruleset_analysis_tpu.models import pipeline
    from ruleset_analysis_tpu.ops.match6 import match_keys6

    rs = aclparse.parse_asa_config(
        synth.synth_config(n_acls=4, rules_per_acl=64, seed=7, v6_fraction=0.5),
        "fw0",
    )
    packed = pack.pack_rulesets([rs])
    b = 1 << 19
    cfg = AnalysisConfig(batch_size=b, sketch=SketchConfig(cms_width=1 << 14, cms_depth=4))
    topk_k = cfg.sketch.topk_chunk_candidates

    # v4 leg
    state = pipeline.init_state(packed.n_keys, cfg)
    rules4 = pipeline.ship_ruleset(packed)
    feeds4_np = [np.ascontiguousarray(_tuples(packed, b, seed=i).T) for i in range(2)]
    valid4 = [int(f[pack.T_VALID].sum()) for f in feeds4_np]
    feeds4 = [jnp.asarray(f) for f in feeds4_np]
    step4 = jax.jit(
        functools.partial(
            pipeline.analysis_step, n_keys=packed.n_keys, topk_k=topk_k
        ),
        donate_argnums=(0,),
    )
    iters = 10
    state, dt4 = _time_steps(step4, state, rules4, feeds4, iters, valid4)

    # v6 leg (same state/key space — the production arrangement)
    rules6 = pipeline.ship_ruleset6(packed)
    feeds6_np = [
        np.ascontiguousarray(synth.synth_tuples6(packed, b, seed=i).T)
        for i in range(2)
    ]
    valid6 = [int(f[pack.T6_VALID].sum()) for f in feeds6_np]
    feeds6 = [jnp.asarray(f) for f in feeds6_np]
    step6 = jax.jit(
        functools.partial(
            pipeline.analysis_step6, n_keys=packed.n_keys, topk_k=topk_k
        ),
        donate_argnums=(0,),
    )
    state, dt6 = _time_steps(step6, state, rules6, feeds6, iters, valid6)

    # correctness: v6 counts == host bincount of device-matched keys
    t6 = synth.synth_tuples6(packed, 4096, seed=99)
    b6 = jnp.asarray(np.ascontiguousarray(t6.T))
    cols6, _ = pipeline.batch_cols6(b6)
    keys6 = np.asarray(match_keys6(cols6, rules6.rules6, rules6.deny_key))
    chk = pipeline.init_state(packed.n_keys, cfg)
    chk, _ = pipeline.analysis_step6(
        chk, rules6, b6, n_keys=packed.n_keys, topk_k=topk_k
    )
    want = np.bincount(
        keys6[t6[:, pack.T6_VALID] == 1], minlength=packed.n_keys
    )
    v6_ok = bool((np.asarray(chk.counts_lo) == want.astype(np.uint32)).all())

    n_dev = len(jax.devices())
    v4_rate = iters * b / dt4 / n_dev
    v6_rate = iters * b / dt6 / n_dev
    return {
        "metric": "config_v6_step_lines_per_sec_per_chip",
        "value": round(v6_rate, 1),
        "unit": "lines/sec/chip",
        "vs_baseline": round(v6_rate / (1e9 / 60 / 8), 4),
        "detail": {
            "batch": b,
            "iters": iters,
            "v4_rows": int(packed.rules.shape[0]),
            "v6_rows": int(packed.rules6.shape[0]),
            "v4_lines_per_sec_per_chip": round(v4_rate, 1),
            "v6_relative_cost": round(v4_rate / v6_rate, 3),
            "design_predicted_cost": 1.5,
            "v6_counts_ok": v6_ok,
        },
    }


def bench_v6recall() -> dict:
    """Sketch-only unused-rule recall on a MIXED v4+v6 stream.

    The north-star accuracy criterion certified with both families live
    in the SAME registers: one exact direct-step run (v4 and v6 chunks
    interleaved) is ground truth; each CMS geometry then re-runs
    sketch-only and the unused sets compare.  Direct step calls (not the
    stream driver) — driver-level mixed correctness is pinned
    oracle-exact by tests/test_stream6.py; this config isolates the
    register-geometry question at scale.
    """
    import os

    import jax
    import jax.numpy as jnp

    from ruleset_analysis_tpu.config import AnalysisConfig, SketchConfig
    from ruleset_analysis_tpu.hostside import aclparse, pack, synth
    from ruleset_analysis_tpu.hostside.oracle import unused_rule_recall
    from ruleset_analysis_tpu.models import pipeline
    from ruleset_analysis_tpu.ops import cms as cms_ops

    on_tpu = jax.devices()[0].platform == "tpu"
    rs = aclparse.parse_asa_config(
        synth.synth_config(n_acls=12, rules_per_acl=48, seed=13, v6_fraction=0.35),
        "fw0",
    )
    packed = pack.pack_rulesets([rs])
    b4, b6 = 1 << 20, 1 << 18
    feeds4 = [
        jnp.asarray(np.ascontiguousarray(synth.synth_tuples(packed, b4, seed=200 + i).T))
        for i in range(2)
    ]
    feeds6 = [
        jnp.asarray(np.ascontiguousarray(synth.synth_tuples6(packed, b6, seed=200 + i).T))
        for i in range(2)
    ]
    epochs = int(os.environ.get("RA_V6RECALL_EPOCHS", "0")) or (24 if on_tpu else 3)
    total = epochs * (2 * b4 + 2 * b6)
    total6 = epochs * 2 * b6
    log(f"v6recall: {packed.n_keys} keys ({packed.rules6.shape[0]} v6 rows), "
        f"{total} lines ({total6} v6), tpu={on_tpu}")

    def run(width: int, depth: int, exact: bool):
        cfg = AnalysisConfig(
            batch_size=b4,
            sketch=SketchConfig(cms_width=width, cms_depth=depth, hll_p=8),
            exact_counts=exact,
        )
        topk_k = cfg.sketch.topk_chunk_candidates
        rules4 = pipeline.ship_ruleset(packed)
        rules6 = pipeline.ship_ruleset6(packed)
        state = pipeline.init_state(packed.n_keys, cfg)
        step4 = jax.jit(
            functools.partial(
                pipeline.analysis_step, n_keys=packed.n_keys, topk_k=topk_k,
                exact_counts=exact,
            ),
            donate_argnums=(0,),
        )
        step6 = jax.jit(
            functools.partial(
                pipeline.analysis_step6, n_keys=packed.n_keys, topk_k=topk_k,
                exact_counts=exact,
            ),
            donate_argnums=(0,),
        )
        for e in range(epochs):
            for f in feeds4:
                state, _ = step4(state, rules4, f)
            for f in feeds6:
                state, _ = step6(state, rules6, f)
        host = pipeline.state_to_host(state)
        if exact:
            import ruleset_analysis_tpu.ops.counts as count_ops

            per_key = count_ops.to_u64(host["counts_lo"], host["counts_hi"])
        else:
            per_key = cms_ops.cms_query_np(
                host["cms"], np.arange(packed.n_keys, dtype=np.uint32)
            )
        unused = [
            (m.firewall, m.acl, m.index)
            for k, m in enumerate(packed.key_meta)
            if not m.implicit_deny and per_key[k] == 0
        ]
        return unused

    t0 = time.perf_counter()
    exact_unused = run(1 << 14, 4, True)
    t_exact = time.perf_counter() - t0
    sweep = []
    for width, depth in [(1 << 12, 4), (1 << 14, 4), (1 << 16, 4)]:
        t0 = time.perf_counter()
        got = run(width, depth, False)
        dt = time.perf_counter() - t0
        recall = unused_rule_recall(exact_unused, got)
        false_unused = [k for k in got if k not in set(exact_unused)]
        sweep.append({
            "width": width, "depth": depth,
            "recall_unused": round(recall, 4),
            "false_unused": len(false_unused),
            "lines_per_sec": round(total / dt, 1),
        })
        log(f"v6recall w={width} d={depth}: {recall:.4f}")
    headline = next(s for s in sweep if s["width"] == 1 << 14)
    return {
        "metric": "v6_mixed_recall_sketch_only_unused_vs_exact",
        "value": headline["recall_unused"],
        "unit": "recall",
        "vs_baseline": round(headline["recall_unused"] / 0.99, 4),
        "detail": {
            "lines_total": total,
            "lines_v6": total6,
            "n_keys": packed.n_keys,
            "v6_rows": int(packed.rules6.shape[0]),
            "n_unused_exact": len(exact_unused),
            "exact_run_sec": round(t_exact, 1),
            "sweep": sweep,
        },
    }


def bench_sustained() -> dict:
    """Sustained end-to-end run through the PRODUCTION CLI path.

    Closes the "projection vs measurement" gap on the e2e arm (VERDICT
    r5 #1): ≥1e8 synthetic lines flow through exactly what an operator
    runs — ``ruleset-analyze run`` over a ``.rawire`` wire file (mmap →
    pipelined ingest → H2D → sharded step → report) — and the emitted
    NORTHSTAR-style JSON separates the one-time jit/compile cost from
    the sustained rate, so this artifact can never be compile-dominated
    the way the 2M-line e2e artifacts were (two committed runs once
    disagreed 7.7x on exactly that).

    ``RA_SUSTAINED_LINES`` overrides the volume (default 1e8; the
    acceptance floor).  A small warm run first fills the in-process jit
    cache; the driver's ``compile_sec`` then prices any residue.
    """
    import os
    import tempfile

    import jax

    from ruleset_analysis_tpu import cli
    from ruleset_analysis_tpu.hostside import pack as pack_mod
    from ruleset_analysis_tpu.hostside import synth
    from ruleset_analysis_tpu.hostside import wire as wire_mod

    n = int(float(os.environ.get("RA_SUSTAINED_LINES", "1e8")))
    batch = 1 << 20
    chunks = max(1, (n + batch - 1) // batch)
    n = chunks * batch
    packed = _setup()
    n_dev = len(jax.devices())
    platform = jax.devices()[0].platform
    with tempfile.TemporaryDirectory() as d:
        prefix = os.path.join(d, "rules")
        pack_mod.save_packed(packed, prefix)

        def write_wire(path: str, n_chunks: int, seed0: int) -> dict:
            w = wire_mod.WireWriter(
                path, wire_mod.ruleset_fingerprint(packed), block_rows=batch
            )
            with w:
                for i in range(n_chunks):
                    t = _tuples(packed, batch, seed=seed0 + i).T
                    t = np.ascontiguousarray(t)
                    dense = t[:, t[pack_mod.T_VALID] == 1]
                    w.add(
                        pack_mod.compact_batch(dense),
                        batch,
                        batch - dense.shape[1],
                    )
            return {"rows": w.n_rows, "bytes": os.path.getsize(path)}

        t0 = time.perf_counter()
        warm_path = os.path.join(d, "warm.rawire")
        write_wire(warm_path, 1, seed0=10_000)
        wire_path = os.path.join(d, "sustained.rawire")
        stats = write_wire(wire_path, chunks, seed0=0)
        t_synth = time.perf_counter() - t0
        log(f"sustained corpus: {n} lines -> {stats['bytes']/1e9:.2f} GB "
            f"wire in {t_synth:.0f}s")

        def run_cli(logs: str, out: str) -> dict:
            rc = cli.main([
                "run", "--ruleset", prefix, "--logs", logs,
                "--batch-size", str(batch), "--json", "--out", out,
            ])
            if rc != 0:
                raise RuntimeError(f"production CLI run failed rc={rc}")
            with open(out, "r", encoding="utf-8") as f:
                return json.load(f)

        # warm: fills the memoized step-builder + jit caches in-process,
        # so the measured run's compile_sec is the honest residue
        t0 = time.perf_counter()
        run_cli(warm_path, os.path.join(d, "warm.json"))
        warm_sec = time.perf_counter() - t0
        t0 = time.perf_counter()
        rep = run_cli(wire_path, os.path.join(d, "sustained.json"))
        elapsed = time.perf_counter() - t0
    totals = rep["totals"]
    sustained = totals["sustained_lines_per_sec"]
    return {
        "metric": "sustained_e2e_wire_lines_per_sec",
        "value": sustained,
        "unit": "lines/sec",
        # vs the north-star e2e volume (1e9 lines/min on the 8-chip part)
        "vs_baseline": round(sustained / (1e9 / 60), 4),
        "detail": {
            "platform": platform,
            "devices": n_dev,
            "lines": n,
            "rows": stats["rows"],
            "file_gb": round(stats["bytes"] / 1e9, 3),
            "elapsed_sec": round(elapsed, 1),
            "lines_per_sec_incl_compile": totals["lines_per_sec"],
            "sustained_lines_per_sec": sustained,
            "sustained_lines_per_min": round(sustained * 60, 1),
            "compile_sec": totals["compile_sec"],
            "warm_run_sec": round(warm_sec, 2),
            "corpus_synth_sec": round(t_synth, 1),
            "ingest": totals.get("ingest"),
            "chunks": totals["chunks"],
            "path": "production CLI: run --logs *.rawire (wire mmap -> "
                    "pipelined ingest -> H2D -> sharded step -> report)",
            "totals": totals,
        },
    }


def bench_obs() -> dict:
    """Observability-plane guard: disarmed overhead + stage attribution.

    Two measured runs over the same wire corpus through the production
    CLI at the sustained bench geometry (batch 1<<20, wire mmap ->
    pipelined ingest -> sharded step):

    - **disarmed** (no --trace-out/--metrics-out): every obs site is one
      None-check.  The sustained rate here is the <2%-regression guard
      against the PR 3 baseline (NORTHSTAR_SUSTAINED_1E8_r06_cpu.json),
      recorded as ``vs_r06_baseline``.
    - **armed** (--trace-out + --metrics-out): prices the observability
      tax when ON, and its merged trace feeds
      ``tools.trace_summary.summarize`` so the artifact records
      per-stage occupancy — the attribution substrate the ISSUE names.

    ``RA_OBS_LINES`` overrides the corpus size (default 4M lines —
    enough chunks for a stable sustained separation on CPU).
    """
    import os
    import tempfile

    import jax

    from ruleset_analysis_tpu import cli
    from ruleset_analysis_tpu.hostside import pack as pack_mod
    from ruleset_analysis_tpu.hostside import wire as wire_mod

    sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "tools"))
    import trace_summary

    n = int(float(os.environ.get("RA_OBS_LINES", "4e6")))
    batch = 1 << 20
    chunks = max(2, (n + batch - 1) // batch)
    n = chunks * batch
    packed = _setup()
    with tempfile.TemporaryDirectory() as d:
        prefix = os.path.join(d, "rules")
        pack_mod.save_packed(packed, prefix)
        wire_path = os.path.join(d, "obs.rawire")
        w = wire_mod.WireWriter(
            wire_path, wire_mod.ruleset_fingerprint(packed), block_rows=batch
        )
        with w:
            for i in range(chunks):
                t = np.ascontiguousarray(_tuples(packed, batch, seed=i).T)
                dense = t[:, t[pack_mod.T_VALID] == 1]
                w.add(pack_mod.compact_batch(dense), batch, batch - dense.shape[1])

        def run_cli(extra: list[str], out: str) -> dict:
            rc = cli.main([
                "run", "--ruleset", prefix, "--logs", wire_path,
                "--batch-size", str(batch), "--json", "--out", out, *extra,
            ])
            if rc != 0:
                raise RuntimeError(f"obs bench CLI run failed rc={rc}")
            with open(out, "r", encoding="utf-8") as f:
                return json.load(f)

        # warm: fills the in-process jit caches so both measured runs
        # carry the same (near-zero) compile residue
        run_cli([], os.path.join(d, "warm.json"))
        rep_off = run_cli([], os.path.join(d, "off.json"))
        trace_dir = os.path.join(d, "trace")
        metrics_path = os.path.join(d, "metrics.jsonl")
        rep_on = run_cli(
            ["--trace-out", trace_dir, "--metrics-out", metrics_path,
             "--metrics-every", "1"],
            os.path.join(d, "on.json"),
        )
        attribution = trace_summary.summarize(
            os.path.join(trace_dir, "trace.json")
        )
        with open(metrics_path, "r", encoding="utf-8") as f:
            metrics_records = [json.loads(ln) for ln in f if ln.strip()]
    off = rep_off["totals"]["sustained_lines_per_sec"]
    on = rep_on["totals"]["sustained_lines_per_sec"]
    baseline_r06 = 439_000.0  # NORTHSTAR_SUSTAINED_1E8_r06_cpu.json, 8-dev CPU
    return {
        "metric": "obs_disarmed_sustained_lines_per_sec",
        "value": off,
        "unit": "lines/sec",
        "vs_baseline": round(off / baseline_r06, 4),
        "detail": {
            "platform": jax.devices()[0].platform,
            "devices": len(jax.devices()),
            "lines": n,
            "chunks": chunks,
            "disarmed_sustained_lines_per_sec": off,
            "armed_sustained_lines_per_sec": on,
            "armed_over_disarmed": round(on / off, 4) if off else 0.0,
            "vs_r06_baseline": round(off / baseline_r06, 4),
            "r06_baseline_lines_per_sec": baseline_r06,
            "metrics_records": len(metrics_records),
            "stage_attribution": {
                "wall_sec": attribution["wall_sec"],
                "processes": attribution["processes"],
                "stages": attribution["stages"],
                "top_stalls": attribution["top_stalls"],
            },
        },
    }


def bench_steptrace() -> dict:
    """Device attribution guard (ISSUE 8): capture window + trace diff.

    Three measured runs over one wire corpus through the production CLI
    at the PRODUCTION batch geometry (batch 1<<16 — attribution must
    cover the step that actually ships; the 1<<20 throughput geometry
    floods the CPU profiler's event buffer and collapses the stage
    table):

    - **disarmed** (no --devprof-out): every devprof seam is one
      None-check; the sustained rate is the baseline.
    - **armed** (--devprof-out, counts_impl=scatter): one bounded
      capture window inside the run.  The artifact records the
      armed/disarmed sustained ratio with the capture pause priced
      apart (``window_wall_sec`` — profiling a step on XLA:CPU emits an
      event per scatter-loop iteration, 10-50x the plain step; the same
      separation discipline as compile_sec, r6), budget >= 0.98
      OUTSIDE the window; the raw including-pause ratio is reported
      alongside, never hidden.  Plus the attributed fraction
      (acceptance >= 0.90, remainder explicit), the per-stage table —
      the named replacement for DESIGN §8's hand-derived fusion.N
      rows — and report bit-identity armed vs disarmed.
    - **armed, counts_impl=matmul**: the second capture
      ``tools/trace_diff.py`` consumes; the per-stage delta table +
      fusion-boundary verdict land in the artifact — the evidence
      format the scatter-wall work (ROADMAP item 2) and the two
      VERDICT inversions will be closed with.

    ``RA_STEPTRACE_LINES`` overrides the corpus size (default 2M).
    """
    import os
    import tempfile

    import jax

    from ruleset_analysis_tpu import cli
    from ruleset_analysis_tpu.hostside import pack as pack_mod
    from ruleset_analysis_tpu.hostside import wire as wire_mod

    sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "tools"))
    import trace_diff

    n = int(float(os.environ.get("RA_STEPTRACE_LINES", "2e6")))
    batch = 1 << 16
    chunks = max(8, (n + batch - 1) // batch)
    n = chunks * batch
    steps = min(4, chunks - 2)
    packed = _setup()
    from ruleset_analysis_tpu.runtime.report import VOLATILE_TOTALS

    volatile = VOLATILE_TOTALS

    def image(rep: dict) -> dict:
        rep = json.loads(json.dumps(rep))
        for k in volatile:
            rep["totals"].pop(k, None)
        return rep

    with tempfile.TemporaryDirectory() as d:
        prefix = os.path.join(d, "rules")
        pack_mod.save_packed(packed, prefix)
        wire_path = os.path.join(d, "steptrace.rawire")
        w = wire_mod.WireWriter(
            wire_path, wire_mod.ruleset_fingerprint(packed), block_rows=batch
        )
        with w:
            for i in range(chunks):
                t = np.ascontiguousarray(_tuples(packed, batch, seed=i).T)
                dense = t[:, t[pack_mod.T_VALID] == 1]
                w.add(pack_mod.compact_batch(dense), batch, batch - dense.shape[1])

        def run_cli(extra: list[str], out: str) -> dict:
            rc = cli.main([
                "run", "--ruleset", prefix, "--logs", wire_path,
                "--batch-size", str(batch), "--json", "--out", out, *extra,
            ])
            if rc != 0:
                raise RuntimeError(f"steptrace bench CLI run failed rc={rc}")
            with open(out, "r", encoding="utf-8") as f:
                return json.load(f)

        # warm the jit caches so both measured runs carry the same
        # (near-zero) compile residue
        run_cli([], os.path.join(d, "warm.json"))
        rep_off = run_cli([], os.path.join(d, "off.json"))
        dp_scatter = os.path.join(d, "dp-scatter")
        rep_on = run_cli(
            ["--devprof-out", dp_scatter, "--devprof-steps", str(steps),
             "--devprof-warmup", "2"],
            os.path.join(d, "on.json"),
        )
        dp_matmul = os.path.join(d, "dp-matmul")
        run_cli(
            ["--counts-impl", "matmul", "--devprof-out", dp_matmul,
             "--devprof-steps", str(steps), "--devprof-warmup", "2"],
            os.path.join(d, "matmul.json"),
        )
        cap_a = trace_diff.load_capture(dp_scatter)
        cap_b = trace_diff.load_capture(dp_matmul)
        diff = trace_diff.diff_captures(cap_a, cap_b)
        for side in ("A", "B"):
            diff[side].pop("path", None)  # tempdir paths are noise
    off = rep_off["totals"]["sustained_lines_per_sec"]
    on = rep_on["totals"]["sustained_lines_per_sec"]
    cap = rep_on["totals"]["devprof"]
    # the capture pause (profiler live for the bounded window) priced
    # apart from the armed run's sustained rate, exactly as compile is:
    # lines_this_run / (elapsed - compile - window_wall)
    t_on = rep_on["totals"]
    pause = cap.get("window_wall_sec") or 0.0
    ex_window = t_on["elapsed_sec"] - t_on["compile_sec"] - pause
    on_ex = (
        round(t_on["throughput"]["lines"] / ex_window, 1)
        if ex_window > 0
        else 0.0
    )
    return {
        "metric": "devprof_attributed_frac",
        "value": cap["attributed_frac"],
        "unit": "fraction of device-step time attributed to named stages",
        "vs_baseline": round(on_ex / off, 4) if off else 0.0,
        "detail": {
            "platform": jax.devices()[0].platform,
            "devices": len(jax.devices()),
            "lines": n,
            "chunks": chunks,
            "capture_steps": cap["steps_profiled"],
            "warmup": cap["warmup"],
            "disarmed_sustained_lines_per_sec": off,
            "armed_sustained_lines_per_sec_ex_window": on_ex,
            "armed_sustained_lines_per_sec_incl_window": on,
            # the >= 0.98 budget applies OUTSIDE the bounded capture
            # pause (reported right below, never hidden)
            "armed_over_disarmed": round(on_ex / off, 4) if off else 0.0,
            "armed_over_disarmed_incl_window": (
                round(on / off, 4) if off else 0.0
            ),
            "capture_pause_sec": pause,
            "attributed_frac": cap["attributed_frac"],
            "unattributed": cap["unattributed"],
            "report_identical_armed_vs_disarmed": (
                image(rep_off) == image(rep_on)
            ),
            "stages": cap["stages"],
            "programs": {
                label: {
                    k: v for k, v in prog.items() if k != "fusions"
                }
                for label, prog in cap["programs"].items()
            },
            "cross_stage_fusions": len(cap["cross_stage_fusions"]),
            "trace_diff_scatter_vs_matmul": diff,
        },
    }


def bench_stepvariants() -> dict:
    """Scatter-vs-sorted A/B grid + the two §8 inversion capture pairs
    (ISSUE 9) — the committed evidence is BENCH_SEGSUM_r13_cpu.json.

    **The grid** drives the production batch geometry (batch 1<<16, the
    default sketch) over one wire corpus through the stream driver for
    every update formulation variant: ``update_impl scatter/sorted`` x
    ``topk_every 1/4``.  Per variant: a warmed sustained e2e rate, a
    bounded devprof capture (per-stage µs/step), a ``trace_diff`` delta
    table vs the scatter baseline, and an explicit keep/reject verdict —
    a measured rejection with trace evidence is a valid outcome; a
    silent keep is not.  Reports are asserted bit-identical between
    impls at equal cadence (the tentpole's contract).

    **The inversion pairs** close VERDICT Weak #2/#3 with committed
    trace diffs instead of smells:

    - flat vs stacked at the ~27k-row multifw geometry (the TPU 0.78x
      inversion, BENCH_SUITE_r05_tpu.json config4): one capture pair +
      fusion-boundary verdict;
    - counts scatter vs matmul at the production geometry (stage win /
      step loss, BENCH_r05_local.json step_variants): one capture pair
      showing where the step time went instead.

    CPU caveat (DESIGN §14): per-stage ABSOLUTE times on XLA:CPU are
    profiling-amplified on loop-lowered scatters; shares are indicative
    and fusion-boundary detection is exact.  The TPU rows re-capture
    through the same plane at the next tunnel window (ROADMAP item 5).
    ``RA_SEGSUM_LINES`` overrides the grid corpus size (default 1M).
    """
    import os
    import tempfile

    import jax

    from ruleset_analysis_tpu.config import AnalysisConfig, SketchConfig
    from ruleset_analysis_tpu.hostside import pack as pack_mod
    from ruleset_analysis_tpu.hostside import wire as wire_mod
    from ruleset_analysis_tpu.runtime import devprof
    from ruleset_analysis_tpu.runtime.stream import (
        run_stream_packed,
        run_stream_wire,
    )

    sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "tools"))
    import trace_diff

    n = int(float(os.environ.get("RA_SEGSUM_LINES", "1e6")))
    batch = 1 << 16
    chunks = max(6, (n + batch - 1) // batch)
    n = chunks * batch
    cap_steps, cap_warmup = 2, 2
    packed = _setup()
    from ruleset_analysis_tpu.runtime.report import VOLATILE_TOTALS

    volatile = VOLATILE_TOTALS

    def image(rep) -> dict:
        j = json.loads(rep.to_json())
        for k in volatile:
            j["totals"].pop(k, None)
        return j

    def verdict(e2e_ratio: float) -> str:
        if e2e_ratio >= 1.02:
            return "keep (measured e2e win on this backend)"
        if e2e_ratio <= 0.98:
            return (
                "reject on cpu (measured e2e loss; stage table + boundary "
                "diff committed — re-evaluate on TPU, ROADMAP item 5)"
            )
        return "neutral on cpu (within noise; TPU decides)"

    with tempfile.TemporaryDirectory() as d:
        wire_path = os.path.join(d, "segsum.rawire")
        w = wire_mod.WireWriter(
            wire_path, wire_mod.ruleset_fingerprint(packed), block_rows=batch
        )
        with w:
            for i in range(chunks):
                t = np.ascontiguousarray(_tuples(packed, batch, seed=i).T)
                dense = t[:, t[pack_mod.T_VALID] == 1]
                w.add(pack_mod.compact_batch(dense), batch, batch - dense.shape[1])

        def cfg_for(update_impl="scatter", topk_every=1, counts_impl="scatter"):
            return AnalysisConfig(
                batch_size=batch,
                sketch=SketchConfig(topk_every=topk_every),
                update_impl=update_impl,
                counts_impl=counts_impl,
            )

        def sustained(cfg) -> tuple[float, object]:
            run_stream_wire(packed, [wire_path], cfg)  # warm the jit
            rep = run_stream_wire(packed, [wire_path], cfg)
            return rep.totals["sustained_lines_per_sec"], rep

        def capture(cfg, name: str) -> dict:
            devprof.shutdown()
            out = os.path.join(d, f"cap-{name}")
            devprof.arm(out, steps=cap_steps, warmup=cap_warmup, label=name)
            run_stream_wire(packed, [wire_path], cfg)
            devprof.finalize_if_armed()
            return trace_diff.load_capture(out)

        variants = [
            ("scatter", dict()),
            ("sorted", dict(update_impl="sorted")),
            ("scatter_topk4", dict(topk_every=4)),
            ("sorted_topk4", dict(update_impl="sorted", topk_every=4)),
        ]
        grid, caps, reps = {}, {}, {}
        for name, kw in variants:
            log(f"stepvariants: grid variant {name}")
            lps, rep = sustained(cfg_for(**kw))
            caps[name] = capture(cfg_for(**kw), name)
            reps[name] = rep
            grid[name] = {"sustained_lines_per_sec": round(lps, 1)}
        base_lps = grid["scatter"]["sustained_lines_per_sec"]
        for name, _kw in variants:
            g = grid[name]
            ratio = round(g["sustained_lines_per_sec"] / base_lps, 4)
            g["e2e_ratio_vs_scatter"] = ratio
            g["step_us_per_step"] = round(
                caps[name]["device_us_total"]
                / max(1, caps[name]["steps_profiled"]),
                1,
            )
            g["stages_pct"] = {
                s: st["pct"] for s, st in caps[name]["stages"].items()
            }
            if name != "scatter":
                diff = trace_diff.diff_captures(caps["scatter"], caps[name])
                for side in ("A", "B"):
                    diff[side].pop("path", None)
                g["trace_diff_vs_scatter"] = diff
                g["verdict"] = verdict(ratio)
            else:
                g["verdict"] = "baseline"
        # the tentpole's contract: bit-identical reports between impls at
        # equal selection cadence (full-matrix enforcement lives in
        # tests/test_sorted_update.py; this pins the bench geometry too)
        ident = {
            "sorted_vs_scatter": image(reps["sorted"]) == image(reps["scatter"]),
            "sorted_vs_scatter_topk4": image(reps["sorted_topk4"])
            == image(reps["scatter_topk4"]),
        }
        if not all(ident.values()):
            raise AssertionError(f"bit-identity violated: {ident}")

        # ---- inversion pair 1: flat vs stacked @ ~27k rows ----
        log("stepvariants: inversion pair flat vs stacked @27k rows")
        packed27 = _setup(n_acls=2, rules_per_acl=1024, firewalls=8)
        batch27, chunks27 = 1 << 13, 5
        feeds = [
            np.ascontiguousarray(_tuples(packed27, batch27, seed=i).T)
            for i in range(2)
        ]

        def arrays():
            for i in range(chunks27):
                yield feeds[i % len(feeds)]

        def run27(layout, cap_dir=None):
            cfg = AnalysisConfig(
                batch_size=batch27,
                sketch=SketchConfig(cms_width=1 << 14, cms_depth=4),
                layout=layout,
            )
            if cap_dir is None:
                run_stream_packed(packed27, arrays(), cfg)  # warm
                t0 = time.perf_counter()
                run_stream_packed(packed27, arrays(), cfg)
                return batch27 * chunks27 / (time.perf_counter() - t0)
            devprof.shutdown()
            devprof.arm(cap_dir, steps=2, warmup=1, label=f"{layout}-27k")
            run_stream_packed(packed27, arrays(), cfg)
            devprof.finalize_if_armed()
            return trace_diff.load_capture(cap_dir)

        flat_lps = run27("flat")
        stacked_lps = run27("stacked")
        cap_flat = run27("flat", os.path.join(d, "cap-flat27"))
        cap_stacked = run27("stacked", os.path.join(d, "cap-stacked27"))
        diff_stacked = trace_diff.diff_captures(cap_flat, cap_stacked)
        for side in ("A", "B"):
            diff_stacked[side].pop("path", None)

        # ---- inversion pair 2: counts scatter vs matmul, production ----
        log("stepvariants: inversion pair counts scatter vs matmul")
        mat_lps, _rep = sustained(cfg_for(counts_impl="matmul"))
        cap_matmul = capture(cfg_for(counts_impl="matmul"), "counts-matmul")
        diff_matmul = trace_diff.diff_captures(caps["scatter"], cap_matmul)
        for side in ("A", "B"):
            diff_matmul[side].pop("path", None)
        devprof.shutdown()

    sorted_ratio = grid["sorted"]["e2e_ratio_vs_scatter"]
    return {
        "metric": "segsum_sorted_over_scatter_e2e",
        "value": sorted_ratio,
        "unit": "sustained e2e ratio, update_impl=sorted vs scatter",
        "vs_baseline": sorted_ratio,
        "detail": {
            "platform": jax.devices()[0].platform,
            "devices": len(jax.devices()),
            "lines": n,
            "chunks": chunks,
            "batch": batch,
            "capture_steps": cap_steps,
            "grid": grid,
            "report_identity": ident,
            "inversions": {
                "stacked_27k": {
                    "flat_rows": int(packed27.rules.shape[0]),
                    "batch": batch27,
                    "cpu_flat_lines_per_sec": round(flat_lps, 1),
                    "cpu_stacked_lines_per_sec": round(stacked_lps, 1),
                    "cpu_stacked_over_flat": round(
                        stacked_lps / max(flat_lps, 1.0), 4
                    ),
                    "tpu_committed_ratio": 0.78,
                    "trace_diff_flat_vs_stacked": diff_stacked,
                    "fusion_boundaries_changed": diff_stacked[
                        "fusion_boundaries_changed"
                    ],
                },
                "counts_matmul": {
                    "cpu_matmul_lines_per_sec": round(mat_lps, 1),
                    "cpu_matmul_over_scatter": round(mat_lps / base_lps, 4),
                    "trace_diff_scatter_vs_matmul": diff_matmul,
                    "fusion_boundaries_changed": diff_matmul[
                        "fusion_boundaries_changed"
                    ],
                },
            },
            "cpu_caveat": (
                "XLA:CPU profiling amplifies loop-lowered scatters and "
                "sorts; shares indicative, boundary detection exact; TPU "
                "re-capture at the next tunnel window (ROADMAP item 5)"
            ),
        },
    }


def bench_coalesce() -> dict:
    """Flow-coalescing guard (ISSUE 5): skewed speedup + uniform overhead.

    Three wire corpora through the production CLI at the sustained
    geometry (batch 1<<20, wire mmap -> pipelined ingest -> sharded
    step), sweeping traffic skew:

    - **uniform** — independent lines (compaction ratio ~1).  Guards the
      overhead: ``--coalesce auto`` must sample, disable itself, and
      land within ~3% of the off baseline; ``on`` prices the always-on
      hash pass.
    - **zipf s=1.0 / s=1.2** — Zipf flow repetition from a bounded pool
      (synth.zipf_weights; the heavy-hitter regime of real firewall
      logs).  Guards the win: ``--coalesce on`` vs ``off`` sustained
      speedup, expected >= 1.3x (the step is scatter/device-bound, so
      shrinking device rows by the compaction ratio dominates).

    ``RA_COALESCE_LINES`` overrides the per-corpus size (default ~6M).
    """
    import os
    import tempfile

    import jax

    from ruleset_analysis_tpu import cli
    from ruleset_analysis_tpu.hostside import pack as pack_mod
    from ruleset_analysis_tpu.hostside import synth as synth_mod
    from ruleset_analysis_tpu.hostside import wire as wire_mod

    n = int(float(os.environ.get("RA_COALESCE_LINES", "6e6")))
    batch = 1 << 20
    chunks = max(3, (n + batch - 1) // batch)
    n = chunks * batch
    pool_flows = 1 << 18
    packed = _setup()
    pool = synth_mod.flow_pool(packed, pool_flows, seed=7)
    sweeps = {}
    with tempfile.TemporaryDirectory() as d:
        prefix = os.path.join(d, "rules")
        pack_mod.save_packed(packed, prefix)

        def write_corpus(path: str, skew: float | None) -> None:
            w = wire_mod.WireWriter(
                path, wire_mod.ruleset_fingerprint(packed), block_rows=batch
            )
            p = (
                synth_mod.zipf_weights(pool.shape[0], skew)
                if skew is not None
                else None
            )
            with w:
                for i in range(chunks):
                    if skew is None:
                        t = _tuples(packed, batch, seed=100 + i)
                    else:
                        rng = np.random.default_rng(1000 + i)
                        t = pool[rng.choice(pool.shape[0], size=batch, p=p)]
                    t = np.ascontiguousarray(t.T)
                    dense = t[:, t[pack_mod.T_VALID] == 1]
                    w.add(
                        pack_mod.compact_batch(dense), batch,
                        batch - dense.shape[1],
                    )

        def run_cli(wire_path: str, coalesce: str, out: str) -> dict:
            rc = cli.main([
                "run", "--ruleset", prefix, "--logs", wire_path,
                "--batch-size", str(batch), "--coalesce", coalesce,
                "--json", "--out", out,
            ])
            if rc != 0:
                raise RuntimeError(f"coalesce bench CLI run failed rc={rc}")
            with open(out, "r", encoding="utf-8") as f:
                return json.load(f)

        for name, skew in [("uniform", None), ("zipf_1.0", 1.0), ("zipf_1.2", 1.2)]:
            wp = os.path.join(d, f"{name}.rawire")
            write_corpus(wp, skew)
            # warm fills the jit caches (off-path shapes); the coalesced
            # bucket shapes compile inside their own measured run's
            # compile_sec, which the sustained rate already excludes
            run_cli(wp, "off", os.path.join(d, "warm.json"))
            rep_off = run_cli(wp, "off", os.path.join(d, f"{name}-off.json"))
            rep_on = run_cli(wp, "on", os.path.join(d, f"{name}-on.json"))
            off = rep_off["totals"]["sustained_lines_per_sec"]
            on = rep_on["totals"]["sustained_lines_per_sec"]
            entry = {
                "skew": skew if skew is not None else "uniform",
                "lines": n,
                "off_sustained_lines_per_sec": off,
                "on_sustained_lines_per_sec": on,
                "on_speedup": round(on / off, 4) if off else 0.0,
                "compaction_ratio": rep_on["totals"]["coalesce"][
                    "compaction_ratio"
                ],
            }
            if skew is None:
                # production setting for unknown traffic: auto samples a
                # few batches and turns itself off — the overhead guard
                rep_auto = run_cli(
                    wp, "auto", os.path.join(d, f"{name}-auto.json")
                )
                auto = rep_auto["totals"]["sustained_lines_per_sec"]
                entry["auto_sustained_lines_per_sec"] = auto
                entry["auto_over_off"] = round(auto / off, 4) if off else 0.0
                entry["auto_disabled"] = (
                    rep_auto["totals"]["coalesce"]["active"] is False
                )
            sweeps[name] = entry

    headline = sweeps["zipf_1.0"]["on_speedup"]
    return {
        "metric": "coalesce_sustained_speedup_zipf1",
        "value": headline,
        "unit": "x vs coalesce=off",
        "vs_baseline": headline,
        "detail": {
            "platform": jax.devices()[0].platform,
            "devices": len(jax.devices()),
            "batch": batch,
            "chunks": chunks,
            "pool_flows": pool_flows,
            "guards": {
                "skewed_speedup_min": 1.3,
                "uniform_auto_overhead_max": 0.03,
                "skewed_speedup_ok": sweeps["zipf_1.0"]["on_speedup"] >= 1.3,
                "uniform_overhead_ok": sweeps["uniform"].get(
                    "auto_over_off", 0.0
                ) >= 0.97,
            },
            "sweeps": sweeps,
        },
    }


def bench_servesoak() -> dict:
    """Serve-mode soak (ISSUE 6): live listener -> windowed reports.

    Drives the production ``serve`` CLI against a synthetic syslog
    stream replayed at a paced rate over a loopback TCP socket, across
    >= 3 deterministic window rotations and ONE mid-stream hot ruleset
    reload (a renumbering re-pack picked up by the file watcher).  The
    artifact records the sustained serve-loop rate, per-rotation
    latency and the reload pause (from the obs trace's serve spans via
    ``tools.trace_summary``), and the drop count — which must be 0 at
    the offered rate for the soak to count as sustained.

    ``RA_SOAK_LINES`` (default 60k; 3 windows) and ``RA_SOAK_RATE``
    (default 12k lines/s offered — within the serve loop's measured
    per-line steady-state capacity on the 8-dev CPU mesh, so the
    kept-up guard is judged against a rate the artifact claims) size
    the soak.
    """
    import os
    import socket
    import tempfile
    import threading

    import jax

    from ruleset_analysis_tpu import cli
    from ruleset_analysis_tpu.hostside import aclparse
    from ruleset_analysis_tpu.hostside import pack as pack_mod
    from ruleset_analysis_tpu.hostside import synth

    sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "tools"))
    import trace_summary

    total = int(float(os.environ.get("RA_SOAK_LINES", "60000")))
    rate = float(os.environ.get("RA_SOAK_RATE", "12000"))
    windows = 3
    w_lines = total // windows
    total = w_lines * windows
    # small batches carry a large fixed dispatch cost (collective setup
    # dominates below ~16k rows); a live service sizes its batch to its
    # window, not to a file
    BATCH = 16384

    # OLD ruleset + a NEW re-pack that deletes the first access-list
    # line: every later rule renumbers, so the mid-soak reload exercises
    # the full migration path (not the identity fast path)
    cfg_text = synth.synth_config(n_acls=2, rules_per_acl=12, seed=0)
    old_packed = pack_mod.pack_rulesets([aclparse.parse_asa_config(cfg_text, "fw1")])
    keep = [ln for ln in cfg_text.splitlines() if ln.startswith("access-list")]
    new_text = cfg_text.replace(keep[0] + "\n", "", 1)
    new_packed = pack_mod.pack_rulesets([aclparse.parse_asa_config(new_text, "fw1")])
    t = _tuples(old_packed, total, seed=3)
    lines = synth.render_syslog(old_packed, t, seed=3)

    def wait_for(pred, timeout, what):
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if pred():
                return
            time.sleep(0.05)
        raise RuntimeError(f"servesoak: timed out waiting for {what}")

    def read_json(path):
        with open(path, "r", encoding="utf-8") as f:
            return json.load(f)

    def http_get(addr, path):
        import urllib.error
        import urllib.request

        for attempt in range(3):
            try:
                with urllib.request.urlopen(
                    f"http://{addr[0]}:{addr[1]}{path}", timeout=10
                ) as r:
                    return json.load(r)
            except (urllib.error.URLError, OSError):
                if attempt == 2:
                    raise
                time.sleep(0.2)

    with tempfile.TemporaryDirectory() as d:
        prefix = os.path.join(d, "rules")
        pack_mod.save_packed(old_packed, prefix)
        serve_dir = os.path.join(d, "serve")
        trace_dir = os.path.join(d, "trace")

        # warm the memoized step builders + jit caches for BOTH rulesets
        # BEFORE the service starts (a production service compiles at
        # deploy, not mid-window), so the measured soak prices the serve
        # loop, not XLA compiles; geometry must MATCH the serve CLI
        # flags below exactly — the builders memoize on (mesh, sketch
        # geometry, n_keys) and jit specializes on the register shapes
        from ruleset_analysis_tpu.config import AnalysisConfig, SketchConfig
        from ruleset_analysis_tpu.runtime.stream import run_stream

        warm_cfg = AnalysisConfig(
            backend="tpu", batch_size=BATCH, prefetch_depth=0,
            sketch=SketchConfig(cms_width=1 << 14, cms_depth=4, hll_p=8),
        )
        run_stream(old_packed, iter(lines[:64]), warm_cfg)
        run_stream(new_packed, iter(lines[:64]), warm_cfg)

        rc: dict = {}
        th = threading.Thread(target=lambda: rc.update(rc=cli.main([
            "serve", "--ruleset", prefix,
            "--listen", "tcp:127.0.0.1:0",
            "--window", f"lines:{w_lines}",
            "--serve-dir", serve_dir,
            "--max-windows", str(windows),
            "--stop-after", "600",
            "--batch-size", str(BATCH),
            "--http", "127.0.0.1:0",
            "--reload-poll", "0.2",
            "--queue-lines", str(1 << 18),
            # ring-checkpoint ONCE at the final rotation: resume safety
            # is exercised, but the paced-rate phase is not serialized
            # behind this filesystem's fsync latency (production windows
            # are minutes-to-hours; these are ~1 s)
            "--checkpoint-every-windows", str(windows),
            "--trace-out", trace_dir,
        ])))
        th.start()
        ep_path = os.path.join(serve_dir, "endpoint.json")
        wait_for(lambda: os.path.exists(ep_path), 60, "serve endpoint")
        ep = read_json(ep_path)
        http = tuple(ep["http"])
        (tcp_addr,) = [a for a in ep["listeners"].values()]

        def send(seg, sock):
            # paced replay: bursts of 500 lines against the wall clock
            t0 = time.perf_counter()
            sent = 0
            for i in range(0, len(seg), 500):
                burst = seg[i:i + 500]
                sock.sendall(("\n".join(burst) + "\n").encode())
                sent += len(burst)
                lag = sent / rate - (time.perf_counter() - t0)
                if lag > 0:
                    time.sleep(lag)

        wall_start = time.time()
        s = socket.create_connection(tuple(tcp_addr))
        cut = w_lines + w_lines // 2  # reload lands mid-window-1
        send(lines[:cut], s)
        t_gap = time.perf_counter()
        pack_mod.save_packed(new_packed, prefix)  # watcher fires the reload
        wait_for(
            lambda: http_get(http, "/health")["reloads"] == 1, 60, "hot reload"
        )
        reload_wait = time.perf_counter() - t_gap  # sender idle, not service
        send(lines[cut:], s)
        s.close()
        th.join(timeout=300)
        if th.is_alive() or rc.get("rc") != 0:
            raise RuntimeError(f"servesoak: serve CLI failed rc={rc.get('rc')}")
        # the sustained clock stops at the LAST window's publication
        # (its report file's mtime): the final ring checkpoint + HTTP
        # teardown after it are shutdown cost, not serve-loop rate
        t_last_pub = os.path.getmtime(
            os.path.join(serve_dir, f"window-{windows - 1:06d}.json")
        )
        elapsed = max(t_last_pub - wall_start - reload_wait, 1e-3)
        summary = read_json(os.path.join(serve_dir, "summary.json"))
        per_window = [
            read_json(os.path.join(serve_dir, f"window-{i:06d}.json"))["totals"]
            for i in range(windows)
        ]
        attribution = trace_summary.summarize(os.path.join(trace_dir, "trace.json"))
    serve_attr = attribution.get("serve", {})
    sustained = round(total / elapsed, 1)
    return {
        "metric": "servesoak_sustained_lines_per_sec",
        "value": sustained,
        "unit": "lines/sec",
        "vs_baseline": round(sustained / rate, 4),  # achieved / offered
        "detail": {
            "platform": jax.devices()[0].platform,
            "devices": len(jax.devices()),
            "lines": total,
            "offered_rate_lines_per_sec": rate,
            "window_lines": w_lines,
            "windows_published": summary["windows_published"],
            "rotations": serve_attr.get("rotations", 0),
            "rotation_mean_ms": serve_attr.get("rotation_mean_ms"),
            "rotation_max_ms": serve_attr.get("rotation_max_ms"),
            "reload_pause_ms": serve_attr.get("reload_pause_ms"),
            "reload_watch_wait_sec": round(reload_wait, 3),
            "drops": summary["drops"],
            "reloads": summary["reloads"],
            "reload_errors": summary["reload_errors"],
            "quarantine_hits": summary["quarantine_hits"],
            "per_window_lines_per_sec": [
                t_["lines_per_sec"] for t_ in per_window
            ],
            "guards": {
                "drop_count_zero": summary["drops"] == 0,
                "three_rotations": summary["windows_published"] >= 3,
                "one_live_reload": summary["reloads"] == 1
                and summary["reload_errors"] == 0,
                "kept_up_with_offered_rate": total / elapsed >= 0.9 * rate,
            },
        },
    }


def bench_autoscale() -> dict:
    """Metrics-driven autoscaling under a square-wave offered load (ISSUE 7).

    Drives the production ``serve --autoscale`` CLI with bursts of
    loopback traffic separated by idle gaps longer than the flap-damping
    window: each burst must scale the device mesh OUT (sustained queue
    pressure), each idle must scale it back IN (sustained starvation)
    after the cooldown, with ZERO flaps and ZERO drops across the whole
    soak.  The bench measures the load->decision response latency from
    the outside (polling the same ``/metrics`` gauges the policy reads)
    and folds in the trace plane's decision evidence + time-to-effect.

    ``RA_AS_BURSTS`` (default 3) and ``RA_AS_BURST_LINES`` (default 100k)
    size the square wave.
    """
    import os
    import socket
    import tempfile
    import threading
    import urllib.request

    import jax

    from ruleset_analysis_tpu import cli
    from ruleset_analysis_tpu.hostside import aclparse
    from ruleset_analysis_tpu.hostside import pack as pack_mod
    from ruleset_analysis_tpu.hostside import synth

    sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "tools"))
    import trace_summary

    bursts = int(os.environ.get("RA_AS_BURSTS", "3"))
    w_lines = int(float(os.environ.get("RA_AS_BURST_LINES", "100000")))
    total = bursts * w_lines
    BATCH = 2048
    QUEUE = 1 << 15
    # policy knobs of the soak: damping window = 2*(cooldown+sustain) = 3s;
    # the idle gaps below hold longer than that, so zero flaps is the
    # CORRECT outcome, not a lucky one
    SUSTAIN, COOLDOWN = 0.5, 1.0
    MIN_W, MAX_W = 2, 4

    cfg_text = synth.synth_config(n_acls=2, rules_per_acl=12, seed=0)
    packed = pack_mod.pack_rulesets([aclparse.parse_asa_config(cfg_text, "fw1")])
    t = _tuples(packed, total, seed=5)
    lines = synth.render_syslog(packed, t, seed=5)

    def wait_for(pred, timeout, what):
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if pred():
                return
            time.sleep(0.05)
        raise RuntimeError(f"autoscale soak: timed out waiting for {what}")

    def read_json(path):
        with open(path, "r", encoding="utf-8") as f:
            return json.load(f)

    def http_json(addr, path):
        import urllib.error

        for attempt in range(3):
            try:
                with urllib.request.urlopen(
                    f"http://{addr[0]}:{addr[1]}{path}", timeout=10
                ) as r:
                    return json.load(r)
            except (urllib.error.URLError, OSError):
                if attempt == 2:
                    raise
                time.sleep(0.2)

    with tempfile.TemporaryDirectory() as d:
        prefix = os.path.join(d, "rules")
        pack_mod.save_packed(packed, prefix)
        serve_dir = os.path.join(d, "serve")
        trace_dir = os.path.join(d, "trace")

        # pre-warm the jit caches for EVERY world rung the ladder can
        # visit (a production deploy compiles its geometries up front;
        # the step builders memoize on mesh identity and the serve
        # driver's fixed max-world batch padding makes the geometry
        # identical across rungs)
        from ruleset_analysis_tpu.config import AnalysisConfig, SketchConfig
        from ruleset_analysis_tpu.parallel import mesh as mesh_lib
        from ruleset_analysis_tpu.runtime.stream import run_stream

        warm_cfg = AnalysisConfig(
            backend="tpu", batch_size=BATCH, prefetch_depth=0,
            sketch=SketchConfig(cms_width=1 << 14, cms_depth=4, hll_p=8),
        )
        devs = list(jax.devices())
        for k in (MIN_W, MAX_W):
            run_stream(
                packed, iter(lines[:64]), warm_cfg,
                mesh=mesh_lib.make_mesh(devs[:k], axis=warm_cfg.mesh_axis),
            )

        rc: dict = {}
        th = threading.Thread(target=lambda: rc.update(rc=cli.main([
            "serve", "--ruleset", prefix,
            "--listen", "tcp:127.0.0.1:0",
            "--window", f"lines:{w_lines}",
            "--serve-dir", serve_dir,
            "--max-windows", str(bursts),
            "--stop-after", "600",
            "--batch-size", str(BATCH),
            "--http", "127.0.0.1:0",
            "--no-reload-watch",
            "--queue-lines", str(QUEUE),
            "--autoscale",
            "--autoscale-min", str(MIN_W),
            "--autoscale-max", str(MAX_W),
            "--autoscale-initial", str(MIN_W),
            "--autoscale-out-threshold", "0.25",
            "--autoscale-in-threshold", "0.8",
            "--autoscale-sustain", str(SUSTAIN),
            "--autoscale-cooldown", str(COOLDOWN),
            "--autoscale-budget", str(2 * bursts + 2),
            "--autoscale-poll", "0.1",
            "--trace-out", trace_dir,
        ])))
        th.start()
        ep_path = os.path.join(serve_dir, "endpoint.json")
        wait_for(lambda: os.path.exists(ep_path), 60, "serve endpoint")
        ep = read_json(ep_path)
        http = tuple(ep["http"])
        (tcp_addr,) = [a for a in ep["listeners"].values()]

        def gauge(name):
            return http_json(http, "/metrics").get(name, 0)

        damping = 2 * (COOLDOWN + SUSTAIN)
        wall_start = time.time()
        response_out, response_in = [], []
        s = socket.create_connection(tuple(tcp_addr))
        for i in range(bursts):
            seen_out = gauge("autoscale_scale_out_total")
            t0 = time.perf_counter()
            # high phase: offer the window's whole line budget as fast
            # as the queue absorbs it, throttling just under the drop
            # line — a closed-loop overload, so the queue-occupancy
            # pressure signal sustains on ANY host regardless of its
            # absolute device rate, and drops stay zero by construction
            seg = lines[i * w_lines:(i + 1) * w_lines]
            fired = False
            # ONE gauge fetch per 4096-line chunk: the serve loop answers
            # HTTP between lines, so a chatty sender would throttle
            # itself below the service's drain rate and never build the
            # very pressure the bench exists to create
            for j in range(0, len(seg), 4096):
                s.sendall(("\n".join(seg[j:j + 4096]) + "\n").encode())
                try:
                    g = http_json(http, "/metrics")
                    if not fired and g.get("autoscale_scale_out_total", 0) > seen_out:
                        response_out.append(round(time.perf_counter() - t0, 3))
                        fired = True
                    throttle_deadline = time.monotonic() + 120
                    while g.get("queue_depth", 0) > 0.55 * QUEUE:
                        if time.monotonic() > throttle_deadline:
                            raise RuntimeError(
                                "autoscale soak: queue never drained "
                                "below the throttle line (service wedged?)"
                            )
                        time.sleep(0.05)  # hold below the drop line
                        g = http_json(http, "/metrics")
                except OSError:
                    if i != bursts - 1 or j + 4096 < len(seg):
                        raise  # only the final rotation may take it down
                    break
            if i == bursts - 1:
                # the service stops itself at the final rotation (its
                # /metrics endpoint goes down with it); the summary's
                # decision log verifies this burst's scale-out below
                if not fired:
                    deadline = time.monotonic() + 120
                    while time.monotonic() < deadline and th.is_alive():
                        try:
                            if gauge("autoscale_scale_out_total") > seen_out:
                                response_out.append(
                                    round(time.perf_counter() - t0, 3)
                                )
                                break
                        except OSError:
                            break  # endpoint gone: the service finished
                        time.sleep(0.1)
                break
            if not fired:
                wait_for(
                    lambda: gauge("autoscale_scale_out_total") > seen_out,
                    120, f"scale-out on burst {i}",
                )
                response_out.append(round(time.perf_counter() - t0, 3))
            # low phase: wait for the scale-in, then hold the idle past
            # the damping window so the NEXT burst's out is a load
            # response, not a flap
            seen_in = gauge("autoscale_scale_in_total")
            t1 = time.perf_counter()
            wait_for(
                lambda: gauge("autoscale_scale_in_total") > seen_in,
                180, f"scale-in after burst {i}",
            )
            response_in.append(round(time.perf_counter() - t1, 3))
            time.sleep(damping)
        s.close()
        th.join(timeout=300)
        if th.is_alive() or rc.get("rc") != 0:
            raise RuntimeError(f"autoscale soak: serve CLI failed rc={rc.get('rc')}")
        elapsed = max(time.time() - wall_start, 1e-3)
        summary = read_json(os.path.join(serve_dir, "summary.json"))
        attribution = trace_summary.summarize(os.path.join(trace_dir, "trace.json"))
    asum = summary["autoscale"]
    tr = attribution.get("autoscale", {})
    mean_out = round(sum(response_out) / max(len(response_out), 1), 3)
    return {
        "metric": "autoscale_scale_out_response_sec",
        "value": mean_out,
        "unit": "sec (burst start -> scale-out observed at /metrics)",
        "vs_baseline": round(mean_out / max(SUSTAIN, 1e-9), 3),  # x sustain floor
        "detail": {
            "platform": jax.devices()[0].platform,
            "devices": len(jax.devices()),
            "lines": total,
            "bursts": bursts,
            "burst_lines": w_lines,
            "square_wave_idle_sec": damping,
            "world_ladder": [MIN_W, MAX_W],
            "queue_lines": QUEUE,
            "policy": {
                "out_threshold": 0.25, "in_threshold": 0.8,
                "sustain_sec": SUSTAIN, "cooldown_sec": COOLDOWN,
                "damping_window_sec": damping,
            },
            "scale_out_events": asum["scale_out"],
            "scale_in_events": asum["scale_in"],
            "flaps": asum["flaps"],
            "budget_left": asum["budget_left"],
            "final_world": summary["world"],
            "decisions": asum["decisions"],
            "response_out_sec": response_out,
            "response_in_sec": response_in,
            "time_to_effect_mean_ms": tr.get("time_to_effect_mean_ms"),
            "time_to_effect_max_ms": tr.get("time_to_effect_max_ms"),
            "trace_flaps": tr.get("flaps"),
            "drops": summary["drops"],
            "windows_published": summary["windows_published"],
            "elapsed_sec": round(elapsed, 1),
            "guards": {
                "scale_out_on_every_burst": asum["scale_out"] >= bursts,
                "scale_in_after_every_idle": asum["scale_in"] >= bursts - 1,
                "zero_flaps": asum["flaps"] == 0 and tr.get("flaps", 0) == 0,
                "zero_drops": summary["drops"] == 0,
                "all_windows_published": summary["windows_published"] == bursts,
            },
        },
    }


def _feedscale_devices() -> int:
    import jax

    return len(jax.devices())


def bench_feedscale() -> dict:
    """Host-feed scale-out (ISSUE 11 / ROADMAP 3): the aggregate
    parse+feed curve an 8-chip mesh needs.

    Four sections, all on one corpus:

    - **simd**: scalar vs dispatched (AVX2/NEON) parse of the same bytes
      — full-parse lines/s A/B plus the bulk newline-scan GB/s A/B —
      with byte-identity asserted in-bench.
    - **parse_scaling**: feeder-consumption lines/s across worker
      counts (parse only, no device) — the per-core ceiling curve.
    - **convert_fleet**: `convert --workers N` wall rates, with w=1 vs
      w=N aggregate accounting asserted equal.
    - **e2e**: full device runs, global-queue vs per-chip-ring feed
      modes, sustained lines/s + the ring occupancy/starved gauges.

    The artifact states the HONEST aggregate: on a 1-core container the
    >=8M lines/s point cannot be demonstrated locally, so the JSON
    carries the measured per-core ceiling and the cores needed to clear
    8M lines/s at that ceiling (the v5e-8 host, with >100 usable cores,
    sits far above that bar).
    """
    import ctypes
    import os
    import tempfile

    from ruleset_analysis_tpu.config import AnalysisConfig, SketchConfig
    from ruleset_analysis_tpu.hostside import aclparse, fastparse, synth
    from ruleset_analysis_tpu.hostside import pack as pack_mod
    from ruleset_analysis_tpu.hostside.convertfleet import (
        convert_logs_fleet,
        read_manifest,
    )
    from ruleset_analysis_tpu.hostside.feeder import ParallelFeeder
    from ruleset_analysis_tpu.runtime import obs
    from ruleset_analysis_tpu.runtime.stream import run_stream_file

    try:
        cores = len(os.sched_getaffinity(0))
    except (AttributeError, OSError):
        cores = os.cpu_count() or 1

    cfg_text = synth.synth_config(
        n_acls=4, rules_per_acl=16, seed=1, egress_acls=True
    )
    rs = aclparse.parse_asa_config(cfg_text, "fw1")
    packed = pack_mod.pack_rulesets([rs])
    n_lines = 400_000
    lines = synth.render_syslog(
        packed, synth.synth_tuples(packed, n_lines, seed=2), seed=3,
        variety=0.6,
    )
    data = ("\n".join(lines) + "\n").encode()
    log(f"feedscale: corpus {n_lines} lines / {len(data) / 1e6:.1f} MB, "
        f"{cores} usable core(s), simd={fastparse.simd_kind()}")

    # ---- simd A/B: full parse + bulk newline scan, identity asserted
    def parse_once():
        pk = fastparse.NativePacker(packed)
        out, nl, used = pk.pack_chunk(
            data, 2 * n_lines, final=True, max_lines=n_lines, n_threads=1
        )
        return out, nl, used, pk.parsed, pk.skipped

    lib = fastparse._load()
    simd = {"kind": fastparse.simd_kind()}
    outs = {}
    for mode, label in ((True, "simd"), (False, "scalar")):
        fastparse.set_simd(mode)
        outs[mode] = parse_once()  # warm + identity capture
        ts = []
        for _ in range(3):
            t0 = time.perf_counter()
            parse_once()
            ts.append(time.perf_counter() - t0)
        simd[f"parse_{label}_lines_per_sec"] = round(n_lines / min(ts), 1)
        t0 = time.perf_counter()
        lib.asa_count_nl(data, len(data))
        simd[f"count_nl_{label}_gb_per_sec"] = round(
            len(data) / (time.perf_counter() - t0) / 1e9, 2
        )
    fastparse.set_simd(True)
    identical = (
        np.array_equal(outs[True][0], outs[False][0])
        and outs[True][1:] == outs[False][1:]
    )
    assert identical, "SIMD parse diverged from scalar"
    simd["byte_identical"] = identical
    simd["parse_speedup"] = round(
        simd["parse_simd_lines_per_sec"] / simd["parse_scalar_lines_per_sec"],
        3,
    )
    simd["count_nl_speedup"] = round(
        simd["count_nl_simd_gb_per_sec"]
        / max(simd["count_nl_scalar_gb_per_sec"], 1e-9),
        2,
    )
    log(f"  simd: parse {simd['parse_simd_lines_per_sec'] / 1e6:.2f}M vs "
        f"scalar {simd['parse_scalar_lines_per_sec'] / 1e6:.2f}M lines/s "
        f"({simd['parse_speedup']}x); count_nl "
        f"{simd['count_nl_simd_gb_per_sec']} vs "
        f"{simd['count_nl_scalar_gb_per_sec']} GB/s")

    td = tempfile.mkdtemp(prefix="feedscale-")
    corpus_path = os.path.join(td, "corpus.log")
    with open(corpus_path, "wb") as f:
        f.write(data)

    worker_counts = [1, 2, 4]

    # ---- parse-only feeder scaling (no device): the host-feed ceiling
    parse_scaling = []
    for w in worker_counts:
        feeder = ParallelFeeder(packed, [corpus_path], n_workers=w)
        t0 = time.perf_counter()
        consumed = 0
        for _batch, n_raw in feeder.batches(0, 1 << 16):
            consumed += n_raw
        dt = time.perf_counter() - t0
        assert consumed == n_lines
        parse_scaling.append({
            "workers": w,
            "lines_per_sec": round(n_lines / dt, 1),
        })
        log(f"  parse-only w={w}: {n_lines / dt / 1e6:.2f}M lines/s")

    # ---- convert fleet scaling (pre-coalesced RAWIREv3 shards)
    convert_fleet = []
    fleet_stats = {}
    for w in worker_counts:
        out_path = os.path.join(td, f"fleet-w{w}.rawire")
        t0 = time.perf_counter()
        stats = convert_logs_fleet(
            packed, [corpus_path], out_path, workers=w
        )
        dt = time.perf_counter() - t0
        fleet_stats[w] = stats
        convert_fleet.append({
            "workers": w,
            "lines_per_sec": round(n_lines / dt, 1),
            "stored_rows": stats["rows"] + stats["rows6"],
            "evals": stats["evals"],
            "bytes": stats["bytes"],
        })
        log(f"  convert fleet w={w}: {n_lines / dt / 1e6:.2f}M lines/s, "
            f"{stats['rows']} rows")
    for w in worker_counts[1:]:
        for k in ("rows", "rows6", "raw_lines", "evals", "skipped"):
            assert fleet_stats[w][k] == fleet_stats[1][k], (
                f"fleet w={w} {k} diverged from w=1"
            )

    # ---- e2e feeder->device: global queue vs per-chip rings
    cfg = AnalysisConfig(
        batch_size=1 << 14,
        sketch=SketchConfig(cms_width=1 << 12, cms_depth=4, hll_p=8),
    )
    e2e = []
    feed_ring_gauges = None
    for mode in ("process", "ring"):
        td_tr = os.path.join(td, f"tr-{mode}")
        obs.start_trace(td_tr, role="main")
        try:
            rep = run_stream_file(
                packed, [corpus_path], cfg, feed_workers=2, feed_mode=mode
            )
        finally:
            merged = obs.merge_trace(td_tr)
            obs.shutdown()
        row = {
            "feed_mode": mode,
            "workers": 2,
            "sustained_lines_per_sec": rep.totals["sustained_lines_per_sec"],
            "ingest": rep.totals.get("ingest"),
        }
        if mode == "ring":
            sys.path.insert(0, os.path.join(os.path.dirname(
                os.path.abspath(__file__)), "tools"))
            try:
                import trace_summary
            finally:
                sys.path.pop(0)
            feed_ring_gauges = trace_summary.summarize(merged).get("feed")
            row["feed"] = feed_ring_gauges
        e2e.append(row)
        log(f"  e2e {mode}: {row['sustained_lines_per_sec'] / 1e6:.3f}M "
            "lines/s sustained")

    # ---- the aggregate statement, honestly bounded by this container
    per_core_ceiling = max(
        max(r["lines_per_sec"] for r in parse_scaling),
        simd["parse_simd_lines_per_sec"],
    )
    target = 8_000_000
    cores_needed = int(np.ceil(target / per_core_ceiling))
    achieved = max(r["lines_per_sec"] for r in parse_scaling)
    aggregate = {
        "target_lines_per_sec": target,
        "container_cores": cores,
        "achieved_aggregate_lines_per_sec": achieved,
        "per_core_ceiling_lines_per_sec": per_core_ceiling,
        "cores_needed_for_target_at_ceiling": cores_needed,
        "target_demonstrated_locally": achieved >= target,
        "extrapolation": (
            f"this container exposes {cores} usable core(s), so the >=8M "
            f"lines/s aggregate cannot be demonstrated locally; at the "
            f"measured per-core ceiling of {per_core_ceiling / 1e6:.2f}M "
            f"lines/s the feed fleet needs {cores_needed} cores — a v5e-8 "
            "host (>100 usable cores) clears the bar with >5x headroom, "
            "and the descriptor/ring planes scale by construction "
            "(disjoint byte ranges, per-chip rings, no shared parse state)"
        ),
    }
    log(f"  aggregate: ceiling {per_core_ceiling / 1e6:.2f}M lines/s/core, "
        f"{cores_needed} cores needed for 8M")

    return {
        "bench": "feedscale",
        "metric": "host_feed_aggregate_lines_per_sec",
        "value": achieved,
        "detail": {
            "devices": _feedscale_devices(),
            "corpus_lines": n_lines,
            "corpus_bytes": len(data),
            "simd": simd,
            "parse_scaling": parse_scaling,
            "convert_fleet": convert_fleet,
            "e2e": e2e,
            "aggregate": aggregate,
            "identity_guards": {
                "simd_vs_scalar_parse": identical,
                "fleet_w1_vs_wN_accounting": True,
            },
        },
    }


def bench_rulescale() -> dict:
    """ISSUE 12: static-analyzer wall time vs R (the O(R²) tiling model).

    Sweeps ruleset size for the two shapes that matter: ONE big ACL
    (the worst case — the pair grid is R², witness pass included) and
    the same total rows split across stacked ACL slabs (per-ACL O(Ra²)
    grids, the shardable case).  The honest model for this 1-core
    container: tile kernels are sequential XLA:CPU dispatches, so wall
    time is ~linear in tiles_run = Σ ceil(Ra/T)² plus the witness pass
    (which scales with overlap density, not R²); on a real mesh the
    tile grid round-robins across chips (pair_relations(devices=...))
    — embarrassingly parallel, since a tile reads only its two row
    blocks.  Verdict parity across shapes/tiles guards the sweep.
    """
    from ruleset_analysis_tpu.hostside import aclparse, pack, synth
    from ruleset_analysis_tpu.runtime import staticanalysis

    def build(n_acls, rules_per_acl, seed=0):
        return pack.pack_rulesets([
            aclparse.parse_asa_config(
                synth.synth_config(
                    n_acls=n_acls, rules_per_acl=rules_per_acl, seed=seed
                ),
                "fw0",
            )
        ])

    def run(packed, tile=None):
        t0 = time.perf_counter()
        res = staticanalysis.analyze_ruleset(packed, tile=tile or 512)
        dt = time.perf_counter() - t0
        return res, dt

    sweep = []
    for r in (128, 256, 512, 1024, 2048):
        flat = build(1, r, seed=r)
        stacked = build(4, r // 4, seed=r)
        res_f, dt_f = run(flat)
        res_s, dt_s = run(stacked)
        rows_f = int(res_f.meta["n_rows"])
        rows_s = int(res_s.meta["n_rows"])
        entry = {
            "rules": r,
            "flat_1acl": {
                "rows": rows_f,
                "pairs_m": round(rows_f ** 2 / 1e6, 3),
                "tiles": res_f.meta["tiles_run"],
                "witnesses": res_f.meta["witnesses_checked"],
                "dead": res_f.meta["dead"],
                "sec": round(dt_f, 3),
            },
            "stacked_4acl": {
                "rows": rows_s,
                "tiles": res_s.meta["tiles_run"],
                "witnesses": res_s.meta["witnesses_checked"],
                "dead": res_s.meta["dead"],
                "sec": round(dt_s, 3),
            },
        }
        sweep.append(entry)
        log(f"rulescale R={r}: flat {dt_f:.2f}s ({res_f.meta['tiles_run']} "
            f"tiles, {res_f.meta['dead']} dead), stacked {dt_s:.2f}s "
            f"({res_s.meta['tiles_run']} tiles)")

    # tile-grid parity at the largest R: a small tile must not change a
    # single verdict (the sharding-safety invariant)
    big = build(1, 512, seed=512)
    v_big, _ = run(big)
    v_small, _ = run(big, tile=128)
    parity = {
        k: (v.verdict, v.basis) for k, v in v_big.verdicts.items()
    } == {
        k: (v.verdict, v.basis) for k, v in v_small.verdicts.items()
    }

    last = sweep[-1]["flat_1acl"]
    sec_per_mpair = last["sec"] / max(last["pairs_m"], 1e-9)
    return {
        "bench": "rulescale",
        "metric": "analyzer_sec_per_million_pairs_flat",
        "value": round(sec_per_mpair, 4),
        "detail": {
            "sweep": sweep,
            "tile": 512,
            "tile_parity_512_vs_128": parity,
            "model": (
                "O(R^2) pairs per ACL, walked as the LOWER-TRIANGLE "
                "[T,T] tile grid only (row order is key-ascending, so "
                "upper tiles cannot survive the earlier-key mask — "
                "~half the pair work, bit-identical verdicts); on this "
                "1-core CPU container tiles dispatch sequentially so "
                "wall ~ tiles_run x per-tile cost + witness pass "
                "(overlap-density-bound, not R^2); stacked ACLs divide "
                "the exponent's base (4 ACLs = R^2/4 total pairs) and "
                "the tile grid itself is embarrassingly device-parallel "
                "(pair_relations devices=) — unmeasured here, 1 core"
            ),
        },
    }


def bench_retrysoak() -> dict:
    """ISSUE 14: transient-fault survival soak + disarmed-overhead guard.

    Two halves:

    1. **Disarmed overhead** — the retry plane is always armed (a table
       lookup + one wrapper frame per seam call); this half measures the
       production text path with the default policies vs
       ``retry_policy="off"`` (single attempts) and guards the ratio
       inside the <2% obs budget.  Best-of-3 interleaved runs, same
       process, compile excluded by a warmup run.

    2. **Degraded-mode soak** — a live ServeDriver with injected
       non-core failures (static analyzer at start, metrics snapshotter
       persistent, disk publisher persistent) PLUS a transient
       device_put burst: ingest must keep serving with /health naming
       the degraded set and the retry engine absorbing the burst; the
       faults then clear (disarm + reload) and every subsystem must
       re-arm.  Recovery counts land in the artifact.
    """
    import os
    import socket
    import tempfile
    import threading

    import jax

    from ruleset_analysis_tpu.config import (
        AnalysisConfig, ServeConfig, SketchConfig,
    )
    from ruleset_analysis_tpu.hostside import aclparse
    from ruleset_analysis_tpu.hostside import pack as pack_mod
    from ruleset_analysis_tpu.hostside import synth
    from ruleset_analysis_tpu.runtime import faults, obs, retrypolicy
    from ruleset_analysis_tpu.runtime.serve import ServeDriver
    from ruleset_analysis_tpu.runtime.stream import run_stream

    n_lines = int(float(os.environ.get("RA_RETRY_SOAK_LINES", "120000")))
    cfg_text = synth.synth_config(n_acls=2, rules_per_acl=10, seed=0)
    packed = pack_mod.pack_rulesets([aclparse.parse_asa_config(cfg_text, "fw1")])
    t = _tuples(packed, n_lines, seed=7)
    lines = synth.render_syslog(packed, t, seed=7)

    # -- half 1: disarmed-path overhead ---------------------------------
    def rate(retry_policy: str) -> float:
        cfg = AnalysisConfig(
            backend="tpu", batch_size=1 << 14, prefetch_depth=0,
            sketch=SketchConfig(cms_width=1 << 12, cms_depth=2, hll_p=6),
            retry_policy=retry_policy,
        )
        t0 = time.perf_counter()
        run_stream(packed, iter(lines), cfg)
        return n_lines / (time.perf_counter() - t0)

    rate("off")  # warmup: compile + caches
    on_rates, off_rates = [], []
    for _ in range(3):  # interleaved best-of-3 (1-core noise)
        on_rates.append(rate(""))
        off_rates.append(rate("off"))
    armed_over_off = max(on_rates) / max(off_rates)

    # -- half 2: degraded-mode soak --------------------------------------
    W = 2000
    soak_lines = lines[:3 * W]
    with tempfile.TemporaryDirectory() as d:
        prefix = os.path.join(d, "rules")
        pack_mod.save_packed(packed, prefix)
        obs.start_metrics(os.path.join(d, "metrics.jsonl"), every_sec=0.1)
        cfg = AnalysisConfig(
            backend="tpu", batch_size=512, prefetch_depth=0,
            sketch=SketchConfig(cms_width=1 << 12, cms_depth=2, hll_p=6),
            fault_plan=(
                "stream.device_put.fail@2:2,analyze.tile@1,"
                "metrics.snapshot.fail@1:99,serve.publish.fail@1:99"
            ),
        )
        scfg = ServeConfig(
            listen=("tcp:127.0.0.1:0",), window_lines=W, ring=4,
            serve_dir=os.path.join(d, "serve"), stop_after_sec=300,
            reload_watch=False, checkpoint_every_windows=0, http="off",
            queue_lines=1 << 17, static_analysis=True,
        )
        out: dict = {}
        drv = ServeDriver(prefix, cfg, scfg, topk=10)

        def runner():
            try:
                out["summary"] = drv.run()
            except BaseException as e:
                out["error"] = e

        th = threading.Thread(target=runner)
        th.start()

        def wait_for(pred, timeout, what):
            deadline = time.monotonic() + timeout
            while time.monotonic() < deadline:
                if pred():
                    return
                time.sleep(0.05)
            raise RuntimeError(f"retrysoak: timed out waiting for {what}")

        wait_for(lambda: drv.listeners.alive() or "error" in out, 60, "listeners")
        s = socket.create_connection(drv.listeners.listeners[0].address)
        s.sendall(("\n".join(soak_lines[:2 * W]) + "\n").encode())
        wait_for(lambda: drv.windows_published >= 2, 180, "2 windows under faults")
        wait_for(
            lambda: {"static_analysis", "metrics", "publisher"}
            <= set(drv.health()["degraded_subsystems"]),
            60, "degraded set",
        )
        degraded_mid = drv.health()["degraded_subsystems"]
        # the faults clear: publisher re-arms on its next write, metrics
        # on its next clean tick, static on the reload's re-analysis
        faults.disarm()
        drv.request_reload()
        s.sendall(("\n".join(soak_lines[2 * W:]) + "\n").encode())
        s.close()
        wait_for(lambda: drv.windows_published >= 3, 180, "window 3")
        wait_for(
            lambda: not drv.health()["degraded_subsystems"], 120, "recovery"
        )
        drv.stop()
        th.join(timeout=120)
        obs.shutdown(merge=False)
        if th.is_alive() or "error" in out:
            raise RuntimeError(f"retrysoak: serve failed: {out.get('error')!r}")
        summary = out["summary"]

    retry_counts = summary["retry"]
    guards = {
        "disarmed_overhead_within_2pct": armed_over_off >= 0.98,
        "ingest_survived_degraded": summary["windows_published"] >= 3
        and summary["drops"] == 0,
        "degraded_set_enumerated": sorted(degraded_mid)
        == ["metrics", "publisher", "static_analysis"],
        "all_recovered": summary["degraded"] == []
        and summary["recovered_events"] >= 3,
        "transient_burst_absorbed": retry_counts.get("device_put", {})
        .get("recoveries", 0) >= 1,
    }
    return {
        "bench": "retrysoak",
        "metric": "retry_armed_over_off_rate_ratio",
        "value": round(armed_over_off, 4),
        "detail": {
            "platform": jax.devices()[0].platform,
            "devices": len(jax.devices()),
            "overhead_lines": n_lines,
            "rates_armed": [round(r, 1) for r in on_rates],
            "rates_off": [round(r, 1) for r in off_rates],
            "soak": {
                "windows_published": summary["windows_published"],
                "drops": summary["drops"],
                "degraded_mid_soak": degraded_mid,
                "degraded_final": summary["degraded"],
                "degraded_events": summary["degraded_events"],
                "recovered_events": summary["recovered_events"],
                "retry_counters": retry_counts,
            },
            "guards": guards,
        },
    }


def bench_blackbox() -> dict:
    """ISSUE 15: flight-recorder overhead guard + postmortem acceptance.

    Three parts:

    1. **Recorder overhead** — the production text path with the
       always-on flight recorder armed (a blackbox dir, the default)
       vs ``--blackbox off``, 5 interleaved pairs through the REAL
       CLI, compile excluded by a warmup run.  Sustained ratio
       (median over median) must be >= 0.98 (the PR 4 <2%%
       observability budget) and the reports must be BIT-IDENTICAL
       (VOLATILE-stripped) — both asserted in-bench.

    2. **Histogram-arm overhead** — the latency histograms are
       unconditionally armed (one ``record`` per committed batch /
       consumed line), so their cost is priced directly: ns per record
       x the production batch cadence -> overhead fraction.

    3. **Postmortem acceptance** — a chaos-killed run with NO
       trace/metrics flags leaves a merged ``postmortem.json`` from
       which ``doctor`` names the failing stage and the fired fault
       site; a clean run leaves nothing.  Asserted in-bench (the same
       contract tests/test_flightrec.py pins in tier-1).
    """
    import os
    import tempfile

    import jax

    from ruleset_analysis_tpu import cli
    from ruleset_analysis_tpu.hostside import aclparse
    from ruleset_analysis_tpu.hostside import pack as pack_mod
    from ruleset_analysis_tpu.hostside import synth
    from ruleset_analysis_tpu.runtime import flightrec
    from ruleset_analysis_tpu.runtime.metrics import LatencyHistogram
    from ruleset_analysis_tpu.runtime.report import VOLATILE_TOTALS

    n_lines = int(float(os.environ.get("RA_BLACKBOX_LINES", "120000")))
    cfg_text = synth.synth_config(n_acls=2, rules_per_acl=10, seed=0)
    packed = pack_mod.pack_rulesets([aclparse.parse_asa_config(cfg_text, "fw1")])
    t = _tuples(packed, n_lines, seed=11)
    lines = synth.render_syslog(packed, t, seed=11)

    def image(rep: dict) -> dict:
        rep = json.loads(json.dumps(rep))
        for k in VOLATILE_TOTALS:
            rep["totals"].pop(k, None)
        return rep

    with tempfile.TemporaryDirectory() as d:
        prefix = os.path.join(d, "rules")
        pack_mod.save_packed(packed, prefix)
        log = os.path.join(d, "fw1.log")
        with open(log, "w", encoding="utf-8") as f:
            f.write("\n".join(lines) + "\n")

        def run_cli(extra: list[str], out: str) -> tuple[int, dict | None]:
            rc = cli.main([
                "run", "--ruleset", prefix, "--logs", log,
                "--batch-size", str(1 << 14), "--cms-width", str(1 << 12),
                "--cms-depth", "2", "--hll-p", "6",
                "--json", "--out", out, *extra,
            ])
            # a fresh recorder per run: cli arming is per-invocation
            flightrec._reset_for_tests()
            if rc == 0:
                with open(out, "r", encoding="utf-8") as f:
                    return rc, json.load(f)
            return rc, None

        bb = os.path.join(d, "bb")
        on_flags = ["--blackbox-dir", bb]
        off_flags = ["--blackbox", "off"]
        run_cli(off_flags, os.path.join(d, "warm.json"))  # compile warmup
        on_rates, off_rates = [], []
        rep_on = rep_off = None
        for i in range(5):  # interleaved pairs (1-core noise)
            t0 = time.perf_counter()
            _, rep_on = run_cli(on_flags, os.path.join(d, f"on{i}.json"))
            on_rates.append(n_lines / (time.perf_counter() - t0))
            t0 = time.perf_counter()
            _, rep_off = run_cli(off_flags, os.path.join(d, f"off{i}.json"))
            off_rates.append(n_lines / (time.perf_counter() - t0))
        # ratio of medians: per-sample jitter on this container is ~±8%,
        # so a best-of-N comparison flakes around the 2% budget; the
        # median of 5 interleaved samples per arm is stable
        med = lambda xs: sorted(xs)[len(xs) // 2]
        ratio = med(on_rates) / med(off_rates)
        assert ratio >= 0.98, f"recorder-on/off sustained ratio {ratio:.4f} < 0.98"
        identical = image(rep_on) == image(rep_off)
        assert identical, "blackbox-armed report diverged from disarmed"
        # clean exits left NO forensics behind
        leftovers = sorted(os.listdir(bb)) if os.path.isdir(bb) else []
        assert not leftovers, f"clean runs left forensics: {leftovers}"

        # -- histogram-arm overhead (the always-on record path) ----------
        h = LatencyHistogram()
        reps = 200_000
        t0 = time.perf_counter()
        for _ in range(reps):
            h.record(1.5e-3)
        ns_per_record = (time.perf_counter() - t0) / reps * 1e9
        # one record per committed batch on the ingest path: overhead
        # fraction at the measured sustained cadence
        batches_per_sec = max(off_rates) / (1 << 14)
        hist_overhead_frac = ns_per_record * 1e-9 * batches_per_sec

        # -- postmortem acceptance ---------------------------------------
        bb2 = os.path.join(d, "bb2")
        rc, _ = run_cli(
            ["--blackbox-dir", bb2, "--fault-plan", "ingest.producer.raise@3"],
            os.path.join(d, "crash.json"),
        )
        assert rc != 0, "chaos run must abort typed"
        bundle = flightrec.load_bundle(bb2)
        sites = bundle["analysis"]["fault_sites_fired"]
        assert sites.get("ingest.producer.raise"), sites
        diags = flightrec.diagnose(bundle, exit_code=rc)
        assert diags and "fault plan" in diags[0]["cause"]
        acceptance = {
            "exit_code": rc,
            "trigger": bundle["trigger"],
            "failing_stage": bundle["analysis"]["failing_stage"],
            "fault_sites_fired": sites,
            "doctor_top_cause": diags[0]["cause"],
            "shards": len(bundle["shards"]),
        }

    guards = {
        "recorder_on_over_off_ge_0p98": ratio >= 0.98,
        "report_bit_identical": identical,
        "clean_exit_leaves_none": not leftovers,
        "postmortem_names_fired_site": True,  # asserted above
    }
    return {
        "bench": "blackbox",
        "metric": "blackbox_on_over_off_rate_ratio",
        "value": round(ratio, 4),
        "detail": {
            "platform": jax.devices()[0].platform,
            "devices": len(jax.devices()),
            "lines": n_lines,
            "rates_on": [round(r, 1) for r in on_rates],
            "rates_off": [round(r, 1) for r in off_rates],
            "histogram_ns_per_record": round(ns_per_record, 1),
            "histogram_overhead_frac_at_batch_cadence": round(
                hist_overhead_frac, 8
            ),
            "acceptance": acceptance,
            "guards": guards,
        },
    }


def bench_tenant() -> dict:
    """ISSUE 16: one packed N-tenant serve process vs N sequential
    solo serves — the committed evidence is BENCH_TENANT_r18_cpu.json.

    N small tenants with DISTINCT geometries (each its own key
    universe) share one rule-rung bucket, so the packed process
    compiles ONE tenant step for all of them while every solo process
    compiles its own flat step, builds its own mesh, and stands up its
    own serve machinery.  Measures wall-clock for the same traffic
    (N x L lines, one window each) both ways; the ratio
    (sequential-solo total / packed) must be >= 2.0 at N=16 — asserted
    in-bench, like the blackbox budget.  Per-tenant window reports are
    spot-checked bit-identical to the solo runs (the full sweep lives
    in tests/test_tenancy.py).
    """
    import os
    import shutil
    import socket
    import tempfile
    import threading

    import jax

    from ruleset_analysis_tpu.config import AnalysisConfig, ServeConfig
    from ruleset_analysis_tpu.hostside import aclparse, synth
    from ruleset_analysis_tpu.hostside import pack as pack_mod
    from ruleset_analysis_tpu.runtime.report import VOLATILE_TOTALS
    from ruleset_analysis_tpu.runtime.serve import ServeDriver
    from ruleset_analysis_tpu.runtime.tenantserve import TenantServeDriver

    n_tenants = int(os.environ.get("RA_TENANTS", "16"))
    n_lines = int(os.environ.get("RA_TENANT_LINES", "100"))
    run_cfg = dict(batch_size=128, prefetch_depth=0)

    def image(rep: dict) -> dict:
        rep = json.loads(json.dumps(rep))
        for k in VOLATILE_TOTALS:
            rep["totals"].pop(k, None)
        rep["totals"].pop("window", None)
        rep["totals"].pop("tenant", None)
        return rep

    def drive(drv, feed, n_listeners):
        out: dict = {}

        def runner():
            try:
                out["summary"] = drv.run()
            except BaseException as e:
                out["error"] = e

        th = threading.Thread(target=runner)
        th.start()
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            if out.get("error") is not None:
                break
            if drv.listeners.alive() == n_listeners:
                break
            time.sleep(0.02)
        if "error" not in out:
            feed(drv)
        th.join(timeout=600)
        if "error" in out:
            raise out["error"]
        return out["summary"]

    td = tempfile.mkdtemp(prefix="ra-bench-tenant-")
    try:
        tenants: dict[str, tuple[str, list[str]]] = {}
        rows = []
        for i in range(n_tenants):
            name = f"t{i:02d}"
            cfg_text = synth.synth_config(
                n_acls=2, rules_per_acl=6 + i, seed=10 + i, v6_fraction=0.0
            )
            packed = pack_mod.pack_rulesets(
                [aclparse.parse_asa_config(cfg_text, f"fw{i}")]
            )
            prefix = os.path.join(td, f"rules{i}")
            pack_mod.save_packed(packed, prefix)
            t = _tuples(packed, n_lines, seed=20 + i)
            lines = synth.render_syslog(packed, t, seed=30 + i)
            tenants[name] = (prefix, lines)
            rows.append({
                "name": name, "ruleset": prefix,
                "listen": ["tcp:127.0.0.1:0"],
            })
        manifest = os.path.join(td, "manifest.json")
        with open(manifest, "w", encoding="utf-8") as f:
            json.dump({"tenants": rows}, f)

        log(f"tenant: packed serve, {n_tenants} tenants x {n_lines} lines")
        t0 = time.perf_counter()
        pdir = os.path.join(td, "packed")
        scfg = ServeConfig(
            listen=(), window_lines=n_lines, ring=4, serve_dir=pdir,
            max_windows=n_tenants, http="off", checkpoint_every_windows=0,
        )
        drv = TenantServeDriver(manifest, AnalysisConfig(**run_cfg), scfg)

        def feed_all(d):
            by_tenant = {
                ln.q.tenant: ln.address for ln in d.listeners.listeners
            }
            for name, (_prefix, lines) in sorted(tenants.items()):
                s = socket.create_connection(tuple(by_tenant[name]))
                s.sendall(("\n".join(lines) + "\n").encode())
                s.close()

        summary = drive(drv, feed_all, n_tenants)
        packed_wall = time.perf_counter() - t0
        assert summary["windows_published"] == n_tenants, summary
        assert summary["lines_unrouted"] == 0, summary

        solo_walls = []
        for name, (prefix, lines) in sorted(tenants.items()):
            log(f"tenant: solo serve {name}")
            t0 = time.perf_counter()
            sdir = os.path.join(td, f"solo-{name}")
            sscfg = ServeConfig(
                listen=("tcp:127.0.0.1:0",), window_lines=n_lines, ring=4,
                serve_dir=sdir, max_windows=1, http="off",
                checkpoint_every_windows=0,
            )
            sdrv = ServeDriver(prefix, AnalysisConfig(**run_cfg), sscfg)

            def feed_one(d, _lines=lines):
                s = socket.create_connection(
                    tuple(d.listeners.listeners[0].address)
                )
                s.sendall(("\n".join(_lines) + "\n").encode())
                s.close()

            drive(sdrv, feed_one, 1)
            solo_walls.append(time.perf_counter() - t0)
        solo_total = sum(solo_walls)
        ratio = solo_total / packed_wall

        identical = 0
        for name in sorted(tenants):
            with open(os.path.join(
                pdir, "t", name, "window-000000.json"
            ), encoding="utf-8") as f:
                a = json.load(f)
            with open(os.path.join(
                td, f"solo-{name}", "window-000000.json"
            ), encoding="utf-8") as f:
                b = json.load(f)
            assert image(a) == image(b), f"{name} diverged from solo"
            identical += 1
        assert ratio >= 2.0, (
            f"packed {n_tenants}-tenant serve is only {ratio:.2f}x "
            f"sequential solo (packed {packed_wall:.1f}s vs "
            f"solo {solo_total:.1f}s); want >= 2.0x"
        )
    finally:
        shutil.rmtree(td, ignore_errors=True)
    return {
        "bench": "tenant",
        "metric": "solo_sequential_over_packed_wall_ratio",
        "value": round(ratio, 2),
        "detail": {
            "platform": jax.devices()[0].platform,
            "devices": len(jax.devices()),
            "tenants": n_tenants,
            "lines_per_tenant": n_lines,
            "packed_wall_sec": round(packed_wall, 2),
            "solo_total_wall_sec": round(solo_total, 2),
            "solo_wall_sec": [round(w, 2) for w in solo_walls],
            "reports_bit_identical": identical,
            "guards": {"ratio_ge_2": True, "bit_identical_all": True},
        },
    }


def bench_servescale() -> dict:
    """Multi-host distributed serve scale-out (ISSUE 17, DESIGN §22).

    Three legs, one corpus, process-mode workers throughout:

    1. **Solo reference** — a single ``ServeDriver`` replays the union
       corpus (per-window: host 0's slice then host 1's slice) at the
       aggregate offered rate.  Its window reports are the bit-identity
       baseline and its sustained rate the per-host ceiling.
    2. **2-host distributed** — ``DistServeDriver`` with two spawned
       worker processes, each fed its own slice at the per-host rate.
       Asserted in-bench: every merged window report AND the cumulative
       report are bit-identical (VOLATILE-stripped) to the solo run —
       registers, per-rule hits, unique-source counts, and the
       unused-rule deletion candidates — with the talkers section's
       heavy-hitter prefix pinned exactly (its deep tail is sampled-
       candidate CMS output, approximate by design; tier-1 pins FULL
       identity, talkers included, at complete candidate coverage);
       zero drops on either side; and the rank-0
       merge+publish stage — the only serialized cross-host work —
       costs <= 0.2 of the ingest wall, which is exactly the condition
       under which N dedicated host cores sustain >= 0.8*N x the
       single-host rate.
    3. **Whole-host chaos** — a fresh 2-host run SIGKILLs host 1
       mid-window: the service must keep publishing every window, name
       ``host_died:1`` in the incomplete markers of the affected
       windows, and lose none of host 0's delivered lines.

    The artifact states the HONEST aggregate: on this 1-core container
    both "hosts" timeshare one CPU, so the >= 0.8*N claim is the
    measured solo rate x N x (1 - measured merge overhead fraction) —
    the same per-core extrapolation discipline as FEEDSCALE_r14.

    ``RA_SERVESCALE_LINES`` (default 24k; 3 windows) and
    ``RA_SERVESCALE_RATE`` (default 4k lines/s offered PER HOST) size
    the soak.
    """
    import os
    import socket
    import tempfile
    import threading

    import jax

    from ruleset_analysis_tpu.config import (
        AnalysisConfig,
        DistServeConfig,
        ServeConfig,
    )
    from ruleset_analysis_tpu.hostside import aclparse, synth
    from ruleset_analysis_tpu.hostside import pack as pack_mod
    from ruleset_analysis_tpu.runtime.distserve import DistServeDriver
    from ruleset_analysis_tpu.runtime.report import VOLATILE_TOTALS
    from ruleset_analysis_tpu.runtime.serve import ServeDriver
    from ruleset_analysis_tpu.runtime.stream import run_stream

    n_hosts = 2
    windows = 3
    rate = float(os.environ.get("RA_SERVESCALE_RATE", "4000"))
    wl = int(float(os.environ.get("RA_SERVESCALE_LINES", "24000"))) // (
        n_hosts * windows
    )
    total = wl * n_hosts * windows
    BATCH = 4096

    cfg_text = synth.synth_config(n_acls=2, rules_per_acl=10, seed=0)
    packed = pack_mod.pack_rulesets([aclparse.parse_asa_config(cfg_text, "fw1")])
    t = _tuples(packed, total, seed=7)
    lines = synth.render_syslog(packed, t, seed=7)
    # union order IS the solo replay order; host r's stream is the
    # concatenation of its per-window slices, so merged window w and
    # solo window w cover the same lines
    host_stream = {
        r: [
            ln
            for w in range(windows)
            for ln in lines[(w * n_hosts + r) * wl:(w * n_hosts + r + 1) * wl]
        ]
        for r in range(n_hosts)
    }

    def image(rep: dict) -> dict:
        rep = json.loads(json.dumps(rep))
        for k in VOLATILE_TOTALS:
            rep["totals"].pop(k, None)
        # window/chunk metadata names hosts and batch segmentation —
        # layout, not analysis content.  talkers compared separately:
        # the section is a sampled-candidate CMS summary whose deep
        # tail is approximate BY DESIGN (per-chunk slot-limited
        # sampling differs with chunk boundaries), so the register-law
        # identity covers everything else bit-exactly while the talker
        # check pins the heavy-hitter prefix.  Full identity including
        # talkers is pinned at the tier-1 geometry (tests/
        # test_distserve.py), where candidate coverage is complete.
        rep["totals"].pop("window", None)
        rep["totals"].pop("chunks", None)
        rep.pop("talkers", None)
        return rep

    def talker_heads(rep: dict, k: int = 3) -> dict:
        return {
            acl: rows[:k] for acl, rows in (rep.get("talkers") or {}).items()
        }

    def read_json(path):
        with open(path, "r", encoding="utf-8") as f:
            return json.load(f)

    def paced_send(addr, seg, rate, *, swallow=False):
        try:
            s = socket.create_connection(tuple(addr))
            t0 = time.perf_counter()
            sent = 0
            for i in range(0, len(seg), 500):
                burst = seg[i:i + 500]
                s.sendall(("\n".join(burst) + "\n").encode())
                sent += len(burst)
                lag = sent / rate - (time.perf_counter() - t0)
                if lag > 0:
                    time.sleep(lag)
            s.close()
        except OSError:
            if not swallow:
                raise

    def wait_for(pred, timeout, what):
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if pred():
                return
            time.sleep(0.05)
        raise RuntimeError(f"servescale: timed out waiting for {what}")

    def run_driver(drv):
        out: dict = {}

        def runner():
            try:
                out["summary"] = drv.run()
            except BaseException as e:  # surfaced by the caller
                out["error"] = e

        th = threading.Thread(target=runner)
        th.start()
        return th, out

    def host_tcp(drv, r):
        with drv._lock:
            h = drv.hosts.get(r)
            addrs = dict(h.addresses) if h else {}
        for lbl, ad in addrs.items():
            if lbl.startswith("tcp"):
                return tuple(ad)
        return None

    with tempfile.TemporaryDirectory() as d:
        prefix = os.path.join(d, "rules")
        pack_mod.save_packed(packed, prefix)

        # warm the jit caches at the exact serve geometry so the solo
        # leg prices the serve loop, not XLA compiles (spawned workers
        # compile in their own processes; the oversized listener queue
        # absorbs that stall without drops)
        warm_cfg = AnalysisConfig(batch_size=BATCH, prefetch_depth=0)
        run_stream(packed, iter(lines[:64]), warm_cfg)

        # ---- leg 1: solo reference over the union ----
        solo_dir = os.path.join(d, "solo")
        solo_drv = ServeDriver(
            prefix,
            AnalysisConfig(batch_size=BATCH, prefetch_depth=0),
            ServeConfig(
                listen=("tcp:127.0.0.1:0",), window_lines=n_hosts * wl,
                serve_dir=solo_dir, max_windows=windows, http="off",
                checkpoint_every_windows=0, reload_watch=False,
                queue_lines=1 << 18,
            ),
        )
        th, out = run_driver(solo_drv)
        ep_path = os.path.join(solo_dir, "endpoint.json")
        wait_for(lambda: os.path.exists(ep_path), 120, "solo endpoint")
        (solo_addr,) = read_json(ep_path)["listeners"].values()
        wall_start = time.time()
        paced_send(solo_addr, lines, n_hosts * rate)
        th.join(timeout=600)
        if th.is_alive() or "error" in out:
            raise RuntimeError(f"servescale: solo leg failed: {out.get('error')}")
        # the sustained clock stops at the LAST window's publication
        solo_elapsed = max(
            os.path.getmtime(
                os.path.join(solo_dir, f"window-{windows - 1:06d}.json")
            ) - wall_start,
            1e-3,
        )
        solo_sum = out["summary"]
        assert solo_sum["drops"] == 0, f"solo dropped {solo_sum['drops']}"
        solo_rate = total / solo_elapsed
        log(f"servescale: solo {solo_rate:,.0f} lines/s over {total} lines")

        # ---- leg 2: 2-host distributed at the same per-host rate ----
        dist_dir = os.path.join(d, "dist")
        dist_drv = DistServeDriver(
            prefix,
            AnalysisConfig(
                batch_size=BATCH, prefetch_depth=0, mesh_shape="hybrid"
            ),
            ServeConfig(
                listen=("tcp:127.0.0.1:0",), window_lines=wl,
                serve_dir=dist_dir, max_windows=windows, http="off",
                checkpoint_every_windows=0, reload_watch=False,
                queue_lines=1 << 18,
            ),
            DistServeConfig(hosts=n_hosts, workers="process"),
        )
        merge_times: list[float] = []
        orig_pub = dist_drv._publish_window

        def timed_pub(*a, **k):
            tp = time.perf_counter()
            r = orig_pub(*a, **k)
            merge_times.append(time.perf_counter() - tp)
            return r

        dist_drv._publish_window = timed_pub
        th, out = run_driver(dist_drv)
        wait_for(
            lambda: out.get("error")
            or all(host_tcp(dist_drv, r) for r in range(n_hosts)),
            300, "distributed host listeners",
        )
        if "error" in out:
            raise RuntimeError(f"servescale: dist leg failed: {out['error']}")
        t_ingest0 = time.perf_counter()
        senders = [
            threading.Thread(
                target=paced_send,
                args=(host_tcp(dist_drv, r), host_stream[r], rate),
            )
            for r in range(n_hosts)
        ]
        for s in senders:
            s.start()
        for s in senders:
            s.join()
        th.join(timeout=600)
        if th.is_alive() or "error" in out:
            raise RuntimeError(f"servescale: dist leg failed: {out.get('error')}")
        ingest_wall = max(time.perf_counter() - t_ingest0, 1e-3)
        dist_sum = out["summary"]
        assert dist_sum["drops"] == 0, f"dist dropped {dist_sum['drops']}"
        assert dist_sum["lines_total"] == total, (
            f"dist published {dist_sum['lines_total']} of {total} lines"
        )
        assert dist_sum["dead_hosts"] == [], dist_sum["dead_hosts"]

        identical = 0
        for w in range(windows):
            a = read_json(os.path.join(dist_dir, f"window-{w:06d}.json"))
            b = read_json(os.path.join(solo_dir, f"window-{w:06d}.json"))
            assert image(a) == image(b), (
                f"merged window {w} diverged from the solo replay"
            )
            assert talker_heads(a) == talker_heads(b), (
                f"merged window {w} heavy-hitter talkers diverged"
            )
            identical += 1
        cum_a = read_json(os.path.join(dist_dir, "cumulative.json"))
        cum_b = read_json(os.path.join(solo_dir, "cumulative.json"))
        cum_same = image(cum_a) == image(cum_b) and (
            talker_heads(cum_a) == talker_heads(cum_b)
        )
        assert cum_same, "cumulative report diverged from the solo replay"

        merge_wall = sum(merge_times)
        merge_frac = merge_wall / ingest_wall
        assert merge_frac <= 0.2, (
            f"rank-0 merge+publish is {merge_frac:.1%} of the ingest wall "
            "(> 20%); the 0.8*N scaling floor does not hold"
        )
        # N dedicated host cores ingest at ~solo_rate each; rank 0's
        # merge is the only serialized stage, so the honest aggregate
        # is N x solo x (1 - merge_frac) >= 0.8 * N * solo
        extrapolated = n_hosts * solo_rate * (1.0 - merge_frac)
        assert extrapolated >= 0.8 * n_hosts * solo_rate

        # ---- leg 3: whole-host SIGKILL chaos ----
        chaos_dir = os.path.join(d, "chaos")
        chaos_drv = DistServeDriver(
            prefix,
            AnalysisConfig(
                batch_size=BATCH, prefetch_depth=0, mesh_shape="hybrid"
            ),
            ServeConfig(
                listen=("tcp:127.0.0.1:0",), window_lines=wl,
                serve_dir=chaos_dir, max_windows=windows, http="off",
                checkpoint_every_windows=0, reload_watch=False,
                queue_lines=1 << 18,
            ),
            DistServeConfig(hosts=n_hosts, workers="process"),
        )
        th, out = run_driver(chaos_drv)
        wait_for(
            lambda: out.get("error")
            or all(host_tcp(chaos_drv, r) for r in range(n_hosts)),
            300, "chaos host listeners",
        )
        if "error" in out:
            raise RuntimeError(f"servescale: chaos leg failed: {out['error']}")
        h0 = threading.Thread(
            target=paced_send,
            args=(host_tcp(chaos_drv, 0), host_stream[0], rate),
        )
        h0.start()
        # host 1 gets 1.5 windows' worth, then dies mid-window — but
        # only after its window-0 epoch reached rank 0, so the kill
        # lands in window 1, not in a still-compiling first batch
        paced_send(
            host_tcp(chaos_drv, 1), host_stream[1][:wl + wl // 2], rate,
            swallow=True,
        )
        wait_for(
            lambda: out.get("error") or chaos_drv.hosts[1].last_wid >= 0,
            300, "host 1's first epoch",
        )
        chaos_drv.kill_host(1)
        h0.join()
        th.join(timeout=600)
        if th.is_alive() or "error" in out:
            raise RuntimeError(
                f"servescale: chaos leg failed: {out.get('error')}"
            )
        chaos_sum = out["summary"]
        assert chaos_sum["dead_hosts"] == [1], chaos_sum["dead_hosts"]
        assert chaos_sum["windows_published"] == windows, chaos_sum
        assert chaos_sum["drops"] == 0, f"chaos dropped {chaos_sum['drops']}"
        # every line host 0 delivered is published, plus host 1's
        # completed window 0 — a dead peer degrades the merge, it does
        # not silently shrink survivors
        assert chaos_sum["lines_total"] >= windows * wl + wl, (
            chaos_sum["lines_total"]
        )
        died_marks = 0
        for w in range(windows):
            meta = read_json(
                os.path.join(chaos_dir, f"window-{w:06d}.json")
            )["totals"]["window"]
            reasons = (meta.get("incomplete") or {}).get("reasons", [])
            if any(r.startswith("host_died:1") for r in reasons):
                died_marks += 1
        assert died_marks >= 1, "no window names the killed host"

    sustained_1core = round(total / ingest_wall, 1)
    return {
        "bench": "servescale",
        "metric": "servescale_extrapolated_aggregate_lines_per_sec",
        "value": round(extrapolated, 1),
        "unit": "lines/sec",
        "vs_baseline": round(extrapolated / solo_rate, 3),  # x single host
        "detail": {
            "platform": jax.devices()[0].platform,
            "devices": len(jax.devices()),
            "hosts": n_hosts,
            "workers": "process",
            "windows": windows,
            "lines_total": total,
            "offered_rate_per_host_lines_per_sec": rate,
            "solo_sustained_lines_per_sec": round(solo_rate, 1),
            "dist_1core_timeshared_lines_per_sec": sustained_1core,
            "merge_publish_wall_sec": round(merge_wall, 3),
            "merge_publish_per_window_ms": [
                round(x * 1e3, 1) for x in merge_times
            ],
            "merge_overhead_frac": round(merge_frac, 4),
            "windows_bit_identical": identical,
            "cumulative_bit_identical": cum_same,
            "chaos": {
                "killed_host": 1,
                "dead_hosts": chaos_sum["dead_hosts"],
                "windows_published": chaos_sum["windows_published"],
                "windows_naming_dead_host": died_marks,
                "lines_published": chaos_sum["lines_total"],
                "drops": chaos_sum["drops"],
            },
            "extrapolation": (
                "both hosts timeshare one CPU core here, so the "
                "aggregate is stated as solo_rate x hosts x (1 - "
                "merge_overhead_frac): per-host ingest is share-nothing "
                "(own listener, queue, feeder, registers) and the "
                "measured rank-0 merge+publish stage is the only "
                "serialized cross-host work"
            ),
            "guards": {
                "bit_identical_all_windows": True,
                "talker_heads_identical": True,
                "cumulative_bit_identical": True,
                "zero_drops_both_runs": True,
                "merge_overhead_le_0p2": True,
                "extrapolated_ge_0p8N": True,
                "chaos_names_dead_host": True,
                "chaos_zero_silent_drops": True,
            },
        },
    }


def bench_failover() -> dict:
    """Supervisor failover soak (DESIGN §23): kill the lease-holder
    mid-soak, elect a successor, replay the spools — and price the
    armed failover plane.

    Four legs, one corpus, thread-mode workers (one process, shared jit
    caches — the kill is the in-process supervisor-death seam, exactly
    what the slow CLI SIGKILL e2e pins end-to-end):

    1. **Bare reference** — lease + spool disabled
       (``lease_ttl_sec=0``, ``spool_budget_mb=0``): the pre-§23 serve
       plane's sustained rate on this corpus.
    2. **Armed control** — lease + spool on, no chaos: publishes every
       window under term 1.  Asserted in-bench: the spool/lease armed
       overhead — 1 - armed_rate/bare_rate — is **< 2%** (the r19
       SERVESCALE plane must not get slower by growing a failover
       plane; both legs are paced identically, so the rates differ
       only by per-window spool fsyncs and ttl/4 lease heartbeats).
    3. **Victim** — a full merge-plane partition
       (``dist.epoch.ship`` armed for the whole leg) parks every epoch
       in the durable spools, then the supervisor dies abruptly with
       ZERO windows published.
    4. **Successor** — wins term 2 off the on-disk lease and replays
       the spools.  Asserted in-bench: every window it publishes is
       **bit-identical** (VOLATILE-stripped, talkers included) to the
       unkilled control's, zero drops, zero skipped windows, every
       window stamped with exactly one fencing term (control windows
       term 1, successor windows term 2 — one publisher per term),
       every replayed window's **lineage record** is identical to the
       control's outside the volatile term/path stamps (DESIGN §24's
       replay-identity law) with a gapless successor ledger frontier,
       and **time-to-takeover** (successor start -> last replayed
       window on disk, election + replay inclusive) is **<= 2x the
       lease TTL**.

    ``RA_FAILOVER_LINES`` (default 12k; 2 hosts x 4 windows) and
    ``RA_FAILOVER_RATE`` (default 3k lines/s offered PER HOST) size
    the soak.
    """
    import os
    import socket
    import tempfile
    import threading

    import jax

    from ruleset_analysis_tpu.config import (
        AnalysisConfig,
        DistServeConfig,
        ServeConfig,
    )
    from ruleset_analysis_tpu.hostside import aclparse, synth
    from ruleset_analysis_tpu.hostside import pack as pack_mod
    from ruleset_analysis_tpu.runtime import faults
    from ruleset_analysis_tpu.runtime.distserve import DistServeDriver
    from ruleset_analysis_tpu.errors import AnalysisError
    from ruleset_analysis_tpu.runtime.report import (
        LINEAGE_VOLATILE,
        VOLATILE_TOTALS,
        lineage_frontier,
    )
    from ruleset_analysis_tpu.runtime.stream import run_stream
    from ruleset_analysis_tpu.runtime.wal import LineageLog

    n_hosts = 2
    windows = 4
    ttl = 2.0
    rate = float(os.environ.get("RA_FAILOVER_RATE", "3000"))
    wl = int(float(os.environ.get("RA_FAILOVER_LINES", "12000"))) // (
        n_hosts * windows
    )
    total = wl * n_hosts * windows
    BATCH = 4096

    cfg_text = synth.synth_config(n_acls=2, rules_per_acl=10, seed=0)
    packed = pack_mod.pack_rulesets([aclparse.parse_asa_config(cfg_text, "fw1")])
    t = _tuples(packed, total, seed=23)
    lines = synth.render_syslog(packed, t, seed=23)
    host_stream = {
        r: [
            ln
            for w in range(windows)
            for ln in lines[(w * n_hosts + r) * wl:(w * n_hosts + r + 1) * wl]
        ]
        for r in range(n_hosts)
    }

    def image(rep: dict) -> dict:
        rep = json.loads(json.dumps(rep))
        for k in VOLATILE_TOTALS:
            rep["totals"].pop(k, None)
        # window meta names hosts, chunks, and the fencing term —
        # provenance, not analysis content (the term is asserted
        # separately: that is the one-publisher-per-term pin)
        rep["totals"].pop("window", None)
        rep["totals"].pop("chunks", None)
        return rep

    def read_json(path):
        with open(path, "r", encoding="utf-8") as f:
            return json.load(f)

    def paced_send(addr, seg, rate):
        s = socket.create_connection(tuple(addr))
        t0 = time.perf_counter()
        sent = 0
        for i in range(0, len(seg), 500):
            burst = seg[i:i + 500]
            s.sendall(("\n".join(burst) + "\n").encode())
            sent += len(burst)
            lag = sent / rate - (time.perf_counter() - t0)
            if lag > 0:
                time.sleep(lag)
        s.close()

    def wait_for(pred, timeout, what):
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if pred():
                return
            time.sleep(0.05)
        raise RuntimeError(f"failover: timed out waiting for {what}")

    def run_driver(drv):
        out: dict = {}

        def runner():
            try:
                out["summary"] = drv.run()
            except BaseException as e:  # surfaced by the caller
                out["error"] = e

        th = threading.Thread(target=runner)
        th.start()
        return th, out

    def host_tcp(drv, r):
        with drv._lock:
            h = drv.hosts.get(r)
            addrs = dict(h.addresses) if h else {}
        for lbl, ad in addrs.items():
            if lbl.startswith("tcp"):
                return tuple(ad)
        return None

    def serve_leg(d, name, dscfg, *, feed=True):
        """One paced 2-host run; returns (driver, summary, sustained)."""
        sd = os.path.join(d, name)
        drv = DistServeDriver(
            os.path.join(d, "rules"),
            AnalysisConfig(
                batch_size=BATCH, prefetch_depth=0, mesh_shape="hybrid"
            ),
            ServeConfig(
                listen=("tcp:127.0.0.1:0",), window_lines=wl,
                serve_dir=sd, max_windows=windows, http="off",
                checkpoint_every_windows=0, reload_watch=False,
                queue_lines=1 << 18,
            ),
            dscfg,
        )
        th, out = run_driver(drv)
        wait_for(
            lambda: out.get("error")
            or all(host_tcp(drv, r) for r in range(n_hosts)),
            300, f"{name} host listeners",
        )
        if "error" in out:
            raise RuntimeError(f"failover: {name} leg failed: {out['error']}")
        t0 = time.perf_counter()
        senders = [
            threading.Thread(
                target=paced_send,
                args=(host_tcp(drv, r), host_stream[r], rate),
            )
            for r in range(n_hosts)
        ]
        for s in senders:
            s.start()
        for s in senders:
            s.join()
        if not feed:
            return drv, th, out, t0
        th.join(timeout=600)
        if th.is_alive() or "error" in out:
            raise RuntimeError(
                f"failover: {name} leg failed: {out.get('error')}"
            )
        last = os.path.join(sd, f"window-{windows - 1:06d}.json")
        sustained = total / max(os.path.getmtime(last) - wall0[name], 1e-3)
        summary = out["summary"]
        assert summary["drops"] == 0, f"{name} dropped {summary['drops']}"
        assert summary["windows_published"] == windows, summary
        return drv, summary, sustained

    wall0: dict = {}

    with tempfile.TemporaryDirectory() as d:
        prefix = os.path.join(d, "rules")
        pack_mod.save_packed(packed, prefix)
        warm_cfg = AnalysisConfig(batch_size=BATCH, prefetch_depth=0)
        run_stream(packed, iter(lines[:64]), warm_cfg)

        # ---- leg 1: bare (no lease, no spool) ----
        wall0["bare"] = time.time()
        _, bare_sum, bare_rate = serve_leg(
            d, "bare",
            DistServeConfig(
                hosts=n_hosts, workers="thread",
                lease_ttl_sec=0.0, spool_budget_mb=0,
            ),
        )
        log(f"failover: bare {bare_rate:,.0f} lines/s over {total} lines")

        # ---- leg 2: armed control (lease + spool, no chaos) ----
        wall0["control"] = time.time()
        _, ctl_sum, armed_rate = serve_leg(
            d, "control",
            DistServeConfig(
                hosts=n_hosts, workers="thread", lease_ttl_sec=ttl,
            ),
        )
        assert ctl_sum["term"] == 1
        overhead = max(0.0, 1.0 - armed_rate / bare_rate)
        log(
            f"failover: armed {armed_rate:,.0f} lines/s "
            f"({overhead:.2%} overhead vs bare)"
        )
        assert overhead < 0.02, (
            f"spool/lease armed overhead {overhead:.2%} >= 2% "
            f"(bare {bare_rate:,.0f} vs armed {armed_rate:,.0f} lines/s)"
        )

        # ---- leg 3: victim (full partition, then supervisor death) ----
        fo_dir = os.path.join(d, "failover")
        with faults.armed(faults.FaultPlan.parse("dist.epoch.ship@1:99999")):
            drv, th, out, _ = serve_leg(
                d, "failover",
                DistServeConfig(
                    hosts=n_hosts, workers="thread",
                    merge_timeout_sec=600, lease_ttl_sec=ttl,
                ),
                feed=False,
            )
            wait_for(
                lambda: out.get("error") or all(
                    drv.host_gauges().get(str(r), {}).get("spool_seq", 0)
                    >= windows
                    for r in range(n_hosts)
                ),
                300, "every epoch durably spooled",
            )
            assert drv.windows_published == 0  # term 1 published NOTHING
            drv.kill_supervisor()
            th.join(timeout=600)
            assert not th.is_alive(), "killed supervisor failed to die"
            err = out.get("error")
            assert isinstance(err, AnalysisError), err

        # ---- leg 4: successor (election + replay) ----
        t_takeover = time.perf_counter()
        succ = DistServeDriver(
            prefix,
            AnalysisConfig(
                batch_size=BATCH, prefetch_depth=0, mesh_shape="hybrid",
                resume=True,
            ),
            ServeConfig(
                listen=("tcp:127.0.0.1:0",), window_lines=wl,
                serve_dir=fo_dir, max_windows=windows, http="off",
                checkpoint_every_windows=0, reload_watch=False,
                queue_lines=1 << 18,
            ),
            DistServeConfig(
                hosts=n_hosts, workers="thread",
                merge_timeout_sec=600, lease_ttl_sec=ttl,
            ),
        )
        th, out = run_driver(succ)
        last = os.path.join(fo_dir, f"window-{windows - 1:06d}.json")
        wait_for(lambda: out.get("error") or os.path.exists(last),
                 300, "successor replay")
        takeover = time.perf_counter() - t_takeover
        th.join(timeout=600)
        if th.is_alive() or "error" in out:
            raise RuntimeError(
                f"failover: successor leg failed: {out.get('error')}"
            )
        s2 = out["summary"]
        assert takeover <= 2 * ttl, (
            f"takeover {takeover:.2f}s > 2x lease TTL ({2 * ttl:.1f}s)"
        )
        assert s2["term"] == 2
        assert s2["windows_published"] == windows, s2
        assert s2["lines_total"] == total, s2
        assert s2["drops"] == 0 and s2["skipped_windows"] == [], s2
        assert s2["failover"]["replay_windows"] == windows, s2["failover"]
        assert s2["failover"]["replay_refused"] == 0, s2["failover"]

        def lineage_identity(rec: dict) -> dict:
            # the replay-identity law (DESIGN §24): strip the volatile
            # fields AND the per-host payload_crc — epoch payloads carry
            # run-local wall stamps, so the byte CRC is only comparable
            # within one serve dir, never across the control/failover pair
            core = {k: v for k, v in rec.items() if k not in LINEAGE_VOLATILE}
            core["hosts"] = [
                {k: v for k, v in h.items() if k != "payload_crc"}
                for h in core["hosts"]
            ]
            return core

        identical = 0
        for w in range(windows):
            a = read_json(os.path.join(fo_dir, f"window-{w:06d}.json"))
            b = read_json(os.path.join(d, "control", f"window-{w:06d}.json"))
            # exactly one publisher per fencing term: the control's
            # windows all carry term 1, the successor's all term 2
            assert a["totals"]["window"]["term"] == 2, a["totals"]["window"]
            assert b["totals"]["window"]["term"] == 1, b["totals"]["window"]
            assert image(a) == image(b), (
                f"replayed window {w} diverged from the unkilled control"
            )
            assert a.get("talkers") == b.get("talkers"), (
                f"replayed window {w} talkers diverged"
            )
            # lineage replay identity: the replayed record is the SAME
            # deterministic function of the delivered lines, term/path
            # volatiles aside ("dist", every host's WAL range + drop
            # counts), and the successor stamps (term 2, path "replay")
            # against the control's (term 1, "live")
            la = a["totals"]["lineage"]
            lb = b["totals"]["lineage"]
            assert lineage_identity(la) == lineage_identity(lb), (
                f"replayed window {w} lineage core diverged"
            )
            assert la["kind"] == "dist" and len(la["hosts"]) == n_hosts
            assert (la["term"], la["path"]) == (2, "replay"), la
            assert (lb["term"], lb["path"]) == (1, "live"), lb
            identical += 1
        # the successor's ledger frontier is gapless and complete
        fr = lineage_frontier(
            LineageLog.read(os.path.join(fo_dir, LineageLog.NAME))
        )
        assert fr["windows"] >= windows and fr["gaps"] == [], fr
        assert fr["last_complete"] == windows - 1, fr
        assert fr["first_incomplete"] is None, fr
        cum_same = image(
            read_json(os.path.join(fo_dir, "cumulative.json"))
        ) == image(read_json(os.path.join(d, "control", "cumulative.json")))
        assert cum_same, "replayed cumulative diverged from the control"

    return {
        "bench": "failover",
        "metric": "failover_time_to_takeover_sec",
        "value": round(takeover, 3),
        "unit": "sec",
        "vs_baseline": round(takeover / (2 * ttl), 3),  # x the 2xTTL budget
        "detail": {
            "platform": jax.devices()[0].platform,
            "devices": len(jax.devices()),
            "hosts": n_hosts,
            "workers": "thread",
            "windows": windows,
            "lines_total": total,
            "offered_rate_per_host_lines_per_sec": rate,
            "lease_ttl_sec": ttl,
            "bare_sustained_lines_per_sec": round(bare_rate, 1),
            "armed_sustained_lines_per_sec": round(armed_rate, 1),
            "armed_overhead_frac": round(overhead, 4),
            "takeover_budget_sec": 2 * ttl,
            "epochs_replayed": s2["failover"]["spool_replayed"],
            "windows_replayed": s2["failover"]["replay_windows"],
            "windows_bit_identical": identical,
            "cumulative_bit_identical": cum_same,
            "victim_windows_published": 0,
            "terms": {"control": 1, "successor": 2},
            "method": (
                "both rate legs are paced identically at the same "
                "offered rate, so the armed-overhead fraction isolates "
                "per-window spool fsyncs + ttl/4 lease heartbeats; the "
                "victim leg parks every epoch behind an armed "
                "dist.epoch.ship partition so term 1 provably publishes "
                "nothing before the in-process supervisor death, and "
                "time-to-takeover clocks the successor from run() start "
                "to the last replayed window on disk (election + spool "
                "replay inclusive)"
            ),
            "guards": {
                "armed_overhead_lt_2pct": True,
                "takeover_le_2x_ttl": True,
                "bit_identical_all_windows": True,
                "talkers_identical": True,
                "cumulative_bit_identical": True,
                "zero_drops_all_legs": True,
                "zero_skipped_windows": True,
                "one_publisher_per_term": True,
                "victim_published_nothing": True,
                "lineage_replay_identity": True,
                "lineage_frontier_complete": True,
            },
        },
    }


def bench_lineage() -> dict:
    """Lineage plane + SLO burn-rate overhead & acceptance (DESIGN §24).

    Three legs, one solo-serve corpus, one process (shared jit caches):

    1. **Overhead pairs** — ``RA_LINEAGE_PAIRS`` (default 3) interleaved
       disarmed/armed runs: disarmed = ``--lineage off``, no ``--slo``,
       trends off; armed = lineage ledger + sealed records + a 2-objective
       SLO policy + trend plane.  Both legs are paced identically at
       ``RA_LINEAGE_RATE`` (default 8k lines/s — under the serve loop's
       measured 1-core capacity, the servesoak discipline); sustained =
       lines / (send start -> last window published), so the ratio
       isolates the armed plane's per-window cost (one canonical-JSON
       CRC + one O_APPEND write + burn-rate arithmetic) from load noise.
       Asserted in-bench: **median armed/disarmed sustained ratio >=
       0.98** (the provenance plane must not tax the hot path).
    2. **Ledger audit** — after the last armed run: every window file's
       ``totals.lineage`` equals the ledger record, every seal CRC
       re-verifies, and the frontier is gapless and complete.
    3. **Breach + recovery e2e** — a fresh armed run with
       ``drop_rate<=0.001``: one window with chaos-injected listener
       drops breaches (fast+slow burn over budget within the rotation),
       three clean windows recover.  Asserted in-bench: exactly one
       ``slo.breach`` and one ``slo.recovered`` transition (gauge
       counters), the breached window's lineage record carries the drop
       count, and the JSON /metrics SLO + build-info gauges agree with
       the prom exposition (flat, labeled, and ``ra_build_info``).
    """
    import os
    import socket
    import tempfile
    import threading

    import jax

    from ruleset_analysis_tpu.config import AnalysisConfig, ServeConfig
    from ruleset_analysis_tpu.hostside import aclparse, synth
    from ruleset_analysis_tpu.hostside import pack as pack_mod
    from ruleset_analysis_tpu.runtime import faults
    from ruleset_analysis_tpu.runtime.report import (
        lineage_frontier, seal_lineage,
    )
    from ruleset_analysis_tpu.runtime.serve import ServeDriver
    from ruleset_analysis_tpu.runtime.stream import run_stream
    from ruleset_analysis_tpu.runtime.wal import LineageLog

    windows = 3
    pairs = int(os.environ.get("RA_LINEAGE_PAIRS", "3"))
    rate = float(os.environ.get("RA_LINEAGE_RATE", "8000"))
    wl = int(float(os.environ.get("RA_LINEAGE_LINES", "9000"))) // windows
    total = wl * windows
    BATCH = 4096
    SLO = "p99_publish_ms<=60000,drop_rate<=0.5"

    cfg_text = synth.synth_config(n_acls=2, rules_per_acl=10, seed=0)
    packed = pack_mod.pack_rulesets([aclparse.parse_asa_config(cfg_text, "fw1")])
    t = _tuples(packed, total, seed=29)
    lines = synth.render_syslog(packed, t, seed=29)

    def read_json(path):
        with open(path, "r", encoding="utf-8") as f:
            return json.load(f)

    def wait_for(pred, timeout, what):
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if pred():
                return
            time.sleep(0.02)
        raise RuntimeError(f"lineage: timed out waiting for {what}")

    def run_serve(d, name, *, armed, feed_lines, wl, http="off", slo=None):
        sd = os.path.join(d, name)
        drv = ServeDriver(
            os.path.join(d, "rules"),
            AnalysisConfig(batch_size=BATCH, prefetch_depth=0),
            ServeConfig(
                listen=("tcp:127.0.0.1:0",), window_lines=wl,
                serve_dir=sd, max_windows=0, http=http,
                checkpoint_every_windows=0, reload_watch=False,
                queue_lines=1 << 18,
                lineage=armed,
                slo=(SLO if slo is None else slo) if armed else "",
                trend_threshold=4.0 if armed else 0.0,
            ),
        )
        out: dict = {}

        def runner():
            try:
                out["summary"] = drv.run()
            except BaseException as e:
                out["error"] = e

        th = threading.Thread(target=runner)
        th.start()
        wait_for(
            lambda: out.get("error") or (
                drv.listeners.listeners and drv.listeners.alive()
                and (http == "off" or drv.http_address)
            ),
            60, f"{name} listener",
        )
        if "error" in out:
            raise RuntimeError(f"lineage: {name} failed: {out['error']}")
        addr = tuple(drv.listeners.listeners[0].address)
        t0 = time.perf_counter()
        s = socket.create_connection(addr)
        # paced replay (the servesoak discipline): bursts of 500 lines
        # against the wall clock, so both legs see the same offered rate
        sent = 0
        for i in range(0, len(feed_lines), 500):
            burst = feed_lines[i:i + 500]
            s.sendall(("\n".join(burst) + "\n").encode())
            sent += len(burst)
            lag = sent / rate - (time.perf_counter() - t0)
            if lag > 0:
                time.sleep(lag)
        s.close()
        want = len(feed_lines) // wl
        wait_for(
            lambda: out.get("error") or drv.windows_published >= want,
            300, f"{name} windows",
        )
        if "error" in out:
            raise RuntimeError(f"lineage: {name} failed: {out['error']}")
        sustained = len(feed_lines) / max(time.perf_counter() - t0, 1e-6)
        return drv, th, out, sustained

    def stop(drv, th, out):
        drv.stop()
        th.join(timeout=120)
        if th.is_alive():
            raise RuntimeError("lineage: serve failed to stop")
        if "error" in out:
            raise RuntimeError(f"lineage: {out['error']}")
        return out["summary"]

    with tempfile.TemporaryDirectory() as d:
        pack_mod.save_packed(packed, os.path.join(d, "rules"))
        run_stream(
            packed, iter(lines[:64]),
            AnalysisConfig(batch_size=BATCH, prefetch_depth=0),
        )

        # ---- leg 1: interleaved disarmed/armed overhead pairs ----
        ratios = []
        rates: dict = {"disarmed": [], "armed": []}
        last_armed_dir = None
        for i in range(pairs):
            _d, th, out, off_rate = run_serve(
                d, f"off-{i}", armed=False, feed_lines=lines, wl=wl,
            )
            soff = stop(_d, th, out)
            assert soff["drops"] == 0
            _a, th, out, on_rate = run_serve(
                d, f"on-{i}", armed=True, feed_lines=lines, wl=wl,
            )
            son = stop(_a, th, out)
            assert son["drops"] == 0
            last_armed_dir = os.path.join(d, f"on-{i}")
            rates["disarmed"].append(round(off_rate, 1))
            rates["armed"].append(round(on_rate, 1))
            ratios.append(on_rate / off_rate)
            log(
                f"lineage: pair {i}: disarmed {off_rate:,.0f} vs armed "
                f"{on_rate:,.0f} lines/s (ratio {ratios[-1]:.4f})"
            )
        med_ratio = sorted(ratios)[len(ratios) // 2]
        assert med_ratio >= 0.98, (
            f"lineage/SLO armed plane costs too much: median sustained "
            f"ratio {med_ratio:.4f} < 0.98 ({ratios})"
        )

        # ---- leg 2: ledger audit on the last armed run ----
        ledger = LineageLog.read(os.path.join(last_armed_dir, LineageLog.NAME))
        assert len(ledger) == windows, f"ledger holds {len(ledger)} records"
        for w in range(windows):
            rep = read_json(
                os.path.join(last_armed_dir, f"window-{w:06d}.json")
            )
            lin = rep["totals"]["lineage"]
            assert lin == ledger[w], f"window {w} record drifted"
            assert seal_lineage(dict(lin))["crc"] == lin["crc"]
            assert lin["path"] == "live" and "incomplete" not in lin
        fr = lineage_frontier(ledger)
        assert fr["last_complete"] == windows - 1
        assert fr["first_incomplete"] is None and fr["gaps"] == []

        # ---- leg 3: provoked breach + recovery, JSON<->prom parity ----
        import urllib.request

        drv, th, out, _rate = run_serve(
            d, "breach", armed=True, feed_lines=lines[:wl], wl=wl,
            http="127.0.0.1:0", slo="drop_rate<=0.001",
        )
        try:
            addr = tuple(drv.listeners.listeners[0].address)
            wait_for(
                lambda: out.get("error") or drv.slo.windows_observed >= 1,
                60, "clean window observed",
            )
            # one window with 200 chaos-dropped lines: drop_rate ~6%
            # >> 0.001 -> fast AND slow burn cross in one rotation
            with faults.armed(faults.FaultPlan.parse("listener.drop@1:200")):
                s = socket.create_connection(addr)
                s.sendall(
                    ("\n".join(lines[wl:2 * wl + 200]) + "\n").encode()
                )
                s.close()
                wait_for(
                    lambda: out.get("error")
                    or drv.slo.windows_observed >= 2,
                    300, "breach window",
                )
            if "error" in out:
                raise RuntimeError(f"lineage: {out['error']}")
            assert drv.slo.breaches_total == 1, drv.slo.gauges()
            assert drv.slo.gauges()["slo_breached"] == 1
            breach_rec = drv.lineage_record(1)
            assert breach_rec["hosts"][0]["drops"] == 200, breach_rec
            # three clean windows: burn_fast falls under 1 -> recovery
            for w in range(3):
                s = socket.create_connection(addr)
                s.sendall(("\n".join(lines[:wl]) + "\n").encode())
                s.close()
                wait_for(
                    lambda: out.get("error")
                    or drv.slo.windows_observed >= 3 + w,
                    300, f"recovery window {w}",
                )
            assert drv.slo.recoveries_total == 1, drv.slo.gauges()
            assert drv.slo.gauges()["slo_breached"] == 0

            host, port = drv.http_address
            with urllib.request.urlopen(
                f"http://{host}:{port}/metrics", timeout=10
            ) as r:
                mjson = json.load(r)
            with urllib.request.urlopen(
                f"http://{host}:{port}/metrics?format=prom", timeout=10
            ) as r:
                prom = r.read().decode()
            prom_vals = {}
            for line in prom.splitlines():
                if line and not line.startswith("#") and " " in line:
                    k, v = line.rsplit(" ", 1)
                    try:
                        prom_vals[k] = float(v)
                    except ValueError:
                        pass
            slo_keys = [k for k in mjson if k.startswith("slo_")]
            assert slo_keys, "no SLO gauges on /metrics"
            for k in slo_keys:
                assert prom_vals.get(f"ra_serve_{k}") == float(mjson[k]), (
                    f"JSON<->prom drift on {k}: "
                    f"{mjson[k]} vs {prom_vals.get(f'ra_serve_{k}')}"
                )
            assert prom_vals.get("ra_serve_lineage_records_total") == float(
                mjson["lineage_records_total"]
            )
            for lk, lv in drv.slo.labeled_gauges()["drop_rate"].items():
                assert (
                    prom_vals[f'ra_serve_{lk}{{objective="drop_rate"}}']
                    == float(lv)
                ), f"labeled drift on {lk}"
            bi = mjson["build_info"]
            assert "ra_build_info{" in prom
            for k, v in bi.items():
                assert f'{k}="{v}"' in prom, f"build_info label {k} missing"
        finally:
            stop(drv, th, out)

    return {
        "bench": "lineage",
        "metric": "lineage_armed_sustained_ratio",
        "value": round(med_ratio, 4),
        "unit": "ratio",
        "vs_baseline": round(med_ratio / 0.98, 4),  # x the floor
        "detail": {
            "platform": jax.devices()[0].platform,
            "devices": len(jax.devices()),
            "pairs": pairs,
            "windows_per_run": windows,
            "lines_per_run": total,
            "offered_rate_lines_per_sec": rate,
            "slo_policy": SLO,
            "disarmed_sustained_lines_per_sec": rates["disarmed"],
            "armed_sustained_lines_per_sec": rates["armed"],
            "sustained_ratios": [round(r, 4) for r in ratios],
            "ledger_records_audited": windows,
            "breach_drop_lines": 200,
            "breaches_total": 1,
            "recoveries_total": 1,
            "method": (
                "interleaved disarmed/armed pairs replay the same corpus "
                "through one solo serve process, paced identically at the "
                "offered rate (sustained = lines / send-start->last-"
                "window, so the ratio isolates the armed plane's "
                "per-window cost from load noise); armed adds the sealed "
                "lineage ledger, a 2-objective SLO burn-rate engine, and "
                "the trend plane.  The breach leg serves under a "
                "drop_rate<=0.001 policy and injects exactly 200 "
                "listener.drop chaos hits into one window (drop_rate "
                "~6% >> bound; fast+slow burn cross in one rotation), "
                "then feeds three clean windows for the recovery "
                "transition; /metrics JSON gauges are compared "
                "numerically against the prom exposition (flat, "
                "objective-labeled, and ra_build_info)"
            ),
            "guards": {
                "median_ratio_ge_0_98": True,
                "ledger_equals_window_files": True,
                "seal_crcs_verify": True,
                "frontier_gapless": True,
                "one_breach_one_recovery": True,
                "breach_window_lineage_names_drops": True,
                "json_prom_slo_parity": True,
                "json_prom_build_info_parity": True,
            },
        },
    }


def bench_epochstore() -> dict:
    """Durable epoch store (DESIGN §25): query speedup, spill tax, crash.

    Three legs, the ISSUE 20 acceptance artifact:

    1. **Range-query speedup** — ``RA_EPOCHSTORE_EPOCHS`` (default 512)
       synthetic epochs spilled through the production spill/compact
       path, then random ``[t0,t1]`` queries spanning >= 256 epochs
       answered twice: the segment-tree decomposition (<= 2 log n stored
       aggregates + one merge fold) vs the naive linear L0 fold.
       Asserted in-bench: **median speedup >= 10x** and every query pair
       **bit-identical** (registers, tracker tables, accounting).
       Compaction throughput (spills/s through the binary-counter
       promote) rides along in the detail.
    2. **Spill overhead pairs** — ``RA_EPOCHSTORE_PAIRS`` (default 3)
       interleaved disarmed/armed solo-serve runs over one corpus, paced
       identically at ``RA_EPOCHSTORE_RATE`` (default 8k lines/s, the
       servesoak discipline); armed = ``--epoch-store`` spilling every
       rotation.  Asserted in-bench: **median armed/disarmed sustained
       ratio >= 0.98**, and the last armed run's ``/report/range`` over
       the full span answers complete with the corpus line total.
    3. **Compaction crash** — a child process spills epochs under an
       armed ``epochstore.compact`` crash plan (os._exit at the worst
       instant: pair chosen, merged node unwritten).  Asserted in-bench:
       the reopened store is readable, holds **every epoch whose spill
       started** (zero lost), repair restores the level invariant, and
       the full-span tree fold still equals the linear fold bit for bit.
    """
    import os
    import socket
    import subprocess
    import tempfile
    import textwrap
    import threading
    import urllib.request

    import jax
    import numpy as np

    from ruleset_analysis_tpu.config import AnalysisConfig, ServeConfig
    from ruleset_analysis_tpu.hostside import aclparse, synth
    from ruleset_analysis_tpu.hostside import pack as pack_mod
    from ruleset_analysis_tpu.runtime import epochstore
    from ruleset_analysis_tpu.runtime.serve import ServeDriver
    from ruleset_analysis_tpu.runtime.stream import run_stream

    n_epochs = int(os.environ.get("RA_EPOCHSTORE_EPOCHS", "512"))
    assert n_epochs >= 512, "leg 1 needs >= 512 epochs for 256-wide spans"
    n_queries = 16
    pairs = int(os.environ.get("RA_EPOCHSTORE_PAIRS", "3"))
    rate = float(os.environ.get("RA_EPOCHSTORE_RATE", "8000"))
    windows = 3
    wl = int(float(os.environ.get("RA_EPOCHSTORE_LINES", "9000"))) // windows
    BATCH = 4096

    def synth_epoch(wid: int):
        rng = np.random.default_rng(wid)

        class _Ep:
            arrays = {
                "counts_lo": rng.integers(0, 2**32, 1024, dtype=np.uint32),
                "counts_hi": rng.integers(0, 3, 1024, dtype=np.uint32),
                "cms": rng.integers(0, 2**32, (4, 1024), dtype=np.uint32),
                "hll": rng.integers(0, 30, (256, 8), dtype=np.uint32),
                "talk_cms": rng.integers(
                    0, 2**32, (4, 1024), dtype=np.uint32
                ),
            }
            meta = {
                "id": wid, "lines": 1000 + wid, "parsed": 990,
                "skipped": 10, "chunks": 2, "drops": 0,
                "started_unix": 10.0 + wid, "ended_unix": 11.0 + wid,
            }
            tracker_tables = {
                int(a): {
                    int(s): int(e) for s, e in zip(
                        rng.integers(0, 2**32, 8),
                        rng.integers(1, 10_000, 8),
                    )
                } for a in range(2)
            }
            quarantine = {}

        return _Ep()

    def agg_equal(a, b):
        return (
            all(np.array_equal(a.arrays[k], b.arrays[k]) for k in a.arrays)
            and a.tables == b.tables and a.summary == b.summary
            and a.quarantine == b.quarantine
        )

    results: dict = {}
    with tempfile.TemporaryDirectory() as d:
        # ---- leg 1: tree vs naive fold over a 512-epoch store ----
        store = epochstore.EpochStore(
            os.path.join(d, "estore"), budget_bytes=256 << 20
        )
        store.bind_base(0)
        t0 = time.perf_counter()
        for wid in range(n_epochs):
            store.spill(synth_epoch(wid))
        build_sec = time.perf_counter() - t0
        rng = np.random.default_rng(7)
        speedups, q_tree_ms, q_naive_ms = [], [], []
        for _ in range(n_queries):
            span = int(rng.integers(256, n_epochs))
            lo = int(rng.integers(0, n_epochs - span))
            hi = lo + span - 1
            t1 = time.perf_counter()
            agg, marker = store.range_agg(lo, hi)
            t2 = time.perf_counter()
            ref, nmarker = store.naive_range_agg(lo, hi)
            t3 = time.perf_counter()
            assert marker is None and nmarker is None, (marker, nmarker)
            assert agg_equal(agg, ref), f"fold drift on [{lo},{hi}]"
            q_tree_ms.append((t2 - t1) * 1e3)
            q_naive_ms.append((t3 - t2) * 1e3)
            speedups.append((t3 - t2) / max(t2 - t1, 1e-9))
        med_speedup = sorted(speedups)[len(speedups) // 2]
        assert med_speedup >= 10.0, (
            f"segment-tree range query only {med_speedup:.1f}x over the "
            f"naive linear fold (need >= 10x): {speedups}"
        )
        depth = store.stats()["depth"]
        store.close()
        log(
            f"epochstore: {n_epochs} epochs, depth {depth}: median query "
            f"{sorted(q_tree_ms)[n_queries // 2]:.2f} ms vs naive "
            f"{sorted(q_naive_ms)[n_queries // 2]:.1f} ms "
            f"({med_speedup:.1f}x); build {n_epochs / build_sec:,.0f} "
            f"spills/s"
        )
        results["leg1"] = {
            "epochs": n_epochs,
            "tree_depth": depth,
            "queries": n_queries,
            "query_tree_ms": [round(x, 3) for x in sorted(q_tree_ms)],
            "query_naive_ms": [round(x, 2) for x in sorted(q_naive_ms)],
            "median_speedup": round(med_speedup, 1),
            "compaction_spills_per_sec": round(n_epochs / build_sec, 1),
        }

        # ---- leg 2: spill-armed vs disarmed serve pairs ----
        cfg_text = synth.synth_config(n_acls=2, rules_per_acl=10, seed=0)
        packed = pack_mod.pack_rulesets(
            [aclparse.parse_asa_config(cfg_text, "fw1")]
        )
        t = _tuples(packed, wl * windows, seed=31)
        lines = synth.render_syslog(packed, t, seed=31)
        pack_mod.save_packed(packed, os.path.join(d, "rules"))
        run_stream(
            packed, iter(lines[:64]),
            AnalysisConfig(batch_size=BATCH, prefetch_depth=0),
        )

        def wait_for(pred, timeout, what):
            deadline = time.monotonic() + timeout
            while time.monotonic() < deadline:
                if pred():
                    return
                time.sleep(0.02)
            raise RuntimeError(f"epochstore: timed out waiting for {what}")

        def run_serve(name, *, armed, http="off"):
            sd = os.path.join(d, name)
            drv = ServeDriver(
                os.path.join(d, "rules"),
                AnalysisConfig(batch_size=BATCH, prefetch_depth=0),
                ServeConfig(
                    listen=("tcp:127.0.0.1:0",), window_lines=wl,
                    serve_dir=sd, max_windows=0, http=http,
                    checkpoint_every_windows=0, reload_watch=False,
                    queue_lines=1 << 18,
                    epoch_store=(
                        os.path.join(sd, "estore") if armed else ""
                    ),
                ),
            )
            out: dict = {}

            def runner():
                try:
                    out["summary"] = drv.run()
                except BaseException as e:
                    out["error"] = e

            th = threading.Thread(target=runner)
            th.start()
            wait_for(
                lambda: out.get("error") or (
                    drv.listeners.listeners and drv.listeners.alive()
                    and (http == "off" or drv.http_address)
                ),
                60, f"{name} listener",
            )
            if "error" in out:
                raise RuntimeError(f"epochstore: {name}: {out['error']}")
            addr = tuple(drv.listeners.listeners[0].address)
            t0 = time.perf_counter()
            s = socket.create_connection(addr)
            sent = 0
            for i in range(0, len(lines), 500):
                burst = lines[i:i + 500]
                s.sendall(("\n".join(burst) + "\n").encode())
                sent += len(burst)
                lag = sent / rate - (time.perf_counter() - t0)
                if lag > 0:
                    time.sleep(lag)
            s.close()
            wait_for(
                lambda: out.get("error")
                or drv.windows_published >= windows,
                300, f"{name} windows",
            )
            if "error" in out:
                raise RuntimeError(f"epochstore: {name}: {out['error']}")
            sustained = len(lines) / max(time.perf_counter() - t0, 1e-6)
            return drv, th, out, sustained

        def stop(drv, th, out):
            drv.stop()
            th.join(timeout=120)
            if th.is_alive():
                raise RuntimeError("epochstore: serve failed to stop")
            if "error" in out:
                raise RuntimeError(f"epochstore: {out['error']}")
            return out["summary"]

        ratios = []
        rates: dict = {"disarmed": [], "armed": []}
        for i in range(pairs):
            last = i == pairs - 1
            drv, th, out, off_rate = run_serve(f"off-{i}", armed=False)
            soff = stop(drv, th, out)
            assert soff["drops"] == 0
            drv, th, out, on_rate = run_serve(
                f"on-{i}", armed=True,
                http="127.0.0.1:0" if last else "off",
            )
            if last:
                # the armed plane must ANSWER, not just keep up: the
                # full-span range report equals the corpus totals
                host, port = drv.http_address
                with urllib.request.urlopen(
                    f"http://{host}:{port}/report/range?from=0"
                    f"&to={windows - 1}", timeout=10,
                ) as r:
                    rng_rep = json.load(r)
                assert "range_incomplete" not in rng_rep, rng_rep
                tot = rng_rep["totals"]
                assert tot["lines_total"] == len(lines), tot
                assert tot["window"]["windows"] == windows, tot
            son = stop(drv, th, out)
            assert son["drops"] == 0
            assert son["epoch_store"]["epochs"] == windows, (
                son["epoch_store"]
            )
            rates["disarmed"].append(round(off_rate, 1))
            rates["armed"].append(round(on_rate, 1))
            ratios.append(on_rate / off_rate)
            log(
                f"epochstore: pair {i}: disarmed {off_rate:,.0f} vs "
                f"armed {on_rate:,.0f} lines/s (ratio {ratios[-1]:.4f})"
            )
        med_ratio = sorted(ratios)[len(ratios) // 2]
        assert med_ratio >= 0.98, (
            f"epoch-store spill taxes the hot path: median sustained "
            f"ratio {med_ratio:.4f} < 0.98 ({ratios})"
        )
        results["leg2"] = {
            "pairs": pairs,
            "windows_per_run": windows,
            "lines_per_run": len(lines),
            "offered_rate_lines_per_sec": rate,
            "disarmed_sustained_lines_per_sec": rates["disarmed"],
            "armed_sustained_lines_per_sec": rates["armed"],
            "sustained_ratios": [round(r, 4) for r in ratios],
            "median_ratio": round(med_ratio, 4),
        }

        # ---- leg 3: crash mid-compaction, reopen, zero lost epochs ----
        crash_dir = os.path.join(d, "crash-estore")
        child = textwrap.dedent("""
            import sys
            import numpy as np
            from ruleset_analysis_tpu.runtime import epochstore

            store = epochstore.EpochStore(sys.argv[1])
            store.bind_base(0)
            for wid in range(32):
                rng = np.random.default_rng(wid)

                class _Ep:
                    arrays = {
                        "counts_lo": rng.integers(
                            0, 2**32, 64, dtype=np.uint32),
                        "counts_hi": np.zeros(64, dtype=np.uint32),
                        "cms": rng.integers(
                            0, 2**32, (2, 64), dtype=np.uint32),
                        "hll": rng.integers(
                            0, 30, (32, 4), dtype=np.uint32),
                        "talk_cms": rng.integers(
                            0, 2**32, (2, 64), dtype=np.uint32),
                    }
                    meta = {
                        "id": wid, "lines": 100, "parsed": 100,
                        "skipped": 0, "chunks": 1, "drops": 0,
                        "started_unix": 1.0 + wid,
                        "ended_unix": 2.0 + wid,
                    }
                    tracker_tables = {0: {wid: wid + 1}}
                    quarantine = {}

                store.spill(_Ep())
                print(wid, flush=True)
        """)
        env = dict(
            os.environ, JAX_PLATFORMS="cpu",
            RA_FAULT_PLAN="epochstore.compact@6",
        )
        proc = subprocess.run(
            [sys.executable, "-c", child, crash_dir],
            capture_output=True, text=True, timeout=300,
            cwd=os.path.dirname(os.path.abspath(__file__)), env=env,
        )
        assert proc.returncode != 0, "crash plan never fired"
        done = [int(x) for x in proc.stdout.split()]
        assert done, f"child crashed before any spill: {proc.stderr[-500:]}"
        survivor = epochstore.EpochStore(crash_dir)
        st = survivor.stats()
        # the crash fires INSIDE a spill's promote, after its L0 append:
        # every started spill is on disk — completed prints + the victim
        assert st["epochs"] == len(done) + 1, (st, done)
        hi = st["epochs"] - 1
        agg, marker = survivor.range_agg(0, hi)
        ref, nmarker = survivor.naive_range_agg(0, hi)
        assert marker is None and nmarker is None, (marker, nmarker)
        assert agg_equal(agg, ref), "post-crash fold drift"
        assert agg.summary["windows"] == st["epochs"]
        survivor.close()
        log(
            f"epochstore: crash leg: {len(done)} spills acked, "
            f"{st['epochs']} epochs survive, repair ok, fold identical"
        )
        results["leg3"] = {
            "spills_acked_before_crash": len(done),
            "epochs_after_reopen": st["epochs"],
            "fault_plan": "epochstore.compact@6",
            "holes_after_repair": st["holes_total"],
        }

    return {
        "bench": "epochstore",
        "metric": "range_query_median_speedup",
        "value": results["leg1"]["median_speedup"],
        "unit": "x_vs_naive_fold",
        "vs_baseline": round(results["leg1"]["median_speedup"] / 10.0, 2),
        "detail": {
            "platform": jax.devices()[0].platform,
            "devices": len(jax.devices()),
            **results["leg1"],
            "spill_overhead": results["leg2"],
            "crash": results["leg3"],
            "method": (
                "leg 1 spills synthetic epochs through the production "
                "spill/compact path and answers random >=256-wide "
                "ranges twice — the segment-tree decomposition vs the "
                "naive linear L0 fold — timing both and comparing "
                "registers, tracker tables, and accounting bit for bit; "
                "leg 2 interleaves disarmed/armed paced solo-serve "
                "runs over one corpus (sustained = lines / send-start->"
                "last-window) and reads /report/range over the full "
                "span from the last armed run; leg 3 crashes a child "
                "process mid-compaction (pair chosen, merged node "
                "unwritten) and reopens the store"
            ),
            "guards": {
                "median_speedup_ge_10x": True,
                "tree_fold_bit_identical_to_naive": True,
                "median_armed_ratio_ge_0_98": True,
                "range_report_complete_over_full_span": True,
                "zero_drops_both_legs": True,
                "crash_store_readable": True,
                "zero_lost_epochs_after_crash": True,
                "post_crash_fold_bit_identical": True,
            },
        },
    }


BENCHES = {
    "stage": bench_stage,
    "exact": bench_exact,
    "cms": bench_cms,
    "hll": bench_hll,
    "multifw": bench_multifw,
    "topk": bench_topk,
    "pallas": bench_pallas,
    "recall": bench_recall,
    "e2e": bench_e2e,
    "sustained": bench_sustained,
    "servesoak": bench_servesoak,
    "autoscale": bench_autoscale,
    "obs": bench_obs,
    "steptrace": bench_steptrace,
    "stepvariants": bench_stepvariants,
    "coalesce": bench_coalesce,
    "convert": bench_convert,
    "feedscale": bench_feedscale,
    "rulescale": bench_rulescale,
    "retrysoak": bench_retrysoak,
    "blackbox": bench_blackbox,
    "tenant": bench_tenant,
    "servescale": bench_servescale,
    "failover": bench_failover,
    "lineage": bench_lineage,
    "epochstore": bench_epochstore,
    "v6": bench_v6,
    "v6recall": bench_v6recall,
}


#: a bare `python bench_suite.py` runs these; `sustained` (≥1e8 lines —
#: minutes of wall time by design), `servesoak` and `autoscale` (paced
#: live-service soaks with sockets + threads), `feedscale` (worker
#: fleets of spawned processes), `tenant` (17 full serve drivers
#: with live sockets), `servescale` (three paced multi-process
#: distributed-serve soaks), `failover` (four paced supervisor
#: kill/election soaks), `lineage` (live-socket lineage/SLO
#: overhead + breach soaks) and `epochstore` (512-epoch store build +
#: paced serve pairs + a crash child) are explicit-only
DEFAULT_BENCHES = [
    n for n in BENCHES
    if n not in ("sustained", "servesoak", "autoscale", "feedscale",
                 "retrysoak", "blackbox", "tenant", "servescale",
                 "failover", "lineage", "epochstore")
]


def main(argv: list[str]) -> int:
    from ruleset_analysis_tpu.runtime.compcache import enable_persistent_cache

    log(f"compilation cache: {enable_persistent_cache()}")
    names = argv or DEFAULT_BENCHES
    for name in names:
        if name not in BENCHES:
            log(f"unknown bench {name!r}; choices: {list(BENCHES)}")
            return 2
        log(f"=== {name} ===")
        t0 = time.perf_counter()
        result = BENCHES[name]()
        result["detail"]["bench_wall_sec"] = round(time.perf_counter() - t0, 1)
        print(json.dumps(result), flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
