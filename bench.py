"""Headline benchmark: ASA syslog lines/sec/chip through the device pipeline.

Prints exactly ONE JSON line on stdout — always, even when the TPU tunnel
is down (round-1 postmortem: the axon plugin can hang indefinitely at
backend init, and a traceback on stdout scores as `parsed: null`).

Structure: this file is an orchestrator that never imports jax itself.
  1. Probe the default backend in a subprocess (60s timeout, 3 attempts
     with backoff).
  2. On success, run the measurement child (`--run`) in the inherited env
     with a generous timeout.
  3. On any failure, rerun the child on a scrubbed 8-device fake-CPU env
     (same code path, smaller geometry) and mark the JSON `backend:
     "cpu-fallback"` with the TPU failure reason.
  4. If even that fails, emit an `{"error": ...}` JSON object.

The headline `value` is the device-pipeline steady-state rate per chip
(batches resident in HBM, state donated) — the per-chip capability number.
`detail` also carries the measured end-to-end rate through the full file
path (text -> native parse -> device_put -> step), plus a roofline-style
utilization estimate, so "is it actually fast" is answerable from the JSON
(VERDICT round 1, weak #3 / next #6).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

_REPO = os.path.dirname(os.path.abspath(__file__))

NORTH_STAR_TOTAL = 1e9 / 60.0  # lines/sec, v5e-8, end-to-end (BASELINE.md)
NORTH_STAR_PER_CHIP = NORTH_STAR_TOTAL / 8.0

# v5e roofline constants (per chip), from public TPU v5e specs / the
# scaling-book numbers: VPU is an (8, 128) vector unit with 4 independent
# ALUs at ~0.94 GHz -> ~3.85e12 u32 ops/s; HBM bandwidth 819 GB/s.
V5E_VPU_U32_OPS = 8 * 128 * 4 * 0.94e9
V5E_HBM_BYTES = 819e9
# u32 VPU ops per (line, rule-row) predicate cell: 11 compares + 10 ands
# + ~2 for the masked min-index reduction (ops/match.py _block_min_row).
OPS_PER_CELL = 23.0


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def emit(obj) -> None:
    print(json.dumps(obj), flush=True)


# ---------------------------------------------------------------------------
# Child: the actual measurement (runs under a known-healthy backend).
# ---------------------------------------------------------------------------


def run_bench(cpu_scale: bool) -> dict:
    import jax
    import numpy as np

    from ruleset_analysis_tpu.config import AnalysisConfig, SketchConfig
    from ruleset_analysis_tpu.hostside import aclparse, pack, synth
    from ruleset_analysis_tpu.models import pipeline
    from ruleset_analysis_tpu.parallel import mesh as mesh_lib
    from ruleset_analysis_tpu.parallel.step import make_parallel_step

    devices = jax.devices()
    n_dev = len(devices)
    platform = devices[0].platform
    log(f"devices: {devices}")

    # BASELINE.json config #1 geometry: one realistic ruleset
    cfg_text = synth.synth_config(n_acls=4, rules_per_acl=64, seed=0)
    rs = aclparse.parse_asa_config(cfg_text, "fw1")
    packed = pack.pack_rulesets([rs])
    log(f"ruleset: {packed.n_rules} rules, {packed.rules.shape[0]} expanded rows")

    per_chip_batch = 1 << 16 if cpu_scale else 1 << 20
    batch_size = per_chip_batch * n_dev
    cfg = AnalysisConfig(
        batch_size=batch_size,
        sketch=SketchConfig(cms_width=1 << 14, cms_depth=4, hll_p=8),
    )

    mesh = mesh_lib.make_mesh(devices)
    step = make_parallel_step(mesh, cfg, packed.n_keys)
    rules = pipeline.ship_ruleset(packed)
    state = pipeline.init_state(packed.n_keys, cfg)

    n_feed = 4
    feeds = []
    for i in range(n_feed):
        b = np.ascontiguousarray(synth.synth_tuples(packed, batch_size, seed=i).T)
        feeds.append(mesh_lib.shard_batch(mesh, b))
    log(f"batch: {batch_size} lines x {n_feed} resident feed buffers")

    t0 = time.perf_counter()
    for i in range(3):
        state, out = step(state, rules, feeds[i % n_feed])
    jax.block_until_ready(state)
    log(f"warmup+compile: {time.perf_counter() - t0:.1f}s")

    iters = 5 if cpu_scale else 20
    t0 = time.perf_counter()
    for i in range(iters):
        state, out = step(state, rules, feeds[i % n_feed])
    jax.block_until_ready(state)
    dt = time.perf_counter() - t0

    lines_per_sec = iters * batch_size / dt
    per_chip = lines_per_sec / n_dev

    # roofline-style utilization (meaningful on TPU only)
    rows = int(packed.rules.shape[0])
    cells_per_sec_chip = per_chip * rows
    vpu_util = (
        round(cells_per_sec_chip * OPS_PER_CELL / V5E_VPU_U32_OPS, 4)
        if platform == "tpu"
        else None
    )
    hbm_util = (
        round(per_chip * 24.0 / V5E_HBM_BYTES, 6) if platform == "tpu" else None
    )

    e2e = _bench_e2e(packed, cfg_text, cpu_scale, mesh)

    detail = {
        "platform": platform,
        "devices": n_dev,
        "total_lines_per_sec": round(lines_per_sec, 1),
        "batch_size": batch_size,
        "iters": iters,
        "rules": int(packed.n_rules),
        "expanded_rows": rows,
        "elapsed_sec": round(dt, 3),
        # device-step roofline: predicate cells (line x rule-row) per sec
        # per chip, and the share of the v5e VPU u32-op peak they imply
        "rule_cells_per_sec_per_chip": round(cells_per_sec_chip, 1),
        "vpu_util_estimate": vpu_util,
        "hbm_util_estimate": hbm_util,
        # honest end-to-end (text file -> native parse -> device) on this
        # host; the headline value above is the device-resident rate
        "e2e": e2e,
        "vs_north_star_e2e": (
            round(e2e["lines_per_sec"] / n_dev / NORTH_STAR_PER_CHIP, 4)
            if e2e and "lines_per_sec" in e2e
            else None
        ),
    }
    return {
        "metric": "asa_syslog_lines_per_sec_per_chip",
        "value": round(per_chip, 1),
        "unit": "lines/sec/chip",
        "vs_baseline": round(per_chip / NORTH_STAR_PER_CHIP, 4),
        "detail": detail,
    }


def _bench_e2e(packed, cfg_text: str, cpu_scale: bool, mesh) -> dict | None:
    """Full-path rate: syslog text file -> parse -> pack -> device steps."""
    import tempfile

    from ruleset_analysis_tpu.config import AnalysisConfig, SketchConfig
    from ruleset_analysis_tpu.hostside import synth
    from ruleset_analysis_tpu.runtime import stream

    n_lines = (1 << 19) if cpu_scale else (1 << 22)
    try:
        with tempfile.TemporaryDirectory() as td:
            path = os.path.join(td, "bench.log")
            t0 = time.perf_counter()
            synth.synth_syslog_file(packed, path, n_lines, seed=7)
            log(f"e2e corpus: {n_lines} lines in {time.perf_counter()-t0:.1f}s")
            cfg = AnalysisConfig(
                batch_size=1 << 20,
                sketch=SketchConfig(cms_width=1 << 14, cms_depth=4, hll_p=8),
            )
            t0 = time.perf_counter()
            report = stream.run_stream_file(packed, path, cfg, mesh=mesh)
            dt = time.perf_counter() - t0
            return {
                "lines": n_lines,
                "elapsed_sec": round(dt, 3),
                "lines_per_sec": round(n_lines / dt, 1),
                "parser": "native" if _native_available() else "python",
            }
    except Exception as e:  # e2e is auxiliary — never sink the headline
        log(f"e2e bench failed: {e!r}")
        return {"error": repr(e)[:500]}


def _native_available() -> bool:
    try:
        from ruleset_analysis_tpu.hostside import fastparse

        return fastparse.available()
    except Exception:
        return False


# ---------------------------------------------------------------------------
# Parent: probe, dispatch, fallback — never imports jax.
# ---------------------------------------------------------------------------


def probe_backend(timeout: float = 60.0, attempts: int = 3) -> str | None:
    """Return None if the default backend is healthy, else the failure."""
    code = "import jax; d = jax.devices(); print(d[0].platform, len(d))"
    last = "unknown"
    for i in range(attempts):
        t0 = time.perf_counter()
        try:
            r = subprocess.run(
                [sys.executable, "-c", code],
                capture_output=True,
                text=True,
                timeout=timeout,
                cwd=_REPO,
            )
            if r.returncode == 0 and r.stdout.strip():
                log(f"backend probe ok: {r.stdout.strip()} "
                    f"({time.perf_counter() - t0:.1f}s)")
                return None
            last = f"rc={r.returncode} stderr={r.stderr[-500:]}"
        except subprocess.TimeoutExpired:
            last = f"probe timed out after {timeout}s (attempt {i + 1})"
        log(f"backend probe failed: {last}")
        if i + 1 < attempts:
            time.sleep(5 * (i + 1))
    return last


def _scrubbed_cpu_env(n_devices: int = 8) -> dict:
    sys.path.insert(0, _REPO)
    from __graft_entry__ import scrubbed_cpu_env

    return scrubbed_cpu_env(n_devices)


def _run_child(env: dict | None, cpu_scale: bool, timeout: float) -> dict | None:
    cmd = [sys.executable, os.path.abspath(__file__), "--run"]
    if cpu_scale:
        cmd.append("--cpu-scale")
    try:
        proc = subprocess.run(
            cmd,
            env=env if env is not None else dict(os.environ),
            cwd=_REPO,
            stdout=subprocess.PIPE,
            stderr=None,  # stream child logs straight to our stderr
            text=True,
            timeout=timeout,
        )
    except subprocess.TimeoutExpired:
        log(f"bench child timed out after {timeout}s")
        return None
    if proc.returncode != 0:
        log(f"bench child failed rc={proc.returncode}")
        return None
    for line in reversed(proc.stdout.strip().splitlines()):
        try:
            return json.loads(line)
        except json.JSONDecodeError:
            continue
    log("bench child produced no JSON line")
    return None


def main(argv: list[str]) -> int:
    if "--run" in argv:
        # child mode: assume the backend this env selects is healthy; let
        # failures propagate as a nonzero exit so the parent can fall back
        # (the always-one-JSON-line contract is the parent's, not ours)
        emit(run_bench(cpu_scale="--cpu-scale" in argv))
        return 0

    failure = probe_backend()
    if failure is None:
        result = _run_child(None, cpu_scale=False, timeout=1800.0)
        if result is not None:
            emit(result)
            return 0
        failure = "default-backend bench child failed or timed out"

    log("falling back to scrubbed 8-device fake-CPU mesh")
    result = _run_child(_scrubbed_cpu_env(8), cpu_scale=True, timeout=900.0)
    if result is not None:
        result["backend"] = "cpu-fallback"
        result.setdefault("detail", {})["tpu_unavailable"] = failure[:500]
        result["vs_baseline"] = 0.0  # a CPU number is not the per-chip claim
        emit(result)
        return 0

    emit(
        {
            "metric": "asa_syslog_lines_per_sec_per_chip",
            "value": 0.0,
            "unit": "lines/sec/chip",
            "vs_baseline": 0.0,
            "error": f"all backends failed; last: {failure[:500]}",
        }
    )
    return 0


if __name__ == "__main__":
    if "--run" in sys.argv[1:]:
        # child: no catch-all — a crash must surface as rc != 0 so the
        # parent distinguishes it from success and tries the fallback
        raise SystemExit(main(sys.argv[1:]))
    try:
        raise SystemExit(main(sys.argv[1:]))
    except SystemExit:
        raise
    except BaseException as e:  # noqa: BLE001 — the JSON line must always appear
        emit(
            {
                "metric": "asa_syslog_lines_per_sec_per_chip",
                "value": 0.0,
                "unit": "lines/sec/chip",
                "vs_baseline": 0.0,
                "error": f"bench orchestrator crashed: {e!r}"[:600],
            }
        )
        raise SystemExit(0)
