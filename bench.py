"""Headline benchmark: ASA syslog lines/sec/chip through the device pipeline.

Prints exactly ONE JSON line on stdout — always, even when the TPU tunnel
is down (round-1 postmortem: the axon plugin can hang indefinitely at
backend init, and a traceback on stdout scores as `parsed: null`).

Structure: this file is an orchestrator that never imports jax itself.
  1. Probe the default backend in a subprocess (60s timeout, 3 attempts
     with backoff).
  2. On success, run the measurement child (`--run`) in the inherited env
     with a generous timeout.
  3. On any failure, rerun the child on a scrubbed 8-device fake-CPU env
     (same code path, smaller geometry) and mark the JSON `backend:
     "cpu-fallback"` with the TPU failure reason.
  4. If even that fails, emit an `{"error": ...}` JSON object.

The headline `value` is the device-pipeline steady-state rate per chip
(batches resident in HBM, state donated) — the per-chip capability number.
`detail` also carries the measured end-to-end rate through the full file
path (text -> native parse -> device_put -> step), plus a roofline-style
utilization estimate, so "is it actually fast" is answerable from the JSON
(VERDICT round 1, weak #3 / next #6).
"""

from __future__ import annotations

import dataclasses
import json
import os
import subprocess
import sys
import time

_REPO = os.path.dirname(os.path.abspath(__file__))

NORTH_STAR_TOTAL = 1e9 / 60.0  # lines/sec, v5e-8, end-to-end (BASELINE.md)
NORTH_STAR_PER_CHIP = NORTH_STAR_TOTAL / 8.0

# v5e roofline constants (per chip), from public TPU v5e specs / the
# scaling-book numbers: VPU is an (8, 128) vector unit with 4 independent
# ALUs at ~0.94 GHz -> ~3.85e12 u32 ops/s; HBM bandwidth 819 GB/s.
V5E_VPU_U32_OPS = 8 * 128 * 4 * 0.94e9
V5E_HBM_BYTES = 819e9
# u32 VPU ops per (line, rule-row) predicate cell: 11 compares + 10 ands
# + ~2 for the masked min-index reduction (ops/match.py _block_min_row).
OPS_PER_CELL = 23.0


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def emit(obj) -> None:
    print(json.dumps(obj), flush=True)


# ---------------------------------------------------------------------------
# Child: the actual measurement (runs under a known-healthy backend).
# ---------------------------------------------------------------------------


class BenchInvalid(RuntimeError):
    """A self-validation check failed; the measurement cannot be trusted."""


def run_bench(cpu_scale: bool) -> dict:
    import jax
    import numpy as np

    from ruleset_analysis_tpu.config import AnalysisConfig, SketchConfig
    from ruleset_analysis_tpu.hostside import aclparse, pack, synth
    from ruleset_analysis_tpu.models import pipeline
    from ruleset_analysis_tpu.parallel import mesh as mesh_lib
    from ruleset_analysis_tpu.parallel.step import make_parallel_step
    from ruleset_analysis_tpu.runtime.compcache import enable_persistent_cache
    from ruleset_analysis_tpu.runtime.timing import timed_validated_steps

    cache_dir = enable_persistent_cache()
    log(f"compilation cache: {cache_dir}")

    devices = jax.devices()
    n_dev = len(devices)
    platform = devices[0].platform
    log(f"devices: {devices}")

    # BASELINE.json config #1 geometry: one realistic ruleset
    cfg_text = synth.synth_config(n_acls=4, rules_per_acl=64, seed=0)
    rs = aclparse.parse_asa_config(cfg_text, "fw1")
    packed = pack.pack_rulesets([rs])
    log(f"ruleset: {packed.n_rules} rules, {packed.rules.shape[0]} expanded rows")

    per_chip_batch = 1 << 16 if cpu_scale else 1 << 20
    batch_size = per_chip_batch * n_dev
    cfg = AnalysisConfig(
        batch_size=batch_size,
        sketch=SketchConfig(cms_width=1 << 14, cms_depth=4, hll_p=8),
    )

    mesh = mesh_lib.make_mesh(devices)
    step = make_parallel_step(mesh, cfg, packed.n_keys)
    rules = pipeline.ship_ruleset(packed)
    state = pipeline.init_state(packed.n_keys, cfg)

    n_feed = 4
    feeds = []
    valid_per_feed = []
    for i in range(n_feed):
        b = np.ascontiguousarray(synth.synth_tuples(packed, batch_size, seed=i).T)
        valid_per_feed.append(int(b[pack.T_VALID].sum()))
        # production wire layout (stream.py ships the same): the step's
        # measured cost includes the on-device bit-unpack
        feeds.append(mesh_lib.shard_batch(mesh, pack.compact_batch(b)))
    log(f"batch: {batch_size} lines x {n_feed} resident feed buffers")

    t0 = time.perf_counter()
    for i in range(2):
        state, _out = step(state, rules, feeds[i % n_feed])
    pipeline.sync_state(state)
    log(f"warmup+compile: {time.perf_counter() - t0:.1f}s")

    # --- self-validating measurement: two runs at 1x and 3x iterations.
    # The count assertion proves the steps executed inside each timed
    # window; the 1x-vs-3x comparison catches a timed window dominated by
    # fixed overhead rather than per-step execution.
    iters = 5 if cpu_scale else 10
    state, dt1, delta1, expect1 = timed_validated_steps(
        step, state, rules, feeds, valid_per_feed, iters
    )
    if delta1 != expect1:
        raise BenchInvalid(
            f"timed window did not execute: counts moved {delta1}, "
            f"expected {expect1} ({iters} steps x {batch_size} lines)"
        )
    state, dt3, delta3, expect3 = timed_validated_steps(
        step, state, rules, feeds, valid_per_feed, 3 * iters
    )
    if delta3 != expect3:
        raise BenchInvalid(
            f"3x timed window did not execute: counts moved {delta3}, expected {expect3}"
        )
    linearity = (dt3 / 3.0) / dt1  # ~1.0 when per-step time dominates
    if not dt3 > dt1:
        raise BenchInvalid(
            f"3x the steps took no longer ({dt3:.4f}s vs {dt1:.4f}s): "
            "the timed window is not observing execution"
        )
    log(f"timed: {iters} iters {dt1:.3f}s, {3*iters} iters {dt3:.3f}s "
        f"(linearity {linearity:.2f})")

    # headline from the longer run (overheads amortized 3x further)
    lines_per_sec = expect3 / dt3
    per_chip = lines_per_sec / n_dev
    step_ms = dt3 / (3 * iters) * 1e3

    # roofline-style utilization (meaningful on TPU only)
    rows = int(packed.rules.shape[0])
    cells_per_sec_chip = per_chip * rows
    vpu_util = (
        round(cells_per_sec_chip * OPS_PER_CELL / V5E_VPU_U32_OPS, 4)
        if platform == "tpu"
        else None
    )
    hbm_util = (
        # 16 B/line: the wire-format batch read; rules/registers are
        # VMEM-resident across the batch and contribute ~nothing per line
        round(per_chip * 16.0 / V5E_HBM_BYTES, 6) if platform == "tpu" else None
    )
    if vpu_util is not None and vpu_util > 1.0:
        raise BenchInvalid(
            f"vpu_util_estimate {vpu_util} > 1.0: measured rate exceeds the "
            f"v5e VPU roofline ({V5E_VPU_U32_OPS:.3g} u32 ops/s); the timed "
            "window cannot be observing real execution"
        )

    # --- step-variant A/Bs: every scatter-bound flip lever from the
    # committed trace attribution (DESIGN.md §8), priced in THE SAME
    # window as the headline so one scarce tunnel grant decides them all
    # (VERDICT r4 #2).  Auxiliary: any failure logs and never sinks the
    # headline.  The pallas/counts/talker variants run on real TPU only —
    # the r4 CPU A/B proved CPU numbers mislead these decisions (sampled
    # selection measured 0.81x on CPU, projected 1.26x on TPU).
    def time_variant(name, cfg_v, rules_v=None):
        step_v = make_parallel_step(mesh, cfg_v, packed.n_keys)
        state_v = pipeline.init_state(packed.n_keys, cfg_v)
        r_v = rules if rules_v is None else rules_v
        state_v, _ = step_v(state_v, r_v, feeds[0])  # warmup/compile
        pipeline.sync_state(state_v)
        state_v, dt_v, delta_v, expect_v = timed_validated_steps(
            step_v, state_v, r_v, feeds, valid_per_feed, iters
        )
        if delta_v != expect_v:
            raise BenchInvalid(f"{name} window did not execute")
        out = {
            "step_ms": round(dt_v / iters * 1e3, 3),
            "speedup_vs_default": round((dt1 / iters) / (dt_v / iters), 3),
        }
        log(f"{name}: {out['step_ms']} ms/step ({out['speedup_vs_default']}x)")
        return out

    variants = {}
    try:
        variants["topk_sampled"] = {
            "topk_sample_shift": 3,
            **time_variant(
                "topk sample shift=3",
                cfg.replace(
                    sketch=dataclasses.replace(cfg.sketch, topk_sample_shift=3)
                ),
            ),
        }
    except Exception as e:
        log(f"sampled-selection bench failed: {e!r}")
    if platform == "tpu":
        try:
            variants["pallas_fused"] = time_variant(
                "pallas_fused step",
                cfg.replace(match_impl="pallas_fused"),
                pipeline.ship_ruleset(packed, match_impl="pallas_fused"),
            )
        except Exception as e:
            log(f"pallas_fused bench failed: {e!r}")
        try:
            variants["counts_matmul"] = time_variant(
                "counts_impl=matmul step", cfg.replace(counts_impl="matmul")
            )
        except Exception as e:
            log(f"counts_matmul bench failed: {e!r}")
        try:
            variants["talk_cms_depth1"] = time_variant(
                "talk_cms_depth=1 step",
                cfg.replace(
                    sketch=dataclasses.replace(cfg.sketch, talk_cms_depth=1)
                ),
            )
        except Exception as e:
            log(f"talk_cms_depth1 bench failed: {e!r}")

    e2e = _bench_e2e(packed, cpu_scale, mesh, per_chip * n_dev)

    # Profile capture runs LAST: on the remote-tunnel plugin, everything
    # stepped after a jax.profiler.trace window runs ~13x slower (r5
    # window measured every post-profile variant at a uniform ~830 ms vs
    # the 62 ms default), so tracing must never precede a timed section.
    profile = _capture_profile(step, state, rules, feeds)

    detail = {
        "platform": platform,
        "devices": n_dev,
        "total_lines_per_sec": round(lines_per_sec, 1),
        "batch_size": batch_size,
        "iters": 3 * iters,
        "rules": int(packed.n_rules),
        "expanded_rows": rows,
        "elapsed_sec": round(dt3, 3),
        "step_ms": round(step_ms, 3),
        # self-validation evidence: the timed window is closed by a host
        # fetch whose count delta must equal steps x valid lines, and the
        # per-step time must scale with iteration count
        "checks": {
            "counts_delta_ok": True,
            "counts_delta": int(delta3),
            "linearity_1x_vs_3x": round(linearity, 3),
            "sync": "device_get(counts)",
        },
        # all step-variant A/Bs from this window, incl. the sampled
        # candidate-selection A/B (TPU adds pallas_fused / counts_matmul /
        # talk_cms_depth1 — the flip levers of VERDICT r4)
        "step_variants": variants,
        # device-step roofline: predicate cells (line x rule-row) per sec
        # per chip, and the share of the v5e VPU u32-op peak they imply
        "rule_cells_per_sec_per_chip": round(cells_per_sec_chip, 1),
        "vpu_util_estimate": vpu_util,
        "hbm_util_estimate": hbm_util,
        "profile": profile,
        # honest end-to-end decomposition (text -> parse -> transfer ->
        # device); the headline value above is the device-resident rate
        "e2e": e2e,
        "vs_north_star_e2e": (
            round(e2e["lines_per_sec"] / n_dev / NORTH_STAR_PER_CHIP, 4)
            if e2e and "lines_per_sec" in e2e
            else None
        ),
        # wire-tier e2e vs the north star, on the PROJECTED real host
        # (tunnel H2D removed) — honest twin of the tunnel-bound number
        "vs_north_star_e2e_wire_projected": (
            round(
                e2e["wire_ingest"]["projection_real_host"][
                    "projected_lines_per_sec"
                ]
                / n_dev
                / NORTH_STAR_PER_CHIP,
                4,
            )
            if e2e and "wire_ingest" in e2e
            else None
        ),
    }
    return {
        "metric": "asa_syslog_lines_per_sec_per_chip",
        "value": round(per_chip, 1),
        "unit": "lines/sec/chip",
        "vs_baseline": round(per_chip / NORTH_STAR_PER_CHIP, 4),
        "detail": detail,
    }


def _capture_profile(step, state, rules, feeds) -> dict | None:
    """Trace a few steps with jax.profiler into profiles/ (best effort).

    The trace answers "is the step match-bound or scatter-bound" on real
    hardware; some PJRT plugins can't profile, so failure only reports
    itself — it never sinks the bench.
    """
    import glob

    import jax

    out_dir = os.path.join(_REPO, "profiles", "bench")
    try:
        from ruleset_analysis_tpu.models import pipeline

        os.makedirs(out_dir, exist_ok=True)
        with jax.profiler.trace(out_dir):
            for i in range(3):
                state, _ = step(state, rules, feeds[i % len(feeds)])
            pipeline.sync_state(state)
        traces = glob.glob(
            os.path.join(out_dir, "**", "*.xplane.pb"), recursive=True
        )
        return {
            "dir": out_dir,
            "captured": bool(traces),
            "trace_files": [os.path.relpath(t, _REPO) for t in traces[:4]],
        }
    except Exception as e:
        log(f"profiler capture failed: {e!r}")
        return {"dir": out_dir, "captured": False, "error": repr(e)[:300]}


def _bench_e2e(packed, cpu_scale: bool, mesh, device_lines_per_sec: float) -> dict | None:
    """Decomposed full-path rate: parse-only, transfer-only, overlapped.

    The overlapped run is the honest end-to-end number; the stage rates
    say WHICH stage bounds it (on the dev tunnel it is host->device
    transfer; on a real v5e host with PCIe it would be the parse).
    """
    import tempfile

    from ruleset_analysis_tpu.config import AnalysisConfig, SketchConfig
    from ruleset_analysis_tpu.hostside import synth
    from ruleset_analysis_tpu.runtime import stream

    # batch matches the headline device measurement (per-chip batch x
    # devices) so the per-stage rates and the overlapped run price the
    # same chunk geometry; enough chunks that the pipelined ingest
    # actually overlaps (a single-chunk corpus cannot pipeline).
    n_lines = (1 << 21) if cpu_scale else (1 << 22)
    batch_size = (1 << 19) if cpu_scale else (1 << 20)
    try:
        with tempfile.TemporaryDirectory() as td:
            path = os.path.join(td, "bench.log")
            t0 = time.perf_counter()
            synth.synth_syslog_file(packed, path, n_lines, seed=7)
            log(f"e2e corpus: {n_lines} lines in {time.perf_counter()-t0:.1f}s")

            # --- stage 1: host parse only (no device traffic at all)
            parse = _bench_parse_only(packed, path, batch_size)
            parse["feeder_scaling"] = _bench_feeder_scaling(packed, path, batch_size)

            # --- stage 2: host->device transfer only (pre-packed batches)
            h2d = _bench_h2d_only(packed, batch_size, mesh)

            # --- overlapped: the production stream driver
            cfg = AnalysisConfig(
                batch_size=batch_size,
                sketch=SketchConfig(cms_width=1 << 14, cms_depth=4, hll_p=8),
            )
            # warm the jit cache so the timed run measures steady state,
            # not compilation (the step builders are memoized per
            # geometry, so the timed run reuses this run's executables);
            # the driver additionally prices any residual first-dispatch
            # compile separately in totals.compile_sec
            stream.run_stream_file(packed, path, cfg, mesh=mesh, max_chunks=1)
            t0 = time.perf_counter()
            rep = stream.run_stream_file(packed, path, cfg, mesh=mesh)
            dt = time.perf_counter() - t0
            overlapped = n_lines / dt
            # the honest pipelined number: rate with the one-time compile
            # priced out (reported separately), as the driver measures it
            sustained = rep.totals.get("sustained_lines_per_sec") or overlapped
            compile_sec = rep.totals.get("compile_sec", 0.0)
            ingest = rep.totals.get("ingest")

            # --- packed ingest tier (SURVEY §8.2 / VERDICT r3 #2): convert
            # once, then the production wire run — repeated analysis pays
            # no host parse, so its bottleneck should be the device step
            # (or the link, on the starved dev tunnel).
            from ruleset_analysis_tpu.hostside import wire as wire_mod

            wire_path = os.path.join(td, "bench.rawire")
            t0 = time.perf_counter()
            wstats = wire_mod.convert_logs(
                packed, [path], wire_path,
                batch_size=batch_size, block_rows=batch_size,
            )
            t_convert = time.perf_counter() - t0
            stream.run_stream_wire(packed, wire_path, cfg, mesh=mesh, max_chunks=1)
            t0 = time.perf_counter()
            rep_w = stream.run_stream_wire(packed, wire_path, cfg, mesh=mesh)
            dt_wire = time.perf_counter() - t0
            wire_lps = n_lines / dt_wire
            wire_sustained = (
                rep_w.totals.get("sustained_lines_per_sec") or wire_lps
            )

            rates = {
                "parse_lines_per_sec": parse["lines_per_sec"],
                "h2d_lines_per_sec": h2d["lines_per_sec"],
                "device_lines_per_sec": round(device_lines_per_sec, 1),
                "overlapped_lines_per_sec": round(overlapped, 1),
                "overlapped_sustained_lines_per_sec": round(sustained, 1),
                "wire_ingest_lines_per_sec": round(wire_lps, 1),
            }
            # Real-host H2D projection input: a v5e host moves ≥8 GB/s
            # over PCIe; ROW_BYTES is the wire format's single source of
            # truth for bytes/line
            pcie_lps = 8e9 / wire_mod.ROW_BYTES
            stage_min = min(
                parse["lines_per_sec"], h2d["lines_per_sec"], device_lines_per_sec
            )
            bottleneck = min(
                ("parse", parse["lines_per_sec"]),
                ("h2d_transfer", h2d["lines_per_sec"]),
                ("device_step", device_lines_per_sec),
                key=lambda kv: kv[1],
            )[0]
            return {
                "lines": n_lines,
                "elapsed_sec": round(dt, 3),
                "lines_per_sec": round(overlapped, 1),
                # the pipelined-driver sustained rate (one-time compile
                # priced out below, never silently folded in)
                "sustained_lines_per_sec": round(sustained, 1),
                "compile_sec": round(compile_sec, 4),
                "ingest": ingest,
                "parser": "native" if _native_available() else "python",
                "stages": rates,
                "parse_detail": parse,
                "h2d_detail": h2d,
                "wire_ingest": {
                    "lines_per_sec": round(wire_lps, 1),
                    "sustained_lines_per_sec": round(wire_sustained, 1),
                    "elapsed_sec": round(dt_wire, 3),
                    "convert_sec": round(t_convert, 3),
                    "convert_lines_per_sec": round(n_lines / t_convert, 1),
                    "rows": wstats["rows"],
                    "file_mb": round(wstats["bytes"] / 1e6, 1),
                    "speedup_vs_text_e2e": round(wire_sustained / sustained, 2),
                    # without parse, the wire path is bounded by link+device
                    "bottleneck": min(
                        ("h2d_transfer", h2d["lines_per_sec"]),
                        ("device_step", device_lines_per_sec),
                        key=lambda kv: kv[1],
                    )[0],
                    # Real-host projection (VERDICT r4 #3): the dev tunnel's
                    # H2D (~23 MB/s measured r4) caps wire e2e at ~1.46M
                    # lines/s and is a LINK artifact, not a design property.
                    # A real v5e host moves ≥8 GB/s over PCIe; at 16 B/line
                    # the projected wire e2e is min(pcie, device_step) —
                    # both the tunnel-measured and projected numbers are
                    # reported so neither can masquerade as the other.
                    "projection_real_host": {
                        "assumed_pcie_bytes_per_sec": 8e9,
                        "pcie_lines_per_sec": round(pcie_lps, 1),
                        "projected_lines_per_sec": round(
                            min(pcie_lps, device_lines_per_sec), 1
                        ),
                        "projected_bottleneck": (
                            "device_step"
                            if device_lines_per_sec < pcie_lps
                            else "pcie_h2d"
                        ),
                    },
                },
                "bottleneck": bottleneck,
                # overlap quality: 1.0 = perfect pipelining to the slowest
                # stage; the serial bound is what zero overlap would give.
                # Measured on the SUSTAINED rate — the one-time compile is
                # priced separately above, not laundered into overlap.
                "pipeline_efficiency": round(sustained / stage_min, 4),
                "pipeline_efficiency_incl_compile": round(
                    overlapped / stage_min, 4
                ),
                "serial_bound_lines_per_sec": round(
                    1.0
                    / (
                        1.0 / parse["lines_per_sec"]
                        + 1.0 / h2d["lines_per_sec"]
                        + 1.0 / device_lines_per_sec
                    ),
                    1,
                ),
            }
    except Exception as e:  # e2e is auxiliary — never sink the headline
        log(f"e2e bench failed: {e!r}")
        return {"error": repr(e)[:500]}


def _bench_parse_only(packed, path: str, batch_size: int) -> dict:
    """Native (or Python) parse of the corpus with no device in the loop."""
    from ruleset_analysis_tpu.hostside import fastparse

    t0 = time.perf_counter()
    total = 0
    if _native_available():
        packer = fastparse.NativePacker(packed)
        for _batch, n in fastparse.batches_from_files([path], packer, batch_size):
            total += n
        parser = "native"
        threads = fastparse.default_parse_threads()
    else:
        from ruleset_analysis_tpu.hostside.pack import LinePacker

        packer = LinePacker(packed)
        with open(path, "r", encoding="utf-8", errors="replace") as f:
            from ruleset_analysis_tpu.runtime.stream import chunked

            for chunk in chunked(f, batch_size):
                packer.pack_lines(chunk, batch_size=batch_size)
                total += len(chunk)
        parser = "python"
        threads = 1
    dt = time.perf_counter() - t0
    log(f"parse-only: {total} lines in {dt:.2f}s = {total/dt:.0f} lines/s")
    return {
        "lines_per_sec": round(total / dt, 1),
        "parser": parser,
        "threads": threads,
        "elapsed_sec": round(dt, 3),
    }


def _bench_feeder_scaling(packed, path: str, batch_size: int) -> dict | None:
    """Parse rate of the multi-process feeder at 1/2/4 workers.

    The input-split tier (SURVEY.md §2 L2): on a multi-core host the rate
    should scale ~linearly with workers; on a single-core host (this dev
    harness) it honestly reports flat numbers and the core count.
    """
    import os

    try:
        from ruleset_analysis_tpu.hostside.feeder import ParallelFeeder

        cores = len(os.sched_getaffinity(0))
        out = {"host_cores": cores}
        for w in (1, 2, 4):
            feeder = ParallelFeeder(packed, [path], n_workers=w)
            it = feeder.batches(0, batch_size)
            # steady state only: the first batch absorbs process spawn
            # (the 'spawn' context re-imports numpy per worker) and the
            # coordinator's scan start — on small inputs that startup
            # would otherwise read as anti-scaling
            first = next(it, None)
            if first is None:
                continue
            t0 = time.perf_counter()
            total = 0
            for _batch, n in it:
                total += n
            dt = time.perf_counter() - t0
            if total:
                out[f"workers_{w}_lines_per_sec"] = round(total / dt, 1)
                log(f"feeder w={w}: {total/dt:.0f} lines/s steady-state")
        return out
    except Exception as e:  # auxiliary measurement — never sink the bench
        log(f"feeder scaling bench failed: {e!r}")
        return {"error": repr(e)[:300]}


def _bench_h2d_only(packed, batch_size: int, mesh) -> dict:
    """Host->device batch transfer rate, synced by a cross-shard readback.

    Measures BOTH layouts — the 16 B/line wire format the stream ships
    and the 28 B/line wide layout it replaced — so the JSON quantifies
    what the bit-packing buys on this link.
    """
    import jax
    import numpy as np

    from ruleset_analysis_tpu.hostside import pack, synth
    from ruleset_analysis_tpu.parallel import mesh as mesh_lib

    wide = np.ascontiguousarray(synth.synth_tuples(packed, batch_size, seed=3).T)
    wire = pack.compact_batch(wide)
    # full reduction, NOT a slice: the batch shards over the mesh's data
    # axis, and a one-shard readback would only prove device 0's transfer
    # finished — the sum's result depends on every shard's bytes
    allsum = jax.jit(lambda x: x.sum(dtype=jax.numpy.uint32))

    def measure(batch) -> tuple[float, float]:
        d = mesh_lib.shard_batch(mesh, batch)  # warmup (allocator, tunnel)
        np.asarray(allsum(d))
        reps = 3
        t0 = time.perf_counter()
        for _ in range(reps):
            d = mesh_lib.shard_batch(mesh, batch)
            np.asarray(allsum(d))  # 4-byte fetch bounding every transfer
        dt = time.perf_counter() - t0
        return reps * batch_size / dt, reps * batch.nbytes / dt / 1e6

    rate, mbps = measure(wire)
    wide_rate, wide_mbps = measure(wide)
    log(f"h2d-only: wire {mbps:.1f} MB/s = {rate:.0f} lines/s; "
        f"wide {wide_mbps:.1f} MB/s = {wide_rate:.0f} lines/s "
        f"(wire speedup {rate/max(wide_rate,1):.2f}x)")
    return {
        "lines_per_sec": round(rate, 1),
        "mb_per_sec": round(mbps, 2),
        "batch_mb": round(wire.nbytes / 1e6, 1),
        "bytes_per_line": round(wire.nbytes / batch_size, 1),
        "wide_lines_per_sec": round(wide_rate, 1),
        "wire_speedup_vs_wide": round(rate / max(wide_rate, 1.0), 3),
    }


def _native_available() -> bool:
    try:
        from ruleset_analysis_tpu.hostside import fastparse

        return fastparse.available()
    except Exception:
        return False


# ---------------------------------------------------------------------------
# Parent: probe, dispatch, fallback — never imports jax.
# ---------------------------------------------------------------------------


def probe_backend(timeout: float = 60.0, attempts: int = 3) -> str | None:
    """Return None if the default backend is healthy, else the failure."""
    code = "import jax; d = jax.devices(); print(d[0].platform, len(d))"
    last = "unknown"
    for i in range(attempts):
        t0 = time.perf_counter()
        try:
            r = subprocess.run(
                [sys.executable, "-c", code],
                capture_output=True,
                text=True,
                timeout=timeout,
                cwd=_REPO,
            )
            if r.returncode == 0 and r.stdout.strip():
                log(f"backend probe ok: {r.stdout.strip()} "
                    f"({time.perf_counter() - t0:.1f}s)")
                return None
            last = f"rc={r.returncode} stderr={r.stderr[-500:]}"
        except subprocess.TimeoutExpired:
            last = f"probe timed out after {timeout}s (attempt {i + 1})"
        log(f"backend probe failed: {last}")
        if i + 1 < attempts:
            time.sleep(5 * (i + 1))
    return last


def _scrubbed_cpu_env(n_devices: int = 8) -> dict:
    sys.path.insert(0, _REPO)
    from __graft_entry__ import scrubbed_cpu_env

    return scrubbed_cpu_env(n_devices)


def _run_child(env: dict | None, cpu_scale: bool, timeout: float) -> dict | None:
    cmd = [sys.executable, os.path.abspath(__file__), "--run"]
    if cpu_scale:
        cmd.append("--cpu-scale")
    try:
        proc = subprocess.run(
            cmd,
            env=env if env is not None else dict(os.environ),
            cwd=_REPO,
            stdout=subprocess.PIPE,
            stderr=None,  # stream child logs straight to our stderr
            text=True,
            timeout=timeout,
        )
    except subprocess.TimeoutExpired:
        log(f"bench child timed out after {timeout}s")
        return None
    if proc.returncode != 0:
        log(f"bench child failed rc={proc.returncode}")
        return None
    for line in reversed(proc.stdout.strip().splitlines()):
        try:
            return json.loads(line)
        except json.JSONDecodeError:
            continue
    log("bench child produced no JSON line")
    return None


def _last_tpu_evidence() -> dict | None:
    """Newest banked real-TPU headline, for embedding in fallback output.

    The driver's artifact has been evidence-free whenever the axon tunnel
    was down at bench time (VERDICT r4 missing #4) even though a committed
    TPU capture existed in the repo.  Embed that capture — value, capture
    commit, validation checks — so BENCH_r{N}.json always carries the TPU
    evidence trail regardless of tunnel state.
    """
    import glob as glob_mod

    here = os.path.dirname(os.path.abspath(__file__))
    candidates = sorted(
        glob_mod.glob(os.path.join(here, "BENCH_r*_local.json")), reverse=True
    )
    for path in candidates:
        name = os.path.basename(path)
        try:
            with open(path, "r", encoding="utf-8") as f:
                d = json.load(f)
        except (OSError, ValueError):
            continue
        if d.get("detail", {}).get("platform") != "tpu" or not d.get("value"):
            continue
        commit = None
        try:
            out = subprocess.run(
                ["git", "log", "-1", "--format=%H %cI", "--", name],
                cwd=here, capture_output=True, text=True, timeout=10,
            )
            if out.returncode == 0 and out.stdout.strip():
                sha, _, when = out.stdout.strip().partition(" ")
                commit = {"sha": sha, "committed_at": when}
        except (OSError, subprocess.SubprocessError):
            pass
        return {
            "source": name,
            "metric": d.get("metric"),
            "value": d.get("value"),
            "unit": d.get("unit"),
            "vs_baseline": d.get("vs_baseline"),
            "checks": d.get("detail", {}).get("checks"),
            "commit": commit,
        }
    return None


def _with_last_tpu(obj: dict) -> dict:
    """Attach the newest banked TPU headline to a non-TPU bench result."""
    last = _last_tpu_evidence()
    if last is not None:
        obj["last_tpu"] = last
    return obj


def main(argv: list[str]) -> int:
    if "--run" in argv:
        # child mode: assume the backend this env selects is healthy; let
        # failures propagate as a nonzero exit so the parent can fall back
        # (the always-one-JSON-line contract is the parent's, not ours).
        # EXCEPT a failed self-validation check: that is a measurement
        # integrity failure, not a backend failure — emit it as the error
        # JSON (value 0) so it can never masquerade as a CPU fallback.
        try:
            emit(run_bench(cpu_scale="--cpu-scale" in argv))
        except BenchInvalid as e:
            emit(
                {
                    "metric": "asa_syslog_lines_per_sec_per_chip",
                    "value": 0.0,
                    "unit": "lines/sec/chip",
                    "vs_baseline": 0.0,
                    "error": f"self-validation failed: {e}"[:600],
                }
            )
        return 0

    failure = probe_backend()
    if failure is None:
        result = _run_child(None, cpu_scale=False, timeout=1800.0)
        if result is not None:
            emit(result)
            return 0
        failure = "default-backend bench child failed or timed out"

    log("falling back to scrubbed 8-device fake-CPU mesh")
    result = _run_child(_scrubbed_cpu_env(8), cpu_scale=True, timeout=900.0)
    if result is not None:
        result["backend"] = "cpu-fallback"
        result.setdefault("detail", {})["tpu_unavailable"] = failure[:500]
        result["vs_baseline"] = 0.0  # a CPU number is not the per-chip claim
        emit(_with_last_tpu(result))
        return 0

    emit(
        _with_last_tpu(
            {
                "metric": "asa_syslog_lines_per_sec_per_chip",
                "value": 0.0,
                "unit": "lines/sec/chip",
                "vs_baseline": 0.0,
                "error": f"all backends failed; last: {failure[:500]}",
            }
        )
    )
    return 0


if __name__ == "__main__":
    if "--run" in sys.argv[1:]:
        # child: no catch-all — a crash must surface as rc != 0 so the
        # parent distinguishes it from success and tries the fallback
        raise SystemExit(main(sys.argv[1:]))
    try:
        raise SystemExit(main(sys.argv[1:]))
    except SystemExit:
        raise
    except BaseException as e:  # noqa: BLE001 — the JSON line must always appear
        emit(
            {
                "metric": "asa_syslog_lines_per_sec_per_chip",
                "value": 0.0,
                "unit": "lines/sec/chip",
                "vs_baseline": 0.0,
                "error": f"bench orchestrator crashed: {e!r}"[:600],
            }
        )
        raise SystemExit(0)
