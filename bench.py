"""Headline benchmark: ASA syslog lines/sec/chip through the device pipeline.

Measures the steady-state fused analysis step (first-match + exact counts +
CMS + HLL + top-K candidates) on pre-packed batches resident in HBM, with
state donation — the device half of the BASELINE.json headline metric
("ASA syslog lines/sec/chip").  The north star is 1e9 lines/min on a
v5e-8, i.e. ~2.083e6 lines/sec/chip: vs_baseline is measured against that
per-chip target (the reference itself publishes no numbers — BASELINE.md).

Prints exactly ONE JSON line on stdout.
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def main() -> int:
    import jax
    import jax.numpy as jnp

    from ruleset_analysis_tpu.config import AnalysisConfig, SketchConfig
    from ruleset_analysis_tpu.hostside import aclparse, pack, synth
    from ruleset_analysis_tpu.models import pipeline
    from ruleset_analysis_tpu.parallel import mesh as mesh_lib
    from ruleset_analysis_tpu.parallel.step import make_parallel_step

    devices = jax.devices()
    n_dev = len(devices)
    log(f"devices: {devices}")

    # BASELINE.json config #1 geometry: one realistic ruleset
    cfg_text = synth.synth_config(n_acls=4, rules_per_acl=64, seed=0)
    rs = aclparse.parse_asa_config(cfg_text, "fw1")
    packed = pack.pack_rulesets([rs])
    log(f"ruleset: {packed.n_rules} rules, {packed.rules.shape[0]} expanded rows")

    per_chip_batch = 1 << 20
    batch_size = per_chip_batch * n_dev
    cfg = AnalysisConfig(
        batch_size=batch_size,
        sketch=SketchConfig(cms_width=1 << 14, cms_depth=4, hll_p=8),
    )

    mesh = mesh_lib.make_mesh(devices)
    step = make_parallel_step(mesh, cfg, packed.n_keys)
    rules = pipeline.ship_ruleset(packed)
    state = pipeline.init_state(packed.n_keys, cfg)

    n_feed = 4
    feeds = []
    for i in range(n_feed):
        b = np.ascontiguousarray(synth.synth_tuples(packed, batch_size, seed=i).T)
        feeds.append(mesh_lib.shard_batch(mesh, b))
    log(f"batch: {batch_size} lines x {n_feed} resident feed buffers")

    # warmup (compile + first runs)
    t0 = time.perf_counter()
    for i in range(3):
        state, out = step(state, rules, feeds[i % n_feed])
    jax.block_until_ready(state)
    log(f"warmup+compile: {time.perf_counter() - t0:.1f}s")

    iters = 20
    t0 = time.perf_counter()
    for i in range(iters):
        state, out = step(state, rules, feeds[i % n_feed])
    jax.block_until_ready(state)
    dt = time.perf_counter() - t0

    lines_per_sec = iters * batch_size / dt
    per_chip = lines_per_sec / n_dev
    north_star_per_chip = 1e9 / 60.0 / 8.0
    result = {
        "metric": "asa_syslog_lines_per_sec_per_chip",
        "value": round(per_chip, 1),
        "unit": "lines/sec/chip",
        "vs_baseline": round(per_chip / north_star_per_chip, 4),
        "detail": {
            "devices": n_dev,
            "total_lines_per_sec": round(lines_per_sec, 1),
            "batch_size": batch_size,
            "iters": iters,
            "rules": int(packed.n_rules),
            "expanded_rows": int(packed.rules.shape[0]),
            "elapsed_sec": round(dt, 3),
        },
    }
    print(json.dumps(result), flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
