"""Syslog parser tests across the handled ASA message classes."""

from ruleset_analysis_tpu.hostside import syslog as S
from ruleset_analysis_tpu.hostside.aclparse import ip_to_u32


def test_106100_tcp():
    line = (
        "Jul 29 07:48:01 fw1 : %ASA-6-106100: access-list OUT permitted tcp "
        "inside/10.1.2.3(41000) -> outside/198.51.100.7(443) hit-cnt 1 first hit [0xabc, 0x0]"
    )
    p = S.parse_line(line)
    assert p is not None
    assert p.firewall == "fw1"
    assert p.acl == "OUT"
    assert p.proto == 6
    assert p.src == ip_to_u32("10.1.2.3")
    assert p.sport == 41000
    assert p.dst == ip_to_u32("198.51.100.7")
    assert p.dport == 443
    assert p.permitted is True


def test_106100_denied_icmp_type_to_dport():
    line = (
        "Jul 29 07:48:01 fw9 : %ASA-6-106100: access-list EDGE denied icmp "
        "outside/192.0.2.1(8) -> inside/10.0.0.1(0) hit-cnt 3 300-second interval [0x0, 0x0]"
    )
    p = S.parse_line(line)
    assert p.proto == 1
    assert p.sport == 0
    assert p.dport == 8  # icmp type echoed into the dport column
    assert p.permitted is False


def test_106023_udp():
    line = (
        '<164>Jul 29 07:48:02 fw2 %ASA-4-106023: Deny udp src dmz:10.5.5.5/137 '
        'dst inside:10.0.0.9/137 by access-group "DMZ-IN" [0x0, 0x0]'
    )
    p = S.parse_line(line)
    assert p.firewall == "fw2"
    assert p.acl == "DMZ-IN"
    assert p.proto == 17
    assert (p.sport, p.dport) == (137, 137)
    assert p.permitted is False


def test_106023_icmp_type_code():
    line = (
        'Jul 29 07:48:02 fw2 : %ASA-4-106023: Deny icmp src outside:192.0.2.9 '
        'dst inside:10.0.0.1 (type 8, code 0) by access-group "EDGE" [0x0, 0x0]'
    )
    p = S.parse_line(line)
    assert p.proto == 1
    assert p.dport == 8
    assert p.sport == 0


def test_302013_inbound():
    line = (
        "Jul 29 07:48:03 fw1 : %ASA-6-302013: Built inbound TCP connection 123456 for "
        "outside:203.0.113.5/51000 (203.0.113.5/51000) to inside:10.0.0.8/22 (10.0.0.8/22)"
    )
    p = S.parse_line(line)
    assert p.acl is None
    assert p.ingress_if == "outside"
    assert p.src == ip_to_u32("203.0.113.5")
    assert p.sport == 51000
    assert p.dst == ip_to_u32("10.0.0.8")
    assert p.dport == 22


def test_302013_outbound_swaps_direction():
    line = (
        "Jul 29 07:48:03 fw1 : %ASA-6-302013: Built outbound TCP connection 7 for "
        "outside:198.51.100.1/443 (198.51.100.1/443) to inside:10.0.0.4/55123 (10.0.0.4/55123)"
    )
    p = S.parse_line(line)
    assert p.ingress_if == "inside"
    assert p.src == ip_to_u32("10.0.0.4")
    assert p.sport == 55123
    assert p.dst == ip_to_u32("198.51.100.1")
    assert p.dport == 443


def test_302015_udp():
    line = (
        "Jul 29 07:48:03 fw3 : %ASA-6-302015: Built inbound UDP connection 9 for "
        "outside:192.0.2.2/53555 (192.0.2.2/53555) to dmz:10.2.0.2/53 (10.2.0.2/53)"
    )
    p = S.parse_line(line)
    assert p.proto == 17
    assert p.dport == 53


def test_non_asa_lines_skipped():
    assert S.parse_line("Jul 29 07:48:01 host sshd[123]: Accepted publickey") is None
    assert S.parse_line("") is None
    assert S.parse_line("Jul 29 fw1 %ASA-6-305011: Built dynamic TCP translation") is None


def test_malformed_address_line_skipped_not_raised():
    """An ASA-shaped line whose address field is corrupt (regex matches,
    ip_to_u32 would refuse) must return None — not leak AclParseError
    into the chunk loop (r5 fuzz regression)."""
    line = (
        "Jul 29 07:48:01 fw1 : %ASA-6-106100: access-list A permitted tcp "
        "inside/198.51.72.9.0.21.47(1000) -> outside/10.0.0.5(443) "
        "hit-cnt 1 first hit [0x0, 0x0]"
    )
    assert S.parse_line(line) is None
