"""Fused pallas match+count kernel: bit-equality with the XLA path.

The fused kernel (ops/pallas_fused.py) replaces the batch-sized
exact-counts scatter with in-VMEM histograms + a row-sized fold.  These
tests pin its keys AND counts delta bit-identical to
``segment_counts(match_keys(...), valid)`` — including no-match lines
(implicit deny), invalid lines, lane/batch padding, and the full stream
driver.  Interpret mode on CPU; compiled on TPU (bench_suite.py pallas).
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from ruleset_analysis_tpu.hostside import aclparse, pack, synth  # noqa: E402
from ruleset_analysis_tpu.ops import pallas_fused, pallas_match  # noqa: E402
from ruleset_analysis_tpu.ops.counts import segment_counts  # noqa: E402
from ruleset_analysis_tpu.ops.match import match_keys  # noqa: E402


def _case(n_acls=3, rules_per_acl=24, n=2048, seed=0, invalidate_every=0):
    cfg_text = synth.synth_config(n_acls=n_acls, rules_per_acl=rules_per_acl, seed=seed)
    rs = aclparse.parse_asa_config(cfg_text, "fw1")
    packed = pack.pack_rulesets([rs])
    tuples = synth.synth_tuples(packed, n, seed=seed + 1)
    valid = np.ones(n, dtype=np.uint32)
    if invalidate_every:
        valid[::invalidate_every] = 0
    cols = {
        k: jnp.asarray(tuples[:, i])
        for k, i in zip(["acl", "proto", "src", "sport", "dst", "dport"], range(6))
    }
    return packed, cols, jnp.asarray(valid), tuples


def _want(packed, cols, valid):
    rules = jnp.asarray(packed.rules)
    deny = jnp.asarray(packed.deny_key.astype(np.uint32))
    keys = match_keys(cols, rules, deny)
    delta = segment_counts(keys, valid, packed.n_keys)
    return np.asarray(keys), np.asarray(delta)


def _got(packed, cols, valid, block_lines=512):
    rules = jnp.asarray(packed.rules)
    deny = jnp.asarray(packed.deny_key.astype(np.uint32))
    keys, delta = pallas_fused.match_keys_and_counts_pallas(
        cols, valid, rules, pallas_match.prep_rules(rules), deny,
        packed.n_keys, block_lines=block_lines, interpret=True,
    )
    return np.asarray(keys), np.asarray(delta)


@pytest.mark.parametrize("seed", [0, 1, 5])
def test_keys_and_counts_match_xla(seed):
    packed, cols, valid, _ = _case(seed=seed)
    wk, wd = _want(packed, cols, valid)
    gk, gd = _got(packed, cols, valid)
    np.testing.assert_array_equal(gk, wk)
    np.testing.assert_array_equal(gd, wd)
    assert int(gd.sum()) == int(np.asarray(valid).sum())  # every valid line counted once


def test_invalid_lines_excluded_from_counts():
    packed, cols, valid, _ = _case(n=1024, seed=2, invalidate_every=3)
    wk, wd = _want(packed, cols, valid)
    gk, gd = _got(packed, cols, valid)
    np.testing.assert_array_equal(gd, wd)
    assert int(gd.sum()) == int(np.asarray(valid).sum())


def test_unmatched_lines_land_on_deny_keys():
    # all-zero lines match nothing (proto 0 with port ranges etc. may
    # match permit-ip-any rules; use acl ids beyond range to force the
    # clamp + deny path on SOME lines instead): zero tuples on acl 0
    packed, cols, valid, _ = _case(n_acls=2, rules_per_acl=8, n=512, seed=4)
    zero_cols = {k: jnp.zeros(512, dtype=jnp.uint32) for k in cols}
    wk, wd = _want(packed, zero_cols, valid)
    gk, gd = _got(packed, zero_cols, valid)
    np.testing.assert_array_equal(gk, wk)
    np.testing.assert_array_equal(gd, wd)


def test_batch_not_multiple_of_block():
    packed, cols, valid, _ = _case(n=700, seed=6)  # 700 % 512 != 0
    wk, wd = _want(packed, cols, valid)
    gk, gd = _got(packed, cols, valid, block_lines=512)
    np.testing.assert_array_equal(gk, wk)
    np.testing.assert_array_equal(gd, wd)


def test_stream_report_identical_fused_vs_xla():
    """Full driver with match_impl=pallas_fused == XLA path, exactly."""
    from ruleset_analysis_tpu.config import AnalysisConfig, SketchConfig
    from ruleset_analysis_tpu.runtime.stream import run_stream

    packed, _, _, tuples = _case(n=600, seed=9)
    lines = synth.render_syslog(packed, tuples, seed=10)

    def run(impl):
        cfg = AnalysisConfig(
            batch_size=256,
            sketch=SketchConfig(cms_width=1 << 10, cms_depth=2, hll_p=6),
            match_impl=impl,
        )
        return run_stream(packed, iter(lines), cfg)

    a, b = run("xla"), run("pallas_fused")
    assert a.per_rule == b.per_rule
    assert a.unused == b.unused
    assert a.talkers == b.talkers
    assert a.totals["lines_matched"] == b.totals["lines_matched"]


def test_stacked_layout_refuses_fused():
    from ruleset_analysis_tpu.config import AnalysisConfig

    with pytest.raises(ValueError, match="layout='flat' only"):
        AnalysisConfig(match_impl="pallas_fused", layout="stacked")


@pytest.mark.parametrize("impl", ["matmul", "reduce"])
def test_counts_impl_reports_identical_to_scatter(impl):
    """counts_impl formulations are bit-identical through the full driver
    (ops/counts.py SEGMENT_COUNTS_IMPLS; the TPU stage bench prices them)."""
    from ruleset_analysis_tpu.config import AnalysisConfig, SketchConfig
    from ruleset_analysis_tpu.runtime.stream import run_stream

    packed, _, _, tuples = _case(n=600, seed=11)
    lines = synth.render_syslog(packed, tuples, seed=12)

    def run(ci):
        cfg = AnalysisConfig(
            batch_size=256,
            sketch=SketchConfig(cms_width=1 << 10, cms_depth=2, hll_p=6),
            counts_impl=ci,
        )
        return run_stream(packed, iter(lines), cfg)

    a, b = run("scatter"), run(impl)
    assert a.per_rule == b.per_rule
    assert a.unused == b.unused
    assert a.totals["lines_matched"] == b.totals["lines_matched"]


def test_counts_impl_validation():
    from ruleset_analysis_tpu.config import AnalysisConfig

    with pytest.raises(ValueError, match="counts_impl"):
        AnalysisConfig(counts_impl="bogus")


def test_out_of_range_acl_counts_match_xla():
    """A valid line with a corrupt acl gid (>= n_acls) must land on the
    LAST ACL's deny key in BOTH keys and counts — the kernel clamps
    exactly as rows_to_keys does (review r5 finding)."""
    packed, cols, valid, _ = _case(n_acls=2, rules_per_acl=8, n=512, seed=8)
    bad = dict(cols)
    bad["acl"] = jnp.full(512, 999, dtype=jnp.uint32)  # way out of range
    wk, wd = _want(packed, bad, valid)
    gk, gd = _got(packed, bad, valid)
    np.testing.assert_array_equal(gk, wk)
    np.testing.assert_array_equal(gd, wd)
    assert int(gd.sum()) == 512  # every valid line counted exactly once


def test_fused_rejects_nondefault_counts_impl():
    from ruleset_analysis_tpu.config import AnalysisConfig

    with pytest.raises(ValueError, match="computes counts in-kernel"):
        AnalysisConfig(match_impl="pallas_fused", counts_impl="matmul")
