"""Register-memory guard + sketch-only CLI plumbing (VERDICT round 2 #6)."""

import numpy as np
import pytest

pytest.importorskip("jax")

from ruleset_analysis_tpu.config import AnalysisConfig, SketchConfig
from ruleset_analysis_tpu.models import pipeline


def test_guard_fires_at_100k_keys_default_p():
    # 100k keys x 256 registers x 4 B = ~100 MiB of HLL alone
    cfg = AnalysisConfig(register_memory_budget_bytes=64 << 20)
    with pytest.raises(ValueError, match="--hll-p"):
        pipeline.init_state(100_000, cfg)


def test_guard_suggestion_fits():
    cfg = AnalysisConfig(register_memory_budget_bytes=64 << 20)
    try:
        pipeline.init_state(100_000, cfg)
        raise AssertionError("guard did not fire")
    except ValueError as e:
        import re

        p = int(re.search(r"--hll-p (\d+)", str(e)).group(1))
    ok = cfg.replace(sketch=SketchConfig(hll_p=p))
    sizes = pipeline.register_bytes(100_000, ok)
    assert sum(sizes.values()) <= cfg.register_memory_budget_bytes
    state = pipeline.init_state(100_000, ok)
    assert state.hll.shape == (100_000, 1 << p)


def test_guard_applies_to_host_init_too():
    cfg = AnalysisConfig(register_memory_budget_bytes=1 << 20)
    with pytest.raises(ValueError):
        pipeline.init_state_host(100_000, cfg)


def test_default_budget_accepts_normal_geometry():
    state = pipeline.init_state(4096, AnalysisConfig())
    assert state.counts_lo.shape == (4096,)


def test_cli_no_exact_counts(tmp_path, capsys):
    """--no-exact-counts runs sketch-only and still reports per-rule hits."""
    from ruleset_analysis_tpu import cli
    from ruleset_analysis_tpu.hostside import aclparse, pack, synth

    cfg_text = synth.synth_config(n_acls=2, rules_per_acl=6, seed=7)
    cfg_path = tmp_path / "fw1.cfg"
    cfg_path.write_text(cfg_text)
    rs = aclparse.parse_asa_config(cfg_text, "fw1")
    packed = pack.pack_rulesets([rs])
    pack.save_packed(packed, str(tmp_path / "packed"))
    tuples = synth.synth_tuples(packed, 400, seed=7)
    log_path = tmp_path / "fw1.log"
    log_path.write_text("\n".join(synth.render_syslog(packed, tuples, seed=7)) + "\n")

    rc = cli.main([
        "run", "--ruleset", str(tmp_path / "packed"), "--logs", str(log_path),
        "--backend", "tpu", "--no-exact-counts", "--json",
        "--out", str(tmp_path / "rep.json"),
    ])
    assert rc == 0
    import json

    rep = json.loads((tmp_path / "rep.json").read_text())
    assert sum(e["hits"] for e in rep["per_rule"]) > 0
    # CMS error is one-sided: a rule with real hits can never show zero,
    # so the unused list is a subset of the exact run's
    rc = cli.main([
        "run", "--ruleset", str(tmp_path / "packed"), "--logs", str(log_path),
        "--backend", "tpu", "--json", "--out", str(tmp_path / "rep_exact.json"),
    ])
    assert rc == 0
    exact = json.loads((tmp_path / "rep_exact.json").read_text())
    assert set(map(tuple, rep["unused"])) <= set(map(tuple, exact["unused"]))


def test_cli_oracle_rejects_no_exact_counts(tmp_path):
    from ruleset_analysis_tpu import cli
    from ruleset_analysis_tpu.hostside import aclparse, pack, synth

    cfg_text = synth.synth_config(n_acls=1, rules_per_acl=2, seed=1)
    (tmp_path / "fw1.cfg").write_text(cfg_text)
    rs = aclparse.parse_asa_config(cfg_text, "fw1")
    pack.save_packed(pack.pack_rulesets([rs]), str(tmp_path / "packed"))
    (tmp_path / "x.log").write_text("\n")
    rc = cli.main([
        "run", "--ruleset", str(tmp_path / "packed"), "--logs", str(tmp_path / "x.log"),
        "--backend", "oracle", "--acl-configs", str(tmp_path / "fw1.cfg"),
        "--no-exact-counts",
    ])
    assert rc == 2
