"""Packed ingest tier: .rawire on-disk wire format (SURVEY.md §8.2).

The production pre-tokenized input path: `convert` writes evaluation rows
once, `run` feeds the device from the mmap'd file.  The contract under
test: a wire run is bit-identical to the text run that produced the file —
same per-rule counts, same unused set, same raw-line totals."""

import numpy as np
import pytest

pytest.importorskip("jax")

from ruleset_analysis_tpu.config import AnalysisConfig, SketchConfig
from ruleset_analysis_tpu.hostside import aclparse, pack, synth, wire
from ruleset_analysis_tpu.runtime import checkpoint as ckpt
from ruleset_analysis_tpu.runtime.stream import (
    run_stream,
    run_stream_file,
    run_stream_wire,
)


@pytest.fixture(scope="module")
def corpus(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("wire-corpus")
    cfg_text = synth.synth_config(n_acls=3, rules_per_acl=10, seed=77)
    rs = aclparse.parse_asa_config(cfg_text, "fw1")
    packed = pack.pack_rulesets([rs])
    tuples = synth.synth_tuples(packed, 2500, seed=77)
    lines = synth.render_syslog(packed, tuples, seed=77)
    log = tmp / "fw1.log"
    log.write_text("\n".join(lines) + "\n")
    return packed, rs, [str(log)], lines


def make_cfg(**kw):
    kw.setdefault("batch_size", 256)
    kw.setdefault("sketch", SketchConfig(cms_width=1 << 11, cms_depth=4, hll_p=6))
    return AnalysisConfig(backend="tpu", **kw)


def hits_of(rep):
    return {(e["firewall"], e["acl"], e["index"]): e["hits"] for e in rep.per_rule}


@pytest.fixture(scope="module")
def wire_path(corpus, tmp_path_factory):
    packed, _rs, logs, _lines = corpus
    out = tmp_path_factory.mktemp("wire-out") / "fw1.rawire"
    stats = wire.convert_logs(packed, logs, str(out), block_rows=128)
    assert stats["rows"] == stats["evals"] > 0
    assert stats["raw_lines"] == len(corpus[3])
    return str(out)


def test_wire_file_sniff_and_header(corpus, wire_path):
    packed = corpus[0]
    assert wire.is_wire_file(wire_path)
    assert not wire.is_wire_file(corpus[2][0])  # the text log
    r = wire.WireReader([wire_path], packed)
    assert r.n_rows == r.n_evals
    assert r.raw_lines == len(corpus[3])
    assert r.raw_lines == r.n_skipped + (r.n_evals - _dual_evals(corpus))
    r.close()


def _dual_evals(corpus) -> int:
    # corpus has no out-direction bindings -> every eval is its own line
    packed = corpus[0]
    assert not packed.bindings_out
    return 0


def test_wire_run_bit_identical_to_text_run(corpus, wire_path):
    packed, _rs, logs, _lines = corpus
    ref = run_stream_file(packed, logs, make_cfg(), topk=5)
    rep = run_stream_wire(packed, wire_path, make_cfg(), topk=5)
    assert hits_of(rep) == hits_of(ref)
    assert rep.unused == ref.unused
    # raw-line accounting restored from the converter's header
    assert rep.totals["lines_total"] == ref.totals["lines_total"]
    assert rep.totals["lines_matched"] == ref.totals["lines_matched"]
    assert rep.totals["lines_skipped"] == ref.totals["lines_skipped"]
    # per-rule unique-source estimates ride the same registers
    us_ref = {e["index"]: e.get("unique_sources") for e in ref.per_rule}
    us_rep = {e["index"]: e.get("unique_sources") for e in rep.per_rule}
    assert us_ref == us_rep


@pytest.mark.parametrize("batch_size", [96, 128, 333])
def test_wire_rechunk_invariance(corpus, wire_path, batch_size):
    """Any run batch size over any block size yields identical registers."""
    packed = corpus[0]
    ref = run_stream_wire(packed, wire_path, make_cfg(), topk=5)
    rep = run_stream_wire(packed, wire_path, make_cfg(batch_size=batch_size), topk=5)
    assert hits_of(rep) == hits_of(ref)
    assert rep.unused == ref.unused
    assert rep.totals["lines_total"] == ref.totals["lines_total"]


def test_wire_multiple_files_concatenate(corpus, tmp_path):
    packed, _rs, logs, lines = corpus
    mid = len(lines) // 2
    a, b = tmp_path / "a.log", tmp_path / "b.log"
    a.write_text("\n".join(lines[:mid]) + "\n")
    b.write_text("\n".join(lines[mid:]) + "\n")
    wa, wb = str(tmp_path / "a.rawire"), str(tmp_path / "b.rawire")
    wire.convert_logs(packed, [str(a)], wa, block_rows=64)
    wire.convert_logs(packed, [str(b)], wb, block_rows=96)
    one = str(tmp_path / "all.rawire")
    wire.convert_logs(packed, logs, one, block_rows=128)
    rep_two = run_stream_wire(packed, [wa, wb], make_cfg(), topk=5)
    rep_one = run_stream_wire(packed, one, make_cfg(), topk=5)
    assert hits_of(rep_two) == hits_of(rep_one)
    assert rep_two.totals["lines_total"] == rep_one.totals["lines_total"]


def test_wire_fingerprint_mismatch_refused(corpus, wire_path):
    other_cfg = synth.synth_config(n_acls=2, rules_per_acl=6, seed=5)
    other = pack.pack_rulesets([aclparse.parse_asa_config(other_cfg, "fw1")])
    with pytest.raises(wire.WireFormatError, match="different ruleset"):
        wire.WireReader([wire_path], other)


def test_wire_truncated_refused(wire_path, tmp_path, corpus):
    data = open(wire_path, "rb").read()
    bad = tmp_path / "trunc.rawire"
    bad.write_bytes(data[: len(data) - 17])
    with pytest.raises(wire.WireFormatError, match="truncated"):
        wire.WireReader([str(bad)], corpus[0])


def test_wire_bad_magic_refused(tmp_path, corpus):
    p = tmp_path / "not.rawire"
    p.write_bytes(b"definitely not a wire file")
    with pytest.raises(wire.WireFormatError, match="magic"):
        wire.WireReader([str(p)], corpus[0])


def test_wire_checkpoint_crash_resume_bit_identical(corpus, wire_path, tmp_path):
    packed = corpus[0]
    ref = run_stream_wire(packed, wire_path, make_cfg(), topk=5)
    ck = dict(checkpoint_every_chunks=2, checkpoint_dir=str(tmp_path / "ck"))
    run_stream_wire(packed, wire_path, make_cfg(**ck), topk=5, max_chunks=3)
    snap = ckpt.load(str(tmp_path / "ck"))
    assert snap is not None and snap.fingerprint.endswith("-wire")
    rep = run_stream_wire(packed, wire_path, make_cfg(**ck, resume=True), topk=5)
    assert hits_of(rep) == hits_of(ref)
    assert rep.unused == ref.unused
    assert rep.totals["lines_matched"] == ref.totals["lines_matched"]
    assert rep.totals["lines_total"] == ref.totals["lines_total"]


def test_text_snapshot_cannot_resume_wire_input(corpus, wire_path, tmp_path):
    """Offsets count raw lines on the text path but rows on the wire path —
    cross-resume must be refused, not silently misaligned."""
    packed, _rs, logs, _lines = corpus
    ck = dict(checkpoint_every_chunks=2, checkpoint_dir=str(tmp_path / "ck"))
    run_stream_file(packed, logs, make_cfg(**ck), topk=5, max_chunks=3)
    with pytest.raises(ckpt.CheckpointMismatch):
        run_stream_wire(packed, wire_path, make_cfg(**ck, resume=True), topk=5)


def test_wire_stacked_layout_parity(corpus, wire_path):
    packed = corpus[0]
    ref = run_stream_wire(packed, wire_path, make_cfg(), topk=5)
    rep = run_stream_wire(
        packed, wire_path, make_cfg(layout="stacked", stacked_lane=64), topk=5
    )
    assert hits_of(rep) == hits_of(ref)
    assert rep.unused == ref.unused


def test_wire_dual_eval_corpus_counts_identical(tmp_path):
    """With out-direction bindings a line can emit two evaluation rows;
    registers and counts still match the text path exactly."""
    cfg_text = synth.synth_config(n_acls=2, rules_per_acl=8, seed=9, egress_acls=True)
    rs = aclparse.parse_asa_config(cfg_text, "fw1")
    packed = pack.pack_rulesets([rs])
    assert packed.bindings_out, "corpus must exercise dual evaluation"
    tuples = synth.synth_tuples(packed, 1200, seed=9)
    lines = synth.render_syslog(packed, tuples, seed=9)
    log = tmp_path / "fw1.log"
    log.write_text("\n".join(lines) + "\n")
    out = str(tmp_path / "fw1.rawire")
    stats = wire.convert_logs(packed, [str(log)], out, block_rows=128)
    assert stats["raw_lines"] == len(lines)
    ref = run_stream_file(packed, [str(log)], make_cfg(), topk=5)
    rep = run_stream_wire(packed, out, make_cfg(), topk=5)
    assert hits_of(rep) == hits_of(ref)
    assert rep.unused == ref.unused
    assert rep.totals["lines_total"] == ref.totals["lines_total"]
    assert rep.totals["lines_matched"] == ref.totals["lines_matched"]
    assert rep.totals["lines_skipped"] == ref.totals["lines_skipped"]


def test_wire_reader_zero_copy_alignment(corpus, wire_path):
    """block_rows == batch_size batches come straight off the mmap."""
    packed = corpus[0]
    r = wire.WireReader([wire_path], packed)
    views = 0
    for batch, n in r.iter_batches(0, 128):  # file written with block_rows=128
        assert batch.shape == (pack.WIRE_COLS, 128)
        if not batch.flags.owndata and not batch.flags.writeable:
            views += 1
    # every full block except possibly the tail must be zero-copy
    assert views >= (r.n_rows // 128) - 1
    r.close()


def test_cli_convert_and_packed_run(corpus, tmp_path, capsys):
    from ruleset_analysis_tpu.cli import main

    packed, _rs, logs, _lines = corpus
    prefix = str(tmp_path / "rs")
    pack.save_packed(packed, prefix)
    out = str(tmp_path / "logs.rawire")
    rc = main(["convert", "--ruleset", prefix, "--logs", *logs, "--out", out])
    assert rc == 0
    rc = main(
        ["run", "--ruleset", prefix, "--logs", out, "--packed-input", "--json",
         "--batch-size", "256"]
    )
    assert rc == 0
    import json

    rep = json.loads(capsys.readouterr().out)
    ref = run_stream_file(packed, logs, make_cfg(batch_size=256,
        sketch=SketchConfig(cms_width=1 << 14, cms_depth=4, hll_p=8)), topk=10)
    got = {(e["firewall"], e["acl"], e["index"]): e["hits"] for e in rep["per_rule"]}
    exp = {(e["firewall"], e["acl"], e["index"]): e["hits"] for e in ref.per_rule}
    assert got == exp
    assert rep["totals"]["lines_total"] == ref.totals["lines_total"]


def test_cli_mixed_inputs_refused(corpus, tmp_path, capsys):
    from ruleset_analysis_tpu.cli import main

    packed, _rs, logs, _lines = corpus
    prefix = str(tmp_path / "rs")
    pack.save_packed(packed, prefix)
    out = str(tmp_path / "logs.rawire")
    assert main(["convert", "--ruleset", prefix, "--logs", *logs, "--out", out]) == 0
    rc = main(["run", "--ruleset", prefix, "--logs", out, *logs])
    assert rc == 2
    assert "mix" in capsys.readouterr().err


def test_aborted_convert_leaves_refused_file(corpus, tmp_path):
    """A crashed/aborted convert must leave a file readers refuse outright,
    not one that validates with part of the rows (code-review finding)."""
    packed = corpus[0]
    p = str(tmp_path / "partial.rawire")
    w = wire.WireWriter(p, wire.ruleset_fingerprint(packed), block_rows=4)
    w.add(np.ones((pack.WIRE_COLS, 10), dtype=np.uint32), 10, 0)
    w.abort()
    with pytest.raises(wire.WireFormatError, match="incomplete"):
        wire.WireReader([p], packed)
    # partial files still sniff as wire files so run/oracle ROUTING sends
    # them to WireReader's loud refusal instead of the text parser
    assert wire.is_wire_file(p)

    # the context manager aborts on exception automatically
    q = str(tmp_path / "crashed.rawire")
    with pytest.raises(RuntimeError):
        with wire.WireWriter(q, wire.ruleset_fingerprint(packed)) as w2:
            w2.add(np.ones((pack.WIRE_COLS, 3), dtype=np.uint32), 3, 0)
            raise RuntimeError("simulated crash mid-convert")
    with pytest.raises(wire.WireFormatError, match="incomplete"):
        wire.WireReader([q], packed)


def test_corrupt_block_rows_header_refused(corpus, wire_path, tmp_path):
    """block_rows == 0 must be a WireFormatError, not a ZeroDivisionError."""
    import struct as _struct

    data = bytearray(open(wire_path, "rb").read())
    data[8:12] = _struct.pack("<I", 0)
    bad = tmp_path / "zeroblock.rawire"
    bad.write_bytes(bytes(data))
    with pytest.raises(wire.WireFormatError, match="block_rows"):
        wire.WireReader([str(bad)], corpus[0])


def test_cli_wire_plus_stdin_refused(corpus, tmp_path, capsys):
    """a.rawire + '-' must be refused, not text-parsed as garbage."""
    from ruleset_analysis_tpu.cli import main

    packed, _rs, logs, _lines = corpus
    prefix = str(tmp_path / "rs")
    pack.save_packed(packed, prefix)
    out = str(tmp_path / "logs.rawire")
    assert main(["convert", "--ruleset", prefix, "--logs", *logs, "--out", out]) == 0
    rc = main(["run", "--ruleset", prefix, "--logs", out, "-"])
    assert rc == 2
    assert "mix" in capsys.readouterr().err


def test_cli_partial_wire_file_refused_loudly(corpus, tmp_path, capsys):
    """A crashed convert's partial file must produce a loud error through
    the run CLI, not an empty text-parse report (code-review finding)."""
    from ruleset_analysis_tpu.cli import main

    packed = corpus[0]
    prefix = str(tmp_path / "rs")
    pack.save_packed(packed, prefix)
    p = str(tmp_path / "partial.rawire")
    w = wire.WireWriter(p, wire.ruleset_fingerprint(packed), block_rows=4)
    w.add(np.ones((pack.WIRE_COLS, 10), dtype=np.uint32), 10, 0)
    w.abort()
    rc = main(["run", "--ruleset", prefix, "--logs", p, "--batch-size", "64"])
    assert rc == 1
    assert "incomplete" in capsys.readouterr().err


def test_cli_convert_block_rows_validation(corpus, tmp_path, capsys):
    from ruleset_analysis_tpu.cli import main

    packed, _rs, logs, _lines = corpus
    prefix = str(tmp_path / "rs")
    pack.save_packed(packed, prefix)
    rc = main(["convert", "--ruleset", prefix, "--logs", *logs,
               "--out", str(tmp_path / "x.rawire"), "--block-rows", "0"])
    assert rc == 2
    assert "block-rows" in capsys.readouterr().err


def test_cli_convert_refuses_wire_input(corpus, tmp_path, capsys):
    """convert over an existing .rawire must be refused, not laundered
    through the text parser into a valid empty file (code-review finding)."""
    from ruleset_analysis_tpu.cli import main

    packed, _rs, logs, _lines = corpus
    prefix = str(tmp_path / "rs")
    pack.save_packed(packed, prefix)
    out = str(tmp_path / "a.rawire")
    assert main(["convert", "--ruleset", prefix, "--logs", *logs, "--out", out]) == 0
    rc = main(["convert", "--ruleset", prefix, "--logs", out,
               "--out", str(tmp_path / "b.rawire")])
    assert rc == 2
    assert "already a wire file" in capsys.readouterr().err


def test_convert_feed_workers_byte_identical(corpus, tmp_path):
    """Multi-process conversion writes the byte-identical file: chunk
    boundaries differ between parse tiers but the row stream does not."""
    from ruleset_analysis_tpu.hostside import fastparse

    if not fastparse.available():
        pytest.skip("native parser not buildable here")
    packed, _rs, logs, _lines = corpus
    seq = str(tmp_path / "seq.rawire")
    par = str(tmp_path / "par.rawire")
    s1 = wire.convert_logs(packed, logs, seq, block_rows=128)
    s2 = wire.convert_logs(packed, logs, par, block_rows=128, feed_workers=2)
    assert s2["parser"] == "native-feeder-x2"
    assert s1["raw_lines"] == s2["raw_lines"]
    assert open(seq, "rb").read() == open(par, "rb").read()


def test_convert_feed_workers_native_false_refused(corpus, tmp_path):
    packed, _rs, logs, _lines = corpus
    with pytest.raises(ValueError, match="native"):
        wire.convert_logs(packed, logs, str(tmp_path / "x.rawire"),
                          native=False, feed_workers=2)


def test_cli_wire_info(corpus, tmp_path, capsys):
    from ruleset_analysis_tpu.cli import main

    packed, _rs, logs, lines = corpus
    prefix = str(tmp_path / "rs")
    pack.save_packed(packed, prefix)
    out = str(tmp_path / "a.rawire")
    assert main(["convert", "--ruleset", prefix, "--logs", *logs, "--out", out]) == 0
    capsys.readouterr()
    rc = main(["wire-info", out, "--ruleset", prefix, "--json"])
    assert rc == 0
    import json

    info = json.loads(capsys.readouterr().out)[0]
    assert info["ok"] and info["raw_lines"] == len(lines)

    # wrong ruleset -> invalid, rc 1
    other_cfg = synth.synth_config(n_acls=2, rules_per_acl=4, seed=3)
    other = pack.pack_rulesets([aclparse.parse_asa_config(other_cfg, "fw1")])
    pack.save_packed(other, str(tmp_path / "rs2"))
    rc = main(["wire-info", out, "--ruleset", str(tmp_path / "rs2")])
    assert rc == 1
    assert "INVALID" in capsys.readouterr().out


def test_cli_wire_info_missing_file_reported(tmp_path, capsys):
    from ruleset_analysis_tpu.cli import main

    rc = main(["wire-info", str(tmp_path / "nope.rawire")])
    assert rc == 1
    assert "INVALID" in capsys.readouterr().out


def test_wire_stacked_checkpoint_crash_resume(corpus, wire_path, tmp_path):
    """All three round-4 features compose: wire input, stacked layout,
    checkpointed crash/resume — counts bit-identical to uninterrupted."""
    packed = corpus[0]
    base = make_cfg(layout="stacked", stacked_lane=64)
    ref = run_stream_wire(packed, wire_path, base, topk=5)
    ck = dict(layout="stacked", stacked_lane=64,
              checkpoint_every_chunks=2, checkpoint_dir=str(tmp_path / "ck"))
    run_stream_wire(packed, wire_path, make_cfg(**ck), topk=5, max_chunks=3)
    snap = ckpt.load(str(tmp_path / "ck"))
    assert snap is not None and snap.fingerprint.endswith("-wire")
    rep = run_stream_wire(packed, wire_path, make_cfg(**ck, resume=True), topk=5)
    assert hits_of(rep) == hits_of(ref)
    assert rep.unused == ref.unused
    assert rep.totals["lines_matched"] == ref.totals["lines_matched"]


def test_cli_topk_sample_shift_plumbing(corpus, tmp_path, capsys):
    """--topk-sample-shift reaches the device step; exact counts and the
    unused set are sample-invariant (only candidate SELECTION samples)."""
    import json

    from ruleset_analysis_tpu.cli import main

    packed, _rs, logs, _lines = corpus
    prefix = str(tmp_path / "rs")
    pack.save_packed(packed, prefix)

    def run(shift):
        out = str(tmp_path / f"rep{shift}.json")
        rc = main(["run", "--ruleset", prefix, "--logs", *logs,
                   "--batch-size", "256", "--topk-sample-shift", str(shift),
                   "--json", "--out", out])
        assert rc == 0
        return json.load(open(out))

    a, b = run(0), run(3)
    ha = {(e["firewall"], e["acl"], e["index"]): e["hits"] for e in a["per_rule"]}
    hb = {(e["firewall"], e["acl"], e["index"]): e["hits"] for e in b["per_rule"]}
    assert ha == hb
    assert a["unused"] == b["unused"]


def test_wire_run_closes_reader_deterministically(corpus, wire_path, monkeypatch):
    """run_stream_wire must release the reader's mmaps in a finally —
    long-lived drivers over many wire inputs cannot wait for GC
    (ADVICE r4)."""
    closed = []
    orig = wire.WireReader.close

    def spy(self):
        closed.append(True)
        return orig(self)

    monkeypatch.setattr(wire.WireReader, "close", spy)
    run_stream_wire(corpus[0], wire_path, make_cfg(), topk=5)
    assert closed, "WireReader.close was never called by the run driver"


def test_wire_close_tolerates_live_zero_copy_views(corpus, tmp_path):
    """block_rows == batch_size serves zero-copy mmap views; the finally-
    block close() must not raise BufferError while a suspended generator
    (max_chunks abort) or loop frame still holds the last view."""
    packed, _rs, logs, _lines = corpus
    out = tmp_path / "aligned.rawire"
    wire.convert_logs(packed, logs, str(out), block_rows=512)
    rep = run_stream_wire(
        packed, str(out), make_cfg(batch_size=512), topk=5, max_chunks=1
    )
    assert rep.totals["chunks"] == 1  # aborted cleanly, no BufferError


def test_wire_midrun_error_not_replaced_by_buffererror(corpus, tmp_path, monkeypatch):
    """An exception raised mid-run (device failure analog) must propagate
    as itself: the close() in the finally runs while the traceback keeps
    the chunk-loop frame (and its mmap view) alive, and a BufferError
    there would mask the real error from callers catching specific types."""
    from ruleset_analysis_tpu.parallel import mesh as mesh_lib

    packed, _rs, logs, _lines = corpus
    out = tmp_path / "aligned2.rawire"
    wire.convert_logs(packed, logs, str(out), block_rows=512)
    calls = []
    orig = mesh_lib.shard_batch

    def boom(*a, **kw):
        calls.append(True)
        if len(calls) == 2:
            raise RuntimeError("injected device failure")
        return orig(*a, **kw)

    monkeypatch.setattr(mesh_lib, "shard_batch", boom)
    with pytest.raises(RuntimeError, match="injected device failure"):
        run_stream_wire(packed, str(out), make_cfg(batch_size=512), topk=5)


def test_wire_reader_corruption_fuzz_clean_refusals(corpus, wire_path, tmp_path):
    """Random byte flips / truncation / extension of a wire file: the
    reader must refuse with AnalysisError (or surface corrupt rows via
    the valid-bit accounting) — never crash with a raw struct/numpy/
    mmap error (r5 fuzz pass)."""
    import random

    from ruleset_analysis_tpu.errors import AnalysisError
    from ruleset_analysis_tpu.hostside.wire import sanity_check_valid_bits

    packed = corpus[0]
    blob = open(wire_path, "rb").read()
    mp = str(tmp_path / "m.rawire")
    for trial in range(300):
        rng = random.Random(trial)
        b = bytearray(blob)
        for _ in range(rng.randint(1, 8)):
            if not b:
                break
            op = rng.randrange(3)
            if op == 0:
                b[rng.randrange(len(b))] = rng.randrange(256)
            elif op == 1:
                b = bytearray(b[: rng.randrange(len(b))])
            else:
                b += bytes(rng.randrange(64))
        with open(mp, "wb") as f:
            f.write(bytes(b))
        try:
            r = wire.WireReader([mp], packed)
            for batch, _n in r.iter_batches(0, 128):
                sanity_check_valid_bits(batch)
            r.close()
        except AnalysisError:
            pass  # loud, typed refusal — the contract
