"""Worker process for tests/test_distributed.py.

Joins a jax.distributed cluster (local CPU coordinator), runs the
distributed stream driver over THIS process's input split, and dumps the
final register files + report JSON for the parent test to compare.

Usage: dist_worker.py PROC_ID N_PROCS PORT RULESET_PREFIX LOG_PATH OUT_PREFIX
           [CKPT_DIR MODE]

MODE (requires CKPT_DIR): "crash" checkpoints every 2 chunks and aborts
after 3; "resume" resumes from the checkpoint and runs to completion;
"stacked" (CKPT_DIR ignored, pass "-") runs the stacked layout;
"stacked-crash"/"stacked-resume" are the checkpointed stacked variants
(collective flush-barrier snapshots); "die" joins the cluster then exits
abruptly (dead-peer failure surface — the survivors must error out in
bounded time, not hang).
"""

import json
import sys


def main() -> int:
    proc_id, n_procs, port = int(sys.argv[1]), int(sys.argv[2]), int(sys.argv[3])
    ruleset_prefix, log_path, out_prefix = sys.argv[4], sys.argv[5], sys.argv[6]
    ckpt_dir = sys.argv[7] if len(sys.argv) > 7 else None
    mode = sys.argv[8] if len(sys.argv) > 8 else None

    from ruleset_analysis_tpu.parallel.distributed import init_distributed

    init_distributed(
        f"127.0.0.1:{port}",
        n_procs,
        proc_id,
        # kill-test runs bound dead-peer detection so the survivor's
        # failure is provably bounded-time, not a hang
        heartbeat_timeout_seconds=10 if mode in ("die", "survivor") else None,
    )

    if mode == "die":
        import os

        # abrupt death AFTER joining the cluster (no atexit, no shutdown
        # handshake) — the most hostile failure the coordinator can see
        print(f"worker {proc_id} dying abruptly", file=sys.stderr, flush=True)
        os._exit(3)

    import numpy as np

    from ruleset_analysis_tpu.config import AnalysisConfig, SketchConfig
    from ruleset_analysis_tpu.hostside import pack
    from ruleset_analysis_tpu.runtime.stream import run_stream_file_distributed

    packed = pack.load_packed(ruleset_prefix)
    cfg = AnalysisConfig(
        batch_size=64,
        sketch=SketchConfig(cms_width=1 << 10, cms_depth=4, hll_p=6),
        **(
            {"checkpoint_every_chunks": 2, "checkpoint_dir": ckpt_dir}
            if ckpt_dir and ckpt_dir != "-"
            else {}
        ),
        resume=mode in ("resume", "stacked-resume"),
        layout="stacked" if mode and mode.startswith("stacked") else "flat",
    )
    max_chunks = {"crash": 3, "stacked-abort": 2, "stacked-crash": 3}.get(mode)
    report, regs = run_stream_file_distributed(
        packed, [log_path], cfg, return_state=True, max_chunks=max_chunks
    )
    np.savez(out_prefix + ".npz", **regs)
    with open(out_prefix + ".json", "w", encoding="utf-8") as f:
        f.write(report.to_json())
    print(f"worker {proc_id}/{n_procs} done", file=sys.stderr)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
