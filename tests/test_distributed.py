"""Multi-process (jax.distributed) path: 2 CPU processes == 1 process.

VERDICT round 2 #5: the multi-host layer needs a demonstrated cross-process
run, not a docstring.  Two worker processes join a local-coordinator
jax.distributed cluster (4 fake CPU devices each -> an 8-device global
mesh), each feeds its own half of the corpus (the input-split analog), and
the final register files must be BIT-IDENTICAL to a single-process run
over the whole corpus — registers are mergeable and order-invariant, so
how lines were split across processes cannot matter.
"""

import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

from ruleset_analysis_tpu.hostside import aclparse, pack, synth

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_WORKER = os.path.join(os.path.dirname(os.path.abspath(__file__)), "dist_worker.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _worker_env(n_local_devices: int) -> dict:
    sys.path.insert(0, _REPO)
    from __graft_entry__ import scrubbed_cpu_env

    env = scrubbed_cpu_env(n_local_devices)
    env["RA_TEST_REEXEC"] = "1"
    return env


def _spawn_and_check(argvs, n_local_devices):
    """Run one process per argv; kill all on timeout; assert every rc==0."""
    procs = [
        subprocess.Popen(
            argv,
            env=_worker_env(n_local_devices),
            cwd=_REPO,
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            text=True,
        )
        for argv in argvs
    ]
    outs = []
    for p in procs:
        try:
            out, err = p.communicate(timeout=300)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outs.append((p.returncode, out, err))
    for rc, _out, err in outs:
        assert rc == 0, f"worker failed rc={rc}\nstderr:\n{err[-3000:]}"
    return outs


def _run_workers(n_procs, port, ruleset_prefix, logs, out_prefixes,
                 n_local_devices, extra=()):
    _spawn_and_check(
        [
            [sys.executable, _WORKER, str(pid), str(n_procs), str(port),
             ruleset_prefix, logs[pid], out_prefixes[pid], *extra]
            for pid in range(n_procs)
        ],
        n_local_devices,
    )


@pytest.fixture(scope="module")
def corpus(tmp_path_factory):
    td = tmp_path_factory.mktemp("dist")
    cfg_text = synth.synth_config(
        n_acls=3, rules_per_acl=8, seed=41, egress_acls=True
    )
    rs = aclparse.parse_asa_config(cfg_text, "fw1")
    packed = pack.pack_rulesets([rs])
    tuples = synth.synth_tuples(packed, 1200, seed=42)
    lines = synth.render_syslog(packed, tuples, seed=43, variety=0.4)
    prefix = str(td / "packed")
    pack.save_packed(packed, prefix)
    full = td / "full.log"
    full.write_text("\n".join(lines) + "\n", encoding="utf-8")
    half0 = td / "half0.log"
    half0.write_text("\n".join(lines[:600]) + "\n", encoding="utf-8")
    half1 = td / "half1.log"
    half1.write_text("\n".join(lines[600:]) + "\n", encoding="utf-8")
    return td, prefix, str(full), str(half0), str(half1)


def test_two_process_registers_bit_identical_to_single(corpus):
    td, prefix, full, half0, half1 = corpus

    # reference: ONE process over the whole corpus (same driver code path)
    _run_workers(1, _free_port(), prefix, [full], [str(td / "ref")], 8)

    # two processes, 4 local fake devices each -> 8-device global mesh
    port = _free_port()
    _run_workers(2, port, prefix, [half0, half1],
                 [str(td / "out0"), str(td / "out1")], 4)

    ref = np.load(str(td / "ref.npz"))
    o0 = np.load(str(td / "out0.npz"))
    o1 = np.load(str(td / "out1.npz"))
    for k in ref.files:
        np.testing.assert_array_equal(ref[k], o0[k], err_msg=f"register {k}")
        np.testing.assert_array_equal(o0[k], o1[k], err_msg=f"register {k} ranks")

    rep_ref = json.loads((td / "ref.json").read_text())
    rep0 = json.loads((td / "out0.json").read_text())
    rep1 = json.loads((td / "out1.json").read_text())
    hits = lambda r: {tuple(e["key"]) if "key" in e else (e["firewall"], e["acl"], e["index"]): e["hits"] for e in r["per_rule"]}  # noqa: E731
    assert hits(rep0) == hits(rep_ref) == hits(rep1)
    assert rep0["unused"] == rep_ref["unused"]
    assert rep0["totals"]["lines_total"] == rep_ref["totals"]["lines_total"]
    assert rep0["totals"]["lines_matched"] == rep_ref["totals"]["lines_matched"]
    assert rep0["totals"]["processes"] == 2


def test_two_process_checkpoint_crash_resume(corpus):
    """Crash after 3 chunks (snapshot every 2), resume, finish: registers
    must be bit-identical to an uninterrupted 2-process run."""
    td, prefix, full, half0, half1 = corpus
    ck = str(td / "ck")

    # uninterrupted reference (2 processes, no checkpointing)
    _run_workers(2, _free_port(), prefix, [half0, half1],
                 [str(td / "u0"), str(td / "u1")], 4)

    # crash mid-run, then resume from the per-process snapshots
    _run_workers(2, _free_port(), prefix, [half0, half1],
                 [str(td / "c0"), str(td / "c1")], 4, extra=(ck, "crash"))
    assert os.path.isdir(os.path.join(ck, "proc-0-of-2"))
    assert os.path.isdir(os.path.join(ck, "proc-1-of-2"))
    _run_workers(2, _free_port(), prefix, [half0, half1],
                 [str(td / "r0"), str(td / "r1")], 4, extra=(ck, "resume"))

    ref = np.load(str(td / "u0.npz"))
    res = np.load(str(td / "r0.npz"))
    for k in ref.files:
        np.testing.assert_array_equal(ref[k], res[k], err_msg=f"register {k}")
    rep_u = json.loads((td / "u0.json").read_text())
    rep_r = json.loads((td / "r0.json").read_text())
    assert rep_r["unused"] == rep_u["unused"]
    assert rep_r["totals"]["lines_total"] == rep_u["totals"]["lines_total"]
    assert rep_r["totals"]["lines_matched"] == rep_u["totals"]["lines_matched"]


def test_stale_foreign_layout_dirs_do_not_block_resume(corpus, tmp_path):
    """proc-*-of-M leftovers must not block a valid proc-*-of-N resume."""
    from ruleset_analysis_tpu.runtime.stream import _dist_ckpt_layout_error

    ck = tmp_path / "ck"
    (ck / "proc-0-of-2").mkdir(parents=True)
    (ck / "proc-1-of-2").mkdir()
    # only foreign dirs -> resuming with 4 processes must refuse
    assert _dist_ckpt_layout_error(str(ck), 4) is not None
    # matching dirs present -> stale foreign dirs are ignored
    (ck / "proc-0-of-4").mkdir()
    assert _dist_ckpt_layout_error(str(ck), 4) is None
    # matching layout, no foreign -> fine
    assert _dist_ckpt_layout_error(str(ck), 2) is None


def test_cli_distributed_two_processes(corpus):
    """The run --distributed CLI path end-to-end across two processes."""
    td, prefix, full, half0, half1 = corpus
    port = _free_port()
    out0 = td / "cli_rep.json"
    _spawn_and_check(
        [
            [sys.executable, "-m", "ruleset_analysis_tpu.cli", "run",
             "--ruleset", prefix, "--logs", log, "--backend", "tpu",
             "--distributed", "--coordinator", f"127.0.0.1:{port}",
             "--num-processes", "2", "--process-id", str(pid),
             "--batch-size", "64", "--json", "--out", str(out0)]
            for pid, log in ((0, half0), (1, half1))
        ],
        4,
    )
    # only rank 0 writes the report (rank 1 returns before output)
    rep = json.loads(out0.read_text(encoding="utf-8"))
    assert rep["totals"]["processes"] == 2
    assert rep["totals"]["lines_total"] == 1200


def test_two_process_stacked_layout(corpus):
    """Stacked (per-ACL slab) layout across two processes: the mergeable
    registers that don't depend on chunk boundaries (counts, cms, hll)
    must be bit-identical to the flat 2-process run's."""
    td, prefix, full, half0, half1 = corpus

    _run_workers(2, _free_port(), prefix, [half0, half1],
                 [str(td / "st0"), str(td / "st1")], 4, extra=("-", "stacked"))

    # flat reference already produced by the first test (module fixture
    # ordering isn't guaranteed, so recompute if missing)
    if not (td / "out0.npz").exists():
        _run_workers(2, _free_port(), prefix, [half0, half1],
                     [str(td / "out0"), str(td / "out1")], 4)

    flat = np.load(str(td / "out0.npz"))
    st0 = np.load(str(td / "st0.npz"))
    st1 = np.load(str(td / "st1.npz"))
    for k in ("counts_lo", "counts_hi", "cms", "hll", "talk_cms"):
        np.testing.assert_array_equal(flat[k], st0[k], err_msg=f"register {k}")
        np.testing.assert_array_equal(st0[k], st1[k], err_msg=f"register {k} ranks")
    rep_flat = json.loads((td / "out0.json").read_text())
    rep_st = json.loads((td / "st0.json").read_text())
    hits = lambda r: {(e["firewall"], e["acl"], e["index"]): e["hits"] for e in r["per_rule"]}  # noqa: E731
    assert hits(rep_st) == hits(rep_flat)
    assert rep_st["unused"] == rep_flat["unused"]
    assert rep_st["totals"]["lines_total"] == rep_flat["totals"]["lines_total"]
    assert rep_st["totals"]["lines_matched"] == rep_flat["totals"]["lines_matched"]


def _ensure_ref(corpus):
    """Single-process reference registers (recompute if test order skipped it)."""
    td, prefix, full, _h0, _h1 = corpus
    if not (td / "ref.npz").exists():
        _run_workers(1, _free_port(), prefix, [full], [str(td / "ref")], 8)
    return np.load(str(td / "ref.npz")), json.loads((td / "ref.json").read_text())


def test_four_process_uneven_splits_including_empty(corpus):
    """VERDICT r3 #6: 4 processes, strongly uneven input splits (700/300/
    200/0 lines — one process has NOTHING).  The collective loop pads dry
    processes, so registers must still be bit-identical to 1 process."""
    td, prefix, full, _h0, _h1 = corpus
    lines = open(full, encoding="utf-8").read().splitlines()
    sizes = (700, 300, 200, 0)
    splits, pos = [], 0
    for i, n in enumerate(sizes):
        p = td / f"q{i}.log"
        p.write_text("".join(ln + "\n" for ln in lines[pos : pos + n]), encoding="utf-8")
        splits.append(str(p))
        pos += n
    assert pos == len(lines)

    _run_workers(4, _free_port(), prefix, splits,
                 [str(td / f"q{i}") for i in range(4)], 2)
    ref, rep_ref = _ensure_ref(corpus)
    outs = [np.load(str(td / f"q{i}.npz")) for i in range(4)]
    for k in ref.files:
        for i, o in enumerate(outs):
            np.testing.assert_array_equal(ref[k], o[k], err_msg=f"register {k} rank {i}")
    rep = json.loads((td / "q0.json").read_text())
    assert rep["totals"]["processes"] == 4
    assert rep["totals"]["lines_total"] == rep_ref["totals"]["lines_total"]
    assert rep["totals"]["lines_matched"] == rep_ref["totals"]["lines_matched"]
    assert rep["unused"] == rep_ref["unused"]


@pytest.mark.slow  # widest fake mesh; 4-process uneven covers multi>2 in tier-1
def test_eight_process_registers_match_single(corpus):
    """8 processes x 1 fake device each == the SURVEY §5 fake-mesh idiom
    at its widest; registers bit-identical to the single-process run."""
    td, prefix, full, _h0, _h1 = corpus
    lines = open(full, encoding="utf-8").read().splitlines()
    splits = []
    for i in range(8):
        p = td / f"e{i}.log"
        p.write_text("".join(ln + "\n" for ln in lines[i * 150 : (i + 1) * 150]),
                     encoding="utf-8")
        splits.append(str(p))

    _run_workers(8, _free_port(), prefix, splits,
                 [str(td / f"e{i}") for i in range(8)], 1)
    ref, _rep_ref = _ensure_ref(corpus)
    o0 = np.load(str(td / "e0.npz"))
    o7 = np.load(str(td / "e7.npz"))
    for k in ref.files:
        np.testing.assert_array_equal(ref[k], o0[k], err_msg=f"register {k}")
        np.testing.assert_array_equal(o0[k], o7[k], err_msg=f"register {k} ranks")
    rep = json.loads((td / "e0.json").read_text())
    assert rep["totals"]["processes"] == 8
    assert rep["totals"]["lines_total"] == 1200


@pytest.mark.slow  # ~100s: jax-level detection needs its full heartbeat
# window on old jax; the elastic tier tests cover peer death in tier-1
def test_killed_process_fails_cleanly_not_hangs(corpus):
    """SURVEY §6 failure detection: when a peer dies abruptly mid-job, the
    survivor must abort with an error in bounded time (heartbeat-driven
    dead-peer detection), never hang in a collective."""
    import time

    td, prefix, full, half0, half1 = corpus
    port = _free_port()
    env = _worker_env(4)
    args = lambda pid, mode: [  # noqa: E731
        sys.executable, _WORKER, str(pid), "2", str(port),
        prefix, half0 if pid == 0 else half1,
        str(td / f"k{pid}"), "-", mode,
    ]
    t0 = time.monotonic()
    survivor = subprocess.Popen(args(0, "survivor"), env=env, cwd=_REPO,
                                stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                                text=True)
    victim = subprocess.Popen(args(1, "die"), env=env, cwd=_REPO,
                              stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                              text=True)
    try:
        _out, verr = victim.communicate(timeout=120)
        assert victim.returncode == 3, verr[-2000:]
        # survivor must FAIL (nonzero) well before the 180s ceiling:
        # heartbeat timeout is 10s, so detection lands in tens of seconds
        _out, serr = survivor.communicate(timeout=180)
    except subprocess.TimeoutExpired:
        survivor.kill()
        victim.kill()
        raise AssertionError("survivor hung after peer death (no bounded-time failure)")
    elapsed = time.monotonic() - t0
    assert survivor.returncode != 0, "survivor reported success despite a dead peer"
    assert elapsed < 180, f"survivor took {elapsed:.0f}s to fail"
    # it died on a real error surface, not a silent exit
    assert serr.strip(), "survivor produced no error output"


@pytest.mark.slow  # stacked snapshot barrier also covered by flat crash +
# single-process stacked tests in tier-1
def test_two_process_stacked_checkpoint_crash_resume(corpus):
    """VERDICT r3 #4: checkpoint/resume on the stacked distributed path.
    Snapshots are collective flush barriers, so crash+resume registers are
    bit-identical to an uninterrupted stacked 2-process run."""
    td, prefix, full, half0, half1 = corpus
    ck = str(td / "ck_st")

    # uninterrupted stacked reference (no checkpointing)
    if not (td / "st0.npz").exists():
        _run_workers(2, _free_port(), prefix, [half0, half1],
                     [str(td / "st0"), str(td / "st1")], 4, extra=("-", "stacked"))

    _run_workers(2, _free_port(), prefix, [half0, half1],
                 [str(td / "sc0"), str(td / "sc1")], 4,
                 extra=(ck, "stacked-crash"))
    assert os.path.isdir(os.path.join(ck, "proc-0-of-2"))
    assert os.path.isdir(os.path.join(ck, "proc-1-of-2"))
    _run_workers(2, _free_port(), prefix, [half0, half1],
                 [str(td / "sr0"), str(td / "sr1")], 4,
                 extra=(ck, "stacked-resume"))

    ref = np.load(str(td / "st0.npz"))
    r0 = np.load(str(td / "sr0.npz"))
    r1 = np.load(str(td / "sr1.npz"))
    # order-invariant registers must be bit-identical (candidate tables
    # are chunk-boundary-sensitive by design and excluded)
    for k in ("counts_lo", "counts_hi", "cms", "hll", "talk_cms"):
        np.testing.assert_array_equal(ref[k], r0[k], err_msg=f"register {k}")
        np.testing.assert_array_equal(r0[k], r1[k], err_msg=f"register {k} ranks")
    rep_ref = json.loads((td / "st0.json").read_text())
    rep_r = json.loads((td / "sr0.json").read_text())
    hits = lambda r: {(e["firewall"], e["acl"], e["index"]): e["hits"] for e in r["per_rule"]}  # noqa: E731
    assert hits(rep_r) == hits(rep_ref)
    assert rep_r["unused"] == rep_ref["unused"]
    assert rep_r["totals"]["lines_total"] == rep_ref["totals"]["lines_total"]
    assert rep_r["totals"]["lines_matched"] == rep_ref["totals"]["lines_matched"]


def test_two_process_wire_input_matches_text(corpus):
    """The distributed path over pre-tokenized .rawire splits: registers
    and raw-line totals must match the text-input distributed run."""
    from ruleset_analysis_tpu.hostside import wire

    td, prefix, full, half0, half1 = corpus
    packed = pack.load_packed(prefix)
    w0, w1 = str(td / "half0.rawire"), str(td / "half1.rawire")
    wire.convert_logs(packed, [half0], w0, block_rows=64)
    wire.convert_logs(packed, [half1], w1, block_rows=64)

    if not (td / "out0.npz").exists():
        _run_workers(2, _free_port(), prefix, [half0, half1],
                     [str(td / "out0"), str(td / "out1")], 4)
    _run_workers(2, _free_port(), prefix, [w0, w1],
                 [str(td / "w0"), str(td / "w1")], 4)

    ref = np.load(str(td / "out0.npz"))
    got0 = np.load(str(td / "w0.npz"))
    got1 = np.load(str(td / "w1.npz"))
    for k in ref.files:
        np.testing.assert_array_equal(ref[k], got0[k], err_msg=f"register {k}")
        np.testing.assert_array_equal(got0[k], got1[k], err_msg=f"register {k} ranks")
    rep_ref = json.loads((td / "out0.json").read_text())
    rep_w = json.loads((td / "w0.json").read_text())
    hits = lambda r: {(e["firewall"], e["acl"], e["index"]): e["hits"] for e in r["per_rule"]}  # noqa: E731
    assert hits(rep_w) == hits(rep_ref)
    assert rep_w["unused"] == rep_ref["unused"]
    # totals_patch restores the converter's raw-line accounting per split
    assert rep_w["totals"]["lines_total"] == rep_ref["totals"]["lines_total"]
    assert rep_w["totals"]["lines_matched"] == rep_ref["totals"]["lines_matched"]
    assert rep_w["totals"]["lines_skipped"] == rep_ref["totals"]["lines_skipped"]


def test_stacked_abort_drains_buffered_lines(corpus):
    """max_chunks abort in stacked mode: lines already counted into the
    totals must still reach the registers (collective post-abort drain)."""
    td, prefix, full, half0, half1 = corpus
    _run_workers(2, _free_port(), prefix, [half0, half1],
                 [str(td / "sa0"), str(td / "sa1")], 4,
                 extra=("-", "stacked-abort"))
    regs = np.load(str(td / "sa0.npz"))
    rep = json.loads((td / "sa0.json").read_text())
    total_counts = int(
        regs["counts_lo"].astype(np.uint64).sum()
        + (regs["counts_hi"].astype(np.uint64).sum() << np.uint64(32))
    )
    # every counted evaluation landed in the registers — no limbo lines
    assert total_counts == rep["totals"]["lines_matched"]
    assert 0 < rep["totals"]["lines_total"] < 1200  # genuinely aborted early


def test_corrupt_one_process_snapshot_fails_all_loudly(corpus):
    """Resume where ONE process's snapshot is corrupt: every process must
    exit with the typed CheckpointCorrupt verdict in bounded time — a
    lone local raise would strand the peers in the resume allgather
    (stream.py evaluates all local conditions first, gathers once, then
    raises the same verdict everywhere)."""
    td, prefix, full, half0, half1 = corpus
    ck = str(td / "ck-corrupt")

    _run_workers(2, _free_port(), prefix, [half0, half1],
                 [str(td / "x0"), str(td / "x1")], 4, extra=(ck, "crash"))
    # corrupt proc-1's snapshot payload (keep the pointer intact)
    pdir = os.path.join(ck, "proc-1-of-2")
    latest = open(os.path.join(pdir, "LATEST")).read().strip()
    state = os.path.join(pdir, latest, "state.npz")
    with open(state, "r+b") as f:
        f.write(b"\xde\xad\xbe\xef" * 8)

    port = _free_port()
    procs = [
        subprocess.Popen(
            [sys.executable, _WORKER, str(pid), "2", str(port),
             prefix, [half0, half1][pid], str(td / f"z{pid}"), ck, "resume"],
            env=_worker_env(4), cwd=_REPO,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        )
        for pid in range(2)
    ]
    errs = []
    for p in procs:
        try:
            _out, err = p.communicate(timeout=240)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise AssertionError("resume with a corrupt snapshot HUNG")
        errs.append((p.returncode, err))
    assert all(rc != 0 for rc, _ in errs), f"some worker succeeded: {errs}"
    assert any("CheckpointCorrupt" in err or "corrupt" in err for _, err in errs)


@pytest.fixture(scope="module")
def corpus6(tmp_path_factory):
    """Unified (v4+v6) corpus: exercises the distributed v6 side path."""
    import random as _random

    from ruleset_analysis_tpu.hostside import oracle as oracle_mod

    td = tmp_path_factory.mktemp("dist6")
    cfg_text = synth.synth_config(
        n_acls=3, rules_per_acl=8, seed=51, v6_fraction=0.4
    )
    rs = aclparse.parse_asa_config(cfg_text, "fw1")
    packed = pack.pack_rulesets([rs])
    assert packed.has_v6
    t4 = synth.synth_tuples(packed, 700, seed=52)
    t6 = synth.synth_tuples6(packed, 500, seed=53)
    lines = synth.render_syslog(packed, t4, seed=54) + synth.render_syslog6(
        packed, t6, seed=55
    )
    _random.Random(5).shuffle(lines)
    res = oracle_mod.Oracle([rs]).consume(list(lines))
    prefix = str(td / "packed")
    pack.save_packed(packed, prefix)
    (td / "full.log").write_text("\n".join(lines) + "\n", encoding="utf-8")
    (td / "half0.log").write_text("\n".join(lines[:600]) + "\n", encoding="utf-8")
    (td / "half1.log").write_text("\n".join(lines[600:]) + "\n", encoding="utf-8")
    return td, prefix, res


def test_two_process_v6_bit_identical_and_oracle_exact(corpus6):
    td, prefix, res = corpus6
    _run_workers(1, _free_port(), prefix, [str(td / "full.log")],
                 [str(td / "ref6")], 8)
    _run_workers(2, _free_port(), prefix,
                 [str(td / "half0.log"), str(td / "half1.log")],
                 [str(td / "o60"), str(td / "o61")], 4)
    ref = np.load(str(td / "ref6.npz"))
    o0 = np.load(str(td / "o60.npz"))
    o1 = np.load(str(td / "o61.npz"))
    for k in ref.files:
        np.testing.assert_array_equal(ref[k], o0[k], err_msg=f"register {k}")
        np.testing.assert_array_equal(o0[k], o1[k], err_msg=f"register {k} ranks")
    rep0 = json.loads((td / "o60.json").read_text())
    rep1 = json.loads((td / "o61.json").read_text())
    got = {
        (e["firewall"], e["acl"], e["index"]): e["hits"]
        for e in rep0["per_rule"] if e["hits"] > 0
    }
    assert got == dict(res.hits)
    # identical-everywhere contract holds for v6 talker rendering too
    assert rep0["talkers"] == rep1["talkers"]


@pytest.mark.slow  # v6 crash/resume is tier-1 single-process (test_stream6)
def test_two_process_v6_crash_resume(corpus6):
    td, prefix, res = corpus6
    ck = str(td / "ck6")
    _run_workers(2, _free_port(), prefix,
                 [str(td / "half0.log"), str(td / "half1.log")],
                 [str(td / "u60"), str(td / "u61")], 4)
    _run_workers(2, _free_port(), prefix,
                 [str(td / "half0.log"), str(td / "half1.log")],
                 [str(td / "c60"), str(td / "c61")], 4, extra=(ck, "crash"))
    _run_workers(2, _free_port(), prefix,
                 [str(td / "half0.log"), str(td / "half1.log")],
                 [str(td / "r60"), str(td / "r61")], 4, extra=(ck, "resume"))
    ref = np.load(str(td / "u60.npz"))
    got = np.load(str(td / "r60.npz"))
    for k in ref.files:
        np.testing.assert_array_equal(ref[k], got[k], err_msg=f"register {k}")


def test_two_process_v6_wire_input_matches_text(corpus6, tmp_path):
    """Distributed wire-v2 input: the phase-2 collective v6 rounds must
    reproduce the text run's registers exactly."""
    from ruleset_analysis_tpu.hostside import wire

    td, prefix, res = corpus6
    packed = pack.load_packed(prefix)
    w0 = str(tmp_path / "h0.rawire")
    w1 = str(tmp_path / "h1.rawire")
    wire.convert_logs(packed, [str(td / "half0.log")], w0)
    wire.convert_logs(packed, [str(td / "half1.log")], w1)
    r = wire.WireReader([w0, w1], packed)
    assert r.n6_rows > 0  # the v2 sections are actually exercised
    r.close()
    _run_workers(2, _free_port(), prefix, [w0, w1],
                 [str(tmp_path / "w60"), str(tmp_path / "w61")], 4)
    # reference: the 2-process TEXT run over the same halves
    _run_workers(2, _free_port(), prefix,
                 [str(td / "half0.log"), str(td / "half1.log")],
                 [str(tmp_path / "t60"), str(tmp_path / "t61")], 4)
    ref = np.load(str(tmp_path / "t60.npz"))
    got = np.load(str(tmp_path / "w60.npz"))
    for k in ref.files:
        # every register file, talk_cms included: all updates are
        # order-invariant merges, so text and wire phase order cannot
        # change the final state
        np.testing.assert_array_equal(ref[k], got[k], err_msg=f"register {k}")
    rep = json.loads((tmp_path / "w60.json").read_text())
    got_hits = {
        (e["firewall"], e["acl"], e["index"]): e["hits"]
        for e in rep["per_rule"] if e["hits"] > 0
    }
    assert got_hits == dict(res.hits)


@pytest.mark.slow  # stacked+v6 each covered separately in tier-1
def test_two_process_v6_stacked_bit_identical(corpus6, tmp_path):
    """Stacked layout + v6 side channel across 2 processes == 1 process."""
    td, prefix, res = corpus6
    _run_workers(1, _free_port(), prefix, [str(td / "full.log")],
                 [str(tmp_path / "sref")], 8, extra=("-", "stacked"))
    _run_workers(2, _free_port(), prefix,
                 [str(td / "half0.log"), str(td / "half1.log")],
                 [str(tmp_path / "s0"), str(tmp_path / "s1")], 4,
                 extra=("-", "stacked"))
    ref = np.load(str(tmp_path / "sref.npz"))
    o0 = np.load(str(tmp_path / "s0.npz"))
    for k in ref.files:
        np.testing.assert_array_equal(ref[k], o0[k], err_msg=f"register {k}")
    rep = json.loads((tmp_path / "s0.json").read_text())
    got_hits = {
        (e["firewall"], e["acl"], e["index"]): e["hits"]
        for e in rep["per_rule"] if e["hits"] > 0
    }
    assert got_hits == dict(res.hits)
