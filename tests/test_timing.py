"""runtime.timing: counts-closed benchmark windows catch fake execution."""

import numpy as np
import pytest

pytest.importorskip("jax")

from ruleset_analysis_tpu.config import AnalysisConfig, SketchConfig
from ruleset_analysis_tpu.hostside import aclparse, pack, synth
from ruleset_analysis_tpu.models import pipeline
from ruleset_analysis_tpu.runtime.timing import timed_validated_steps


@pytest.fixture(scope="module")
def setup():
    cfg_text = synth.synth_config(n_acls=2, rules_per_acl=8, seed=81)
    rs = aclparse.parse_asa_config(cfg_text, "fw1")
    packed = pack.pack_rulesets([rs])
    cfg = AnalysisConfig(
        batch_size=256, sketch=SketchConfig(cms_width=1 << 10, cms_depth=2, hll_p=5)
    )
    b = np.ascontiguousarray(synth.synth_tuples(packed, 256, seed=81).T)
    feeds = [pack.compact_batch(b)]
    valid = [int(b[pack.T_VALID].sum())]
    import functools
    import jax

    step = jax.jit(
        functools.partial(
            pipeline.analysis_step,
            n_keys=packed.n_keys,
            topk_k=cfg.sketch.topk_chunk_candidates,
        )
    )
    return packed, cfg, step, feeds, valid


def test_real_execution_validates(setup):
    packed, cfg, step, feeds, valid = setup
    state = pipeline.init_state(packed.n_keys, cfg)
    state, dt, delta, expect = timed_validated_steps(
        step, state, pipeline.ship_ruleset(packed), feeds, valid, 4
    )
    assert delta == expect == 4 * valid[0]
    assert dt > 0


def test_fake_step_is_caught(setup):
    """A step that never runs (returns its inputs) must show delta=0."""
    packed, cfg, _, feeds, valid = setup

    def fake_step(state, rules, batch):
        return state, None

    state = pipeline.init_state(packed.n_keys, cfg)
    state, _dt, delta, expect = timed_validated_steps(
        fake_step, state, pipeline.ship_ruleset(packed), feeds, valid, 4
    )
    assert delta == 0
    assert expect == 4 * valid[0]
    assert delta != expect  # the caller's integrity check would fire
