"""Inventory-driven ruleset acquisition (SURVEY.md §4.1 getaccesslists loop)."""

import pytest

from ruleset_analysis_tpu.hostside import acquire, aclparse, synth


CFG = synth.synth_config(n_acls=2, rules_per_acl=4, seed=71)


def test_obtain_from_file(tmp_path):
    p = tmp_path / "fw.cfg"
    p.write_text(CFG)
    assert acquire.obtain_config(str(p)) == CFG


def test_obtain_from_command(tmp_path):
    p = tmp_path / "fw.cfg"
    p.write_text(CFG)
    text = acquire.obtain_config(f"cmd:cat {p}")
    assert text == CFG


def test_obtain_command_failure_raises():
    with pytest.raises(aclparse.AclParseError, match="rc="):
        acquire.obtain_config("cmd:false")


def test_load_inventory_file(tmp_path):
    inv = tmp_path / "inv.txt"
    inv.write_text(
        "# firewalls\n"
        "edge1 = /etc/cfg/edge1.cfg\n"
        "edge2 = cmd:ssh edge2 show run\n"
        "\n"
    )
    got = acquire.load_inventory(str(inv))
    assert got == {
        "edge1": "/etc/cfg/edge1.cfg",
        "edge2": "cmd:ssh edge2 show run",
    }


def test_load_inventory_default_is_config_firewalls(monkeypatch):
    from ruleset_analysis_tpu import config as config_mod

    monkeypatch.setattr(config_mod, "FIREWALLS", {"fwA": "/tmp/a.cfg"})
    assert acquire.load_inventory(None) == {"fwA": "/tmp/a.cfg"}


def test_malformed_inventory_line(tmp_path):
    inv = tmp_path / "inv.txt"
    inv.write_text("edge1 /etc/cfg/edge1.cfg\n")
    with pytest.raises(aclparse.AclParseError, match="name = source"):
        acquire.load_inventory(str(inv))


def test_acquire_rulesets_and_cli(tmp_path, capsys):
    from ruleset_analysis_tpu import cli

    c1 = tmp_path / "fw1.cfg"
    c1.write_text(synth.synth_config(n_acls=2, rules_per_acl=4, seed=72, hostname="fw1"))
    c2 = tmp_path / "fw2.cfg"
    c2.write_text(synth.synth_config(n_acls=1, rules_per_acl=3, seed=73, hostname="fw2"))
    inv = tmp_path / "inv.txt"
    inv.write_text(f"fw1 = {c1}\nfw2 = cmd:cat {c2}\n")

    rulesets = acquire.acquire_rulesets(acquire.load_inventory(str(inv)))
    assert [rs.firewall for rs in rulesets] == ["fw1", "fw2"]
    assert rulesets[0].rule_count() == 8 and rulesets[1].rule_count() == 3

    rc = cli.main(["fetch-acls", "--inventory", str(inv), "--out", str(tmp_path / "packed")])
    assert rc == 0
    err = capsys.readouterr().err
    assert "fw1 <-" in err and "fw2 <-" in err
    from ruleset_analysis_tpu.hostside import pack

    packed = pack.load_packed(str(tmp_path / "packed"))
    assert packed.n_rules == 11
    assert {fw for fw, _ in packed.acl_gid} == {"fw1", "fw2"}


def test_cli_empty_inventory(capsys):
    from ruleset_analysis_tpu import cli

    rc = cli.main(["fetch-acls", "--out", "/tmp/nope"])
    assert rc == 2
    assert "empty inventory" in capsys.readouterr().err
