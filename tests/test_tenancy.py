"""Multi-tenant serve: packing ladder, routing, bit-identity, isolation.

Pins the tenancy-plane contract (ISSUE 16; DESIGN §21):

- **Bit-identity**: an N-tenant packed serve process publishes, for
  every tenant, window and cumulative reports (registers, counts,
  unused-rule lists) bit-identical to N dedicated single-tenant serve
  processes fed the same lines — packing is invisible in the output.
- **Isolation**: hot-reloading one tenant mid-window migrates that
  tenant's counters only; every other tenant's ingest, window
  rotation, and published reports proceed untouched (their reports
  stay bit-identical to solo runs).
- **Routing** never guesses: explicit tag > listener binding > syslog
  hostname > manifest default, and unroutable lines are counted.
- **WAL compat**: the v2 record format round-trips the tenant key and
  pre-tenancy (v1) segments replay under the default tenant.
"""

import json
import os
import socket
import struct
import threading
import time
import urllib.request
import zlib

import numpy as np
import pytest

pytest.importorskip("jax")
import jax

from ruleset_analysis_tpu.config import AnalysisConfig, ServeConfig
from ruleset_analysis_tpu.errors import AnalysisError, InjectedFault
from ruleset_analysis_tpu.hostside import aclparse, pack, synth
from ruleset_analysis_tpu.parallel import mesh as mesh_lib
from ruleset_analysis_tpu.runtime import faults
from ruleset_analysis_tpu.runtime import wal as wal_mod
from ruleset_analysis_tpu.runtime.serve import ServeDriver, build_migration
from ruleset_analysis_tpu.runtime.tenancy import (
    DEFAULT_TENANT, TenantEngine, TenantLineQueue, TenantRouter, TenantTap,
    acl_rung, bucket_key, load_manifest, rule_rung, tenant_rung,
)
from ruleset_analysis_tpu.runtime.tenantserve import TenantServeDriver
from ruleset_analysis_tpu.ops.match import RULE_BLOCK

# ONE volatile-keys list (runtime/report.py): the registry auditor
# (verify/registry.py) flags any module keeping a private copy.
from ruleset_analysis_tpu.runtime.report import VOLATILE_TOTALS as VOLATILE


def image(obj) -> dict:
    """Report image for bit-identity comparisons: volatile totals,
    window metadata, and the tenant stamp (solo reports carry none)
    stripped; everything else must match exactly."""
    obj = json.loads(json.dumps(obj))
    for k in VOLATILE:
        obj["totals"].pop(k, None)
    obj["totals"].pop("window", None)
    obj["totals"].pop("tenant", None)
    return obj


RUN_CFG = dict(batch_size=128, prefetch_depth=0)


def make_tenant(td, i, n_lines=200, rules_per_acl=6):
    """One synthetic tenant: packed ruleset on disk + rendered lines."""
    cfg_text = synth.synth_config(
        n_acls=2, rules_per_acl=rules_per_acl + i, seed=10 + i,
        v6_fraction=0.0,
    )
    rs = aclparse.parse_asa_config(cfg_text, f"fw{i}")
    packed = pack.pack_rulesets([rs])
    prefix = os.path.join(str(td), f"rules{i}")
    pack.save_packed(packed, prefix)
    t = synth.synth_tuples(packed, n_lines, seed=20 + i)
    lines = synth.render_syslog(packed, t, seed=30 + i)
    return packed, prefix, lines


def write_manifest(td, rows) -> str:
    path = os.path.join(str(td), "manifest.json")
    with open(path, "w", encoding="utf-8") as f:
        json.dump({"tenants": rows}, f)
    return path


def start_tenant_serve(manifest, cfg, scfg, n_listeners):
    drv = TenantServeDriver(manifest, cfg, scfg)
    out: dict = {}

    def runner():
        try:
            out["summary"] = drv.run()
        except BaseException as e:  # surfaced by finish()
            out["error"] = e

    th = threading.Thread(target=runner)
    th.start()
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        if out.get("error"):
            break
        if drv.listeners.alive() == n_listeners and (
            scfg.http == "off" or drv.http_address
        ):
            break
        time.sleep(0.05)
    return drv, th, out


def finish(th, out, timeout=180):
    th.join(timeout=timeout)
    assert not th.is_alive(), "tenant serve hung"
    if "error" in out:
        raise out["error"]
    return out["summary"]


def run_solo(prefix, lines, serve_dir, window_lines, max_windows):
    """A dedicated single-tenant serve over the same lines: the ground
    truth every packed tenant's reports must match bit-for-bit."""
    scfg = ServeConfig(
        listen=("tcp:127.0.0.1:0",), window_lines=window_lines, ring=8,
        serve_dir=serve_dir, max_windows=max_windows, http="off",
        checkpoint_every_windows=0,
    )
    drv = ServeDriver(prefix, AnalysisConfig(**RUN_CFG), scfg)
    out: dict = {}

    def runner():
        try:
            out["summary"] = drv.run()
        except BaseException as e:
            out["error"] = e

    th = threading.Thread(target=runner)
    th.start()
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline and not (
        drv.listeners.listeners and drv.listeners.alive()
    ):
        time.sleep(0.05)
    s = socket.create_connection(tuple(drv.listeners.listeners[0].address))
    s.sendall(("\n".join(lines) + "\n").encode())
    s.close()
    th.join(timeout=180)
    assert not th.is_alive(), "solo serve hung"
    if "error" in out:
        raise out["error"]
    return out["summary"]


def wait_for(pred, timeout=60, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.05)
    raise AssertionError(f"timed out waiting for {msg}")


# ---------------------------------------------------------------------------
# Packing ladder + manifest + router (no device work).
# ---------------------------------------------------------------------------


def test_geometric_rungs():
    assert rule_rung(1) == RULE_BLOCK
    assert rule_rung(RULE_BLOCK) == RULE_BLOCK
    assert rule_rung(RULE_BLOCK + 1) == 2 * RULE_BLOCK
    assert rule_rung(4 * RULE_BLOCK - 1) == 4 * RULE_BLOCK
    assert acl_rung(1) == 1
    assert acl_rung(5) == 8
    assert tenant_rung(1) == 1
    assert tenant_rung(9) == 16
    # the ladder bounds padding waste at 2x per axis
    for n in (1, 3, 200, 4096):
        assert n <= rule_rung(n) < 2 * max(n, RULE_BLOCK)


def test_manifest_load_and_refusals(tmp_path):
    rows = [
        {"name": "acme", "ruleset": "/x/a", "listen": ["tcp:127.0.0.1:0"],
         "default": True},
        {"name": "globex", "ruleset": "/x/b", "hosts": ["fw-g1", "fw-g2"]},
    ]
    specs = load_manifest(write_manifest(tmp_path, rows))
    assert [s.name for s in specs] == ["acme", "globex"]
    assert specs[0].default and not specs[1].default
    assert specs[1].hosts == ("fw-g1", "fw-g2")

    with pytest.raises(AnalysisError, match="duplicate tenant name"):
        load_manifest(write_manifest(
            tmp_path, [{"name": "a", "ruleset": "x"},
                       {"name": "a", "ruleset": "y"}]
        ))
    with pytest.raises(AnalysisError, match="claimed by tenants"):
        load_manifest(write_manifest(
            tmp_path, [{"name": "a", "ruleset": "x", "hosts": ["h"]},
                       {"name": "b", "ruleset": "y", "hosts": ["h"]}]
        ))
    with pytest.raises(AnalysisError, match="default tenants"):
        load_manifest(write_manifest(
            tmp_path, [{"name": "a", "ruleset": "x", "default": True},
                       {"name": "b", "ruleset": "y", "default": True}]
        ))
    with pytest.raises(AnalysisError, match="invalid tenant name"):
        load_manifest(write_manifest(
            tmp_path, [{"name": "Bad/Name", "ruleset": "x"}]
        ))
    with pytest.raises(AnalysisError, match="non-empty 'tenants'"):
        load_manifest(write_manifest(tmp_path, []))


def test_router_precedence(tmp_path):
    specs = load_manifest(write_manifest(tmp_path, [
        {"name": "acme", "ruleset": "x", "default": True},
        {"name": "globex", "ruleset": "y", "hosts": ["fw-g1"]},
    ]))
    r = TenantRouter(specs)
    # explicit tag wins over everything, and is stripped
    assert r.route("@tenant globex %ASA-6: x", "acme") == (
        "globex", "%ASA-6: x"
    )
    # a tag naming an unknown tenant is unroutable, never guessed
    assert r.route("@tenant nosuch %ASA-6: x", "acme") == (
        None, "@tenant nosuch %ASA-6: x"
    )
    # listener binding beats the hostname map
    line_g1 = "Jan  1 00:00:00 fw-g1 %ASA-4-106023: Deny tcp src a dst b"
    assert r.route(line_g1, "acme")[0] == "acme"
    # hostname map beats the default
    assert r.route(line_g1, None)[0] == "globex"
    # default catches the rest
    assert r.route("Jan  1 00:00:00 fw-zzz %ASA: x", None)[0] == "acme"
    # no default -> unroutable, counted by the caller
    r2 = TenantRouter([s for s in specs if s.name == "globex"])
    assert r2.route("Jan  1 00:00:00 fw-zzz %ASA: x", None) == (
        None, "Jan  1 00:00:00 fw-zzz %ASA: x"
    )


def test_tenant_queue_and_tap():
    q = TenantLineQueue(capacity=3)
    TenantTap(q, "acme").put("a")
    TenantTap(q, None).put("b")
    assert q.pop_tagged(0.1)[::2] == ("a", "acme")
    got = q.pop_tagged(0.1)
    assert got[0] == "b" and got[2] is None
    # drop accounting is inherited from the single-tenant queue
    tap = TenantTap(q, "acme")
    assert all(tap.put(f"l{i}") for i in range(3))
    assert not tap.put("overflow")
    assert q.snapshot()["dropped"] == 1


# ---------------------------------------------------------------------------
# WAL record format v2 + v1 backward compatibility (no device work).
# ---------------------------------------------------------------------------


def test_wal_v2_tenant_roundtrip(tmp_path):
    w = wal_mod.WriteAheadLog(str(tmp_path))
    w.append("alpha", tenant="acme")
    w.append("beta")  # single-tenant callers never pass a tenant
    w.append("gamma", tenant="globex")
    w.close()
    got = list(wal_mod.WriteAheadLog(str(tmp_path)).replay(0))
    assert got == [
        (0, "alpha", "acme"),
        (1, "beta", DEFAULT_TENANT),
        (2, "gamma", "globex"),
    ]


def _write_v1_segment(path, lines, start_seq=0):
    """Hand-write a pre-tenancy (v1) WAL segment: payload IS the line."""
    with open(path, "wb") as f:
        f.write(struct.pack("<8sQ", wal_mod.MAGIC, start_seq))
        for line in lines:
            payload = line.encode()
            f.write(struct.pack(
                "<II", len(payload), zlib.crc32(payload) & 0xFFFFFFFF
            ) + payload)


def test_wal_v1_segments_replay_as_default_tenant(tmp_path):
    _write_v1_segment(
        str(tmp_path / f"seg-{0:020d}.wal"), ["old one", "old two"]
    )
    w = wal_mod.WriteAheadLog(str(tmp_path))
    # a tenant-aware process APPENDS v2 segments after the v1 spool;
    # the chain replays as one stream
    w.append("new line", tenant="acme")
    w.close()
    got = list(wal_mod.WriteAheadLog(str(tmp_path)).replay(0))
    assert got == [
        (0, "old one", DEFAULT_TENANT),
        (1, "old two", DEFAULT_TENANT),
        (2, "new line", "acme"),
    ]
    assert wal_mod.MAGIC != wal_mod.MAGIC2


# ---------------------------------------------------------------------------
# Engine: bucketing, restack preservation, restack chaos.
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def small_big(tmp_path_factory):
    td = tmp_path_factory.mktemp("tenancy-engine")
    small, small_prefix, _ = make_tenant(td, 0, n_lines=1)
    # > RULE_BLOCK v4 rows -> the next rule rung -> a different bucket
    cfg_text = synth.synth_config(
        n_acls=2, rules_per_acl=RULE_BLOCK, seed=99, v6_fraction=0.0
    )
    big = pack.pack_rulesets([aclparse.parse_asa_config(cfg_text, "fwbig")])
    return small, big


def engine_for(rulesets):
    mesh = mesh_lib.make_mesh(list(jax.devices())[:1], "data")
    return TenantEngine(mesh, AnalysisConfig(**RUN_CFG), rulesets)


def test_engine_buckets_by_rung(small_big):
    small, big = small_big
    assert bucket_key(small) != bucket_key(big)
    eng = engine_for({"a": small, "b": small, "c": big})
    d = eng.describe()
    assert d["tenants"]["a"]["bucket"] == d["tenants"]["b"]["bucket"]
    assert d["tenants"]["a"]["bucket"] != d["tenants"]["c"]["bucket"]
    # two same-bucket tenants share one stack (rung 2), distinct slots
    assert {eng.slot_of("a"), eng.slot_of("b")} == {0, 1}
    assert eng.bucket_of("a").t_pad == 2


def test_engine_restack_preserves_live_registers(small_big):
    small, big = small_big
    eng = engine_for({"a": small, "b": big})
    arrays = eng.host_arrays("a")
    for i, field in enumerate(sorted(arrays)):
        arrays[field] = (
            np.arange(arrays[field].size, dtype=np.uint32) + 7 * i
        ).reshape(arrays[field].shape)
    eng.set_arrays("a", arrays)
    # shrink b into a's bucket: a bucket move that grows a's stack
    # (t_pad 1 -> 2) through _restack, with a's registers live
    eng.reload_tenant("b", small)
    assert eng.bucket_of("a") is eng.bucket_of("b")
    after = eng.host_arrays("a")
    for field, want in arrays.items():
        np.testing.assert_array_equal(after[field], want, err_msg=field)


def test_engine_restack_fault_leaves_others_intact(small_big):
    small, big = small_big
    eng = engine_for({"a": small, "b": big})
    arrays = eng.host_arrays("a")
    arrays["counts_lo"] = arrays["counts_lo"] + 11
    eng.set_arrays("a", arrays)
    with faults.armed(faults.FaultPlan.parse("tenancy.reload.restack@1")):
        with pytest.raises(InjectedFault):
            eng.reload_tenant("b", small)
    # the mid-restack fault left a's stack and registers fully intact
    after = eng.host_arrays("a")
    np.testing.assert_array_equal(after["counts_lo"], arrays["counts_lo"])
    assert eng.bucket_of("a").t_pad == 1


def test_engine_refuses_v6_rows():
    cfg_text = synth.synth_config(
        n_acls=2, rules_per_acl=6, seed=3, v6_fraction=0.5
    )
    packed6 = pack.pack_rulesets([aclparse.parse_asa_config(cfg_text, "f6")])
    with pytest.raises(AnalysisError, match="IPv6"):
        engine_for({"a": packed6})


# ---------------------------------------------------------------------------
# The tentpole property: N-tenant packed serve == N solo serves.
# ---------------------------------------------------------------------------


def test_packed_serve_bit_identical_to_solo_runs(tmp_path):
    """Two tenants interleaved line-by-line through one process: every
    window report, cumulative report, and unused-rule list must be
    bit-identical to a dedicated single-tenant serve per tenant —
    plus the fairness/labeled-metrics surface while it runs."""
    tenants = {f"t{i}": make_tenant(tmp_path, i) for i in range(2)}
    manifest = write_manifest(tmp_path, [
        {"name": n, "ruleset": p, "listen": ["tcp:127.0.0.1:0"]}
        for n, (_, p, _) in sorted(tenants.items())
    ])
    scfg = ServeConfig(
        listen=(), window_lines=100, ring=8,
        serve_dir=os.path.join(str(tmp_path), "serve"),
        http="127.0.0.1:0", checkpoint_every_windows=0,
    )
    drv, th, out = start_tenant_serve(
        manifest, AnalysisConfig(**RUN_CFG), scfg, n_listeners=2
    )
    try:
        by_tenant = {
            ln.q.tenant: ln.address for ln in drv.listeners.listeners
        }
        socks = {
            n: socket.create_connection(tuple(by_tenant[n]))
            for n in tenants
        }
        for i in range(200):  # strict interleave, one line at a time
            for n in sorted(tenants):
                socks[n].sendall((tenants[n][2][i] + "\n").encode())
        for s in socks.values():
            s.close()
        wait_for(
            lambda: drv.windows_published >= 4, timeout=120,
            msg="4 packed windows",
        )

        # fairness + per-tenant SLO surface, JSON and labeled prom
        host, port = drv.http_address
        with urllib.request.urlopen(
            f"http://{host}:{port}/metrics", timeout=10
        ) as r:
            m = json.load(r)
        assert m["tenants_hosted"] == 2
        assert set(m["tenants"]) == {"t0", "t1"}
        for g in m["tenants"].values():
            assert g["windows_published"] == 2
            assert "latency_ingest_to_publish_p99_sec" in g
        shares = m["fairness"]["shares"]
        assert abs(shares["t0"] - 0.5) < 0.01  # interleaved feed: ~equal
        with urllib.request.urlopen(
            f"http://{host}:{port}/metrics?format=prom", timeout=10
        ) as r:
            prom = r.read().decode()
        for n in ("t0", "t1"):
            assert f'ra_serve_tenant_windows_published{{tenant="{n}"}}' in prom
            assert (
                f'ra_serve_tenant_ingest_to_publish_seconds_count'
                f'{{tenant="{n}"}}'
            ) in prom
        with urllib.request.urlopen(
            f"http://{host}:{port}/tenants", timeout=10
        ) as r:
            tob = json.load(r)
        assert set(tob["engine"]["tenants"]) == {"t0", "t1"}
    finally:
        drv.stop()
    summary = finish(th, out)
    assert summary["windows_published"] == 4
    assert summary["lines_unrouted"] == 0

    for n, (_, prefix, lines) in sorted(tenants.items()):
        solo_dir = os.path.join(str(tmp_path), f"solo-{n}")
        run_solo(prefix, lines, solo_dir, window_lines=100, max_windows=2)
        for w in range(2):
            with open(os.path.join(solo_dir, f"window-{w:06d}.json")) as f:
                solo = json.load(f)
            with open(os.path.join(
                scfg.serve_dir, "t", n, f"window-{w:06d}.json"
            )) as f:
                packed_rep = json.load(f)
            assert image(solo) == image(packed_rep), f"{n} window {w}"
            # the unused-rule report is the paper's headline output:
            # spell out that packing didn't change it
            assert solo["unused"] == packed_rep["unused"], f"{n} window {w}"
        with open(os.path.join(solo_dir, "cumulative.json")) as f:
            solo_c = json.load(f)
        with open(os.path.join(
            scfg.serve_dir, "t", n, "cumulative.json"
        )) as f:
            packed_c = json.load(f)
        assert image(solo_c) == image(packed_c), f"{n} cumulative"


def test_hot_reload_one_tenant_mid_window_leaves_others_alone(tmp_path):
    """Reload t0 (renumbered ruleset) in the middle of everyone's
    window: t0 migrates; t1's rotation cadence and its published
    reports stay bit-identical to a solo run that never saw a reload."""
    tenants = {f"t{i}": make_tenant(tmp_path, i) for i in range(2)}
    manifest = write_manifest(tmp_path, [
        {"name": n, "ruleset": p, "listen": ["tcp:127.0.0.1:0"]}
        for n, (_, p, _) in sorted(tenants.items())
    ])
    scfg = ServeConfig(
        listen=(), window_lines=100, ring=8,
        serve_dir=os.path.join(str(tmp_path), "serve"),
        http="off", checkpoint_every_windows=0, reload_watch=False,
    )
    drv, th, out = start_tenant_serve(
        manifest, AnalysisConfig(**RUN_CFG), scfg, n_listeners=2
    )
    try:
        by_tenant = {
            ln.q.tenant: ln.address for ln in drv.listeners.listeners
        }
        socks = {
            n: socket.create_connection(tuple(by_tenant[n]))
            for n in tenants
        }
        # half a window everywhere, then reload ONLY t0 mid-window
        for i in range(50):
            for n in sorted(tenants):
                socks[n].sendall((tenants[n][2][i] + "\n").encode())
        wait_for(
            lambda: all(
                h["routed_total"] >= 50
                for h in drv.health()["tenants"].values()
            ),
            msg="both lanes mid-window",
        )
        # renumbered ruleset for t0: same rule TEXTS in reversed
        # per-ACL order, so the migration map is a real permutation
        # (rule identity is firewall/ACL/text), never quarantine
        packed0, prefix0, _ = tenants["t0"]
        cfg_text = synth.synth_config(
            n_acls=2, rules_per_acl=6, seed=10, v6_fraction=0.0
        )
        cfg_lines = cfg_text.splitlines()
        by_acl: dict = {}
        for idx, l in enumerate(cfg_lines):
            if l.startswith("access-list "):
                by_acl.setdefault(l.split()[1], []).append(idx)
        for idxs in by_acl.values():
            vals = [cfg_lines[i] for i in idxs]
            for i, v in zip(idxs, reversed(vals)):
                cfg_lines[i] = v
        rs = aclparse.parse_asa_config("\n".join(cfg_lines) + "\n", "fw0")
        repacked = pack.pack_rulesets([rs])
        mig = build_migration(packed0, repacked, tenant="t0")
        assert mig.tenant == "t0" and not mig.identity
        pack.save_packed(repacked, prefix0)
        drv.request_reload("t0")
        wait_for(
            lambda: drv.health()["tenants"]["t0"]["reloads"] == 1,
            msg="t0 reload",
        )
        assert drv.health()["tenants"]["t1"]["reloads"] == 0
        # t1 was NOT flushed or rotated by t0's reload
        t1 = drv.health()["tenants"]["t1"]
        assert t1["windows_published"] == 0
        assert t1["current_window"]["id"] == 0
        # finish both windows; both lanes rotate on their own counts
        for i in range(50, 200):
            for n in sorted(tenants):
                socks[n].sendall((tenants[n][2][i] + "\n").encode())
        for s in socks.values():
            s.close()
        wait_for(
            lambda: drv.windows_published >= 4, timeout=120,
            msg="4 windows after reload",
        )
    finally:
        drv.stop()
    summary = finish(th, out)
    assert summary["tenants"]["t0"]["reloads"] == 1
    assert summary["tenants"]["t1"]["reloads"] == 0
    assert summary["tenants"]["t1"]["windows_published"] == 2

    # t1 never saw the reload: bit-identical to a solo run
    _, prefix1, lines1 = tenants["t1"]
    solo_dir = os.path.join(str(tmp_path), "solo-t1")
    run_solo(prefix1, lines1, solo_dir, window_lines=100, max_windows=2)
    for w in range(2):
        with open(os.path.join(solo_dir, f"window-{w:06d}.json")) as f:
            solo = json.load(f)
        with open(os.path.join(
            scfg.serve_dir, "t", "t1", f"window-{w:06d}.json"
        )) as f:
            packed_rep = json.load(f)
        assert image(solo) == image(packed_rep), f"t1 window {w}"
    # t0 migrated under the reversed numbering with zero quarantine
    # (every old rule maps by identity to a new slot)
    assert summary["tenants"]["t0"]["quarantine_hits"] == 0
    assert summary["tenants"]["t0"]["windows_published"] == 2


@pytest.mark.slow
def test_sixteen_tenants_one_process(tmp_path):
    """ISSUE 16 acceptance: one serve process hosting 16 tenants, each
    publishing a report bit-identical to its solo run."""
    n_t = 16
    tenants = {
        f"t{i:02d}": make_tenant(tmp_path, i, n_lines=100)
        for i in range(n_t)
    }
    manifest = write_manifest(tmp_path, [
        {"name": n, "ruleset": p, "listen": ["tcp:127.0.0.1:0"]}
        for n, (_, p, _) in sorted(tenants.items())
    ])
    scfg = ServeConfig(
        listen=(), window_lines=100, ring=4,
        serve_dir=os.path.join(str(tmp_path), "serve"),
        http="off", checkpoint_every_windows=0,
    )
    drv, th, out = start_tenant_serve(
        manifest, AnalysisConfig(**RUN_CFG), scfg, n_listeners=n_t
    )
    try:
        by_tenant = {
            ln.q.tenant: ln.address for ln in drv.listeners.listeners
        }
        socks = {
            n: socket.create_connection(tuple(by_tenant[n]))
            for n in tenants
        }
        for i in range(100):
            for n in sorted(tenants):
                socks[n].sendall((tenants[n][2][i] + "\n").encode())
        for s in socks.values():
            s.close()
        wait_for(
            lambda: drv.windows_published >= n_t, timeout=300,
            msg="16 packed windows",
        )
        assert drv.metrics_gauges()["tenants_hosted"] == n_t
    finally:
        drv.stop()
    summary = finish(th, out, timeout=300)
    assert summary["windows_published"] == n_t
    assert summary["lines_unrouted"] == 0
    for n, (_, prefix, lines) in sorted(tenants.items()):
        solo_dir = os.path.join(str(tmp_path), f"solo-{n}")
        run_solo(prefix, lines, solo_dir, window_lines=100, max_windows=1)
        with open(os.path.join(solo_dir, "window-000000.json")) as f:
            solo = json.load(f)
        with open(os.path.join(
            scfg.serve_dir, "t", n, "window-000000.json"
        )) as f:
            packed_rep = json.load(f)
        assert image(solo) == image(packed_rep), n
