"""Pairwise rule-relation kernel units (ops/overlap.py, ISSUE 12).

Pins the kernel's contract against an independent numpy twin: per-pair
covered/overlap semantics, padding/ACL isolation, and tiled-grid ==
single-tile equivalence (the property the analyzer's O(R²) sharding
rests on).
"""

import numpy as np
import pytest

pytest.importorskip("jax")

from ruleset_analysis_tpu.hostside.pack import NO_ACL, R_ACL, RULE_COLS
from ruleset_analysis_tpu.ops.overlap import (
    PAIR_TILE,
    iter_pair_tiles,
    pair_relations,
    pair_relations_np,
    relation_tile,
)

_FIELD_LOHI = [(1, 2), (3, 4), (5, 6), (7, 8), (9, 10)]


def random_rules(rng, r, n_acls=2, pad=0):
    """Random valid rule tensor: lo <= hi everywhere, some 'any' fields."""
    rules = np.zeros((r + pad, RULE_COLS), dtype=np.uint32)
    rules[:, R_ACL] = NO_ACL
    for i in range(r):
        rules[i, R_ACL] = rng.integers(0, n_acls)
        rules[i, 11] = i  # key
        for lo, hi in _FIELD_LOHI:
            if rng.random() < 0.25:  # any
                a, b = 0, np.iinfo(np.uint32).max
            else:
                a, b = sorted(rng.integers(0, 100, size=2))
            rules[i, lo], rules[i, hi] = a, b
    return rules


def test_relation_tile_matches_numpy_twin():
    rng = np.random.default_rng(0)
    rules = random_rules(rng, 40, n_acls=3, pad=8)
    cov_np, ovl_np = pair_relations_np(rules)
    cov, ovl = relation_tile(rules, rules)
    np.testing.assert_array_equal(np.asarray(cov), cov_np)
    np.testing.assert_array_equal(np.asarray(ovl), ovl_np)
    # covered is a sub-relation of overlap (boxes are non-empty)
    assert not (cov_np & ~ovl_np).any()
    # padding rows relate to nothing, in either direction
    assert not cov_np[40:].any() and not cov_np[:, 40:].any()
    assert not ovl_np[40:].any() and not ovl_np[:, 40:].any()


def test_cross_acl_rows_never_relate():
    rng = np.random.default_rng(1)
    rules = random_rules(rng, 20, n_acls=1)
    other = rules.copy()
    other[:, R_ACL] = 1  # identical boxes, different ACL
    both = np.concatenate([rules, other])
    _, ovl = pair_relations(both)
    assert not ovl[:20, 20:].any() and not ovl[20:, :20].any()
    # within an ACL every row overlaps itself
    assert ovl[np.arange(20), np.arange(20)].all()


def test_known_relations():
    def row(acl, plo, phi, slo, shi):
        out = [acl, plo, phi, slo, shi, 0, 65535, 0, 0xFFFFFFFF, 0, 65535, 0]
        return out

    rules = np.asarray(
        [
            row(0, 6, 6, 10, 20),  # 0: tcp src 10-20
            row(0, 6, 6, 0, 100),  # 1: tcp src 0-100 (covers 0)
            row(0, 6, 6, 15, 30),  # 2: tcp src 15-30 (partial vs 0)
            row(0, 17, 17, 10, 20),  # 3: udp — proto-disjoint from all
        ],
        dtype=np.uint32,
    )
    cov, ovl = pair_relations(rules)
    assert cov[0, 1] and not cov[1, 0]  # 1 covers 0, not vice versa
    assert ovl[0, 2] and not cov[0, 2] and not cov[2, 0]  # partial
    assert not ovl[0, 3] and not ovl[3, 0]  # disjoint on proto
    assert cov[0, 0]  # a row covers itself


@pytest.mark.parametrize("tile", [4, 16])
def test_tiled_grid_equals_single_tile(tile):
    rng = np.random.default_rng(2)
    rules = random_rules(rng, 37, n_acls=2)  # not a tile multiple
    cov1, ovl1 = pair_relations(rules)  # one PAIR_TILE tile
    covt, ovlt = pair_relations(rules, tile=tile)
    np.testing.assert_array_equal(cov1, covt)
    np.testing.assert_array_equal(ovl1, ovlt)


def test_tile_grid_iterator_covers_every_pair_once():
    seen = np.zeros((37, 37), dtype=int)
    for i0, i1, j0, j1 in iter_pair_tiles(37, 16):
        seen[i0:i1, j0:j1] += 1
    assert (seen == 1).all()


def test_on_tile_seam_fires_per_tile_and_devices_shard():
    import jax

    rng = np.random.default_rng(3)
    rules = random_rules(rng, 33, n_acls=2)
    calls = []
    cov, ovl = pair_relations(
        rules, tile=16, devices=list(jax.devices()),
        on_tile=lambda i0, j0: calls.append((i0, j0)),
    )
    assert len(calls) == 9  # ceil(33/16)^2 tiles
    c2, o2 = pair_relations(rules, tile=PAIR_TILE)
    np.testing.assert_array_equal(cov, c2)
    np.testing.assert_array_equal(ovl, o2)


def test_lower_only_skips_upper_triangle_tiles_losslessly():
    """The analyzer's half-grid mode: tiles with j0 > i0 are skipped and
    their entries stay False; everything at or below the tile diagonal
    is identical to the full grid."""
    rng = np.random.default_rng(4)
    rules = random_rules(rng, 33, n_acls=2)
    calls = []
    cov, ovl = pair_relations(
        rules, tile=16, lower_only=True,
        on_tile=lambda i0, j0: calls.append((i0, j0)),
    )
    assert all(j0 <= i0 for i0, j0 in calls)
    assert len(calls) == 6  # lower-triangle tiles of a 3x3 grid
    full_cov, full_ovl = pair_relations(rules, tile=16)
    for a in range(33):
        for b in range(33):
            if b // 16 <= a // 16:  # tile at-or-below the diagonal
                assert cov[a, b] == full_cov[a, b]
                assert ovl[a, b] == full_ovl[a, b]
            else:
                assert not cov[a, b] and not ovl[a, b]


def test_empty_and_single_row():
    empty = np.zeros((0, RULE_COLS), dtype=np.uint32)
    cov, ovl = pair_relations(empty)
    assert cov.shape == (0, 0)
    one = np.zeros((1, RULE_COLS), dtype=np.uint32)
    one[0, 2] = 255  # proto any-ish; acl 0, all other ranges [0, 0]
    cov, ovl = pair_relations(one)
    assert cov[0, 0] and ovl[0, 0]
