"""Device attribution plane (ISSUE 8): scopes, capture windows, diffs.

Assertion tiers:

- **semantic naming** — every ops stage traces under its ``ra.*``
  named scope: the scope token is present in the optimized HLO text of
  a tiny jit of each stage, and the full parallel step program's static
  stage table covers the whole taxonomy;
- **capture windows** — ``devprof.arm`` + a driver run produce a
  well-formed ``devprof.json`` across sync/prefetch x text/wire x
  v4/v6: the requested number of dispatches profiled, >= 90% of
  measured device time attributed to named stages, the unattributed
  remainder reported explicitly, and the report BIT-IDENTICAL to the
  disarmed run;
- **trace diffs** — ``tools/trace_diff.py`` on two captures emits the
  per-stage delta table and detects fusion-boundary changes;
- **failure model** — ``--devprof-out`` under ``--distributed`` is a
  typed CLI refusal (single-controller capture only), and the
  ``devprof.capture`` fault site (4 seeded schedules, start + stop
  seams, sync + prefetch) ends every run in a typed abort or a clean
  no-trace run with a bit-identical report — never a hang, never a
  half-written summary.
"""

import json
import os
import sys

import pytest

pytest.importorskip("jax")

import jax
import jax.numpy as jnp
import numpy as np

from ruleset_analysis_tpu.config import AnalysisConfig, DevprofConfig, SketchConfig
from ruleset_analysis_tpu.errors import InjectedFault
from ruleset_analysis_tpu.hostside import aclparse, pack, synth
from ruleset_analysis_tpu.hostside import wire as wire_mod
from ruleset_analysis_tpu.runtime import devprof, obs
from ruleset_analysis_tpu.runtime.stream import (
    run_stream_file,
    run_stream_wire,
)

sys.path.insert(
    0,
    os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tools"),
)
import trace_diff  # noqa: E402

# ONE volatile-keys list (runtime/report.py): the registry auditor
# (verify/registry.py) flags any module keeping a private copy.
from ruleset_analysis_tpu.runtime.report import VOLATILE_TOTALS as VOLATILE


def report_image(rep) -> dict:
    j = json.loads(rep.to_json())
    for k in VOLATILE:
        j["totals"].pop(k, None)
    return j


@pytest.fixture(autouse=True)
def _devprof_clean():
    """Every test starts and ends disarmed, with no dangling profiler."""
    devprof.shutdown()
    obs._reset_for_tests()
    yield
    devprof.shutdown()
    obs._reset_for_tests()


@pytest.fixture(scope="module")
def corpus(tmp_path_factory):
    # DELIBERATELY the same ruleset + sketch geometry as test_obs's
    # corpus (synth seed 7, 3 ACLs x 8 rules, batch 512, cms 1<<10 x 2,
    # hll_p 6): the specialized step jit is keyed on the ruleset VALUE,
    # so the two suites share one XLA compile in a tier-1 process
    # instead of paying it twice — the 870 s gate is a hard budget.
    td = tmp_path_factory.mktemp("devprof")
    cfg_text = synth.synth_config(n_acls=3, rules_per_acl=8, seed=7)
    rs = aclparse.parse_asa_config(cfg_text, "fw1")
    packed = pack.pack_rulesets([rs])
    tuples = synth.synth_tuples(packed, 2600, seed=18)
    lines = synth.render_syslog(packed, tuples, seed=19)
    log = str(td / "dp.log")
    with open(log, "w", encoding="utf-8") as f:
        f.write("\n".join(lines) + "\n")
    wirep = str(td / "dp.rawire")
    wire_mod.convert_logs(packed, [log], wirep, block_rows=512)
    prefix = str(td / "packed")
    pack.save_packed(packed, prefix)
    return packed, prefix, log, wirep


@pytest.fixture(scope="module")
def wire_baselines(corpus):
    """Fault-free disarmed reports per prefetch depth (identity anchors).

    Computed once per module: every identity/chaos assertion below
    compares against these instead of re-running its own baseline —
    tier-1 wall time is a hard budget (ROADMAP).
    """
    packed, _prefix, _log, wirep = corpus
    return {
        depth: run_stream_wire(packed, [wirep], _cfg(depth=depth))
        for depth in (0, 2)
    }


@pytest.fixture(scope="module")
def corpus6(tmp_path_factory):
    """Mixed v4+v6 corpus so the capture sees the step.v6 program too."""
    td = tmp_path_factory.mktemp("devprof6")
    cfg_text = synth.synth_config(
        n_acls=2, rules_per_acl=8, seed=27, v6_fraction=0.4
    )
    rs = aclparse.parse_asa_config(cfg_text, "fw1")
    packed = pack.pack_rulesets([rs])
    t4 = synth.synth_tuples(packed, 1400, seed=28)
    lines = synth.render_syslog(packed, t4, seed=29)
    t6 = synth.synth_tuples6(packed, 1000, seed=30)
    lines += synth.render_syslog6(packed, t6, seed=31)
    log = str(td / "dp6.log")
    with open(log, "w", encoding="utf-8") as f:
        f.write("\n".join(lines) + "\n")
    return packed, log


def _cfg(depth=0, **kw):
    # geometry matches test_obs._cfg — see the corpus fixture note
    return AnalysisConfig(
        batch_size=512,
        sketch=SketchConfig(cms_width=1 << 10, cms_depth=2, hll_p=6),
        prefetch_depth=depth,
        stall_timeout_sec=5.0,
        **kw,
    )


# ---------------------------------------------------------------------------
# Semantic naming: scopes present in lowered (optimized) HLO text.
# ---------------------------------------------------------------------------


def _compiled_text(fn, *args) -> str:
    return jax.jit(fn).lower(*args).compile().as_text()


def test_every_ops_stage_scoped_in_hlo():
    """Each register-update stage's scope survives into optimized HLO."""
    from ruleset_analysis_tpu.models import pipeline
    from ruleset_analysis_tpu.ops import cms as cms_ops
    from ruleset_analysis_tpu.ops import counts as count_ops
    from ruleset_analysis_tpu.ops import hll as hll_ops
    from ruleset_analysis_tpu.ops import topk as topk_ops
    from ruleset_analysis_tpu.ops.match import match_keys
    from ruleset_analysis_tpu.ops.match6 import fold_src32, match_keys6

    b = 128
    keys = jnp.zeros(b, jnp.uint32)
    w = jnp.ones(b, jnp.uint32)
    src = jnp.arange(b, dtype=jnp.uint32)

    # counts: every formulation carries the same stage label
    for impl, fn in count_ops.SEGMENT_COUNTS_IMPLS.items():
        assert "ra.counts" in _compiled_text(
            lambda k, v: fn(k, v, 16), keys, w
        ), f"counts impl {impl} lost its scope"
    assert "ra.counts" in _compiled_text(
        count_ops.add64, jnp.zeros(16, jnp.uint32), jnp.zeros(16, jnp.uint32),
        jnp.ones(16, jnp.uint32),
    )
    assert "ra.cms" in _compiled_text(
        lambda k, v: cms_ops.cms_update(cms_ops.cms_init(256, 2), k, v), keys, w
    )
    assert "ra.hll" in _compiled_text(
        lambda k, s, v: hll_ops.hll_update(hll_ops.hll_init(16, 4), k, s, v),
        keys, src, w,
    )
    txt = _compiled_text(
        lambda a, s, v: topk_ops.talker_chunk_update(
            cms_ops.cms_init(256, 2), a, s, v, 8
        ),
        keys, src, w,
    )
    assert "ra.talk" in txt and "ra.topk" in txt

    # match kernels (flat v4 + v6) and the wire unpack / weight plane
    rules = jnp.zeros((4, pack.RULE_COLS), jnp.uint32)
    deny = jnp.zeros(4, jnp.uint32)
    cols = {
        n: jnp.zeros(b, jnp.uint32)
        for n in ("acl", "proto", "src", "sport", "dst", "dport")
    }
    assert "ra.match" in _compiled_text(
        lambda c: match_keys(c, rules, deny), cols
    )
    rules6 = jnp.zeros((4, pack.RULE6_COLS), jnp.uint32)
    cols6 = {
        n: jnp.zeros(b, jnp.uint32)
        for n in (
            "acl", "proto", "sport", "dport",
            *(f"src{i}" for i in range(4)), *(f"dst{i}" for i in range(4)),
        )
    }
    txt6 = _compiled_text(lambda c: match_keys6(c, rules6, deny), cols6)
    assert "ra.match6" in txt6
    assert "ra.match6" in _compiled_text(fold_src32, cols6)
    wire_batch = jnp.zeros((pack.WIREW_COLS, b), jnp.uint32)
    assert "ra.unpack" in _compiled_text(
        lambda x: pipeline.batch_cols(x)[1], wire_batch
    )


def test_scope_classifier_shared():
    assert devprof.scope_of("jit(f)/jit(main)/ra.counts/scatter-add") == "ra.counts"
    # outermost wins: the talker plane owns its inner CMS helper
    assert devprof.scope_of("jit(f)/ra.talk/ra.cms/scatter") == "ra.talk"
    assert devprof.scope_of("jit(f)/jit(main)/broadcast") is None
    assert devprof.classify_event_name("fusion.5") is None
    assert devprof.classify_event_name(
        "fusion.5", {"long_name": "jit(step)/ra.hll/scatter-max"}
    ) == "ra.hll"
    assert devprof.classify_event_name("ra.merge/all-reduce.3") == "ra.merge"


def test_parse_hlo_module_index_and_fusions():
    text = """\
HloModule jit_step, entry_computation_layout={()->()}

%fused_computation.1 (p0: u32[8]) -> u32[8] {
  %p0 = u32[8]{0} parameter(0)
  %mul.1 = u32[8]{0} multiply(%p0, %p0), metadata={op_name="jit(f)/ra.match/mul"}
  ROOT %add.2 = u32[8]{0} add(%mul.1, %p0), metadata={op_name="jit(f)/ra.counts/add"}
}

ENTRY %main.9 (a: u32[8]) -> u32[8] {
  %a = u32[8]{0} parameter(0)
  %fusion.1 = u32[8]{0} fusion(u32[8]{0} %a), kind=kLoop, calls=%fused_computation.1, metadata={op_name="jit(f)/ra.counts/add"}
  ROOT %copy.1 = u32[8]{0} copy(u32[8]{0} %fusion.1)
}
"""
    mod = devprof.parse_hlo_module(text)
    assert mod["entry"]["fusion.1"]["scope"] == "ra.counts"
    assert mod["entry"]["fusion.1"]["bytes"] == 32
    assert mod["entry"]["copy.1"]["scope"] is None
    assert "mul.1" in mod["nested"] and "mul.1" not in mod["entry"]
    [fu] = mod["fusions"]
    assert fu["name"] == "fusion.1"
    assert fu["stages"] == ["ra.counts", "ra.match"]  # cross-stage fusion


# ---------------------------------------------------------------------------
# Capture windows across driver x input x family.
# ---------------------------------------------------------------------------


def _assert_capture_well_formed(summary: dict, steps: int) -> None:
    assert summary["steps_profiled"] == steps
    assert 0.0 <= summary["attributed_frac"] <= 1.0
    # the acceptance bar: >= 90% of device-step time attributed to named
    # stages, remainder reported explicitly
    assert summary["attributed_frac"] >= 0.9, summary
    assert summary["unattributed"]["device_us"] >= 0.0
    assert summary["stages"], "no stages attributed"
    assert abs(
        sum(st["pct"] for st in summary["stages"].values())
        + summary["unattributed"]["pct"]
        - 100.0
    ) < 0.1
    for prog in summary["programs"].values():
        assert prog["dispatches"] >= 1
        assert prog["hlo_instructions"] > 0
        assert prog["stages_static"]
        assert prog["flops"] > 0
        assert prog["bytes_accessed"] > 0


def test_capture_sync_text_v4(corpus, tmp_path):
    packed, _prefix, log, _wirep = corpus
    devprof.arm(str(tmp_path / "dp"), steps=2, warmup=1)
    rep = run_stream_file(packed, [log], _cfg(depth=0), native=False)
    dp = rep.totals["devprof"]
    _assert_capture_well_formed(dp, 2)
    # the full step program exercises the whole v4 stage taxonomy
    static = dp["programs"]["step.flat"]["stages_static"]
    for stage in ("ra.unpack", "ra.match", "ra.counts", "ra.cms",
                  "ra.hll", "ra.talk", "ra.topk", "ra.merge"):
        assert stage in static, f"{stage} missing from the step program"
    # the summary also landed on disk, identically
    disk = json.load(open(tmp_path / "dp" / "devprof.json"))
    assert disk["steps_profiled"] == dp["steps_profiled"]
    assert disk["stages"].keys() == dp["stages"].keys()


def test_capture_prefetch_wire(corpus, tmp_path):
    packed, _prefix, _log, wirep = corpus
    devprof.arm(str(tmp_path / "dp"), steps=2, warmup=1)
    rep = run_stream_wire(packed, [wirep], _cfg(depth=2))
    dp = rep.totals["devprof"]
    _assert_capture_well_formed(dp, 2)
    assert dp["programs"]["step.flat"]["dispatches"] == 2


def test_capture_v6_program(corpus6, tmp_path):
    packed, log = corpus6
    # warmup 0 + a window longer than the stream: capture EVERY dispatch
    # of both family programs (v6 chunk cadence is data-dependent)
    devprof.arm(str(tmp_path / "dp"), steps=64, warmup=0)
    rep = run_stream_file(packed, [log], _cfg(depth=0), native=False)
    dp = rep.totals["devprof"]
    assert dp["steps_profiled"] >= 4
    # slightly below the 0.9 acceptance bar the warmed captures assert:
    # warmup=0 (deliberate here — v6 chunk cadence is data-dependent)
    # profiles each program's FIRST dispatch, whose compile-adjacent
    # thunk events can land unattributed under host load (observed
    # 0.896 on a contended container vs ~0.95 idle)
    assert dp["attributed_frac"] >= 0.85
    # both family programs were captured and the v6 kernel attributed
    assert "step.v6" in dp["programs"]
    assert "ra.match6" in dp["programs"]["step.v6"]["stages_static"]


def test_capture_window_shorter_than_stream(corpus, tmp_path):
    """A stream ending before the window opens reports itself, cleanly."""
    packed, _prefix, log, _wirep = corpus
    devprof.arm(str(tmp_path / "dp"), steps=4, warmup=100)
    rep = run_stream_file(packed, [log], _cfg(depth=0), native=False)
    dp = rep.totals["devprof"]
    assert dp["steps_profiled"] == 0
    assert "note" in dp and "capture window" in dp["note"]


def test_report_bit_identical_armed_vs_disarmed(corpus, wire_baselines, tmp_path):
    packed, _prefix, _log, wirep = corpus
    base = wire_baselines[2]
    assert "devprof" not in base.totals
    devprof.arm(str(tmp_path / "dp"), steps=2, warmup=1)
    armed = run_stream_wire(packed, [wirep], _cfg(depth=2))
    assert "devprof" in armed.totals
    assert report_image(base) == report_image(armed)


# ---------------------------------------------------------------------------
# Trace diffs.
# ---------------------------------------------------------------------------


def _synthetic_capture(step_us: dict, fusion_stages: list, steps=4) -> dict:
    total = float(sum(step_us.values())) * steps
    return {
        "requested_steps": steps,
        "warmup": 1,
        "steps_profiled": steps,
        "backend": "cpu",
        "devices": 8,
        "device_us_total": total,
        "attributed_frac": 1.0,
        "unattributed": {"device_us": 0.0, "pct": 0.0},
        "stages": {
            s: {
                "device_us": us * steps,
                "pct": round(100.0 * us * steps / total, 2),
                "events": 10,
            }
            for s, us in step_us.items()
        },
        "programs": {
            "step.flat": {
                "dispatches": steps,
                "hlo_instructions": 50,
                "stages_static": {},
                "fusions": [{"name": f"fusion.{i}", "stages": st}
                            for i, st in enumerate(fusion_stages)],
                "flops": 1e6,
                "bytes_accessed": 1e6,
            }
        },
        "cross_stage_fusions": [],
    }


def test_trace_diff_delta_table_and_boundaries(tmp_path):
    a = _synthetic_capture(
        {"ra.counts": 900.0, "ra.hll": 500.0, "ra.match": 10.0},
        [["ra.counts"], ["ra.match", "ra.unpack"]],
    )
    b = _synthetic_capture(
        {"ra.counts": 90.0, "ra.hll": 510.0, "ra.match": 10.0, "ra.merge": 40.0},
        [["ra.counts", "ra.hll"], ["ra.match", "ra.unpack"]],
        steps=8,
    )
    pa, pb = tmp_path / "a", tmp_path / "b"
    pa.mkdir(), pb.mkdir()
    json.dump(a, open(pa / "devprof.json", "w"))
    json.dump(b, open(pb / "devprof.json", "w"))
    d = trace_diff.diff_captures(
        trace_diff.load_capture(str(pa)), trace_diff.load_capture(str(pb))
    )
    rows = {r["stage"]: r for r in d["stages"]}
    # normalized per step despite different window lengths
    assert rows["ra.counts"]["A_us_per_step"] == 900.0
    assert rows["ra.counts"]["B_us_per_step"] == 90.0
    assert rows["ra.counts"]["ratio"] == 0.1
    assert rows["ra.merge"]["ratio"] is None  # stage new in B
    assert rows["ra.match"]["ratio"] == 1.0
    # fusion-boundary change: counts fused alone in A, with hll in B
    assert d["fusion_boundaries_changed"]
    ch = d["fusion_boundary_changes"]["step.flat"]
    assert ["ra.counts", "x1"] in ch["only_A"]
    assert ["ra.counts", "ra.hll", "x1"] in ch["only_B"]
    # the renderer runs over the machine form
    text = trace_diff.render(d)
    assert "ra.counts" in text and "fusion boundaries CHANGED" in text
    # identical captures: no boundary noise
    d_same = trace_diff.diff_captures(a, a)
    assert not d_same["fusion_boundaries_changed"]
    assert all(r["ratio"] == 1.0 for r in d_same["stages"])


def test_trace_diff_csv_mode(tmp_path, capsys):
    a = _synthetic_capture(
        {"ra.counts": 900.0, "ra.hll": 500.0},
        [["ra.counts"]],
    )
    b = _synthetic_capture(
        {"ra.counts": 90.0, "ra.hll": 510.0},
        [["ra.counts", "ra.hll"]],
        steps=8,
    )
    d = trace_diff.diff_captures(a, b)
    csv_text = trace_diff.render_csv(d)
    lines = csv_text.strip().splitlines()
    assert lines[0].startswith("stage,A_us_per_step,B_us_per_step,")
    rows = {ln.split(",")[0]: ln.split(",") for ln in lines[1:]}
    assert rows["ra.counts"][1] == "900.0" and rows["ra.counts"][2] == "90.0"
    assert rows["ra.counts"][4] == "0.1"
    # the totals row carries the step ratio + boundary verdict
    assert rows["(step)"][-1] == "True"
    # the CLI surface: --csv prints the same table
    pa, pb = tmp_path / "a", tmp_path / "b"
    pa.mkdir(), pb.mkdir()
    json.dump(a, open(pa / "devprof.json", "w"))
    json.dump(b, open(pb / "devprof.json", "w"))
    assert trace_diff.main([str(pa), str(pb), "--csv"]) == 0
    assert capsys.readouterr().out == csv_text
    # --json and --csv are mutually exclusive
    with pytest.raises(SystemExit):
        trace_diff.main([str(pa), str(pb), "--csv", "--json"])


@pytest.mark.slow
def test_trace_diff_cli_on_real_captures(corpus, tmp_path):
    """Two real captures (counts scatter vs reduce) diff end to end.

    ``slow``: two full captures (each re-lowers + compiles the step for
    attribution) on top of the synthetic-diff coverage above; the
    committed DEVPROF_r12_cpu.json artifact exercises the same path.
    """
    packed, _prefix, _log, wirep = corpus
    outs = {}
    for impl in ("scatter", "reduce"):
        devprof.shutdown()
        devprof.arm(str(tmp_path / impl), steps=2, warmup=1, label=impl)
        run_stream_wire(packed, [wirep], _cfg(depth=0, counts_impl=impl))
        devprof.finalize_if_armed()
        outs[impl] = str(tmp_path / impl)
        assert os.path.exists(os.path.join(outs[impl], "devprof.json"))
    rc = trace_diff.main([outs["scatter"], outs["reduce"], "--json"])
    assert rc == 0
    d = trace_diff.diff_captures(
        trace_diff.load_capture(outs["scatter"]),
        trace_diff.load_capture(outs["reduce"]),
    )
    assert {r["stage"] for r in d["stages"]} >= {"ra.counts", "ra.hll"}
    assert d["A"]["label"] == "scatter" and d["B"]["label"] == "reduce"


# ---------------------------------------------------------------------------
# Typed refusals + failure model.
# ---------------------------------------------------------------------------


def test_cli_refuses_distributed_capture(corpus, capsys):
    from ruleset_analysis_tpu import cli

    _packed, prefix, log, _wirep = corpus
    rc = cli.main([
        "run", "--ruleset", prefix, "--logs", log,
        "--distributed", "--num-processes", "2", "--process-id", "0",
        "--devprof-out", "/tmp/never",
    ])
    assert rc == 2
    err = capsys.readouterr().err
    assert "single-controller" in err
    rc = cli.main([
        "run", "--ruleset", prefix, "--logs", log, "--devprof-steps", "9",
    ])
    assert rc == 2
    assert "--devprof-out" in capsys.readouterr().err


def test_devprof_config_validation():
    with pytest.raises(ValueError):
        DevprofConfig(out_dir="")
    with pytest.raises(ValueError):
        DevprofConfig(out_dir="x", steps=0)
    with pytest.raises(ValueError):
        DevprofConfig(out_dir="x", warmup=-1)


def test_device_memory_gauges_graceful():
    g = devprof.device_memory_gauges()
    assert set(g) == {
        "device_mem_bytes_in_use",
        "device_mem_peak_bytes_in_use",
        "device_mem_bytes_limit",
    }
    for v in g.values():
        assert v is None or isinstance(v, int)


def test_profiler_failure_is_clean_no_trace_run(
    corpus, wire_baselines, tmp_path, monkeypatch
):
    """A REAL profiler start failure degrades to a no-trace run with the
    report intact — observability must never take down the run."""
    packed, _prefix, _log, wirep = corpus
    base = wire_baselines[0]

    def boom(*a, **k):
        raise RuntimeError("profiler backend unavailable")

    monkeypatch.setattr(jax.profiler, "start_trace", boom)
    devprof.arm(str(tmp_path / "dp"), steps=2, warmup=1)
    rep = run_stream_wire(packed, [wirep], _cfg(depth=0))
    dp = rep.totals["devprof"]
    assert dp["steps_profiled"] == 0
    assert "profiler start failed" in dp["error"]
    assert report_image(rep) == report_image(base)
    assert not os.path.exists(tmp_path / "dp" / "devprof.json")


#: 4 seeded chaos schedules (tier-1): the devprof.capture site fires at
#: the window's START (hit 1) or STOP (hit 2) seam, under the sync and
#: prefetch drivers.  Invariant: typed abort (InjectedFault is an
#: AnalysisError), no hang, no half-written devprof.json, and the NEXT
#: run in the same process is healthy and bit-identical to baseline.
_CHAOS = [
    ("devprof.capture@1,seed=101", 0),
    ("devprof.capture@2,seed=102", 0),
    ("devprof.capture@1,seed=103", 2),
    ("devprof.capture@2,seed=104", 2),
]


@pytest.mark.parametrize("plan,depth", _CHAOS)
def test_chaos_capture_site(corpus, wire_baselines, tmp_path, plan, depth):
    packed, _prefix, _log, wirep = corpus
    out = tmp_path / "dp"
    devprof.arm(str(out), steps=2, warmup=1)
    with pytest.raises(InjectedFault):
        run_stream_wire(packed, [wirep], _cfg(depth=depth, fault_plan=plan))
    # never a torn summary on the abort path
    assert not os.path.exists(out / "devprof.json")
    devprof.shutdown()  # stops any dangling profiler (the stop-seam case)
    # the process is healthy afterwards: a fresh disarmed run matches
    # the module's fault-free baseline bit for bit
    again = run_stream_wire(packed, [wirep], _cfg(depth=depth))
    assert report_image(again) == report_image(wire_baselines[depth])
