"""Multi-process parse feeder: shared-memory workers == sequential path.

Chunk boundaries in feeder mode follow raw-line counts (grouped batches
are 2x wide under egress bindings instead of closing early), so the
assertions here are the boundary-invariant ones: identical registers,
per-rule hits, unused set, and counters.
"""

import numpy as np
import pytest

pytest.importorskip("jax")

from ruleset_analysis_tpu.config import AnalysisConfig, SketchConfig
from ruleset_analysis_tpu.hostside import aclparse, fastparse, oracle, pack, synth
from ruleset_analysis_tpu.hostside.feeder import (
    ParallelFeeder,
    RingFeeder,
    ThreadedFeeder,
    _scan_batches,
)
from ruleset_analysis_tpu.runtime.stream import run_stream_file

pytestmark = pytest.mark.skipif(
    not fastparse.available(), reason="native parser not buildable here"
)


@pytest.fixture(scope="module")
def corpus(tmp_path_factory):
    td = tmp_path_factory.mktemp("feed")
    cfg_text = synth.synth_config(n_acls=3, rules_per_acl=10, seed=61, egress_acls=True)
    rs = aclparse.parse_asa_config(cfg_text, "fw1")
    packed = pack.pack_rulesets([rs])
    tuples = synth.synth_tuples(packed, 3000, seed=62)
    lines = synth.render_syslog(packed, tuples, seed=63, variety=0.4)
    p1 = td / "a.log"
    p1.write_text("\n".join(lines[:1700]) + "\n", encoding="utf-8")
    p2 = td / "b.log"
    p2.write_text("\n".join(lines[1700:]) + "\n", encoding="utf-8")
    res = oracle.Oracle([rs]).consume(list(lines))
    return packed, rs, [str(p1), str(p2)], res


def test_scan_batches_covers_every_line(corpus):
    packed, rs, paths, res = corpus
    descs = list(_scan_batches(paths, 256, 0))
    assert sum(d[3] for d in descs) == 3000
    # descriptors tile each file contiguously
    by_file = {}
    for path_i, off, nbytes, n in descs:
        by_file.setdefault(path_i, []).append((off, nbytes, n))
    for segs in by_file.values():
        pos = 0
        for off, nbytes, n in segs:
            assert off == pos
            assert 1 <= n <= 256
            pos = off + nbytes


def test_scan_batches_skip_lines(corpus):
    packed, rs, paths, res = corpus
    descs = list(_scan_batches(paths, 256, 500))
    assert sum(d[3] for d in descs) == 2500


def test_feeder_source_matches_sequential_counters(corpus):
    packed, rs, paths, res = corpus
    feeder = ParallelFeeder(packed, paths, n_workers=3)
    total_lines = 0
    total_valid = 0
    for batch, n_raw in feeder.batches(0, 256):
        total_lines += n_raw
        total_valid += int(batch[pack.T_VALID].sum())
    assert total_lines == 3000
    assert feeder.packer.parsed == total_valid == res.lines_matched
    assert feeder.packer.skipped == res.lines_skipped


@pytest.mark.parametrize("workers", [2, 3])
def test_feeder_report_equals_sequential(corpus, workers):
    packed, rs, paths, res = corpus
    cfg = AnalysisConfig(
        batch_size=256,
        sketch=SketchConfig(cms_width=1 << 11, cms_depth=4, hll_p=6),
    )
    seq = run_stream_file(packed, paths, cfg)
    par = run_stream_file(packed, paths, cfg, feed_workers=workers)
    hs = {(e["firewall"], e["acl"], e["index"]): e["hits"] for e in seq.per_rule}
    hp = {(e["firewall"], e["acl"], e["index"]): e["hits"] for e in par.per_rule}
    assert hs == hp
    assert seq.unused == par.unused
    assert seq.totals["lines_total"] == par.totals["lines_total"] == 3000
    assert seq.totals["lines_matched"] == par.totals["lines_matched"]
    # per-rule unique-source estimates come straight from the HLL
    # registers, which are order-invariant -> must agree exactly
    us = {tuple(e["key"]) if "key" in e else (e["firewall"], e["acl"], e["index"]): e.get("unique_sources")
          for e in seq.per_rule}
    up = {tuple(e["key"]) if "key" in e else (e["firewall"], e["acl"], e["index"]): e.get("unique_sources")
          for e in par.per_rule}
    assert us == up


def test_feeder_resume_checkpoint(corpus, tmp_path):
    packed, rs, paths, res = corpus
    ck = str(tmp_path / "ck")
    cfg = AnalysisConfig(
        batch_size=256,
        sketch=SketchConfig(cms_width=1 << 11, cms_depth=4, hll_p=6),
        checkpoint_every_chunks=3,
        checkpoint_dir=ck,
    )
    # crash after 5 chunks (max_chunks), then resume with the feeder
    run_stream_file(packed, paths, cfg, feed_workers=2, max_chunks=5)
    rep = run_stream_file(packed, paths, cfg.replace(resume=True), feed_workers=2)
    full = run_stream_file(packed, paths, cfg.replace(checkpoint_every_chunks=0))
    hr = {(e["firewall"], e["acl"], e["index"]): e["hits"] for e in rep.per_rule}
    hf = {(e["firewall"], e["acl"], e["index"]): e["hits"] for e in full.per_rule}
    assert hr == hf
    assert rep.totals["lines_total"] == 3000


def test_killed_worker_detected_not_hung(corpus):
    """An OS-killed worker (OOM analog) must surface as RuntimeError via
    the liveness timeout, not hang the coordinator on done_q forever."""
    import os
    import signal

    packed, rs, paths, res = corpus
    feeder = ParallelFeeder(packed, paths, n_workers=1)
    gen = feeder.batches(0, 64)  # 3000 lines -> ~47 batches, plenty left
    next(gen)
    os.kill(feeder._workers[0].pid, signal.SIGKILL)
    with pytest.raises(RuntimeError, match="died without reporting"):
        for _ in gen:
            pass


# ---------------------------------------------------------------------------
# threaded tier: same descriptors, same in-order commit, no processes
# ---------------------------------------------------------------------------


def test_threaded_feeder_report_equals_process_tier(corpus):
    """Thread and process tiers chop identical descriptors, so the FULL
    report — including chunk-boundary-sensitive top-K candidates — must
    match between them (not just the order-invariant registers)."""
    import json

    packed, rs, paths, res = corpus
    cfg = AnalysisConfig(
        batch_size=256,
        sketch=SketchConfig(cms_width=1 << 11, cms_depth=4, hll_p=6),
    )
    thr = run_stream_file(packed, paths, cfg, feed_workers=3, feed_mode="thread")
    prc = run_stream_file(packed, paths, cfg, feed_workers=3, feed_mode="process")
    jt, jp = json.loads(thr.to_json()), json.loads(prc.to_json())
    from ruleset_analysis_tpu.runtime.report import VOLATILE_TOTALS

    for k in VOLATILE_TOTALS:
        jt["totals"].pop(k, None)
        jp["totals"].pop(k, None)
    assert jt == jp
    assert thr.totals["lines_matched"] == res.lines_matched


def test_threaded_feeder_registers_equal_sequential(corpus):
    packed, rs, paths, res = corpus
    cfg = AnalysisConfig(
        batch_size=256,
        sketch=SketchConfig(cms_width=1 << 11, cms_depth=4, hll_p=6),
    )
    seq = run_stream_file(packed, paths, cfg)
    thr = run_stream_file(packed, paths, cfg, feed_workers=2, feed_mode="thread")
    hs = {(e["firewall"], e["acl"], e["index"]): e["hits"] for e in seq.per_rule}
    ht = {(e["firewall"], e["acl"], e["index"]): e["hits"] for e in thr.per_rule}
    assert hs == ht
    assert seq.unused == thr.unused
    assert thr.totals["lines_total"] == 3000
    assert thr.totals["lines_matched"] == res.lines_matched


# ---------------------------------------------------------------------------
# v6 plane: both feed tiers carry the SAME evaluation-row streams, byte
# for byte, as the sequential native parse (VERDICT Missing #4 closure)
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def corpus6(tmp_path_factory):
    td = tmp_path_factory.mktemp("feed6")
    cfg_text = synth.synth_config(
        n_acls=2, rules_per_acl=8, seed=77, v6_fraction=0.5
    )
    rs = aclparse.parse_asa_config(cfg_text, "fw1")
    packed = pack.pack_rulesets([rs])
    n4, n6 = 1600, 1400
    lines = synth.render_syslog(
        packed, synth.synth_tuples(packed, n4, seed=78), seed=79
    )
    lines += synth.render_syslog6(
        packed, synth.synth_tuples6(packed, n6, seed=80), seed=81
    )
    import random

    random.Random(7).shuffle(lines)
    p = td / "mixed.log"
    p.write_text("\n".join(lines) + "\n", encoding="utf-8")
    assert packed.has_v6
    return packed, [str(p)]


def _row_streams(batches_it, take_v6):
    """(v4 valid-row stream, v6 row stream) concatenated over all batches."""
    v4, v6 = [], []
    for batch, _n in batches_it:
        v4.append(batch[:, batch[pack.T_VALID] == 1].copy())
        rows6 = take_v6()
        if len(rows6):
            v6.append(np.asarray(rows6, dtype=np.uint32))
    cat4 = np.concatenate(v4, axis=1) if v4 else np.zeros((pack.TUPLE_COLS, 0))
    cat6 = (
        np.concatenate(v6) if v6 else np.zeros((0, pack.TUPLE6_COLS))
    )
    return cat4, cat6


@pytest.mark.parametrize("tier", ["process", "thread", "ring"])
def test_feeder_v6_plane_byte_identical_to_sequential(corpus6, tier):
    packed, paths = corpus6
    packer = fastparse.NativePacker(packed)
    seq4, seq6 = _row_streams(
        fastparse.batches_from_files(paths, packer, 256), packer.take_v6
    )
    assert seq6.shape[0] > 0  # the corpus genuinely exercises the plane
    feeder_cls = {
        "process": ParallelFeeder, "thread": ThreadedFeeder,
        "ring": RingFeeder,
    }[tier]
    if tier == "ring":
        # 13-tuple v6 staging rides the per-chip rings (ISSUE 11): the
        # committed stream must still be the sequential parse's, byte
        # for byte, in line order across ring partitions
        feeder = feeder_cls(packed, paths, n_workers=2, n_rings=8)
    else:
        feeder = feeder_cls(packed, paths, n_workers=2)
    par4, par6 = _row_streams(feeder.batches(0, 256), feeder.take_v6)
    assert np.array_equal(seq4, par4)
    assert np.array_equal(seq6, par6)
    # capped digest->address map: same rows in the same stream order ->
    # identical first-seen winners
    from ruleset_analysis_tpu.hostside.pack import (
        T6_SRC, V6_DIGEST_CAP, fold_src32_host, limbs_u128,
    )

    want: dict[int, int] = {}
    for r in seq6:
        if len(want) >= V6_DIGEST_CAP:
            break
        src = limbs_u128(*r[T6_SRC:T6_SRC + 4])
        want.setdefault(fold_src32_host(src), src)
    assert feeder.v6_digests == want


# ---------------------------------------------------------------------------
# per-chip feeder rings (ISSUE 11): one shared-memory ring per device,
# producer pool partitioned by chip, per-chip device_put fed straight
# from the rings — reports bit-identical to the global-queue tier
# ---------------------------------------------------------------------------


def _stripped(rep):
    import json

    from ruleset_analysis_tpu.runtime.report import VOLATILE_TOTALS

    j = json.loads(rep.to_json())
    for k in VOLATILE_TOTALS:
        j["totals"].pop(k, None)
    return j


def test_ring_feeder_report_equals_queue_tier(corpus):
    """Full report — including the chunk-boundary-sensitive top-K
    talkers — must match the global-queue process tier, under BOTH the
    prefetched per-chip-device_put path and the sync assembled path
    (ring groups cover exactly the lines queue batches cover, and every
    register update is padding/order-invariant)."""
    packed, rs, paths, res = corpus
    cfg = AnalysisConfig(
        batch_size=256,
        sketch=SketchConfig(cms_width=1 << 11, cms_depth=4, hll_p=6),
    )
    queue_rep = _stripped(
        run_stream_file(packed, paths, cfg, feed_workers=2, feed_mode="process")
    )
    ring_prefetch = _stripped(
        run_stream_file(packed, paths, cfg, feed_workers=2, feed_mode="ring")
    )
    ring_sync = _stripped(
        run_stream_file(
            packed, paths, cfg.replace(prefetch_depth=0),
            feed_workers=2, feed_mode="ring",
        )
    )
    assert ring_prefetch == queue_rep
    assert ring_sync == queue_rep


def test_ring_feeder_resume_checkpoint(corpus, tmp_path):
    """Crash-at-K resume through the ring plane: registers, per-rule
    hits, HLL uniques, and the unused set must equal an uninterrupted
    queue-tier run (chunk counts may differ — a snapshot flushes partial
    v6 buffers — so the pin is register-level, exactly like the other
    feeder tiers' resume contract)."""
    packed, rs, paths, res = corpus
    ck = str(tmp_path / "ck")
    cfg = AnalysisConfig(
        batch_size=256,
        sketch=SketchConfig(cms_width=1 << 11, cms_depth=4, hll_p=6),
        checkpoint_every_chunks=3,
        checkpoint_dir=ck,
    )
    run_stream_file(packed, paths, cfg, feed_workers=2, feed_mode="ring",
                    max_chunks=5)
    rep = run_stream_file(
        packed, paths, cfg.replace(resume=True), feed_workers=2,
        feed_mode="ring",
    )
    full = run_stream_file(
        packed, paths, cfg.replace(checkpoint_every_chunks=0),
        feed_workers=2, feed_mode="process",
    )
    hr = {(e["firewall"], e["acl"], e["index"]): (e["hits"], e.get("unique_sources"))
          for e in rep.per_rule}
    hf = {(e["firewall"], e["acl"], e["index"]): (e["hits"], e.get("unique_sources"))
          for e in full.per_rule}
    assert hr == hf
    assert rep.unused == full.unused
    assert rep.totals["lines_total"] == 3000
    assert rep.totals["lines_matched"] == full.totals["lines_matched"]


def test_ring_feeder_killed_worker_detected_not_hung(corpus):
    """An OS-killed ring worker must surface as a typed FeedWorkerError
    via the liveness probe — one starved chip must never hang the run."""
    import os
    import signal

    packed, rs, paths, res = corpus
    feeder = RingFeeder(packed, paths, n_workers=2, n_rings=8)
    gen = feeder.batches(0, 256)
    next(gen)
    os.kill(feeder._workers[0].pid, signal.SIGKILL)
    with pytest.raises(RuntimeError, match="died without reporting"):
        for _ in gen:
            pass


def test_ring_feeder_rejects_runtime_coalesce(corpus):
    from ruleset_analysis_tpu.errors import AnalysisError

    packed, rs, paths, res = corpus
    cfg = AnalysisConfig(
        batch_size=256,
        sketch=SketchConfig(cms_width=1 << 11, cms_depth=4, hll_p=6),
        coalesce="on",
    )
    with pytest.raises(AnalysisError, match="convert --coalesce"):
        run_stream_file(packed, paths, cfg, feed_workers=2, feed_mode="ring")


def test_ring_feeder_gauges_cover_every_ring(corpus, tmp_path):
    """The metrics sampler must expose per-ring occupancy, partition
    imbalance, and starved-chip seconds — the gauges the trace_summary
    feed block renders."""
    packed, rs, paths, res = corpus
    from ruleset_analysis_tpu.runtime import obs

    obs.start_metrics(str(tmp_path / "m.jsonl"), every_sec=60.0)
    seen = {}
    try:
        feeder = RingFeeder(packed, paths, n_workers=2, n_rings=4)
        gen = feeder.batches(0, 256)
        try:
            for i, _ in enumerate(gen):
                if i == 2:
                    seen = dict(obs.metrics_snapshot().get("feeder", {}))
                    break
        finally:
            gen.close()
    finally:
        obs.shutdown()
    assert seen.get("mode") == "ring"
    assert seen.get("rings") == 4
    assert len(seen.get("ring_occupancy", [])) == 4
    assert len(seen.get("starved_sec", [])) == 4
    assert "partition_imbalance" in seen


def test_ring_feeder_summary_instant_feeds_trace_summary(corpus, tmp_path):
    """A ring run under --trace-out leaves one feeder.summary instant;
    tools/trace_summary.py renders it as the feed block."""
    import sys

    from ruleset_analysis_tpu.runtime import obs

    sys.path.insert(0, "tools")
    try:
        import trace_summary
    finally:
        sys.path.pop(0)
    packed, rs, paths, res = corpus
    td = str(tmp_path / "tr")
    obs.start_trace(td, role="main")
    try:
        feeder = RingFeeder(packed, paths, n_workers=2, n_rings=4)
        for _ in feeder.batches(0, 256):
            pass
    finally:
        merged = obs.merge_trace(td)
        obs.shutdown()
    s = trace_summary.summarize(merged)
    feed = s.get("feed")
    assert feed and feed["mode"] == "ring" and feed["rings"] == 4
    assert len(feed["ring_occupancy_pct"]) == 4
    assert len(feed["starved_sec"]) == 4
    assert "partition_imbalance_pct" in feed
    text = trace_summary.render(s)
    assert "feed: ring x4" in text and "ring 0:" in text


def test_ring_feeder_requires_workers_at_api_level(corpus):
    """feed_mode='ring' without feed_workers must be a typed refusal at
    the run_stream_file API, not a silent fall-through to the plain
    source (the requested topology would otherwise be dropped)."""
    from ruleset_analysis_tpu.errors import AnalysisError

    packed, rs, paths, res = corpus
    cfg = AnalysisConfig(
        batch_size=256,
        sketch=SketchConfig(cms_width=1 << 11, cms_depth=4, hll_p=6),
    )
    with pytest.raises(AnalysisError, match="feed_workers"):
        run_stream_file(packed, paths, cfg, feed_mode="ring")


def test_ring_feeder_slot_exhaustion_aborts_typed(corpus):
    """A consumer that hoards unreleased _RingBatch views past the ring
    depth must get a typed FeedWorkerError, never a silently truncated
    corpus (the generator cannot make progress while every slot of a
    ring is held)."""
    from ruleset_analysis_tpu.errors import FeedWorkerError

    packed, rs, paths, res = corpus
    feeder = RingFeeder(
        packed, paths, n_workers=2, n_rings=2, ring_depth=2
    )
    feeder.emit_views = True
    held = []
    gen = feeder.batches(0, 256)
    try:
        with pytest.raises(FeedWorkerError, match="ring slots exhausted"):
            for rb, _n in gen:
                held.append(rb)  # never released: slots run dry
    finally:
        for rb in held:
            rb.release()
        gen.close()
