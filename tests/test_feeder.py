"""Multi-process parse feeder: shared-memory workers == sequential path.

Chunk boundaries in feeder mode follow raw-line counts (grouped batches
are 2x wide under egress bindings instead of closing early), so the
assertions here are the boundary-invariant ones: identical registers,
per-rule hits, unused set, and counters.
"""

import numpy as np
import pytest

pytest.importorskip("jax")

from ruleset_analysis_tpu.config import AnalysisConfig, SketchConfig
from ruleset_analysis_tpu.hostside import aclparse, fastparse, oracle, pack, synth
from ruleset_analysis_tpu.hostside.feeder import ParallelFeeder, _scan_batches
from ruleset_analysis_tpu.runtime.stream import run_stream_file

pytestmark = pytest.mark.skipif(
    not fastparse.available(), reason="native parser not buildable here"
)


@pytest.fixture(scope="module")
def corpus(tmp_path_factory):
    td = tmp_path_factory.mktemp("feed")
    cfg_text = synth.synth_config(n_acls=3, rules_per_acl=10, seed=61, egress_acls=True)
    rs = aclparse.parse_asa_config(cfg_text, "fw1")
    packed = pack.pack_rulesets([rs])
    tuples = synth.synth_tuples(packed, 3000, seed=62)
    lines = synth.render_syslog(packed, tuples, seed=63, variety=0.4)
    p1 = td / "a.log"
    p1.write_text("\n".join(lines[:1700]) + "\n", encoding="utf-8")
    p2 = td / "b.log"
    p2.write_text("\n".join(lines[1700:]) + "\n", encoding="utf-8")
    res = oracle.Oracle([rs]).consume(list(lines))
    return packed, rs, [str(p1), str(p2)], res


def test_scan_batches_covers_every_line(corpus):
    packed, rs, paths, res = corpus
    descs = list(_scan_batches(paths, 256, 0))
    assert sum(d[3] for d in descs) == 3000
    # descriptors tile each file contiguously
    by_file = {}
    for path_i, off, nbytes, n in descs:
        by_file.setdefault(path_i, []).append((off, nbytes, n))
    for segs in by_file.values():
        pos = 0
        for off, nbytes, n in segs:
            assert off == pos
            assert 1 <= n <= 256
            pos = off + nbytes


def test_scan_batches_skip_lines(corpus):
    packed, rs, paths, res = corpus
    descs = list(_scan_batches(paths, 256, 500))
    assert sum(d[3] for d in descs) == 2500


def test_feeder_source_matches_sequential_counters(corpus):
    packed, rs, paths, res = corpus
    feeder = ParallelFeeder(packed, paths, n_workers=3)
    total_lines = 0
    total_valid = 0
    for batch, n_raw in feeder.batches(0, 256):
        total_lines += n_raw
        total_valid += int(batch[pack.T_VALID].sum())
    assert total_lines == 3000
    assert feeder.packer.parsed == total_valid == res.lines_matched
    assert feeder.packer.skipped == res.lines_skipped


@pytest.mark.parametrize("workers", [2, 3])
def test_feeder_report_equals_sequential(corpus, workers):
    packed, rs, paths, res = corpus
    cfg = AnalysisConfig(
        batch_size=256,
        sketch=SketchConfig(cms_width=1 << 11, cms_depth=4, hll_p=6),
    )
    seq = run_stream_file(packed, paths, cfg)
    par = run_stream_file(packed, paths, cfg, feed_workers=workers)
    hs = {(e["firewall"], e["acl"], e["index"]): e["hits"] for e in seq.per_rule}
    hp = {(e["firewall"], e["acl"], e["index"]): e["hits"] for e in par.per_rule}
    assert hs == hp
    assert seq.unused == par.unused
    assert seq.totals["lines_total"] == par.totals["lines_total"] == 3000
    assert seq.totals["lines_matched"] == par.totals["lines_matched"]
    # per-rule unique-source estimates come straight from the HLL
    # registers, which are order-invariant -> must agree exactly
    us = {tuple(e["key"]) if "key" in e else (e["firewall"], e["acl"], e["index"]): e.get("unique_sources")
          for e in seq.per_rule}
    up = {tuple(e["key"]) if "key" in e else (e["firewall"], e["acl"], e["index"]): e.get("unique_sources")
          for e in par.per_rule}
    assert us == up


def test_feeder_resume_checkpoint(corpus, tmp_path):
    packed, rs, paths, res = corpus
    ck = str(tmp_path / "ck")
    cfg = AnalysisConfig(
        batch_size=256,
        sketch=SketchConfig(cms_width=1 << 11, cms_depth=4, hll_p=6),
        checkpoint_every_chunks=3,
        checkpoint_dir=ck,
    )
    # crash after 5 chunks (max_chunks), then resume with the feeder
    run_stream_file(packed, paths, cfg, feed_workers=2, max_chunks=5)
    rep = run_stream_file(packed, paths, cfg.replace(resume=True), feed_workers=2)
    full = run_stream_file(packed, paths, cfg.replace(checkpoint_every_chunks=0))
    hr = {(e["firewall"], e["acl"], e["index"]): e["hits"] for e in rep.per_rule}
    hf = {(e["firewall"], e["acl"], e["index"]): e["hits"] for e in full.per_rule}
    assert hr == hf
    assert rep.totals["lines_total"] == 3000


def test_killed_worker_detected_not_hung(corpus):
    """An OS-killed worker (OOM analog) must surface as RuntimeError via
    the liveness timeout, not hang the coordinator on done_q forever."""
    import os
    import signal

    packed, rs, paths, res = corpus
    feeder = ParallelFeeder(packed, paths, n_workers=1)
    gen = feeder.batches(0, 64)  # 3000 lines -> ~47 batches, plenty left
    next(gen)
    os.kill(feeder._workers[0].pid, signal.SIGKILL)
    with pytest.raises(RuntimeError, match="died without reporting"):
        for _ in gen:
            pass
