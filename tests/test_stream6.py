"""End-to-end IPv6: mixed-family stream runs vs the exact oracle.

The v6 side path (runtime/stream.py): text sources stage v6 evaluations
separately; the driver steps them through the v6 device program into the
SAME registers, flushing at checkpoints and end-of-stream.
"""

import random

import numpy as np
import pytest

pytest.importorskip("jax")

from ruleset_analysis_tpu.config import AnalysisConfig, SketchConfig
from ruleset_analysis_tpu.errors import AnalysisError
from ruleset_analysis_tpu.hostside import aclparse, oracle, pack
from ruleset_analysis_tpu.runtime.stream import run_stream, run_stream_file

CFG = """\
hostname fw1
access-list A extended permit tcp any host 10.0.0.5 eq 443
access-list A extended permit tcp any6 2001:db8:1::/48 eq 443
access-list A extended permit udp 2001:db8:2::/64 any6 eq 53
access-list A extended deny tcp any6 host 2001:db8::bad
access-list A extended permit ip any any
access-list B extended permit tcp any6 any6 range 8000 8100
access-group A in interface outside
"""


def mixed_lines(n, seed=0, v6_share=0.4):
    rng = random.Random(seed)
    out = []
    for i in range(n):
        acl = "A" if rng.random() < 0.8 else "B"
        if rng.random() < v6_share:
            src = f"2001:db8:2::{rng.randrange(1, 40):x}" if rng.random() < 0.5 else \
                  f"2001:db8:{rng.randrange(0, 4):x}::{rng.randrange(1, 2000):x}"
            dst = "2001:db8::bad" if rng.random() < 0.1 else \
                  f"2001:db8:{rng.randrange(0, 4):x}:1::{rng.randrange(1, 99):x}"
            proto = rng.choice(["tcp", "udp"])
            sport = rng.randrange(1024, 60000)
            dport = rng.choice([443, 53, 8050, 9999])
        else:
            src = f"10.1.{rng.randrange(256)}.{rng.randrange(1, 255)}"
            dst = "10.0.0.5" if rng.random() < 0.5 else "10.9.9.9"
            proto = "tcp"
            sport = rng.randrange(1024, 60000)
            dport = rng.choice([443, 80])
        out.append(
            f"Jul 29 07:48:{i % 60:02d} fw1 : %ASA-6-106100: access-list {acl} "
            f"permitted {proto} inside/{src}({sport}) -> outside/{dst}({dport}) "
            f"hit-cnt 1 first hit [0x0, 0x0]"
        )
    return out


@pytest.fixture(scope="module")
def corpus():
    rs = aclparse.parse_asa_config(CFG, "fw1")
    packed = pack.pack_rulesets([rs])
    lines = mixed_lines(2500, seed=5)
    res = oracle.Oracle([rs]).consume(list(lines))
    return packed, rs, lines, res


def run_cfg(**kw):
    return AnalysisConfig(
        backend="tpu",
        batch_size=256,
        sketch=SketchConfig(cms_width=1 << 12, cms_depth=4, hll_p=8),
        **kw,
    )


def report_hits(rep):
    return {
        (e["firewall"], e["acl"], e["index"]): e["hits"]
        for e in rep.per_rule
        if e["hits"] > 0
    }


def test_mixed_stream_counts_match_oracle(corpus):
    packed, rs, lines, res = corpus
    rep = run_stream(packed, iter(lines), run_cfg(), topk=5)
    assert report_hits(rep) == dict(res.hits)
    assert rep.totals["lines_matched"] == res.lines_matched
    assert rep.totals["lines_skipped"] == res.lines_skipped == 0
    assert rep.unused == res.unused_rules([rs])


def test_v6_talkers_render_addresses(corpus):
    packed, rs, lines, res = corpus
    # one dominant source per family: both must surface in the SAME
    # merged per-ACL talker section, each rendered in its own notation
    heavy6 = [
        "Jul 29 07:49:00 fw1 : %ASA-6-106100: access-list A permitted tcp "
        "inside/2001:db8:1::7777(4321) -> outside/2001:db8:1::1(443) "
        "hit-cnt 1 first hit [0x0, 0x0]"
    ] * 400
    heavy4 = [
        "Jul 29 07:49:01 fw1 : %ASA-6-106100: access-list A permitted tcp "
        "inside/10.1.2.3(4321) -> outside/10.0.0.5(443) "
        "hit-cnt 1 first hit [0x0, 0x0]"
    ] * 300
    rep = run_stream(
        packed, iter(list(lines) + heavy6 + heavy4), run_cfg(), topk=5
    )
    talk = rep.talkers.get("fw1 A", [])
    assert any(ip == "2001:db8:1::7777" for ip, _ in talk), talk
    assert any(ip == "10.1.2.3" for ip, _ in talk), talk


def test_crash_resume_bit_identity_with_v6(corpus, tmp_path):
    packed, rs, lines, res = corpus
    base = run_cfg(checkpoint_every_chunks=2, checkpoint_dir=str(tmp_path / "ck"))
    uninterrupted = run_stream(packed, iter(lines), base, topk=5)
    # crash after 5 chunks, then resume over the same stream
    ck2 = str(tmp_path / "ck2")
    crash_cfg = run_cfg(checkpoint_every_chunks=2, checkpoint_dir=ck2)
    run_stream(packed, iter(lines), crash_cfg, topk=5, max_chunks=5)
    resume_cfg = run_cfg(
        checkpoint_every_chunks=2, checkpoint_dir=ck2, resume=True
    )
    resumed = run_stream(packed, iter(lines), resume_cfg, topk=5)
    assert report_hits(resumed) == report_hits(uninterrupted) == dict(res.hits)
    assert resumed.unused == uninterrupted.unused


def test_native_parser_analyzes_v6_corpora(corpus, tmp_path):
    """The native parse tier handles v6 via its dual-family entry
    (the feeder tier's v6 path is pinned by test_feeder_v6_*)."""
    packed, rs, lines, res = corpus
    p = tmp_path / "logs.txt"
    p.write_text("\n".join(lines) + "\n")
    from ruleset_analysis_tpu.hostside import fastparse

    if fastparse.available():
        rep_native = run_stream_file(
            packed, str(p), run_cfg(), native=True, topk=5
        )
        assert report_hits(rep_native) == dict(res.hits)
        assert rep_native.unused == res.unused_rules([rs])
    rep = run_stream_file(packed, str(p), run_cfg(), native=False, topk=5)
    assert report_hits(rep) == dict(res.hits)


def test_fold_host_device_agree():
    from ruleset_analysis_tpu.ops import match6 as match6_ops

    rng = random.Random(9)
    vals = [rng.getrandbits(128) for _ in range(512)]
    batch = np.zeros((len(vals), pack.TUPLE6_COLS), dtype=np.uint32)
    for i, v in enumerate(vals):
        batch[i, pack.T6_SRC:pack.T6_SRC + 4] = pack.u128_limbs(v)
    import jax.numpy as jnp

    b = jnp.asarray(np.ascontiguousarray(batch.T))
    cols = {f"src{i}": b[pack.T6_SRC + i] for i in range(4)}
    dev = np.asarray(match6_ops.fold_src32(cols))
    host = np.array([pack.fold_src32_host(v) for v in vals], dtype=np.uint32)
    np.testing.assert_array_equal(dev, host)


def test_synth_unified_corpus_end_to_end():
    """Randomized unified (v4+v6) synth config through the full stream."""
    from ruleset_analysis_tpu.hostside import synth

    cfg_text = synth.synth_config(
        n_acls=3, rules_per_acl=14, seed=33, v6_fraction=0.35
    )
    rs = aclparse.parse_asa_config(cfg_text, "fw1")
    packed = pack.pack_rulesets([rs])
    assert packed.has_v6 and packed.rules.shape[0] > 0
    t4 = synth.synth_tuples(packed, 900, seed=33)
    t6 = synth.synth_tuples6(packed, 600, seed=33)
    lines = synth.render_syslog(packed, t4, seed=33) + synth.render_syslog6(
        packed, t6, seed=34
    )
    rng = random.Random(7)
    rng.shuffle(lines)
    res = oracle.Oracle([rs]).consume(list(lines))
    rep = run_stream(packed, iter(lines), run_cfg(), topk=5)
    assert report_hits(rep) == dict(res.hits)
    assert rep.unused == res.unused_rules([rs])
    assert rep.totals["lines_matched"] == res.lines_matched


@pytest.mark.parametrize("seed", [2, 8])
def test_native_python_v6_differential(seed):
    """Python LinePacker vs native parser: bit-identical dual-family packs."""
    from ruleset_analysis_tpu.hostside import fastparse, synth

    if not fastparse.available():
        pytest.skip("no native toolchain")
    cfg_text = synth.synth_config(
        n_acls=3, rules_per_acl=10, seed=seed, v6_fraction=0.4
    )
    rs = aclparse.parse_asa_config(cfg_text, "fw1")
    packed = pack.pack_rulesets([rs])
    t4 = synth.synth_tuples(packed, 400, seed=seed)
    t6 = synth.synth_tuples6(packed, 300, seed=seed)
    lines = synth.render_syslog(packed, t4, seed=seed) + synth.render_syslog6(
        packed, t6, seed=seed + 1
    )
    rng = random.Random(seed)
    rng.shuffle(lines)
    py = pack.LinePacker(packed)
    ref4, ref6 = py.pack_lines2(lines, batch_size=2 * len(lines))
    nat = fastparse.NativePacker(packed)
    got4, got6 = nat.pack_lines2(lines, batch_size=2 * len(lines))
    np.testing.assert_array_equal(ref4, got4)
    np.testing.assert_array_equal(ref6, got6)
    assert (py.parsed, py.skipped) == (nat.parsed, nat.skipped)


V6_EDGE_LINES = [
    # valid compressions and embedded v4 forms
    "Jul 29 0 fw1 : %ASA-6-106100: access-list A permitted tcp i/::1(1) -> o/::(2) hit",
    "Jul 29 0 fw1 : %ASA-6-106100: access-list A permitted tcp "
    "i/1:2:3:4:5:6:7:8(1) -> o/::ffff:10.0.0.5(2) hit",
    "Jul 29 0 fw1 : %ASA-6-106100: access-list A permitted tcp "
    "i/1:2:3:4:5:6:1.2.3.4(1) -> o/fe80::(2) hit",
    # invalid: too many groups / double '::' / bad embedded v4 / stray ':'
    "Jul 29 0 fw1 : %ASA-6-106100: access-list A permitted tcp "
    "i/1:2:3:4:5:6:7:8:9(1) -> o/::1(2) hit",
    "Jul 29 0 fw1 : %ASA-6-106100: access-list A permitted tcp "
    "i/1::2::3(1) -> o/::1(2) hit",
    "Jul 29 0 fw1 : %ASA-6-106100: access-list A permitted tcp "
    "i/::ffff:1.2.3.999(1) -> o/::1(2) hit",
    "Jul 29 0 fw1 : %ASA-6-106100: access-list A permitted tcp "
    "i/1:2:(1) -> o/::1(2) hit",
    "Jul 29 0 fw1 : %ASA-6-106100: access-list A permitted tcp "
    "i/12345::1(1) -> o/::1(2) hit",
    # mixed family: skipped by both parsers
    "Jul 29 0 fw1 : %ASA-6-106100: access-list A permitted tcp "
    "i/::1(1) -> o/10.0.0.5(2) hit",
    # 106023 / 302013 / 106001 v6 shapes
    'Jul 29 0 fw1 : %ASA-4-106023: Deny tcp src inside:2001:db8::9/100 '
    'dst outside:2001:db8:1::5/200 by access-group "A" [0x0]',
    "Jul 29 0 fw1 : %ASA-6-302013: Built inbound TCP connection 9 for "
    "outside:2001:db8::7/1000 (2001:db8::7/1000) to inside:2001:db8::8/80 "
    "(2001:db8::8/80)",
    "Jul 29 0 fw1 : %ASA-2-106001: Inbound TCP connection denied from "
    "2001:db8::9/5555 to 2001:db8:1::5/443 flags SYN on interface outside",
    # icmp6 with type/code parens
    "Jul 29 0 fw1 : %ASA-6-106100: access-list A permitted icmp6 "
    "i/2001:db8::9(128) -> o/2001:db8::5(0) hit-cnt 1",
]


def test_native_python_v6_edge_lines_bit_identical():
    from ruleset_analysis_tpu.hostside import fastparse

    if not fastparse.available():
        pytest.skip("no native toolchain")
    rs = aclparse.parse_asa_config(CFG, "fw1")
    packed = pack.pack_rulesets([rs])
    py = pack.LinePacker(packed)
    ref4, ref6 = py.pack_lines2(V6_EDGE_LINES, batch_size=32)
    nat = fastparse.NativePacker(packed)
    got4, got6 = nat.pack_lines2(V6_EDGE_LINES, batch_size=32)
    np.testing.assert_array_equal(ref4, got4)
    np.testing.assert_array_equal(ref6, got6)
    assert (py.parsed, py.skipped) == (nat.parsed, nat.skipped)


def test_feeder_v6_registers_match_text_run(tmp_path):
    """Multi-process feeder on a unified corpus: same hits as the text run."""
    from ruleset_analysis_tpu.hostside import fastparse, synth

    if not fastparse.available():
        pytest.skip("no native toolchain")
    cfg_text = synth.synth_config(
        n_acls=3, rules_per_acl=10, seed=77, v6_fraction=0.4
    )
    rs = aclparse.parse_asa_config(cfg_text, "fw1")
    packed = pack.pack_rulesets([rs])
    t4 = synth.synth_tuples(packed, 700, seed=77)
    t6 = synth.synth_tuples6(packed, 400, seed=77)
    lines = synth.render_syslog(packed, t4, seed=77) + synth.render_syslog6(
        packed, t6, seed=78
    )
    random.Random(3).shuffle(lines)
    p = tmp_path / "logs.txt"
    p.write_text("\n".join(lines) + "\n")
    res = oracle.Oracle([rs]).consume(list(lines))
    rep_text = run_stream(packed, iter(lines), run_cfg(), topk=5)
    rep_feed = run_stream_file(
        packed, str(p), run_cfg(), feed_workers=2, topk=5
    )
    assert report_hits(rep_feed) == report_hits(rep_text) == dict(res.hits)
    assert rep_feed.unused == rep_text.unused == res.unused_rules([rs])
    assert rep_feed.totals["lines_matched"] == res.lines_matched


@pytest.mark.parametrize("seed", [1, 7])
def test_v6_mutation_fuzz_parity(seed):
    """Randomized mutations of v6/v4 syslog: both parsers bit-identical."""
    from ruleset_analysis_tpu.hostside import fastparse, synth

    if not fastparse.available():
        pytest.skip("no native toolchain")
    cfg_text = synth.synth_config(n_acls=3, rules_per_acl=10, seed=5, v6_fraction=0.5)
    rs = aclparse.parse_asa_config(cfg_text, "fw1")
    packed = pack.pack_rulesets([rs])
    base = synth.render_syslog6(packed, synth.synth_tuples6(packed, 60, seed=5), seed=6)
    base += synth.render_syslog(packed, synth.synth_tuples(packed, 60, seed=5), seed=6)
    rng = random.Random(seed)
    chars = ":abcdef0123456789./ ()->%ASA"
    mutants = []
    for _ in range(800):
        ln = rng.choice(base)
        pos = rng.randrange(len(ln))
        k = rng.randrange(5)
        if k == 0:
            ln = ln[:pos] + rng.choice(chars) + ln[pos + 1:]
        elif k == 1:
            ln = ln[:pos] + ln[pos + 1:]
        elif k == 2:
            ln = ln[:pos] + rng.choice(chars) + ln[pos:]
        elif k == 3:
            ln = ln[:pos]
        else:
            p2 = rng.randrange(len(ln))
            ls = list(ln)
            ls[pos], ls[p2] = ls[p2], ls[pos]
            ln = "".join(ls)
        mutants.append(ln)
    py = pack.LinePacker(packed)
    r4, r6 = py.pack_lines2(mutants, batch_size=4 * len(mutants))
    nat = fastparse.NativePacker(packed)
    g4, g6 = nat.pack_lines2(mutants, batch_size=4 * len(mutants))
    np.testing.assert_array_equal(r4, g4)
    np.testing.assert_array_equal(r6, g6)
    assert (py.parsed, py.skipped) == (nat.parsed, nat.skipped)


def test_v6_adversarial_endpoint_parity():
    """Hex-ish iface names x pathological v6 literals across all message
    classes: the iface:addr split and address validation must agree
    between the regex path and the native scanner."""
    from ruleset_analysis_tpu.hostside import fastparse

    if not fastparse.available():
        pytest.skip("no native toolchain")
    cfg = (
        "access-list A extended permit ip any any\n"
        "access-list A extended permit ip any6 any6\n"
        "access-group A in interface abc\n"
        "access-group A in interface fe80\n"
        "access-group A out interface def0\n"
    )
    rs = aclparse.parse_asa_config(cfg, "fw1")
    packed = pack.pack_rulesets([rs])
    rng = random.Random(7)
    ifaces = ["abc", "fe80", "def0", "a:b", "x", "0", "eth0"]
    addrs = ["1.2.3.4", "::1", "fe80::1", "1:2:3:4:5:6:7:8", "::ffff:1.2.3.4",
             "1:2:3:4:5:6:1.2.3.4", "2001:db8::9", "::", "abcd::", "a::b",
             "1:::2", "1::2::3", ":::", "1.2.3", "1.2.3.4.5", "12345::",
             "g::1", "0:0:0:0:0:0:0:0", "::1.2.3.999",
             "1:2:3:4:5:6:7:1.2.3.4",
             "ffff:ffff:ffff:ffff:ffff:ffff:ffff:ffff"]
    msgs = []
    for _ in range(1500):
        a, b = rng.choice(addrs), rng.choice(addrs)
        fi, fo = rng.choice(ifaces), rng.choice(ifaces)
        sp, dp = rng.randrange(1 << 17), rng.randrange(1 << 17)
        k = rng.randrange(5)
        if k == 0:
            msgs.append(f"J 1 0 fw1 : %ASA-6-106100: access-list A permitted tcp {fi}/{a}({sp}) -> {fo}/{b}({dp}) hit")
        elif k == 1:
            msgs.append(f'J 1 0 fw1 : %ASA-4-106023: Deny udp src {fi}:{a}/{sp} dst {fo}:{b}/{dp} by access-group "A"')
        elif k == 2:
            msgs.append(f'J 1 0 fw1 : %ASA-4-106023: Deny icmp6 src {fi}:{a} dst {fo}:{b} (type 128, code 0) by access-group "A"')
        elif k == 3:
            msgs.append(f"J 1 0 fw1 : %ASA-6-302013: Built inbound TCP connection 7 for {fi}:{a}/{sp} ({a}/{sp}) to {fo}:{b}/{dp} ({b}/{dp})")
        else:
            msgs.append(f"J 1 0 fw1 : %ASA-2-106001: Inbound TCP connection denied from {a}/{sp} to {b}/{dp} flags SYN on interface {fi}")
    py = pack.LinePacker(packed)
    r4, r6 = py.pack_lines2(msgs, batch_size=4 * len(msgs))
    nat = fastparse.NativePacker(packed)
    g4, g6 = nat.pack_lines2(msgs, batch_size=4 * len(msgs))
    np.testing.assert_array_equal(r4, g4)
    np.testing.assert_array_equal(r6, g6)
    assert (py.parsed, py.skipped) == (nat.parsed, nat.skipped)


def test_native_v6_mt_bit_identical_to_single_thread():
    """asa_pack_chunk2's worker/compaction path (n_threads>1) must match
    the sequential loop bit-for-bit, including line-atomic batch closes."""
    from ruleset_analysis_tpu.hostside import fastparse, synth

    if not fastparse.available():
        pytest.skip("no native toolchain")
    cfg_text = synth.synth_config(
        n_acls=3, rules_per_acl=10, seed=9, v6_fraction=0.5, egress_acls=True
    )
    rs = aclparse.parse_asa_config(cfg_text, "fw1")
    packed = pack.pack_rulesets([rs])
    t4 = synth.synth_tuples(packed, 1500, seed=9)
    t6 = synth.synth_tuples6(packed, 1200, seed=9)
    lines = synth.render_syslog(packed, t4, seed=9, variety=0.4)
    lines += synth.render_syslog6(packed, t6, seed=10)
    random.Random(9).shuffle(lines)
    data = ("\n".join(lines) + "\n").encode()

    for cap in (4096, 700):  # ample AND batch-closing capacities
        p1 = fastparse.NativePacker(packed)
        o1, l1, u1 = p1.pack_chunk(data, cap, final=True, max_lines=cap,
                                   n_threads=1)
        r61 = p1.take_v6()
        p4 = fastparse.NativePacker(packed)
        o4, l4, u4 = p4.pack_chunk(data, cap, final=True, max_lines=cap,
                                   n_threads=4)
        r64 = p4.take_v6()
        assert (l1, u1) == (l4, u4)
        np.testing.assert_array_equal(o1, o4)
        np.testing.assert_array_equal(np.asarray(r61), np.asarray(r64))
        assert (p1.parsed, p1.skipped) == (p4.parsed, p4.skipped)


def test_synth_v6_variety_corpus_end_to_end_and_native_parity():
    """v6 variety tier (all message classes, binding-resolved): oracle-
    exact through the stream AND bit-identical across both parsers."""
    from ruleset_analysis_tpu.hostside import fastparse, synth

    cfg_text = synth.synth_config(
        n_acls=3, rules_per_acl=10, seed=44, v6_fraction=0.5, egress_acls=True
    )
    rs = aclparse.parse_asa_config(cfg_text, "fw1")
    packed = pack.pack_rulesets([rs])
    t6 = synth.synth_tuples6(packed, 800, seed=44)
    lines = synth.render_syslog6(packed, t6, seed=45, variety=0.5)
    res = oracle.Oracle([rs]).consume(list(lines))
    rep = run_stream(packed, iter(lines), run_cfg(), topk=5)
    assert report_hits(rep) == dict(res.hits)
    assert rep.totals["lines_matched"] == res.lines_matched
    if fastparse.available():
        py = pack.LinePacker(packed)
        r4, r6 = py.pack_lines2(lines, batch_size=4 * len(lines))
        nat = fastparse.NativePacker(packed)
        g4, g6 = nat.pack_lines2(lines, batch_size=4 * len(lines))
        np.testing.assert_array_equal(r4, g4)
        np.testing.assert_array_equal(r6, g6)
        assert (py.parsed, py.skipped) == (nat.parsed, nat.skipped)


def test_stacked_text_v6_matches_flat(corpus):
    """Single-process stacked layout over the mixed text corpus."""
    packed, rs, lines, res = corpus
    rep = run_stream(packed, iter(lines), run_cfg(layout="stacked"), topk=5)
    assert report_hits(rep) == dict(res.hits)
    assert rep.unused == res.unused_rules([rs])


def test_106023_src_mid_token_colon_backtracks_like_regex():
    """The 106023 SRC endpoint must try LATER colon splits when an earlier
    one leaves token residue (regex \\s+dst backtracking); the DST endpoint
    commits to its first structural split (followed by .*?by).  Soak-found
    divergence pinned."""
    from ruleset_analysis_tpu.hostside import fastparse

    if not fastparse.available():
        pytest.skip("no native toolchain")
    cfg = (
        "access-list A extended permit ip any any\n"
        "access-group A in interface outside\n"
    )
    rs = aclparse.parse_asa_config(cfg, "fw1")
    packed = pack.pack_rulesets([rs])
    lines = [
        # src splits at the SECOND colon (first leaves "side:..." residue)
        'J 1 0 fw1 : %ASA-4-106023: Deny icmp src inside:1side:172.17.70.70 '
        'dst outside:198.51.0.225 (type 9, code 0) by access-group "A"',
        # dst with the same shape: first split commits, value "1" invalid,
        # line skips (both parsers)
        'J 1 0 fw1 : %ASA-4-106023: Deny tcp src inside:10.0.0.1/1 '
        'dst outside:1side:1.2.3.4/2 by access-group "A"',
        # port residue in the src token also backtracks/fails structurally
        'J 1 0 fw1 : %ASA-4-106023: Deny tcp src inside:2.3.4.5/19x '
        'dst outside:1.2.3.4/2 by access-group "A"',
    ]
    py = pack.LinePacker(packed)
    r4, _ = py.pack_lines2(lines, batch_size=8)
    nat = fastparse.NativePacker(packed)
    g4, _ = nat.pack_lines2(lines, batch_size=8)
    np.testing.assert_array_equal(r4, g4)
    assert (py.parsed, py.skipped) == (nat.parsed, nat.skipped)
    # and the first line really did parse (src = 172.17.70.70)
    assert py.parsed >= 1


def test_zero_valid_v4_batches_skip_device_step():
    """A mostly-IPv6 corpus must not step all-invalid v4 device chunks:
    _TextSource yields (None, n_raw) for zero-fill batches and the driver
    accounts the raw lines without a v4 step (ADVICE r5 #3)."""
    from ruleset_analysis_tpu.runtime.stream import _TextSource

    rs = aclparse.parse_asa_config(CFG, "fw1")
    packed = pack.pack_rulesets([rs])
    v6_only = mixed_lines(512, seed=11, v6_share=1.0)
    src = _TextSource(packed, iter(v6_only))
    got = list(src.batches(0, 128))
    # every batch is a zero-fill marker: raw lines accounted, no array
    assert [n for _b, n in got] == [128, 128, 128, 128]
    assert all(b is None for b, _n in got)
    assert src.packer.parsed > 0  # the v6 rows went to the side channel
    assert len(src.take_v6()) == src.packer.parsed

    # end-to-end: the driver steps ONLY v6 chunks for this corpus and the
    # report still matches the oracle exactly
    res = oracle.Oracle([rs]).consume(list(v6_only))
    cfg128 = run_cfg().replace(batch_size=128)
    rep = run_stream(packed, iter(v6_only), cfg128, topk=5)
    assert report_hits(rep) == dict(res.hits)
    assert rep.totals["lines_total"] == 512
    assert rep.totals["lines_matched"] == res.lines_matched
    # chunk count covers the v6 program only — the pre-fix driver stepped
    # one all-invalid v4 chunk per 128 v6 raw lines on top of these
    evals = res.lines_matched
    assert rep.totals["chunks"] == -(-evals // 128)  # ceil: v6 chunks alone


def test_convert_python_tier_handles_zero_valid_batches(tmp_path):
    """The python-tier converter must survive (None, n_raw) zero-v4
    batches from _TextSource: raw-line/skip accounting still lands in the
    wire header, and the wire run matches the oracle exactly."""
    from ruleset_analysis_tpu.hostside import wire
    from ruleset_analysis_tpu.runtime.stream import run_stream_wire

    rs = aclparse.parse_asa_config(CFG, "fw1")
    packed = pack.pack_rulesets([rs])
    v6_only = mixed_lines(300, seed=12, v6_share=1.0)
    p = tmp_path / "v6.log"
    p.write_text("\n".join(v6_only) + "\n")
    out = str(tmp_path / "v6.rawire")
    stats = wire.convert_logs(
        packed, [str(p)], out, native=False, batch_size=128, block_rows=128
    )
    assert stats["raw_lines"] == 300
    assert stats["rows"] == 0  # no v4 evaluation rows at all
    assert stats["rows6"] > 0
    res = oracle.Oracle([rs]).consume(list(v6_only))
    rep = run_stream_wire(packed, out, run_cfg(), topk=5)
    assert report_hits(rep) == dict(res.hits)
    assert rep.totals["lines_total"] == 300
