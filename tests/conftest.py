"""Test harness setup.

Must run before any jax import: force the CPU backend with 8 fake devices so
multi-chip sharding tests (SURVEY.md §5 "multi-node without a cluster") run
anywhere, exactly as they would on a real v5e-8 mesh.

This environment injects a TPU plugin via a ``sitecustomize`` on
``PYTHONPATH`` that registers itself at interpreter start — before any of
this runs — and can hang the whole process at backend init when the device
tunnel is down (setting ``JAX_PLATFORMS=cpu`` here is too late to stop it).
So when that hook is detected, pytest re-execs itself once in a scrubbed
environment (in ``pytest_configure``, after restoring the captured stdout
fds); the fresh process never sees the plugin.  No test module imports jax
before configure time, so the hostile process never reaches backend init.
"""

import os
import sys

_AXON_MARKER = ".axon_site"


def _needs_reexec() -> bool:
    return (
        _AXON_MARKER in os.environ.get("PYTHONPATH", "")
        and os.environ.get("RA_TEST_REEXEC") != "1"
    )


def _scrubbed_env() -> dict:
    # one source of truth for the scrub recipe: the driver entry module
    from __graft_entry__ import scrubbed_cpu_env

    env = scrubbed_cpu_env(8)
    env["RA_TEST_REEXEC"] = "1"
    return env


def pytest_configure(config):
    if not _needs_reexec():
        return
    # restore the real stdout/stderr fds that pytest's global capture
    # dup2'ed away, so the re-exec'd run is visible to the invoker
    capman = config.pluginmanager.getplugin("capturemanager")
    if capman is not None:
        capman.stop_global_capturing()
    os.execve(sys.executable, [sys.executable, "-m", "pytest", *sys.argv[1:]], _scrubbed_env())


if not _needs_reexec():
    os.environ["JAX_PLATFORMS"] = "cpu"
    # Disable the flight recorder's DEFAULT-on CLI arming (DESIGN §20)
    # so incidental cli.main() invocations across the suite don't write
    # out/blackbox forensics into the working tree; tests that exercise
    # the recorder pass an explicit --blackbox-dir, which overrides this.
    os.environ.setdefault("RA_BLACKBOX", "off")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    # NOTE: do NOT point RA_XLA_CACHE_DIR at a shared cache here to speed
    # the spawned workers up — on this jaxlib the XLA:CPU persistent
    # cache reloads executables that compute WRONG register values
    # (observed: corrupted HLL registers in the distributed wire test).
    # runtime/compcache.py skips the CPU cache by default for exactly
    # this class of problem.

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import time as _time  # noqa: E402

import pytest  # noqa: E402  (after the re-exec guard above)

# ---------------------------------------------------------------------------
# Test-calibration note (carried forward from PR 10): the 2 in-suite
# distributed flakes occasionally seen on this 1-core container are LOAD
# artifacts — xla:cpu collective/heartbeat timeouts when the host is
# oversubscribed — not product bugs.  Do not chase them, and NEVER run
# anything concurrently with the tier-1 gate run (a parallel build or
# bench steals the core and manufactures exactly these failures).
# ---------------------------------------------------------------------------

# ---------------------------------------------------------------------------
# Tier-1 wall-budget guard (ROADMAP: the `-m 'not slow'` suite must stay
# under the 870 s gate, with headroom).  Suite-budget discipline is part
# of the test contract — new variant tests share compiles and mark
# redundant matrix cells `slow` — and this hook makes an overrun a FAILED
# run instead of a silent drift toward the external timeout.  Active only
# for full-suite sessions (small selections tell nothing about the gate).
# ---------------------------------------------------------------------------

_T1_GATE_SEC = float(os.environ.get("RA_T1_GATE_SEC", "870"))
_T1_WARN_FRAC = 0.92  # loudly flag runs inside the last 8% of the gate
_T1_MIN_TESTS = 400  # below this the session is a hand-picked subset
_t1_start = _time.monotonic()


def pytest_sessionfinish(session, exitstatus):
    collected = getattr(session, "testscollected", 0)
    if collected < _T1_MIN_TESTS:
        return
    # only the `-m 'not slow'` tier is governed by the gate: a full run
    # INCLUDING the slow soak legitimately exceeds it and must not be
    # turned into a spurious failure
    markexpr = getattr(session.config.option, "markexpr", "") or ""
    if "not slow" not in markexpr:
        return
    dur = _time.monotonic() - _t1_start
    frac = dur / _T1_GATE_SEC
    line = (
        f"[t1-budget] {dur:.1f}s of the {_T1_GATE_SEC:.0f}s tier-1 gate "
        f"({100 * frac:.1f}%, {collected} tests)"
    )
    if dur > _T1_GATE_SEC:
        print(f"{line} — EXCEEDED: mark redundant cells `slow` or share "
              "compiles (see ROADMAP tier-1 verify)", file=sys.stderr)
        if exitstatus == 0:
            session.exitstatus = 1
    elif frac > _T1_WARN_FRAC:
        print(f"{line} — WARNING: inside the gate's last "
              f"{100 * (1 - _T1_WARN_FRAC):.0f}%", file=sys.stderr)
    else:
        print(line, file=sys.stderr)


@pytest.fixture(autouse=True)
def _no_leaked_workers():
    """Fail any test that leaks our worker threads or child processes.

    Every thread the pipeline spawns carries an ``ra-`` name prefix
    (ingest producers, feed workers, heartbeats, watchdogs) and every
    worker process is a ``multiprocessing`` child, so a cheap enumerate
    catches a shutdown path that stranded one — the audit the chaos
    harness relies on ("zero leaked threads/processes").  A short grace
    window absorbs teardown still in flight; the zero-leak case costs
    one enumerate and no sleep.
    """
    yield
    import multiprocessing
    import threading
    import time

    def leaked():
        ts = [
            t
            for t in threading.enumerate()
            if t.is_alive() and t.name.startswith("ra-")
        ]
        return ts, multiprocessing.active_children()

    deadline = time.monotonic() + 5.0
    ts, procs = leaked()
    while (ts or procs) and time.monotonic() < deadline:
        for p in procs:
            p.join(timeout=0.1)
        time.sleep(0.05)
        ts, procs = leaked()
    assert not ts and not procs, (
        f"leaked workers after test: threads={[t.name for t in ts]} "
        f"processes={[p.pid for p in procs]}"
    )
