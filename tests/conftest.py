"""Test harness setup.

Must run before any jax import: force the CPU backend with 8 fake devices so
multi-chip sharding tests (SURVEY.md §5 "multi-node without a cluster") run
anywhere, exactly as they would on a real v5e-8 mesh.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
