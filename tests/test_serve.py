"""Always-on serve mode: listeners, windowed registers, hot reload.

Pins the three serve invariants (ISSUE 6; DESIGN §12):

- **Window fidelity**: each published window report is bit-identical to
  an offline ``run_stream`` over exactly that window's lines, and
  merging K rotated epoch registers is bit-identical to a single replay
  over the concatenated traffic (the ``_merge_tail`` laws, host-side) —
  flat x v4/v6 x text/wire.
- **Drop accounting**: a line that cannot be delivered is counted and
  the overlapping window carries an explicit WindowIncomplete marker —
  never a silent zero-hit window (the chaos side lives in test_chaos).
- **Reload migration**: a live re-pack maps counters through rule
  identity (renumber/insert/delete), quarantines unmappable keys with
  exact accounting, and a failed reload changes nothing.
"""

import json
import os
import socket
import threading
import time
import urllib.request

import numpy as np
import pytest

pytest.importorskip("jax")

from ruleset_analysis_tpu.config import AnalysisConfig, ServeConfig, SketchConfig
from ruleset_analysis_tpu.errors import AnalysisError, InjectedFault
from ruleset_analysis_tpu.hostside import aclparse, pack, synth
from ruleset_analysis_tpu.hostside.listener import (
    FileTailer, LineQueue, UdpSyslogListener, parse_listen_spec,
)
from ruleset_analysis_tpu.runtime import checkpoint as ckpt
from ruleset_analysis_tpu.runtime import report as report_mod
from ruleset_analysis_tpu.runtime.serve import (
    ServeDriver, build_migration, merge_register_arrays, migrate_arrays,
    migrate_tracker_tables, window_incomplete, zero_arrays,
)
from ruleset_analysis_tpu.runtime.stream import run_stream, run_stream_wire

#: volatile totals excluded from bit-identity images (same list as the
#: chaos harness, plus the serve-only window/hll blocks compared apart)
# ONE volatile-keys list (runtime/report.py): the registry auditor
# (verify/registry.py) flags any module keeping a private copy.
from ruleset_analysis_tpu.runtime.report import VOLATILE_TOTALS as VOLATILE

def image(obj) -> dict:
    if not isinstance(obj, dict):
        obj = json.loads(obj.to_json())
    obj = json.loads(json.dumps(obj))
    for k in VOLATILE:
        obj["totals"].pop(k, None)
    obj["totals"].pop("window", None)
    return obj


@pytest.fixture(scope="module")
def corpus(tmp_path_factory):
    """v4+v6 packed ruleset + 600 mixed lines + wire form."""
    td = tmp_path_factory.mktemp("serve")
    cfg_text = synth.synth_config(
        n_acls=2, rules_per_acl=8, seed=0, v6_fraction=0.25
    )
    rs = aclparse.parse_asa_config(cfg_text, "fw1")
    packed = pack.pack_rulesets([rs])
    prefix = str(td / "rules")
    pack.save_packed(packed, prefix)
    t = synth.synth_tuples(packed, 500, seed=1)
    lines = synth.render_syslog(packed, t, seed=1)
    t6 = synth.synth_tuples6(packed, 100, seed=2)
    lines += synth.render_syslog6(packed, t6, seed=3)
    return packed, prefix, lines, str(td)


RUN_CFG = dict(batch_size=128, prefetch_depth=0)


def serve_cfg(**kw) -> AnalysisConfig:
    return AnalysisConfig(**{**RUN_CFG, **kw})


def start_serve(prefix, cfg, scfg):
    drv = ServeDriver(prefix, cfg, scfg)
    out: dict = {}

    def runner():
        try:
            out["summary"] = drv.run()
        except BaseException as e:  # surfaced by finish()
            out["error"] = e

    th = threading.Thread(target=runner)
    th.start()
    deadline = time.monotonic() + 20
    while time.monotonic() < deadline:
        if out.get("error"):
            break
        if drv.listeners.listeners and drv.listeners.alive() and (
            scfg.http == "off" or drv.http_address
        ):
            break
        time.sleep(0.05)
    return drv, th, out


def finish(th, out, timeout=120):
    th.join(timeout=timeout)
    assert not th.is_alive(), "serve hung"
    if "error" in out:
        raise out["error"]
    return out["summary"]


def send_tcp(addr, lines):
    s = socket.create_connection(addr)
    s.sendall(("\n".join(lines) + "\n").encode())
    s.close()


def get_json(http, path, retries=3):
    host, port = http
    for attempt in range(retries):
        try:
            with urllib.request.urlopen(
                f"http://{host}:{port}{path}", timeout=10
            ) as r:
                return json.load(r)
        except (urllib.error.URLError, ConnectionError, OSError):
            # transient reset under parallel-suite load; the endpoint
            # itself staying down fails the surrounding wait_for anyway
            if attempt == retries - 1:
                raise
            time.sleep(0.2)


def wait_for(pred, timeout=60, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.05)
    raise AssertionError(f"timed out waiting for {msg}")


# ---------------------------------------------------------------------------
# Listener tier (no device work).
# ---------------------------------------------------------------------------


def test_line_queue_counts_drops_explicitly():
    q = LineQueue(capacity=3)
    assert all(q.put(f"l{i}") for i in range(3))
    assert not q.put("overflow-1")
    assert not q.put("overflow-2")
    snap = q.snapshot()
    assert snap == {
        "capacity": 3, "depth": 3, "received": 5, "dropped": 2,
        "forced_drops": 0,
    }
    assert q.pop() == "l0"  # FIFO survives the overflow


def test_parse_listen_spec():
    assert parse_listen_spec("udp:127.0.0.1:514") == ("udp", "127.0.0.1", 514)
    assert parse_listen_spec("tcp:0.0.0.0:6514") == ("tcp", "0.0.0.0", 6514)
    assert parse_listen_spec("tail:/var/log/asa.log") == (
        "tail", "", "/var/log/asa.log",
    )
    assert parse_listen_spec("tail0:/var/log/asa.log") == (
        "tail0", "", "/var/log/asa.log",
    )
    for bad in ("udp:nohost", "udp:h:xx", "smtp:1:2", "tail:", "tail0:"):
        with pytest.raises(AnalysisError):
            parse_listen_spec(bad)


def test_udp_listener_roundtrip():
    q = LineQueue(1024)
    ln = UdpSyslogListener(q, "127.0.0.1", 0)
    ln.start()
    try:
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        for i in range(20):
            s.sendto(f"msg {i}\n".encode(), ln.address)
        s.close()
        wait_for(lambda: q.snapshot()["received"] == 20, 10, "udp delivery")
    finally:
        ln.close()
    got = []
    while True:
        line = q.pop(timeout=0.05)
        if line is None:
            break
        got.append(line)
    assert got == [f"msg {i}" for i in range(20)]
    assert q.snapshot()["dropped"] == 0


def test_file_tailer_follows_rotation(tmp_path):
    path = str(tmp_path / "spool.log")
    q = LineQueue(1024)
    tailer = FileTailer(q, path, poll_sec=0.02)
    tailer.start()
    try:
        # file appears after the tailer: read from the start
        with open(path, "w") as f:
            f.write("a1\na2\n")
        wait_for(lambda: q.snapshot()["received"] == 2, 10, "pre-rotation lines")
        # rotate: rename away, recreate; no post-rotation line may be lost
        os.rename(path, path + ".1")
        with open(path, "w") as f:
            f.write("b1\nb2\nb3\n")
        wait_for(lambda: q.snapshot()["received"] == 5, 10, "post-rotation lines")
    finally:
        tailer.close()
    got = [q.pop(0.05) for _ in range(5)]
    assert got == ["a1", "a2", "b1", "b2", "b3"]


# ---------------------------------------------------------------------------
# Epoch ring merge laws: merging K rotated epochs == one replay over the
# concatenated traffic (flat x v4/v6 x text/wire), at the REGISTER level.
# ---------------------------------------------------------------------------


def _final_arrays(run, out_dir):
    """Final register image of a driver run, via the checkpoint plane."""
    cfg = serve_cfg(checkpoint_every_chunks=10_000, checkpoint_dir=out_dir)
    run(cfg)
    snap = ckpt.load(out_dir)
    assert snap is not None
    return snap.arrays


@pytest.mark.parametrize("kind", ["text", "wire"])
def test_epoch_ring_merge_law(corpus, tmp_path, kind):
    packed, _prefix, lines, td = corpus
    cuts = [0, 150, 370, 600]  # deliberately uneven windows
    if kind == "wire":
        from ruleset_analysis_tpu.hostside import wire as wire_mod

        paths = []
        for i in range(len(cuts) - 1):
            p = str(tmp_path / f"seg{i}.rawire")
            wire_mod.convert_logs(
                packed,
                [_write_lines(tmp_path, f"seg{i}", lines[cuts[i]:cuts[i + 1]])],
                p, block_rows=256,
            )
            paths.append(p)
        full = str(tmp_path / "full.rawire")
        wire_mod.convert_logs(
            packed, [_write_lines(tmp_path, "full", lines)], full, block_rows=256
        )
        seg_arrays = [
            _final_arrays(
                lambda cfg, p=p: run_stream_wire(packed, p, cfg),
                str(tmp_path / f"ck{os.path.basename(p)}"),
            )
            for p in paths
        ]
        full_arrays = _final_arrays(
            lambda cfg: run_stream_wire(packed, full, cfg),
            str(tmp_path / "ckfull"),
        )
    else:
        seg_arrays = [
            _final_arrays(
                lambda cfg, i=i: run_stream(
                    packed, iter(lines[cuts[i]:cuts[i + 1]]), cfg
                ),
                str(tmp_path / f"ck{i}"),
            )
            for i in range(len(cuts) - 1)
        ]
        full_arrays = _final_arrays(
            lambda cfg: run_stream(packed, iter(lines), cfg),
            str(tmp_path / "ckf"),
        )
    merged = merge_register_arrays(seg_arrays)
    for field in full_arrays:
        assert np.array_equal(merged[field], full_arrays[field]), (
            f"{kind}: merged epoch register {field} != single-replay register"
        )
    # associativity: ((a+b)+c) == (a+(b+c)) — ring merges compose
    left = merge_register_arrays(
        [merge_register_arrays(seg_arrays[:2]), seg_arrays[2]]
    )
    right = merge_register_arrays(
        [seg_arrays[0], merge_register_arrays(seg_arrays[1:])]
    )
    for field in left:
        assert np.array_equal(left[field], right[field])


def _write_lines(tmp_path, name, lines) -> str:
    p = str(tmp_path / f"{name}.log")
    with open(p, "w", encoding="utf-8") as f:
        f.write("\n".join(lines) + "\n")
    return p


# ---------------------------------------------------------------------------
# End-to-end serve: rotations + HTTP endpoint + live reload (acceptance).
# ---------------------------------------------------------------------------

OLD_CFG = """\
hostname fwx
access-list A extended permit tcp any host 10.0.0.5 eq 443
access-list A extended permit udp any host 10.0.0.6 eq 53
access-list A extended deny tcp any host 10.0.0.7 eq 22
access-list B extended permit ip any any
access-group A in interface outside
"""

#: renumber + insert + delete vs OLD_CFG: a new rule lands at index 1
#: (everything below renumbers), the udp rule is deleted; key count stays
#: equal so the compiled step geometry is shared across the reload.
NEW_CFG = """\
hostname fwx
access-list A extended permit tcp any host 10.9.9.9 eq 8080
access-list A extended permit tcp any host 10.0.0.5 eq 443
access-list A extended deny tcp any host 10.0.0.7 eq 22
access-list B extended permit ip any any
access-group A in interface outside
"""


def _fwx_lines(n, seed):
    import random

    rng = random.Random(seed)
    out = []
    for i in range(n):
        acl = "A" if rng.random() < 0.8 else "B"
        dst, port, proto = rng.choice([
            ("10.0.0.5", 443, "tcp"),
            ("10.0.0.6", 53, "udp"),
            ("10.0.0.7", 22, "tcp"),
            ("10.8.8.8", 80, "tcp"),
        ])
        src = f"10.1.{rng.randrange(4)}.{rng.randrange(1, 250)}"
        out.append(
            f"Jul 29 07:48:{i % 60:02d} fwx : %ASA-6-106100: access-list "
            f"{acl} permitted {proto} inside/{src}({rng.randrange(1024, 60000)})"
            f" -> outside/{dst}({port}) hit-cnt 1 first hit [0x0, 0x0]"
        )
    return out


@pytest.fixture(scope="module")
def reload_corpus(tmp_path_factory):
    td = tmp_path_factory.mktemp("reload")
    old_packed = pack.pack_rulesets([aclparse.parse_asa_config(OLD_CFG, "fwx")])
    new_packed = pack.pack_rulesets([aclparse.parse_asa_config(NEW_CFG, "fwx")])
    assert old_packed.n_keys == new_packed.n_keys  # shared step geometry
    prefix = str(td / "fwx")
    pack.save_packed(old_packed, prefix)
    lines = _fwx_lines(600, seed=7)
    return old_packed, new_packed, prefix, lines, str(td)


def test_serve_e2e_rotations_and_live_reload(reload_corpus, tmp_path):
    """The acceptance scenario: loopback socket, 3 window rotations, one
    live ruleset reload mid-window; published reports (fetched via the
    JSON endpoint) are bit-identical to offline batch runs per window,
    drop count 0, migrated counters exact, quarantine exact."""
    old_packed, new_packed, prefix, lines, td = reload_corpus
    W = 200
    cfg = serve_cfg()
    scfg = ServeConfig(
        listen=("tcp:127.0.0.1:0",),
        window_lines=W,
        ring=4,
        serve_dir=str(tmp_path / "serve"),
        # NOT max_windows=3: that would tear the HTTP endpoint down the
        # instant window 2 rotates, racing the fetches below — the test
        # stops the service explicitly once it has read everything
        max_windows=0,
        stop_after_sec=90,
        reload_watch=False,
        queue_lines=10_000,
    )
    drv, th, out = start_serve(prefix, cfg, scfg)
    try:
        addr = drv.listeners.listeners[0].address
        http = drv.http_address
        # window 0 (old ruleset) + first half of window 1; the id check
        # makes the wait race-free against an in-flight rotation (the
        # current_window block flips to id 1 only once window 1 is open)
        send_tcp(addr, lines[:300])
        wait_for(
            lambda: (
                lambda cw: cw["id"] >= 1 and cw["pushed"] >= 100
            )(get_json(http, "/health")["current_window"]),
            60, "w0 rotation + half of w1 consumed",
        )
        # live reload: re-pack the renumbered ruleset mid-stream
        pack.save_packed(new_packed, prefix)
        drv.request_reload()
        wait_for(
            lambda: get_json(http, "/health")["reloads"] == 1, 30, "reload"
        )
        # second half of window 1 + all of window 2 (new ruleset)
        send_tcp(addr, lines[300:600])
        wait_for(
            lambda: get_json(http, "/health")["windows_published"] >= 3,
            60, "3 windows",
        )
        health = get_json(http, "/health")
        w0 = get_json(http, "/report/window/0")
        w1 = get_json(http, "/report/window/1")
        w2 = get_json(http, "/report/window/2")
        cum = get_json(http, "/report/cumulative")
        diff = get_json(http, "/diff")
        merged2 = get_json(http, "/report/merged/2")
        # refuse-don't-shrink, same rule ServeConfig applies to --view:
        # asking for more windows than the ring retains is a 400, not a
        # silently-thinner answer
        for bad in (0, scfg.ring + 1):
            with pytest.raises(urllib.error.HTTPError) as ei:
                get_json(http, f"/report/merged/{bad}", retries=1)
            assert ei.value.code == 400
    finally:
        drv.stop()
        summary = finish(th, out)

    assert summary["windows_published"] == 3
    assert summary["drops"] == 0 and health["queue"]["dropped"] == 0
    assert summary["reloads"] == 1 and summary["reload_errors"] == 0

    # windows 0 (pre-reload) and 2 (post-reload) are pure: bit-identical
    # to offline batch runs over the same per-window traffic
    assert image(w0) == image(run_stream(old_packed, iter(lines[:W]), cfg))
    assert image(w2) == image(run_stream(new_packed, iter(lines[400:]), cfg))
    for rep in (w0, w1, w2):
        assert window_incomplete(rep) is None

    # window 1 contains the reload: migrated counters must be EXACT —
    # migrate(offline old over its first half) merged with offline new
    # over its second half, computed through the same register plane
    ck_a, ck_b = str(tmp_path / "cka"), str(tmp_path / "ckb")
    run_stream(
        old_packed, iter(lines[200:300]),
        serve_cfg(checkpoint_every_chunks=10_000, checkpoint_dir=ck_a),
    )
    run_stream(
        new_packed, iter(lines[300:400]),
        serve_cfg(checkpoint_every_chunks=10_000, checkpoint_dir=ck_b),
    )
    mig = build_migration(old_packed, new_packed)
    arrays_a, quarantine = migrate_arrays(
        ckpt.load(ck_a).arrays, mig, old_packed, cfg
    )
    expected = merge_register_arrays([arrays_a, ckpt.load(ck_b).arrays])
    u64 = np.uint64
    exp_counts = expected["counts_lo"].astype(u64) + (
        expected["counts_hi"].astype(u64) << u64(32)
    )
    got_counts = {
        (e["firewall"], e["acl"], e["index"]): e["hits"]
        for e in w1["per_rule"]
    }
    for kid, meta in enumerate(new_packed.key_meta):
        assert got_counts[(meta.firewall, meta.acl, meta.index)] == int(
            exp_counts[kid]
        ), f"migrated counter for {meta.firewall}/{meta.acl}/{meta.index}"

    # quarantine: the deleted udp rule's pre-reload hits, exact, reported
    assert list(quarantine) == [
        ("fwx", "A", 2, "access-list A extended permit udp any host 10.0.0.6 eq 53")
    ]
    q1 = w1["totals"]["quarantine"]
    assert q1["hits"] == sum(quarantine.values()) > 0
    assert q1["rules"][0]["rule"] == "fwx A 2"
    # cumulative quarantine covers every pre-reload window (w0 included)
    full_old = run_stream(old_packed, iter(lines[:300]), cfg)
    old_hits = {
        (e["firewall"], e["acl"], e["index"]): e["hits"]
        for e in json.loads(full_old.to_json())["per_rule"]
    }
    assert cum["totals"]["quarantine"]["hits"] == old_hits[("fwx", "A", 2)]

    # the window-over-window diff published via the diff machinery
    assert diff["windows"] == [1, 2]
    assert set(diff) >= {"stable_unused", "newly_unused", "newly_used"}

    # a valid merged view names exactly the windows it covers
    assert merged2["totals"]["window"]["merged_windows"] == [1, 2]


def test_serve_wallclock_windows_and_partial_stop(reload_corpus, tmp_path):
    """Wall-clock cadence rotates without traffic; a stop publishes the
    final partial window with an explicit partial marker."""
    _old, _new, prefix, lines, _td = reload_corpus
    cfg = serve_cfg()
    scfg = ServeConfig(
        listen=("tcp:127.0.0.1:0",),
        window_sec=1.0,
        ring=4,
        serve_dir=str(tmp_path / "serve"),
        max_windows=2,
        stop_after_sec=30,
        reload_watch=False,
        checkpoint_every_windows=0,
        http="off",
    )
    drv, th, out = start_serve(prefix, cfg, scfg)
    try:
        send_tcp(drv.listeners.listeners[0].address, lines[:120])
    finally:
        summary = finish(th, out)
    assert summary["windows_published"] == 2
    reps = sorted(
        f for f in os.listdir(scfg.serve_dir) if f.startswith("window-")
    )
    assert len(reps) == 2
    total = 0
    for f in reps:
        rep = json.load(open(os.path.join(scfg.serve_dir, f)))
        assert rep["totals"]["window"]["mode"] == "sec"
        total += rep["totals"]["lines_total"]
    assert total == 120  # every received line lands in exactly one window


def test_serve_ring_checkpoint_resume(reload_corpus, tmp_path):
    """A restarted serve keeps its window history (ring + cumulative)."""
    old_packed, _new, prefix, lines, _td = reload_corpus
    # a fresh prefix: the module one may have been re-packed by the e2e
    prefix2 = str(tmp_path / "fwx")
    pack.save_packed(old_packed, prefix2)
    cfg = serve_cfg()

    def scfg(**kw):
        return ServeConfig(
            listen=("tcp:127.0.0.1:0",),
            window_lines=100,
            ring=4,
            serve_dir=str(tmp_path / "serve"),
            stop_after_sec=60,
            reload_watch=False,
            queue_lines=10_000,
            **kw,
        )

    drv, th, out = start_serve(prefix2, cfg, scfg(max_windows=2))
    try:
        send_tcp(drv.listeners.listeners[0].address, lines[:200])
    finally:
        summary = finish(th, out)
    assert summary["windows_published"] == 2

    # restart with --resume: history intact, ids continue, cumulative
    # covers the pre-restart traffic
    drv2, th2, out2 = start_serve(
        prefix2, cfg.replace(resume=True), scfg(max_windows=3)
    )
    try:
        h = drv2.health()
        assert h["windows_published"] == 2
        assert h["window"]["ring_windows"] == [0, 1]
        # the restored history is served IMMEDIATELY (no 404 until the
        # next rotation): /report answers with the pre-restart last
        # window and /report/window/<id> covers the whole restored ring
        assert drv2.published("report") is not None
        assert drv2.published("cumulative") is not None
        w0 = drv2.window_report(0)
        assert w0 is not None and w0["totals"]["window"]["id"] == 0
        assert image(w0) == image(run_stream(old_packed, iter(lines[:100]), cfg))
        send_tcp(drv2.listeners.listeners[0].address, lines[200:300])
    finally:
        summary2 = finish(th2, out2)
    assert summary2["windows_published"] == 3
    cum = json.load(open(os.path.join(str(tmp_path / "serve"), "cumulative.json")))
    off = run_stream(old_packed, iter(lines[:300]), cfg)
    assert image(cum)["per_rule"] == image(off)["per_rule"]
    assert image(cum)["unused"] == image(off)["unused"]
    w2 = json.load(open(os.path.join(str(tmp_path / "serve"), "window-000002.json")))
    assert image(w2) == image(run_stream(old_packed, iter(lines[200:300]), cfg))

    # resume against a different ruleset is a typed refusal
    other = pack.pack_rulesets([aclparse.parse_asa_config(NEW_CFG, "fwx")])
    prefix3 = str(tmp_path / "other")
    pack.save_packed(other, prefix3)
    drv3 = ServeDriver(prefix3, cfg.replace(resume=True), scfg(max_windows=3))
    with pytest.raises(ckpt.CheckpointMismatch):
        drv3.run()


# ---------------------------------------------------------------------------
# Migration unit laws: renumber / insert / delete, quarantine exact.
# ---------------------------------------------------------------------------


def _mk(cfg_text):
    return pack.pack_rulesets([aclparse.parse_asa_config(cfg_text, "fwx")])


def test_migration_map_identity():
    p = _mk(OLD_CFG)
    mig = build_migration(p, p)
    assert mig.identity
    arrays = zero_arrays(p.n_keys, serve_cfg())
    arrays["counts_lo"][:] = 7
    out, q = migrate_arrays(arrays, mig, p, serve_cfg())
    assert q == {} and np.array_equal(out["counts_lo"], arrays["counts_lo"])


def test_migration_renumber_insert_delete_exact():
    old, new = _mk(OLD_CFG), _mk(NEW_CFG)
    cfg = serve_cfg()
    mig = build_migration(old, new)
    assert not mig.identity
    # old key 0 = 443 rule (now index 2 -> new key 1); old key 1 = deleted
    # udp rule; old key 2 = ssh deny (now new key 2); implicit denies map
    assert mig.key_map[0] == 1
    assert mig.key_map[1] == -1
    assert mig.key_map[2] == 2
    arrays = zero_arrays(old.n_keys, cfg)
    hits = np.arange(1, old.n_keys + 1, dtype=np.uint32)  # distinct counts
    arrays["counts_lo"][:] = hits
    arrays["hll"][:, 0] = hits  # a marker rank per key row
    out, quarantine = migrate_arrays(arrays, mig, old, cfg)
    # every mapped key keeps its exact count at the NEW position
    for kid in range(old.n_keys):
        t = int(mig.key_map[kid])
        if t >= 0:
            assert out["counts_lo"][t] == hits[kid]
            assert out["hll"][t, 0] == hits[kid]  # HLL rows travel
    # the inserted rule starts at zero
    assert out["counts_lo"][0] == 0
    # the deleted rule's count is quarantined, exact, never dropped
    assert quarantine == {
        ("fwx", "A", 2, "access-list A extended permit udp any host 10.0.0.6 eq 53"): 2,
    }
    # conservation: mapped + quarantined == everything that existed
    assert int(out["counts_lo"].sum()) + sum(quarantine.values()) == int(
        hits.sum()
    )


def test_migration_64bit_counts_exact():
    old, new = _mk(OLD_CFG), _mk(NEW_CFG)
    cfg = serve_cfg()
    mig = build_migration(old, new)
    arrays = zero_arrays(old.n_keys, cfg)
    arrays["counts_lo"][1] = 0xFFFFFFFF  # deleted rule, carry-heavy count
    arrays["counts_hi"][1] = 3
    arrays["counts_lo"][0] = 5
    out, quarantine = migrate_arrays(arrays, mig, old, cfg)
    key = ("fwx", "A", 2, "access-list A extended permit udp any host 10.0.0.6 eq 53")
    assert quarantine[key] == (3 << 32) + 0xFFFFFFFF
    assert out["counts_lo"][1] == 5 and out["counts_hi"][1] == 0


def test_migration_tracker_regid():
    old, new = _mk(OLD_CFG), _mk(NEW_CFG)
    mig = build_migration(old, new)
    gid_a = old.acl_gid[("fwx", "A")]
    tables = {gid_a: {123: 9}, 0x80000000 | gid_a: {77: 4}}
    out, dropped = migrate_tracker_tables(tables, mig)
    ng = new.acl_gid[("fwx", "A")]
    assert out == {ng: {123: 9}, 0x80000000 | ng: {77: 4}}
    assert dropped == 0
    # an ACL that disappears drops its talkers, counted
    mig2 = build_migration(old, _mk("hostname fwx\naccess-list B extended permit ip any any\n"))
    out2, dropped2 = migrate_tracker_tables(tables, mig2)
    assert out2 == {} and dropped2 == 2


def test_reload_failure_is_atomic(reload_corpus, tmp_path):
    """reload.midbatch firing leaves the old tensor + counters serving:
    the published reports are bit-identical to a no-reload run."""
    old_packed, new_packed, _prefix, lines, _td = reload_corpus
    prefix = str(tmp_path / "fwx")
    pack.save_packed(old_packed, prefix)
    cfg = serve_cfg(fault_plan="reload.midbatch@1")
    scfg = ServeConfig(
        listen=("tcp:127.0.0.1:0",),
        window_lines=150,
        ring=4,
        serve_dir=str(tmp_path / "serve"),
        max_windows=2,
        stop_after_sec=60,
        reload_watch=False,
        checkpoint_every_windows=0,
        queue_lines=10_000,
        http="off",
    )
    drv, th, out = start_serve(prefix, cfg, scfg)
    try:
        send_tcp(drv.listeners.listeners[0].address, lines[:150])
        wait_for(lambda: drv.windows_published >= 1, 60, "w0")
        pack.save_packed(new_packed, prefix)  # new bits on disk...
        drv.request_reload()  # ...but the swap dies at the fault site
        wait_for(lambda: drv.reload_errors == 1, 30, "failed reload")
        send_tcp(drv.listeners.listeners[0].address, lines[150:300])
    finally:
        summary = finish(th, out)
    assert summary["reloads"] == 0 and summary["reload_errors"] == 1
    assert summary["quarantine_hits"] == 0
    # both windows still analyzed under the OLD ruleset, bit-identical
    for i, seg in ((0, lines[:150]), (1, lines[150:300])):
        rep = json.load(
            open(os.path.join(scfg.serve_dir, f"window-{i:06d}.json"))
        )
        assert image(rep) == image(run_stream(old_packed, iter(seg), cfg.replace(fault_plan="")))


def test_reload_flush_step_failure_aborts_typed(reload_corpus, tmp_path):
    """A device-step failure inside the reload's pre-swap flush is NOT a
    recoverable reload error: the batcher tail is already consumed at
    that point, so swallowing it would publish a window missing
    delivered lines with no marker — the service aborts typed instead."""
    old_packed, new_packed, _prefix, lines, _td = reload_corpus
    prefix = str(tmp_path / "fwx")
    pack.save_packed(old_packed, prefix)
    # hits 1+2 are window 0's 128-line chunk + its 22-line rotation
    # flush; hit 3 is the reload flush of the 100 in-flight window-1
    # lines (100 < batch 128, so no chunk boundary fires in between).
    # :99 makes the fault PERSISTENT past the device_put retry budget —
    # a single fire would now be absorbed by the retry engine.
    cfg = serve_cfg(fault_plan="stream.device_put.fail@3:99")
    scfg = ServeConfig(
        listen=("tcp:127.0.0.1:0",),
        window_lines=150,
        ring=4,
        serve_dir=str(tmp_path / "serve"),
        max_windows=0,
        stop_after_sec=60,
        reload_watch=False,
        checkpoint_every_windows=0,
        queue_lines=10_000,
        http="off",
    )
    drv, th, out = start_serve(prefix, cfg, scfg)
    try:
        send_tcp(drv.listeners.listeners[0].address, lines[:150])
        wait_for(lambda: drv.windows_published >= 1, 60, "w0")
        send_tcp(drv.listeners.listeners[0].address, lines[150:250])
        wait_for(
            lambda: getattr(drv, "win_pushed", 0) >= 100, 30, "w1 in flight"
        )
        pack.save_packed(new_packed, prefix)
        drv.request_reload()
        with pytest.raises(InjectedFault):
            finish(th, out, timeout=60)
    finally:
        if th.is_alive():
            drv.stop()
            th.join(timeout=30)
    # the failure was an abort, never misfiled as an atomic reload error
    assert drv.reload_errors == 0 and drv.reloads == 0


def test_http_bind_failure_is_typed_construction_error(corpus, tmp_path):
    """An unbindable --http port fails at construction (where the CLI's
    'cannot bind --listen/--http' handler catches it, exit 2), not
    mid-run after listeners already started."""
    _packed, prefix, _lines, _td = corpus
    blocker = socket.socket()
    try:
        blocker.bind(("127.0.0.1", 0))
        port = blocker.getsockname()[1]
        with pytest.raises(OSError):
            ServeDriver(prefix, serve_cfg(), ServeConfig(
                listen=("tcp:127.0.0.1:0",),
                window_lines=100,
                serve_dir=str(tmp_path / "s"),
                http=f"127.0.0.1:{port}",
            ))
    finally:
        blocker.close()


def test_serve_missing_ruleset_is_typed(tmp_path, capsys):
    """A bad --ruleset prefix is a typed load error, never misreported
    as the listener bind failure the construction handler covers."""
    from ruleset_analysis_tpu import cli

    rc = cli.main([
        "serve", "--ruleset", str(tmp_path / "nope"),
        "--listen", "udp:127.0.0.1:0", "--window", "lines:100",
        "--serve-dir", str(tmp_path / "s"),
    ])
    err = capsys.readouterr().err
    assert rc == 1
    assert "cannot read packed ruleset" in err and "cannot bind" not in err


# ---------------------------------------------------------------------------
# HLL band + hint; diff --expect-window.
# ---------------------------------------------------------------------------


def test_hll_band_and_hint_in_report(corpus):
    packed, _prefix, lines, _td = corpus
    rep = run_stream(
        packed, iter(lines[:200]), serve_cfg(sketch=SketchConfig(hll_p=10))
    )
    obj = json.loads(rep.to_json())
    hll = obj["totals"]["hll"]
    assert hll["p"] == 10 and hll["m"] == 1024
    assert hll["rel_err_p90"] == round(1.04 / 32, 4)
    # tiny per-rule cardinalities vs a 1024-register sketch: a concrete
    # smaller --hll-p recommendation must appear
    assert "hint" in hll and "--hll-p" in hll["hint"]
    text = rep.to_text()
    assert "% p90)" in text and "# hint:" in text


def test_diff_expect_window_typed_refusal(tmp_path):
    from ruleset_analysis_tpu import cli

    def fake_report(mode, length, wid=0):
        return {
            "totals": {"window": {"mode": mode, "length": length, "id": wid}},
            "per_rule": [], "unused": [], "talkers": {},
        }

    a = tmp_path / "a.json"
    b = tmp_path / "b.json"
    a.write_text(json.dumps(fake_report("lines", 200)))
    b.write_text(json.dumps(fake_report("lines", 200, wid=1)))
    assert cli.main(["diff-reports", str(a), str(b), "--expect-window", "lines:200"]) == 0
    # mismatched window length: typed refusal, not a misleading diff
    b.write_text(json.dumps(fake_report("lines", 500, wid=1)))
    assert cli.main(["diff-reports", str(a), str(b), "--expect-window", "lines:200"]) == 1
    # a batch report has no window at all
    c = tmp_path / "c.json"
    c.write_text(json.dumps({"per_rule": [], "unused": [], "totals": {}}))
    assert cli.main(["diff-reports", str(a), str(c), "--expect-window", "lines:200"]) == 1
    # without the flag the diff still works (back-compat)
    assert cli.main(["diff-reports", str(a), str(b)]) == 0
    with pytest.raises(AnalysisError):
        report_mod.parse_window_spec("lines:banana")
    assert report_mod.parse_window_spec("24h") == ("sec", 86400.0)


def test_serve_cli_tail_roundtrip(tmp_path):
    """CLI wiring: `serve` with a tail0 listener replays a pre-written
    spool, publishes a window and the endpoint file, then exits on
    --max-windows."""
    from ruleset_analysis_tpu import cli

    old_packed = _mk(OLD_CFG)
    prefix = str(tmp_path / "fwx")
    pack.save_packed(old_packed, prefix)
    spool = str(tmp_path / "spool.log")
    serve_dir = str(tmp_path / "serve")
    # written BEFORE serve starts: tail0 reads an existing spool from
    # offset 0 (plain tail would race the listener start and seek past)
    with open(spool, "w") as f:
        f.write("\n".join(_fwx_lines(100, seed=3)) + "\n")
    rc = {}
    th = threading.Thread(
        target=lambda: rc.update(rc=cli.main([
            "serve", "--ruleset", prefix, "--listen", f"tail0:{spool}",
            "--window", "lines:100", "--serve-dir", serve_dir,
            "--max-windows", "1", "--stop-after", "60",
            "--batch-size", "128", "--http", "127.0.0.1:0",
            "--no-reload-watch",
        ]))
    )
    th.start()
    th.join(timeout=120)
    assert not th.is_alive() and rc["rc"] == 0
    ep = json.load(open(os.path.join(serve_dir, "endpoint.json")))
    assert ep["http"] and ep["listeners"]
    rep = json.load(open(os.path.join(serve_dir, "window-000000.json")))
    assert rep["totals"]["lines_total"] == 100
    assert rep["totals"]["window"]["id"] == 0


# ---------------------------------------------------------------------------
# Durable ingest WAL (ISSUE 14 / DESIGN §19): a hard abort mid-window
# loses NOTHING that was consumed — serve --resume replays the spool
# tail and the interrupted window publishes bit-identical over its
# delivered lines.  The seeded chaos variant lives in test_chaos.py.
# ---------------------------------------------------------------------------


def test_wal_resume_after_hard_abort_bit_identical(corpus, tmp_path):
    from ruleset_analysis_tpu.runtime.wal import WriteAheadLog

    packed, prefix, lines, _td = corpus
    lines = lines[:150]  # v4 prefix of the corpus
    serve_dir = str(tmp_path / "serve")
    # batch 32 so window 1 dispatches a chunk mid-window; hit 5 is that
    # chunk (window 0 = 3 full chunks + 1 rotation-flush chunk), and
    # :99 keeps failing past the device_put retry budget -> the run
    # dies TYPED mid-window-1 with no graceful shutdown accounting —
    # the in-process stand-in for a SIGKILL (same on-disk state)
    cfg = serve_cfg(
        batch_size=32, fault_plan="stream.device_put.fail@5:99"
    )
    scfg = ServeConfig(
        listen=("tcp:127.0.0.1:0",), window_lines=100, ring=4,
        serve_dir=serve_dir, stop_after_sec=60, reload_watch=False,
        checkpoint_every_windows=1, http="off", queue_lines=10_000,
        wal=True,
    )
    drv, th, out = start_serve(prefix, cfg, scfg)
    send_tcp(drv.listeners.listeners[0].address, lines)
    th.join(timeout=120)
    assert not th.is_alive(), "serve hung"
    assert isinstance(out.get("error"), InjectedFault), out
    assert drv.windows_published == 1  # window 0 landed + checkpointed

    # ground truth: what the spool durably holds past the checkpoint
    wal = WriteAheadLog(os.path.join(serve_dir, "wal"))
    delivered = [line for _seq, line, _t in wal.replay(100)]
    wal.close()
    assert delivered, "window 1 consumed lines before the abort"
    assert delivered == lines[100:100 + len(delivered)]  # prefix, no gap

    # resume: replay the tail, then stop gracefully -> the interrupted
    # window publishes over exactly the delivered lines
    cfg2 = serve_cfg(batch_size=32, resume=True)
    drv2, th2, out2 = start_serve(prefix, cfg2, scfg)
    try:
        wait_for(
            lambda: getattr(drv2, "wal_replayed", 0) == len(delivered),
            60, "wal replay",
        )
    finally:
        drv2.stop()
        summary = finish(th2, out2)
    assert summary["wal"]["replayed"] == len(delivered)
    assert summary["wal"]["lost"] == 0 and not summary["wal"]["lost_unknown"]
    assert summary["windows_published"] == 2
    base_cfg = serve_cfg(batch_size=32)
    # window 0: restored history, bit-identical to offline lines[:100]
    w0 = json.load(open(os.path.join(serve_dir, "window-000000.json")))
    want0 = image(run_stream(packed, iter(lines[:100]), base_cfg, topk=10))
    assert image(w0) == want0
    # window 1: the interrupted window, REPLAYED — bit-identical over
    # the delivered lines, with no incomplete marker (nothing was lost)
    w1 = json.load(open(os.path.join(serve_dir, "window-000001.json")))
    want1 = image(run_stream(packed, iter(delivered), base_cfg, topk=10))
    assert image(w1) == want1
    # the stop-time partial window may carry the usual shutdown marker
    # (ingress closes before the final rotate) — but it must claim ZERO
    # loss: nothing dropped, nothing wal_lost (full replay)
    inc = window_incomplete(w1)
    if inc is not None:
        assert inc["drops"] == 0 and "wal_lost" not in inc["reasons"], inc
    assert summary["drops"] == 0


def test_wal_eviction_gap_marks_window_incomplete(corpus, tmp_path):
    """A resume whose checkpoint seq predates the surviving WAL head
    (budget eviction while down) publishes the replayed window with the
    wal_lost incomplete reason and the EXACT gap count."""
    from ruleset_analysis_tpu.runtime.wal import WriteAheadLog

    packed, prefix, lines, _td = corpus
    lines = lines[:80]
    serve_dir = str(tmp_path / "serve")
    scfg = ServeConfig(
        listen=("tcp:127.0.0.1:0",), window_lines=100, ring=4,
        serve_dir=serve_dir, stop_after_sec=60, reload_watch=False,
        checkpoint_every_windows=1, http="off", queue_lines=10_000,
        wal=True, wal_segment_bytes=4096, wal_budget_bytes=8192,
    )
    cfg = serve_cfg(batch_size=32, fault_plan="stream.device_put.fail@1:99")
    drv, th, out = start_serve(prefix, cfg, scfg)
    send_tcp(drv.listeners.listeners[0].address, lines)
    th.join(timeout=120)
    assert isinstance(out.get("error"), InjectedFault), out

    # simulate eviction while down: the tiny budget already evicted, or
    # we force it by appending junk traffic directly to the spool
    wal = WriteAheadLog(
        os.path.join(serve_dir, "wal"), segment_bytes=4096, budget_bytes=8192
    )
    consumed = wal.next_seq
    for i in range(400):  # push the spool far past its budget
        wal.append(f"evict-filler {i} {'x' * 80}")
    assert wal.evicted_records > 0
    wal.close()

    cfg2 = serve_cfg(batch_size=32, resume=True)
    drv2, th2, out2 = start_serve(prefix, cfg2, scfg)
    try:
        wait_for(
            lambda: getattr(drv2, "wal_replayed", 0) > 0, 60, "wal replay"
        )
    finally:
        drv2.stop()
        summary = finish(th2, out2)
    w = summary["wal"]
    assert w["lost"] > 0 and not w["lost_unknown"]
    # exact accounting: replayed + lost == everything ever spooled
    assert w["replayed"] + w["lost"] == consumed + 400
    w0 = json.load(open(os.path.join(serve_dir, "window-000000.json")))
    inc = window_incomplete(w0)
    assert inc and "wal_lost" in inc["reasons"]
    assert inc["drops"] >= w["lost"]


# ---------------------------------------------------------------------------
# Degraded-mode serving (ISSUE 14): non-core failures mark the service
# degraded — /health enumerates the set, reports carry totals.degraded,
# recovery re-arms — while ingest keeps serving.
# ---------------------------------------------------------------------------


def test_publisher_degrades_and_recovers(corpus, tmp_path):
    """serve.publish.fail@1:99 (past the retry budget): disk publication
    degrades, in-memory endpoints keep serving, and the next successful
    write re-arms the publisher."""
    from ruleset_analysis_tpu.runtime import faults

    packed, prefix, lines, _td = corpus
    lines = lines[:200]
    scfg = ServeConfig(
        listen=("tcp:127.0.0.1:0",), window_lines=100, ring=4,
        serve_dir=str(tmp_path / "serve"), stop_after_sec=60,
        reload_watch=False, checkpoint_every_windows=0, http="off",
        queue_lines=10_000,
    )
    cfg = serve_cfg(fault_plan="serve.publish.fail@1:99")
    drv, th, out = start_serve(prefix, cfg, scfg)
    try:
        addr = drv.listeners.listeners[0].address
        send_tcp(addr, lines[:100])
        wait_for(lambda: drv.windows_published >= 1, 60, "window 0")
        # degraded, not dead: the window exists in memory, not on disk
        assert "publisher" in drv.health()["degraded_subsystems"]
        assert drv.window_report(0) is not None
        assert not os.path.exists(
            os.path.join(scfg.serve_dir, "window-000000.json")
        )
        # ingest is alive and the window report carries totals.degraded
        assert drv.window_report(0)["totals"]["degraded"] == ["publisher"]
        # the fault clears (transient outage ends): next publish re-arms
        faults.disarm()
        send_tcp(addr, lines[100:200])
        wait_for(lambda: drv.windows_published >= 2, 60, "window 1")
        wait_for(
            lambda: "publisher" not in drv.health()["degraded_subsystems"],
            30, "publisher recovery",
        )
        assert os.path.exists(
            os.path.join(scfg.serve_dir, "window-000001.json")
        )
    finally:
        drv.stop()
        summary = finish(th, out)
    assert summary["degraded"] == []
    assert summary["degraded_events"] >= 1
    assert summary["recovered_events"] >= 1


def test_publisher_transient_retry_recovers_silently(corpus, tmp_path):
    """serve.publish.fail@2:2 (below the attempt bound): the retry
    absorbs the burst — files land on disk, nothing ever degrades."""
    from ruleset_analysis_tpu.runtime import retrypolicy

    packed, prefix, lines, _td = corpus
    scfg = ServeConfig(
        listen=("tcp:127.0.0.1:0",), window_lines=100, ring=4,
        serve_dir=str(tmp_path / "serve"), stop_after_sec=60,
        reload_watch=False, checkpoint_every_windows=0, http="off",
        queue_lines=10_000,
    )
    cfg = serve_cfg(fault_plan="serve.publish.fail@2:2")
    drv, th, out = start_serve(prefix, cfg, scfg)
    try:
        send_tcp(drv.listeners.listeners[0].address, lines[:100])
        wait_for(lambda: drv.windows_published >= 1, 60, "window 0")
    finally:
        drv.stop()
        summary = finish(th, out)
    assert summary["degraded"] == []
    assert summary["retry"].get("serve.publish", {}).get("recoveries", 0) >= 1
    assert os.path.exists(os.path.join(scfg.serve_dir, "window-000000.json"))


def test_degraded_static_and_metrics_recover(corpus, tmp_path):
    """Injected static-analysis + metrics failures leave ingest serving:
    /health enumerates the degraded set, window reports carry
    totals.degraded, and recovery (fault clears; reload re-analyzes)
    re-arms both subsystems."""
    from ruleset_analysis_tpu.runtime import faults, obs

    packed, prefix, lines, _td = corpus
    lines = lines[:200]
    scfg = ServeConfig(
        listen=("tcp:127.0.0.1:0",), window_lines=100, ring=4,
        serve_dir=str(tmp_path / "serve"), stop_after_sec=90,
        reload_watch=False, checkpoint_every_windows=0, http="off",
        queue_lines=10_000, static_analysis=True,
    )
    cfg = serve_cfg(
        fault_plan="analyze.tile@1,metrics.snapshot.fail@1:99"
    )
    obs.start_metrics(str(tmp_path / "m.jsonl"), every_sec=0.05)
    try:
        drv, th, out = start_serve(prefix, cfg, scfg)
        try:
            addr = drv.listeners.listeners[0].address
            send_tcp(addr, lines[:100])
            wait_for(lambda: drv.windows_published >= 1, 60, "window 0")
            wait_for(
                lambda: {"static_analysis", "metrics"}
                <= set(drv.health()["degraded_subsystems"]),
                30, "degraded set",
            )
            h = drv.health()
            assert h["status"] == "degraded"
            # ingest kept serving: the window report exists and says
            # which subsystems were down while it was earned
            w0 = drv.window_report(0)
            assert w0 is not None
            assert set(w0["totals"]["degraded"]) >= {"static_analysis"}
            assert drv.published("static") is None  # no partial table, ever
            # recovery: the faults clear; a reload re-analyzes (static)
            # and the snapshotter's next clean tick re-arms (metrics)
            faults.disarm()
            drv.request_reload()
            wait_for(
                lambda: not drv.health()["degraded_subsystems"],
                60, "recovery re-arms",
            )
            assert drv.published("static") is not None
        finally:
            drv.stop()
            summary = finish(th, out)
    finally:
        obs.shutdown(merge=False)
    assert summary["degraded"] == []
    assert summary["degraded_events"] >= 2
    assert summary["recovered_events"] >= 2
