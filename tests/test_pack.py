"""Packing tests: rule matrix layout, key universe, line packing, serialization."""

import numpy as np

from ruleset_analysis_tpu.hostside import aclparse, pack, synth

CFG = """\
hostname fw1
access-list A extended permit tcp any host 10.0.0.5 eq 443
access-list A extended deny ip any any
access-list B extended permit udp any any eq 53
access-group A in interface outside
"""


def packed_fixture():
    rs = aclparse.parse_asa_config(CFG, "fw1")
    return pack.pack_rulesets([rs]), rs


def test_key_universe():
    packed, _ = packed_fixture()
    assert packed.n_rules == 3
    assert packed.n_acls == 2
    assert packed.n_keys == 5
    # deny keys come after rule keys, one per ACL
    gid_a = packed.acl_gid[("fw1", "A")]
    meta = packed.key_meta[int(packed.deny_key[gid_a])]
    assert meta.implicit_deny and meta.acl == "A" and meta.index == 0


def test_rule_rows_in_config_order():
    packed, _ = packed_fixture()
    rules = packed.rules
    real = rules[:, pack.R_ACL] != pack.NO_ACL
    assert int(real.sum()) == 3
    keys = rules[real][:, pack.R_KEY]
    assert list(keys) == sorted(keys)  # config order preserved


def test_padding_rows_never_match():
    rs = aclparse.parse_asa_config(CFG, "fw1")
    packed = pack.pack_rulesets([rs], pad_rules_to=16)
    assert packed.rules.shape == (16, pack.RULE_COLS)
    padding = packed.rules[3:]
    assert (padding[:, pack.R_ACL] == pack.NO_ACL).all()
    # ranges are [0, 0] which cannot contain any port>0, and acl_gid can
    # never equal a real gid
    assert (padding[:, pack.R_PHI] == 0).all()


def test_bindings_resolve_to_gid():
    packed, _ = packed_fixture()
    assert packed.bindings[("fw1", "outside")] == packed.acl_gid[("fw1", "A")]


def test_line_packer():
    packed, _ = packed_fixture()
    lp = pack.LinePacker(packed)
    lines = [
        "Jul 29 07:48:01 fw1 : %ASA-6-106100: access-list A permitted tcp "
        "inside/1.2.3.4(1000) -> outside/10.0.0.5(443) hit-cnt 1 first hit [0x0, 0x0]",
        "garbage line",
        "Jul 29 07:48:01 fw1 : %ASA-6-106100: access-list NOPE permitted tcp "
        "inside/1.2.3.4(1000) -> outside/10.0.0.5(443) hit-cnt 1 first hit [0x0, 0x0]",
    ]
    batch = lp.pack_lines(lines, batch_size=8)
    assert batch.shape == (8, pack.TUPLE_COLS)
    assert batch[0, pack.T_VALID] == 1
    assert batch[0, pack.T_ACL] == packed.acl_gid[("fw1", "A")]
    assert batch[0, pack.T_DPORT] == 443
    assert int(batch[:, pack.T_VALID].sum()) == 1
    assert lp.skipped == 2


def test_save_load_roundtrip(tmp_path):
    packed, _ = packed_fixture()
    prefix = str(tmp_path / "rules")
    pack.save_packed(packed, prefix)
    loaded = pack.load_packed(prefix)
    np.testing.assert_array_equal(loaded.rules, packed.rules)
    assert loaded.n_rules == packed.n_rules
    assert loaded.acl_gid == packed.acl_gid
    assert loaded.bindings == packed.bindings
    assert loaded.key_meta[0].acl == packed.key_meta[0].acl


def test_multi_firewall_pack():
    rs1 = aclparse.parse_asa_config(CFG, "fw1")
    rs2 = aclparse.parse_asa_config(
        "access-list Z extended permit ip any any\n", "fw2"
    )
    packed = pack.pack_rulesets([rs1, rs2])
    assert packed.n_acls == 3
    assert ("fw2", "Z") in packed.acl_gid
    # keys remain globally unique across firewalls
    assert packed.n_keys == packed.n_rules + packed.n_acls


def test_synth_config_parses_and_packs():
    text = synth.synth_config(n_acls=2, rules_per_acl=8, seed=3)
    rs = aclparse.parse_asa_config(text, "fw1")
    assert rs.rule_count() == 16
    packed = pack.pack_rulesets([rs])
    assert packed.n_rules == 16
    tuples = synth.synth_tuples(packed, 100, seed=3)
    assert tuples.shape == (100, pack.TUPLE_COLS)
    assert tuples[:, pack.T_VALID].all()


def test_pack_overflow_is_clean_error():
    import pytest

    packed, _ = packed_fixture()
    lp = pack.LinePacker(packed)
    line = (
        "Jul 29 07:48:01 fw1 : %ASA-6-106100: access-list A permitted tcp "
        "inside/1.2.3.4(1000) -> outside/10.0.0.5(443) hit-cnt 1 first hit [0x0, 0x0]"
    )
    with pytest.raises(ValueError, match="batch_size"):
        lp.pack_lines([line] * 8, batch_size=4)


def test_load_packed_rejects_inverted_range(tmp_path):
    """A stale artifact with an inverted lo/hi range must fail loudly.

    Under the wraparound predicate (x - lo) <= (hi - lo) an inverted
    range matches nearly everything, silently inflating that rule's hits
    and hiding it from the unused set (ADVICE r4, medium) — so load
    refuses the matrix instead of shipping it.
    """
    import pytest

    from ruleset_analysis_tpu.errors import AnalysisError

    packed, _ = packed_fixture()
    prefix = str(tmp_path / "stale")
    # simulate a pre-wraparound-check build: dport range [443, 80]
    packed.rules[0, pack.R_DPLO] = 443
    packed.rules[0, pack.R_DPHI] = 80
    pack.save_packed(packed, prefix)
    with pytest.raises(AnalysisError, match=r"row 0.*inverted dport|inverted dport.*row 0"):
        pack.load_packed(prefix)


def test_validate_rule_ranges_accepts_padding_and_full_ranges():
    rs = aclparse.parse_asa_config(CFG, "fw1")
    packed = pack.pack_rulesets([rs], pad_rules_to=32)
    pack.validate_rule_ranges(packed.rules)  # must not raise
