"""out-direction access-groups + the 106001/106006/106015 deny classes.

SURVEY.md §4.3 defines the mapper over the ASA access-list / connection
message family with the ACL resolved from the configuration's
``access-group`` bindings.  These tests pin:

- (iface, direction)-keyed binding parsing (both in and out on one iface),
- the three additional deny message classes in the Python parser,
- dual evaluation of connection lines (ingress in-ACL + egress out-ACL)
  in the oracle, the Python packer, and the native C++ packer,
- end-to-end equality of the TPU backend against the oracle on an
  egress-bound config with a mixed-message corpus.
"""

import numpy as np
import pytest

pytest.importorskip("jax")

from ruleset_analysis_tpu.config import AnalysisConfig, SketchConfig
from ruleset_analysis_tpu.hostside import aclparse, fastparse, oracle, pack, synth
from ruleset_analysis_tpu.hostside.syslog import parse_line
from ruleset_analysis_tpu.runtime.stream import run_stream


EGRESS_CFG = """
hostname fwx
access-list INBOUND extended permit tcp any host 10.0.0.5 eq 443
access-list INBOUND extended deny ip any any
access-list OUTBOUND extended permit tcp 10.0.0.0 255.0.0.0 any eq 443
access-list OUTBOUND extended deny ip any any
access-list MGMT extended permit udp any any eq 514
access-group INBOUND in interface outside
access-group OUTBOUND out interface dmz
access-group MGMT in interface dmz
"""

# inbound TCP conn outside -> dmz: evaluated against INBOUND (in on
# outside) AND OUTBOUND (out on dmz)
CONN_DUAL = (
    "Jul 29 01:02:03 fwx : %ASA-6-302013: Built inbound TCP connection 11 "
    "for outside:10.1.2.3/1234 (10.1.2.3/1234) to dmz:10.0.0.5/443 (10.0.0.5/443)"
)
# inbound conn landing on an interface with no out-binding: single eval
CONN_SINGLE = (
    "Jul 29 01:02:03 fwx : %ASA-6-302013: Built inbound TCP connection 12 "
    "for outside:10.1.2.3/1234 (10.1.2.3/1234) to inside:10.0.0.5/443 (10.0.0.5/443)"
)
DENY_106001 = (
    "Jul 29 01:02:03 fwx : %ASA-2-106001: Inbound TCP connection denied from "
    "10.9.9.9/5555 to 10.0.0.5/443 flags SYN on interface outside"
)
DENY_106006 = (
    "Jul 29 01:02:03 fwx : %ASA-2-106006: Deny inbound UDP from "
    "10.9.9.9/5555 to 10.0.0.7/514 on interface dmz"
)
DENY_106015 = (
    "Jul 29 01:02:03 fwx : %ASA-6-106015: Deny TCP (no connection) from "
    "10.9.9.9/5555 to 10.0.0.5/443 flags RST ACK on interface outside"
)


def _setup():
    rs = aclparse.parse_asa_config(EGRESS_CFG, "fwx")
    packed = pack.pack_rulesets([rs])
    return rs, packed


class TestBindings:
    def test_both_directions_on_one_interface(self):
        rs, packed = _setup()
        assert rs.bindings[("outside", "in")] == "INBOUND"
        assert rs.bindings[("dmz", "out")] == "OUTBOUND"
        assert rs.bindings[("dmz", "in")] == "MGMT"
        gid = packed.acl_gid[("fwx", "OUTBOUND")]
        assert packed.bindings_out[("fwx", "dmz")] == gid
        assert packed.bindings[("fwx", "dmz")] == packed.acl_gid[("fwx", "MGMT")]

    def test_save_load_roundtrip_keeps_out_bindings(self, tmp_path):
        _, packed = _setup()
        prefix = str(tmp_path / "p")
        pack.save_packed(packed, prefix)
        loaded = pack.load_packed(prefix)
        assert loaded.bindings_out == packed.bindings_out


class TestParseNewClasses:
    def test_106001(self):
        p = parse_line(DENY_106001)
        assert p is not None
        assert (p.proto, p.sport, p.dport) == (6, 5555, 443)
        assert p.ingress_if == "outside" and p.acl is None and p.egress_if is None
        assert p.permitted is False

    def test_106006(self):
        p = parse_line(DENY_106006)
        assert p is not None
        assert (p.proto, p.sport, p.dport) == (17, 5555, 514)
        assert p.ingress_if == "dmz"

    def test_106015(self):
        p = parse_line(DENY_106015)
        assert p is not None
        assert (p.proto, p.sport, p.dport) == (6, 5555, 443)
        assert p.ingress_if == "outside"

    def test_302013_carries_egress_interface(self):
        p = parse_line(CONN_DUAL)
        assert p is not None
        assert p.ingress_if == "outside" and p.egress_if == "dmz"
        out = parse_line(
            "Jul 29 01:02:03 fwx : %ASA-6-302013: Built outbound TCP connection 9 "
            "for dmz:8.8.8.8/443 (8.8.8.8/443) to inside:10.2.3.4/5999 (10.2.3.4/5999)"
        )
        assert out is not None
        # outbound: initiated at the "to" side; packet exits the "for" side
        assert out.ingress_if == "inside" and out.egress_if == "dmz"
        assert out.src == aclparse.ip_to_u32("10.2.3.4")


class TestDualEvaluation:
    def test_oracle_counts_both_acls(self):
        rs, _ = _setup()
        res = oracle.Oracle([rs]).consume([CONN_DUAL])
        assert res.hits[("fwx", "INBOUND", 1)] == 1
        assert res.hits[("fwx", "OUTBOUND", 1)] == 1
        assert res.lines_total == 1
        assert res.lines_matched == 2  # evaluations
        assert res.lines_skipped == 0

    def test_oracle_single_when_no_out_binding(self):
        rs, _ = _setup()
        res = oracle.Oracle([rs]).consume([CONN_SINGLE])
        assert res.lines_matched == 1
        assert res.hits[("fwx", "INBOUND", 1)] == 1

    def test_line_packer_emits_two_rows(self):
        _, packed = _setup()
        lp = pack.LinePacker(packed)
        batch = lp.pack_lines([CONN_DUAL], batch_size=4)
        assert int(batch[:, pack.T_VALID].sum()) == 2
        gids = set(batch[batch[:, pack.T_VALID] == 1][:, pack.T_ACL].tolist())
        assert gids == {
            packed.acl_gid[("fwx", "INBOUND")],
            packed.acl_gid[("fwx", "OUTBOUND")],
        }
        assert lp.parsed == 2 and lp.skipped == 0

    def test_deny_classes_resolve_via_in_binding(self):
        rs, _ = _setup()
        res = oracle.Oracle([rs]).consume([DENY_106001, DENY_106006, DENY_106015])
        # 106001/106015 hit INBOUND rule 1 (443 permit); 106006 hits MGMT 514
        assert res.hits[("fwx", "INBOUND", 1)] == 2
        assert res.hits[("fwx", "MGMT", 1)] == 1


@pytest.mark.skipif(not fastparse.available(), reason="no native toolchain")
class TestNativeParity:
    CORPUS = [
        CONN_DUAL, CONN_SINGLE, DENY_106001, DENY_106006, DENY_106015,
        # near-misses the native parser must also skip
        "Jul 29 x fwx : %ASA-2-106001: Inbound TCP connection denied from "
        "10.9.9.9/5555 to 10.0.0.5/443 on interface outside",  # no flags
        "Jul 29 x fwx : %ASA-2-106006: Deny inbound UDP from 10.9.9.9/5555 "
        "to 10.0.0.7/514 due to DNS Query on interface dmz",  # not adjacent
        "Jul 29 x fwx : %ASA-6-106015: Deny TCP (no  connection) from "
        "10.9.9.9/5555 to 10.0.0.5/443 flags RST on interface outside",  # 2 spaces
        "Jul 29 x fwx : %ASA-2-106001: Inbound TCP connection denied from "
        "10.9.9.9/5555 to 10.0.0.5/443 flags SYN on interface",  # no iface
    ]

    def test_handwritten_corpus_bit_identical(self):
        _, packed = _setup()
        py = pack.LinePacker(packed)
        ref = py.pack_lines(self.CORPUS, batch_size=32)
        nat = fastparse.NativePacker(packed)
        got = nat.pack_lines(self.CORPUS, batch_size=32)
        np.testing.assert_array_equal(ref, got)
        assert (py.parsed, py.skipped) == (nat.parsed, nat.skipped)
        assert py.parsed == 6 and py.skipped == 4  # dual conn counts twice

    def test_synth_variety_corpus_bit_identical(self):
        cfg_text = synth.synth_config(
            n_acls=3, rules_per_acl=16, seed=11, egress_acls=True
        )
        rs = aclparse.parse_asa_config(cfg_text, "fw1")
        packed = pack.pack_rulesets([rs])
        tuples = synth.synth_tuples(packed, 4000, seed=12)
        lines = synth.render_syslog(packed, tuples, seed=13, variety=0.5)
        py = pack.LinePacker(packed)
        ref = py.pack_lines(lines, batch_size=2 * len(lines))
        nat = fastparse.NativePacker(packed)
        got = nat.pack_lines(lines, batch_size=2 * len(lines))
        np.testing.assert_array_equal(ref, got)
        assert (py.parsed, py.skipped) == (nat.parsed, nat.skipped)
        assert py.parsed > len(lines) - py.skipped  # some dual evaluations

    def test_multithread_with_dual_rows(self):
        cfg_text = synth.synth_config(
            n_acls=2, rules_per_acl=12, seed=21, egress_acls=True
        )
        rs = aclparse.parse_asa_config(cfg_text, "fw1")
        packed = pack.pack_rulesets([rs])
        tuples = synth.synth_tuples(packed, 3000, seed=22)
        lines = synth.render_syslog(packed, tuples, seed=23, variety=0.6)
        data = ("\n".join(lines) + "\n").encode()
        nat1 = fastparse.NativePacker(packed)
        out1, l1, u1 = nat1.pack_chunk(data, 2 * len(lines), final=True, n_threads=1)
        nat4 = fastparse.NativePacker(packed)
        out4, l4, u4 = nat4.pack_chunk(data, 2 * len(lines), final=True, n_threads=4)
        np.testing.assert_array_equal(out1, out4)
        assert (l1, u1) == (l4, u4)
        assert (nat1.parsed, nat1.skipped) == (nat4.parsed, nat4.skipped)

    def test_oversized_fields_skipped_identically(self):
        """Ports > 65535 / protos > 255 exceed the wire field widths; both
        parsers must SKIP such lines (truncating could forge a match)."""
        _, packed = _setup()
        lines = [
            "Jul 29 x fwx : %ASA-6-106100: access-list INBOUND permitted tcp "
            "outside/1.2.3.4(70000) -> dmz/10.0.0.5(443) hit-cnt 1",
            "Jul 29 x fwx : %ASA-6-106100: access-list INBOUND permitted tcp "
            "outside/1.2.3.4(1234) -> dmz/10.0.0.5(70443) hit-cnt 1",
            "Jul 29 x fwx : %ASA-6-106100: access-list INBOUND permitted 300 "
            "outside/1.2.3.4(1) -> dmz/10.0.0.5(2) hit-cnt 1",
            # in-range control line
            "Jul 29 x fwx : %ASA-6-106100: access-list INBOUND permitted tcp "
            "outside/1.2.3.4(65535) -> dmz/10.0.0.5(443) hit-cnt 1",
        ]
        py = pack.LinePacker(packed)
        ref = py.pack_lines(lines, batch_size=8)
        nat = fastparse.NativePacker(packed)
        got = nat.pack_lines(lines, batch_size=8)
        np.testing.assert_array_equal(ref, got)
        assert (py.parsed, py.skipped) == (nat.parsed, nat.skipped) == (1, 3)

    def test_pack_lines_default_capacity_fits_dual_rows(self):
        """Default (no batch_size) capacity must hold two rows per line
        when out-bindings exist (regression: raised ValueError)."""
        _, packed = _setup()
        py = pack.LinePacker(packed)
        batch = py.pack_lines([CONN_DUAL])  # no batch_size
        assert int(batch[:, pack.T_VALID].sum()) == 2
        nat = fastparse.NativePacker(packed)
        nbatch = nat.pack_lines([CONN_DUAL])
        assert int(nbatch[:, pack.T_VALID].sum()) == 2

    def test_row_cap_closes_batch_line_atomically(self):
        """A dual-eval line whose rows don't fit stays for the next batch."""
        _, packed = _setup()
        lines = [CONN_SINGLE, CONN_DUAL]  # 1 row, then 2 rows
        data = ("\n".join(lines) + "\n").encode()
        nat = fastparse.NativePacker(packed)
        out, n_lines, used = nat.pack_chunk(data, 2, final=True)
        # second line's two rows don't fit after the first row: batch
        # closes with 1 line / 1 row; the dual line is not consumed
        assert n_lines == 1
        assert int(out[6].sum()) == 1
        assert used == len(CONN_SINGLE.encode()) + 1
        out2, n2, _ = nat.pack_chunk(data[used:], 2, final=True)
        assert n2 == 1 and int(out2[6].sum()) == 2


class TestEndToEnd:
    @pytest.fixture(scope="class")
    def corpus(self):
        cfg_text = synth.synth_config(
            n_acls=3, rules_per_acl=10, seed=31, egress_acls=True
        )
        rs = aclparse.parse_asa_config(cfg_text, "fw1")
        packed = pack.pack_rulesets([rs])
        tuples = synth.synth_tuples(packed, 2500, seed=32)
        lines = synth.render_syslog(packed, tuples, seed=33, variety=0.5)
        res = oracle.Oracle([rs]).consume(list(lines))
        return packed, rs, lines, res

    def _run(self, packed, lines, batch_size=512):
        cfg = AnalysisConfig(
            backend="tpu",
            batch_size=batch_size,
            sketch=SketchConfig(cms_width=1 << 12, cms_depth=4, hll_p=8),
        )
        return run_stream(packed, iter(lines), cfg, topk=5)

    def test_tpu_matches_oracle_on_egress_corpus(self, corpus):
        packed, rs, lines, res = corpus
        rep = self._run(packed, lines)
        got = {
            (e["firewall"], e["acl"], e["index"]): e["hits"]
            for e in rep.per_rule
            if e["hits"] > 0
        }
        assert got == dict(res.hits)
        assert rep.totals["lines_matched"] == res.lines_matched
        assert rep.totals["lines_total"] == res.lines_total
        assert rep.unused == res.unused_rules([rs])

    def test_batch_size_invariance_with_dual_rows(self, corpus):
        packed, rs, lines, res = corpus
        a = self._run(packed, lines, batch_size=512)
        b = self._run(packed, lines, batch_size=97)
        ha = {(e["firewall"], e["acl"], e["index"]): e["hits"] for e in a.per_rule}
        hb = {(e["firewall"], e["acl"], e["index"]): e["hits"] for e in b.per_rule}
        assert ha == hb
        assert a.totals["lines_total"] == b.totals["lines_total"]
