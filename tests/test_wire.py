"""Wire format: 16 B/line bit-packed batches (pack.compact_batch).

The stream driver ships batches to the device bit-packed (4 uint32 words
per line instead of 7) because host->device transfer is the narrowest e2e
stage on PCIe-starved links; the device step unpacks with shifts
(pipeline.batch_cols).  These tests pin the packing as lossless and the
device results as bit-identical between layouts.
"""

import numpy as np
import pytest

pytest.importorskip("jax")

from ruleset_analysis_tpu.config import AnalysisConfig, SketchConfig
from ruleset_analysis_tpu.hostside import aclparse, pack, synth
from ruleset_analysis_tpu.models import pipeline


@pytest.fixture(scope="module")
def setup():
    cfg_text = synth.synth_config(n_acls=3, rules_per_acl=16, seed=5)
    rs = aclparse.parse_asa_config(cfg_text, "fw1")
    packed = pack.pack_rulesets([rs])
    cfg = AnalysisConfig(
        batch_size=1024,
        sketch=SketchConfig(cms_width=1 << 10, cms_depth=4, hll_p=6),
    )
    batch = np.ascontiguousarray(synth.synth_tuples(packed, 1024, seed=5).T)
    return packed, cfg, batch


def test_compact_roundtrip(setup):
    _, _, batch = setup
    wire = pack.compact_batch(batch)
    assert wire.shape == (pack.WIRE_COLS, batch.shape[1])
    assert wire.dtype == np.uint32
    np.testing.assert_array_equal(pack.expand_batch(wire), batch)


def test_compact_roundtrip_extremes():
    """Boundary field values survive the bit pack exactly."""
    rows = np.array(
        [
            # acl,            proto, src,        sport, dst,        dport, valid
            [0,               0,     0,          0,     0,          0,     0],
            [pack.WIRE_MAX_ACLS - 1, 255, 0xFFFFFFFF, 65535, 0xFFFFFFFF, 65535, 1],
            [7,               6,     0x0A000001, 1024,  0xC0A80101, 443,   1],
        ],
        dtype=np.uint32,
    )
    batch = np.ascontiguousarray(rows.T)
    np.testing.assert_array_equal(pack.expand_batch(pack.compact_batch(batch)), batch)


def test_grouped_compact_roundtrip(setup):
    packed, _, batch = setup
    lane = 1024
    grouped = pack.group_tuples(
        np.ascontiguousarray(batch.T), max(packed.n_acls, 1), lane
    )
    wire = pack.compact_grouped(grouped)
    assert wire.shape == (grouped.shape[0], pack.WIRE_COLS, lane)
    # expand each group and compare
    for g in range(grouped.shape[0]):
        np.testing.assert_array_equal(pack.expand_batch(wire[g]), grouped[g])


def test_step_bit_identical_between_layouts(setup):
    """analysis_step(wide batch) == analysis_step(wire batch), bit for bit."""
    packed, cfg, batch = setup
    rules = pipeline.ship_ruleset(packed)
    kw = dict(
        n_keys=packed.n_keys,
        topk_k=cfg.sketch.topk_chunk_candidates,
        exact_counts=True,
    )
    s_wide, o_wide = pipeline.analysis_step(
        pipeline.init_state(packed.n_keys, cfg), rules, batch, **kw
    )
    s_wire, o_wire = pipeline.analysis_step(
        pipeline.init_state(packed.n_keys, cfg), rules, pack.compact_batch(batch), **kw
    )
    for a, b in zip(s_wide, s_wire):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    for a, b in zip(o_wide, o_wire):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_sharded_step_accepts_wire(setup):
    """The production parallel step runs on wire batches across the mesh."""
    import jax

    from ruleset_analysis_tpu.parallel import mesh as mesh_lib
    from ruleset_analysis_tpu.parallel.step import make_parallel_step

    packed, cfg, batch = setup
    mesh = mesh_lib.make_mesh()
    n = mesh.shape[cfg.mesh_axis]
    b = (batch.shape[1] // n) * n
    batch = batch[:, :b]
    step = make_parallel_step(mesh, cfg, packed.n_keys)
    state = pipeline.init_state(packed.n_keys, cfg)
    rules = pipeline.ship_ruleset(packed)
    wire_dev = mesh_lib.shard_batch(mesh, pack.compact_batch(batch))
    state, _ = step(state, rules, wire_dev)
    total = pipeline.counts_total(state)
    assert total == int(batch[pack.T_VALID].sum())


def test_counts_total_is_exact_sync(setup):
    """counts_total returns the exact number of valid lines stepped."""
    packed, cfg, batch = setup
    rules = pipeline.ship_ruleset(packed)
    state = pipeline.init_state(packed.n_keys, cfg)
    for i in range(3):
        state, _ = pipeline.analysis_step(
            state, rules, pack.compact_batch(batch),
            n_keys=packed.n_keys,
            topk_k=cfg.sketch.topk_chunk_candidates,
        )
    assert pipeline.counts_total(state) == 3 * int(batch[pack.T_VALID].sum())


def test_weighted_wire_layout_round_trip_and_step(setup):
    """The WEIGHTED wire layout (ISSUE 5): expand round-trips weights,
    and the device step over [WIREW_COLS, U] coalesced rows produces
    registers bit-identical to the raw batch's."""
    packed, cfg, batch = setup
    # force repetition so coalescing actually merges rows
    rep = np.ascontiguousarray(np.tile(batch[:, :256], (1, 4)))
    cb = pack.coalesce_batch(rep)
    ww = pack.compact_batch_w(cb)
    assert ww.shape == (pack.WIREW_COLS, cb.shape[1])
    np.testing.assert_array_equal(pack.expand_batch(ww), cb)
    kw = dict(
        n_keys=packed.n_keys,
        topk_k=cfg.sketch.topk_chunk_candidates,
        exact_counts=True,
    )
    s_raw, _ = pipeline.analysis_step(
        pipeline.init_state(packed.n_keys, cfg), pipeline.ship_ruleset(packed),
        pack.compact_batch(rep), **kw
    )
    s_w, _ = pipeline.analysis_step(
        pipeline.init_state(packed.n_keys, cfg), pipeline.ship_ruleset(packed),
        ww, **kw
    )
    for a, b in zip(s_raw, s_w):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert pipeline.counts_total(s_w) == int(rep[pack.T_VALID].sum())
