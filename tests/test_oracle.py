"""Golden first-match semantics tests against the exact oracle (SURVEY.md §5)."""

from collections import Counter

from ruleset_analysis_tpu.hostside import aclparse, oracle, pack, synth
from ruleset_analysis_tpu.hostside.syslog import parse_line

CFG = """\
hostname fw1
access-list OUT extended permit tcp any host 10.0.0.5 eq 443
access-list OUT extended permit tcp any host 10.0.0.5 eq 80
access-list OUT extended deny tcp any 10.0.0.0 255.255.255.0
access-list OUT extended permit ip any any
access-group OUT in interface outside
"""


def logline(acl, proto, src, sport, dst, dport, fw="fw1"):
    return (
        f"Jul 29 07:48:01 {fw} : %ASA-6-106100: access-list {acl} permitted {proto} "
        f"inside/{src}({sport}) -> outside/{dst}({dport}) hit-cnt 1 first hit [0x0, 0x0]"
    )


def run(lines, cfg=CFG):
    rs = aclparse.parse_asa_config(cfg, "fw1")
    orc = oracle.Oracle([rs])
    return orc.consume(lines), rs


def test_first_match_wins_over_later_rules():
    res, _ = run([logline("OUT", "tcp", "1.2.3.4", 1000, "10.0.0.5", 443)])
    assert res.hits == Counter({("fw1", "OUT", 1): 1})


def test_overlapping_rules_ordered():
    # 10.0.0.5:80 matches rule 2 (eq 80) before the broader deny rule 3
    res, _ = run([logline("OUT", "tcp", "1.2.3.4", 1000, "10.0.0.5", 80)])
    assert res.hits == Counter({("fw1", "OUT", 2): 1})
    # 10.0.0.9:80 skips rules 1-2 (wrong host) and lands on the subnet deny
    res, _ = run([logline("OUT", "tcp", "1.2.3.4", 1000, "10.0.0.9", 80)])
    assert res.hits == Counter({("fw1", "OUT", 3): 1})


def test_catch_all_and_implicit_deny():
    # udp anywhere -> rule 4 (permit ip any any)
    res, _ = run([logline("OUT", "udp", "9.9.9.9", 53, "8.8.8.8", 53)])
    assert res.hits == Counter({("fw1", "OUT", 4): 1})
    # with no catch-all, unmatched traffic lands on implicit deny (index 0)
    cfg = "access-list X extended permit tcp any any eq 22\n"
    res, _ = run([logline("X", "udp", "9.9.9.9", 53, "8.8.8.8", 53)], cfg=cfg)
    assert res.hits == Counter({("fw1", "X", 0): 1})


def test_unknown_acl_and_firewall_skipped():
    res, _ = run(
        [
            logline("NOPE", "tcp", "1.1.1.1", 1, "2.2.2.2", 2),
            logline("OUT", "tcp", "1.1.1.1", 1, "2.2.2.2", 2, fw="otherfw"),
        ]
    )
    assert res.lines_skipped == 2
    assert not res.hits


def test_conn_message_resolved_via_binding():
    line = (
        "Jul 29 07:48:03 fw1 : %ASA-6-302013: Built inbound TCP connection 1 for "
        "outside:203.0.113.5/51000 (203.0.113.5/51000) to inside:10.0.0.5/443 (10.0.0.5/443)"
    )
    res, _ = run([line])
    assert res.hits == Counter({("fw1", "OUT", 1): 1})


def test_unused_rules_report():
    res, rs = run([logline("OUT", "tcp", "1.2.3.4", 1000, "10.0.0.5", 443)])
    unused = res.unused_rules([rs])
    assert ("fw1", "OUT", 2) in unused
    assert ("fw1", "OUT", 3) in unused
    assert ("fw1", "OUT", 4) in unused
    assert ("fw1", "OUT", 1) not in unused


def test_sources_and_talkers():
    lines = [
        logline("OUT", "tcp", "1.2.3.4", 1000, "10.0.0.5", 443),
        logline("OUT", "tcp", "1.2.3.4", 1001, "10.0.0.5", 443),
        logline("OUT", "tcp", "5.6.7.8", 1002, "10.0.0.5", 443),
    ]
    res, _ = run(lines)
    key = ("fw1", "OUT", 1)
    assert res.hits[key] == 3
    assert len(res.sources[key]) == 2
    top = res.talkers[("fw1", "OUT")].most_common(1)
    assert top[0][1] == 2


def test_oracle_matches_packed_semantics_on_synthetic_corpus():
    """Cross-check: oracle (Ruleset scan) vs an independent packed-row scan."""
    cfg_text = synth.synth_config(n_acls=3, rules_per_acl=16, seed=7)
    rs = aclparse.parse_asa_config(cfg_text, "fw1")
    packed = pack.pack_rulesets([rs])
    tuples = synth.synth_tuples(packed, 500, seed=7)
    lines = synth.render_syslog(packed, tuples, seed=7)

    orc = oracle.Oracle([rs])
    res = orc.consume(lines)

    # independent numpy first-match over the packed matrix
    packer = pack.LinePacker(packed)
    batch = packer.pack_lines(lines)
    hits = Counter()
    rules = packed.rules
    import numpy as np

    for row in batch:
        if not row[pack.T_VALID]:
            continue
        ok = (
            (rules[:, pack.R_ACL] == row[pack.T_ACL])
            & (rules[:, pack.R_PLO] <= row[pack.T_PROTO])
            & (row[pack.T_PROTO] <= rules[:, pack.R_PHI])
            & (rules[:, pack.R_SLO] <= row[pack.T_SRC])
            & (row[pack.T_SRC] <= rules[:, pack.R_SHI])
            & (rules[:, pack.R_SPLO] <= row[pack.T_SPORT])
            & (row[pack.T_SPORT] <= rules[:, pack.R_SPHI])
            & (rules[:, pack.R_DLO] <= row[pack.T_DST])
            & (row[pack.T_DST] <= rules[:, pack.R_DHI])
            & (rules[:, pack.R_DPLO] <= row[pack.T_DPORT])
            & (row[pack.T_DPORT] <= rules[:, pack.R_DPHI])
        )
        idx = np.nonzero(ok)[0]
        if len(idx):
            key_id = int(rules[idx[0], pack.R_KEY])
        else:
            key_id = int(packed.deny_key[int(row[pack.T_ACL])])
        m = packed.key_meta[key_id]
        hits[(m.firewall, m.acl, m.index)] += 1

    oracle_hits = {k: v for k, v in res.hits.items()}
    assert hits == Counter(oracle_hits)
