"""Differential sweep: randomized configs, device path vs oracle.

The golden tests pin a few fixed corpora; this sweep broadens coverage by
generating MANY (config, syslog) pairs across the synth generator's
feature space — varied ACL counts, rule densities, egress bindings, seed
variety — and asserting the full TPU stream path reproduces the exact
oracle's per-rule hits and unused set on every one.  A kernel or parser
regression that happens to dodge the fixed goldens gets caught here.
"""

import numpy as np
import pytest

pytest.importorskip("jax")

from ruleset_analysis_tpu.config import AnalysisConfig, SketchConfig
from ruleset_analysis_tpu.hostside import aclparse, oracle, pack, synth
from ruleset_analysis_tpu.runtime.stream import run_stream


CASES = [
    # (seed, n_acls, rules_per_acl, egress, lines, batch)
    (101, 1, 4, False, 400, 64),
    (102, 2, 16, False, 800, 128),
    (103, 3, 8, True, 800, 96),
    (104, 5, 24, False, 1200, 256),
    (105, 2, 12, True, 1000, 100),  # odd batch, egress dual-eval
    (106, 4, 6, False, 600, 601),  # batch larger than the corpus
    (107, 1, 48, True, 900, 128),
    (108, 6, 10, False, 1000, 250),
]


# The pallas impls run the padding-stress subset (odd batch + egress
# dual-eval, batch > corpus) — the fused kernel's counts fold has its own
# failure modes there (deny routing, lane padding, grid accumulation) —
# plus two plain cases; xla runs everything.  Interpret mode on CPU.
# The sorted register-update formulation (ISSUE 9) rides the same
# flip-variant pattern: the padding-stress cases again (odd batches are
# where a sort+segment boundary bug would hide) with the deferred
# candidate cadence flipped alongside — re-certifying that topk_every
# never perturbs exact hits or the unused set.
IMPL_CASES = (
    [("xla", 1, c) for c in CASES]
    + [
        (impl, 1, c)
        for impl in ("pallas", "pallas_fused")
        for c in (CASES[1], CASES[2], CASES[4], CASES[5])
    ]
    + [
        ("xla+sorted", every, c)
        for every, c in ((1, CASES[2]), (2, CASES[4]), (4, CASES[5]))
    ]
)


@pytest.mark.parametrize("impl,every,case", IMPL_CASES)
def test_device_matches_oracle(impl, every, case):
    seed, n_acls, rules, egress, lines, batch = case
    cfg_text = synth.synth_config(
        n_acls=n_acls, rules_per_acl=rules, seed=seed, egress_acls=egress
    )
    rs = aclparse.parse_asa_config(cfg_text, "fw1")
    packed = pack.pack_rulesets([rs])
    tuples = synth.synth_tuples(packed, lines, seed=seed)
    log_lines = synth.render_syslog(packed, tuples, seed=seed, variety=0.3)
    res = oracle.Oracle([rs]).consume(list(log_lines))

    match_impl, _, update = impl.partition("+")
    rep = run_stream(
        packed,
        iter(log_lines),
        AnalysisConfig(
            batch_size=batch,
            sketch=SketchConfig(
                cms_width=1 << 11, cms_depth=4, hll_p=6, topk_every=every
            ),
            match_impl=match_impl,
            update_impl=update or "scatter",
        ),
        topk=5,
    )
    got = {
        (e["firewall"], e["acl"], e["index"]): e["hits"]
        for e in rep.per_rule
        if e["hits"] > 0
    }
    exp = dict(res.hits)
    assert got == exp, f"seed {seed}: device hits != oracle"
    assert rep.unused == res.unused_rules([rs]), f"seed {seed}: unused set"
    assert rep.totals["lines_matched"] == res.lines_matched
    assert rep.totals["lines_skipped"] == res.lines_skipped
