"""Grouped/stacked multi-firewall match (BASELINE.json config #4).

The stacked path buckets lines by ACL gid host-side and vmaps the
first-match kernel over per-ACL rule slabs.  Registers are mergeable and
order-invariant, so its state must equal the flat path's bit-for-bit on
the same multiset of lines — that equivalence (plus host grouping
round-trips) is what these tests pin down.
"""

import functools

import numpy as np
import pytest

from ruleset_analysis_tpu.config import AnalysisConfig, SketchConfig
from ruleset_analysis_tpu.hostside import aclparse, pack, synth
from ruleset_analysis_tpu.models import pipeline


@pytest.fixture(scope="module")
def multi_fw():
    """Three firewalls' rulesets packed into one key universe."""
    rulesets = [
        aclparse.parse_asa_config(
            synth.synth_config(n_acls=2, rules_per_acl=12, seed=s), f"fw{s}"
        )
        for s in range(3)
    ]
    return pack.pack_rulesets(rulesets)


def _cfg(n=1024):
    return AnalysisConfig(
        batch_size=n, sketch=SketchConfig(cms_width=1 << 10, cms_depth=4, hll_p=6)
    )


def _states_equal(a, b):
    for f in pipeline.AnalysisState._fields:
        np.testing.assert_array_equal(
            np.asarray(getattr(a, f)), np.asarray(getattr(b, f)), err_msg=f
        )


class TestStackRules:
    def test_slabs_cover_all_rows_in_order(self, multi_fw):
        rules3d = pack.stack_rules(multi_fw)
        assert rules3d.shape[0] == multi_fw.n_acls
        real = multi_fw.rules[multi_fw.rules[:, pack.R_ACL] != pack.NO_ACL]
        for gid in range(multi_fw.n_acls):
            slab = rules3d[gid]
            rows = slab[slab[:, pack.R_ACL] != pack.NO_ACL]
            want = real[real[:, pack.R_ACL] == gid]
            np.testing.assert_array_equal(rows, want)  # order preserved

    def test_padding_never_matches(self):
        """Slab padding rows must lose to the implicit deny, even for
        all-zero tuple fields (which a zeroed padding row would 'match'
        if its ACL id were left real)."""
        import jax.numpy as jnp

        from ruleset_analysis_tpu.ops.match import match_keys_stacked

        cfg = (
            "access-list A extended permit tcp host 10.0.0.1 host 10.0.0.2 eq 80\n"
            "access-list B extended permit ip any any\n"
            "access-list B extended deny ip any any\n"
        )
        rs = aclparse.parse_asa_config(cfg, "fw1")
        packed = pack.pack_rulesets([rs])
        rules3d = pack.stack_rules(packed)
        assert rules3d.shape[1] >= 2  # ACL A's slab has >= 1 padding row
        # a line on ACL A (gid 0) with all-zero fields: matches nothing
        # real, and must NOT match A's zero-valued padding rows
        cols = {
            k: jnp.zeros((packed.n_acls, 4), dtype=jnp.uint32)
            for k in ["acl", "proto", "src", "sport", "dst", "dport"]
        }
        cols["acl"] = jnp.broadcast_to(
            jnp.arange(packed.n_acls, dtype=jnp.uint32)[:, None], (packed.n_acls, 4)
        )
        keys = np.asarray(
            match_keys_stacked(cols, jnp.asarray(rules3d), jnp.asarray(packed.deny_key))
        )
        gid_a = packed.acl_gid[("fw1", "A")]
        assert (keys[gid_a] == packed.deny_key[gid_a]).all()


class TestGrouping:
    def test_group_tuples_roundtrip(self, multi_fw):
        tuples = synth.synth_tuples(multi_fw, 500, seed=1)
        grouped = pack.group_tuples(tuples, multi_fw.n_acls, lane=512)
        # every valid line lands in its gid's lane, fields intact
        total = 0
        for gid in range(multi_fw.n_acls):
            lane = grouped[gid]
            n = int(lane[pack.T_VALID].sum())
            total += n
            assert (lane[pack.T_ACL, :n] == gid).all()
            assert (lane[pack.T_VALID, n:] == 0).all()
        assert total == int(tuples[:, pack.T_VALID].sum())

    def test_group_tuples_overflow_raises(self, multi_fw):
        tuples = synth.synth_tuples(multi_fw, 500, seed=1)
        with pytest.raises(ValueError):
            pack.group_tuples(tuples, multi_fw.n_acls, lane=8)

    def test_group_buffer_carries_overflow(self, multi_fw):
        tuples = synth.synth_tuples(multi_fw, 700, seed=2)
        buf = pack.GroupBuffer(multi_fw.n_acls, lane=64)
        batches = []
        for i in range(0, 700, 100):
            batches += buf.add(tuples[i:i + 100])
        batches += buf.flush()
        got = sum(int(b[:, pack.T_VALID, :].sum()) for b in batches)
        assert got == int(tuples[:, pack.T_VALID].sum())
        for b in batches:
            for gid in range(multi_fw.n_acls):
                n = int(b[gid, pack.T_VALID].sum())
                assert (b[gid, pack.T_ACL, :n] == gid).all()


class TestStackedStep:
    def test_state_matches_flat_path(self, multi_fw):
        import jax.numpy as jnp

        cfg = _cfg()
        tuples = synth.synth_tuples(multi_fw, cfg.batch_size, seed=3)

        flat_state = pipeline.init_state(multi_fw.n_keys, cfg)
        flat_rules = pipeline.ship_ruleset(multi_fw)
        step = functools.partial(
            pipeline.analysis_step,
            n_keys=multi_fw.n_keys,
            topk_k=cfg.sketch.topk_chunk_candidates,
        )
        flat_state, _ = step(
            flat_state, flat_rules, jnp.asarray(np.ascontiguousarray(tuples.T))
        )

        g_state = pipeline.init_state(multi_fw.n_keys, cfg)
        g_rules = pipeline.ship_ruleset_stacked(multi_fw)
        grouped = pack.group_tuples(tuples, multi_fw.n_acls, lane=cfg.batch_size)
        g_step = functools.partial(
            pipeline.analysis_step_stacked,
            n_keys=multi_fw.n_keys,
            topk_k=cfg.sketch.topk_chunk_candidates,
        )
        g_state, _ = g_step(g_state, g_rules, jnp.asarray(grouped))

        _states_equal(flat_state, g_state)

    def test_counts_match_oracle(self, multi_fw):
        import jax.numpy as jnp

        cfg = _cfg(512)
        tuples = synth.synth_tuples(multi_fw, 512, seed=4)
        # oracle over the same tuples via the flat-path reference:
        # flat step is already oracle-verified (test_match/test_e2e), so
        # exact counts from the stacked step must match flat's
        grouped = pack.group_tuples(tuples, multi_fw.n_acls, lane=512)
        g_state = pipeline.init_state(multi_fw.n_keys, cfg)
        g_rules = pipeline.ship_ruleset_stacked(multi_fw)
        g_state, _ = pipeline.analysis_step_stacked(
            g_state, g_rules, jnp.asarray(grouped),
            n_keys=multi_fw.n_keys, topk_k=cfg.sketch.topk_chunk_candidates,
        )
        got = np.asarray(g_state.counts_lo)

        from ruleset_analysis_tpu.ops.match import match_keys
        import jax

        cols = {
            "acl": jnp.asarray(tuples[:, pack.T_ACL]),
            "proto": jnp.asarray(tuples[:, pack.T_PROTO]),
            "src": jnp.asarray(tuples[:, pack.T_SRC]),
            "sport": jnp.asarray(tuples[:, pack.T_SPORT]),
            "dst": jnp.asarray(tuples[:, pack.T_DST]),
            "dport": jnp.asarray(tuples[:, pack.T_DPORT]),
        }
        flat_rules = pipeline.ship_ruleset(multi_fw)
        keys = np.asarray(match_keys(cols, flat_rules.rules, flat_rules.deny_key))
        want = np.bincount(
            keys[tuples[:, pack.T_VALID] == 1], minlength=multi_fw.n_keys
        ).astype(np.uint32)
        np.testing.assert_array_equal(got, want)

    def test_jit_and_shapes(self, multi_fw):
        import jax
        import jax.numpy as jnp

        cfg = _cfg(256)
        tuples = synth.synth_tuples(multi_fw, 256, seed=5)
        grouped = pack.group_tuples(tuples, multi_fw.n_acls, lane=64)
        state = pipeline.init_state(multi_fw.n_keys, cfg)
        rules = pipeline.ship_ruleset_stacked(multi_fw)
        step = jax.jit(
            functools.partial(
                pipeline.analysis_step_stacked,
                n_keys=multi_fw.n_keys,
                topk_k=cfg.sketch.topk_chunk_candidates,
            )
        )
        state, out = step(state, rules, jnp.asarray(grouped))
        assert np.asarray(state.counts_lo).sum() == (tuples[:, pack.T_VALID] == 1).sum()


def _rule_stats(report):
    """Exact (hits, uniq) per rule + the unused set — the register-derived
    report content that must agree between layouts (talker candidates are
    chunk-composition-dependent approximations and may differ)."""
    per = {
        (e["firewall"], e["acl"], e["index"]): (e["hits"], e.get("unique_sources"))
        for e in report.per_rule
    }
    return per, set(report.unused)


class TestStackedStream:
    """The productionized stacked path: runtime/stream.py layout='stacked'."""

    def _lines(self, multi_fw, n=3000, seed=5):
        tuples = synth.synth_tuples(multi_fw, n, seed=seed)
        return synth.render_syslog(multi_fw, tuples, seed=seed + 1)

    def test_stream_report_matches_flat(self, multi_fw):
        from ruleset_analysis_tpu.runtime.stream import run_stream

        lines = self._lines(multi_fw)
        cfg = _cfg(512)
        rep_flat = run_stream(multi_fw, iter(lines), cfg, topk=5)
        rep_st = run_stream(
            multi_fw, iter(lines), cfg.replace(layout="stacked"), topk=5
        )
        assert _rule_stats(rep_flat) == _rule_stats(rep_st)
        assert rep_st.totals["lines_matched"] == rep_flat.totals["lines_matched"]

    def test_stream_stacked_lane_override(self, multi_fw):
        from ruleset_analysis_tpu.runtime.stream import run_stream

        lines = self._lines(multi_fw, n=1500, seed=9)
        cfg = _cfg(512)
        rep_a = run_stream(
            multi_fw, iter(lines), cfg.replace(layout="stacked"), topk=5
        )
        rep_b = run_stream(
            multi_fw,
            iter(lines),
            cfg.replace(layout="stacked", stacked_lane=64),
            topk=5,
        )
        assert _rule_stats(rep_a) == _rule_stats(rep_b)

    def test_sharded_stacked_step_matches_single_device(self, multi_fw):
        import jax
        import jax.numpy as jnp

        from ruleset_analysis_tpu.parallel import mesh as mesh_lib
        from ruleset_analysis_tpu.parallel.step import make_parallel_step_stacked

        cfg = _cfg(1024)
        tuples = synth.synth_tuples(multi_fw, 1024, seed=11)
        lane = 256  # divisible by the 8-device fake mesh
        grouped = pack.group_tuples(tuples, multi_fw.n_acls, lane=lane)

        mesh8 = mesh_lib.make_mesh(jax.devices("cpu")[:8])
        step8 = make_parallel_step_stacked(mesh8, cfg, multi_fw.n_keys)
        st8 = pipeline.init_state(multi_fw.n_keys, cfg)
        rules = pipeline.ship_ruleset_stacked(multi_fw)
        st8, _ = step8(st8, rules, mesh_lib.shard_grouped(mesh8, grouped), 0)

        st1 = pipeline.init_state(multi_fw.n_keys, cfg)
        st1, _ = pipeline.analysis_step_stacked(
            st1, rules, jnp.asarray(grouped),
            n_keys=multi_fw.n_keys, topk_k=cfg.sketch.topk_chunk_candidates,
        )
        _states_equal(st8, st1)

    def test_stacked_checkpoint_kill_resume(self, multi_fw, tmp_path):
        from ruleset_analysis_tpu.runtime.stream import run_stream

        lines = self._lines(multi_fw, n=2000, seed=13)
        cfg = _cfg(256).replace(
            layout="stacked",
            checkpoint_every_chunks=1,
            checkpoint_dir=str(tmp_path / "ck"),
        )
        # uninterrupted reference
        ref = run_stream(multi_fw, iter(lines), _cfg(256).replace(layout="stacked"), topk=5)
        # crash after 3 source chunks, then resume
        run_stream(multi_fw, iter(lines), cfg, topk=5, max_chunks=3)
        rep = run_stream(multi_fw, iter(lines), cfg.replace(resume=True), topk=5)
        assert _rule_stats(rep) == _rule_stats(ref)
        assert rep.totals["lines_total"] == ref.totals["lines_total"]


def test_group_buffer_randomized_conservation():
    """Property: over random add/flush sequences (skewed ACL mixes, odd
    batch sizes, small lanes), every valid input line is emitted exactly
    once, in its own ACL's slab, with intra-ACL order preserved — and
    invalid lines never appear."""
    import random

    rng = np.random.default_rng(77)
    for trial in range(25):
        r = random.Random(trial)
        n_groups = r.randint(1, 6)
        lane = r.choice([4, 8, 16, 32])
        buf = pack.GroupBuffer(n_groups, lane)
        sent: dict[int, list[int]] = {g: [] for g in range(n_groups)}
        got: dict[int, list[int]] = {g: [] for g in range(n_groups)}
        serial = 1  # src doubles as a unique line id (0 is padding)

        def consume(grouped_batches):
            for gb in grouped_batches:
                assert gb.shape == (n_groups, pack.TUPLE_COLS, lane)
                for g in range(n_groups):
                    v = gb[g, pack.T_VALID] == 1
                    assert (gb[g, pack.T_ACL][v] == g).all()
                    got[g].extend(gb[g, pack.T_SRC][v].tolist())
                    # padding must be all-zero, valid=0
                    assert (gb[g, pack.T_SRC][~v] == 0).all()

        for _step in range(r.randint(1, 12)):
            b = r.randint(1, 64)
            batch = np.zeros((b, pack.TUPLE_COLS), dtype=np.uint32)
            # skewed ACL choice: sometimes all one group
            if r.random() < 0.3:
                acls = np.full(b, r.randrange(n_groups), dtype=np.uint32)
            else:
                acls = rng.integers(0, n_groups, size=b).astype(np.uint32)
            valid = (rng.random(b) < 0.8).astype(np.uint32)
            batch[:, pack.T_ACL] = acls
            batch[:, pack.T_VALID] = valid
            for i in range(b):
                if valid[i]:
                    batch[i, pack.T_SRC] = serial
                    sent[int(acls[i])].append(serial)
                    serial += 1
            consume(buf.add(batch))
        consume(buf.flush())
        assert got == sent, f"trial {trial}: lines lost/duplicated/reordered"
        assert buf.flush() == []  # drained
