"""Grouped/stacked multi-firewall match (BASELINE.json config #4).

The stacked path buckets lines by ACL gid host-side and vmaps the
first-match kernel over per-ACL rule slabs.  Registers are mergeable and
order-invariant, so its state must equal the flat path's bit-for-bit on
the same multiset of lines — that equivalence (plus host grouping
round-trips) is what these tests pin down.
"""

import functools

import numpy as np
import pytest

from ruleset_analysis_tpu.config import AnalysisConfig, SketchConfig
from ruleset_analysis_tpu.hostside import aclparse, pack, synth
from ruleset_analysis_tpu.models import pipeline


@pytest.fixture(scope="module")
def multi_fw():
    """Three firewalls' rulesets packed into one key universe."""
    rulesets = [
        aclparse.parse_asa_config(
            synth.synth_config(n_acls=2, rules_per_acl=12, seed=s), f"fw{s}"
        )
        for s in range(3)
    ]
    return pack.pack_rulesets(rulesets)


def _cfg(n=1024):
    return AnalysisConfig(
        batch_size=n, sketch=SketchConfig(cms_width=1 << 10, cms_depth=4, hll_p=6)
    )


def _states_equal(a, b):
    for f in pipeline.AnalysisState._fields:
        np.testing.assert_array_equal(
            np.asarray(getattr(a, f)), np.asarray(getattr(b, f)), err_msg=f
        )


class TestStackRules:
    def test_slabs_cover_all_rows_in_order(self, multi_fw):
        rules3d = pack.stack_rules(multi_fw)
        assert rules3d.shape[0] == multi_fw.n_acls
        real = multi_fw.rules[multi_fw.rules[:, pack.R_ACL] != pack.NO_ACL]
        for gid in range(multi_fw.n_acls):
            slab = rules3d[gid]
            rows = slab[slab[:, pack.R_ACL] != pack.NO_ACL]
            want = real[real[:, pack.R_ACL] == gid]
            np.testing.assert_array_equal(rows, want)  # order preserved

    def test_padding_never_matches(self):
        """Slab padding rows must lose to the implicit deny, even for
        all-zero tuple fields (which a zeroed padding row would 'match'
        if its ACL id were left real)."""
        import jax.numpy as jnp

        from ruleset_analysis_tpu.ops.match import match_keys_stacked

        cfg = (
            "access-list A extended permit tcp host 10.0.0.1 host 10.0.0.2 eq 80\n"
            "access-list B extended permit ip any any\n"
            "access-list B extended deny ip any any\n"
        )
        rs = aclparse.parse_asa_config(cfg, "fw1")
        packed = pack.pack_rulesets([rs])
        rules3d = pack.stack_rules(packed)
        assert rules3d.shape[1] >= 2  # ACL A's slab has >= 1 padding row
        # a line on ACL A (gid 0) with all-zero fields: matches nothing
        # real, and must NOT match A's zero-valued padding rows
        cols = {
            k: jnp.zeros((packed.n_acls, 4), dtype=jnp.uint32)
            for k in ["acl", "proto", "src", "sport", "dst", "dport"]
        }
        cols["acl"] = jnp.broadcast_to(
            jnp.arange(packed.n_acls, dtype=jnp.uint32)[:, None], (packed.n_acls, 4)
        )
        keys = np.asarray(
            match_keys_stacked(cols, jnp.asarray(rules3d), jnp.asarray(packed.deny_key))
        )
        gid_a = packed.acl_gid[("fw1", "A")]
        assert (keys[gid_a] == packed.deny_key[gid_a]).all()


class TestGrouping:
    def test_group_tuples_roundtrip(self, multi_fw):
        tuples = synth.synth_tuples(multi_fw, 500, seed=1)
        grouped = pack.group_tuples(tuples, multi_fw.n_acls, lane=512)
        # every valid line lands in its gid's lane, fields intact
        total = 0
        for gid in range(multi_fw.n_acls):
            lane = grouped[gid]
            n = int(lane[pack.T_VALID].sum())
            total += n
            assert (lane[pack.T_ACL, :n] == gid).all()
            assert (lane[pack.T_VALID, n:] == 0).all()
        assert total == int(tuples[:, pack.T_VALID].sum())

    def test_group_tuples_overflow_raises(self, multi_fw):
        tuples = synth.synth_tuples(multi_fw, 500, seed=1)
        with pytest.raises(ValueError):
            pack.group_tuples(tuples, multi_fw.n_acls, lane=8)

    def test_group_buffer_carries_overflow(self, multi_fw):
        tuples = synth.synth_tuples(multi_fw, 700, seed=2)
        buf = pack.GroupBuffer(multi_fw.n_acls, lane=64)
        batches = []
        for i in range(0, 700, 100):
            batches += buf.add(tuples[i:i + 100])
        batches += buf.flush()
        got = sum(int(b[:, pack.T_VALID, :].sum()) for b in batches)
        assert got == int(tuples[:, pack.T_VALID].sum())
        for b in batches:
            for gid in range(multi_fw.n_acls):
                n = int(b[gid, pack.T_VALID].sum())
                assert (b[gid, pack.T_ACL, :n] == gid).all()


class TestStackedStep:
    def test_state_matches_flat_path(self, multi_fw):
        import jax.numpy as jnp

        cfg = _cfg()
        tuples = synth.synth_tuples(multi_fw, cfg.batch_size, seed=3)

        flat_state = pipeline.init_state(multi_fw.n_keys, cfg)
        flat_rules = pipeline.ship_ruleset(multi_fw)
        step = functools.partial(
            pipeline.analysis_step,
            n_keys=multi_fw.n_keys,
            topk_k=cfg.sketch.topk_chunk_candidates,
        )
        flat_state, _ = step(
            flat_state, flat_rules, jnp.asarray(np.ascontiguousarray(tuples.T))
        )

        g_state = pipeline.init_state(multi_fw.n_keys, cfg)
        g_rules = pipeline.ship_ruleset_stacked(multi_fw)
        grouped = pack.group_tuples(tuples, multi_fw.n_acls, lane=cfg.batch_size)
        g_step = functools.partial(
            pipeline.analysis_step_stacked,
            n_keys=multi_fw.n_keys,
            topk_k=cfg.sketch.topk_chunk_candidates,
        )
        g_state, _ = g_step(g_state, g_rules, jnp.asarray(grouped))

        _states_equal(flat_state, g_state)

    def test_counts_match_oracle(self, multi_fw):
        import jax.numpy as jnp

        cfg = _cfg(512)
        tuples = synth.synth_tuples(multi_fw, 512, seed=4)
        # oracle over the same tuples via the flat-path reference:
        # flat step is already oracle-verified (test_match/test_e2e), so
        # exact counts from the stacked step must match flat's
        grouped = pack.group_tuples(tuples, multi_fw.n_acls, lane=512)
        g_state = pipeline.init_state(multi_fw.n_keys, cfg)
        g_rules = pipeline.ship_ruleset_stacked(multi_fw)
        g_state, _ = pipeline.analysis_step_stacked(
            g_state, g_rules, jnp.asarray(grouped),
            n_keys=multi_fw.n_keys, topk_k=cfg.sketch.topk_chunk_candidates,
        )
        got = np.asarray(g_state.counts_lo)

        from ruleset_analysis_tpu.ops.match import match_keys
        import jax

        cols = {
            "acl": jnp.asarray(tuples[:, pack.T_ACL]),
            "proto": jnp.asarray(tuples[:, pack.T_PROTO]),
            "src": jnp.asarray(tuples[:, pack.T_SRC]),
            "sport": jnp.asarray(tuples[:, pack.T_SPORT]),
            "dst": jnp.asarray(tuples[:, pack.T_DST]),
            "dport": jnp.asarray(tuples[:, pack.T_DPORT]),
        }
        flat_rules = pipeline.ship_ruleset(multi_fw)
        keys = np.asarray(match_keys(cols, flat_rules.rules, flat_rules.deny_key))
        want = np.bincount(
            keys[tuples[:, pack.T_VALID] == 1], minlength=multi_fw.n_keys
        ).astype(np.uint32)
        np.testing.assert_array_equal(got, want)

    def test_jit_and_shapes(self, multi_fw):
        import jax
        import jax.numpy as jnp

        cfg = _cfg(256)
        tuples = synth.synth_tuples(multi_fw, 256, seed=5)
        grouped = pack.group_tuples(tuples, multi_fw.n_acls, lane=64)
        state = pipeline.init_state(multi_fw.n_keys, cfg)
        rules = pipeline.ship_ruleset_stacked(multi_fw)
        step = jax.jit(
            functools.partial(
                pipeline.analysis_step_stacked,
                n_keys=multi_fw.n_keys,
                topk_k=cfg.sketch.topk_chunk_candidates,
            )
        )
        state, out = step(state, rules, jnp.asarray(grouped))
        assert np.asarray(state.counts_lo).sum() == (tuples[:, pack.T_VALID] == 1).sum()
