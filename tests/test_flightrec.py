"""Always-on flight recorder + crash forensics + latency SLO plane.

Pins the ISSUE 15 contract (DESIGN §20):

- **Dump-on-abort**: a chaos-killed run with NO trace/metrics flags
  armed leaves a complete, parseable ``postmortem.json`` naming the
  fired fault site and the failing stage; ``doctor`` turns it into a
  ranked diagnosis; a CLEAN exit leaves nothing behind.
- **Crash merge**: an injected worker kill (``feeder.worker.crash`` =
  the SIGKILL/OOM analog, ``os._exit`` with no teardown) yields a
  merged bundle from the surviving processes — the dying worker's ring
  dumps at the fault site, the survivors seal at teardown
  (``worker-exit``), and the supervising CLI merges them all.
- **Triggers**: every registered dump trigger — ``abort``, ``stall``,
  ``unhandled``, ``signal``, ``crash``, ``worker-exit`` — is exercised
  here (the registry auditor fails ``make lint`` if one loses its
  test).
- **Latency SLO**: the log2-bucket histograms are mergeable by
  addition, quantiles are conservative, and serve ``/metrics`` renders
  the SAME histogram as JSON p50/p90/p99 gauges and as a well-formed
  Prometheus histogram whose bucket-derived p99 equals the JSON gauge.
"""

import json
import os
import signal
import time
import urllib.request

import pytest

pytest.importorskip("jax")

from ruleset_analysis_tpu import cli
from ruleset_analysis_tpu.config import AnalysisConfig, ServeConfig
from ruleset_analysis_tpu.errors import AnalysisError, StallError
from ruleset_analysis_tpu.hostside import aclparse, fastparse, pack, synth
from ruleset_analysis_tpu.runtime import flightrec, obs
from ruleset_analysis_tpu.runtime.flightrec import (
    TRIGGERS, FlightRing, classify, diagnose, load_bundle, stage_occupancy,
)
from ruleset_analysis_tpu.runtime.metrics import (
    LATENCY_BUCKET_BOUNDS, LatencyHistogram, quantile_from_prom,
)

from test_serve import finish, get_json, start_serve, wait_for


@pytest.fixture(autouse=True)
def _reset_recorder():
    """Every test starts and ends disarmed (module state is global)."""
    flightrec._reset_for_tests()
    yield
    flightrec._reset_for_tests()


@pytest.fixture(scope="module")
def corpus(tmp_path_factory):
    """Small packed ruleset + syslog corpus shared by every CLI run here
    (ONE geometry -> one specialized-step compile for the whole module)."""
    td = tmp_path_factory.mktemp("flightrec")
    cfg_text = synth.synth_config(n_acls=2, rules_per_acl=8, seed=3)
    rs = aclparse.parse_asa_config(cfg_text, "fw1")
    packed = pack.pack_rulesets([rs])
    prefix = str(td / "rules")
    pack.save_packed(packed, prefix)
    t = synth.synth_tuples(packed, 2000, seed=3)
    lines = synth.render_syslog(packed, t, seed=3)
    log = str(td / "fw1.log")
    with open(log, "w", encoding="utf-8") as f:
        f.write("\n".join(lines) + "\n")
    return packed, prefix, log, lines


#: one geometry for every CLI run in this module (memoized step builders
#: make the second and later runs compile-free)
RUN_FLAGS = [
    "--batch-size", "256", "--cms-width", "4096", "--cms-depth", "2",
    "--hll-p", "6",
]


def run_cli(prefix, log, bb_dir, *extra):
    return cli.main([
        "run", "--ruleset", prefix, "--logs", log, *RUN_FLAGS,
        "--blackbox-dir", str(bb_dir), "--json",
        "--out", os.devnull, *extra,
    ])


# ---------------------------------------------------------------------------
# Ring + histogram units (no device work).
# ---------------------------------------------------------------------------


def test_ring_overwrites_in_place_oldest_first():
    r = FlightRing(capacity=8)
    for i in range(20):
        r.append({"i": i})
    assert r.total == 20 and r.capacity == 8
    got = [e["i"] for e in r.events()]
    assert got == list(range(12, 20))  # last 8, oldest first


def test_ring_refuses_tiny_capacity():
    with pytest.raises(AnalysisError):
        FlightRing(capacity=2)


def test_latency_histogram_buckets_and_conservative_quantiles():
    h = LatencyHistogram()
    # bucket upper bounds: quantiles never under-report
    h.record(0.9e-6)  # -> 1us bucket
    h.record(3e-6)    # -> 4us bucket
    for _ in range(98):
        h.record(100e-6)  # -> 128us bucket
    s = h.summary()
    assert s["count"] == 100
    assert s["p50_sec"] == 128e-6 and s["p99_sec"] == 128e-6
    assert h.quantile(0.001) == 1e-6
    # overflow clamps to the largest finite bound
    h2 = LatencyHistogram()
    h2.record(LATENCY_BUCKET_BOUNDS[-1] * 4)
    assert h2.counts[-1] == 1
    assert h2.quantile(0.99) == LATENCY_BUCKET_BOUNDS[-1]


def test_latency_histogram_merge_is_addition():
    a, b, both = LatencyHistogram(), LatencyHistogram(), LatencyHistogram()
    for us, n in ((5, 3), (700, 2), (90_000, 1)):
        a.record(us * 1e-6, n=n)
        both.record(us * 1e-6, n=n)
    for us, n in ((12, 4), (700, 5)):
        b.record(us * 1e-6, n=n)
        both.record(us * 1e-6, n=n)
    a.merge(b)
    assert a.counts == both.counts
    assert a.count == both.count == 15
    assert a.summary() == both.summary()


def test_latency_histogram_prom_rendering_matches_json():
    h = LatencyHistogram()
    for us in (3, 40, 40, 500, 2_000, 2_000, 70_000, 900_000):
        h.record(us * 1e-6)
    prom = h.render_prom("ra_t_seconds")
    # well-formed: TYPE line, cumulative non-decreasing buckets, +Inf
    assert prom.startswith("# TYPE ra_t_seconds histogram")
    cums = [
        int(line.rsplit(" ", 1)[1])
        for line in prom.splitlines()
        if line.startswith("ra_t_seconds_bucket")
    ]
    assert all(b >= a for a, b in zip(cums, cums[1:]))
    assert cums[-1] == h.count
    assert f"ra_t_seconds_count {h.count}" in prom
    for p in (0.5, 0.9, 0.99):
        assert quantile_from_prom(prom, "ra_t_seconds", p) == h.quantile(p)


# ---------------------------------------------------------------------------
# The obs tap: events reach the ring with the trace plane DISARMED.
# ---------------------------------------------------------------------------


def test_obs_tap_records_into_ring_without_trace(tmp_path):
    assert obs.active_tracer() is None
    rec = flightrec.arm(str(tmp_path / "bb"), role="main")
    t0 = time.perf_counter()
    obs.complete("step.dispatch", t0, time.perf_counter(), args={"kind": "v4"})
    obs.instant("fault.test", args={"hit": 1})
    with obs.span("ingest.produce", n_raw=7):
        pass
    names = [e["name"] for e in rec.ring.events()]
    assert names == ["step.dispatch", "fault.test", "ingest.produce"]
    assert obs.recording()
    # nothing touched disk: the ring only lands at a dump trigger
    assert not os.path.exists(str(tmp_path / "bb"))


def test_dump_and_merge_shard_roundtrip(tmp_path):
    d = str(tmp_path / "bb")
    flightrec.arm(d, role="main")
    obs.instant("fault.stream.device_put.fail", args={"hit": 1})
    with obs.span("ingest.backpressure"):
        time.sleep(0.002)
    flightrec.cursor(committed_batches=5, wal_seq=17)
    shard_path = flightrec.dump("abort", error=AnalysisError("x"), exit_code=1)
    assert shard_path and os.path.exists(shard_path)
    pm = flightrec.merge(d, trigger="abort", error=AnalysisError("x"), exit_code=1)
    bundle = load_bundle(d)  # a dir holding postmortem.json also loads
    assert bundle["kind"] == "ra-postmortem" and pm.endswith("postmortem.json")
    (shard,) = bundle["shards"]
    assert shard["cursors"] == {"committed_batches": 5, "wal_seq": 17}
    a = bundle["analysis"]
    assert a["fault_sites_fired"] == {"stream.device_put.fail": 1}
    assert a["failing_stage"] == "ingest.backpressure"
    occ = a["per_shard"][0]["stage_occupancy_pct"]
    assert occ.get("ingest.backpressure", 0) > 0
    # unregistered triggers refuse loudly
    with pytest.raises(AnalysisError):
        flightrec.dump("not-a-trigger")


def test_classifier_covers_registered_triggers():
    assert classify(StallError("x")) == "stall"
    assert classify(AnalysisError("x")) == "abort"
    assert classify(ValueError("x")) == "unhandled"
    for t in ("stall", "abort", "unhandled", "signal", "crash", "worker-exit"):
        assert t in TRIGGERS


def test_sigquit_dumps_a_live_snapshot(tmp_path):
    d = str(tmp_path / "bb")
    flightrec.arm(d, role="main")
    obs.instant("checkpoint.commit", args={"chunk": 3})
    os.kill(os.getpid(), signal.SIGQUIT)
    # CPython delivers the signal on the main thread at the next bytecode
    wait_for(
        lambda: os.path.exists(os.path.join(d, "postmortem.json")),
        timeout=10, msg="SIGQUIT postmortem",
    )
    bundle = load_bundle(d)
    assert bundle["trigger"] == "signal"
    assert bundle["shards"][0]["trigger"] == "signal"
    # the operator snapshot did NOT stop the process (we are still here)
    # and a later clean finalize keeps the signal-dumped evidence
    assert flightrec.finalize() is not None


def test_sigquit_while_main_thread_holds_ring_lock(tmp_path):
    # the handler fires ON the main thread; the snapshot must not run
    # inline there or it deadlocks on the very locks the interrupted
    # frame holds (ring/cursor/sampler critical sections)
    d = str(tmp_path / "bb")
    rec = flightrec.arm(d, role="main")
    with rec.ring._lock:
        os.kill(os.getpid(), signal.SIGQUIT)
        time.sleep(0.3)  # signal delivers here, inside the critical section
    wait_for(
        lambda: os.path.exists(os.path.join(d, "postmortem.json")),
        timeout=10, msg="SIGQUIT snapshot under held lock",
    )
    assert load_bundle(d)["trigger"] == "signal"


def test_keyboard_interrupt_is_teardown_not_a_crash(tmp_path):
    # operator Ctrl-C of an armed run must not leave forensics claiming
    # an unhandled crash for the doctor to misdiagnose
    d = str(tmp_path / "bb")
    flightrec.arm(d, role="main")
    try:
        raise KeyboardInterrupt()
    except KeyboardInterrupt:
        out = flightrec.finalize()
    assert out is None
    assert not (os.path.isdir(d) and os.listdir(d))


def test_noted_abort_merges_presealed_worker_shards(tmp_path):
    # the elastic supervisor catches typed aborts and returns an exit
    # code; its note_abort must make finalize MERGE the generation
    # workers' sealed shards instead of pruning them as a clean exit
    d = str(tmp_path / "bb")
    flightrec.arm(d, role="elastic-supervisor")
    os.makedirs(d, exist_ok=True)
    with open(os.path.join(d, "blackbox-9999.json"), "w") as f:
        json.dump({
            "kind": "ra-blackbox-shard", "role": "elastic-worker",
            "pid": 9999, "trigger": "worker-exit", "ring_events": [],
            "cursors": {},
        }, f)
    flightrec.note_abort(AnalysisError("autoscale fault"), 2)
    pm = flightrec.finalize()
    assert pm is not None
    roles = {s.get("role") for s in load_bundle(d)["shards"]}
    assert roles == {"elastic-worker", "elastic-supervisor"}


def test_rearm_same_dir_forgets_previous_runs_failure(tmp_path):
    # two runs in one process sharing a blackbox dir (the default-path
    # case): run 1's noted abort must not leak a postmortem out of run
    # 2's clean finalize
    d = str(tmp_path / "bb")
    flightrec.arm(d, role="main")
    flightrec.note_abort(AnalysisError("run-1 failure"), 1)
    flightrec.arm(d, role="main")  # same dir -> idempotent early return
    assert flightrec.finalize() is None
    assert not (os.path.isdir(d) and os.listdir(d))


def test_stage_occupancy_empty_and_instant_only():
    assert stage_occupancy([]) == {}
    assert stage_occupancy([{"ph": "i", "name": "x", "ts": 1}]) == {}


# ---------------------------------------------------------------------------
# CLI end-to-end: abort -> bundle -> doctor; clean -> nothing.
# ---------------------------------------------------------------------------


def test_chaos_abort_leaves_parseable_postmortem_and_doctor_names_site(
    corpus, tmp_path, capsys
):
    _, prefix, log, _ = corpus
    bb = tmp_path / "bb"
    rc = run_cli(
        prefix, log, bb, "--fault-plan", "ingest.producer.raise@2",
    )
    assert rc == 1  # typed InjectedFault abort
    bundle = load_bundle(str(bb))
    assert bundle["exit_code"] == 1 and bundle["trigger"] == "abort"
    assert bundle["error_type"] == "InjectedFault"
    a = bundle["analysis"]
    assert a["fault_sites_fired"] == {"ingest.producer.raise": 1}
    assert a["failing_stage"]  # a concrete stage, not None
    # ring carried real pipeline spans from the UNARMED trace plane
    names = {e["name"] for s in bundle["shards"] for e in s["ring_events"]}
    assert "step.dispatch" in names and "ingest.produce" in names
    # doctor: ranked diagnosis names the injected site
    capsys.readouterr()
    assert cli.main(["doctor", str(bb)]) == 0
    text = capsys.readouterr().out
    assert "ingest.producer.raise" in text and "INJECTED" in text
    diags = diagnose(bundle)
    assert diags[0]["cause"] == "an armed fault plan fired"


def test_clean_exit_leaves_no_forensics(corpus, tmp_path):
    _, prefix, log, _ = corpus
    bb = tmp_path / "bb-clean"
    assert run_cli(prefix, log, bb) == 0
    # no shards, no postmortem — an unarmed-trace clean run is untouched
    assert not os.path.exists(str(bb)) or not os.listdir(str(bb))


def test_stall_bundle_attributes_the_starved_side(corpus, tmp_path, capsys):
    _, prefix, log, _ = corpus
    bb = tmp_path / "bb-stall"
    rc = run_cli(
        prefix, log, bb,
        "--fault-plan", "ingest.queue.stall@2", "--stall-timeout", "3",
    )
    assert rc == 6  # StallError: watchdog bounded the wedged producer
    bundle = load_bundle(str(bb))
    assert bundle["trigger"] == "stall"
    capsys.readouterr()
    assert cli.main(["doctor", str(bb)]) == 0
    text = capsys.readouterr().out
    assert "STARVED" in text or "stall" in text.lower()


@pytest.mark.skipif(not fastparse.available(), reason="needs native parser")
def test_worker_kill_merges_bundle_from_surviving_processes(
    corpus, tmp_path
):
    _, prefix, log, _ = corpus
    bb = tmp_path / "bb-kill"
    rc = run_cli(
        prefix, log, bb,
        "--feed-workers", "2", "--native-parse",
        "--fault-plan", "feeder.worker.crash@2",
    )
    assert rc == 5  # FeedWorkerError: the coordinator saw the dead worker
    bundle = load_bundle(str(bb))
    shards = bundle["shards"]
    roles = {s["role"] for s in shards}
    triggers = {s["trigger"] for s in shards}
    # the supervising process dumped on the typed abort...
    assert "main" in roles and "abort" in triggers
    # ...and the dying worker's ring dumped AT the crash site (os._exit
    # runs no teardown — the "crash" trigger is its only exit path), so
    # the merged bundle spans more than one process
    assert len(shards) >= 2
    assert "crash" in triggers
    # hit counters are per process: every worker reaching its Nth task
    # crashes, and every crash dump carries its own fault instant
    assert bundle["analysis"]["fault_sites_fired"].get(
        "feeder.worker.crash", 0
    ) >= 1
    diags = diagnose(bundle, exit_code=5)
    assert any("feed tier" in d["cause"] for d in diags)


# ---------------------------------------------------------------------------
# Serve: end-to-end latency SLO histograms on /metrics (JSON + prom).
# ---------------------------------------------------------------------------


def test_serve_latency_histograms_json_and_prom_agree(corpus, tmp_path):
    _, prefix, _, lines = corpus
    spool = tmp_path / "spool.log"
    spool.write_text("\n".join(lines[:200]) + "\n", encoding="utf-8")
    scfg = ServeConfig(
        listen=(f"tail0:{spool}",),
        window_lines=100,
        ring=4,
        serve_dir=str(tmp_path / "serve"),
        checkpoint_every_windows=0,
        reload_watch=False,
        stop_after_sec=120,
    )
    cfg = AnalysisConfig(batch_size=128, prefetch_depth=0)
    drv, th, out = start_serve(prefix, cfg, scfg)
    try:
        wait_for(
            lambda: drv.windows_published >= 2 or "error" in out,
            timeout=90, msg="two windows",
        )
        assert "error" not in out, out.get("error")
        http = drv.http_address
        # JSON gauges: the SLO percentiles
        m = get_json(http, "/metrics")
        assert m["latency_ingest_to_publish_count"] >= 200
        p99_json = m["latency_ingest_to_publish_p99_sec"]
        assert p99_json > 0
        # prom: a well-formed HISTOGRAM whose bucket-derived p99 matches
        host, port = http
        with urllib.request.urlopen(
            f"http://{host}:{port}/metrics?format=prom", timeout=10
        ) as r:
            prom = r.read().decode()
        name = "ra_serve_ingest_to_publish_seconds"
        assert f"# TYPE {name} histogram" in prom
        assert f'{name}_bucket{{le="+Inf"}}' in prom
        assert quantile_from_prom(prom, name, 0.99) == p99_json
        # the prom gauge rendering carries the same percentile gauges
        assert "ra_serve_latency_ingest_to_publish_p99_sec" in prom
        # window report: totals.latency.ingest_to_publish
        rep = get_json(http, "/report")
        lat = rep["totals"]["latency"]["ingest_to_publish"]
        assert lat["count"] >= 100 and lat["p99_sec"] > 0
        cum = get_json(http, "/report/cumulative")
        assert cum["totals"]["latency"]["ingest_to_publish"]["count"] >= 200
    finally:
        drv.stop()
    finish(th, out)


def test_ingest_latency_rides_metrics_sampler(corpus):
    """The batch-e2e histogram lands in the ingest sampler gauges."""
    from ruleset_analysis_tpu.runtime.ingest import PrefetchingSource
    from ruleset_analysis_tpu.runtime.stream import run_stream

    from ruleset_analysis_tpu.config import SketchConfig

    packed, _, _, lines = corpus
    # the module's ONE CLI geometry: the step compile is already cached
    cfg = AnalysisConfig(
        backend="tpu", batch_size=256, prefetch_depth=2,
        sketch=SketchConfig(cms_width=4096, cms_depth=2, hll_p=6),
    )
    src_holder = {}
    orig_init = PrefetchingSource.__init__

    def spy_init(self, *a, **kw):
        orig_init(self, *a, **kw)
        src_holder["src"] = self

    PrefetchingSource.__init__ = spy_init
    try:
        run_stream(packed, iter(lines[:600]), cfg)
    finally:
        PrefetchingSource.__init__ = orig_init
    src = src_holder["src"]
    assert src.latency.count >= 1
    gauges = src._sample_metrics()
    assert "latency_batch_e2e_p99_sec" in gauges
    assert src.latency_summary()["batch_e2e"]["count"] == src.latency.count


# ---------------------------------------------------------------------------
# trace_summary + registry audit.
# ---------------------------------------------------------------------------


def test_trace_summary_renders_blackbox_block(tmp_path):
    import sys

    sys.path.insert(
        0, os.path.join(os.path.dirname(os.path.dirname(__file__)), "tools")
    )
    import trace_summary

    d = str(tmp_path / "bb")
    flightrec.arm(d, role="main")
    with obs.span("ingest.produce", n_raw=3):
        time.sleep(0.001)
    obs.instant("fault.listener.drop", args={"hit": 1})
    flightrec.cursor(window=4)
    flightrec.dump("stall", error=StallError("wedged"), exit_code=6)
    pm = flightrec.merge(d, trigger="stall", error=StallError("wedged"),
                         exit_code=6)
    s = trace_summary.summarize(pm)
    bb = s["blackbox"]
    assert bb["trigger"] == "stall" and bb["exit_code"] == 6
    assert bb["fault_sites_fired"] == {"listener.drop": 1}
    assert bb["shards"][0]["cursors"] == {"window": 4}
    assert bb["shards"][0]["stage_occupancy_pct"].get("ingest.produce", 0) > 0
    text = trace_summary.render(s)
    assert "blackbox:" in text and "cursors: window=4" in text


def test_observability_registry_audit_is_clean():
    from ruleset_analysis_tpu.verify.registry import audit_observability

    findings = audit_observability()
    assert findings == [], [f"{f.kind}:{f.subject}" for f in findings]
