"""ralint — the static program-invariant lint plane (DESIGN §18).

Tier-1 coverage of the verify/ package:

- the representative program subset traces and lints clean (abstract
  eval only — no device data, no XLA compile, so this module is cheap);
- the DERIVED weighted-refusal verdicts equal the declarative table
  (config.WEIGHTED_INPUT_REFUSALS) the runtime refusal path reads — the
  no-drift acceptance criterion;
- a set of deliberately broken mini-programs (nonlinear weight use,
  ``indices_are_sorted`` without a sort, missing/unregistered ``ra.*``
  scopes, wrong merge dtype/law, weight-dependent scatter routing) is
  MUST-flag: this pins zero false negatives, not just zero false
  positives;
- the repo registry auditor (fault sites / CLI flags vs README+PARITY /
  VOLATILE totals keys) passes clean;
- the runtime weighted-input refusals are typed and driven by the table.

The FULL grid lint (~76 programs) runs under ``make lint`` and as a
``slow``-marked test here; the tier-1 subset covers every verdict class
and check dimension at least once.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import pytest  # noqa: E402

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
from jax import lax  # noqa: E402

from ruleset_analysis_tpu.config import (  # noqa: E402
    WEIGHTED_INPUT_REFUSALS,
    AnalysisConfig,
)
from ruleset_analysis_tpu.errors import AnalysisError  # noqa: E402
from ruleset_analysis_tpu.verify import (  # noqa: E402
    ProgramSpec,
    fast_grid,
    lint_program,
    shipping_grid,
    trace_program,
)
from ruleset_analysis_tpu.verify.grid import _sds, trace_fixture  # noqa: E402
from ruleset_analysis_tpu.verify.report import (  # noqa: E402
    check_table_drift,
    expected_weighted_refusal,
)


@pytest.fixture(scope="module")
def fast_lints():
    """Trace + lint the representative subset once per module."""
    return [lint_program(trace_program(s)) for s in fast_grid()]


# ---------------------------------------------------------------------------
# Shipping-grid verdicts
# ---------------------------------------------------------------------------


def test_fast_grid_zero_violations(fast_lints):
    """No shipping program violates scatter/scope/merge invariants."""
    for pl in fast_lints:
        viols = [f for f in pl.findings if f.severity == "violation"]
        assert not viols, (pl.spec.name, [f.kind for f in viols])


def test_fast_grid_weight_verdicts(fast_lints):
    """Derived weight-linearity per impl family, exactly as designed:
    xla/pallas matches and scatter/reduce counts and both update impls
    prove LINEAR; matmul counts derive float-bounded; the opaque
    pallas_fused kernel derives unprovable."""
    by_name = {pl.spec.name: pl for pl in fast_lints}
    for name, pl in by_name.items():
        if "pallas_fused" in name:
            assert pl.weight_verdict == "unprovable", name
        elif "matmul" in name:
            assert pl.weight_verdict == "float-bounded", name
        else:
            assert pl.weight_verdict == "linear", name


def test_fast_grid_scope_coverage(fast_lints):
    """Zero unattributed register-update primitives in shipping code."""
    for pl in fast_lints:
        scope = [f for f in pl.findings if f.check == "scope"]
        assert not scope, (pl.spec.name, [f.kind for f in scope])


def test_fast_grid_merge_seams(fast_lints):
    """Every register output crossed its law's collective with its
    law's dtype (counts exempt only under exact_counts=False)."""
    for pl in fast_lints:
        merge = [f for f in pl.findings if f.check == "merge"]
        assert not merge, (pl.spec.name, [f.kind for f in merge])
        if getattr(pl.spec, "exact_counts", True):
            assert "psum" in pl.outputs["counts_lo"]["prov"], pl.spec.name
        assert "pmax" in pl.outputs["hll"]["prov"], pl.spec.name
        assert "psum" not in pl.outputs["hll"]["prov"], pl.spec.name
        assert "all_gather" in pl.outputs["cand_est"]["prov"], pl.spec.name


def test_derived_refusals_match_table(fast_lints):
    """The no-drift criterion: derived weighted-refusal set == the ONE
    declarative table, in both directions."""
    assert check_table_drift(fast_lints) == []
    # and the table's members really are what ships in config.py
    fields = {(r.field, r.value) for r in WEIGHTED_INPUT_REFUSALS}
    assert ("match_impl", "pallas_fused") in fields
    assert ("counts_impl", "matmul") in fields
    assert len(fields) == 2  # today's exact refusal set, nothing more


def test_full_grid_enumerates_all_shipping_combos():
    """Grid membership is derived from AnalysisConfig validation: every
    spec is constructible, invalid combos are absent, and the grid
    covers the whole impl space (enumeration only — no tracing)."""
    grid = shipping_grid()
    names = {s.name for s in grid}
    assert len(names) == len(grid)  # no duplicates
    assert len(grid) >= 60
    for s in grid:
        assert s.is_shipping(), s.name
    # pallas_fused ships only with scatter counts + scatter updates
    fused = [s for s in grid if s.match_impl == "pallas_fused"]
    assert fused and all(
        s.counts_impl == "scatter" and s.update_impl == "scatter"
        for s in fused
    )
    # sorted x pallas_fused (config-refused) must NOT appear
    assert not any(
        s.match_impl == "pallas_fused" and s.update_impl == "sorted"
        for s in grid
    )
    # every kind and impl axis is represented
    assert {s.kind for s in grid} == {"flat", "stacked", "v6", "tenant"}
    assert {s.counts_impl for s in grid} == {"scatter", "matmul", "reduce"}
    assert {s.update_impl for s in grid} == {"scatter", "sorted"}


@pytest.mark.slow
def test_full_grid_lint_clean():
    """The whole shipping grid (what `make lint` traces): zero
    violations, zero table drift."""
    lints = [lint_program(trace_program(s)) for s in shipping_grid()]
    for pl in lints:
        assert pl.ok, (pl.spec.name, [f.kind for f in pl.findings])
    assert check_table_drift(lints) == []


# ---------------------------------------------------------------------------
# Negative fixtures — MUST flag (zero false negatives)
# ---------------------------------------------------------------------------

_K = 8


def _lint_fixture(fn, out_names=("out",), name="fixture"):
    keys = _sds((32,))
    w = _sds((32,))
    traced = trace_fixture(
        fn, (keys, w), weight_arg=1, output_names=out_names, name=name
    )
    return lint_program(traced)


def _kinds(pl):
    return {f.kind for f in pl.findings}


def test_fixture_nonlinear_weight_use():
    pl = _lint_fixture(
        lambda k, w: (jnp.zeros(_K, jnp.uint32).at[k].add(w * w, mode="drop"),)
    )
    assert "nonlinear-into-add" in _kinds(pl)
    assert pl.weight_verdict == "nonlinear"


def test_fixture_gated_into_add():
    """The pallas_fused bug class spelled in pure jax: counting one per
    valid row instead of the row's weight."""
    pl = _lint_fixture(
        lambda k, w: (
            jnp.zeros(_K, jnp.uint32)
            .at[k]
            .add((w > 0).astype(jnp.uint32), mode="drop"),
        )
    )
    assert "gated-into-add" in _kinds(pl)
    assert pl.weight_verdict == "gated"


def test_fixture_float_roundtrip():
    pl = _lint_fixture(
        lambda k, w: (
            jnp.zeros(_K, jnp.uint32)
            .at[k]
            .add(w.astype(jnp.float32).astype(jnp.uint32), mode="drop"),
        )
    )
    assert "float-into-add" in _kinds(pl)
    assert pl.weight_verdict == "float-bounded"


def test_fixture_sorted_claim_without_sort():
    pl = _lint_fixture(
        lambda k, w: (
            jnp.zeros(_K, jnp.uint32)
            .at[k]
            .add(w, mode="drop", indices_are_sorted=True),
        )
    )
    assert "sorted-claim-without-sort" in _kinds(pl)


def test_fixture_sorted_claim_with_sort_passes():
    """The positive twin: a genuine sort on the key chain is accepted."""

    def fn(k, w):
        ks, ws = lax.sort((k, w), num_keys=1)
        with jax.named_scope("ra.counts"):
            d = jnp.zeros(_K, jnp.uint32).at[ks].add(
                ws, mode="drop", indices_are_sorted=True
            )
        return (d,)

    pl = _lint_fixture(fn)
    assert "sorted-claim-without-sort" not in _kinds(pl)


def test_fixture_scatter_without_drop():
    pl = _lint_fixture(
        lambda k, w: (jnp.zeros(_K, jnp.uint32).at[k].add(w, mode="clip"),)
    )
    assert "scatter-not-drop" in _kinds(pl)


def test_fixture_missing_scope():
    pl = _lint_fixture(
        lambda k, w: (jnp.zeros(_K, jnp.uint32).at[k].add(w, mode="drop"),)
    )
    assert "unattributed-register-update" in _kinds(pl)


def test_fixture_unregistered_stage():
    def fn(k, w):
        with jax.named_scope("ra.bogus"):
            return (jnp.zeros(_K, jnp.uint32).at[k].add(w, mode="drop"),)

    pl = _lint_fixture(fn)
    assert "unregistered-stage" in _kinds(pl)


def test_fixture_linear_into_max():
    """Weight magnitude into a max-law register: not idempotent."""
    pl = _lint_fixture(
        lambda k, w: (jnp.zeros(_K, jnp.uint32).at[k].max(w, mode="drop"),)
    )
    assert "linear-into-max" in _kinds(pl)


def test_fixture_wrong_merge_law():
    """An hll output merged by psum: wrong law + missing pmax seam."""

    def fn(k, w):
        with jax.named_scope("ra.hll"):
            d = jnp.zeros(_K, jnp.uint32).at[k].max(
                (w > 0).astype(jnp.uint32), mode="drop"
            )
        with jax.named_scope("ra.merge"):
            return (lax.psum(d, "data"),)

    pl = _lint_fixture(fn, out_names=("hll",))
    assert "wrong-merge-law" in _kinds(pl)
    assert "missing-merge-seam" in _kinds(pl)


def test_fixture_missing_merge_seam():
    def fn(k, w):
        with jax.named_scope("ra.cms"):
            return (jnp.zeros(_K, jnp.uint32).at[k].add(w, mode="drop"),)

    pl = _lint_fixture(fn, out_names=("cms",))
    assert "missing-merge-seam" in _kinds(pl)


def test_fixture_bad_register_dtype():
    def fn(k, w):
        with jax.named_scope("ra.cms"):
            d = jnp.zeros(_K, jnp.float32).at[k].add(
                w.astype(jnp.float32), mode="drop"
            )
        with jax.named_scope("ra.merge"):
            return (lax.psum(d, "data"),)

    pl = _lint_fixture(fn, out_names=("cms",))
    assert "register-dtype" in _kinds(pl)


def test_fixture_weight_dependent_indices():
    pl = _lint_fixture(
        lambda k, w: (
            jnp.zeros(_K, jnp.uint32)
            .at[(k + w) % _K]
            .add(jnp.ones(32, jnp.uint32), mode="drop"),
        )
    )
    assert "tainted-scatter-indices" in _kinds(pl)
    assert pl.weight_verdict == "nonlinear"


# ---------------------------------------------------------------------------
# Registry auditor + runtime refusal path
# ---------------------------------------------------------------------------


def test_registry_audit_clean():
    """Fault sites <-> call sites <-> tests; CLI flags <-> README <->
    PARITY; VOLATILE keys <-> producers: all clean in this repo."""
    from ruleset_analysis_tpu.verify import audit_registry

    findings = audit_registry()
    assert findings == [], [
        (f.registry, f.kind, f.subject) for f in findings
    ]


def test_weighted_runtime_refusals_typed_and_table_driven():
    """The runtime refusal path consumes the SAME table the linter
    cross-checks: both table entries refuse typed, everything else
    passes (incl. sorted updates — weight-linear by construction)."""
    from ruleset_analysis_tpu.runtime.stream import (
        _check_weighted_input_config,
    )

    with pytest.raises(AnalysisError, match="pallas_fused"):
        _check_weighted_input_config(
            AnalysisConfig(match_impl="pallas_fused")
        )
    with pytest.raises(AnalysisError, match="matmul"):
        _check_weighted_input_config(AnalysisConfig(counts_impl="matmul"))
    _check_weighted_input_config(AnalysisConfig())
    _check_weighted_input_config(AnalysisConfig(update_impl="sorted"))
    _check_weighted_input_config(AnalysisConfig(counts_impl="reduce"))


def test_expected_refusal_helper_matches_config_fields():
    assert expected_weighted_refusal(
        ProgramSpec(kind="flat", match_impl="pallas_fused")
    ) == "unprovable"
    assert expected_weighted_refusal(
        ProgramSpec(kind="v6", counts_impl="matmul")
    ) == "float-bounded"
    assert expected_weighted_refusal(ProgramSpec(kind="flat")) is None


def test_stages_taxonomy_single_source():
    """devprof re-exports the stages.py tuple — identity, not a copy."""
    from ruleset_analysis_tpu import stages
    from ruleset_analysis_tpu.runtime import devprof

    assert devprof.STAGES is stages.STAGES
    assert stages.scope_of("jit/ra.talk/ra.cms/scatter") == "ra.talk"
    assert stages.scope_of("fusion.5") is None


def test_volatile_totals_imported_by_identity_suites():
    """The canonical list covers every key the per-module lists held."""
    from ruleset_analysis_tpu.runtime.report import VOLATILE_TOTALS

    assert set(VOLATILE_TOTALS) >= {
        "elapsed_sec", "lines_per_sec", "compile_sec",
        "sustained_lines_per_sec", "ingest", "throughput", "coalesce",
        "autoscale", "recovery", "devprof",
    }
