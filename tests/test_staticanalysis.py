"""Static ruleset analysis plane (ISSUE 12; DESIGN §17).

Pins the subsystem's four contracts:

- **Exactness** (the acceptance bar): on exhaustively enumerable field
  domains the analyzer's dead set equals a brute-force first-match
  oracle's EXACTLY, across seeded random rulesets (any/point/range
  fields, duplicates, multi-ACE rules) — and every dead verdict carries
  an exact single-rule cover or a complete witness-exhaustion record.
- **Evidence join**: unused rules classify into provably-dead vs
  traffic-dependent vs undecided; a hit on a dead-verdict rule is a
  typed AnalyzerContradiction (strict) or an annotated contradiction
  (reports spanning reloads) — never silent.
- **Chaos**: an `analyze.tile` fault mid-grid aborts typed; a partial
  verdict table is never published as complete (direct + serve-reload).
- **Serve integration**: /report/static, window-report verdict fields,
  freshness gauges, and signature-reuse across hot reload.
"""

import itertools
import json
import socket
import threading
import time
import urllib.request

import numpy as np
import pytest

pytest.importorskip("jax")

from ruleset_analysis_tpu.config import AnalysisConfig, ServeConfig
from ruleset_analysis_tpu.errors import (
    AnalysisError,
    AnalyzerContradiction,
    InjectedFault,
)
from ruleset_analysis_tpu.hostside import aclparse, pack, synth
from ruleset_analysis_tpu.hostside.aclparse import Ace, AclRule, Ruleset
from ruleset_analysis_tpu.runtime import faults
from ruleset_analysis_tpu.runtime import report as report_mod
from ruleset_analysis_tpu.runtime import staticanalysis as sa_mod

# ---------------------------------------------------------------------------
# Brute-force oracle over tiny enumerable domains.
#
# The universe is the PRODUCT of the tiny domains: every rule interval is
# drawn inside it ("any" == the full tiny domain), so reachability over
# the enumerated universe equals reachability over uint32 space — the
# oracle is exact, not a sample.
# ---------------------------------------------------------------------------

DOM_PROTO, DOM_ADDR, DOM_PORT = 4, 16, 4  # 4*16*4*16*4 = 16384 packets

_G = np.meshgrid(
    np.arange(DOM_PROTO), np.arange(DOM_ADDR), np.arange(DOM_PORT),
    np.arange(DOM_ADDR), np.arange(DOM_PORT), indexing="ij",
)
PKT_PROTO, PKT_SRC, PKT_SPORT, PKT_DST, PKT_DPORT = (
    g.ravel().astype(np.int64) for g in _G
)


def oracle_reachable(rules: list[AclRule]) -> set[int]:
    """First-match scan over EVERY packet: which rule positions can win."""
    unclaimed = np.ones(PKT_PROTO.size, dtype=bool)
    reach: set[int] = set()
    for k, rule in enumerate(rules):
        m = np.zeros(PKT_PROTO.size, dtype=bool)
        for a in rule.aces:
            m |= (
                (PKT_PROTO >= a.proto_lo) & (PKT_PROTO <= a.proto_hi)
                & (PKT_SRC >= a.src_lo) & (PKT_SRC <= a.src_hi)
                & (PKT_SPORT >= a.sport_lo) & (PKT_SPORT <= a.sport_hi)
                & (PKT_DST >= a.dst_lo) & (PKT_DST <= a.dst_hi)
                & (PKT_DPORT >= a.dport_lo) & (PKT_DPORT <= a.dport_hi)
            )
        if (m & unclaimed).any():
            reach.add(k)
        unclaimed &= ~m
    return reach


def _iv(rng, dom):
    r = rng.random()
    if r < 0.3:
        return 0, dom - 1  # any
    if r < 0.6:
        v = int(rng.integers(dom))
        return v, v  # point
    a, b = sorted(int(x) for x in rng.integers(0, dom, size=2))
    return a, b


def tiny_ruleset(rng, n_rules: int) -> Ruleset:
    rules = []
    for i in range(n_rules):
        n_aces = 1 if rng.random() < 0.8 else 2
        aces = [
            Ace(
                int(rng.integers(2)),
                *_iv(rng, DOM_PROTO), *_iv(rng, DOM_ADDR), *_iv(rng, DOM_PORT),
                *_iv(rng, DOM_ADDR), *_iv(rng, DOM_PORT),
            )
            for _ in range(n_aces)
        ]
        rules.append(AclRule(acl="T", index=i + 1, text=f"r{i}", aces=aces))
    return Ruleset(firewall="fw", acls={"T": rules})


@pytest.mark.parametrize("seed", range(10))
def test_analyzer_matches_bruteforce_oracle_exactly(seed):
    rng = np.random.default_rng(seed)
    n_rules = int(rng.integers(5, 11))
    rs = tiny_ruleset(rng, n_rules)
    # fixed pad -> one shared first_match compile across all seeds
    packed = pack.pack_rulesets([rs], pad_rules_to=32)
    res = sa_mod.analyze_ruleset(packed, witness_budget=1 << 17)

    reach = oracle_reachable(rs.acls["T"])
    dead_oracle = set(range(n_rules)) - reach
    dead_analyzer = res.dead_keys()
    assert dead_analyzer == dead_oracle, (
        f"seed {seed}: analyzer dead {sorted(dead_analyzer)} != oracle "
        f"dead {sorted(dead_oracle)}"
    )
    # proof-object discipline: every dead verdict is certified with an
    # exact cover or a COMPLETE exhaustion record; nothing undecided can
    # be dead
    for kid, v in res.verdicts.items():
        if v.dead:
            assert v.certified
            assert v.basis in ("single-cover", "witness-exhaustion")
            if v.basis == "single-cover":
                assert v.cover_key is not None and v.cover_key < kid
            else:
                assert v.witness_grid >= v.witnesses_checked > 0
        if v.witness is not None:
            # a claimed witness really is a packet the rule wins
            p = v.witness
            claimed = oracle_reachable(rs.acls["T"][: kid + 1])
            assert kid in claimed
    assert res.meta["complete"] is True


def test_implicit_any_rule_first_kills_everything_after():
    """Degenerate but common misconfig: any-any first -> all later dead."""
    rng = np.random.default_rng(99)
    rs = tiny_ruleset(rng, 6)
    any_rule = AclRule(
        acl="T", index=1, text="any",
        aces=[Ace(1, 0, DOM_PROTO - 1, 0, DOM_ADDR - 1, 0, DOM_PORT - 1,
                  0, DOM_ADDR - 1, 0, DOM_PORT - 1)],
    )
    rs.acls["T"] = [any_rule] + [
        AclRule(acl="T", index=i + 2, text=r.text, aces=r.aces)
        for i, r in enumerate(rs.acls["T"])
    ]
    packed = pack.pack_rulesets([rs])
    res = sa_mod.analyze_ruleset(packed)
    assert res.verdicts[0].verdict == sa_mod.REACHABLE
    assert res.dead_keys() == set(range(1, 7))


# ---------------------------------------------------------------------------
# Verdict lattice on a hand-built ruleset.
# ---------------------------------------------------------------------------

LATTICE_CFG = """
hostname fw1
access-list A extended permit tcp any any eq 80
access-list A extended deny tcp any any eq 80
access-list A extended permit tcp host 10.0.0.1 any eq 80
access-list A extended permit udp any any range 100 200
access-list A extended deny udp any any range 150 250
access-list A extended permit udp any any range 100 250
access-list A extended permit ip any any
access-group A in interface outside
"""


@pytest.fixture(scope="module")
def lattice_packed():
    rs = aclparse.parse_asa_config(LATTICE_CFG, "fw1")
    return pack.pack_rulesets([rs])


@pytest.fixture(scope="module")
def lattice_analysis(lattice_packed):
    return sa_mod.analyze_ruleset(lattice_packed)


def test_verdict_lattice_hand_built(lattice_packed, lattice_analysis):
    v = lattice_analysis.verdicts
    assert v[0].verdict == sa_mod.REACHABLE and v[0].basis == "disjoint"
    # same box, different action, single earlier cover -> conflict
    assert v[1].verdict == sa_mod.CONFLICT and v[1].cover_key == 0
    # subset box, same action -> redundant
    assert v[2].verdict == sa_mod.REDUNDANT and v[2].cover_key == 0
    assert v[3].verdict == sa_mod.REACHABLE
    # udp 150-250 partially masked by 100-200; witness must be a dport
    # in 201..250 (device-checked concrete packet)
    assert v[4].verdict == sa_mod.PARTIAL and v[4].basis == "witness"
    assert v[4].certified and 201 <= v[4].witness[4] <= 250
    # udp 100-250 covered by the UNION of rules 4+5 only: dead via
    # complete witness exhaustion, never via a single cover
    assert v[5].verdict == sa_mod.SHADOWED
    assert v[5].basis == "witness-exhaustion"
    assert v[5].certified and v[5].witness_grid == v[5].witnesses_checked > 0
    assert v[6].verdict == sa_mod.PARTIAL and v[6].basis == "witness"


def test_witness_budget_truncation_is_honest(lattice_packed):
    """Grid > budget and no witness found -> undecided, NEVER dead."""
    res = sa_mod.analyze_ruleset(lattice_packed, witness_budget=1)
    v = res.verdicts[5]  # the union-shadowed rule (grid of 2)
    assert v.verdict == sa_mod.PARTIAL
    assert v.basis == "witness-budget"
    assert not v.certified
    assert v.witness_grid > 1 and v.witnesses_checked == 1
    assert 5 not in res.dead_keys()


def test_v6_bearing_rules_never_die_from_v4_plane():
    cfg = """
hostname fw1
access-list A extended permit ip any any
access-list A extended permit tcp any any eq 80
access-group A in interface inside
"""
    rs = aclparse.parse_asa_config(cfg, "fw1")
    packed = pack.pack_rulesets([rs])
    res = sa_mod.analyze_ruleset(packed)
    if packed.has_v6:
        # unified-ACL semantics: rule 2 has v6 rows too; its v4 side is
        # single-covered but the v4 plane must not certify it dead
        v = res.verdicts[1]
        assert v.verdict == sa_mod.PARTIAL
        assert v.basis == "v4-dead-v6-unanalyzed"
        assert not v.certified
        assert not res.dead_keys()
    else:  # pure-v4 expansion: plain single-cover redundancy
        assert res.verdicts[1].verdict == sa_mod.REDUNDANT


def test_tile_grid_independence(lattice_packed, lattice_analysis):
    """Tiny tiles (multi-tile grid) produce identical verdicts."""
    res = sa_mod.analyze_ruleset(lattice_packed, tile=2)
    assert res.meta["tiles_run"] > lattice_analysis.meta["tiles_run"]
    assert {
        k: (v.verdict, v.basis) for k, v in res.verdicts.items()
    } == {
        k: (v.verdict, v.basis)
        for k, v in lattice_analysis.verdicts.items()
    }


# ---------------------------------------------------------------------------
# Report join: evidence classes + the contradiction invariant.
# ---------------------------------------------------------------------------


def _report_with_hits(packed, hits_by_kid):
    hits = {}
    for kid, h in hits_by_kid.items():
        m = packed.key_meta[kid]
        hits[(m.firewall, m.acl, m.index)] = h
    return report_mod.build_report(packed, hits, backend="test")


def test_unused_rules_classify_by_evidence(lattice_packed, lattice_analysis):
    # traffic hit only rule 0: everything else is unused, split by verdict
    rep = _report_with_hits(lattice_packed, {0: 10})
    sa_mod.attach_static(rep, lattice_packed, lattice_analysis)
    st = rep.totals["static"]
    classes = st["unused_classes"]
    dead_rules = {f"fw1 A {k + 1}" for k in (1, 2, 5)}
    assert set(classes[sa_mod.CLASS_SAFE]) == dead_rules
    assert "fw1 A 4" in classes[sa_mod.CLASS_TRAFFIC]  # reachable
    assert "fw1 A 5" in classes[sa_mod.CLASS_TRAFFIC]  # certified witness
    assert classes[sa_mod.CLASS_UNDECIDED] == []
    # per-rule fields joined; implicit-deny keys carry none
    assert rep.per_rule[1]["verdict"] == sa_mod.CONFLICT
    assert "verdict" not in rep.per_rule[-1]
    # the text rendering names the classes
    txt = rep.to_text()
    assert "provably dead — safe to delete" in txt
    assert "reachable — traffic-dependent" in txt


def test_hit_on_dead_rule_is_typed_contradiction(
    lattice_packed, lattice_analysis
):
    rep = _report_with_hits(lattice_packed, {1: 3})  # dead rule with hits
    with pytest.raises(AnalyzerContradiction, match="fw1 A 2"):
        sa_mod.attach_static(rep, lattice_packed, lattice_analysis)
    # non-strict (counters spanning a reload): annotated, never silent
    rep2 = _report_with_hits(lattice_packed, {1: 3})
    sa_mod.attach_static(rep2, lattice_packed, lattice_analysis, strict=False)
    cons = rep2.totals["static"]["contradictions"]
    assert cons == [{"rule": "fw1 A 2", "hits": 3, "verdict": "conflict"}]
    assert "CONTRADICTION" in rep2.to_text()


def test_report_without_analysis_is_untouched(lattice_packed):
    rep = _report_with_hits(lattice_packed, {0: 1})
    assert "static" not in rep.totals
    assert "verdict" not in rep.per_rule[0]
    assert "[provably dead" not in rep.to_text()


def test_diff_reports_verdict_transitions(lattice_packed, lattice_analysis):
    rep_a = _report_with_hits(lattice_packed, {0: 1})
    rep_b = _report_with_hits(lattice_packed, {0: 2})
    sa_mod.attach_static(rep_a, lattice_packed, lattice_analysis)
    sa_mod.attach_static(rep_b, lattice_packed, lattice_analysis)
    obj_a = json.loads(rep_a.to_json())
    obj_b = json.loads(rep_b.to_json())
    # same verdicts -> present but empty
    assert report_mod.diff_report_objs(obj_a, obj_b)["verdict_transitions"] == []
    # a verdict flip is a TYPED diff row
    for e in obj_b["per_rule"]:
        if e["index"] == 4 and not e.get("verdict") is None:
            e["verdict"] = sa_mod.SHADOWED
    d = report_mod.diff_report_objs(obj_a, obj_b)
    assert d["verdict_transitions"] == [
        {"rule": "fw1 A 4", "old": "reachable", "new": "shadowed"}
    ]
    # verdict-free reports (analysis off) don't grow the key at all
    plain_a = json.loads(_report_with_hits(lattice_packed, {0: 1}).to_json())
    plain_b = json.loads(_report_with_hits(lattice_packed, {0: 2}).to_json())
    assert "verdict_transitions" not in report_mod.diff_report_objs(
        plain_a, plain_b
    )


# ---------------------------------------------------------------------------
# Incremental re-analysis (the reload path's signature reuse).
# ---------------------------------------------------------------------------

TWO_ACL_CFG = """
hostname fwx
access-list A extended permit tcp any any eq 80
access-list A extended permit tcp any any eq 80
access-list B extended permit udp any any eq 53
access-list B extended deny udp any any eq 53
access-group A in interface inside
access-group B in interface outside
"""

TWO_ACL_CFG_B_CHANGED = """
hostname fwx
access-list A extended permit tcp any any eq 80
access-list A extended permit tcp any any eq 80
access-list B extended deny udp any any eq 53
access-list B extended permit udp any any eq 53
access-group A in interface inside
access-group B in interface outside
"""


def test_reanalysis_reuses_unchanged_acls_exactly():
    old = pack.pack_rulesets(
        [aclparse.parse_asa_config(TWO_ACL_CFG, "fwx")]
    )
    new = pack.pack_rulesets(
        [aclparse.parse_asa_config(TWO_ACL_CFG_B_CHANGED, "fwx")]
    )
    sa_old = sa_mod.analyze_ruleset(old)
    incremental = sa_mod.analyze_ruleset(new, reuse=sa_old)
    assert incremental.meta["reused_acls"] == 1  # A untouched
    assert incremental.meta["analyzed_acls"] == 1  # B re-tiled
    fresh = sa_mod.analyze_ruleset(new)
    assert {
        k: (v.verdict, v.basis, v.certified, v.cover_key)
        for k, v in incremental.verdicts.items()
    } == {
        k: (v.verdict, v.basis, v.certified, v.cover_key)
        for k, v in fresh.verdicts.items()
    }
    # B's swap changed which rule dies (deny now first)
    assert incremental.verdicts[3].verdict == sa_mod.CONFLICT
    # identical ruleset -> everything reused, nothing re-tiled
    cached = sa_mod.analyze_ruleset(old, reuse=sa_old)
    assert cached.meta["reused_acls"] == 2
    assert cached.meta["analyzed_acls"] == 0
    assert cached.meta["tiles_run"] == 0


def test_reuse_remaps_key_ids_across_renumbering():
    """An ACL inserted BEFORE an unchanged one shifts its key ids; the
    reused verdicts (cover keys included) must follow."""
    base_cfg = """
hostname fwx
access-list Z extended permit tcp any any eq 80
access-list Z extended deny tcp any any eq 80
access-group Z in interface inside
"""
    grown_cfg = """
hostname fwx
access-list A extended permit udp any any eq 1
access-list A extended permit udp any any eq 2
access-list A extended permit udp any any eq 3
access-list Z extended permit tcp any any eq 80
access-list Z extended deny tcp any any eq 80
access-group A in interface outside
access-group Z in interface inside
"""
    old = pack.pack_rulesets([aclparse.parse_asa_config(base_cfg, "fwx")])
    new = pack.pack_rulesets([aclparse.parse_asa_config(grown_cfg, "fwx")])
    sa_old = sa_mod.analyze_ruleset(old)
    inc = sa_mod.analyze_ruleset(new, reuse=sa_old)
    assert inc.meta["reused_acls"] == 1
    # Z's keys moved 0,1 -> 3,4; the conflict + its cover pointer moved too
    assert inc.verdicts[4].verdict == sa_mod.CONFLICT
    assert inc.verdicts[4].cover_key == 3


# ---------------------------------------------------------------------------
# Chaos: the analyze.tile fault site (satellite; 2 seeded schedules).
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("seed", [0, 1])
def test_chaos_tile_fault_aborts_typed_never_partial(lattice_packed, seed):
    """Seeded schedules over the analyze.tile site: the analysis must
    abort with a TYPED error before any verdict object exists — a
    partial verdict table can never be mistaken for a complete one."""
    plan = faults.FaultPlan.random(seed, sites=["analyze.tile"], n_faults=1)
    at = plan.specs["analyze.tile"].at
    with faults.armed(plan):
        # tile=2 -> the 7-row ACL runs the 10 lower-triangle tiles of
        # its ceil(7/2)^2 grid >= any at (max 4)
        with pytest.raises(InjectedFault) as ei:
            sa_mod.analyze_ruleset(lattice_packed, tile=2)
    assert isinstance(ei.value, AnalysisError)
    assert f"hit {at}" in str(ei.value)
    # disarmed, the same call completes with the full verdict set
    res = sa_mod.analyze_ruleset(lattice_packed, tile=2)
    assert len(res.verdicts) == lattice_packed.n_rules
    assert res.meta["complete"] is True


# ---------------------------------------------------------------------------
# Serve integration: endpoint, gauges, reload reuse, atomic failure.
# ---------------------------------------------------------------------------

SERVE_OLD_CFG = """
hostname fws
access-list A extended permit tcp any any eq 80
access-list A extended deny tcp any any eq 80
access-list B extended permit udp any any eq 53
access-group A in interface inside
access-group B in interface outside
"""

SERVE_NEW_CFG = """
hostname fws
access-list A extended permit tcp any any eq 80
access-list A extended deny tcp any any eq 80
access-list B extended permit udp any any eq 53
access-list B extended permit udp any any eq 53
access-group A in interface inside
access-group B in interface outside
"""


def _serve_lines(packed, n, seed):
    t = synth.synth_tuples(packed, n, seed=seed)
    return synth.render_syslog(packed, t, seed=seed)


def test_serve_static_plane_end_to_end(tmp_path):
    from tests.test_serve import (  # shared driver harness
        finish, get_json, send_tcp, start_serve, wait_for,
    )

    old = pack.pack_rulesets([aclparse.parse_asa_config(SERVE_OLD_CFG, "fws")])
    new = pack.pack_rulesets([aclparse.parse_asa_config(SERVE_NEW_CFG, "fws")])
    prefix = str(tmp_path / "fws")
    pack.save_packed(old, prefix)
    lines = _serve_lines(old, 120, seed=5)

    cfg = AnalysisConfig(batch_size=128, prefetch_depth=0)
    scfg = ServeConfig(
        listen=("tcp:127.0.0.1:0",),
        window_lines=100,
        ring=4,
        serve_dir=str(tmp_path / "serve"),
        stop_after_sec=90,
        reload_watch=False,
        static_analysis=True,
        checkpoint_every_windows=0,
    )
    drv, th, out = start_serve(prefix, cfg, scfg)
    try:
        http = drv.http_address
        # verdicts are served before any traffic arrives
        st = get_json(http, "/report/static")
        assert st["meta"]["complete"] is True
        assert st["meta"]["dead"] == 1  # A's rule 2 (conflict)
        verd = {v["rule"]: v["verdict"] for v in st["verdicts"]}
        assert verd["fws A 2"] == sa_mod.CONFLICT
        # freshness gauges, JSON and prom text from the same source
        g = get_json(http, "/metrics")
        assert g["static_analysis_age_sec"] >= 0
        assert g["static_analysis_duration_sec"] >= 0
        host, port = http
        with urllib.request.urlopen(
            f"http://{host}:{port}/metrics?format=prom", timeout=10
        ) as r:
            prom = r.read().decode()
        assert "ra_serve_static_analysis_age_sec" in prom
        assert "ra_serve_static_analysis_duration_sec" in prom

        # a rotated window joins the verdicts into its report
        addr = drv.listeners.listeners[0].address
        send_tcp(addr, lines[:100])
        wait_for(
            lambda: get_json(http, "/health")["windows_published"] >= 1,
            60, "first rotation",
        )
        w0 = get_json(http, "/report/window/0")
        assert w0["totals"]["static"]["meta"]["dead"] == 1
        assert any(
            e.get("verdict") == sa_mod.CONFLICT for e in w0["per_rule"]
        )
        classes = w0["totals"]["static"]["unused_classes"]
        assert "fws A 2" in classes[sa_mod.CLASS_SAFE]

        # atomic reload failure: analyze.tile fires during re-analysis,
        # BEFORE anything swaps — old verdicts keep serving, complete
        pack.save_packed(new, prefix)
        faults.arm(faults.FaultPlan.parse("analyze.tile@1"))
        try:
            drv.request_reload()
            wait_for(
                lambda: get_json(http, "/health")["reload_errors"] == 1,
                30, "failed reload",
            )
        finally:
            faults.disarm()
        assert get_json(http, "/health")["reloads"] == 0
        still = get_json(http, "/report/static")
        assert still["meta"]["n_rules"] == old.n_rules
        assert still["meta"]["complete"] is True

        # successful reload: unchanged ACL A reuses its verdicts
        drv.request_reload()
        wait_for(lambda: get_json(http, "/health")["reloads"] == 1, 30,
                 "reload")
        st2 = get_json(http, "/report/static")
        assert st2["meta"]["n_rules"] == new.n_rules
        assert st2["meta"]["reused_acls"] == 1  # A
        assert st2["meta"]["analyzed_acls"] == 1  # B grew a rule
        verd2 = {v["rule"]: v["verdict"] for v in st2["verdicts"]}
        assert verd2["fws B 2"] == sa_mod.REDUNDANT  # duplicated udp rule
    finally:
        drv.stop()
        summary = finish(th, out)
    assert summary["reload_errors"] == 1
    # static verdicts landed on disk next to the window reports
    disk = json.loads((tmp_path / "serve" / "static.json").read_text())
    assert disk["meta"]["n_rules"] == new.n_rules


def test_serve_without_static_analysis_unchanged(tmp_path):
    """Analysis off (the default): no endpoint, no gauges, no report
    fields — the pre-ISSUE-12 service surface, bit-identical."""
    from tests.test_serve import finish, get_json, send_tcp, start_serve, wait_for

    old = pack.pack_rulesets([aclparse.parse_asa_config(SERVE_OLD_CFG, "fws")])
    prefix = str(tmp_path / "fws")
    pack.save_packed(old, prefix)
    cfg = AnalysisConfig(batch_size=128, prefetch_depth=0)
    scfg = ServeConfig(
        listen=("tcp:127.0.0.1:0",),
        window_lines=50,
        serve_dir=str(tmp_path / "serve"),
        stop_after_sec=60,
        reload_watch=False,
        checkpoint_every_windows=0,
    )
    drv, th, out = start_serve(prefix, cfg, scfg)
    try:
        http = drv.http_address
        with pytest.raises(urllib.error.HTTPError) as ei:
            get_json(http, "/report/static", retries=1)
        assert ei.value.code == 404
        assert "static_analysis_age_sec" not in get_json(http, "/metrics")
        send_tcp(drv.listeners.listeners[0].address,
                 _serve_lines(old, 50, seed=6))
        wait_for(
            lambda: get_json(http, "/health")["windows_published"] >= 1,
            60, "rotation",
        )
        w0 = get_json(http, "/report/window/0")
        assert "static" not in w0["totals"]
        assert all("verdict" not in e for e in w0["per_rule"])
    finally:
        drv.stop()
        finish(th, out)


# ---------------------------------------------------------------------------
# Units: guards + serialization.
# ---------------------------------------------------------------------------


def test_analyze_rejects_bad_budget(lattice_packed):
    with pytest.raises(AnalysisError, match="witness budget"):
        sa_mod.analyze_ruleset(lattice_packed, witness_budget=0)


def test_to_obj_round_trips_through_json(lattice_packed, lattice_analysis):
    obj = lattice_analysis.to_obj(lattice_packed)
    again = json.loads(json.dumps(obj))
    assert again == obj
    rules = [v["rule"] for v in obj["verdicts"]]
    assert rules == sorted(rules, key=lambda r: int(r.rsplit(" ", 1)[1]))


def test_key_meta_action_round_trips_and_defaults(tmp_path, lattice_packed):
    prefix = str(tmp_path / "p")
    pack.save_packed(lattice_packed, prefix)
    loaded = pack.load_packed(prefix)
    assert [m.action for m in loaded.key_meta] == [
        m.action for m in lattice_packed.key_meta
    ]
    assert loaded.key_meta[0].action == aclparse.PERMIT
    assert loaded.key_meta[1].action == aclparse.DENY
    # pre-ISSUE-12 artifact (no action in the json): loads as unknown
    meta_path = prefix + ".json"
    meta = json.loads(open(meta_path).read())
    for m in meta["key_meta"]:
        m.pop("action")
    with open(meta_path, "w") as f:
        json.dump(meta, f)
    old_style = pack.load_packed(prefix)
    assert all(
        m.action == -1 for m in old_style.key_meta if not m.implicit_deny
    )
    # unknown actions degrade covered verdicts to the action-free
    # "shadowed" (still dead) — never a wrong redundant/conflict claim
    res = sa_mod.analyze_ruleset(old_style)
    assert res.verdicts[1].verdict == sa_mod.SHADOWED
    assert res.verdicts[2].verdict == sa_mod.SHADOWED
