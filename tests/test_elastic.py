"""Elastic recovery: automatic cluster re-formation after peer death.

The acceptance surface of the SURVEY §3b elastic/retry analog
(runtime/elastic.py): 4 launcher processes run `run --distributed
--elastic` over 4 shards; one process is killed mid-run (abrupt
``os._exit`` mid-collective, the injected-fault analog of a node dying).
The survivors must detect the loss, re-form jax.distributed at world
size 3 with a re-elected coordinator, re-split the unread shards, resume
from the shared epoch checkpoint — and the final unused-rule report must
be BIT-IDENTICAL to an uninterrupted run over the same input, with no
manual ``--resume`` invocation anywhere.
"""

import json
import os
import subprocess
import sys

import pytest

from ruleset_analysis_tpu.hostside import aclparse, pack, synth
from ruleset_analysis_tpu.runtime.elastic import assign_shards

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# Pure-host units (no processes, no jax)
# ---------------------------------------------------------------------------


def test_assign_shards_round_robin_complete():
    shards = [f"s{i}" for i in range(5)]
    out = assign_shards(shards, {1: 100}, {0}, 3)
    # every remaining shard assigned exactly once, cursors preserved
    assert out == [
        [(1, "s1", 100), (4, "s4", 0)],
        [(2, "s2", 0)],
        [(3, "s3", 0)],
    ]
    # shrinking world re-splits the same remaining work
    out2 = assign_shards(shards, {1: 100}, {0}, 2)
    flat = sorted(x for part in out2 for x in part)
    assert flat == [(1, "s1", 100), (2, "s2", 0), (3, "s3", 0), (4, "s4", 0)]


def test_assign_shards_more_ranks_than_shards():
    out = assign_shards(["a", "b"], {}, {}, 4)
    assert out == [[(0, "a", 0)], [(1, "b", 0)], [], []]


def test_supervisor_refuses_wire_shards_and_no_cadence(tmp_path):
    from ruleset_analysis_tpu.config import AnalysisConfig
    from ruleset_analysis_tpu.errors import AnalysisError
    from ruleset_analysis_tpu.hostside import wire
    from ruleset_analysis_tpu.runtime.elastic import ElasticSupervisor

    log = tmp_path / "a.log"
    log.write_text("x\n")
    with pytest.raises(AnalysisError, match="checkpoint"):
        ElasticSupervisor(
            str(tmp_path / "d"), 0, 2, "rs", [str(log)],
            AnalysisConfig(checkpoint_every_chunks=0),
        )
    w = tmp_path / "a.rawire"
    w.write_bytes(wire.MAGIC + b"\0" * 64)
    with pytest.raises(AnalysisError, match="rawire"):
        ElasticSupervisor(
            str(tmp_path / "d"), 0, 2, "rs", [str(w)],
            AnalysisConfig(checkpoint_every_chunks=2),
        )


# ---------------------------------------------------------------------------
# Multi-process recovery (the acceptance tests)
# ---------------------------------------------------------------------------


def _launcher_env(n_local_devices: int) -> dict:
    sys.path.insert(0, _REPO)
    from __graft_entry__ import scrubbed_cpu_env

    env = scrubbed_cpu_env(n_local_devices)
    env["RA_TEST_REEXEC"] = "1"
    return env


@pytest.fixture(scope="module")
def corpus(tmp_path_factory):
    td = tmp_path_factory.mktemp("elastic")
    cfg_text = synth.synth_config(
        n_acls=3, rules_per_acl=8, seed=41, egress_acls=True
    )
    rs = aclparse.parse_asa_config(cfg_text, "fw1")
    packed = pack.pack_rulesets([rs])
    tuples = synth.synth_tuples(packed, 1600, seed=42)
    lines = synth.render_syslog(packed, tuples, seed=43, variety=0.4)
    prefix = str(td / "packed")
    pack.save_packed(packed, prefix)
    shards = []
    for i in range(4):
        p = td / f"shard{i}.log"
        p.write_text(
            "".join(ln + "\n" for ln in lines[i * 400 : (i + 1) * 400]),
            encoding="utf-8",
        )
        shards.append(str(p))
    return td, prefix, shards


def _spawn_launchers(td, prefix, shards, *, fault=None, max_reforms=2,
                     timeout=400):
    env = _launcher_env(2)
    if fault:
        env["RA_ELASTIC_FAULT"] = fault
    eldir = str(td / "eldir")
    procs = [
        subprocess.Popen(
            [sys.executable, "-m", "ruleset_analysis_tpu.cli", "run",
             "--ruleset", prefix, "--logs", *shards, "--backend", "tpu",
             "--distributed", "--elastic", "--elastic-dir", eldir,
             "--num-processes", "4", "--process-id", str(pid),
             "--batch-size", "64", "--checkpoint-every", "2",
             "--max-reforms", str(max_reforms),
             "--json", "--out", str(td / f"rep{pid}.json")]
            ,
            env=env, cwd=_REPO,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        )
        for pid in range(4)
    ]
    outs = []
    for p in procs:
        try:
            out, err = p.communicate(timeout=timeout)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise AssertionError("elastic launcher HUNG (no bounded-time exit)")
        outs.append((p.returncode, out, err))
    return outs


def _reference_report(prefix, shards):
    from ruleset_analysis_tpu.config import AnalysisConfig
    from ruleset_analysis_tpu.runtime.stream import run_stream_file

    packed = pack.load_packed(prefix)
    rep = run_stream_file(packed, shards, AnalysisConfig(batch_size=64))
    return json.loads(rep.to_json())


def test_kill_one_of_four_auto_reforms_bit_identical(corpus):
    """Kill tag 2 mid-run: survivors re-form at world 3, resume from the
    epoch checkpoint, and the unused-rule report is bit-identical to an
    uninterrupted run — no manual --resume anywhere."""
    td, prefix, shards = corpus
    outs = _spawn_launchers(td, prefix, shards, fault="tag=2,after_batches=4")

    from ruleset_analysis_tpu.runtime.elastic import DIE_RC

    assert outs[2][0] == DIE_RC, (
        f"victim exited rc={outs[2][0]}\nstderr:\n{outs[2][2][-2000:]}"
    )
    for pid in (0, 1, 3):
        rc, _out, err = outs[pid]
        assert rc == 0, f"survivor {pid} failed rc={rc}\nstderr:\n{err[-3000:]}"

    # the re-elected rank 0 (lowest surviving tag) wrote the report
    rep = json.loads((td / "rep0.json").read_text(encoding="utf-8"))
    t = rep["totals"]
    assert t["processes"] == 3  # re-formed at the surviving world size
    assert t["elastic_epoch"] >= 1  # at least one re-formation happened
    # recovery events + time-to-recover surfaced in the report totals
    rec = t["recovery"]
    assert rec["reforms_used"] >= 1
    assert rec["recovery_events"] >= 1
    assert all(e["time_to_recover_sec"] >= 0 for e in rec["recoveries"])

    ref = _reference_report(prefix, shards)
    hits = lambda r: {  # noqa: E731
        (e["firewall"], e["acl"], e["index"]): e["hits"] for e in r["per_rule"]
    }
    assert hits(rep) == hits(ref)
    assert rep["unused"] == ref["unused"]
    assert t["lines_total"] == ref["totals"]["lines_total"] == 1600
    assert t["lines_matched"] == ref["totals"]["lines_matched"]
    assert t["lines_skipped"] == ref["totals"]["lines_skipped"]


def test_max_reforms_exhausted_aborts_cleanly(corpus, tmp_path_factory):
    """--max-reforms 0 + an injected death: every survivor must abort with
    the clean budget-exhausted error in bounded time — no hangs."""
    td = tmp_path_factory.mktemp("elastic_budget")
    _td, prefix, shards = corpus
    outs = _spawn_launchers(
        td, prefix, shards, fault="tag=1,after_batches=4", max_reforms=0,
        timeout=300,
    )
    from ruleset_analysis_tpu.errors import EXIT_REFORM_BUDGET
    from ruleset_analysis_tpu.runtime.elastic import DIE_RC

    # normally the injected death (77); if an unrelated generation failure
    # raced ahead, the victim aborts on the exhausted budget instead —
    # either way it exited, cleanly and bounded, with the documented
    # failure-class exit code (7 = reform budget exhausted)
    assert outs[1][0] in (DIE_RC, EXIT_REFORM_BUDGET), outs[1][2][-1500:]
    for pid in (0, 2, 3):
        rc, _out, err = outs[pid]
        assert rc == EXIT_REFORM_BUDGET, (
            f"launcher {pid} rc={rc} (want {EXIT_REFORM_BUDGET})"
        )
        assert "budget exhausted" in err, err[-1500:]
    # no report: the run never completed
    assert not (td / "rep0.json").exists()
