"""Typed retry/backoff engine (ISSUE 14 / DESIGN §19).

Three layers:

- **Units**: policy-spec grammar, seeded-deterministic backoff (same
  seed -> same jitter sequence ACROSS PROCESSES — the property test
  spawns an interpreter), transient-vs-permanent classification, budget
  accounting.
- **Transient chaos schedules**: literal ``site@N:k`` plans (k below
  the attempt bound) over the batch drivers — the STRENGTHENED
  invariant: the run must NOT abort, the report must be bit-identical
  to the fault-free baseline, the retry counters must record the
  recovery, and drop accounting must be untouched (zero unaccounted
  drops — the totals are part of the compared image).
- **Escalation schedules**: literal ``site@N:99`` plans (past every
  attempt bound) proving an exhausted budget escalates to the EXISTING
  typed aborts — no hang, no leak, no new failure class.  The registry
  auditor (verify/registry.py::audit_retry) greps this file for both
  schedule shapes per registered retry site.
"""

import json
import os
import socket
import subprocess
import sys
import time

import pytest

pytest.importorskip("jax")

from ruleset_analysis_tpu import errors
from ruleset_analysis_tpu.config import AnalysisConfig, SketchConfig
from ruleset_analysis_tpu.errors import (
    AnalysisError,
    CheckpointCorrupt,
    InjectedFault,
    is_transient,
)
from ruleset_analysis_tpu.hostside import aclparse, pack, wire as wire_mod
from ruleset_analysis_tpu.hostside.listener import LineQueue, UdpSyslogListener
from ruleset_analysis_tpu.runtime import faults, obs, retrypolicy
from ruleset_analysis_tpu.runtime.report import VOLATILE_TOTALS as VOLATILE
from ruleset_analysis_tpu.runtime.stream import run_stream_file, run_stream_wire

# same ruleset text + batch geometry as the chaos harness so every
# specialized step program here rides the jit cache the earlier suites
# already paid for (suite-budget discipline, tests/conftest.py)
CFG6 = """\
hostname fw1
access-list A extended permit tcp any host 10.0.0.5 eq 443
access-list A extended permit tcp any6 2001:db8:1::/48 eq 443
access-list A extended permit udp 2001:db8:2::/64 any6 eq 53
access-list A extended deny tcp any6 host 2001:db8::bad
access-list A extended permit ip any any
access-list B extended permit tcp any6 any6 range 8000 8100
access-group A in interface outside
"""


def report_image(rep) -> dict:
    j = rep if isinstance(rep, dict) else json.loads(rep.to_json())
    j = json.loads(json.dumps(j))
    for k in VOLATILE:
        j["totals"].pop(k, None)
    return j


def _mixed_lines(n, seed=0):
    import random

    rng = random.Random(seed)
    out = []
    for i in range(n):
        acl = "A" if rng.random() < 0.8 else "B"
        if rng.random() < 0.3:
            src = f"2001:db8:2::{rng.randrange(1, 40):x}"
            dst = f"2001:db8:1:1::{rng.randrange(1, 99):x}"
            proto = rng.choice(["tcp", "udp"])
        else:
            src = f"10.1.{rng.randrange(256)}.{rng.randrange(1, 255)}"
            dst = "10.0.0.5" if rng.random() < 0.5 else "10.9.9.9"
            proto = "tcp"
        out.append(
            f"Jul 29 07:48:{i % 60:02d} fw1 : %ASA-6-106100: access-list {acl} "
            f"permitted {proto} inside/{src}({rng.randrange(1024, 60000)}) -> "
            f"outside/{dst}({rng.choice([443, 53, 8050])}) "
            f"hit-cnt 1 first hit [0x0, 0x0]"
        )
    return out


@pytest.fixture(scope="module")
def corpus(tmp_path_factory):
    td = tmp_path_factory.mktemp("retry")
    rs = aclparse.parse_asa_config(CFG6, "fw1")
    packed = pack.pack_rulesets([rs])
    text = str(td / "mix.log")
    with open(text, "w", encoding="utf-8") as f:
        f.write("\n".join(_mixed_lines(1500, seed=21)) + "\n")
    wirep = str(td / "mix.rawire")
    wire_mod.convert_logs(packed, [text], wirep, batch_size=512, block_rows=512)
    return packed, text, wirep


def _cfg(depth, cadence, ckpt_dir, resume=False):
    return AnalysisConfig(
        batch_size=512,
        sketch=SketchConfig(cms_width=1 << 10, cms_depth=2, hll_p=6),
        prefetch_depth=depth,
        checkpoint_every_chunks=cadence,
        checkpoint_dir=ckpt_dir,
        resume=resume,
        stall_timeout_sec=3.0,
    )


@pytest.fixture(scope="module")
def baselines(corpus, tmp_path_factory):
    cache: dict = {}
    td = tmp_path_factory.mktemp("retry_base")

    def get(inp, depth, cadence):
        key = (inp, depth, cadence)
        if key not in cache:
            packed, text, wirep = corpus
            cfg = _cfg(depth, cadence, str(td / f"ck-{inp}-{depth}-{cadence}"))
            rep = (
                run_stream_wire(packed, wirep, cfg, topk=5)
                if inp == "wire"
                else run_stream_file(packed, text, cfg, topk=5)
            )
            cache[key] = report_image(rep)
        return cache[key]

    return get


# ---------------------------------------------------------------------------
# Units: grammar, classification, deterministic backoff, budgets.
# ---------------------------------------------------------------------------


def test_policy_spec_grammar():
    ov, seed = retrypolicy.parse_spec("device_put=7/0.5,seed=9")
    assert ov["device_put"].attempts == 7
    assert ov["device_put"].base_sec == 0.5
    assert seed == 9
    ov, _ = retrypolicy.parse_spec("checkpoint.save=3")
    assert ov["checkpoint.save"].attempts == 3
    assert (
        ov["checkpoint.save"].base_sec
        == retrypolicy.DEFAULT_POLICIES["checkpoint.save"].base_sec
    )
    off, _ = retrypolicy.parse_spec("off")
    assert all(p.attempts == 1 for p in off.values())
    assert set(off) == set(retrypolicy.RETRY_SITES)
    for bad in ("nosuch=3", "device_put", "device_put=x", "seed=x"):
        with pytest.raises(AnalysisError):
            retrypolicy.parse_spec(bad)


def test_every_site_has_policy_and_fault_mapping():
    assert set(retrypolicy.DEFAULT_POLICIES) == set(retrypolicy.RETRY_SITES)
    for site, meta in retrypolicy.RETRY_SITES.items():
        assert meta.fault_site in faults.SITES, site


def test_transient_classification_table():
    assert is_transient(InjectedFault("x"))
    assert is_transient(ConnectionResetError("x"))
    assert is_transient(TimeoutError("x"))
    assert is_transient(OSError(errno_of("EADDRINUSE"), "in use"))
    assert is_transient(RuntimeError("RESOURCE_EXHAUSTED: oom"))
    # permanent: typed refusals, missing files, program bugs
    assert not is_transient(CheckpointCorrupt("x"))
    assert not is_transient(AnalysisError("x"))
    assert not is_transient(FileNotFoundError("x"))
    assert not is_transient(PermissionError("x"))
    assert not is_transient(ValueError("x"))
    assert not is_transient(RuntimeError("shape mismatch"))


def errno_of(name):
    import errno

    return getattr(errno, name)


def test_backoff_deterministic_same_seed_and_shape():
    retrypolicy.configure("")
    a = retrypolicy.backoff_schedule("device_put", 8, seed=7)
    b = retrypolicy.backoff_schedule("device_put", 8, seed=7)
    assert a == b
    assert retrypolicy.backoff_schedule("device_put", 8, seed=8) != a
    # exponential shape under the cap, jitter within +/-50%
    pol = retrypolicy.DEFAULT_POLICIES["device_put"]
    for i, d in enumerate(a):
        raw = min(pol.cap_sec, pol.base_sec * pol.mult**i)
        assert 0.5 * raw <= d < 1.5 * raw


def test_backoff_deterministic_across_processes():
    """Same seed -> same jitter sequence in a FRESH interpreter (no
    PYTHONHASHSEED dependence — the acceptance property)."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    sys.path.insert(0, repo)
    from __graft_entry__ import scrubbed_cpu_env

    env = scrubbed_cpu_env(1)
    env["PYTHONHASHSEED"] = "random"
    out = subprocess.run(
        [sys.executable, "-c",
         "import json\n"
         "from ruleset_analysis_tpu.runtime import retrypolicy\n"
         "print(json.dumps(retrypolicy.backoff_schedule("
         "'checkpoint.save', 6, seed=42)))"],
        env=env, cwd=repo, capture_output=True, text=True, timeout=120,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    theirs = json.loads(out.stdout.strip())
    assert theirs == retrypolicy.backoff_schedule("checkpoint.save", 6, seed=42)


def test_call_budget_and_permanent_escalation():
    retrypolicy.configure("device_put=3/0.001")
    # permanent errors escalate on attempt 1, no sleeps
    with pytest.raises(CheckpointCorrupt):
        retrypolicy.call("device_put", lambda: (_ for _ in ()).throw(
            CheckpointCorrupt("no")
        ))
    c = retrypolicy.counters()["device_put"]
    assert c == {"attempts": 0, "recoveries": 0, "giveups": 1}
    # transient exhaust: attempts-1 retries then the ORIGINAL error
    n = {"v": 0}

    def always():
        n["v"] += 1
        raise InjectedFault("t")

    with pytest.raises(InjectedFault):
        retrypolicy.call("device_put", always)
    assert n["v"] == 3
    c = retrypolicy.counters()["device_put"]
    assert c["attempts"] == 2 and c["giveups"] == 2
    g = retrypolicy.gauges()
    assert g["retry_attempts_total"] == 2
    assert g["retry_device_put_giveups"] == 2
    retrypolicy.configure("")  # restore defaults for later suites


def test_off_spec_disables_retries():
    retrypolicy.configure("off")
    n = {"v": 0}

    def once():
        n["v"] += 1
        raise InjectedFault("t")

    with pytest.raises(InjectedFault):
        retrypolicy.call("wire.read", once)
    assert n["v"] == 1
    retrypolicy.configure("")


# ---------------------------------------------------------------------------
# Transient chaos schedules (the strengthened invariant): LITERAL
# ``site@N:k`` plans with single-digit k — the retry engine must RECOVER,
# the report must be bit-identical to the fault-free baseline, and drop
# accounting must be untouched.  12 seeded schedules across the batch
# drivers; the serve-side transients live in test_serve/test_chaos.
# ---------------------------------------------------------------------------

TRANSIENT_SCHEDULES = [
    # (plan, input, prefetch depth, checkpoint cadence)
    ("stream.device_put.fail@1:2,seed=301", "text", 0, 0),
    ("stream.device_put.fail@2:3,seed=302", "text", 2, 0),
    ("stream.device_put.fail@1:4,seed=303", "wire", 0, 0),
    ("stream.device_put.fail@3:2,seed=304", "wire", 2, 0),
    ("stream.device_put.fail@2:2,seed=305", "text", 0, 2),
    ("checkpoint.torn_state@1:2,seed=306", "text", 0, 2),
    ("checkpoint.torn_state@2:3,seed=307", "wire", 0, 2),
    ("checkpoint.torn_state@1:1,seed=308", "wire", 2, 2),
    ("checkpoint.torn_manifest@1:2,seed=309", "text", 0, 2),
    ("checkpoint.torn_manifest@2:2,seed=310", "wire", 2, 2),
    ("stream.wire.read.fail@1:2,seed=311", "wire", 0, 0),
    ("stream.wire.read.fail@1:3,seed=312", "wire", 2, 2),
]


@pytest.mark.parametrize("plan,inp,depth,cadence", TRANSIENT_SCHEDULES)
def test_transient_schedule_recovers_bit_identical(
    corpus, baselines, tmp_path, plan, inp, depth, cadence
):
    packed, text, wirep = corpus
    base = baselines(inp, depth, cadence)
    cfg = _cfg(depth, cadence, str(tmp_path / "ck"))
    site = plan.split("@")[0]
    retry_site = next(
        s for s, m in retrypolicy.RETRY_SITES.items() if m.fault_site == site
    ) if site != "checkpoint.torn_manifest" else "checkpoint.save"
    with faults.armed(faults.FaultPlan.parse(plan)):
        rep = (
            run_stream_wire(packed, wirep, cfg, topk=5)
            if inp == "wire"
            else run_stream_file(packed, text, cfg, topk=5)
        )  # must NOT raise: the whole point of the survival plane
    # bit-identical INCLUDING line/skip totals: zero unaccounted drops
    assert report_image(rep) == base, f"{plan} diverged after recovery"
    c = retrypolicy.counters().get(retry_site, {})
    assert c.get("recoveries", 0) >= 1, (plan, retrypolicy.counters())
    assert c.get("giveups", 0) == 0, (plan, c)


def test_transient_schedules_meet_acceptance_floor():
    assert len(TRANSIENT_SCHEDULES) >= 12


# ---------------------------------------------------------------------------
# Budget exhaustion per retryable site: LITERAL ``@N:99`` plans (k far
# past every attempt bound) — escalation must stay TYPED, bounded in
# time, and leak-free (the conftest leak audit covers the latter).
# ---------------------------------------------------------------------------


def test_exhaustion_device_put_escalates_typed(corpus, baselines, tmp_path):
    packed, text, _ = corpus
    cfg = _cfg(0, 0, str(tmp_path / "ck"))
    t0 = time.monotonic()
    with faults.armed(faults.FaultPlan.parse("stream.device_put.fail@1:99")):
        with pytest.raises(InjectedFault):
            run_stream_file(packed, text, cfg, topk=5)
    assert time.monotonic() - t0 < 30
    assert retrypolicy.counters()["device_put"]["giveups"] >= 1
    # the process is healthy afterwards: a disarmed run matches baseline
    rep = run_stream_file(packed, text, _cfg(0, 0, str(tmp_path / "ck2")), topk=5)
    assert report_image(rep) == baselines("text", 0, 0)


def test_exhaustion_checkpoint_save_escalates_typed(corpus, tmp_path):
    packed, text, _ = corpus
    cfg = _cfg(0, 2, str(tmp_path / "ck"))
    with faults.armed(faults.FaultPlan.parse("checkpoint.torn_manifest@1:99")):
        with pytest.raises(InjectedFault):
            run_stream_file(packed, text, cfg, topk=5)
    # no litter from the retried attempts
    leftovers = [
        e for e in os.listdir(tmp_path / "ck") if e.startswith(".tmp-")
    ]
    assert not leftovers, leftovers


def test_exhaustion_wire_read_escalates_typed(corpus, tmp_path):
    packed, _, wirep = corpus
    cfg = _cfg(0, 0, str(tmp_path / "ck"))
    with faults.armed(faults.FaultPlan.parse("stream.wire.read.fail@1:99")):
        with pytest.raises(InjectedFault):
            run_stream_wire(packed, wirep, cfg, topk=5)
    assert retrypolicy.counters()["wire.read"]["giveups"] >= 1


def test_listener_bind_transient_recovers_and_exhaustion_typed():
    retrypolicy.configure("listener.bind=4/0.01")
    try:
        # transient: two consecutive bind failures, then the bind lands
        with faults.armed(faults.FaultPlan.parse("listener.bind.fail@1:2")):
            q = LineQueue(64)
            ln = UdpSyslogListener(q, "127.0.0.1", 0)
            ln.start()
            try:
                s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
                s.sendto(b"hello\n", ln.address)
                s.close()
                deadline = time.monotonic() + 10
                while time.monotonic() < deadline and not len(q):
                    time.sleep(0.02)
                assert q.pop(0.1) == "hello"
            finally:
                ln.close()
        assert retrypolicy.counters()["listener.bind"]["recoveries"] >= 1
        # exhaustion: the constructor escalates the typed error —
        # exactly the CLI's documented clean bind failure path
        with faults.armed(faults.FaultPlan.parse("listener.bind.fail@1:99")):
            with pytest.raises(InjectedFault):
                UdpSyslogListener(LineQueue(64), "127.0.0.1", 0)
    finally:
        retrypolicy.configure("")


def test_listener_accept_transient_recovers_and_exhaustion_dead():
    retrypolicy.configure("listener.accept=4/0.01")
    try:
        # transient: the receive loop faults twice mid-iteration; the
        # retry re-enters it and traffic still flows — with the line in
        # flight at each fault surfacing as a COUNTED drop, never a gap
        with faults.armed(faults.FaultPlan.parse("listener.accept.fail@3:2")):
            q = LineQueue(64)
            ln = UdpSyslogListener(q, "127.0.0.1", 0)
            ln.start()
            try:
                s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
                for i in range(10):
                    s.sendto(f"m{i}\n".encode(), ln.address)
                    time.sleep(0.02)
                s.close()
                deadline = time.monotonic() + 15
                while time.monotonic() < deadline:
                    snap = q.snapshot()
                    if snap["received"] >= 10:
                        break
                    time.sleep(0.05)
                snap = q.snapshot()
                assert snap["received"] + snap["dropped"] >= 9
                assert ln.is_alive() and not ln.dead
            finally:
                ln.close()
        assert retrypolicy.counters()["listener.accept"]["recoveries"] >= 1
        # exhaustion: the listener dies with the error RECORDED (the
        # serve loop's existing dead-listener escalation takes over)
        with faults.armed(faults.FaultPlan.parse("listener.accept.fail@1:99")):
            q = LineQueue(64)
            ln = UdpSyslogListener(q, "127.0.0.1", 0)
            ln.start()
            try:
                deadline = time.monotonic() + 15
                while time.monotonic() < deadline and not ln.dead:
                    time.sleep(0.05)
                assert ln.dead
                assert isinstance(ln.error, InjectedFault)
            finally:
                ln.close()
    finally:
        retrypolicy.configure("")


def test_metrics_snapshot_failures_counted_thread_survives(tmp_path):
    """metrics.snapshot.fail: tick errors are counted, the ra-metrics
    thread never dies, and a clean tick resets consec_errors — the
    signal serve's degraded plane keys on."""
    mf = str(tmp_path / "m.jsonl")
    with faults.armed(faults.FaultPlan.parse("metrics.snapshot.fail@1:2")):
        obs.start_metrics(mf, every_sec=0.05)
        try:
            deadline = time.monotonic() + 15
            h = None
            while time.monotonic() < deadline:
                h = obs.metrics_health()
                if h["errors"] >= 2 and h["consec_errors"] == 0:
                    break
                time.sleep(0.05)
            assert h is not None and h["alive"], h
            assert h["errors"] >= 2 and h["consec_errors"] == 0, h
        finally:
            obs.shutdown(merge=False)
    # snapshots resumed after the burst: the file holds real records
    with open(mf, encoding="utf-8") as f:
        recs = [json.loads(ln) for ln in f if ln.strip()]
    assert any(r.get("kind") == "snapshot" for r in recs)


# serve.publish schedules (transient @1:2 recovery and @1:99 degradation)
# live in tests/test_serve.py::test_publisher_degrades_and_recovers —
# they need the full driver; the audit greps the whole tests tree.
