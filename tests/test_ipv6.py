"""IPv6 data model (DESIGN.md "IPv6 position" — now BUILT, not skipped).

128-bit addresses as 4x uint32 limbs in a separate rule/tuple family
(pack.rules6 / TUPLE6 layout); family split preserves first-match order
because cross-family matches are impossible.  ``any`` resolves per
ruleset: v4-only for pure-v4 configs (bit-identical historical
expansion), both families when the config carries explicit v6 content
(ASA 9.0+ unified-ACL semantics).
"""

import numpy as np
import pytest

from ruleset_analysis_tpu.errors import AnalysisError
from ruleset_analysis_tpu.hostside import aclparse, oracle, pack, syslog

CFG_MIXED = """\
hostname fw1
access-list A extended permit tcp any host 10.0.0.5 eq 443
access-list A extended permit tcp any6 host 2001:db8::5 eq 443
access-list A extended permit ip host 2001:db8::7 any
access-list A extended deny ip any any
access-group A in interface outside
"""

V4_LINE = (
    "Jul 29 07:48:01 fw1 : %ASA-6-106100: access-list A permitted tcp "
    "inside/1.2.3.4(1000) -> outside/10.0.0.5(443) hit-cnt 1 first hit [0x0, 0x0]"
)
V6_LINE = (
    "Jul 29 07:48:02 fw1 : %ASA-6-106100: access-list A permitted tcp "
    "inside/2001:db8::9(1000) -> outside/2001:db8::5(443) hit-cnt 1 "
    "first hit [0x0, 0x0]"
)


def test_mixed_config_parses_both_families():
    rs = aclparse.parse_asa_config(CFG_MIXED, "fw1", strict=True)
    assert rs.skipped == []
    rules = rs.acls["A"]
    assert [r.index for r in rules] == [1, 2, 3, 4]
    fams = [sorted({a.family for a in r.aces}) for r in rules]
    # rule 1 v4; rules 2-3 v6; rule 4 (any/any in a v6-bearing config)
    # covers both families
    assert fams == [[4], [6], [6], [4, 6]]


def test_pure_v4_config_expansion_is_unchanged():
    """The wildcard gate: without explicit v6 content, ``any`` stays v4."""
    rs = aclparse.parse_asa_config(
        "access-list B extended permit ip any any\n", "fw", strict=True
    )
    aces = rs.acls["B"][0].aces
    assert [a.family for a in aces] == [4]
    assert aces[0].src_hi == aclparse.U32_MAX
    packed = pack.pack_rulesets([rs])
    assert not packed.has_v6 and packed.rules6.shape == (0, pack.RULE6_COLS)


def test_cross_family_only_rule_rejected():
    with pytest.raises(aclparse.AclParseError, match="same-family"):
        aclparse.parse_asa_config(
            "access-list D extended permit ip any4 host 2001:db8::1\n",
            "fw", strict=True,
        )


def test_pack_splits_families_and_shares_keys():
    rs = aclparse.parse_asa_config(CFG_MIXED, "fw1")
    packed = pack.pack_rulesets([rs])
    assert packed.has_v6
    # v4 tensor: rule 1 + rule 4's v4 ace; v6 tensor: rules 2, 3 + rule 4's twin
    assert packed.rules.shape[0] == 2 and packed.rules6.shape[0] == 3
    v4_keys = sorted(int(k) for k in packed.rules[:, pack.R_KEY])
    v6_keys = sorted(int(k) for k in packed.rules6[:, pack.R6_KEY])
    assert v4_keys == [0, 3] and v6_keys == [1, 2, 3]


def test_limb_roundtrip():
    v = aclparse.ip6_to_int("2001:db8:dead:beef::1234:5678")
    assert pack.limbs_u128(*pack.u128_limbs(v)) == v


def test_save_load_roundtrip_with_rules6(tmp_path):
    rs = aclparse.parse_asa_config(CFG_MIXED, "fw1")
    packed = pack.pack_rulesets([rs])
    pack.save_packed(packed, str(tmp_path / "p"))
    p2 = pack.load_packed(str(tmp_path / "p"))
    np.testing.assert_array_equal(p2.rules6, packed.rules6)
    assert p2.has_v6


def test_load_rejects_inverted_v6_address_range(tmp_path):
    rs = aclparse.parse_asa_config(CFG_MIXED, "fw1")
    packed = pack.pack_rulesets([rs])
    # invert a src bound pair in the LOW limb only: the lexicographic
    # validator must still catch it
    packed.rules6[0, pack.R6_SLO:pack.R6_SLO + 4] = (0, 0, 0, 5)
    packed.rules6[0, pack.R6_SHI:pack.R6_SHI + 4] = (0, 0, 0, 4)
    pack.save_packed(packed, str(tmp_path / "bad"))
    with pytest.raises(AnalysisError, match="inverted src address range"):
        pack.load_packed(str(tmp_path / "bad"))


def test_v6_syslog_line_parses():
    p = syslog.parse_line(V6_LINE)
    assert p is not None and p.family == 6
    assert p.src == aclparse.ip6_to_int("2001:db8::9")
    assert p.dst == aclparse.ip6_to_int("2001:db8::5")
    assert (p.sport, p.dport) == (1000, 443)


def test_mixed_family_syslog_line_skipped():
    line = V6_LINE.replace("outside/2001:db8::5(443)", "outside/10.0.0.5(443)")
    assert syslog.parse_line(line) is None


def test_packer_routes_families():
    rs = aclparse.parse_asa_config(CFG_MIXED, "fw1")
    packed = pack.pack_rulesets([rs])
    lp = pack.LinePacker(packed)
    b4, b6 = lp.pack_lines2([V4_LINE, V6_LINE, V4_LINE], batch_size=4)
    assert lp.parsed == 3 and lp.skipped == 0
    assert int(b4[:, pack.T_VALID].sum()) == 2
    assert int(b6[:, pack.T6_VALID].sum()) == 1
    row = b6[0]
    assert pack.limbs_u128(*row[pack.T6_SRC:pack.T6_SRC + 4]) == aclparse.ip6_to_int(
        "2001:db8::9"
    )


def test_v6_line_against_pure_v4_ruleset_is_counted_skip():
    rs = aclparse.parse_asa_config(
        "access-list A extended permit ip any any\naccess-group A in interface i0\n",
        "fw1",
    )
    packed = pack.pack_rulesets([rs])
    lp = pack.LinePacker(packed)
    b4, b6 = lp.pack_lines2([V6_LINE], batch_size=2)
    assert lp.skipped == 1 and b6.shape[0] == 0
    assert int(b4[:, pack.T_VALID].sum()) == 0


def test_v4_only_pack_parsed_raises_on_v6_rows():
    rs = aclparse.parse_asa_config(CFG_MIXED, "fw1")
    packed = pack.pack_rulesets([rs])
    lp = pack.LinePacker(packed)
    with pytest.raises(AnalysisError, match="IPv6"):
        lp.pack_lines([V6_LINE], batch_size=2)


def test_oracle_family_guard():
    rs = aclparse.parse_asa_config(CFG_MIXED, "fw1")
    orc = oracle.Oracle([rs])
    p4 = syslog.parse_line(V4_LINE)
    p6 = syslog.parse_line(V6_LINE)
    assert orc.match_keys(p4) == [("fw1", "A", 1)]
    assert orc.match_keys(p6) == [("fw1", "A", 2)]
    # v6 packet matching no v6 ACE falls through to rule 4's v6 twin
    p6b = syslog.parse_line(
        V6_LINE.replace("tcp", "udp").replace("(443)", "(53)").replace("(1000)", "(53)")
    )
    assert orc.match_keys(p6b) == [("fw1", "A", 4)]


def test_icmp6_named_types_resolve_per_family():
    """ICMPv6 named types use RFC 4443 numbers, not their v4 namesakes."""
    cfg = (
        "access-list I extended permit icmp6 any6 any6 echo-reply\n"
        "access-list I4 extended permit icmp any any echo-reply\n"
    )
    rs = aclparse.parse_asa_config(cfg, "fw1", strict=True)
    (a6,) = rs.acls["I"][0].aces
    assert (a6.dport_lo, a6.dport_hi) == (129, 129)  # v6 echo-reply
    # I4's any/any wildcard expands to both families in this v6-bearing
    # ruleset; check its v4-family ace
    a4 = next(a for a in rs.acls["I4"][0].aces if a.family == 4)
    assert (a4.dport_lo, a4.dport_hi) == (0, 0)  # v4 echo-reply
    # and the matching line (type rides dport) hits rule 1
    p = syslog.parse_line(
        "J 1 0 fw1 : %ASA-6-106100: access-list I permitted icmp6 "
        "i/2001:db8::9(129) -> o/2001:db8::5(0) hit-cnt 1"
    )
    assert p is not None and p.dport == 129
    orc = oracle.Oracle([rs])
    assert orc.match_keys(p) == [("fw1", "I", 1)]


def test_icmp6_type_object_groups_resolve_per_family():
    """icmp-type object-group members referenced from an icmp6 ACE must
    resolve through the v6 table (review finding: they used the v4 one)."""
    cfg = (
        "object-group icmp-type G\n"
        " icmp-object echo-reply\n"
        " icmp-object packet-too-big\n"
        "access-list I extended permit icmp6 any6 any6 object-group G\n"
    )
    rs = aclparse.parse_asa_config(cfg, "fw1", strict=True)
    types = sorted((a.dport_lo, a.dport_hi) for a in rs.acls["I"][0].aces)
    assert types == [(2, 2), (129, 129)]  # packet-too-big, v6 echo-reply
    # the same group from a v4 icmp ACE keeps v4 numbers (and rejects
    # the v6-only name)
    cfg4 = (
        "object-group icmp-type G4\n"
        " icmp-object echo-reply\n"
        "access-list I4 extended permit icmp any any object-group G4\n"
    )
    rs4 = aclparse.parse_asa_config(cfg4, "fw1", strict=True)
    (a4,) = rs4.acls["I4"][0].aces
    assert (a4.dport_lo, a4.dport_hi) == (0, 0)
