"""IPv6 position (DESIGN.md; SURVEY.md §8.0 tags v6 "later").

The packed model is v4-only: IPv6 ACEs are counted-skipped in lenient
mode (preserving later rules' device-side indices), rejected loudly in
strict mode, and IPv6 syslog lines are parse-skipped — NEVER mis-parsed
into uint32 columns."""

import pytest

from ruleset_analysis_tpu.hostside import aclparse, pack, syslog

CFG_MIXED = """\
hostname fw1
access-list A extended permit tcp any host 10.0.0.5 eq 443
access-list A extended permit tcp any6 host 2001:db8::5 eq 443
access-list A extended permit ip host 2001:db8::7 any
access-list A extended deny ip any any
access-group A in interface outside
"""


def test_lenient_counts_ipv6_aces_and_preserves_indices():
    rs = aclparse.parse_asa_config(CFG_MIXED, "fw1", strict=False)
    # both v6 ACEs are recorded with an explicit IPv6 reason
    assert len(rs.skipped) == 2
    for _lineno, reason, _line in rs.skipped:
        assert "IPv6" in reason
    # surviving rules keep their config positions: 1 and 4
    assert [r.index for r in rs.acls["A"]] == [1, 4]
    # and the pack carries the skip accounting forward
    packed = pack.pack_rulesets([rs])
    assert len(packed.parse_skips) == 2
    assert all("IPv6" in reason for _fw, _lineno, reason in packed.parse_skips)


def test_strict_rejects_ipv6_loudly():
    with pytest.raises(aclparse.AclParseError, match="IPv6"):
        aclparse.parse_asa_config(CFG_MIXED, "fw1", strict=True)


def test_ipv6_syslog_line_is_skipped_not_misparsed():
    line = (
        "Jul 29 07:48:01 fw1 : %ASA-6-106100: access-list A permitted tcp "
        "inside/2001:db8::9(1000) -> outside/10.0.0.5(443) hit-cnt 1 "
        "first hit [0x0, 0x0]"
    )
    assert syslog.parse_line(line) is None


def test_ipv6_syslog_lines_land_in_lines_skipped():
    rs = aclparse.parse_asa_config(CFG_MIXED, "fw1", strict=False)
    packed = pack.pack_rulesets([rs])
    lp = pack.LinePacker(packed)
    v4 = (
        "Jul 29 07:48:01 fw1 : %ASA-6-106100: access-list A permitted tcp "
        "inside/1.2.3.4(1000) -> outside/10.0.0.5(443) hit-cnt 1 first hit [0x0, 0x0]"
    )
    v6 = (
        "Jul 29 07:48:02 fw1 : %ASA-6-106100: access-list A permitted tcp "
        "inside/2001:db8::9(1000) -> outside/10.0.0.5(443) hit-cnt 1 first hit [0x0, 0x0]"
    )
    batch = lp.pack_lines([v4, v6, v4], batch_size=4)
    assert lp.parsed == 2 and lp.skipped == 1
    # the skipped line contributed no valid evaluation row
    assert int(batch[:, pack.T_VALID].sum()) == 2
