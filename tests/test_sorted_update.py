"""Sorted segment-reduce register updates (ISSUE 9, DESIGN §15).

Assertion tiers:

- **op-level value identity** — the sorted formulations
  (ops/sorted_update.py) produce byte-equal register arrays to the
  scatter formulations on adversarial inputs: out-of-range keys (the
  ``mode="drop"`` contract), zero and >1 weights (the coalesce plane),
  slot collisions, plus the composite-overflow fallback;
- **driver bit-identity matrix** — ``update_impl=sorted`` reports are
  bit-identical to ``scatter`` across flat/stacked x text/wire x
  v4/v6 x sync/prefetch x weighted/unweighted, including crash-at-K
  resume ACROSS impls (the checkpoint fingerprint deliberately excludes
  update_impl) and seeded chaos schedules;
- **deferred selection** — ``topk_every > 1`` defers candidate
  selection identically in both impls (cross-impl identity at the same
  cadence), registers/hits/unused are cadence-invariant, and the
  cadence folds into the checkpoint fingerprint only when non-default;
- **typed refusals + weight safety** — sorted x pallas_fused is a
  config-time refusal (CLI exit 2), while weighted (RAWIREv3) inputs
  are ACCEPTED under sorted everywhere (weight-linear by construction);
- **attribution** — the sorts trace under the ``ra.sort`` named scope
  and the taxonomy knows the stage.

The corpus deliberately reuses test_obs/test_devprof's ruleset + sketch
geometry (synth seed 7, 3 ACLs x 8 rules, batch 512, cms 1<<10 x 2,
hll_p 6): the SCATTER-side specialized step jit is keyed on the ruleset
value, so the baseline runs here share one XLA compile with those
suites in a tier-1 process — only the sorted-side programs compile
fresh (the 870 s gate is a hard budget, ROADMAP).
"""

import json
import os

import pytest

pytest.importorskip("jax")

import jax
import jax.numpy as jnp
import numpy as np

from ruleset_analysis_tpu.config import AnalysisConfig, SketchConfig
from ruleset_analysis_tpu.errors import InjectedFault
from ruleset_analysis_tpu.hostside import aclparse, pack, synth
from ruleset_analysis_tpu.hostside import wire as wire_mod
from ruleset_analysis_tpu.ops import cms as cms_ops
from ruleset_analysis_tpu.ops import counts as count_ops
from ruleset_analysis_tpu.ops import hll as hll_ops
from ruleset_analysis_tpu.ops import sorted_update as sorted_ops
from ruleset_analysis_tpu.ops import topk as topk_ops
from ruleset_analysis_tpu.runtime import checkpoint as ckpt_mod
from ruleset_analysis_tpu.runtime.stream import (
    run_stream_file,
    run_stream_wire,
)

# ONE volatile-keys list (runtime/report.py): the registry auditor
# (verify/registry.py) flags any module keeping a private copy.
from ruleset_analysis_tpu.runtime.report import VOLATILE_TOTALS as VOLATILE


def report_image(rep) -> dict:
    j = json.loads(rep.to_json())
    for k in VOLATILE:
        j["totals"].pop(k, None)
    return j


@pytest.fixture(scope="module")
def corpus(tmp_path_factory):
    # SAME ruleset + geometry as test_obs/test_devprof (see module doc)
    td = tmp_path_factory.mktemp("sorted")
    cfg_text = synth.synth_config(n_acls=3, rules_per_acl=8, seed=7)
    rs = aclparse.parse_asa_config(cfg_text, "fw1")
    packed = pack.pack_rulesets([rs])
    tuples = synth.synth_tuples(packed, 2600, seed=18)
    lines = synth.render_syslog(packed, tuples, seed=19)
    log = str(td / "s.log")
    with open(log, "w", encoding="utf-8") as f:
        f.write("\n".join(lines) + "\n")
    wirep = str(td / "s.rawire")
    wire_mod.convert_logs(packed, [log], wirep, block_rows=512)
    wirew = str(td / "sw.rawire")
    wire_mod.convert_logs(
        packed, [log], wirew, batch_size=512, block_rows=512, coalesce=True
    )
    prefix = str(td / "packed")
    pack.save_packed(packed, prefix)
    return packed, prefix, log, wirep, wirew


@pytest.fixture(scope="module")
def corpus6(tmp_path_factory):
    """Mixed v4+v6 corpus so the matrix covers the step.v6 program."""
    td = tmp_path_factory.mktemp("sorted6")
    cfg_text = synth.synth_config(
        n_acls=2, rules_per_acl=8, seed=27, v6_fraction=0.4
    )
    rs = aclparse.parse_asa_config(cfg_text, "fw1")
    packed = pack.pack_rulesets([rs])
    t4 = synth.synth_tuples(packed, 1400, seed=28)
    lines = synth.render_syslog(packed, t4, seed=29)
    t6 = synth.synth_tuples6(packed, 1000, seed=30)
    lines += synth.render_syslog6(packed, t6, seed=31)
    log = str(td / "s6.log")
    with open(log, "w", encoding="utf-8") as f:
        f.write("\n".join(lines) + "\n")
    return packed, log


def _cfg(depth=0, **kw):
    sk = dict(cms_width=1 << 10, cms_depth=2, hll_p=6)
    sk.update(kw.pop("sk", {}))
    return AnalysisConfig(
        batch_size=512,
        sketch=SketchConfig(**sk),
        prefetch_depth=depth,
        stall_timeout_sec=5.0,
        **kw,
    )


@pytest.fixture(scope="module")
def baselines(corpus):
    """Fault-free SCATTER reports (identity anchors), computed once.

    The scatter step at this geometry is the compile test_obs and
    test_devprof already paid for in a tier-1 process.
    """
    packed, _prefix, log, wirep, wirew = corpus
    return {
        "wire0": run_stream_wire(packed, [wirep], _cfg(depth=0)),
        "wire2": run_stream_wire(packed, [wirep], _cfg(depth=2)),
        "text0": run_stream_file(packed, [log], _cfg(depth=0), native=False),
        "wirew0": run_stream_wire(packed, [wirew], _cfg(depth=0)),
    }


# ---------------------------------------------------------------------------
# Op-level value identity.
# ---------------------------------------------------------------------------


def test_counts_hll_sorted_matches_scatter_ops():
    rng = np.random.default_rng(0)
    b, n_keys, p = 1500, 37, 4
    m = 1 << p
    keys = jnp.asarray(rng.integers(0, n_keys + 7, b), dtype=jnp.uint32)
    w = jnp.asarray(rng.integers(0, 5, b), dtype=jnp.uint32)  # weights incl 0
    src = jnp.asarray(rng.integers(0, 2**32, b, dtype=np.uint32))
    hll0 = jnp.asarray(rng.integers(0, 3, (n_keys, m)), dtype=jnp.uint32)

    ref_counts = count_ops.segment_counts(keys, w, n_keys)
    ref_hll = hll_ops.hll_update(hll0, keys, src, w)
    delta, new_hll = sorted_ops.counts_hll_sorted(
        hll0, keys, w, src, n_keys, need_counts=True
    )
    assert np.array_equal(np.asarray(delta), np.asarray(ref_counts))
    assert np.array_equal(np.asarray(new_hll), np.asarray(ref_hll))
    # counts skipped when another counts_impl owns the stage
    none_delta, only_hll = sorted_ops.counts_hll_sorted(
        hll0, keys, w, src, n_keys, need_counts=False
    )
    assert none_delta is None
    assert np.array_equal(np.asarray(only_hll), np.asarray(ref_hll))


def test_talker_tables_sorted_match_scatter_tables():
    rng = np.random.default_rng(1)
    b, width, depth, slots = 2000, 1 << 10, 2, topk_ops.CAND_SLOTS
    acl = jnp.asarray(rng.integers(0, 6, b), dtype=jnp.uint32)
    src = jnp.asarray(rng.integers(0, 50, b), dtype=jnp.uint32)  # collisions
    w = jnp.asarray(rng.integers(0, 4, b), dtype=jnp.uint32)
    salt = jnp.uint32(5)
    talk0 = jnp.asarray(rng.integers(0, 9, (depth, width)), dtype=jnp.uint32)

    pair = topk_ops.hash_pair(acl, src)
    ref_cms = cms_ops.cms_update(talk0, pair, w)
    slot = np.asarray(topk_ops.cand_slot(pair, salt, slots))
    v32 = np.asarray(w)
    ref_cnt = np.zeros(slots, np.uint32)
    np.add.at(ref_cnt, slot, v32)
    ref_rep = np.full(slots, -1, np.int64)
    for i in range(b):
        if v32[i] > 0:
            ref_rep[slot[i]] = max(ref_rep[slot[i]], i)

    cd, cnt, rep = sorted_ops.talker_tables_sorted(
        acl, src, w, salt, width=width, depth=depth, slots=slots
    )
    assert np.array_equal(np.asarray(talk0 + cd), np.asarray(ref_cms))
    assert np.array_equal(np.asarray(cnt), ref_cnt)
    assert np.array_equal(np.asarray(rep), ref_rep)
    # the deferred-chunk variant: same CMS values, empty tables
    cd2, cnt2, rep2 = sorted_ops.talker_tables_sorted(
        acl, src, w, salt, width=width, depth=depth, slots=slots,
        with_candidates=False,
    )
    assert np.array_equal(np.asarray(cd2), np.asarray(cd))
    assert int(np.asarray(cnt2).sum()) == 0
    assert np.all(np.asarray(rep2) == -1)


def test_composite_overflow_falls_back_value_identically(monkeypatch):
    """Geometries whose (key, register) composite would wrap uint32 take
    the scatter path inside the sorted entry point — same values."""
    assert sorted_ops.composite_fits(1 << 20, 256)
    assert not sorted_ops.composite_fits(1 << 24, 256)
    rng = np.random.default_rng(2)
    b, n_keys, p = 600, 19, 3
    keys = jnp.asarray(rng.integers(0, n_keys + 3, b), dtype=jnp.uint32)
    w = jnp.asarray(rng.integers(0, 3, b), dtype=jnp.uint32)
    src = jnp.asarray(rng.integers(0, 2**32, b, dtype=np.uint32))
    hll0 = jnp.zeros((n_keys, 1 << p), dtype=jnp.uint32)
    want_d, want_h = sorted_ops.counts_hll_sorted(
        hll0, keys, w, src, n_keys, need_counts=True
    )
    monkeypatch.setattr(sorted_ops, "COMPOSITE_LIMIT", 4)  # force fallback
    got_d, got_h = sorted_ops.counts_hll_sorted(
        hll0, keys, w, src, n_keys, need_counts=True
    )
    assert np.array_equal(np.asarray(want_d), np.asarray(got_d))
    assert np.array_equal(np.asarray(want_h), np.asarray(got_h))


def test_sorted_scopes_in_hlo():
    """The sorts trace under ra.sort; devprof's taxonomy knows the stage."""
    from ruleset_analysis_tpu.runtime import devprof

    assert "ra.sort" in devprof.STAGES
    assert devprof.scope_of("jit(f)/ra.sort/sort.1") == "ra.sort"
    b = 128
    keys = jnp.zeros(b, jnp.uint32)
    w = jnp.ones(b, jnp.uint32)
    src = jnp.arange(b, dtype=jnp.uint32)
    txt = (
        jax.jit(
            lambda k, v, s: sorted_ops.counts_hll_sorted(
                jnp.zeros((16, 16), jnp.uint32), k, v, s, 16, need_counts=True
            )
        )
        .lower(keys, w, src)
        .compile()
        .as_text()
    )
    assert "ra.sort" in txt and "ra.counts" in txt and "ra.hll" in txt
    txt2 = (
        jax.jit(
            lambda a, s, v: sorted_ops.talker_tables_sorted(
                a, s, v, jnp.uint32(0), width=256, depth=2, slots=1 << 10
            )
        )
        .lower(keys, src, w)
        .compile()
        .as_text()
    )
    assert "ra.sort" in txt2 and "ra.talk" in txt2 and "ra.topk" in txt2


# ---------------------------------------------------------------------------
# Driver bit-identity matrix.
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("inp,depth", [
    ("wire", 0),
    ("wire", 2),   # prefetch: same sorted program, jit cache hit
    ("text", 0),
])
def test_sorted_flat_bit_identical(corpus, baselines, inp, depth):
    packed, _prefix, log, wirep, _wirew = corpus
    cfg = _cfg(depth=depth, update_impl="sorted")
    rep = (
        run_stream_wire(packed, [wirep], cfg)
        if inp == "wire"
        else run_stream_file(packed, [log], cfg, native=False)
    )
    assert report_image(rep) == report_image(baselines[f"{inp}{depth}"])


def test_sorted_weighted_wire_accepted_and_identical(corpus, baselines):
    """RAWIREv3 weighted input under sorted: accepted (weight-linear by
    construction) and bit-identical to the scatter path on the SAME file."""
    packed, _prefix, _log, _wirep, wirew = corpus
    rep = run_stream_wire(
        packed, [wirew], _cfg(depth=0, update_impl="sorted")
    )
    assert rep.totals["wire_weighted"] is True
    assert report_image(rep) == report_image(baselines["wirew0"])


def test_sorted_v6_coalesced_bit_identical(corpus6):
    """Mixed v4+v6 text under runtime coalescing: both family programs
    run the sorted tail over the weighted valid plane."""
    packed, log = corpus6
    base = run_stream_file(
        packed, [log], _cfg(depth=2, coalesce="on"), native=False
    )
    rep = run_stream_file(
        packed, [log], _cfg(depth=2, coalesce="on", update_impl="sorted"),
        native=False,
    )
    assert report_image(rep) == report_image(base)


def test_sorted_stacked_bit_identical(corpus):
    packed, _prefix, log, _wirep, _wirew = corpus
    kw = dict(layout="stacked", stacked_lane=8192)
    base = run_stream_file(packed, [log], _cfg(depth=0, **kw), native=False)
    rep = run_stream_file(
        packed, [log], _cfg(depth=0, update_impl="sorted", **kw), native=False
    )
    assert report_image(rep) == report_image(base)


def test_crash_resume_across_impls(corpus, baselines, tmp_path):
    """Crash under scatter at chunk K, resume under sorted — identical to
    the uninterrupted scatter run.  This is only sound because the two
    formulations produce bit-identical REGISTERS, and is why update_impl
    stays out of the checkpoint fingerprint."""
    packed, _prefix, log, _wirep, _wirew = corpus
    ref = run_stream_file(packed, [log], _cfg(depth=0), native=False)
    ck = str(tmp_path / "ck")
    cfg = _cfg(depth=0).replace(checkpoint_every_chunks=2, checkpoint_dir=ck)
    crashed = run_stream_file(packed, [log], cfg, native=False, max_chunks=3)
    assert crashed.totals["lines_total"] < ref.totals["lines_total"]
    resumed = run_stream_file(
        packed, [log], cfg.replace(resume=True, update_impl="sorted"),
        native=False,
    )
    assert report_image(resumed) == report_image(ref)


#: Seeded chaos schedules under update_impl=sorted: the sorted programs
#: must inherit the whole failure model — typed abort, no hang, process
#: healthy afterwards (the bit-identical next run).  Two in tier-1
#: (producer raise + coalesce fault x sorted), two more in the slow soak.
_CHAOS = [
    ("ingest.producer.raise@2,seed=201", 2, {}),
    ("ingest.coalesce.fail@1,seed=202", 2, {"coalesce": "on"}),
]
_CHAOS_SLOW = [
    ("ingest.queue.stall@2,seed=203", 2, {}),
    ("ingest.producer.raise@1,seed=204", 3, {"coalesce": "on"}),
]


@pytest.mark.parametrize("plan,depth,kw", _CHAOS)
def test_chaos_sorted_typed_abort_then_healthy(corpus, baselines, plan, depth, kw):
    from ruleset_analysis_tpu.errors import AnalysisError

    packed, _prefix, _log, wirep, _wirew = corpus
    with pytest.raises(AnalysisError):
        run_stream_wire(
            packed, [wirep],
            _cfg(depth=depth, update_impl="sorted", fault_plan=plan, **kw),
        )
    again = run_stream_wire(
        packed, [wirep], _cfg(depth=2, update_impl="sorted", **kw)
    )
    assert report_image(again) == report_image(baselines["wire2"])


@pytest.mark.slow
@pytest.mark.parametrize("plan,depth,kw", _CHAOS_SLOW)
def test_chaos_sorted_soak(corpus, baselines, plan, depth, kw):
    from ruleset_analysis_tpu.errors import AnalysisError

    packed, _prefix, _log, wirep, _wirew = corpus
    with pytest.raises(AnalysisError):
        run_stream_wire(
            packed, [wirep],
            _cfg(depth=depth, update_impl="sorted", fault_plan=plan, **kw),
        )
    again = run_stream_wire(
        packed, [wirep], _cfg(depth=2, update_impl="sorted", **kw)
    )
    assert report_image(again) == report_image(baselines["wire2"])


# ---------------------------------------------------------------------------
# Deferred selection (--topk-every).
# ---------------------------------------------------------------------------


def test_topk_every_cross_impl_identity_and_cadence_invariants(
    corpus, baselines
):
    packed, _prefix, _log, wirep, _wirew = corpus
    sc = run_stream_wire(
        packed, [wirep], _cfg(depth=0, sk={"topk_every": 3})
    )
    so = run_stream_wire(
        packed, [wirep],
        _cfg(depth=0, update_impl="sorted", sk={"topk_every": 3}),
    )
    # both impls defer identically: reports agree at the same cadence
    assert report_image(sc) == report_image(so)
    # registers are selection-independent: hits/unused match the
    # every-chunk baseline exactly; only the candidate stream may thin
    base = baselines["wire0"]
    ib, ic = report_image(base), report_image(sc)
    assert ic["per_rule"] == ib["per_rule"]
    assert ic["unused"] == ib["unused"]
    assert sc.talkers, "deferred selection must still surface talkers"


def test_topk_every_fingerprint_and_validation(corpus):
    packed, _prefix, _log, _wirep, _wirew = corpus
    f1 = ckpt_mod.fingerprint(packed, _cfg())
    f2 = ckpt_mod.fingerprint(packed, _cfg(sk={"topk_every": 2}))
    f3 = ckpt_mod.fingerprint(packed, _cfg(update_impl="sorted"))
    assert f1 != f2, "non-default cadence must change the snapshot identity"
    assert f1 == f3, "update_impl must NOT change the snapshot identity"
    with pytest.raises(ValueError):
        SketchConfig(topk_every=0)
    with pytest.raises(ValueError):
        SketchConfig(topk_every=1 << 13)


# ---------------------------------------------------------------------------
# Typed refusals + CLI surface.
# ---------------------------------------------------------------------------


def test_config_refuses_sorted_with_pallas_fused():
    with pytest.raises(ValueError, match="pallas_fused"):
        AnalysisConfig(update_impl="sorted", match_impl="pallas_fused")
    with pytest.raises(ValueError, match="update_impl"):
        AnalysisConfig(update_impl="bogus")
    # the weight-safe combinations all construct
    AnalysisConfig(update_impl="sorted", coalesce="on")
    AnalysisConfig(update_impl="sorted", counts_impl="reduce")


def test_cli_refusals(corpus, capsys):
    from ruleset_analysis_tpu import cli

    _packed, prefix, log, _wirep, _wirew = corpus
    rc = cli.main([
        "run", "--ruleset", prefix, "--logs", log,
        "--update-impl", "sorted",
        "--experimental-match-impl", "pallas_fused",
    ])
    assert rc == 2
    assert "pallas_fused" in capsys.readouterr().err
    # oracle backend: device-formulation knobs are tpu-only
    rc = cli.main([
        "run", "--ruleset", prefix, "--logs", log,
        "--backend", "oracle", "--update-impl", "sorted",
    ])
    assert rc == 2
    assert "--update-impl" in capsys.readouterr().err
    rc = cli.main([
        "run", "--ruleset", prefix, "--logs", log,
        "--backend", "oracle", "--topk-every", "4",
    ])
    assert rc == 2
    assert "--topk-every" in capsys.readouterr().err
