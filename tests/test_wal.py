"""Durable serve ingest WAL (runtime/wal.py, ISSUE 14 / DESIGN §19).

Pure host-side unit pins — no jax, no device.  The serve-level story
(hard-kill resume replaying the interrupted window bit-identical) lives
in tests/test_serve.py and a seeded chaos schedule in test_chaos.py;
this file pins the on-disk format's three load-bearing properties:

- **round trip**: append N, replay from any seq -> exactly the suffix,
  in order, byte-identical;
- **budget eviction**: disk stays bounded and the eviction loss is
  EXACTLY countable at replay via seq arithmetic (no side counters);
- **corruption**: a CRC/framing-damaged segment is a typed quarantine
  (renamed aside, exact loss count where a successor pins it, replay
  continues) — never a crash, never a silent gap.
"""

import os
import struct

from ruleset_analysis_tpu.runtime import wal as wal_mod
from ruleset_analysis_tpu.runtime.wal import HEADER_BYTES, WriteAheadLog


def _fill(d, n, *, segment=4096, budget=1 << 20, width=100):
    w = WriteAheadLog(str(d), segment_bytes=segment, budget_bytes=budget)
    for i in range(n):
        assert w.append(f"{'x' * width} {i}") == i
    w.close()
    return w


def _segments(d):
    return sorted(n for n in os.listdir(d) if n.endswith(".wal"))


def test_round_trip_and_suffix_replay(tmp_path):
    _fill(tmp_path, 50)
    w = WriteAheadLog(str(tmp_path), segment_bytes=4096, budget_bytes=1 << 20)
    assert w.next_seq == 50  # scan-on-open recovers the append cursor
    for start in (0, 17, 49, 50):
        got = list(w.replay(start))
        assert [s for s, _, _t in got] == list(range(start, 50))
        assert all(line == f"{'x' * 100} {s}" for s, line, _t in got)
        assert w.replay_lost == 0 and not w.replay_lost_unknown
    w.close()


def test_append_resumes_after_reopen(tmp_path):
    _fill(tmp_path, 10)
    w = WriteAheadLog(str(tmp_path), segment_bytes=4096, budget_bytes=1 << 20)
    assert w.append("late line") == 10
    w.close()
    w2 = WriteAheadLog(str(tmp_path), segment_bytes=4096, budget_bytes=1 << 20)
    got = list(w2.replay(9))
    assert [s for s, _, _t in got] == [9, 10]
    assert got[-1][1] == "late line"
    w2.close()


def test_torn_tail_is_clean_end_not_corruption(tmp_path):
    """A SIGKILL mid-append leaves a short final record: replay must end
    cleanly there (the interrupted append never 'happened'), with zero
    loss and zero quarantine."""
    _fill(tmp_path, 20)
    last = os.path.join(str(tmp_path), _segments(tmp_path)[-1])
    with open(last, "ab") as f:
        f.write(struct.pack("<II", 40, 0) + b"only-part-of")  # torn record
    w = WriteAheadLog(str(tmp_path), segment_bytes=4096, budget_bytes=1 << 20)
    assert w.next_seq == 20  # the torn record does not count
    got = list(w.replay(0))
    assert [s for s, _, _t in got] == list(range(20))
    assert w.replay_lost == 0 and not w.quarantined
    w.close()


def test_budget_eviction_exact_drop_accounting(tmp_path):
    """Disk budget eviction: bytes stay bounded, and the replay-visible
    gap equals the evicted record count EXACTLY (seq arithmetic, the
    acceptance criterion's 'exact drop accounting')."""
    w = _fill(tmp_path, 500, segment=4096, budget=8192)
    assert w.evicted_segments > 0
    st = w.stats()
    assert st["bytes"] <= 8192
    w2 = WriteAheadLog(str(tmp_path), segment_bytes=4096, budget_bytes=8192)
    got = list(w2.replay(0))
    first = got[0][0]
    # the gap [0, first) is exactly the evicted records — nothing else
    assert w2.replay_lost == first == w.evicted_records
    assert [s for s, _, _t in got] == list(range(first, 500))
    w2.close()


def test_crc_corruption_quarantines_segment_exact_loss(tmp_path):
    """Flip one payload byte mid-chain: that segment quarantines from
    the damaged record on (renamed *.quarantined), the loss is pinned
    exactly by the successor's start seq, and replay CONTINUES with the
    next segment — replayed + lost == appended."""
    _fill(tmp_path, 300)
    segs = _segments(tmp_path)
    assert len(segs) >= 3
    victim = os.path.join(str(tmp_path), segs[1])
    with open(victim, "r+b") as f:
        f.seek(HEADER_BYTES + 8 + 20)  # into record 0's payload
        b = f.read(1)
        f.seek(-1, 1)
        f.write(bytes([b[0] ^ 0xFF]))
    w = WriteAheadLog(str(tmp_path), segment_bytes=4096, budget_bytes=1 << 20)
    got = list(w.replay(0))
    assert len(got) + w.replay_lost == 300
    assert w.replay_lost > 0 and not w.replay_lost_unknown
    assert w.quarantined == [segs[1] + ".quarantined"]
    assert os.path.exists(victim + ".quarantined")
    assert not os.path.exists(victim)
    # the surviving seqs are a prefix + a suffix with ONE gap — never a
    # silently renumbered stream
    seqs = [s for s, _, _t in got]
    gaps = [
        (a, b) for a, b in zip(seqs, seqs[1:]) if b != a + 1
    ]
    assert len(gaps) == 1
    a, b = gaps[0]
    assert b - a - 1 == w.replay_lost
    w.close()


def test_final_segment_crc_damage_is_countable(tmp_path):
    """CRC damage in the FINAL segment leaves framing intact, so the
    open-time scan still pins the exact loss (no 'unknown')."""
    _fill(tmp_path, 300)
    segs = _segments(tmp_path)
    victim = os.path.join(str(tmp_path), segs[-1])
    with open(victim, "r+b") as f:
        f.seek(HEADER_BYTES + 8 + 3)
        b = f.read(1)
        f.seek(-1, 1)
        f.write(bytes([b[0] ^ 0xFF]))
    w = WriteAheadLog(str(tmp_path), segment_bytes=4096, budget_bytes=1 << 20)
    got = list(w.replay(0))
    assert len(got) + w.replay_lost == 300
    assert not w.replay_lost_unknown
    w.close()


def test_framing_damage_in_final_segment_marks_unknown(tmp_path):
    """A corrupted LENGTH word in the final segment breaks framing: the
    tail count is genuinely unknowable, and the WAL says so explicitly
    (replay_lost_unknown) instead of inventing a number."""
    _fill(tmp_path, 300)
    segs = _segments(tmp_path)
    victim = os.path.join(str(tmp_path), segs[-1])
    with open(victim, "r+b") as f:
        f.seek(HEADER_BYTES)  # record 0's length word
        f.write(struct.pack("<I", 0xFFFFFFFF))
    w = WriteAheadLog(str(tmp_path), segment_bytes=4096, budget_bytes=1 << 20)
    list(w.replay(0))
    assert w.replay_lost_unknown
    assert any(n.endswith(".quarantined") for n in os.listdir(tmp_path))
    w.close()


def test_gc_releases_checkpoint_covered_segments_only(tmp_path):
    w = _fill(tmp_path, 200, segment=4096, budget=1 << 20)
    w2 = WriteAheadLog(str(tmp_path), segment_bytes=4096, budget_bytes=1 << 20)
    before = len(_segments(tmp_path))
    w2.gc(upto_seq=100)
    after = len(_segments(tmp_path))
    assert after < before
    # everything >= 100 must still replay (the uncheckpointed tail)
    got = list(w2.replay(100))
    assert [s for s, _, _t in got] == list(range(100, 200))
    assert w2.replay_lost == 0
    w2.close()
    assert w.appended == 200


def test_reset_starts_fresh(tmp_path):
    _fill(tmp_path, 30)
    w = WriteAheadLog(str(tmp_path), segment_bytes=4096, budget_bytes=1 << 20)
    w.reset()
    assert w.next_seq == 0 and not _segments(tmp_path)
    assert w.append("fresh") == 0
    w.close()


def test_unwritable_dir_is_typed(tmp_path):
    import pytest

    from ruleset_analysis_tpu.errors import AnalysisError, WalQuarantine

    blocker = tmp_path / "file"
    blocker.write_text("not a dir")
    with pytest.raises(WalQuarantine) as ei:
        WriteAheadLog(str(blocker / "wal"))
    assert isinstance(ei.value, AnalysisError)
    assert wal_mod.MAGIC.startswith(b"RAWAL1")
