"""Wire format v2: the IPv6 section of .rawire files (DESIGN.md).

v4 rows keep the exact v1 bytes (all-v4 corpora still emit v1 files);
v6 rows append as a 40 B/line second section.  The wire run must be
bit-equal to the text run over the same corpus.
"""

import numpy as np
import pytest

pytest.importorskip("jax")

from ruleset_analysis_tpu.config import AnalysisConfig, SketchConfig
from ruleset_analysis_tpu.hostside import aclparse, oracle, pack, synth, wire
from ruleset_analysis_tpu.runtime.stream import run_stream, run_stream_wire

from tests.test_stream6 import CFG, mixed_lines


@pytest.fixture(scope="module")
def corpus(tmp_path_factory):
    td = tmp_path_factory.mktemp("wire6")
    rs = aclparse.parse_asa_config(CFG, "fw1")
    packed = pack.pack_rulesets([rs])
    lines = mixed_lines(2000, seed=11)
    log = td / "logs.txt"
    log.write_text("\n".join(lines) + "\n", encoding="utf-8")
    res = oracle.Oracle([rs]).consume(list(lines))
    return td, packed, rs, lines, str(log), res


def run_cfg(**kw):
    return AnalysisConfig(
        backend="tpu",
        batch_size=256,
        sketch=SketchConfig(cms_width=1 << 12, cms_depth=4, hll_p=8),
        **kw,
    )


def test_convert_writes_v2_and_counts(corpus, tmp_path):
    td, packed, rs, lines, log, res = corpus
    out = str(tmp_path / "logs.rawire")
    stats = wire.convert_logs(packed, [log], out, native=None)
    # the native tier parses v6 via its dual-family entry when available
    assert stats["rows"] > 0 and stats["rows6"] > 0
    assert stats["rows"] + stats["rows6"] == res.lines_matched
    with open(out, "rb") as f:
        assert f.read(8) == wire.MAGIC6
    r = wire.WireReader([out], packed)
    assert (r.n_rows, r.n6_rows) == (stats["rows"], stats["rows6"])
    r.close()


def test_all_v4_corpus_still_writes_v1(tmp_path):
    cfg_text = synth.synth_config(n_acls=2, rules_per_acl=8, seed=3)
    rs = aclparse.parse_asa_config(cfg_text, "fw1")
    packed = pack.pack_rulesets([rs])
    t = synth.synth_tuples(packed, 300, seed=3)
    log = tmp_path / "v4.txt"
    log.write_text("\n".join(synth.render_syslog(packed, t, seed=3)) + "\n")
    out = str(tmp_path / "v4.rawire")
    stats = wire.convert_logs(packed, [str(log)], out)
    assert "rows6" in stats and stats["rows6"] == 0
    with open(out, "rb") as f:
        assert f.read(8) == wire.MAGIC


def test_wire_run_equals_text_run(corpus, tmp_path):
    td, packed, rs, lines, log, res = corpus
    out = str(tmp_path / "logs.rawire")
    wire.convert_logs(packed, [log], out)
    rep_text = run_stream(packed, iter(lines), run_cfg(), topk=5)
    rep_wire = run_stream_wire(packed, out, run_cfg(), topk=5)
    hits = lambda r: {  # noqa: E731
        (e["firewall"], e["acl"], e["index"]): e["hits"]
        for e in r.per_rule
        if e["hits"] > 0
    }
    assert hits(rep_wire) == hits(rep_text) == dict(res.hits)
    assert rep_wire.unused == rep_text.unused == res.unused_rules([rs])
    assert rep_wire.totals["lines_total"] == len(lines)
    # v6 talkers render real addresses from the wire digest map too
    talk = rep_wire.talkers.get("fw1 A", [])
    assert any(":" in ip and not ip.startswith("v6#") for ip, _ in talk) or not any(
        ":" in ip or ip.startswith("v6#") for ip, _ in talk
    )


def test_wire_crash_resume_across_phase_boundary(corpus, tmp_path):
    """Resume from a snapshot taken INSIDE the v6 phase must be exact."""
    td, packed, rs, lines, log, res = corpus
    out = str(tmp_path / "logs.rawire")
    wire.convert_logs(packed, [log], out)
    uninterrupted = run_stream_wire(packed, out, run_cfg(), topk=5)

    # count total chunks first so the crash lands in the v6 phase
    r = wire.WireReader([out], packed)
    n4_chunks = (r.n_rows + 255) // 256
    r.close()
    ck = str(tmp_path / "ck")
    crash_cfg = run_cfg(checkpoint_every_chunks=1, checkpoint_dir=ck)
    run_stream_wire(packed, out, crash_cfg, topk=5, max_chunks=n4_chunks + 1)
    resumed = run_stream_wire(
        packed, out,
        run_cfg(checkpoint_every_chunks=1, checkpoint_dir=ck, resume=True),
        topk=5,
    )
    hits = lambda r: {  # noqa: E731
        (e["firewall"], e["acl"], e["index"]): e["hits"]
        for e in r.per_rule
        if e["hits"] > 0
    }
    assert hits(resumed) == hits(uninterrupted) == dict(res.hits)
    assert resumed.unused == uninterrupted.unused


def test_truncated_v6_section_refused(corpus, tmp_path):
    td, packed, rs, lines, log, res = corpus
    out = str(tmp_path / "t.rawire")
    wire.convert_logs(packed, [log], out)
    import os

    size = os.path.getsize(out)
    with open(out, "r+b") as f:
        f.truncate(size - 17)  # cut into the v6 section
    with pytest.raises(wire.WireFormatError, match="truncated"):
        wire.WireReader([out], packed)


def test_compact_expand6_roundtrip():
    rng = np.random.default_rng(4)
    b = np.zeros((pack.TUPLE6_COLS, 128), dtype=np.uint32)
    for i in (pack.T6_SRC, pack.T6_SRC + 1, pack.T6_SRC + 2, pack.T6_SRC + 3,
              pack.T6_DST, pack.T6_DST + 1, pack.T6_DST + 2, pack.T6_DST + 3):
        b[i] = rng.integers(0, 1 << 32, 128, dtype=np.uint32)
    b[pack.T6_ACL] = rng.integers(0, 1 << 23, 128, dtype=np.uint32)
    b[pack.T6_PROTO] = rng.integers(0, 256, 128, dtype=np.uint32)
    b[pack.T6_SPORT] = rng.integers(0, 1 << 16, 128, dtype=np.uint32)
    b[pack.T6_DPORT] = rng.integers(0, 1 << 16, 128, dtype=np.uint32)
    b[pack.T6_VALID] = rng.integers(0, 2, 128, dtype=np.uint32)
    np.testing.assert_array_equal(
        pack.expand_batch6(pack.compact_batch6(b)), b
    )


def test_v2_corruption_fuzz_refuses_loudly_never_crashes(corpus, tmp_path):
    """Byte-flips/truncations/extensions of a v2 file: the reader either
    refuses with WireFormatError or reads masked rows — never raises raw
    (same contract the v1 fuzz pinned in round 5)."""
    import random

    td, packed, rs, lines, log, res = corpus
    out = str(tmp_path / "f.rawire")
    wire.convert_logs(packed, [log], out)
    blob = open(out, "rb").read()
    rng = random.Random(3)
    crashes = []
    for _ in range(300):
        b = bytearray(blob)
        k = rng.randrange(4)
        if k == 0:
            pos = rng.randrange(len(b))
            b[pos] ^= 1 << rng.randrange(8)
        elif k == 1:
            b = b[: rng.randrange(len(b))]
        elif k == 2:
            b += bytes(rng.randrange(1, 64))
        else:
            pos = rng.randrange(len(b))
            b[pos:pos + 8] = rng.randbytes(8)
        p = str(tmp_path / "m.rawire")
        open(p, "wb").write(bytes(b))
        try:
            r = wire.WireReader([p], packed)
            for _batch, _n in r.iter_batches(0, 256):
                pass
            for _batch, _n in r.iter_batches6(0, 256):
                pass
            r.close()
        except wire.WireFormatError:
            pass
        except Exception as e:  # noqa: BLE001 - the point of the fuzz
            crashes.append((type(e).__name__, str(e)[:120]))
    assert not crashes, crashes[:3]


def test_wire_resume_past_input_refused_pure_v4(tmp_path):
    """Resume offset beyond the wire input must raise, even for pure-v4
    rulesets where the v6 phase never runs (guard regression pinned)."""
    from ruleset_analysis_tpu.errors import ResumeInputMismatch
    from ruleset_analysis_tpu.runtime.stream import _WireFileSource

    cfg_text = synth.synth_config(n_acls=2, rules_per_acl=6, seed=8)
    rs = aclparse.parse_asa_config(cfg_text, "fw1")
    packed = pack.pack_rulesets([rs])
    t = synth.synth_tuples(packed, 200, seed=8)
    log = tmp_path / "v4.txt"
    log.write_text("\n".join(synth.render_syslog(packed, t, seed=8)) + "\n")
    out = str(tmp_path / "v4.rawire")
    stats = wire.convert_logs(packed, [str(log)], out)
    src = _WireFileSource(packed, [out])
    with pytest.raises(ResumeInputMismatch):
        list(src.batches(stats["rows"] + stats["rows6"] + 1, 64))
    src.close()


def test_wire_fingerprint_covers_v6_rules(corpus, tmp_path):
    """A ruleset differing ONLY in v6 content must refuse the wire file."""
    td, packed, rs, lines, log, res = corpus
    out = str(tmp_path / "fp.rawire")
    wire.convert_logs(packed, [log], out)
    # same config with one v6 ACE's ADDRESS changed: v4 rows, key
    # numbering, and gids all identical - only rules6 bytes differ
    cfg2 = CFG.replace("host 2001:db8::bad", "host 2001:db8::bae")
    rs2 = aclparse.parse_asa_config(cfg2, "fw1")
    packed2 = pack.pack_rulesets([rs2])
    np.testing.assert_array_equal(packed2.rules, packed.rules)
    with pytest.raises(wire.WireFormatError, match="different ruleset"):
        wire.WireReader([out], packed2)


def test_stacked_wire_v6_matches_flat(corpus, tmp_path):
    """Stacked layout over a v2 wire file: same report as the flat run."""
    td, packed, rs, lines, log, res = corpus
    out = str(tmp_path / "s.rawire")
    wire.convert_logs(packed, [log], out)
    rep_flat = run_stream_wire(packed, out, run_cfg(), topk=5)
    rep_st = run_stream_wire(packed, out, run_cfg(layout="stacked"), topk=5)
    hits = lambda r: {  # noqa: E731
        (e["firewall"], e["acl"], e["index"]): e["hits"]
        for e in r.per_rule
        if e["hits"] > 0
    }
    assert hits(rep_st) == hits(rep_flat) == dict(res.hits)
    assert rep_st.unused == rep_flat.unused == res.unused_rules([rs])


def test_convert_v6_byte_identical_across_parse_tiers(corpus, tmp_path):
    """python / native / feeder converts of a unified corpus must write
    byte-identical v2 files (the row stream is parser-independent)."""
    from ruleset_analysis_tpu.hostside import fastparse

    td, packed, rs, lines, log, res = corpus
    outs = {}
    wire.convert_logs(packed, [log], str(tmp_path / "py.rawire"), native=False)
    outs["python"] = open(tmp_path / "py.rawire", "rb").read()
    if fastparse.available():
        wire.convert_logs(packed, [log], str(tmp_path / "nat.rawire"), native=True)
        outs["native"] = open(tmp_path / "nat.rawire", "rb").read()
        wire.convert_logs(
            packed, [log], str(tmp_path / "fd.rawire"), feed_workers=2
        )
        outs["feeder"] = open(tmp_path / "fd.rawire", "rb").read()
    ref = outs.pop("python")
    for name, blob in outs.items():
        assert blob == ref, f"{name} convert differs from python"
