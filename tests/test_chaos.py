"""Chaos harness: seeded fault schedules over the full stream pipeline.

The system invariant (ISSUE 3; DESIGN §9): under ANY armed fault
schedule, a run either produces a report BIT-IDENTICAL to the fault-free
baseline or exits with a typed ``AnalysisError`` subclass — never a hang
(every wait is watchdog-bounded), never a silent wrong answer, and never
a leaked thread, worker process, or temp/rendezvous file (the autouse
conftest fixture enforces the leak half after every test here).

Tier-1 runs 20 deterministic seeded schedules across layout x input x
sync/prefetch plus the feeder tiers; the ``slow``-marked soak adds 20
more seeds and the multi-process elastic scenarios (worker death,
heartbeat drop), and can emit a chaos-pass-rate artifact via
``metrics.RecoveryMeter`` (RA_CHAOS_ARTIFACT=path).
"""

import json
import os
import random
import time

import numpy as np
import pytest

pytest.importorskip("jax")

from ruleset_analysis_tpu.config import AnalysisConfig, SketchConfig
from ruleset_analysis_tpu.errors import (
    AnalysisError,
    EXIT_CHECKPOINT_CORRUPT,
    EXIT_CHECKPOINT_MISMATCH,
    EXIT_FEED,
    EXIT_REFORM_BUDGET,
    EXIT_STALL,
    CheckpointCorrupt,
    CheckpointMismatch,
    FeedWorkerError,
    IngestError,
    InjectedFault,
    ReformBudgetExhausted,
    ResumeInputMismatch,
    StallError,
    WireCorrupt,
    exit_code_for,
)
from ruleset_analysis_tpu.hostside import aclparse, fastparse, pack, synth
from ruleset_analysis_tpu.hostside import wire as wire_mod
from ruleset_analysis_tpu.runtime import faults
from ruleset_analysis_tpu.runtime.stream import run_stream_file, run_stream_wire

# ONE volatile-keys list (runtime/report.py): the registry auditor
# (verify/registry.py) flags any module keeping a private copy.
from ruleset_analysis_tpu.runtime.report import VOLATILE_TOTALS as VOLATILE

CFG6 = """\
hostname fw1
access-list A extended permit tcp any host 10.0.0.5 eq 443
access-list A extended permit tcp any6 2001:db8:1::/48 eq 443
access-list A extended permit udp 2001:db8:2::/64 any6 eq 53
access-list A extended deny tcp any6 host 2001:db8::bad
access-list A extended permit ip any any
access-list B extended permit tcp any6 any6 range 8000 8100
access-group A in interface outside
"""

#: fast watchdog bound so injected stalls abort in seconds, not minutes
STALL_SEC = 3.0


def report_image(rep) -> dict:
    j = rep if isinstance(rep, dict) else json.loads(rep.to_json())
    j = json.loads(json.dumps(j))
    for k in VOLATILE:
        j["totals"].pop(k, None)
    return j


def _mixed_lines(n, seed=0, v6_share=0.3):
    rng = random.Random(seed)
    out = []
    for i in range(n):
        acl = "A" if rng.random() < 0.8 else "B"
        if rng.random() < v6_share:
            src = f"2001:db8:2::{rng.randrange(1, 40):x}"
            dst = f"2001:db8:{rng.randrange(0, 4):x}:1::{rng.randrange(1, 99):x}"
            proto = rng.choice(["tcp", "udp"])
        else:
            src = f"10.1.{rng.randrange(256)}.{rng.randrange(1, 255)}"
            dst = "10.0.0.5" if rng.random() < 0.5 else "10.9.9.9"
            proto = "tcp"
        out.append(
            f"Jul 29 07:48:{i % 60:02d} fw1 : %ASA-6-106100: access-list {acl} "
            f"permitted {proto} inside/{src}({rng.randrange(1024, 60000)}) -> "
            f"outside/{dst}({rng.choice([443, 53, 8050, 80])}) "
            f"hit-cnt 1 first hit [0x0, 0x0]"
        )
    return out


@pytest.fixture(scope="module")
def chaos_corpus(tmp_path_factory):
    """Mixed v4+v6 corpus, text + wire forms, shared across schedules."""
    td = tmp_path_factory.mktemp("chaos")
    rs = aclparse.parse_asa_config(CFG6, "fw1")
    packed = pack.pack_rulesets([rs])
    text = str(td / "mix.log")
    with open(text, "w", encoding="utf-8") as f:
        f.write("\n".join(_mixed_lines(2500, seed=11)) + "\n")
    wirep = str(td / "mix.rawire")
    wire_mod.convert_logs(packed, [text], wirep, batch_size=512, block_rows=512)
    return packed, text, wirep


@pytest.fixture(scope="module")
def baselines(chaos_corpus, tmp_path_factory):
    """Lazy fault-free reference images keyed (layout, input, cadence)."""
    cache: dict = {}
    td = tmp_path_factory.mktemp("chaos_base")

    def get(layout: str, inp: str, cadence: int) -> dict:
        key = (layout, inp, cadence)
        if key not in cache:
            packed, text, wirep = chaos_corpus
            ck = str(td / f"ck-{layout}-{inp}-{cadence}")
            cfg = _cfg(0, layout, cadence, ck)
            rep = (
                run_stream_wire(packed, wirep, cfg, topk=5)
                if inp == "wire"
                else run_stream_file(packed, text, cfg, topk=5)
            )
            cache[key] = report_image(rep)
        return cache[key]

    return get


def _cfg(depth: int, layout: str, cadence: int, ckpt_dir: str, resume=False,
         coalesce="off"):
    return AnalysisConfig(
        batch_size=512,
        sketch=SketchConfig(cms_width=1 << 10, cms_depth=2, hll_p=6),
        prefetch_depth=depth,
        layout=layout,
        checkpoint_every_chunks=cadence,
        checkpoint_dir=ckpt_dir,
        resume=resume,
        stall_timeout_sec=STALL_SEC,
        coalesce=coalesce,
    )


def schedule_for(seed: int):
    """Deterministic schedule from a seed: combo + one armed site.

    Replaying any failure needs only its seed number — the whole point
    of seeded chaos (DESIGN §9).
    """
    rng = random.Random(seed)
    layout = rng.choice(["flat", "stacked"])
    inp = rng.choice(["text", "wire"])
    depth = rng.choice([0, 2])
    # the coalesced path joins the matrix on flat layouts, where its
    # reports are unconditionally bit-identical to the off baseline
    # (stacked emission cadence shifts candidate pools — DESIGN §11;
    # its identity regime is pinned separately in test_coalesce.py)
    coalesce = rng.choice(["off", "on"]) if layout == "flat" else "off"
    sites = ["stream.device_put.fail", "checkpoint.torn_state",
             "checkpoint.torn_manifest"]
    if depth:
        sites += ["ingest.producer.raise", "ingest.queue.stall"]
    if inp == "wire":
        sites += ["stream.wire.corrupt"]
    if coalesce != "off":
        sites += ["ingest.coalesce.fail"]
    site = rng.choice(sites)
    cadence = 2 if site.startswith("checkpoint.") else rng.choice([0, 2])
    plan = faults.FaultPlan([faults.FaultSpec(site, rng.randint(1, 4))], seed=seed)
    return layout, inp, depth, cadence, coalesce, plan


def run_schedule(seed, chaos_corpus, baseline_of, tmp_path) -> bool:
    """One seeded schedule end to end; returns invariant-held verdict.

    Shared by the tier-1 parametrization and the slow soak (which
    aggregates verdicts into the chaos pass-rate artifact).
    """
    packed, text, wirep = chaos_corpus
    layout, inp, depth, cadence, coalesce, plan = schedule_for(seed)
    ck = str(tmp_path / f"ck-{seed}")
    cfg = _cfg(depth, layout, cadence, ck, coalesce=coalesce)

    def run(c):
        return (
            run_stream_wire(packed, wirep, c, topk=5)
            if inp == "wire"
            else run_stream_file(packed, text, c, topk=5)
        )

    # the baseline is always the coalesce-OFF fault-free run: a coalesced
    # schedule asserts BOTH halves at once — fault invariant and the
    # tentpole's bit-identical-report claim
    base = baseline_of(layout, inp, cadence)
    aborted = False
    with faults.armed(plan):
        try:
            rep = run(cfg)
        except AnalysisError:
            aborted = True  # typed abort: the allowed failure outcome
        else:
            # no abort: the schedule's hit count never fired (or the
            # fault landed somewhere recoverable) — the report must be
            # bit-identical to the fault-free baseline
            assert report_image(rep) == base, f"seed {seed} silently diverged"
    if aborted and cadence:
        # recovery half: whatever the fault tore mid-save, the pointer
        # protocol + CRCs must serve a consistent prior epoch and the
        # resumed run must land bit-identical to the fault-free baseline
        resumed = run(
            _cfg(depth, layout, cadence, ck, resume=True, coalesce=coalesce)
        )
        assert report_image(resumed) == base, f"seed {seed} bad recovery"
        leftovers = [
            e for e in os.listdir(ck)
            if e.startswith(".tmp-") or e.endswith(".ptr.tmp")
        ]
        assert not leftovers, f"seed {seed} leaked checkpoint temp files: {leftovers}"
    return True


@pytest.mark.parametrize("seed", range(20))
def test_chaos_schedule(seed, chaos_corpus, baselines, tmp_path):
    assert run_schedule(seed, chaos_corpus, baselines, tmp_path)


# ---------------------------------------------------------------------------
# Feeder-tier chaos (native parser; separate because the feed tiers are
# selected per run, not per config)
# ---------------------------------------------------------------------------


@pytest.mark.skipif(
    not fastparse.available(), reason="native parser not buildable here"
)
def test_chaos_feeder_thread_stall_bounded(chaos_corpus, tmp_path):
    """A wedged feed worker thread bounds to StallError, never a hang."""
    packed, text, _ = chaos_corpus
    cfg = _cfg(0, "flat", 0, str(tmp_path / "ck"))
    t0 = time.monotonic()
    with faults.armed(faults.FaultPlan.parse("feeder.worker.stall@2")):
        with pytest.raises(StallError):
            run_stream_file(
                packed, text, cfg, topk=5, feed_workers=2, feed_mode="thread"
            )
    assert time.monotonic() - t0 < 10 * STALL_SEC


@pytest.mark.skipif(
    not fastparse.available(), reason="native parser not buildable here"
)
def test_chaos_feeder_process_crash_typed(chaos_corpus, tmp_path):
    """An OOM-killed feed worker process surfaces as FeedWorkerError.

    The plan reaches the spawned worker through the RA_FAULT_PLAN env
    export — the same channel production chaos drills use."""
    packed, text, _ = chaos_corpus
    cfg = _cfg(0, "flat", 0, str(tmp_path / "ck"))
    with faults.armed(faults.FaultPlan.parse("feeder.worker.crash@2")):
        with pytest.raises((FeedWorkerError, StallError)):
            run_stream_file(
                packed, text, cfg, topk=5, feed_workers=2, feed_mode="process"
            )


@pytest.mark.skipif(
    not fastparse.available(), reason="native parser not buildable here"
)
def test_chaos_feeder_under_prefetch_typed(chaos_corpus, tmp_path):
    """Feeder fault below the prefetch wrapper: still typed, still no
    leak — the producer shutdown must close the inner feeder generator
    so its worker pool is torn down deterministically."""
    packed, text, _ = chaos_corpus
    cfg = _cfg(2, "flat", 0, str(tmp_path / "ck"))
    with faults.armed(faults.FaultPlan.parse("feeder.worker.stall@3")):
        with pytest.raises((StallError, FeedWorkerError, IngestError)):
            run_stream_file(
                packed, text, cfg, topk=5, feed_workers=2, feed_mode="thread"
            )


@pytest.mark.skipif(
    not fastparse.available(), reason="native parser not buildable here"
)
def test_chaos_ring_stall_bounded_sync(chaos_corpus, tmp_path):
    """feeder.ring.stall (ISSUE 11): a wedged per-chip ring producer
    starves exactly one chip; the coordinator's watchdog must bound it
    to a typed StallError naming the dry ring — never a hang."""
    packed, text, _ = chaos_corpus
    cfg = _cfg(0, "flat", 0, str(tmp_path / "ck"))
    t0 = time.monotonic()
    with faults.armed(faults.FaultPlan.parse("feeder.ring.stall@2")):
        with pytest.raises(StallError, match="rings dry"):
            run_stream_file(
                packed, text, cfg, topk=5, feed_workers=2, feed_mode="ring"
            )
    assert time.monotonic() - t0 < 10 * STALL_SEC


@pytest.mark.skipif(
    not fastparse.available(), reason="native parser not buildable here"
)
def test_chaos_ring_stall_under_prefetch_typed(chaos_corpus, tmp_path):
    """Ring stall below the prefetch wrapper (the production path: the
    ring coordinator runs inside the pump): still a typed abort, still
    no leaked ring workers or shared memory (the autouse leak fixture
    enforces the latter)."""
    packed, text, _ = chaos_corpus
    cfg = _cfg(2, "flat", 0, str(tmp_path / "ck"))
    with faults.armed(faults.FaultPlan.parse("feeder.ring.stall@3")):
        with pytest.raises((StallError, FeedWorkerError, IngestError)):
            run_stream_file(
                packed, text, cfg, topk=5, feed_workers=2, feed_mode="ring"
            )


@pytest.mark.skipif(
    not fastparse.available(), reason="native parser not buildable here"
)
def test_chaos_ring_worker_crash_typed(chaos_corpus, tmp_path):
    """An OOM-killed ring worker surfaces as FeedWorkerError via the
    liveness probe (the plan reaches the spawned worker through the
    RA_FAULT_PLAN env export, as in production drills)."""
    packed, text, _ = chaos_corpus
    cfg = _cfg(0, "flat", 0, str(tmp_path / "ck"))
    with faults.armed(faults.FaultPlan.parse("feeder.worker.crash@2")):
        with pytest.raises((FeedWorkerError, StallError)):
            run_stream_file(
                packed, text, cfg, topk=5, feed_workers=2, feed_mode="ring"
            )


# ---------------------------------------------------------------------------
# Units: plan round-trips, exit codes, on-disk wire damage
# ---------------------------------------------------------------------------


def test_fault_plan_round_trip_every_registered_site():
    for site in faults.SITES:
        for at in (1, 3):
            plan = faults.FaultPlan.parse(f"{site}@{at}")
            assert plan.specs[site].at == at
            assert plan.specs[site].count == 1
            assert faults.FaultPlan.parse(plan.to_str()).to_str() == plan.to_str()
    multi = faults.FaultPlan.parse(
        "ingest.producer.raise@2,stream.wire.corrupt@1,seed=9"
    )
    assert set(multi.specs) == {"ingest.producer.raise", "stream.wire.corrupt"}
    assert multi.seed == 9
    assert faults.FaultPlan.parse(multi.to_str()).to_str() == multi.to_str()


def test_fault_plan_transient_grammar_round_trip():
    """``site@N:k`` (ISSUE 14): fire k consecutive hits, then clear."""
    plan = faults.FaultPlan.parse("stream.device_put.fail@2:3,seed=4")
    spec = plan.specs["stream.device_put.fail"]
    assert (spec.at, spec.count) == (2, 3)
    assert [spec.fires_on(n) for n in range(1, 7)] == [
        False, True, True, True, False, False,
    ]
    assert plan.to_str() == "stream.device_put.fail@2:3,seed=4"
    assert faults.FaultPlan.parse(plan.to_str()).to_str() == plan.to_str()
    # single-shot stays the historical serialization (no ':1' noise)
    assert faults.FaultPlan.parse("listener.drop@5:1").to_str() == "listener.drop@5"
    with pytest.raises(AnalysisError, match=">= 1"):
        faults.FaultPlan.parse("listener.drop@5:0")
    with pytest.raises(AnalysisError, match="site@N"):
        faults.FaultPlan.parse("listener.drop@5:x")


def test_fault_plan_rejects_unknown_site_and_bad_hit():
    with pytest.raises(AnalysisError, match="unknown fault site"):
        faults.FaultPlan.parse("no.such.site@1")
    with pytest.raises(AnalysisError, match=">= 1"):
        faults.FaultPlan.parse("ingest.producer.raise@0")
    with pytest.raises(AnalysisError, match="no sites"):
        faults.FaultPlan.parse("seed=4")


def test_fault_plan_random_deterministic_and_armable():
    a = faults.FaultPlan.random(123, n_faults=2)
    b = faults.FaultPlan.random(123, n_faults=2)
    assert a.to_str() == b.to_str()
    assert faults.FaultPlan.random(124, n_faults=2).to_str() != a.to_str()
    with faults.armed(a):
        assert os.environ[faults.ENV_VAR] == a.to_str()
    assert faults.ENV_VAR not in os.environ
    assert faults.active_plan() is None


def test_exit_codes_map_failure_classes():
    assert exit_code_for(CheckpointCorrupt("x")) == EXIT_CHECKPOINT_CORRUPT == 3
    assert exit_code_for(CheckpointMismatch("x")) == EXIT_CHECKPOINT_MISMATCH == 4
    assert exit_code_for(ResumeInputMismatch("x")) == 4
    assert exit_code_for(FeedWorkerError("x")) == EXIT_FEED == 5
    assert exit_code_for(IngestError("x")) == 5
    assert exit_code_for(WireCorrupt("x")) == 5
    assert exit_code_for(StallError("x")) == EXIT_STALL == 6
    assert exit_code_for(ReformBudgetExhausted("x")) == EXIT_REFORM_BUDGET == 7
    assert exit_code_for(AnalysisError("x")) == 1
    assert exit_code_for(InjectedFault("x")) == 1
    # the distributed stall face maps with its base class
    from ruleset_analysis_tpu.runtime.elastic import FormationTimeout

    assert exit_code_for(FormationTimeout("x")) == 6


def test_on_disk_wire_valid_bit_damage_refused(tmp_path):
    """Clear one stored row's valid bit in the FILE: typed WireCorrupt.

    The converter never stores an invalid row, so this byte pattern only
    exists through post-conversion damage — the reader must refuse, not
    skip-count (the pre-PR behavior silently absorbed it)."""
    from ruleset_analysis_tpu.hostside.pack import W_META, WIRE_COLS
    from ruleset_analysis_tpu.hostside.wire import HEADER_BYTES

    cfg_text = synth.synth_config(n_acls=2, rules_per_acl=6, seed=3)
    rs = aclparse.parse_asa_config(cfg_text, "fw1")
    packed = pack.pack_rulesets([rs])  # pure-v4: v1 header, simple offsets
    tuples = synth.synth_tuples(packed, 900, seed=4)
    lines = synth.render_syslog(packed, tuples, seed=5)
    log = tmp_path / "w.log"
    log.write_text("\n".join(lines) + "\n", encoding="utf-8")
    wp = str(tmp_path / "w.rawire")
    stats = wire_mod.convert_logs(packed, [str(log)], wp, block_rows=512)
    r0 = min(512, stats["rows"])  # rows in block 0 ([WIRE_COLS, r0] plane)
    j = 5
    off = HEADER_BYTES + 4 * (W_META * r0 + j)
    with open(wp, "r+b") as f:
        f.seek(off)
        word = int.from_bytes(f.read(4), "little")
        assert word & (1 << 23), "picked a non-stored row; offset math wrong"
        f.seek(off)
        f.write((word & ~(1 << 23)).to_bytes(4, "little"))
    cfg = _cfg(0, "flat", 0, str(tmp_path / "ck"))
    with pytest.raises(WireCorrupt, match="valid bit"):
        run_stream_wire(packed, wp, cfg, topk=5)


def test_disarmed_sites_cost_nothing_and_change_nothing(chaos_corpus):
    """With no plan armed, fire() is a no-op returning its payload."""
    arr = np.arange(4, dtype=np.uint32)
    assert faults.fire("stream.wire.corrupt", payload=arr) is arr
    assert faults.fire("ingest.producer.raise") is None


# ---------------------------------------------------------------------------
# Serve-mode chaos (ISSUE 6): seeded schedules over the listener/reload
# tier.  The windowed invariant: every published window report is either
# bit-identical to an offline replay over exactly the lines that were
# DELIVERED to it, or carries an explicit WindowIncomplete marker with
# exact drop accounting — and a run under any schedule ends in a report
# or a typed abort, never a hang and never a silent zero-hit window.
# ---------------------------------------------------------------------------

SERVE_W = 100  # lines per window (deterministic rotation)
SERVE_LINES = 310  # 3 full windows + a tail that must never publish dirty


def serve_schedule(seed: int):
    """Seeded serve schedule: site from the seed, hit count from its rng.

    The site cycles so 12 seeds cover each of the four failure classes
    three times; hit counts above SERVE_LINES are deliberate never-fire
    schedules (the clean-run branch of the invariant).
    """
    sites = ["listener.drop", "listener.stall", "reload.midbatch",
             "stream.device_put.fail"]
    rng = random.Random(seed)
    site = sites[seed % len(sites)]
    if site == "listener.drop":
        at = rng.choice([5, 150, 205, 1000])
    elif site == "listener.stall":
        at = rng.choice([50, 1000])
    elif site == "reload.midbatch":
        at = 1
    else:  # stream.device_put.fail: lands in the first windows' chunks
        at = rng.randint(1, 4)
    return site, at, faults.FaultPlan([faults.FaultSpec(site, at)], seed=seed)


@pytest.fixture(scope="module")
def serve_chaos_corpus(chaos_corpus, tmp_path_factory):
    packed, _text, _wirep = chaos_corpus
    td = tmp_path_factory.mktemp("chaos_serve")
    prefix = str(td / "rules")
    pack.save_packed(packed, prefix)
    return packed, prefix, _mixed_lines(SERVE_LINES, seed=77)


@pytest.mark.parametrize("seed", range(12))
def test_chaos_serve_schedule(seed, serve_chaos_corpus, tmp_path):
    """One seeded listener/reload schedule against a live serve loop."""
    import socket
    import threading

    from ruleset_analysis_tpu.config import ServeConfig
    from ruleset_analysis_tpu.runtime.serve import ServeDriver, window_incomplete
    from ruleset_analysis_tpu.runtime.stream import run_stream

    packed, prefix, lines = serve_chaos_corpus
    site, at, plan = serve_schedule(seed)
    cfg = _cfg(0, "flat", 0, str(tmp_path / "ck"))
    scfg = ServeConfig(
        listen=("tcp:127.0.0.1:0",), window_lines=SERVE_W, ring=4,
        serve_dir=str(tmp_path / "serve"), max_windows=3,
        stop_after_sec=60, reload_watch=False,
        checkpoint_every_windows=0, http="off", queue_lines=10_000,
    )
    out: dict = {}
    with faults.armed(plan):
        drv = ServeDriver(prefix, cfg, scfg, topk=5)

        def runner():
            try:
                out["summary"] = drv.run()
            except BaseException as e:
                out["error"] = e

        th = threading.Thread(target=runner)
        th.start()
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline and not (
            "error" in out or drv.listeners.alive()
        ):
            time.sleep(0.05)
        if site == "reload.midbatch":
            # the ruleset on disk is unchanged; the fault site fires
            # before the (identity) migration even starts
            drv.request_reload()
        if "error" not in out and drv.listeners.alive():
            s = socket.create_connection(drv.listeners.listeners[0].address)
            s.sendall(("\n".join(lines) + "\n").encode())
            s.close()
        th.join(timeout=120)
        assert not th.is_alive(), f"seed {seed} ({site}@{at}): serve HUNG"
    if "error" in out:
        # the typed-abort branch (injected device failure, or the
        # wedged-listener watchdog escalating a stalled ingress)
        assert isinstance(out["error"], AnalysisError), (
            f"seed {seed} ({site}@{at}): untyped abort {out['error']!r}"
        )
        return

    summary = out["summary"]
    dropped_idx = (
        at - 1 if site == "listener.drop" and at <= SERVE_LINES else None
    )
    delivered = [ln for i, ln in enumerate(lines) if i != dropped_idx]
    n_full = min(3, len(delivered) // SERVE_W)
    # the bounded stop (max_windows) discards the queued backlog as
    # COUNTED drops and publishes one final marked partial window for
    # it — never a silent discard
    backlog = len(delivered) - n_full * SERVE_W
    n_win = summary["windows_published"]
    assert n_win == n_full + (1 if backlog else 0), f"seed {seed} ({site}@{at})"
    marked = []
    for i in range(n_full):
        with open(
            os.path.join(scfg.serve_dir, f"window-{i:06d}.json"),
            encoding="utf-8",
        ) as f:
            rep = json.load(f)
        # registers answer for exactly the delivered lines — true with
        # or without the incompleteness marker (the marker is about the
        # lines that never arrived, not the ones analyzed)
        seg = delivered[i * SERVE_W:(i + 1) * SERVE_W]
        got = report_image(rep)
        want = report_image(run_stream(packed, iter(seg), cfg, topk=5))
        got["totals"].pop("window", None)
        want["totals"].pop("window", None)
        assert got == want, f"seed {seed} ({site}@{at}): window {i} diverged"
        inc = window_incomplete(rep)
        if inc:
            marked.append((i, inc))
    if backlog:
        with open(
            os.path.join(scfg.serve_dir, f"window-{n_full:06d}.json"),
            encoding="utf-8",
        ) as f:
            prep = json.load(f)
        inc = window_incomplete(prep)
        assert prep["totals"]["lines_total"] == 0, (
            f"seed {seed}: backlog window analyzed lines it should not have"
        )
        assert inc and inc["drops"] == backlog, (
            f"seed {seed}: shutdown backlog not marked ({inc})"
        )
    forced = 1 if dropped_idx is not None else 0
    assert summary["drops"] == forced + backlog, (
        f"seed {seed} ({site}@{at}): drop accounting off"
    )
    if dropped_idx is not None:
        # the drop is accounted exactly once, on exactly one full
        # window — never silently absorbed into a zero-hit report
        assert len(marked) == 1 and marked[0][1]["drops"] == 1, (
            f"seed {seed}: dropped line not marked ({marked})"
        )
    else:
        assert marked == [], f"seed {seed} ({site}@{at})"
    if site == "reload.midbatch":
        # atomic failed reload: nothing swapped, nothing quarantined
        assert summary["reload_errors"] == 1 and summary["reloads"] == 0
        assert summary["quarantine_hits"] == 0


# ---------------------------------------------------------------------------
# Slow soak: more seeds + the multi-process elastic scenarios; emits the
# chaos-robustness artifact (pass rate + mean time-to-recover).
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_chaos_soak_matrix(chaos_corpus, baselines, tmp_path):
    from ruleset_analysis_tpu.runtime.metrics import RecoveryMeter

    meter = RecoveryMeter()
    for seed in range(100, 120):
        meter.record_run(
            run_schedule(seed, chaos_corpus, baselines, tmp_path)
        )
    s = meter.summary()
    assert s["chaos_runs"] == 20 and s["chaos_pass_rate"] == 1.0
    out = os.environ.get("RA_CHAOS_ARTIFACT")
    if out:
        with open(out, "w", encoding="utf-8") as f:
            json.dump(
                {
                    "suite": "chaos_soak_matrix",
                    "seeds": [100, 119],
                    **s,
                },
                f,
                indent=2,
            )


def _spawn_elastic_chaos(td, prefix, shards, victim_plan, victim_tag,
                         timeout=400):
    """4 elastic launchers; the victim's fault plan rides ITS env only."""
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    sys.path.insert(0, repo)
    from __graft_entry__ import scrubbed_cpu_env

    eldir = str(td / "eldir")
    procs = []
    for pid in range(4):
        env = scrubbed_cpu_env(2)
        env["RA_TEST_REEXEC"] = "1"
        if pid == victim_tag:
            env[faults.ENV_VAR] = victim_plan
        procs.append(
            subprocess.Popen(
                [sys.executable, "-m", "ruleset_analysis_tpu.cli", "run",
                 "--ruleset", prefix, "--logs", *shards, "--backend", "tpu",
                 "--distributed", "--elastic", "--elastic-dir", eldir,
                 "--num-processes", "4", "--process-id", str(pid),
                 "--batch-size", "64", "--checkpoint-every", "2",
                 "--json", "--out", str(td / f"rep{pid}.json")],
                env=env, cwd=repo,
                stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            )
        )
    outs = []
    for p in procs:
        try:
            out, err = p.communicate(timeout=timeout)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise AssertionError("elastic chaos launcher HUNG")
        outs.append((p.returncode, out, err))
    return eldir, outs


@pytest.fixture(scope="module")
def elastic_corpus(tmp_path_factory):
    td = tmp_path_factory.mktemp("chaos_elastic")
    cfg_text = synth.synth_config(n_acls=3, rules_per_acl=8, seed=41)
    rs = aclparse.parse_asa_config(cfg_text, "fw1")
    packed = pack.pack_rulesets([rs])
    tuples = synth.synth_tuples(packed, 1600, seed=42)
    lines = synth.render_syslog(packed, tuples, seed=43)
    prefix = str(td / "packed")
    pack.save_packed(packed, prefix)
    shards = []
    for i in range(4):
        p = td / f"shard{i}.log"
        p.write_text(
            "".join(ln + "\n" for ln in lines[i * 400:(i + 1) * 400]),
            encoding="utf-8",
        )
        shards.append(str(p))
    return td, prefix, shards


@pytest.mark.slow
def test_chaos_soak_elastic_worker_die(elastic_corpus, tmp_path_factory):
    """Plan-driven node death (elastic.worker.die): survivors re-form and
    the report is bit-identical; no rendezvous temp litter remains."""
    td = tmp_path_factory.mktemp("chaos_die")
    _td, prefix, shards = elastic_corpus
    eldir, outs = _spawn_elastic_chaos(
        td, prefix, shards, "elastic.worker.die@4", victim_tag=2
    )
    from ruleset_analysis_tpu.runtime.elastic import DIE_RC

    assert outs[2][0] == DIE_RC, outs[2][2][-2000:]
    for pid in (0, 1, 3):
        assert outs[pid][0] == 0, (
            f"survivor {pid} rc={outs[pid][0]}\n{outs[pid][2][-3000:]}"
        )
    rep = json.loads((td / "rep0.json").read_text(encoding="utf-8"))
    assert rep["totals"]["processes"] == 3
    rec = rep["totals"]["recovery"]
    assert rec["reforms_used"] >= 1 and rec["recovery_events"] >= 1
    assert rec["mean_time_to_recover_sec"] >= 0
    # fault-free reference over the same shards
    packed = pack.load_packed(prefix)
    ref = run_stream_file(packed, shards, AnalysisConfig(batch_size=64))
    ref = json.loads(ref.to_json())
    hits = lambda r: {  # noqa: E731
        (e["firewall"], e["acl"], e["index"]): e["hits"] for e in r["per_rule"]
    }
    assert hits(rep) == hits(ref) and rep["unused"] == ref["unused"]
    # rendezvous hygiene: no temp-write litter survives the run
    litter = [
        os.path.join(root, e)
        for root, _dirs, files in os.walk(eldir)
        for e in files
        if e.endswith(".tmp") or e.startswith(".tmp-")
    ]
    assert not litter, f"leaked rendezvous temp files: {litter}"


@pytest.mark.slow
def test_chaos_soak_elastic_heartbeat_drop(elastic_corpus, tmp_path_factory):
    """A partitioned member (heartbeat stops): peers re-form WITHOUT it at
    world 3 with a bit-identical report; the victim aborts typed instead
    of computing on as a zombie."""
    td = tmp_path_factory.mktemp("chaos_hb")
    _td, prefix, shards = elastic_corpus
    _eldir, outs = _spawn_elastic_chaos(
        td, prefix, shards, "elastic.heartbeat.drop@6", victim_tag=2,
        timeout=500,
    )
    assert outs[2][0] != 0, "partitioned member claimed success"
    for pid in (0, 1, 3):
        assert outs[pid][0] == 0, (
            f"survivor {pid} rc={outs[pid][0]}\n{outs[pid][2][-3000:]}"
        )
    rep = json.loads((td / "rep0.json").read_text(encoding="utf-8"))
    assert rep["totals"]["processes"] == 3
    packed = pack.load_packed(prefix)
    ref = run_stream_file(packed, shards, AnalysisConfig(batch_size=64))
    ref = json.loads(ref.to_json())
    hits = lambda r: {  # noqa: E731
        (e["firewall"], e["acl"], e["index"]): e["hits"] for e in r["per_rule"]
    }
    assert hits(rep) == hits(ref) and rep["unused"] == ref["unused"]


# ---------------------------------------------------------------------------
# Durable-WAL chaos (ISSUE 14): a seeded hard abort mid-window (a
# persistently-failing device_put past the retry budget — the on-disk
# state a SIGKILL leaves) followed by serve --resume.  Invariant: the
# interrupted window's delivered lines replay from the spool and publish
# bit-identical, with zero unaccounted drops.
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("seed", [0])
def test_chaos_serve_wal_hard_abort_resume(seed, serve_chaos_corpus, tmp_path):
    import threading

    from ruleset_analysis_tpu.config import ServeConfig
    from ruleset_analysis_tpu.runtime.serve import ServeDriver, window_incomplete
    from ruleset_analysis_tpu.runtime.stream import run_stream
    from ruleset_analysis_tpu.runtime.wal import WriteAheadLog

    packed, prefix, lines = serve_chaos_corpus
    rng = random.Random(seed)
    # window 0 = 3 full 32-line chunks + a rotation flush = 4 hits; the
    # seeded hit lands in window 1's chunks, :99 exhausts the budget
    at = 5 + rng.randrange(2)
    cfg = _cfg(0, "flat", 0, str(tmp_path / "ck")).replace(
        batch_size=32, fault_plan=f"stream.device_put.fail@{at}:99"
    )
    scfg = ServeConfig(
        listen=("tcp:127.0.0.1:0",), window_lines=SERVE_W, ring=4,
        serve_dir=str(tmp_path / "serve"), stop_after_sec=60,
        reload_watch=False, checkpoint_every_windows=1, http="off",
        queue_lines=10_000, wal=True,
    )

    def spin(drv, out):
        def runner():
            try:
                out["summary"] = drv.run()
            except BaseException as e:
                out["error"] = e
        th = threading.Thread(target=runner)
        th.start()
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline and not (
            "error" in out or drv.listeners.alive()
        ):
            time.sleep(0.05)
        return th

    out: dict = {}
    drv = ServeDriver(prefix, cfg, scfg, topk=5)
    th = spin(drv, out)
    import socket

    s = socket.create_connection(drv.listeners.listeners[0].address)
    s.sendall(("\n".join(lines[:180]) + "\n").encode())
    s.close()
    th.join(timeout=120)
    assert not th.is_alive(), f"seed {seed}: serve HUNG"
    assert isinstance(out.get("error"), AnalysisError), out
    assert drv.windows_published == 1

    wal = WriteAheadLog(os.path.join(scfg.serve_dir, "wal"))
    delivered = [ln for _s, ln, _t in wal.replay(SERVE_W)]
    wal.close()
    assert delivered == lines[SERVE_W:SERVE_W + len(delivered)]

    out2: dict = {}
    drv2 = ServeDriver(
        prefix, _cfg(0, "flat", 0, str(tmp_path / "ck")).replace(
            batch_size=32, resume=True
        ), scfg, topk=5,
    )
    th2 = spin(drv2, out2)
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline and drv2.wal_replayed != len(delivered):
        time.sleep(0.05)
    drv2.stop()
    th2.join(timeout=120)
    assert not th2.is_alive() and "error" not in out2, out2.get("error")
    summary = out2["summary"]
    assert summary["wal"]["replayed"] == len(delivered)
    assert summary["wal"]["lost"] == 0
    base_cfg = _cfg(0, "flat", 0, str(tmp_path / "ckb")).replace(batch_size=32)
    for wid, seg in ((0, lines[:SERVE_W]), (1, delivered)):
        with open(
            os.path.join(scfg.serve_dir, f"window-{wid:06d}.json"),
            encoding="utf-8",
        ) as f:
            rep = json.load(f)
        got = report_image(rep)
        want = report_image(run_stream(packed, iter(seg), base_cfg, topk=5))
        got["totals"].pop("window", None)
        want["totals"].pop("window", None)
        assert got == want, f"seed {seed}: window {wid} diverged after replay"
        inc = window_incomplete(rep)
        # zero unaccounted drops: any marker must claim no loss
        assert inc is None or (
            inc["drops"] == 0 and "wal_lost" not in inc["reasons"]
        ), inc
    assert summary["drops"] == 0
