"""Lenient parse mode: unsupported ACEs skip-and-count instead of aborting."""

import pytest

from ruleset_analysis_tpu.hostside import aclparse, oracle, pack, synth

MIXED_CFG = """
hostname fw6
access-list A extended permit tcp any host 10.0.0.5 eq 443
access-list A extended permit tcp any6 any6 eq 443
access-list A extended deny ip 2001:db8::0 ffff:ffff:: any
access-list A extended deny ip any any
access-list B extended permit udp object-group NOSUCHGROUP any
access-group A in interface outside
"""


def test_strict_mode_still_raises():
    with pytest.raises(aclparse.AclParseError):
        aclparse.parse_asa_config(MIXED_CFG, "fw6")


def test_lenient_skips_and_counts_exactly():
    rs = aclparse.parse_asa_config(MIXED_CFG, "fw6", strict=False)
    # the any6 rule PARSES now (v6 data model); the net+mask v6 spelling
    # is a mis-parse hazard and skips, as does the unknown group
    assert len(rs.skipped) == 2
    assert [ln for ln, _, _ in rs.skipped] != []
    reasons = " ".join(r for _, r, _ in rs.skipped)
    assert "NOSUCHGROUP" in reasons
    assert "IPv6 network operand requires /prefixlen" in reasons
    # surviving entries keep their DEVICE-side rule positions: line 1 ->
    # index 1, the v6 rule -> 2, the skipped line consumes 3, deny -> 4
    a = rs.acls["A"]
    assert [r.index for r in a] == [1, 2, 4]
    assert {ace.family for ace in a[1].aces} == {6}
    # ACL B exists (bindable, reportable) even though its only entry skipped
    assert rs.acls["B"] == []


def test_lenient_truncated_standard_acl_skips():
    """Regression: a truncated STANDARD entry must skip in lenient mode,
    not abort with IndexError."""
    cfg = (
        "access-list S standard permit host\n"
        "access-list S standard permit 10.0.0.0\n"
        "access-list S standard permit any\n"
    )
    with pytest.raises(aclparse.AclParseError):
        aclparse.parse_asa_config(cfg, "fw7")
    rs = aclparse.parse_asa_config(cfg, "fw7", strict=False)
    assert len(rs.skipped) == 2
    assert [r.index for r in rs.acls["S"]] == [3]


def test_lenient_v4_analysis_completes():
    rs = aclparse.parse_asa_config(MIXED_CFG, "fw6", strict=False)
    packed = pack.pack_rulesets([rs])
    line = (
        "Jul 29 01:02:03 fw6 : %ASA-6-106100: access-list A permitted tcp "
        "outside/1.2.3.4(999) -> inside/10.0.0.5(443) hit-cnt 1"
    )
    res = oracle.Oracle([rs]).consume([line])
    assert res.hits[("fw6", "A", 1)] == 1
    lp = pack.LinePacker(packed)
    batch = lp.pack_lines([line])
    assert lp.parsed == 1


def test_cli_lenient_flag(tmp_path, capsys):
    from ruleset_analysis_tpu import cli

    p = tmp_path / "fw6.cfg"
    p.write_text(MIXED_CFG)
    rc = cli.main(["parse-acls", str(p), "--out", str(tmp_path / "packed")])
    assert rc == 1  # strict default: abort
    rc = cli.main(["parse-acls", str(p), "--lenient", "--out", str(tmp_path / "packed")])
    assert rc == 0
    err = capsys.readouterr().err
    assert "skipped=2" in err
    assert "NOSUCHGROUP" in err


def test_parse_skips_surface_in_report(tmp_path):
    """A leniently-packed ruleset carries its skip count into the report."""
    from ruleset_analysis_tpu.runtime.report import build_report

    rs = aclparse.parse_asa_config(MIXED_CFG, "fw6", strict=False)
    packed = pack.pack_rulesets([rs])
    assert len(packed.parse_skips) == 2
    prefix = str(tmp_path / "p")
    pack.save_packed(packed, prefix)
    loaded = pack.load_packed(prefix)
    assert loaded.parse_skips == packed.parse_skips

    rep = build_report(loaded, {}, backend="tpu")
    assert rep.totals["config_entries_skipped"] == 2
    assert "WARNING" in rep.to_text()

    strict_rs = aclparse.parse_asa_config(
        "access-list A extended permit ip any any\n", "fw8"
    )
    clean = build_report(pack.pack_rulesets([strict_rs]), {}, backend="tpu")
    assert "config_entries_skipped" not in clean.totals


def test_lenient_skips_inverted_range():
    """Inverted ranges abort strict parses but skip-and-count leniently,
    keeping rule positions for the entries that remain."""
    from ruleset_analysis_tpu.hostside.aclparse import parse_asa_config

    cfg = """hostname fw1
access-list A extended permit tcp any any range 100 50
access-list A extended permit udp any any eq 53
access-group A in interface outside
"""
    rs = parse_asa_config(cfg, "fw1", strict=False)
    assert rs.rule_count() == 1
    assert len(rs.skipped) == 1
    assert "inverted port range" in rs.skipped[0][1]


def test_parser_fuzz_never_crashes():
    """Randomized mutations of valid configs: lenient mode must SKIP, not
    crash, and strict mode must raise only AclParseError — never a raw
    ValueError/IndexError from token handling (r5 fuzz found ip_to_u32
    leaking int('') ValueErrors through both modes)."""
    import random

    base = synth.synth_config(n_acls=3, rules_per_acl=12, seed=5)
    lines = base.splitlines()
    garbage = ["%$#@", "999999", "eq", "range", "object-group", "host",
               "::", "256.1.2.3", "-1", "\x00", "any6"]
    for trial in range(400):
        rng = random.Random(trial)
        muts = lines[:]
        for _ in range(rng.randint(1, 4)):
            i = rng.randrange(len(muts))
            toks = muts[i].split()
            if not toks:
                continue
            op = rng.randrange(5)
            if op == 0 and len(toks) > 1:
                toks = toks[: rng.randrange(1, len(toks))]
            elif op == 1:
                j, k = rng.randrange(len(toks)), rng.randrange(len(toks))
                toks[j], toks[k] = toks[k], toks[j]
            elif op == 2:
                toks.insert(rng.randrange(len(toks) + 1), rng.choice(garbage))
            elif op == 3:
                j = rng.randrange(len(toks))
                toks[j] = toks[j][: max(0, len(toks[j]) - 2)] or "x"
            else:
                toks = toks + toks
            muts[i] = " ".join(toks)
        text = "\n".join(muts)
        rs = aclparse.parse_asa_config(text, "fw", strict=False)  # no raise
        assert rs.firewall == "fw"
        try:
            aclparse.parse_asa_config(text, "fw", strict=True)
        except aclparse.AclParseError:
            pass  # the only acceptable strict-mode failure
