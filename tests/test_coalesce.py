"""Flow-coalescing ingest (ISSUE 5): weighted batch compaction.

The tentpole invariant: a coalesced run's FINAL REPORT is bit-identical
to the uncoalesced run's — per-rule hits, unused set, unique-source
estimates, top-K talker representatives, totals — because every register
update is weight-linear (counts/CMS/talker scatter-adds) or idempotent
(HLL max), and unique rows are emitted in first-occurrence order so the
candidate table's representative selection is preserved.  Pinned here
across flat x text/wire x v4/v6 x sync/prefetch, plus the stacked
layout's identity regime (single-emission: lane >= per-ACL rows), the
weighted .rawire v3 format, crash-at-K resume, the auto threshold, and
the compactor units (native vs numpy bit-identity, composition).
"""

import json
import random

import numpy as np
import pytest

pytest.importorskip("jax")

from ruleset_analysis_tpu.config import AnalysisConfig, SketchConfig
from ruleset_analysis_tpu.errors import AnalysisError, InjectedFault
from ruleset_analysis_tpu.hostside import aclparse, fastparse, pack, synth
from ruleset_analysis_tpu.hostside import wire as wire_mod
from ruleset_analysis_tpu.runtime import faults
from ruleset_analysis_tpu.runtime.coalesce import Coalescer, _ladder
from ruleset_analysis_tpu.runtime.stream import (
    run_stream_file,
    run_stream_wire,
)

# ONE volatile-keys list (runtime/report.py): the registry auditor
# (verify/registry.py) flags any module keeping a private copy.
from ruleset_analysis_tpu.runtime.report import VOLATILE_TOTALS as VOLATILE


def report_image(rep) -> dict:
    j = json.loads(rep.to_json())
    for k in VOLATILE:
        j["totals"].pop(k, None)
    return j


CFG6 = """\
hostname fw1
access-list A extended permit tcp any host 10.0.0.5 eq 443
access-list A extended permit tcp any6 2001:db8:1::/48 eq 443
access-list A extended permit udp 2001:db8:2::/64 any6 eq 53
access-list A extended deny tcp any6 host 2001:db8::bad
access-list A extended permit ip any any
access-list B extended permit tcp any6 any6 range 8000 8100
access-group A in interface outside
"""


def _mixed_lines(n, seed=0, v6_share=0.35):
    """Mixed-family corpus with bounded field pools, so flows REPEAT."""
    rng = random.Random(seed)
    out = []
    for i in range(n):
        acl = "A" if rng.random() < 0.8 else "B"
        if rng.random() < v6_share:
            src = f"2001:db8:2::{rng.randrange(1, 7):x}"
            dst = f"2001:db8:1:1::{rng.randrange(1, 5):x}"
            proto = rng.choice(["tcp", "udp"])
        else:
            src = f"10.1.0.{rng.randrange(1, 7)}"
            dst = "10.0.0.5" if rng.random() < 0.5 else "10.9.9.9"
            proto = "tcp"
        out.append(
            f"Jul 29 07:48:{i % 60:02d} fw1 : %ASA-6-106100: access-list {acl} "
            f"permitted {proto} inside/{src}({rng.randrange(1024, 1028)}) -> "
            f"outside/{dst}({rng.choice([443, 53])}) "
            f"hit-cnt 1 first hit [0x0, 0x0]"
        )
    return out


@pytest.fixture(scope="module")
def corpus4(tmp_path_factory):
    """v4 corpus of 4000 lines over ~120 distinct flows (ratio >> 1)."""
    td = tmp_path_factory.mktemp("coal4")
    cfg_text = synth.synth_config(n_acls=3, rules_per_acl=8, seed=41)
    rs = aclparse.parse_asa_config(cfg_text, "fw1")
    packed = pack.pack_rulesets([rs])
    tuples = synth.synth_flow_tuples(packed, 4000, 120, skew=1.0, seed=5)
    lines = synth.render_syslog(packed, tuples, seed=6)
    p = td / "v4.log"
    p.write_text("\n".join(lines) + "\n", encoding="utf-8")
    wp = td / "v4.rawire"
    wire_mod.convert_logs(packed, [str(p)], str(wp), batch_size=512, block_rows=512)
    return packed, str(p), str(wp)


@pytest.fixture(scope="module")
def corpus6(tmp_path_factory):
    """Mixed v4+v6 repetitive corpus against a unified ruleset."""
    td = tmp_path_factory.mktemp("coal6")
    rs = aclparse.parse_asa_config(CFG6, "fw1")
    packed = pack.pack_rulesets([rs])
    p = td / "v6.log"
    p.write_text("\n".join(_mixed_lines(3000, seed=7)) + "\n", encoding="utf-8")
    wp = td / "v6.rawire"
    wire_mod.convert_logs(packed, [str(p)], str(wp), batch_size=512, block_rows=512)
    return packed, str(p), str(wp)


def _cfg(coalesce, depth=2, layout="flat", **kw):
    return AnalysisConfig(
        batch_size=512,
        sketch=SketchConfig(cms_width=1 << 10, cms_depth=2, hll_p=6),
        prefetch_depth=depth,
        layout=layout,
        coalesce=coalesce,
        **kw,
    )


# ---------------------------------------------------------------------------
# Compactor units
# ---------------------------------------------------------------------------


def _repetitive_batch(n=4000, pool=150, seed=0):
    rng = np.random.default_rng(seed)
    p = np.zeros((6, pool), dtype=np.uint32)
    p[0] = rng.integers(0, 40, pool)
    p[1] = rng.integers(0, 256, pool)
    p[2] = rng.integers(0, 2**32, pool, dtype=np.uint32)
    p[3] = rng.integers(0, 2**16, pool)
    p[4] = rng.integers(0, 2**32, pool, dtype=np.uint32)
    p[5] = rng.integers(0, 2**16, pool)
    batch = np.zeros((pack.TUPLE_COLS, n), dtype=np.uint32)
    batch[:6] = p[:, rng.integers(0, pool, size=n)]
    batch[pack.T_VALID] = (rng.random(n) < 0.9).astype(np.uint32)
    return batch


def test_numpy_and_native_compactors_bit_identical():
    batch = np.ascontiguousarray(_repetitive_batch())
    out_np, fi_np = pack._np_coalesce(batch, True)
    if not fastparse.available():
        pytest.skip("native library not buildable here")
    out_nat, fi_nat = fastparse.native_coalesce(batch, True)
    assert np.array_equal(out_np, out_nat)
    assert np.array_equal(fi_np, fi_nat)


def test_coalesce_weights_order_and_composition():
    batch = _repetitive_batch()
    out, first = pack.coalesce_cols(np.ascontiguousarray(batch), True)
    # weights conserve the raw valid count
    assert int(out[-1].sum()) == int(batch[pack.T_VALID].sum())
    # first-occurrence order: source indices strictly increase
    assert np.all(np.diff(first) > 0)
    # every unique row's fields match its first occurrence
    assert np.array_equal(out[:-1], batch[:-1, first])
    # composition: re-coalescing a coalesced plane is a fixed point
    again, _ = pack.coalesce_cols(out)
    assert np.array_equal(again, out)
    # all-invalid input -> zero columns
    dead = np.zeros((pack.TUPLE_COLS, 32), dtype=np.uint32)
    empty, _ = pack.coalesce_cols(dead)
    assert empty.shape == (pack.TUPLE_COLS, 0)


def test_wire_and_tuple_compaction_agree():
    batch = _repetitive_batch(seed=3)
    ww = pack.coalesce_wire(pack.compact_batch(batch))
    assert ww.shape[0] == pack.WIREW_COLS
    tb = pack.coalesce_batch(batch)
    assert np.array_equal(pack.expand_batch(ww), tb)
    # weighted meta keeps the valid bit set on every stored row
    assert np.all((ww[pack.W_META] >> 23) & 1 == (ww[pack.W_WEIGHT] > 0))


def test_pad_weighted_and_bucket_ladder():
    assert _ladder(512, 8) == [512, 256, 128, 64, 32, 16]
    assert _ladder(48, 8) == [48, 24]  # 12 not divisible by 8
    c = Coalescer("on", 512, 8)
    b = _repetitive_batch(600, pool=100)[:, :512]
    out = c.tuple4(b)
    assert out.shape[1] in (128, 256)  # ~100 uniques pad to a bucket
    assert int(out[pack.T_VALID].sum()) == int(b[pack.T_VALID].sum())


# ---------------------------------------------------------------------------
# Zipf flow-repetition generator (satellite): requested ratio within ±10%
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("skew", [0.0, 1.0, 1.2])
def test_synth_flow_tuples_hit_expected_unique(skew):
    cfg_text = synth.synth_config(n_acls=3, rules_per_acl=8, seed=41)
    packed = pack.pack_rulesets([aclparse.parse_asa_config(cfg_text, "fw1")])
    n, n_flows = 20000, 2000
    t = synth.synth_flow_tuples(packed, n, n_flows, skew=skew, seed=11)
    assert t.shape == (n, pack.TUPLE_COLS)
    pool = synth.flow_pool(packed, n_flows, seed=11)
    view = np.ascontiguousarray(t).view(
        [("", np.uint32)] * t.shape[1]
    ).ravel()
    uniq = np.unique(view).size
    want = synth.expected_unique(n, pool.shape[0], skew)
    assert abs(uniq - want) <= 0.10 * want, (skew, uniq, want)
    # deterministic in seed
    t2 = synth.synth_flow_tuples(packed, n, n_flows, skew=skew, seed=11)
    assert np.array_equal(t, t2)


# ---------------------------------------------------------------------------
# Bit-identity matrix: flat x text/wire x v4/v6 x sync/prefetch
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("family", ["v4", "v6"])
@pytest.mark.parametrize("inp", ["text", "wire"])
@pytest.mark.parametrize("depth", [0, 2])
def test_flat_coalesced_bit_identical(corpus4, corpus6, family, inp, depth):
    packed, text, wirep = corpus4 if family == "v4" else corpus6

    def run(cfg):
        return (
            run_stream_wire(packed, wirep, cfg, topk=5)
            if inp == "wire"
            else run_stream_file(packed, text, cfg, topk=5)
        )

    base = run(_cfg("off", depth))
    rep = run(_cfg("on", depth))
    co = rep.totals["coalesce"]
    assert co["active"] and co["compaction_ratio"] > 1.5, co
    assert report_image(rep) == report_image(base)


@pytest.mark.parametrize("family", ["v4", "v6"])
def test_stacked_coalesced_bit_identical_single_emission(
    corpus4, corpus6, family
):
    """Stacked identity regime: lane >= per-ACL rows (single emission).

    Multi-emission cadence shifts the candidate pool between runs (the
    same caveat as the feeder tier, DESIGN §11); registers and the
    unused-rule report are cadence-invariant either way.
    """
    packed, text, _ = corpus4 if family == "v4" else corpus6
    kw = dict(layout="stacked", stacked_lane=8192)
    base = run_stream_file(packed, text, _cfg("off", 2, **kw), topk=5)
    rep = run_stream_file(packed, text, _cfg("on", 2, **kw), topk=5)
    assert report_image(rep) == report_image(base)


def test_crash_at_chunk_k_resume_coalesced(corpus6, tmp_path):
    """Crash simulation + resume with coalesce on == sync off baseline."""
    packed, text, _ = corpus6
    ref = run_stream_file(
        packed,
        text,
        _cfg("off", 0).replace(
            checkpoint_every_chunks=2, checkpoint_dir=str(tmp_path / "ref")
        ),
        topk=5,
    )
    ck = str(tmp_path / "ck")
    cfg = _cfg("on", 3).replace(checkpoint_every_chunks=2, checkpoint_dir=ck)
    crashed = run_stream_file(packed, text, cfg, topk=5, max_chunks=3)
    assert crashed.totals["lines_total"] < ref.totals["lines_total"]
    resumed = run_stream_file(packed, text, cfg.replace(resume=True), topk=5)
    assert report_image(resumed) == report_image(ref)


# ---------------------------------------------------------------------------
# auto mode
# ---------------------------------------------------------------------------


def test_auto_disables_on_uniform_and_stays_on_skewed(corpus4, tmp_path):
    packed, text, _ = corpus4
    # skewed corpus: auto stays on
    rep = run_stream_file(packed, text, _cfg("auto"), topk=5)
    assert rep.totals["coalesce"]["active"] is True
    # uniform corpus (independent draws): auto turns itself off and the
    # report still matches the off baseline bit for bit
    tuples = synth.synth_tuples(packed, 3000, seed=9)
    p = tmp_path / "uniform.log"
    p.write_text(
        "\n".join(synth.render_syslog(packed, tuples, seed=10)) + "\n",
        encoding="utf-8",
    )
    base = run_stream_file(packed, str(p), _cfg("off"), topk=5)
    auto = run_stream_file(packed, str(p), _cfg("auto"), topk=5)
    co = auto.totals["coalesce"]
    assert co["active"] is False and co["compaction_ratio"] < 1.25, co
    assert report_image(auto) == report_image(base)


# ---------------------------------------------------------------------------
# Weighted wire files (RAWIREv3)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("family", ["v4", "v6"])
def test_weighted_wire_file_report_content(
    corpus4, corpus6, family, tmp_path
):
    packed, text, wirep = corpus4 if family == "v4" else corpus6
    wpw = str(tmp_path / "w.rawire")
    stats = wire_mod.convert_logs(
        packed, [text], wpw, batch_size=512, block_rows=512, coalesce=True
    )
    assert stats["weighted"] and stats["rows"] < stats["evals"]
    base = run_stream_wire(packed, wirep, _cfg("off"), topk=5)
    rep = run_stream_wire(packed, wpw, _cfg("off"), topk=5)
    assert rep.totals["wire_weighted"] is True
    assert rep.totals["wire_evals"] == stats["evals"]
    # totals state ORIGINAL input accounting; stored-row-derived keys
    # (chunks, wire_rows) legitimately differ from the plain file's
    iw, ib = report_image(rep), report_image(base)
    for k in ("chunks", "wire_rows", "wire_evals", "wire_weighted"):
        iw["totals"].pop(k, None)
        ib["totals"].pop(k, None)
    assert iw == ib


def test_weighted_wire_file_resume_and_recoalesce(corpus4, tmp_path):
    """Crash/resume on a weighted file, and --coalesce on top composes."""
    packed, text, _ = corpus4
    wpw = str(tmp_path / "w.rawire")
    wire_mod.convert_logs(
        packed, [text], wpw, batch_size=512, block_rows=512, coalesce=True
    )
    ref = run_stream_wire(
        packed,
        wpw,
        _cfg("off", 0).replace(
            checkpoint_every_chunks=2, checkpoint_dir=str(tmp_path / "ref")
        ),
        topk=5,
    )
    ck = str(tmp_path / "ck")
    cfg = _cfg("off", 2).replace(checkpoint_every_chunks=2, checkpoint_dir=ck)
    run_stream_wire(packed, wpw, cfg, topk=5, max_chunks=2)
    resumed = run_stream_wire(packed, wpw, cfg.replace(resume=True), topk=5)
    assert report_image(resumed) == report_image(ref)
    # run-time coalescing on top of an already-weighted file merges
    # cross-batch duplicates (weights compose additively)
    rep = run_stream_wire(packed, wpw, _cfg("on"), topk=5)
    assert report_image(rep) == report_image(ref)


def test_weighted_wire_file_stacked_under_prefetch(corpus4, tmp_path):
    """Stacked layout over a weighted file, THROUGH the prefetch wrapper:
    the weighted flag must survive the wrap (a crushed weights plane
    here would silently divide every count by the compaction ratio)."""
    packed, text, wirep = corpus4
    wpw = str(tmp_path / "w.rawire")
    wire_mod.convert_logs(
        packed, [text], wpw, batch_size=512, block_rows=512, coalesce=True
    )
    kw = dict(layout="stacked", stacked_lane=8192)
    base = run_stream_wire(packed, wirep, _cfg("off", 2, **kw), topk=5)
    rep = run_stream_wire(packed, wpw, _cfg("off", 2, **kw), topk=5)
    iw, ib = report_image(rep), report_image(base)
    for k in ("chunks", "wire_rows", "wire_evals", "wire_weighted"):
        iw["totals"].pop(k, None)
        ib["totals"].pop(k, None)
    assert iw == ib


def test_weighted_and_plain_wire_files_refuse_to_mix(corpus4, tmp_path):
    packed, text, wirep = corpus4
    wpw = str(tmp_path / "w.rawire")
    wire_mod.convert_logs(
        packed, [text], wpw, batch_size=512, block_rows=512, coalesce=True
    )
    with pytest.raises(wire_mod.WireFormatError, match="mix weighted"):
        wire_mod.WireReader([wirep, wpw], packed)


def test_wire_info_reports_weighted_fields(corpus4, tmp_path, capsys):
    from ruleset_analysis_tpu.cli import main

    packed, text, _ = corpus4
    wpw = str(tmp_path / "w.rawire")
    stats = wire_mod.convert_logs(
        packed, [text], wpw, batch_size=512, block_rows=512, coalesce=True
    )
    assert main(["wire-info", wpw, "--json"]) == 0
    info = json.loads(capsys.readouterr().out)[0]
    assert info["weighted"] is True
    assert info["evals"] == stats["evals"]
    assert info["rows"] == stats["rows"]
    assert info["bytes_per_row"] == wire_mod.ROWW_BYTES


# ---------------------------------------------------------------------------
# Guard rails
# ---------------------------------------------------------------------------


def test_coalesce_fault_site_aborts_typed(corpus4):
    packed, text, _ = corpus4
    with faults.armed(faults.FaultPlan.parse("ingest.coalesce.fail@2")):
        with pytest.raises(AnalysisError):
            run_stream_file(packed, text, _cfg("on", 0), topk=5)
    # sync path raises the InjectedFault subclass directly
    with faults.armed(faults.FaultPlan.parse("ingest.coalesce.fail@1")):
        with pytest.raises(InjectedFault):
            run_stream_file(packed, text, _cfg("on", 0), topk=5)


def test_distributed_rejects_runtime_coalesce(corpus4):
    from ruleset_analysis_tpu.runtime.stream import (
        run_stream_file_distributed,
    )

    packed, text, _ = corpus4
    with pytest.raises(AnalysisError, match="convert --coalesce"):
        run_stream_file_distributed(packed, text, _cfg("on"))


def test_config_validates_coalesce():
    with pytest.raises(ValueError, match="coalesce"):
        AnalysisConfig(coalesce="sometimes")
    with pytest.raises(ValueError, match="matmul"):
        AnalysisConfig(
            coalesce="on", counts_impl="matmul", batch_size=1 << 24
        )
    # the fused kernel's in-VMEM histogram is not weight-linear
    with pytest.raises(ValueError, match="pallas_fused"):
        AnalysisConfig(coalesce="on", match_impl="pallas_fused")
    cfg = AnalysisConfig(coalesce="auto")
    assert AnalysisConfig.from_dict(cfg.to_dict()) == cfg


def test_weighted_wire_input_refuses_non_weight_linear_impls(
    corpus4, tmp_path
):
    """A weighted file reaches the step with weights the config validator
    never saw: the drivers must refuse pallas_fused (histogram adds one
    per line) and matmul counts (f32-exact bound assumes raw rows)."""
    packed, text, _ = corpus4
    wpw = str(tmp_path / "w.rawire")
    wire_mod.convert_logs(
        packed, [text], wpw, batch_size=512, block_rows=512, coalesce=True
    )
    with pytest.raises(AnalysisError, match="pallas_fused"):
        run_stream_wire(
            packed, wpw, _cfg("off").replace(match_impl="pallas_fused"),
            topk=5,
        )
    with pytest.raises(AnalysisError, match="matmul"):
        run_stream_wire(
            packed, wpw, _cfg("off").replace(counts_impl="matmul"), topk=5
        )


def test_weighted_chunk_overflow_refused():
    """Summed weights >= 2^32 in one chunk would wrap the uint32 count
    scatter undetected — the source refuses loudly instead."""
    from ruleset_analysis_tpu.runtime.stream import _WireFileSource

    with pytest.raises(AnalysisError, match="2\\^32"):
        _WireFileSource._check_chunk_weight(1 << 32)
    _WireFileSource._check_chunk_weight((1 << 32) - 1)  # boundary ok
