"""Sketch property tests (SURVEY.md §5): CMS one-sided error, HLL accuracy,
clz exactness, 64-bit carry, merge laws (sum/max) that scale-out relies on."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp

from ruleset_analysis_tpu.ops import cms as cms_ops
from ruleset_analysis_tpu.ops import counts as count_ops
from ruleset_analysis_tpu.ops import hashing
from ruleset_analysis_tpu.ops import hll as hll_ops
from ruleset_analysis_tpu.ops import topk as topk_ops


def test_clz32_exact():
    xs = np.array([0, 1, 2, 3, 4, 255, 256, 2**16 - 1, 2**16, 2**31, 2**32 - 1], dtype=np.uint32)
    got = np.asarray(hashing.clz32(jnp.asarray(xs)))
    exp = np.array([32 - int(x).bit_length() for x in xs], dtype=np.uint32)
    np.testing.assert_array_equal(got, exp)


def test_fmix32_avalanche():
    """Flipping one input bit should flip ~half the output bits on average."""
    rng = np.random.default_rng(0)
    x = rng.integers(0, 2**32, size=2000, dtype=np.uint32)
    h0 = np.asarray(hashing.fmix32(jnp.asarray(x)))
    h1 = np.asarray(hashing.fmix32(jnp.asarray(x ^ np.uint32(1))))
    flips = np.unpackbits((h0 ^ h1).view(np.uint8)).mean() * 32
    assert 14 < flips < 18


def test_add64_carry():
    lo = jnp.asarray(np.array([0xFFFFFFFF, 0xFFFFFFFE, 5], dtype=np.uint32))
    hi = jnp.asarray(np.array([0, 7, 1], dtype=np.uint32))
    delta = jnp.asarray(np.array([1, 1, 0], dtype=np.uint32))
    nlo, nhi = count_ops.add64(lo, hi, delta)
    total = count_ops.to_u64(np.asarray(nlo), np.asarray(nhi))
    np.testing.assert_array_equal(
        total, np.array([1 << 32, (7 << 32) + 0xFFFFFFFF, (1 << 32) + 5], dtype=np.uint64)
    )


def _random_stream(n, n_keys, seed, zipf=1.3):
    rng = np.random.default_rng(seed)
    keys = rng.zipf(zipf, size=n).astype(np.uint32) % n_keys
    return keys


def test_cms_one_sided_and_bounded():
    n, n_keys, width, depth = 20000, 500, 1 << 10, 4
    keys = _random_stream(n, n_keys, seed=1)
    true = np.bincount(keys, minlength=n_keys)

    sk = cms_ops.cms_init(width, depth)
    sk = cms_ops.cms_update(sk, jnp.asarray(keys), jnp.ones(n, dtype=jnp.uint32))
    est = np.asarray(cms_ops.cms_query(sk, jnp.asarray(np.arange(n_keys, dtype=np.uint32))))

    assert (est >= true).all(), "CMS must never underestimate"
    # error <= e*N/width with prob 1-exp(-depth); allow 3x slack for a seeded test
    bound = 3 * np.e * n / width
    assert (est - true).max() <= bound
    # numpy query path agrees with device query path
    est_np = cms_ops.cms_query_np(np.asarray(sk), np.arange(n_keys, dtype=np.uint32))
    np.testing.assert_array_equal(est, est_np)


def test_cms_merge_is_sum():
    keys = _random_stream(5000, 200, seed=2)
    half = len(keys) // 2
    ones = jnp.ones(half, dtype=jnp.uint32)
    a = cms_ops.cms_update(cms_ops.cms_init(1 << 9, 3), jnp.asarray(keys[:half]), ones)
    b = cms_ops.cms_update(cms_ops.cms_init(1 << 9, 3), jnp.asarray(keys[half:]), ones)
    whole = cms_ops.cms_update(
        cms_ops.cms_init(1 << 9, 3), jnp.asarray(keys), jnp.ones(len(keys), dtype=jnp.uint32)
    )
    np.testing.assert_array_equal(np.asarray(a) + np.asarray(b), np.asarray(whole))


def test_cms_weights_respected():
    sk = cms_ops.cms_init(1 << 8, 2)
    keys = jnp.asarray(np.array([7, 7, 9], dtype=np.uint32))
    w = jnp.asarray(np.array([1, 0, 1], dtype=np.uint32))  # middle line invalid
    sk = cms_ops.cms_update(sk, keys, w)
    est = np.asarray(cms_ops.cms_query(sk, jnp.asarray(np.array([7, 9], dtype=np.uint32))))
    assert est[0] == 1 and est[1] == 1


def test_hll_accuracy():
    n_keys, p = 8, 10  # m=1024 -> ~3.2% typical error
    true_cards = [1, 10, 100, 1000, 5000, 20000, 0, 3]
    keys_list, vals_list = [], []
    rng = np.random.default_rng(3)
    for k, c in enumerate(true_cards):
        if c == 0:
            continue
        vals = rng.choice(2**32, size=c, replace=False).astype(np.uint32)
        # each unique value appears 1-3 times
        reps = rng.integers(1, 4, size=c)
        keys_list.append(np.full(int(reps.sum()), k, dtype=np.uint32))
        vals_list.append(np.repeat(vals, reps))
    keys = np.concatenate(keys_list)
    vals = np.concatenate(vals_list)
    perm = rng.permutation(len(keys))
    keys, vals = keys[perm], vals[perm]

    hll = hll_ops.hll_init(n_keys, p)
    hll = hll_ops.hll_update(
        hll, jnp.asarray(keys), jnp.asarray(vals), jnp.ones(len(keys), dtype=jnp.uint32)
    )
    est = hll_ops.hll_estimate_np(np.asarray(hll))
    for k, c in enumerate(true_cards):
        if c == 0:
            assert est[k] == 0
        else:
            assert abs(est[k] - c) / c < 0.15, (k, c, est[k])


def test_hll_merge_is_max_and_order_invariant():
    rng = np.random.default_rng(4)
    vals = rng.integers(0, 2**32, size=4000, dtype=np.uint32)
    keys = rng.integers(0, 4, size=4000).astype(np.uint32)
    ones = jnp.ones(2000, dtype=jnp.uint32)
    a = hll_ops.hll_update(hll_ops.hll_init(4, 6), jnp.asarray(keys[:2000]), jnp.asarray(vals[:2000]), ones)
    b = hll_ops.hll_update(hll_ops.hll_init(4, 6), jnp.asarray(keys[2000:]), jnp.asarray(vals[2000:]), ones)
    whole = hll_ops.hll_update(
        hll_ops.hll_init(4, 6), jnp.asarray(keys), jnp.asarray(vals), jnp.ones(4000, dtype=jnp.uint32)
    )
    np.testing.assert_array_equal(np.maximum(np.asarray(a), np.asarray(b)), np.asarray(whole))


def test_hll_invalid_lines_are_identity():
    hll0 = hll_ops.hll_init(2, 6)
    keys = jnp.asarray(np.array([0, 1], dtype=np.uint32))
    vals = jnp.asarray(np.array([123, 456], dtype=np.uint32))
    out = hll_ops.hll_update(hll0, keys, vals, jnp.zeros(2, dtype=jnp.uint32))
    assert (np.asarray(out) == 0).all()


def test_topk_tracker_finds_heavy_hitters():
    rng = np.random.default_rng(5)
    # skewed stream over one ACL: talker i has weight ~ 1/i
    n = 30000
    srcs = rng.zipf(1.5, size=n).astype(np.uint32) % 1000
    acls = np.zeros(n, dtype=np.uint32)
    true = np.bincount(srcs, minlength=1000)
    true_top = set(np.argsort(true)[-5:])

    sk = cms_ops.cms_init(1 << 12, 4)
    tracker = topk_ops.TopKTracker(capacity=64)
    for i in range(0, n, 4096):
        a = jnp.asarray(acls[i : i + 4096])
        s = jnp.asarray(srcs[i : i + 4096])
        v = jnp.ones(a.shape[0], dtype=jnp.uint32)
        sk, ca, cs, ce = topk_ops.talker_chunk_update(sk, a, s, v, 32)
        tracker.offer_chunk(np.asarray(ca), np.asarray(cs), np.asarray(ce))

    got_top = {src for src, _ in tracker.top(0, 5)}
    assert len(true_top & got_top) >= 4  # at least 4/5 of true heavy hitters


def test_topk_salt_breaks_persistent_slot_collisions():
    """Two (acl, src) pairs sharing a candidate slot under one salt must
    land in different slots under (almost always) another salt, so a
    talker can't be starved forever; same salt stays deterministic."""
    # find two pairs colliding under salt=0 on host
    srcs = np.arange(200000, dtype=np.uint32)
    pair = np.asarray(topk_ops.hash_pair(jnp.zeros_like(jnp.asarray(srcs)), jnp.asarray(srcs)))
    slot0 = np.asarray(
        topk_ops.fmix32(jnp.asarray(pair) ^ jnp.uint32(0))
    ) & (topk_ops.CAND_SLOTS - 1)
    order = np.argsort(slot0, kind="stable")
    dup = np.nonzero(slot0[order][1:] == slot0[order][:-1])[0]
    assert dup.size > 0, "no collision found in probe range"
    a, b = srcs[order][dup[0]], srcs[order][dup[0] + 1]

    # X (=b) and H (=a) share a slot; the slot representative is the max
    # line index, so ordering H's lines first guarantees X holds the slot
    # at salt=0 and H is suppressed
    batch_src = np.concatenate([
        np.full(100, a, np.uint32), np.full(1000, b, np.uint32),
        np.arange(1000, dtype=np.uint32) + 1_000_000,
    ])
    acl = np.zeros(batch_src.size, dtype=np.uint32)
    v = np.ones(batch_src.size, dtype=np.uint32)
    sk = cms_ops.cms_init(1 << 12, 2)

    def cands(salt):
        _, ca, cs, ce = topk_ops.talker_chunk_update(
            sk, jnp.asarray(acl), jnp.asarray(batch_src), jnp.asarray(v), 16,
            salt=salt,
        )
        return set(np.asarray(cs)[np.asarray(ce) > 0].tolist())

    assert a not in cands(0)  # suppressed by the hotter collider
    assert b in cands(0)
    # under varied salts, H surfaces in the vast majority of chunks
    seen = sum(int(a in cands(s)) for s in range(1, 9))
    assert seen >= 6
    # determinism: same salt, same candidates (checkpoint resume replay)
    assert cands(3) == cands(3)


# ---------------------------------------------------------------------------
# HLL large-range behavior (VERDICT r3 weak #5): prove the 1.04/sqrt(m)
# bound holds near and beyond 2^31 distinct sources WITHOUT the classic
# 32-bit collision correction — fmix32 is a bijection on uint32, so there
# are no hash collisions to correct for (see hll_estimate_np docstring).
# ---------------------------------------------------------------------------


def _simulate_hll_registers(n: int, p: int, seed: int) -> np.ndarray:
    """Registers after folding n DISTINCT uint32 values, by exact
    inverse-CDF sampling of each register's max rank.

    fmix32 is a bijection, so n distinct inputs contribute n distinct
    rank-hash values sampled without replacement from the 2^32 space,
    each landing in a uniform register.  Values with rank >= r number
    2^(33-r) (rank 33 is the single value 0), each present with
    probability q = n/2^32 and in register j with probability 1/m, so
    P(max_j <= r) = (1 - q/m)^(2^(32-r)); inverting at u ~ U(0,1) gives
    max_j = ceil(32 - log2(ln u / ln(1 - q/m))), clipped to [0, 33].
    """
    m = 1 << p
    rng = np.random.default_rng(seed)
    q = n / 2.0**32
    u = rng.random(m)
    with np.errstate(divide="ignore"):
        L = np.log(u) / np.log1p(-q / m)
        r = np.ceil(32.0 - np.log2(L))
    return np.clip(r, 0, 33).astype(np.uint32)[None, :]


@pytest.mark.parametrize("n", [2**31, 3_800_000_000])
def test_hll_estimator_holds_bound_near_2_31(n):
    p = 14
    m = 1 << p
    ests = [
        float(hll_ops.hll_estimate_np(_simulate_hll_registers(n, p, seed=s))[0])
        for s in range(8)
    ]
    rel_err = abs(np.mean(ests) - n) / n
    # mean of 8 runs: SE ~ (1.04/sqrt(m))/sqrt(8) ~ 0.29%; allow 3x the
    # single-run bound so the test is deterministic-seed robust
    assert rel_err < 3 * 1.04 / np.sqrt(m), (n, np.mean(ests), rel_err)


def test_hll_classic_collision_correction_would_be_wrong():
    """The classic -2^32 ln(1 - E/2^32) correction assumes colliding
    hashes; with bijective fmix32 it would inflate ~39% at n = 2^31."""
    n = 2**31
    est = float(np.mean([
        hll_ops.hll_estimate_np(_simulate_hll_registers(n, 14, seed=s))[0]
        for s in range(8)
    ]))
    corrected = -(2.0**32) * np.log1p(-est / 2.0**32)
    assert abs(est - n) / n < 0.03  # uncorrected: within bound
    assert corrected > 1.3 * n  # "corrected": badly inflated


def test_hll_estimate_capped_at_value_space():
    """Fully saturated registers (n -> 2^32) must not report > 2^32 —
    the folded values are uint32 IPv4 addresses."""
    regs = np.full((1, 1 << 14), 33, dtype=np.uint32)
    est = float(hll_ops.hll_estimate_np(regs)[0])
    assert est == 2.0**32


def test_talker_sampled_selection_still_finds_heavy_hitters():
    """sample_shift selects candidates from a stride sample; the sketch
    still covers every line, so estimates are unchanged and persistent
    heavy hitters still surface (they recur within any stride)."""
    rng = np.random.default_rng(7)
    b = 1 << 14
    # one dominant talker at 30% of lines, background uniform
    src = rng.integers(0, 1 << 32, size=b, dtype=np.uint32)
    hot = np.uint32(0x0A0A0A0A)
    src[rng.random(b) < 0.3] = hot
    acl = np.zeros(b, dtype=np.uint32)
    valid = np.ones(b, dtype=np.uint32)
    sk = cms_ops.cms_init(1 << 12, 2)

    full = topk_ops.talker_chunk_update(
        sk, jnp.asarray(acl), jnp.asarray(src), jnp.asarray(valid), 8, salt=1
    )
    samp = topk_ops.talker_chunk_update(
        sk, jnp.asarray(acl), jnp.asarray(src), jnp.asarray(valid), 8, salt=1,
        sample_shift=3,
    )
    # identical sketch state (every line absorbed either way)
    np.testing.assert_array_equal(np.asarray(full[0]), np.asarray(samp[0]))
    cand_full = set(np.asarray(full[2])[np.asarray(full[3]) > 0].tolist())
    cand_samp = set(np.asarray(samp[2])[np.asarray(samp[3]) > 0].tolist())
    assert int(hot) in cand_full and int(hot) in cand_samp
    # and the hot talker's estimate comes from the full sketch both ways
    est_full = dict(zip(np.asarray(full[2]).tolist(), np.asarray(full[3]).tolist()))
    est_samp = dict(zip(np.asarray(samp[2]).tolist(), np.asarray(samp[3]).tolist()))
    assert est_full[int(hot)] == est_samp[int(hot)]


def test_talker_sampled_phase_rotates_with_salt():
    """The sampled column rotates by salt, so grouped (group-major) lines
    cannot alias entire groups out of the sample across chunks."""
    b = 1 << 10
    stride = 8
    # put a unique talker at flat positions with index % 8 == 5 only
    src = np.arange(b, dtype=np.uint32) + 1
    acl = np.zeros(b, dtype=np.uint32)
    valid = np.zeros(b, dtype=np.uint32)
    valid[5::stride] = 1  # only phase-5 lines are valid
    sk = cms_ops.cms_init(1 << 12, 2)

    def cands(salt):
        _, ca, cs, ce = topk_ops.talker_chunk_update(
            sk, jnp.asarray(acl), jnp.asarray(src), jnp.asarray(valid), 8,
            salt=salt, sample_shift=3,
        )
        return set(np.asarray(cs)[np.asarray(ce) > 0].tolist())

    # across stride consecutive salts, at least one chunk samples phase 5
    seen = [len(cands(s)) > 0 for s in range(stride)]
    assert any(seen), "rotation never reached the valid phase"
    assert not all(seen), "with only phase-5 valid, other phases must be empty"


def test_talker_sampled_selection_small_batch_degrades_to_exact():
    """A per-shard batch smaller than 2**shift must fall back to exact
    full-batch selection — not silently select zero candidates every
    chunk (ADVICE r4)."""
    b = 4  # < 2**3
    src = np.asarray([7, 7, 7, 9], dtype=np.uint32)
    acl = np.zeros(b, dtype=np.uint32)
    valid = np.ones(b, dtype=np.uint32)
    sk = cms_ops.cms_init(1 << 10, 2)
    _, ca, cs, ce = topk_ops.talker_chunk_update(
        sk, jnp.asarray(acl), jnp.asarray(src), jnp.asarray(valid), 4,
        salt=1, sample_shift=3,
    )
    winners = set(np.asarray(cs)[np.asarray(ce) > 0].tolist())
    assert 7 in winners
