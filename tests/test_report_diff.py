"""diff-reports: cross-run stability view (the delete-decision tool)."""

import json

import numpy as np
import pytest

pytest.importorskip("jax")

from ruleset_analysis_tpu.cli import main
from ruleset_analysis_tpu.hostside import aclparse, pack, synth


@pytest.fixture(scope="module")
def two_reports(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("diff")
    cfg_text = synth.synth_config(n_acls=2, rules_per_acl=8, seed=55)
    rs = aclparse.parse_asa_config(cfg_text, "fw1")
    packed = pack.pack_rulesets([rs])
    prefix = str(tmp / "rs")
    pack.save_packed(packed, prefix)
    paths = []
    for i, (n, seed) in enumerate([(500, 55), (700, 56)]):
        tuples = synth.synth_tuples(packed, n, seed=seed)
        lines = synth.render_syslog(packed, tuples, seed=seed)
        log = tmp / f"l{i}.log"
        log.write_text("\n".join(lines) + "\n")
        out = str(tmp / f"rep{i}.json")
        rc = main(["run", "--ruleset", prefix, "--logs", str(log),
                   "--batch-size", "128", "--json", "--out", out])
        assert rc == 0
        paths.append(out)
    return paths


def test_diff_reports_text_and_json(two_reports, capsys):
    old, new = two_reports
    assert main(["diff-reports", old, new]) == 0
    text = capsys.readouterr().out
    assert "stable unused" in text

    assert main(["diff-reports", old, new, "--json"]) == 0
    d = json.loads(capsys.readouterr().out)
    a = json.load(open(old))
    b = json.load(open(new))
    ua = {tuple(k) for k in a["unused"]}
    ub = {tuple(k) for k in b["unused"]}
    assert len(d["stable_unused"]) == len(ua & ub)
    assert len(d["newly_used"]) == len(ua - ub)
    assert len(d["newly_unused"]) == len(ub - ua)


def test_diff_reports_bad_input(tmp_path, capsys):
    bad = tmp_path / "x.json"
    bad.write_text("not json")
    rc = main(["diff-reports", str(bad), str(bad)])
    assert rc == 2
    assert "unreadable report" in capsys.readouterr().err


def test_diff_reports_ruleset_churn_not_mislabeled(tmp_path, capsys):
    """A rule present in only one report is ruleset churn, never
    newly-used/newly-unused (code-review finding)."""
    a = {"per_rule": [
            {"firewall": "fw1", "acl": "A", "index": 1, "hits": 0},
            {"firewall": "fw1", "acl": "A", "index": 2, "hits": 5}],
         "unused": [["fw1", "A", 1]]}
    b = {"per_rule": [
            {"firewall": "fw1", "acl": "A", "index": 2, "hits": 9},
            {"firewall": "fw1", "acl": "A", "index": 3, "hits": 0}],
         "unused": [["fw1", "A", 3]]}
    pa, pb = tmp_path / "a.json", tmp_path / "b.json"
    pa.write_text(json.dumps(a))
    pb.write_text(json.dumps(b))
    assert main(["diff-reports", str(pa), str(pb), "--json"]) == 0
    d = json.loads(capsys.readouterr().out)
    assert d["newly_used"] == [] and d["newly_unused"] == []
    assert d["rules_removed"] == ["fw1 A 1"]
    assert d["rules_added"] == ["fw1 A 3"]
    assert d["top_hit_movers"] == [{"rule": "fw1 A 2", "old": 5, "new": 9}]


def test_diff_reports_negative_top_rejected(tmp_path, capsys):
    p = tmp_path / "r.json"
    p.write_text(json.dumps({"per_rule": [], "unused": []}))
    assert main(["diff-reports", str(p), str(p), "--top", "-1"]) == 2
    assert "--top" in capsys.readouterr().err
