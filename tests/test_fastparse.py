"""Native C++ parser parity: fastparse must match the pure-Python path.

The native parser is a from-scratch reimplementation of
hostside/syslog.py + LinePacker (SURVEY.md §4.3 mapper parse semantics);
these tests hold the two paths bit-identical on batches, counters,
streaming chunking, and the full run_stream driver incl. resume.
"""

import numpy as np
import pytest

from ruleset_analysis_tpu.config import AnalysisConfig, SketchConfig
from ruleset_analysis_tpu.hostside import aclparse, fastparse, pack, synth

pytestmark = pytest.mark.skipif(
    not fastparse.available(), reason="native parser not buildable here"
)


EDGE_CFG = """
access-list OUT extended permit tcp any host 10.0.0.5 eq 443
access-list OUT extended deny ip any any
access-list IN extended permit udp 192.168.0.0 255.255.0.0 any range 5000 6000
access-group OUT in interface outside
access-group IN in interface inside
"""

EDGE_LINES = [
    "Jul 29 01:02:03 fw9 : %ASA-6-106100: access-list OUT permitted tcp "
    "outside/1.2.3.4(1234) -> inside/10.0.0.5(443) hit-cnt 1 first hit [0x0, 0x0]",
    "Jul 29 01:02:03 fw9 : %ASA-6-106100: access-list OUT est-allowed tcp "
    "outside/1.2.3.4(999) -> inside/10.0.0.5(443) hit-cnt 1",
    # icmp 106100: parenthesised values are type/code -> type lands in dport
    "Jul 29 01:02:03 fw9 : %ASA-6-106100: access-list OUT denied icmp "
    "outside/1.2.3.4(8) -> inside/10.0.0.5(0) hit-cnt 1",
    'Jul 29 01:02:03 fw9 : %ASA-4-106023: Deny tcp src outside:5.6.7.8/55 '
    'dst inside:10.0.0.5/443 by access-group "OUT" [0x0, 0x0]',
    'Jul 29 01:02:03 fw9 : %ASA-4-106023: Deny icmp src outside:5.6.7.8 '
    'dst inside:10.0.0.5 (type 3, code 1) by access-group "OUT"',
    "<166>Jul 29 01:02:03 fw9 : %ASA-6-302013: Built inbound TCP connection 123 "
    "for outside:9.9.9.9/1000 (9.9.9.9/1000) to inside:10.0.0.5/443 (10.0.0.5/443)",
    "<166>Jul 29 01:02:03 fw9 : %ASA-6-302013: Built outbound UDP connection 9 "
    "for outside:8.8.8.8/53 (8.8.8.8/53) to inside:192.168.1.7/5500 (192.168.1.7/5500)",
    "fw9: %ASA-6-302015: Built outbound UDP connection 9 for outside:8.8.8.8/53 "
    "to inside:192.168.1.7/5501",
    # unknown host / unknown acl / unhandled msgid / garbage / no host token
    "Jul 29 01:02:03 other : %ASA-6-106100: access-list OUT permitted tcp "
    "outside/1.2.3.4(1) -> inside/10.0.0.5(443) x",
    "Jul 29 01:02:03 fw9 : %ASA-6-106100: access-list NOPE permitted tcp "
    "outside/1.2.3.4(1) -> inside/10.0.0.5(443) x",
    "Jul 29 01:02:03 fw9 : %ASA-6-305011: Built dynamic TCP translation",
    "totally not a syslog line",
    "%ASA-6-106100: access-list OUT permitted tcp outside/1.2.3.4(1) -> "
    "inside/10.0.0.5(443) x",
    # hostname with attached colon, no space before the tag
    'fw9:%ASA-4-106023: Deny udp src inside:192.168.2.2/5000 '
    'dst outside:1.1.1.1/6000 by access-group "IN"',
    # identity-firewall user info after both endpoints (idfw)
    "Jul 30 01:02:03 fw9 : %ASA-6-106100: access-list OUT permitted tcp "
    "outside/1.2.3.4(1234)(DOMAIN\\user1) -> inside/10.0.0.5(443)(DOMAIN\\svc) "
    "hit-cnt 1 first hit [0x0, 0x0]",
    # 300-second-interval hit-count phrasing
    "Jul 30 01:02:03 fw9 : %ASA-6-106100: access-list OUT permitted tcp "
    "outside/1.2.3.4(5555) -> inside/10.0.0.5(443) hit-cnt 44 300-second interval [0x0, 0x0]",
    # syslog priority + ISO timestamp prefix
    "<166>2026-07-30T01:02:03Z fw9 : %ASA-6-106100: access-list OUT denied tcp "
    "outside/9.8.7.6(1) -> inside/10.0.0.5(80) hit-cnt 1",
    # interface names with dashes + flow-hash tail
    'Jul 30 01:02:03 fw9 : %ASA-4-106023: Deny tcp src outside:5.6.7.8/55 '
    'dst dmz-web:10.0.0.5/443 by access-group "OUT" [0x8a2b, 0x0]',
    # malformed bodies
    "Jul 29 fw9 : %ASA-6-106100: access-list OUT permitted tcp garbage",
    'Jul 29 fw9 : %ASA-4-106023: Deny tcp src outside:5.6.7.8 dst missing-group',
    "Jul 29 fw9 : %ASA-6-302013: Built sideways TCP connection 1 for a:1.2.3.4/5 to b:6.7.8.9/10",
    # 302013 hitting an interface with no access-group binding
    "Jul 29 fw9 : %ASA-6-302013: Built inbound TCP connection 5 for "
    "dmz:9.9.9.9/1000 to inside:10.0.0.5(443)",
    "",
]


def _edge_packed():
    rs = aclparse.parse_asa_config(EDGE_CFG, "fw9")
    return pack.pack_rulesets([rs])


def _synth_case(n=4000, seed=0):
    cfg_text = synth.synth_config(n_acls=3, rules_per_acl=24, seed=seed)
    rs = aclparse.parse_asa_config(cfg_text, "fw1")
    packed = pack.pack_rulesets([rs])
    tuples = synth.synth_tuples(packed, n, seed=seed + 1)
    lines = synth.render_syslog(packed, tuples, seed=seed + 2)
    return packed, lines


def _both(packed, lines, batch_size):
    py = pack.LinePacker(packed)
    ref = py.pack_lines(lines, batch_size=batch_size)
    nat = fastparse.NativePacker(packed)
    got = nat.pack_lines(lines, batch_size=batch_size)
    return py, ref, nat, got


class TestParity:
    def test_edge_corpus(self):
        packed = _edge_packed()
        py, ref, nat, got = _both(packed, EDGE_LINES, 32)
        np.testing.assert_array_equal(ref, got)
        assert (py.parsed, py.skipped) == (nat.parsed, nat.skipped)
        assert py.parsed > 0 and py.skipped > 0  # corpus exercises both

    def test_synth_corpus(self):
        packed, lines = _synth_case()
        py, ref, nat, got = _both(packed, lines, len(lines))
        np.testing.assert_array_equal(ref, got)
        assert (py.parsed, py.skipped) == (nat.parsed, nat.skipped)

    def test_crlf_and_no_trailing_newline(self):
        packed = _edge_packed()
        data = ("\r\n".join(EDGE_LINES[:6])).encode()  # CRLF, unterminated tail
        nat = fastparse.NativePacker(packed)
        out, n_lines, used = nat.pack_chunk(data, 16, final=True)
        py = pack.LinePacker(packed)
        ref = py.pack_lines(EDGE_LINES[:6], batch_size=16).T
        assert n_lines == 6 and used == len(data)
        np.testing.assert_array_equal(np.ascontiguousarray(ref), out)

    def test_partial_tail_held_back(self):
        packed = _edge_packed()
        line = EDGE_LINES[0] + "\n"
        data = (line + "Jul 29 01:02:03 fw9 : %ASA-6-1061").encode()
        nat = fastparse.NativePacker(packed)
        out, n_lines, used = nat.pack_chunk(data, 8, final=False)
        assert n_lines == 1 and used == len(line.encode())
        assert out[6, 0] == 1 and out[6, 1] == 0

    @pytest.mark.parametrize("n_threads", [2, 3, 8])
    def test_multithread_bit_identical(self, n_threads):
        """The parallel parse (worker slabs + compaction) must produce the
        same batch, counters, and consumed bytes as one thread — for any
        thread count, including more workers than lines per split."""
        packed, lines = _synth_case(n=5000, seed=3)
        corpus = lines + EDGE_LINES  # include skipped/edge lines mid-stream
        data = ("\n".join(corpus) + "\n").encode()
        nat1 = fastparse.NativePacker(packed)
        out1, l1, u1 = nat1.pack_chunk(data, len(corpus), final=True, n_threads=1)
        natn = fastparse.NativePacker(packed)
        outn, ln, un = natn.pack_chunk(data, len(corpus), final=True, n_threads=n_threads)
        np.testing.assert_array_equal(out1, outn)
        assert (l1, u1) == (ln, un)
        assert (nat1.parsed, nat1.skipped) == (natn.parsed, natn.skipped)

    def test_multithread_respects_max_lines(self):
        packed, lines = _synth_case(n=3000, seed=4)
        data = ("\n".join(lines) + "\n").encode()
        nat = fastparse.NativePacker(packed)
        out, n_lines, used = nat.pack_chunk(
            data, 3000, final=True, max_lines=1500, n_threads=4
        )
        assert n_lines == 1500
        nat2 = fastparse.NativePacker(packed)
        out2, n2, used2 = nat2.pack_chunk(
            data, 3000, final=True, max_lines=1500, n_threads=1
        )
        np.testing.assert_array_equal(out, out2)
        assert (n_lines, used) == (n2, used2)


class TestFileStream:
    def _write(self, tmp_path, lines, name="a.log"):
        p = tmp_path / name
        p.write_text("\n".join(lines) + "\n", encoding="utf-8")
        return str(p)

    def test_batches_match_text_path(self, tmp_path):
        packed, lines = _synth_case(n=1000)
        path = self._write(tmp_path, lines)
        bs = 128  # 1000 lines -> 7 full batches + partial
        nat = fastparse.NativePacker(packed)
        got = list(fastparse.batches_from_file(path, nat, bs))
        py = pack.LinePacker(packed)
        from ruleset_analysis_tpu.runtime.stream import chunked

        ref = [
            (np.ascontiguousarray(py.pack_lines(c, batch_size=bs).T), len(c))
            for c in chunked(iter(lines), bs)
        ]
        assert len(got) == len(ref)
        for (gb, gn), (rb, rn) in zip(got, ref):
            assert gn == rn
            np.testing.assert_array_equal(gb, rb)
        assert (nat.parsed, nat.skipped) == (py.parsed, py.skipped)

    def test_skip_lines_resume(self, tmp_path):
        packed, lines = _synth_case(n=600)
        path = self._write(tmp_path, lines)
        nat = fastparse.NativePacker(packed)
        got = list(fastparse.batches_from_file(path, nat, 100, skip_lines=250))
        assert sum(n for _, n in got) == 350

    def test_skip_past_eof_raises(self, tmp_path):
        from ruleset_analysis_tpu.errors import ResumeInputMismatch

        packed, lines = _synth_case(n=50)
        path = self._write(tmp_path, lines)
        nat = fastparse.NativePacker(packed)
        with pytest.raises(ResumeInputMismatch):
            list(fastparse.batches_from_file(path, nat, 100, skip_lines=51))

    def test_multi_file_chain_and_skip(self, tmp_path):
        packed, lines = _synth_case(n=300)
        p1 = self._write(tmp_path, lines[:120], "a.log")
        p2 = self._write(tmp_path, lines[120:], "b.log")
        nat = fastparse.NativePacker(packed)
        got = list(fastparse.batches_from_files([p1, p2], nat, 64, skip_lines=150))
        assert sum(n for _, n in got) == 150

    def test_multi_file_batches_identical_to_text_path(self, tmp_path):
        # files chain into ONE stream: batches straddle the file boundary
        # exactly like the text path, incl. an unterminated last line
        packed, lines = _synth_case(n=500)
        p1 = tmp_path / "a.log"
        p1.write_text("\n".join(lines[:333]), encoding="utf-8")  # no trailing \n
        p2 = self._write(tmp_path, lines[333:], "b.log")
        nat = fastparse.NativePacker(packed)
        got = list(fastparse.batches_from_files([str(p1), p2], nat, 128))
        py = pack.LinePacker(packed)
        from ruleset_analysis_tpu.runtime.stream import chunked

        ref = [
            (np.ascontiguousarray(py.pack_lines(c, batch_size=128).T), len(c))
            for c in chunked(iter(lines), 128)
        ]
        assert [n for _, n in got] == [n for _, n in ref]
        for (gb, _), (rb, _) in zip(got, ref):
            np.testing.assert_array_equal(gb, rb)

    def test_skip_spanning_many_read_blocks(self, tmp_path):
        # regression: the resume fast-skip must refill mid-fragment when
        # the skip region spans multiple read blocks
        packed, lines = _synth_case(n=400)
        path = self._write(tmp_path, lines)
        nat = fastparse.NativePacker(packed)
        got = list(
            fastparse.batches_from_file(path, nat, 64, skip_lines=301, read_block=64)
        )
        assert sum(n for _, n in got) == 99

    def test_full_batches_with_tiny_read_block(self, tmp_path):
        # regression: when batch_size lines span many read blocks, the
        # reader must keep buffering until a FULL batch of raw lines is
        # available — mid-stream chunk boundaries must match the text path
        packed, lines = _synth_case(n=400)
        path = self._write(tmp_path, lines)
        nat = fastparse.NativePacker(packed)
        got = list(fastparse.batches_from_file(path, nat, 128, read_block=256))
        assert [n for _, n in got] == [128, 128, 128, 16]
        py = pack.LinePacker(packed)
        from ruleset_analysis_tpu.runtime.stream import chunked

        for (gb, _), c in zip(got, chunked(iter(lines), 128)):
            rb = np.ascontiguousarray(py.pack_lines(c, batch_size=128).T)
            np.testing.assert_array_equal(gb, rb)

    def test_count_lines(self, tmp_path):
        p = tmp_path / "c.log"
        p.write_bytes(b"a\nb\nc")
        assert fastparse.count_lines_in_file(str(p)) == 3
        p.write_bytes(b"a\nb\n")
        assert fastparse.count_lines_in_file(str(p)) == 2


class TestDriver:
    def _cfg(self, **kw):
        return AnalysisConfig(
            batch_size=256,
            sketch=SketchConfig(cms_width=1 << 10, cms_depth=4, hll_p=6),
            **kw,
        )

    def test_run_stream_file_matches_text(self, tmp_path):
        from ruleset_analysis_tpu.runtime.stream import run_stream, run_stream_file

        packed, lines = _synth_case(n=900)
        path = tmp_path / "s.log"
        path.write_text("\n".join(lines) + "\n", encoding="utf-8")

        rep_text = run_stream(packed, (ln for ln in lines), self._cfg())
        rep_nat = run_stream_file(packed, str(path), self._cfg(), native=True)
        assert rep_nat.per_rule == rep_text.per_rule
        assert rep_nat.unused == rep_text.unused
        assert rep_nat.totals["lines_total"] == rep_text.totals["lines_total"]
        assert rep_nat.totals["lines_matched"] == rep_text.totals["lines_matched"]

    def test_checkpoint_resume_native(self, tmp_path):
        from ruleset_analysis_tpu.runtime.stream import run_stream_file

        packed, lines = _synth_case(n=1024)
        path = tmp_path / "s.log"
        path.write_text("\n".join(lines) + "\n", encoding="utf-8")
        ckdir = str(tmp_path / "ck")

        full = run_stream_file(packed, str(path), self._cfg(), native=True)
        # crash after 2 chunks (snapshot each chunk), then resume
        cfg1 = self._cfg(checkpoint_every_chunks=1, checkpoint_dir=ckdir)
        run_stream_file(packed, str(path), cfg1, native=True, max_chunks=2)
        cfg2 = self._cfg(checkpoint_every_chunks=1, checkpoint_dir=ckdir, resume=True)
        resumed = run_stream_file(packed, str(path), cfg2, native=True)
        assert resumed.per_rule == full.per_rule
        assert resumed.totals["lines_total"] == full.totals["lines_total"]


class TestFuzzParity:
    def test_mutated_corpus_bit_identical_and_crash_free(self):
        """Randomized corruption of valid syslog lines: both parsers must
        SKIP malformed lines (r5 fuzz: a corrupt address like
        '1.2.3.4.5.6' leaked an AclParseError out of the Python
        parse_line) and stay bit-identical to each other on the
        survivors."""
        import random

        packed, lines = _synth_case(n=300, seed=3)
        garbage = ["", "\x00\x01\x02", "%ASA-6-106100", "a" * 5000, "\u0661\u0660"]
        mutated = []
        for trial in range(1024):
            rng = random.Random(trial)
            line = rng.choice(lines)
            op = rng.randrange(4)
            if op == 0:
                line = line[: rng.randrange(len(line))]
            elif op == 1:
                i = rng.randrange(len(line))
                line = line[:i] + rng.choice("()/:->% \x00日\u0661") + line[i + 1:]
            elif op == 2:
                line = line + rng.choice(garbage)
            else:
                i, j = sorted(rng.randrange(len(line)) for _ in range(2))
                line = line[:j] + line[i:]
            mutated.append(line.replace("\n", " ").replace("\r", " "))
        py, ref, nat, got = _both(packed, mutated, 2048)
        np.testing.assert_array_equal(ref, got)
        assert (py.parsed, py.skipped) == (nat.parsed, nat.skipped)
        assert py.skipped > 0  # the corpus really contains corrupt lines


def test_pack_lines_stages_v6_rows_for_take_v6():
    """pack_lines on a unified corpus returns the v4 plane and stages the
    v6 evaluations for take_v6 — the same side-channel contract as the
    chunk API and the streaming drivers (the old loud v4-only refusal
    was deleted with ISSUE 11, which closed the last v6-refusing tier)."""
    cfg = synth.synth_config(n_acls=2, rules_per_acl=6, seed=3, v6_fraction=0.5)
    rs = aclparse.parse_asa_config(cfg, "fw6")
    packed = pack.pack_rulesets([rs])
    assert packed.has_v6
    v6_lines = synth.render_syslog6(
        packed, synth.synth_tuples6(packed, 4, seed=3), seed=4
    )
    v4_lines = synth.render_syslog(
        packed, synth.synth_tuples(packed, 4, seed=5), seed=6
    )
    nat = fastparse.NativePacker(packed)
    out = nat.pack_lines(v6_lines + v4_lines, batch_size=16)
    assert out.shape == (16, pack.TUPLE_COLS)
    assert int((out[:, pack.T_VALID] == 1).sum()) == len(v4_lines)
    rows6 = nat.take_v6()
    assert len(rows6) == len(v6_lines)  # staged, not lost, not raised
    assert len(nat.take_v6()) == 0  # drained exactly once
    # the dual-plane API remains the convenient route for unified corpora
    b4, b6 = nat.pack_lines2(v6_lines + v4_lines, batch_size=16)
    assert int((b6[:, pack.T6_VALID] == 1).sum()) == len(v6_lines)


# ---------------------------------------------------------------------------
# SIMD tokenizer byte-identity (ISSUE 11): the dispatched parse (AVX2 /
# NEON line parser + bulk newline scans) must produce byte-for-byte the
# output of the scalar reference on ANY input — well-formed lines of all
# seven message classes in both address families, 12k adversarial
# mutants, and truncated/oversize tails placed flush against the end of
# exactly-sized buffers (no primitive may read past the buffer).
# ---------------------------------------------------------------------------

simd_required = pytest.mark.skipif(
    not (fastparse.available() and fastparse.simd_active()),
    reason="CPU has neither AVX2 nor NEON (or RA_SIMD=off): nothing to A/B",
)


@pytest.fixture
def _simd_restore():
    was = fastparse.simd_active()
    yield
    fastparse.set_simd(was)


def _dual_family_case(seed=11):
    cfg_text = synth.synth_config(
        n_acls=3, rules_per_acl=12, seed=seed, v6_fraction=0.4,
        egress_acls=True,
    )
    rs = aclparse.parse_asa_config(cfg_text, "fwS")
    packed = pack.pack_rulesets([rs])
    lines = synth.render_syslog(
        packed, synth.synth_tuples(packed, 500, seed=seed + 1),
        seed=seed + 2, variety=0.7,
    )
    lines += synth.render_syslog6(
        packed, synth.synth_tuples6(packed, 400, seed=seed + 3),
        seed=seed + 4, variety=0.7,
    )
    return packed, lines


def _parse_both_modes(packed, data: bytes, cap: int, *, final=True,
                      max_lines=None):
    """Parse the same bytes under SIMD and scalar dispatch; return both."""
    results = {}
    for mode in (True, False):
        fastparse.set_simd(mode)
        pk = fastparse.NativePacker(packed)
        out, lines, used = pk.pack_chunk(
            data, cap, final=final,
            max_lines=max_lines if max_lines is not None else cap,
            n_threads=1,
        )
        rows6 = pk.take_v6() if packed.has_v6 else []
        results[mode] = (out, lines, used, pk.parsed, pk.skipped,
                         np.asarray(rows6, dtype=np.uint32))
    return results[True], results[False]


def _assert_identical(simd_res, scal_res):
    s_out, s_lines, s_used, s_p, s_s, s_v6 = simd_res
    c_out, c_lines, c_used, c_p, c_s, c_v6 = scal_res
    np.testing.assert_array_equal(s_out, c_out)
    np.testing.assert_array_equal(s_v6, c_v6)
    assert (s_lines, s_used, s_p, s_s) == (c_lines, c_used, c_p, c_s)


@simd_required
def test_simd_identity_all_message_classes(_simd_restore):
    """Well-formed corpus, all 7 msg classes, both families, dual-row
    connection lines (egress bindings): SIMD == scalar byte-for-byte."""
    packed, lines = _dual_family_case()
    data = ("\n".join(lines) + "\n").encode()
    simd_res, scal_res = _parse_both_modes(
        packed, data, 2 * len(lines), max_lines=len(lines)
    )
    _assert_identical(simd_res, scal_res)
    assert simd_res[3] > 0 and len(simd_res[5]) > 0  # both planes exercised


@simd_required
def test_simd_identity_mutant_sweep_12k(_simd_restore):
    """12k adversarial mutants (truncations, substitutions, splices,
    garbage tails) of all-message-class dual-family lines: the SIMD and
    scalar parses must agree byte-for-byte, including counters and
    consumed bytes.  The mutation grammar mirrors the python-vs-native
    sweep above; this one A/Bs the two NATIVE dispatch states."""
    import random

    packed, lines = _dual_family_case(seed=21)
    garbage = ["", "\x00\x01\x02", "%ASA-6-106100", "a" * 5000, "١٠",
               ":" * 40, "." * 40, "1" * 40, "f" * 40]
    mutated = []
    for trial in range(12000):
        rng = random.Random(trial)
        line = rng.choice(lines)
        op = rng.randrange(5)
        if op == 0:
            line = line[: rng.randrange(len(line))]
        elif op == 1:
            i = rng.randrange(len(line))
            line = line[:i] + rng.choice("()/:->% .\x00日١") + line[i + 1:]
        elif op == 2:
            line = line + rng.choice(garbage)
        elif op == 3:
            i, j = sorted(rng.randrange(len(line)) for _ in range(2))
            line = line[:j] + line[i:]
        else:
            # digit-run stress: double a random digit run (overlong
            # octets/ports exercise the ipv4 fast path's defer branch)
            i = rng.randrange(len(line))
            line = line[:i] + line[i:i + rng.randrange(1, 8)] + line[i:]
        mutated.append(line.replace("\n", " ").replace("\r", " "))
    data = ("\n".join(mutated) + "\n").encode()
    simd_res, scal_res = _parse_both_modes(
        packed, data, 2 * len(mutated), max_lines=len(mutated)
    )
    _assert_identical(simd_res, scal_res)
    assert simd_res[4] > 0  # the corpus really contains corrupt lines


@simd_required
def test_simd_identity_buffer_edge_tails(_simd_restore):
    """Lines placed flush against the end of exactly-sized buffers, with
    truncated (final=False) and final unterminated tails: every
    dispatch state must consume identical bytes and emit identical
    rows.  Covers address runs of every length straddling the 16/32-byte
    SIMD windows at the buffer edge."""
    packed, lines = _dual_family_case(seed=31)
    base = "\n".join(lines[:50]).encode()
    edge_tails = [
        b"",  # clean newline-terminated end
        b"\nJul 29 01:02:03 fwS : %ASA-6-106100: access-list A denied tcp "
        b"a/1.2.3.4(1) -> b/5.6.7.8",          # truncated mid-address
        b"\nfwS : %ASA-6-106100: access-list A permitted tcp a/" +
        b"1" * 40,                              # oversize digit run at EOF
        b"\nfwS : %ASA-4-106023: Deny tcp src a:" + b"0001.2.3.4",
        b"\nfwS : %ASA-6-302013: Built inbound TCP connection 1 for "
        b"a:255.255.255.255/65535",             # max-width quad at EOF
        b"\nfwS : %ASA-6-106100: access-list A permitted tcp a/"
        b"2001:db8::1(80) -> b/2001:db8::2",    # v6 run truncated at EOF
    ]
    for tail in edge_tails:
        for final in (True, False):
            data = bytes(base + tail)  # exactly sized; no slack bytes
            simd_res, scal_res = _parse_both_modes(
                packed, data, 2 * 64, max_lines=64, final=final
            )
            _assert_identical(simd_res, scal_res)


@simd_required
def test_simd_identity_bulk_newline_primitives(_simd_restore):
    """count_nl / count_lines (nl_skip) primitives: scalar vs SIMD over
    newline layouts that stress block boundaries — runs of newlines,
    32/16-byte-aligned clusters, no trailing newline, k edge cases."""
    import ctypes
    import random

    lib = fastparse._load()
    rng = random.Random(5)
    cases = [
        b"",
        b"\n",
        b"x" * 31 + b"\n",
        b"\n" * 200,
        (b"y" * 15 + b"\n") * 40,
        b"tail-without-newline",
    ]
    for _ in range(40):
        n = rng.randrange(0, 400)
        cases.append(bytes(rng.choice(b"ab\n") for _ in range(n)))
    for data in cases:
        got = {}
        for mode in (True, False):
            lib.asa_simd_set(1 if mode else 0)
            cnt = int(lib.asa_count_nl(data, len(data)))
            per_k = []
            for k in (0, 1, 3, 10**6):
                for final in (0, 1):
                    used = ctypes.c_int64(0)
                    c = int(lib.asa_count_lines(
                        data, len(data), final, k, ctypes.byref(used)))
                    per_k.append((k, final, c, int(used.value)))
            got[mode] = (cnt, per_k)
        assert got[True] == got[False], f"bulk scan divergence on {data!r}"
