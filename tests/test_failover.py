"""Supervisor failover + partition-tolerant epoch merge (DESIGN §23).

Pins the PR-18 tentpole invariants:

- **Exactly one winner per term**: the filesystem lease's
  ``O_CREAT|O_EXCL`` claim files make every fencing term have exactly
  one holder, racing acquirers included; a holder that loses its lease
  (higher term observed, or its own renewals aged past the TTL) stops
  publishing *typed* before a successor can win — split brain can
  never produce two publications for one window id.
- **Durable epoch spool**: every window epoch lands in a CRC'd
  ``RASPOOL1`` segment (the WAL discipline) BEFORE it ships, so it
  survives its producer and any supervisor.  Byte-level corruption —
  every truncation offset, every flipped byte — is a typed
  refusal/quarantine, never a crash, a hang, or a wrong merge.
- **Failover replay**: an elected successor replays all spooled epochs
  past the fenced merge frontier and publishes windows bit-identical
  to what the dead supervisor would have published.
- **Partition degraded mode**: a host that cannot reach the supervisor
  keeps ingesting to its spool, marks ``partition:<rank>``, and
  reconciles on heal with zero silent drops.

Chaos seams exercised here (the fault/retry registry auditors grep
this file): ``lease.acquire``, ``lease.renew``, ``dist.epoch.spool``,
and the ``dist.epoch.ship`` retry seam — transient recovery via the
``dist.epoch.ship@1:2`` schedule, budget exhaustion into partition
mode via ``dist.epoch.ship@1:6`` and ``dist.epoch.ship@1:99``.

Thread-mode only in tier 1; the CLI supervisor-SIGKILL e2e is
``slow``-marked (a spawned interpreter recompiles XLA from scratch).
"""

import json
import os
import shutil
import signal
import socket
import subprocess
import sys
import threading
import time
import urllib.request

import numpy as np
import pytest

pytest.importorskip("jax")

from ruleset_analysis_tpu.config import (
    AnalysisConfig,
    DistServeConfig,
    ServeConfig,
)
from ruleset_analysis_tpu.errors import (
    EXIT_CODE_NAMES,
    AnalysisError,
    InjectedFault,
    SupervisorFenced,
    WalQuarantine,
    exit_code_for,
)
from ruleset_analysis_tpu.hostside import aclparse, pack, synth
from ruleset_analysis_tpu.parallel.distributed import (
    pack_epoch_payload,
    unpack_epoch_payload,
)
from ruleset_analysis_tpu.runtime import faults
from ruleset_analysis_tpu.runtime import checkpoint as ckpt
from ruleset_analysis_tpu.runtime.distserve import DistServeDriver
from ruleset_analysis_tpu.runtime.lease import EpochSpool, SupervisorLease
from ruleset_analysis_tpu.runtime.report import VOLATILE_TOTALS as VOLATILE
from ruleset_analysis_tpu.runtime.report import lineage_core, lineage_frontier
from ruleset_analysis_tpu.runtime.wal import LineageLog


def image(obj) -> dict:
    if not isinstance(obj, dict):
        obj = json.loads(obj.to_json())
    obj = json.loads(json.dumps(obj))
    for k in VOLATILE:
        obj["totals"].pop(k, None)
    # window meta carries per-host blocks + the fencing term; neither is
    # analysis content — bit-identity is about the counters
    obj["totals"].pop("window", None)
    obj["totals"].pop("chunks", None)
    return obj


@pytest.fixture(scope="module")
def corpus(tmp_path_factory):
    """v4+v6 packed ruleset + 800 mixed lines (same geometry as the
    distserve suite, so the in-process jit caches stay warm)."""
    td = tmp_path_factory.mktemp("failover")
    cfg_text = synth.synth_config(
        n_acls=2, rules_per_acl=8, seed=0, v6_fraction=0.25
    )
    rs = aclparse.parse_asa_config(cfg_text, "fw1")
    packed = pack.pack_rulesets([rs])
    prefix = str(td / "rules")
    pack.save_packed(packed, prefix)
    t = synth.synth_tuples(packed, 600, seed=1)
    lines = synth.render_syslog(packed, t, seed=1)
    t6 = synth.synth_tuples6(packed, 200, seed=2)
    lines += synth.render_syslog6(packed, t6, seed=3)
    return packed, prefix, lines, str(td)


RUN_CFG = dict(batch_size=128, prefetch_depth=0)
WL = 200
TTL = 3.0  # generous enough that a 1-core jit-compile pause cannot
# age a healthy holder past self-fence mid-test


def dist_cfg(**kw) -> AnalysisConfig:
    return AnalysisConfig(**{**RUN_CFG, "mesh_shape": "hybrid", **kw})


def dist_scfg(serve_dir, **kw) -> ServeConfig:
    return ServeConfig(**{
        "listen": ("tcp:127.0.0.1:0",), "window_lines": WL,
        "serve_dir": str(serve_dir), "http": "off",
        "checkpoint_every_windows": 0, "reload_watch": False,
        **kw,
    })


def host_slices(lines, n_hosts, windows, wl=WL):
    return {
        r: [
            ln
            for w in range(windows)
            for ln in lines[(w * n_hosts + r) * wl:(w * n_hosts + r + 1) * wl]
        ]
        for r in range(n_hosts)
    }


def start_dist(prefix, cfg, scfg, dscfg, **kw):
    drv = DistServeDriver(prefix, cfg, scfg, dscfg, **kw)
    out: dict = {}

    def runner():
        try:
            out["summary"] = drv.run()
        except BaseException as e:  # surfaced by finish_dist()
            out["error"] = e

    th = threading.Thread(target=runner, daemon=True)
    th.start()
    return drv, th, out


def finish_dist(th, out, timeout=240):
    th.join(timeout=timeout)
    assert not th.is_alive(), "distributed serve hung"
    if "error" in out:
        raise out["error"]
    return out["summary"]


def host_tcp(drv, rank):
    with drv._lock:
        h = drv.hosts.get(rank)
        addrs = dict(h.addresses) if h else {}
    for lbl, ad in addrs.items():
        if lbl.startswith("tcp"):
            return tuple(ad)
    return None


def wait_for(pred, timeout=120, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.05)
    raise AssertionError(f"timed out waiting for {msg}")


def wait_hosts_up(drv, out, n_hosts, timeout=120):
    wait_for(
        lambda: out.get("error")
        or all(host_tcp(drv, r) for r in range(n_hosts)),
        timeout, "host listeners",
    )
    if "error" in out:
        raise out["error"]


def send_tcp(addr, lines):
    s = socket.create_connection(addr)
    s.sendall(("\n".join(lines) + "\n").encode())
    s.close()


def read_json(path):
    with open(path, "r", encoding="utf-8") as f:
        return json.load(f)


# ---------------------------------------------------------------------------
# Lease protocol: exactly one winner per term, steal, fence, release.
# ---------------------------------------------------------------------------

def test_lease_exactly_one_winner_per_term(tmp_path):
    """Racing acquirers each win a DISTINCT term — the O_EXCL claim
    file makes a term's winner unique even under a thundering herd."""
    d = str(tmp_path / "lease")
    leases = [
        SupervisorLease(d, holder=f"sup-{i}", ttl_sec=0.2) for i in range(4)
    ]
    won = []
    lock = threading.Lock()

    def go(lease):
        t = lease.acquire(timeout=60)
        with lock:
            won.append(t)

    ths = [threading.Thread(target=go, args=(L,)) for L in leases]
    for t in ths:
        t.start()
    for t in ths:
        t.join(timeout=60)
    assert sorted(won) == [1, 2, 3, 4]  # one winner per term, no reuse
    for term in (1, 2, 3, 4):
        assert os.path.exists(
            os.path.join(d, f"term-{term:020d}.claim")
        )


def test_lease_steal_fence_and_release(tmp_path):
    d = str(tmp_path / "lease")
    a = SupervisorLease(d, holder="sup-a", ttl_sec=0.2)
    assert a.acquire(timeout=10) == 1
    # a successor must wait out the 1.5x-TTL staleness window before
    # it can steal: the incumbent provably self-fences (age > TTL)
    # strictly first
    t0 = time.monotonic()
    b = SupervisorLease(d, holder="sup-b", ttl_sec=0.2)
    assert b.acquire(timeout=10) == 2
    assert time.monotonic() - t0 >= 0.2
    assert a.age() > a.ttl  # the loser had already self-fenced by age
    b._write_stamp()

    fenced = []
    a._on_fenced = lambda: fenced.append(1)
    a.renew()
    a.renew()  # idempotent: the callback fires exactly once
    assert fenced == [1]
    assert a.fenced
    assert a.observed() == (2, "sup-b")

    # a fenced holder must NOT clear the winner's stamp on release
    a.release()
    assert read_json(os.path.join(d, "lease.json"))["holder"] == "sup-b"
    b.release()
    b.release()  # idempotent
    assert not os.path.exists(os.path.join(d, "lease.json"))
    # with the stamp cleared, the next holder only waits on claim-file
    # staleness, never on a dead stamp
    c = SupervisorLease(d, holder="sup-c", ttl_sec=0.2)
    assert c.acquire(timeout=10) == 3


def test_lease_fault_seams(tmp_path):
    """lease.acquire / lease.renew chaos seams: typed at startup,
    self-fence-by-age when renewals stop (the heartbeat never renews
    again after an injected partition)."""
    with faults.armed(faults.FaultPlan.parse("lease.acquire@1")):
        with pytest.raises(InjectedFault):
            SupervisorLease(
                str(tmp_path / "l1"), holder="x", ttl_sec=0.2
            ).acquire(timeout=5)

    lease = SupervisorLease(str(tmp_path / "l2"), holder="x", ttl_sec=0.2)
    assert lease.acquire(timeout=5) == 1
    with faults.armed(faults.FaultPlan.parse("lease.renew@1")):
        lease.start_heartbeat()
        wait_for(lambda: lease.fenced, timeout=10, msg="self-fence by age")
    assert lease.renews == 0  # the partitioned heartbeat never renewed
    lease.release()


def test_fence_fingerprint_roundtrip():
    assert ckpt.fence_fingerprint("abc-distserve", 7) == "abc-distserve-t7"
    assert ckpt.split_fence("abc-distserve-t7") == ("abc-distserve", 7)
    # a pre-failover snapshot has no suffix: term 0, full fp preserved
    assert ckpt.split_fence("abc-distserve") == ("abc-distserve", 0)
    assert ckpt.split_fence("weird-t") == ("weird-t", 0)


# ---------------------------------------------------------------------------
# Epoch spool: durability, fault seam, byte-level corruption fuzz.
# ---------------------------------------------------------------------------

def test_spool_roundtrip_and_fault_seam(tmp_path):
    sp = EpochSpool(str(tmp_path / "spool"), budget_bytes=1 << 20)
    with faults.armed(faults.FaultPlan.parse("dist.epoch.spool@1")):
        with pytest.raises(InjectedFault):
            sp.append_epoch(b"epoch-0")
    assert sp.append_epoch(b"epoch-0") == 0  # the failed try burned no seq
    assert sp.append_epoch(b"epoch-1") == 1
    sp.close()
    sp2 = EpochSpool(str(tmp_path / "spool"), budget_bytes=1 << 20)
    assert [(s, p) for s, p in sp2.replay(0)] == [
        (0, b"epoch-0"), (1, b"epoch-1"),
    ]
    sp2.close()


def test_spool_corruption_fuzz(tmp_path):
    """Truncate at EVERY offset and flip a bit at EVERY byte of a spool
    segment: the reader must yield only exact original records (a
    prefix-consistent subset), quarantining or clipping the rest —
    never crash, never hang, never a wrong payload."""
    src = tmp_path / "pristine"
    sp = EpochSpool(str(src), budget_bytes=1 << 20)
    payloads = [bytes([0x40 + i]) * (20 + i) for i in range(3)]
    for p in payloads:
        sp.append_epoch(p)
    sp.close()
    (seg,) = [n for n in os.listdir(src) if n.endswith(".wal")]
    pristine = (src / seg).read_bytes()

    scratch = tmp_path / "scratch"

    def check(blob: bytes) -> None:
        if scratch.exists():
            shutil.rmtree(scratch)
        scratch.mkdir()
        (scratch / seg).write_bytes(blob)
        try:
            spool = EpochSpool(str(scratch), budget_bytes=1 << 20)
        except WalQuarantine:
            return  # typed refusal at open is a legal outcome
        try:
            seen = -1
            for seq, payload in spool.replay(0):
                assert payload == payloads[seq], (
                    f"corrupt spool yielded a WRONG payload at seq {seq}"
                )
                assert seq > seen
                seen = seq
        finally:
            spool.close()

    check(pristine)  # the harness itself round-trips
    for cut in range(len(pristine)):
        check(pristine[:cut])
    for off in range(len(pristine)):
        blob = bytearray(pristine)
        blob[off] ^= 0x40
        check(bytes(blob))


def test_epoch_payload_byte_fuzz():
    """RAEP1 frames: every truncation and every single-byte flip is a
    typed AnalysisError — the CRC spans both bodies and the length
    fields self-check, so no mutation can decode silently wrong."""
    arrays = {"counts": np.arange(6, dtype=np.uint32)}
    extra = {"rank": 0, "meta": {"id": 1, "lines": 6}, "wal_next": 3}
    payload = pack_epoch_payload(arrays, extra)
    unpack_epoch_payload(payload)  # sanity: pristine decodes
    for cut in range(len(payload)):
        with pytest.raises(AnalysisError):
            unpack_epoch_payload(payload[:cut])
    for off in range(len(payload)):
        torn = bytearray(payload)
        torn[off] ^= 0x10
        with pytest.raises(AnalysisError):
            unpack_epoch_payload(bytes(torn))


# ---------------------------------------------------------------------------
# Replay brain: frontier, gap markers, corrupt-record refusal (unit).
# ---------------------------------------------------------------------------

def _bare_supervisor(serve_dir, next_wid=0):
    drv = DistServeDriver.__new__(DistServeDriver)
    drv._lock = threading.Lock()
    drv.dscfg = DistServeConfig(hosts=2, workers="thread", spool_budget_mb=4)
    drv.scfg = dist_scfg(serve_dir)
    drv.next_wid = next_wid
    drv.skipped_windows = []
    drv._host_wal_restored = {}
    drv.spool_replayed_total = 0
    drv.replay_windows_total = 0
    drv.replay_lag_windows = 0
    drv.replay_refused_total = 0
    published = []
    drv._publish_window = lambda w, recs, dead, missing, path="live": (
        published.append((w, sorted(recs), dead, missing))
    )
    return drv, published


def _epoch(rank, wid):
    return pack_epoch_payload(
        {"c": np.arange(4, dtype=np.uint32) + wid},
        {"rank": rank, "meta": {"id": wid}, "wal_next": 100 * wid + rank},
    )


def test_replay_frontier_gaps_and_refusal(tmp_path):
    sd = str(tmp_path / "serve")
    drv, published = _bare_supervisor(sd)
    # host 0 spooled w0..w2; host 1 spooled w0 and w2 (w1 lost) plus one
    # record that is WAL-valid but not a decodable RAEP1 frame
    for rank, wids in ((0, [0, 1, 2]), (1, [0, 2])):
        sp = EpochSpool(drv._host_spool_dir(rank), budget_bytes=4 << 20)
        for w in wids:
            sp.append_epoch(_epoch(rank, w))
        sp.append_epoch(b"RAEP1 but not really a frame")
        sp.close()

    drv._replay_spools()
    assert published == [
        (0, [0, 1], [], []),
        (1, [0], [], [1]),  # host 1 spooled PAST w1: typed host_missing
        (2, [0, 1], [], []),
    ]
    assert drv.replay_refused_total == 2  # one garbage record per host
    assert drv.spool_replayed_total == 5
    assert drv.replay_windows_total == 3
    assert drv.next_wid == 3
    assert drv.skipped_windows == []
    # replayed WAL cursors supersede checkpointed ones (no double replay
    # when the host itself rejoins)
    assert drv._host_wal_restored == {0: 200, 1: 201}

    # a restored frontier is respected: nothing below it re-publishes
    drv2, published2 = _bare_supervisor(sd, next_wid=2)
    drv2._replay_spools()
    assert published2 == [(2, [0, 1], [], [])]

    # a window BELOW every surviving record is skipped loudly, never a
    # frontier hang: drop both hosts' w0+w1 spools, keep w2 only
    sd3 = str(tmp_path / "serve3")
    drv3, published3 = _bare_supervisor(sd3)
    sp = EpochSpool(drv3._host_spool_dir(0), budget_bytes=4 << 20)
    sp.append_epoch(_epoch(0, 2))
    sp.close()
    drv3._replay_spools()
    assert published3 == [(2, [0], [], [])]
    assert drv3.skipped_windows == [0, 1]


# ---------------------------------------------------------------------------
# Partition tolerance: transient recovery, park + heal, zero drops.
# ---------------------------------------------------------------------------

def test_ship_transient_recovery_and_spool_degrade(corpus):
    """A transient merge-plane burst (dist.epoch.ship@1:2 — under the
    4-attempt budget) recovers in place; a dead spool volume
    (dist.epoch.spool@1:9) degrades failover durability typed while
    the live merge keeps publishing."""
    packed, prefix, lines, td = corpus
    union = lines[:WL]
    sd = os.path.join(td, "transient")
    with faults.armed(faults.FaultPlan.parse(
        "dist.epoch.ship@1:2,dist.epoch.spool@1:9"
    )):
        drv, th, out = start_dist(
            prefix, dist_cfg(), dist_scfg(sd, max_windows=1),
            DistServeConfig(
                hosts=1, workers="thread", merge_timeout_sec=600,
                lease_ttl_sec=TTL,
            ),
        )
        wait_hosts_up(drv, out, 1)
        send_tcp(host_tcp(drv, 0), union)
        summary = finish_dist(th, out)
    assert summary["windows_published"] == 1
    assert summary["lines_total"] == len(union)
    assert summary["drops"] == 0
    ship = summary["retry"]["dist.epoch.ship"]
    assert ship["recoveries"] >= 1  # retried in place, never parked
    assert ship["giveups"] == 0
    # the spool died typed; serving continued
    assert "spool" in summary["hosts"]["0"]["summary"]["degraded"]
    meta = read_json(
        os.path.join(sd, "window-000000.json")
    )["totals"]["window"]
    assert "incomplete" not in meta


def test_partition_park_heal_zero_silent_drops(corpus):
    """Budget exhaustion (dist.epoch.ship@1:6) enters partition mode:
    the epoch parks in the backlog (spool-backed), /health carries
    partition:<rank>, and the heal probes drain everything in window
    order — all windows publish complete, zero silent drops."""
    packed, prefix, lines, td = corpus
    windows = 2
    union = lines[:windows * WL]
    sd = os.path.join(td, "heal")
    with faults.armed(faults.FaultPlan.parse("dist.epoch.ship@1:6")):
        drv, th, out = start_dist(
            prefix, dist_cfg(), dist_scfg(sd, max_windows=windows),
            DistServeConfig(
                hosts=1, workers="thread", merge_timeout_sec=600,
                lease_ttl_sec=TTL,
            ),
        )
        wait_hosts_up(drv, out, 1)
        send_tcp(host_tcp(drv, 0), union)
        # the retry ladder exhausts (hits 1-4), parks, and the host
        # marks itself partitioned — visible in the supervisor's gauges
        wait_for(
            lambda: out.get("error")
            or "partition:0" in (drv.hosts[0].degraded or []),
            msg="partition degraded marker",
        )
        summary = finish_dist(th, out)
    assert summary["windows_published"] == windows
    assert summary["lines_total"] == len(union)
    assert summary["drops"] == 0
    assert summary["retry"]["dist.epoch.ship"]["giveups"] >= 1  # parked
    # the heal drained the backlog: the host ends un-degraded
    assert summary["hosts"]["0"]["summary"]["degraded"] == []
    for w in range(windows):
        meta = read_json(
            os.path.join(sd, f"window-{w:06d}.json")
        )["totals"]["window"]
        assert "incomplete" not in meta, (w, meta)
        assert meta["lines"] == WL


# ---------------------------------------------------------------------------
# The tentpole: kill the supervisor, elect a successor, replay the
# spools, publish bit-identically. Exactly one publisher per term.
# ---------------------------------------------------------------------------

def test_supervisor_failover_replay_bit_identity(corpus):
    packed, prefix, lines, td = corpus
    n_hosts, windows = 2, 2
    union = lines[:n_hosts * windows * WL]
    streams = host_slices(union, n_hosts, windows)

    # control: identical per-host streams, no chaos — exactly what the
    # victim supervisor WOULD have published
    ctl_dir = os.path.join(td, "ctl")
    drv, th, out = start_dist(
        prefix, dist_cfg(), dist_scfg(ctl_dir, max_windows=windows),
        DistServeConfig(hosts=n_hosts, workers="thread", lease_ttl_sec=TTL),
    )
    wait_hosts_up(drv, out, n_hosts)
    for r in range(n_hosts):
        send_tcp(host_tcp(drv, r), streams[r])
    sc = finish_dist(th, out)
    assert sc["windows_published"] == windows and sc["drops"] == 0
    assert sc["term"] == 1

    # victim: a full merge-plane partition parks EVERY epoch in the
    # durable spools (none reach the supervisor), then the supervisor
    # dies abruptly with all windows unpublished
    fo_dir = os.path.join(td, "failover")
    with faults.armed(faults.FaultPlan.parse("dist.epoch.ship@1:99")):
        drv, th, out = start_dist(
            prefix, dist_cfg(), dist_scfg(fo_dir, max_windows=windows),
            DistServeConfig(
                hosts=n_hosts, workers="thread", merge_timeout_sec=600,
                lease_ttl_sec=TTL,
            ),
        )
        wait_hosts_up(drv, out, n_hosts)
        assert drv.term == 1
        assert drv.health()["term"] == 1
        assert drv.metrics_gauges()["leader_term"] == 1
        for r in range(n_hosts):
            send_tcp(host_tcp(drv, r), streams[r])
        wait_for(
            lambda: out.get("error") or all(
                drv.host_gauges().get(str(r), {}).get("spool_seq", 0)
                >= windows
                for r in range(n_hosts)
            ),
            msg="all epochs durably spooled",
        )
        wait_for(
            lambda: out.get("error") or all(
                f"partition:{r}" in (drv.hosts[r].degraded or [])
                for r in range(n_hosts)
            ),
            msg="partition markers on both hosts",
        )
        assert drv.windows_published == 0  # term 1 published NOTHING
        drv.kill_supervisor()
        with pytest.raises(AnalysisError, match="supervisor killed"):
            finish_dist(th, out)
    assert not os.path.exists(os.path.join(fo_dir, "window-000000.json"))

    # successor: wins the next term, replays both spools past the
    # (empty) frontier, and publishes every window bit-identically
    drv, th, out = start_dist(
        prefix, dist_cfg(resume=True), dist_scfg(fo_dir, max_windows=windows),
        DistServeConfig(
            hosts=n_hosts, workers="thread", merge_timeout_sec=600,
            lease_ttl_sec=TTL,
        ),
    )
    s2 = finish_dist(th, out)
    assert s2["term"] == 2  # exactly one publisher per term
    assert s2["windows_published"] == windows
    assert s2["lines_total"] == len(union)
    assert s2["drops"] == 0
    assert s2["failover"]["spool_replayed"] == n_hosts * windows
    assert s2["failover"]["replay_windows"] == windows
    assert s2["failover"]["replay_refused"] == 0
    assert s2["skipped_windows"] == []
    for w in range(windows):
        a = read_json(os.path.join(fo_dir, f"window-{w:06d}.json"))
        b = read_json(os.path.join(ctl_dir, f"window-{w:06d}.json"))
        assert a["totals"]["window"]["term"] == 2
        assert b["totals"]["window"]["term"] == 1
        assert a.get("talkers") == b.get("talkers"), f"window {w} talkers"
        assert image(a) == image(b), f"window {w} diverged"
        # lineage replay-identity law (DESIGN §24): the record is a
        # deterministic function of the delivered lines, so the
        # failover-replayed window's core — per-host WAL ranges, drop
        # counts, host set — is IDENTICAL to the control publication;
        # only the volatile envelope (term, path, timestamp) moves.
        # payload_crc covers the exact epoch BYTES (which carry run-local
        # wall-clock meta), so it is compared within-run below, against
        # the spool, not across the two runs
        la, lb = a["totals"]["lineage"], b["totals"]["lineage"]

        def no_crc(rec):
            core = lineage_core(rec)
            core["hosts"] = [
                {k: v for k, v in h.items() if k != "payload_crc"}
                for h in core["hosts"]
            ]
            return core

        assert no_crc(la) == no_crc(lb), f"window {w} lineage"
        assert la["kind"] == "dist" and len(la["hosts"]) == n_hosts
        assert all(h["payload_crc"] for h in la["hosts"])
        assert (la["term"], la["path"]) == (2, "replay")
        assert (lb["term"], lb["path"]) == (1, "live")
    # within the failover dir the law is exact: the spool still holds
    # the bytes the replay consumed, and the stamped payload_crc is
    # crc32 of exactly those bytes — the same stamp a live E-frame
    # arrival would have produced for the same payload
    import zlib as zlib_mod
    fo_lineage = {
        r["window"]: r
        for r in LineageLog.read(os.path.join(fo_dir, LineageLog.NAME))
    }
    for r in range(n_hosts):
        spool = EpochSpool(os.path.join(fo_dir, f"host-{r}", "spool"))
        try:
            for _seq, payload in spool.replay(0):
                arrays, extra = unpack_epoch_payload(payload)
                wid = int(extra["meta"]["id"])
                hrec = next(
                    h for h in fo_lineage[wid]["hosts"] if h["rank"] == r
                )
                assert hrec["payload_crc"] == zlib_mod.crc32(payload) & 0xFFFFFFFF
        finally:
            spool.close()
    ca = read_json(os.path.join(fo_dir, "cumulative.json"))
    cb = read_json(os.path.join(ctl_dir, "cumulative.json"))
    assert image(ca) == image(cb)
    # the successor's ledger alone reconstructs the full frontier
    fr = lineage_frontier(LineageLog.read(os.path.join(fo_dir, LineageLog.NAME)))
    assert fr["windows"] == windows and fr["last_complete"] == windows - 1
    assert fr["first_incomplete"] is None and fr["gaps"] == []


def test_dual_supervisor_race_fences_the_stale_one(corpus):
    """Induced split brain: while supervisor A holds term 1, a rival
    claims term 2.  A's very next renewal observes the higher term,
    stops publishing typed (SupervisorFenced, exit code 8) — it can
    never produce a second publication for a window the winner owns."""
    packed, prefix, lines, td = corpus
    sd = os.path.join(td, "race")
    drv, th, out = start_dist(
        prefix, dist_cfg(), dist_scfg(sd, max_windows=10),
        DistServeConfig(
            hosts=1, workers="thread", merge_timeout_sec=600,
            lease_ttl_sec=30.0,
        ),
    )
    wait_hosts_up(drv, out, 1)
    send_tcp(host_tcp(drv, 0), lines[:WL])
    wait_for(
        lambda: out.get("error") or drv.windows_published >= 1,
        msg="window 0 published under term 1",
    )
    w0 = read_json(os.path.join(sd, "window-000000.json"))
    assert w0["totals"]["window"]["term"] == 1

    # the rival wins term 2 (same O_EXCL protocol the lease uses)
    claim = os.path.join(drv._lease_dir(), f"term-{2:020d}.claim")
    fd = os.open(claim, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
    os.close(fd)
    drv._lease.renew()  # next heartbeat: observe, fence, stop
    with pytest.raises(SupervisorFenced) as ei:
        finish_dist(th, out)
    assert "term 2" in str(ei.value)
    assert exit_code_for(ei.value) == 8
    assert EXIT_CODE_NAMES[8] == "supervisor-fenced"
    # the fenced gate also refuses directly — the publication-path half
    with pytest.raises(SupervisorFenced):
        drv._check_fenced()
    # nothing was published past the fence, and window 0 still carries
    # exactly the term that produced it
    assert not os.path.exists(os.path.join(sd, "window-000001.json"))
    assert read_json(
        os.path.join(sd, "window-000000.json")
    )["totals"]["window"]["term"] == 1


def test_registry_failover_audits_clean():
    from ruleset_analysis_tpu.verify.registry import (
        audit_distserve,
        audit_retry,
    )

    assert audit_retry() == []
    assert audit_distserve() == []


# ---------------------------------------------------------------------------
# CLI supervisor-SIGKILL e2e (spawned interpreter: slow-marked).
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_cli_supervisor_sigkill_failover_e2e(corpus):
    """SIGKILL the whole CLI supervisor process mid-deployment; an
    in-process successor wins the next term off the on-disk lease,
    replays the spools, republishes the already-published window
    bit-identically (idempotent republication) and publishes the
    window the dead supervisor never merged."""
    packed, prefix, lines, td = corpus
    wl, windows, n_hosts = 100, 2, 2
    union = lines[:n_hosts * windows * wl]
    streams = host_slices(union, n_hosts, windows, wl=wl)
    sd = os.path.join(td, "sigkill")

    # two consecutive fixed ports (host rank offsets the listen spec)
    for _ in range(20):
        s0 = socket.socket()
        s0.bind(("127.0.0.1", 0))
        port = s0.getsockname()[1]
        s1 = socket.socket()
        try:
            s1.bind(("127.0.0.1", port + 1))
        except OSError:
            continue
        finally:
            s1.close()
            s0.close()
        break

    proc = subprocess.Popen(
        [
            sys.executable, "-m", "ruleset_analysis_tpu.cli", "serve",
            "--ruleset", prefix, "--listen", f"tcp:127.0.0.1:{port}",
            "--window", f"lines:{wl}", "--max-windows", str(windows),
            "--serve-dir", sd, "--batch-size", "128",
            "--mesh", "hybrid", "--distributed", "--dist-hosts", "2",
            "--dist-workers", "thread", "--dist-merge-timeout", "600",
            "--dist-lease-ttl", "2",
        ],
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
    )
    try:
        ep_path = os.path.join(sd, "endpoint.json")
        wait_for(
            lambda: os.path.exists(ep_path) or proc.poll() is not None,
            timeout=600, msg="CLI endpoint",
        )
        assert proc.poll() is None, "CLI supervisor died at startup"
        ep = read_json(ep_path)
        assert ep["term"] == 1

        def up(p):
            try:
                socket.create_connection(("127.0.0.1", p), timeout=0.5).close()
                return True
            except OSError:
                return False

        wait_for(lambda: up(port) and up(port + 1), timeout=600,
                 msg="host listeners")
        send_tcp(("127.0.0.1", port), streams[0])  # both windows
        send_tcp(("127.0.0.1", port + 1), streams[1][:wl])  # window 0 only
        w0_path = os.path.join(sd, "window-000000.json")
        wait_for(lambda: os.path.exists(w0_path) or proc.poll() is not None,
                 timeout=600, msg="window 0 published")
        assert proc.poll() is None

        # window 1 is merge-pending (host 1 silent, huge merge timeout):
        # host 0's w1 epoch is already durably spooled — watch rank 0's
        # /metrics until the supervisor itself says so
        host, hport = ep["http"]

        def pending():
            try:
                with urllib.request.urlopen(
                    f"http://{host}:{hport}/metrics", timeout=2
                ) as r:
                    g = json.loads(r.read())
            except OSError:
                return False
            return g.get("gauges", g).get("merge_pending_windows", 0) >= 1

        wait_for(pending, timeout=600, msg="window 1 merge-pending")
        w0_before = read_json(w0_path)

        os.kill(ep["pid"], signal.SIGKILL)
        proc.wait(timeout=60)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=60)

    drv, th, out = start_dist(
        prefix,
        dist_cfg(resume=True),
        dist_scfg(sd, window_lines=wl, max_windows=windows),
        DistServeConfig(
            hosts=n_hosts, workers="thread", merge_timeout_sec=600,
            lease_ttl_sec=2.0,
        ),
    )
    s2 = finish_dist(th, out, timeout=900)
    assert s2["term"] == 2
    assert s2["windows_published"] == windows
    # the victim's ring checkpoint (default cadence: every window)
    # covered w0, so the restored frontier is 1 and replay publishes
    # exactly the window the dead supervisor never merged — a fenced
    # t1 fingerprint restored by a t2 holder, never a refusal
    assert s2["failover"]["replay_windows"] == 1

    # the checkpoint-covered window is NOT republished: it still
    # carries the term that produced it, byte for byte
    w0_after = read_json(w0_path)
    assert w0_after["totals"]["window"]["term"] == 1
    assert image(w0_after) == image(w0_before)
    assert w0_after.get("talkers") == w0_before.get("talkers")
    # window 1: host 0's spooled epoch, published by the successor
    w1 = read_json(os.path.join(sd, "window-000001.json"))
    assert w1["totals"]["window"]["term"] == 2
    assert set(w1["totals"]["window"]["hosts"]) == {"0"}
    assert w1["totals"]["window"]["lines"] == wl
