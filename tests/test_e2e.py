"""End-to-end: full TPU-backend stream run vs the exact oracle (SURVEY.md §5)."""

import numpy as np
import pytest

pytest.importorskip("jax")

from ruleset_analysis_tpu.config import AnalysisConfig, SketchConfig
from ruleset_analysis_tpu.hostside import aclparse, oracle, pack, synth
from ruleset_analysis_tpu.runtime.stream import run_stream


@pytest.fixture(scope="module")
def corpus():
    cfg_text = synth.synth_config(n_acls=3, rules_per_acl=12, seed=21)
    rs = aclparse.parse_asa_config(cfg_text, "fw1")
    packed = pack.pack_rulesets([rs])
    tuples = synth.synth_tuples(packed, 3000, seed=21)
    lines = synth.render_syslog(packed, tuples, seed=21)
    res = oracle.Oracle([rs]).consume(list(lines))
    return packed, rs, lines, res


def run_tpu(packed, lines, **kw):
    cfg = AnalysisConfig(
        backend="tpu",
        batch_size=512,
        sketch=SketchConfig(cms_width=1 << 12, cms_depth=4, hll_p=8),
        **kw,
    )
    return run_stream(packed, iter(lines), cfg, topk=5)


def test_tpu_exact_counts_match_oracle(corpus):
    packed, rs, lines, res = corpus
    rep = run_tpu(packed, lines)
    got = {
        (e["firewall"], e["acl"], e["index"]): e["hits"]
        for e in rep.per_rule
        if e["hits"] > 0
    }
    exp = {k: v for k, v in res.hits.items()}
    assert got == exp
    assert rep.totals["lines_matched"] == res.lines_matched


def test_tpu_unused_rules_match_oracle_exactly(corpus):
    packed, rs, lines, res = corpus
    rep = run_tpu(packed, lines)
    exact_unused = res.unused_rules([rs])
    assert rep.unused == exact_unused
    assert oracle.unused_rule_recall(exact_unused, rep.unused) == 1.0


def test_tpu_sketched_backend_recall(corpus):
    """CMS-only counts (exact disabled) still recover the unused set (>=99%)."""
    packed, rs, lines, res = corpus
    rep = run_tpu(packed, lines, exact_counts=False)
    exact_unused = res.unused_rules([rs])
    recall = oracle.unused_rule_recall(exact_unused, rep.unused)
    assert recall >= 0.99
    # CMS is one-sided: rules with real hits can never be reported unused
    # unless CMS says zero — impossible; so only over-counting can occur,
    # shrinking (never growing) the unused set spuriously... verify direction:
    assert set(rep.unused) <= set(exact_unused)


def test_tpu_unique_sources_close_to_oracle(corpus):
    packed, rs, lines, res = corpus
    rep = run_tpu(packed, lines)
    for e in rep.per_rule:
        key = (e["firewall"], e["acl"], e["index"])
        if key in res.sources and "unique_sources" in e:
            true = len(res.sources[key])
            est = e["unique_sources"]
            if true >= 20:
                assert abs(est - true) / true < 0.25, (key, true, est)


def test_tpu_talkers_cover_oracle_heavy_hitters(corpus):
    packed, rs, lines, res = corpus
    rep = run_tpu(packed, lines)
    from ruleset_analysis_tpu.hostside.aclparse import u32_to_ip

    for (fw, acl), counter in res.talkers.items():
        # oracle talker identities are (family, addr); this corpus is v4
        heavy = [src for (_fam, src), c in counter.most_common(3) if c >= 50]
        if not heavy:
            continue
        got = {ip for ip, _ in rep.talkers.get(f"{fw} {acl}", [])}
        covered = sum(1 for ip in heavy if u32_to_ip(ip) in got)
        assert covered >= len(heavy) - 1, (fw, acl, heavy, got)


def test_batch_size_invariance(corpus):
    """Chunking must not change exact results (mergeability/order-invariance)."""
    packed, rs, lines, res = corpus
    r1 = run_tpu(packed, lines)
    cfg2 = AnalysisConfig(
        backend="tpu", batch_size=257, sketch=SketchConfig(cms_width=1 << 12, cms_depth=4, hll_p=8)
    )
    r2 = run_stream(packed, iter(lines), cfg2, topk=5)
    h1 = {(e["firewall"], e["acl"], e["index"]): e["hits"] for e in r1.per_rule}
    h2 = {(e["firewall"], e["acl"], e["index"]): e["hits"] for e in r2.per_rule}
    assert h1 == h2
    assert r1.unused == r2.unused
