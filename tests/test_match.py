"""Device first-match kernel vs the exact oracle (golden semantics, SURVEY.md §5)."""

import numpy as np
import pytest

from ruleset_analysis_tpu.hostside import aclparse, oracle, pack, synth
from ruleset_analysis_tpu.ops import match as match_ops

jnp = pytest.importorskip("jax.numpy")


def cols_from_batch(batch_np):
    b = jnp.asarray(np.ascontiguousarray(batch_np.T))
    return (
        {
            "acl": b[pack.T_ACL],
            "proto": b[pack.T_PROTO],
            "src": b[pack.T_SRC],
            "sport": b[pack.T_SPORT],
            "dst": b[pack.T_DST],
            "dport": b[pack.T_DPORT],
        },
        b[pack.T_VALID],
    )


CFG = """\
hostname fw1
access-list OUT extended permit tcp any host 10.0.0.5 eq 443
access-list OUT extended permit tcp any host 10.0.0.5 eq 80
access-list OUT extended deny tcp any 10.0.0.0 255.255.255.0
access-list OUT extended permit ip any any
access-list DMZ extended permit udp 10.9.0.0 255.255.0.0 any eq 53
"""


def make_packed(cfg=CFG):
    rs = aclparse.parse_asa_config(cfg, "fw1")
    return pack.pack_rulesets([rs]), rs


def tuples(rows):
    out = np.zeros((len(rows), pack.TUPLE_COLS), dtype=np.uint32)
    for i, r in enumerate(rows):
        out[i] = r
    return out


def test_first_match_golden():
    packed, _ = make_packed()
    gid = packed.acl_gid[("fw1", "OUT")]
    ip = aclparse.ip_to_u32
    batch = tuples(
        [
            (gid, 6, ip("1.2.3.4"), 999, ip("10.0.0.5"), 443, 1),  # rule 1
            (gid, 6, ip("1.2.3.4"), 999, ip("10.0.0.5"), 80, 1),  # rule 2 (not 3)
            (gid, 6, ip("1.2.3.4"), 999, ip("10.0.0.9"), 80, 1),  # rule 3 deny
            (gid, 17, ip("9.9.9.9"), 53, ip("8.8.8.8"), 53, 1),  # rule 4 catch-all
        ]
    )
    cols, _ = cols_from_batch(batch)
    keys = match_ops.match_keys(cols, jnp.asarray(packed.rules), jnp.asarray(packed.deny_key))
    got = [packed.key_meta[int(k)].index for k in np.asarray(keys)]
    assert got == [1, 2, 3, 4]


def test_implicit_deny_key():
    packed, _ = make_packed()
    gid = packed.acl_gid[("fw1", "DMZ")]
    ip = aclparse.ip_to_u32
    batch = tuples([(gid, 6, ip("1.1.1.1"), 1, ip("2.2.2.2"), 2, 1)])
    cols, _ = cols_from_batch(batch)
    keys = match_ops.match_keys(cols, jnp.asarray(packed.rules), jnp.asarray(packed.deny_key))
    meta = packed.key_meta[int(keys[0])]
    assert meta.implicit_deny and meta.acl == "DMZ"


def test_acl_isolation():
    """A line on ACL DMZ must not match OUT's rules even if ranges align."""
    packed, _ = make_packed()
    gid_dmz = packed.acl_gid[("fw1", "DMZ")]
    ip = aclparse.ip_to_u32
    # would hit OUT rule 4 (permit ip any any) if ACL weren't checked
    batch = tuples([(gid_dmz, 6, ip("3.3.3.3"), 5, ip("4.4.4.4"), 6, 1)])
    cols, _ = cols_from_batch(batch)
    keys = match_ops.match_keys(cols, jnp.asarray(packed.rules), jnp.asarray(packed.deny_key))
    assert packed.key_meta[int(keys[0])].implicit_deny


@pytest.mark.parametrize("rule_block", [4, 512])
def test_scan_path_equals_single_block(rule_block):
    """Blocked rule-axis scan must equal the unblocked result (synthetic corpus)."""
    cfg_text = synth.synth_config(n_acls=3, rules_per_acl=20, seed=11)
    rs = aclparse.parse_asa_config(cfg_text, "fw1")
    packed = pack.pack_rulesets([rs])
    batch_np = synth.synth_tuples(packed, 256, seed=11)
    cols, _ = cols_from_batch(batch_np)
    rules_padded = jnp.asarray(
        np.ascontiguousarray(
            __import__(
                "ruleset_analysis_tpu.models.pipeline", fromlist=["pad_rules"]
            ).pad_rules(packed.rules, rule_block)
        )
    )
    deny = jnp.asarray(packed.deny_key)
    a = match_ops.match_keys(cols, rules_padded, deny, rule_block)
    b = match_ops.match_keys(cols, jnp.asarray(packed.rules), deny, packed.rules.shape[0] + 1)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_match_keys_agree_with_oracle_on_synthetic_corpus():
    cfg_text = synth.synth_config(n_acls=4, rules_per_acl=24, seed=5)
    rs = aclparse.parse_asa_config(cfg_text, "fw1")
    packed = pack.pack_rulesets([rs])
    tuples_np = synth.synth_tuples(packed, 1000, seed=5)
    lines = synth.render_syslog(packed, tuples_np, seed=5)

    orc = oracle.Oracle([rs])
    res = orc.consume(lines)

    packer = pack.LinePacker(packed)
    batch_np = packer.pack_lines(lines, batch_size=1024)
    cols, valid = cols_from_batch(batch_np)
    keys = match_ops.match_keys(cols, jnp.asarray(packed.rules), jnp.asarray(packed.deny_key))
    keys_np = np.asarray(keys)
    valid_np = np.asarray(valid)

    from collections import Counter

    got = Counter()
    for k, v in zip(keys_np, valid_np):
        if v:
            m = packed.key_meta[int(k)]
            got[(m.firewall, m.acl, m.index)] += 1
    assert got == res.hits
