"""Multi-device tests on the fake 8-device CPU mesh (SURVEY.md §5).

The decisive property: psum/pmax-merged registers from a sharded run are
BIT-IDENTICAL to the single-device run over the same concatenated batch —
integer adds/maxes are exactly associative and commutative.  This is the
rebuild's substitute for the reference's (nonexistent) distributed tests
and the correctness basis for multi-chip scale-out and resume-by-merge.
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp

from ruleset_analysis_tpu.config import AnalysisConfig, SketchConfig
from ruleset_analysis_tpu.hostside import aclparse, oracle, pack, synth
from ruleset_analysis_tpu.models import pipeline
from ruleset_analysis_tpu.parallel import mesh as mesh_lib
from ruleset_analysis_tpu.parallel.step import make_parallel_step


def cpu_devices():
    """8 fake CPU devices (conftest's --xla_force_host_platform_device_count).

    Under the axon tunnel the default platform stays TPU regardless of
    JAX_PLATFORMS, so meshes are built from the explicit cpu backend.
    """
    devs = jax.devices("cpu")
    assert len(devs) == 8, "conftest must provide 8 fake CPU devices"
    return devs


@pytest.fixture(scope="module")
def setup():
    cfg_text = synth.synth_config(n_acls=3, rules_per_acl=12, seed=31)
    rs = aclparse.parse_asa_config(cfg_text, "fw1")
    packed = pack.pack_rulesets([rs])
    cfg = AnalysisConfig(
        batch_size=1024, sketch=SketchConfig(cms_width=1 << 10, cms_depth=4, hll_p=6)
    )
    batch_np = np.ascontiguousarray(synth.synth_tuples(packed, 1024, seed=31).T)
    return packed, rs, cfg, batch_np


def run_on_mesh(packed, cfg, batch_np, devices):
    mesh = mesh_lib.make_mesh(devices)
    step = make_parallel_step(mesh, cfg, packed.n_keys)
    state = pipeline.init_state(packed.n_keys, cfg)
    rules = pipeline.ship_ruleset(packed)
    batch = mesh_lib.shard_batch(mesh, batch_np)
    state, out = step(state, rules, batch)
    return jax.device_get(state), jax.device_get(out)


def test_eight_device_state_bit_identical_to_single(setup):
    packed, rs, cfg, batch_np = setup
    s8, _ = run_on_mesh(packed, cfg, batch_np, cpu_devices())
    s1, _ = run_on_mesh(packed, cfg, batch_np, cpu_devices()[:1])
    for name in pipeline.AnalysisState._fields:
        np.testing.assert_array_equal(
            np.asarray(getattr(s8, name)), np.asarray(getattr(s1, name)), err_msg=name
        )


def test_shard_order_invariance(setup):
    """Permuting lines across shards must not change merged registers."""
    packed, rs, cfg, batch_np = setup
    rng = np.random.default_rng(0)
    perm = rng.permutation(batch_np.shape[1])
    s_a, _ = run_on_mesh(packed, cfg, batch_np, cpu_devices())
    s_b, _ = run_on_mesh(packed, cfg, np.ascontiguousarray(batch_np[:, perm]), cpu_devices())
    for name in ("counts_lo", "counts_hi", "cms", "hll", "talk_cms"):
        np.testing.assert_array_equal(
            np.asarray(getattr(s_a, name)), np.asarray(getattr(s_b, name)), err_msg=name
        )


def test_parallel_counts_match_oracle(setup):
    packed, rs, cfg, batch_np = setup
    s8, _ = run_on_mesh(packed, cfg, batch_np, cpu_devices())
    # oracle over the same tuples (render -> parse round trip)
    lines = synth.render_syslog(packed, np.ascontiguousarray(batch_np.T), seed=31)
    res = oracle.Oracle([rs]).consume(lines)
    from ruleset_analysis_tpu.ops.counts import to_u64

    per_key = to_u64(np.asarray(s8.counts_lo), np.asarray(s8.counts_hi))
    got = {}
    for key_id, meta in enumerate(packed.key_meta):
        if per_key[key_id]:
            got[(meta.firewall, meta.acl, meta.index)] = int(per_key[key_id])
    assert got == dict(res.hits)


def test_candidates_are_replicated_and_cover_all_shards(setup):
    packed, rs, cfg, batch_np = setup
    _, out = run_on_mesh(packed, cfg, batch_np, cpu_devices())
    k = cfg.sketch.topk_chunk_candidates
    assert out.cand_acl.shape == (8 * k,)
    assert out.cand_src.shape == (8 * k,)


def test_run_stream_uses_mesh_and_matches_single(setup):
    """Full driver on the 8-device mesh == oracle (end to end)."""
    from ruleset_analysis_tpu.runtime.stream import run_stream

    packed, rs, cfg, batch_np = setup
    lines = synth.render_syslog(packed, np.ascontiguousarray(batch_np.T), seed=31)
    rep = run_stream(
        packed, iter(lines), cfg, topk=5, mesh=mesh_lib.make_mesh(cpu_devices())
    )
    res = oracle.Oracle([rs]).consume(lines)
    got = {
        (e["firewall"], e["acl"], e["index"]): e["hits"] for e in rep.per_rule if e["hits"]
    }
    assert got == dict(res.hits)
    assert rep.unused == res.unused_rules([rs])


def test_step_specialization_cache_correct_across_rulesets():
    """The ruleset-specialized step cache must dispatch by VALUE: two
    different rulesets through one step object give each its own correct
    counts, and an equal-valued re-shipped ruleset reuses the executable."""
    cfg = AnalysisConfig(batch_size=64, sketch=SketchConfig(cms_width=1 << 10, cms_depth=2, hll_p=4))
    mesh = mesh_lib.make_mesh(axis=cfg.mesh_axis)

    def setup(seed):
        rs = aclparse.parse_asa_config(
            synth.synth_config(n_acls=2, rules_per_acl=6, seed=seed), "fw1"
        )
        packed = pack.pack_rulesets([rs])
        tup = synth.synth_tuples(packed, 64, seed=seed)
        wire = pack.compact_batch(np.ascontiguousarray(tup.T))
        return packed, wire

    pa, wa = setup(1)
    pb, wb = setup(2)
    assert pa.n_keys == pb.n_keys  # same key space, different rule values
    step = make_parallel_step(mesh, cfg, pa.n_keys)

    def run(packed, wire):
        rules = pipeline.ship_ruleset(packed)
        st = pipeline.init_state(packed.n_keys, cfg)
        st, _ = step(st, rules, mesh_lib.shard_batch(mesh, wire, cfg.mesh_axis))
        return np.asarray(st.counts_lo).copy()

    ca1 = run(pa, wa)
    cb = run(pb, wb)
    ca2 = run(pa, wa)  # re-shipped equal-valued ruleset (new object)
    np.testing.assert_array_equal(ca1, ca2)
    assert not np.array_equal(ca1, cb)  # different rules really dispatched
    assert ca1.sum() > 0 and cb.sum() > 0
