"""Durable epoch store + segment-tree range merges (ISSUE 20).

Pins the DESIGN §25 invariants:

- **Fold-shape independence**: any ``[t0,t1]`` range answered from the
  segment tree (<= 2 log n stored aggregates + one merge fold) is
  bit-identical — registers, tracker tables, accounting — to the naive
  linear fold over the same level-0 epochs, and to a single-shot merge
  of the raw images.  Degenerates (single epoch, empty range) included.
- **Typed incompleteness**: a quarantined gap, an empty store, a bound
  beyond the frontier, or a query crossing a keyspace migration yields
  a ``range_incomplete`` marker naming the reason — never silent zeros,
  never a partial answer.
- **Crash discipline**: ``epochstore.spill`` failing degrades the
  subsystem and leaves the on-disk store readable; a crash at the worst
  instant of compaction (pair chosen, merged node unwritten) loses zero
  epochs — repair at the next open rebuilds the missing summary nodes.
- **Suffix-merge reuse**: the merged-K rendering cache returns exactly
  the arrays a cold merge would produce, misses (never lies) when its
  window ids drift, and heals after an invalidation.
"""

import json
import os
import socket
import subprocess
import sys
import threading
import time
import urllib.request

import numpy as np
import pytest

pytest.importorskip("jax")

from ruleset_analysis_tpu.config import AnalysisConfig, ServeConfig
from ruleset_analysis_tpu.errors import AnalysisError
from ruleset_analysis_tpu.hostside import aclparse, pack, synth
from ruleset_analysis_tpu.runtime import epochstore, faults
from ruleset_analysis_tpu.runtime.serve import (
    ServeDriver,
    SuffixMergeCache,
    merge_register_arrays,
)

RUN_CFG = dict(batch_size=128, prefetch_depth=0)
WL = 150


# ---------------------------------------------------------------------------
# Offline store: fold-shape independence + typed refusals.
# ---------------------------------------------------------------------------

def _mk_epoch(wid, *, n_keys=16, unix_base=1.7e9):
    rng = np.random.default_rng(1000 + wid)

    class _Ep:
        arrays = {
            "counts_lo": rng.integers(0, 2**32, n_keys, dtype=np.uint32),
            "counts_hi": rng.integers(0, 3, n_keys, dtype=np.uint32),
            "cms": rng.integers(0, 2**32, (2, 32), dtype=np.uint32),
            "hll": rng.integers(0, 30, (n_keys, 8), dtype=np.uint32),
            "talk_cms": rng.integers(0, 2**32, (2, 32), dtype=np.uint32),
        }
        meta = {
            "id": wid, "lines": 100 + wid, "parsed": 95, "skipped": 5,
            "chunks": 1, "drops": 0,
            "started_unix": unix_base + 60.0 * wid,
            "ended_unix": unix_base + 60.0 * wid + 59.0,
        }
        tracker_tables = {
            0: {int(rng.integers(0, 2**32)): int(rng.integers(1, 500))}
        }
        quarantine = {("fw1", "acl0", 0, "line"): wid + 1} if wid % 3 else {}

    return _Ep()


def _agg_equal(a, b):
    return (
        set(a.arrays) == set(b.arrays)
        and all(np.array_equal(a.arrays[k], b.arrays[k]) for k in a.arrays)
        and a.tables == b.tables
        and a.summary == b.summary
        and a.quarantine == b.quarantine
    )


def test_fold_shape_independence_tree_naive_oneshot(tmp_path):
    store = epochstore.EpochStore(str(tmp_path / "es"))
    store.bind_base(0)
    eps = [_mk_epoch(w) for w in range(64)]
    for ep in eps:
        assert store.spill(ep)
    rng = np.random.default_rng(5)
    for _ in range(24):
        lo = int(rng.integers(0, 63))
        hi = int(rng.integers(lo, 64))
        tree, m1 = store.range_agg(lo, hi)
        naive, m2 = store.naive_range_agg(lo, hi)
        assert m1 is None and m2 is None
        assert _agg_equal(tree, naive), f"tree != naive on [{lo},{hi}]"
        # the third shape: one merge_aggs left-fold over fresh images
        one = epochstore.agg_from_epoch(eps[lo])
        for ep in eps[lo + 1:hi + 1]:
            one = epochstore.merge_aggs(one, epochstore.agg_from_epoch(ep))
        assert _agg_equal(tree, one), f"tree != one-shot on [{lo},{hi}]"
    # the tree genuinely decomposes: full span touches few nodes
    assert store.stats()["depth"] >= 6
    store.close()


def test_range_degenerates_and_unix_resolution(tmp_path):
    store = epochstore.EpochStore(str(tmp_path / "es"))
    # empty store refuses typed
    _, m = store.range_agg(0, 5)
    assert m["range_incomplete"] and m["reason"] == "empty_store"
    store.bind_base(10)  # non-zero base: ids are absolute window numbers
    for w in range(10, 17):
        store.spill(_mk_epoch(w))
    # single epoch
    agg, m = store.range_agg(12, 12)
    assert m is None and agg.span == (12, 13)
    assert agg.summary["windows"] == 1
    # empty range (from > to)
    _, m = store.range_agg(14, 12)
    assert m["range_incomplete"] and m["reason"] == "empty_range"
    # beyond the frontier: the marker names the first unspilled window
    _, m = store.range_agg(10, 99)
    assert m["reason"] == "beyond_frontier" and m["window"] == 17
    # before recorded history
    _, m = store.range_agg(2, 12)
    assert m["reason"] == "missing"
    # defaults cover the full extent
    agg, m = store.range_agg(None, None)
    assert m is None and agg.span == (10, 17)
    # unix-second bounds map through the spill index
    ep12 = _mk_epoch(12)
    lo, hi = store.resolve_range(
        str(ep12.meta["started_unix"]), str(_mk_epoch(14).meta["ended_unix"])
    )
    assert (lo, hi) == (12, 14)
    with pytest.raises(AnalysisError):
        store.resolve_range("not-a-number", None)
    store.close()


def test_quarantined_gap_refuses_typed_but_coarse_spans_answer(tmp_path):
    d = str(tmp_path / "es")
    store = epochstore.EpochStore(d)
    store.bind_base(0)
    for w in range(8):
        store.spill(_mk_epoch(w))
    expect_full, m = store.range_agg(0, 7)
    assert m is None
    store.close()
    # flip one payload byte of a level-0 record (all 8 fit one segment)
    l0 = os.path.join(d, "L00")
    seg = os.path.join(l0, sorted(os.listdir(l0))[0])
    with open(seg, "r+b") as f:
        f.seek(os.path.getsize(seg) - 40)
        b = f.read(1)
        f.seek(-1, 1)
        f.write(bytes([b[0] ^ 0xFF]))
    store = epochstore.EpochStore(d)
    store.bind_base(8)
    # an unaligned query needing the damaged level-0 chain: typed refusal
    _, m = store.range_agg(7, 7)
    assert m == {
        "range_incomplete": True, "from": 7, "to": 7, "reason": "missing",
        "window": 7,
    }
    assert store.range_incomplete_total == 1
    assert store.stats()["quarantined_segments"] >= 1
    # the aligned full span rides the intact level-3 summary node —
    # bit-identical to the pre-damage fold, zero level-0 reads
    agg, m = store.range_agg(0, 7)
    assert m is None and _agg_equal(agg, expect_full)
    store.close()


def test_keyspace_migration_era_refusal_and_holes(tmp_path):
    store = epochstore.EpochStore(str(tmp_path / "es"))
    store.bind_base(0)
    for w in range(3):
        store.spill(_mk_epoch(w))
    store.mark_era(3, generation=1)
    for w in range(3, 6):
        store.spill(_mk_epoch(w))
    # crossing the era start: typed keyspace_migration refusal
    _, m = store.range_agg(1, 4)
    assert m["range_incomplete"] and m["reason"] == "keyspace_migration"
    assert m["window"] == 2  # the newest window in the dead key space
    # entirely inside the new era: answered, identical to naive
    agg, m = store.range_agg(3, 5)
    ref, _ = store.naive_range_agg(3, 5)
    assert m is None and _agg_equal(agg, ref)
    # the pair straddling the era produced a hole, never a bogus merge
    assert store.holes_total >= 1
    store.close()


def test_reopen_repair_preserves_fold_and_counts(tmp_path):
    d = str(tmp_path / "es")
    store = epochstore.EpochStore(d)
    store.bind_base(0)
    for w in range(13):
        store.spill(_mk_epoch(w))
    want, _ = store.naive_range_agg(0, 12)
    stats = store.stats()
    store.close()
    again = epochstore.EpochStore(d)
    again.bind_base(13)  # resume exactly at the frontier
    assert again.stats()["epochs"] == 13
    assert again.stats()["nodes"] == stats["nodes"]
    agg, m = again.range_agg(0, 12)
    assert m is None and _agg_equal(agg, want)
    # a gapped resume is a typed startup refusal, not silent misnumbering
    with pytest.raises(AnalysisError):
        again.bind_base(20)
    again.close()


# ---------------------------------------------------------------------------
# Suffix-merge cache (merged-K rendering reuse).
# ---------------------------------------------------------------------------

def test_suffix_cache_bit_identical_and_self_healing():
    sc = SuffixMergeCache((3,))
    eps = [_mk_epoch(w) for w in range(10)]
    for i, ep in enumerate(eps):
        sc.push(ep.meta["id"], ep.arrays)
        ids = [e.meta["id"] for e in eps[max(0, i - 2):i + 1]]
        got = sc.merged(3, ids)
        assert got is not None
        ref = merge_register_arrays(
            [e.arrays for e in eps[max(0, i - 2):i + 1]]
        )
        for k in ref:
            assert np.array_equal(got[k], ref[k]), k
    # id drift (a restore rewrote the ring): miss, never a wrong image
    assert sc.merged(3, [99, 8, 9]) is None
    # invalidation heals as fresh rotations refill the window
    sc.invalidate()
    assert sc.merged(3, [7, 8, 9]) is None
    for ep in eps[8:]:
        sc.push(ep.meta["id"], ep.arrays)
    got = sc.merged(3, [8, 9])  # exact match on the refilled short window
    assert got is not None
    ref = merge_register_arrays([eps[8].arrays, eps[9].arrays])
    for k in ref:
        assert np.array_equal(got[k], ref[k]), k
    assert sc.misses == 2 and sc.hits == 11


# ---------------------------------------------------------------------------
# Serve e2e: spill every rotation, /report/range == cumulative, last-hit.
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def corpus(tmp_path_factory):
    td = tmp_path_factory.mktemp("epochstore")
    cfg_text = synth.synth_config(
        n_acls=2, rules_per_acl=8, seed=0, v6_fraction=0.25
    )
    rs = aclparse.parse_asa_config(cfg_text, "fw1")
    packed = pack.pack_rulesets([rs])
    prefix = str(td / "rules")
    pack.save_packed(packed, prefix)
    t = synth.synth_tuples(packed, 500, seed=1)
    lines = synth.render_syslog(packed, t, seed=1)
    return packed, prefix, lines, str(td)


def start_serve(prefix, cfg, scfg):
    drv = ServeDriver(prefix, cfg, scfg)
    out: dict = {}

    def runner():
        try:
            out["summary"] = drv.run()
        except BaseException as e:  # surfaced by finish()
            out["error"] = e

    th = threading.Thread(target=runner)
    th.start()
    deadline = time.monotonic() + 20
    while time.monotonic() < deadline:
        if out.get("error"):
            break
        if drv.listeners.listeners and drv.listeners.alive() and (
            scfg.http == "off" or drv.http_address
        ):
            break
        time.sleep(0.05)
    return drv, th, out


def finish(th, out, timeout=120):
    th.join(timeout=timeout)
    assert not th.is_alive(), "serve hung"
    if "error" in out:
        raise out["error"]
    return out["summary"]


def send_tcp(addr, lines):
    s = socket.create_connection(addr)
    s.sendall(("\n".join(lines) + "\n").encode())
    s.close()


def get_json(http, path):
    host, port = http
    with urllib.request.urlopen(
        f"http://{host}:{port}{path}", timeout=10
    ) as r:
        return json.load(r)


def wait_for(pred, timeout=60, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.05)
    raise AssertionError(f"timed out waiting for {msg}")


def test_serve_range_report_replay_free_e2e(corpus, tmp_path):
    packed, prefix, lines, td = corpus
    es_dir = str(tmp_path / "estore")
    scfg = ServeConfig(
        listen=("tcp:127.0.0.1:0",), window_lines=WL, ring=2,
        serve_dir=str(tmp_path / "serve"), max_windows=0, stop_after_sec=90,
        reload_watch=False, checkpoint_every_windows=0, http="127.0.0.1:0",
        queue_lines=10_000, epoch_store=es_dir,
    )
    drv, th, out = start_serve(prefix, AnalysisConfig(**RUN_CFG), scfg)
    try:
        addr = tuple(drv.listeners.listeners[0].address)
        for w in range(3):
            send_tcp(addr, lines[w * WL:(w + 1) * WL])
            wait_for(
                lambda w=w: out.get("error") or drv.windows_published > w,
                msg=f"window {w}",
            )
        if "error" in out:
            raise out["error"]
        http = drv.http_address

        # ring=2 but the store holds all 3: the full-span range report
        # answers replay-free, per-rule identical to the cumulative
        full = get_json(http, "/report/range?from=0&to=2")
        assert "range_incomplete" not in full
        cum = get_json(http, "/report/cumulative")
        assert full["per_rule"] == cum["per_rule"]
        assert full["totals"]["lines_total"] == cum["totals"]["lines_total"]
        win = full["totals"]["window"]
        assert win["range"] == [0, 2] and win["windows"] == 3
        assert win["drops"] == 0 and "incomplete" not in win
        assert win["mode"] == "lines" and win["length"] == WL
        # sub-span: identical to replaying just that window's lines
        one = get_json(http, "/report/range?from=1&to=1")
        win1 = get_json(http, "/report/window/1")
        assert one["per_rule"] == win1["per_rule"]

        # typed incompleteness over HTTP: future bound -> 404 marker
        host, port = http
        req = urllib.request.Request(
            f"http://{host}:{port}/report/range?from=0&to=9"
        )
        try:
            urllib.request.urlopen(req, timeout=10)
            raise AssertionError("future range served a report")
        except urllib.error.HTTPError as e:
            marker = json.loads(e.read().decode())
            assert e.code == 404
            assert marker["range_incomplete"]
            assert marker["reason"] == "beyond_frontier"

        # the quiet-horizon join: /report/last-hit + totals.static
        lh = get_json(http, "/report/last-hit")
        assert lh["frontier"] == 2
        assert lh["rules"], "no last-hit rows despite 3 spilled windows"
        static = full["totals"].get("static")
        if static is not None and "last_hit" in static:
            assert static["last_hit"]["horizon_window"] == 2

        # gauges + prom parity + lineage frontier
        m = get_json(http, "/metrics")
        assert m["epochstore_spilled_total"] == 3
        assert m["epochstore_epochs"] == 3
        assert m["epochstore_range_queries_total"] >= 3
        with urllib.request.urlopen(
            f"http://{host}:{port}/metrics?format=prom", timeout=10
        ) as r:
            prom = r.read().decode()
        assert "ra_serve_epochstore_spilled_total 3" in prom
        assert "ra_serve_range_query_seconds_count" in prom
        tail = drv.lineage_tail()
        assert tail["epoch_store"]["last_spilled_window"] == 2
    finally:
        drv.stop()
    summary = finish(th, out)
    assert summary["epoch_store"]["epochs"] == 3
    assert summary["drops"] == 0

    # replay-free across process death: a fresh store open answers the
    # same span without any serve loop or WAL replay
    again = epochstore.EpochStore(es_dir)
    agg, m = again.range_agg(0, 2)
    assert m is None
    assert agg.summary["windows"] == 3
    assert agg.summary["lines"] == summary["lines_total"]
    again.close()


# ---------------------------------------------------------------------------
# Chaos schedules (tier-1): spill raise + compaction crash.
# ---------------------------------------------------------------------------

def test_chaos_spill_raise_degrades_and_store_stays_readable(
    corpus, tmp_path
):
    packed, prefix, lines, td = corpus
    es_dir = str(tmp_path / "estore")
    scfg = ServeConfig(
        listen=("tcp:127.0.0.1:0",), window_lines=WL, ring=4,
        serve_dir=str(tmp_path / "serve"), max_windows=3, stop_after_sec=60,
        reload_watch=False, checkpoint_every_windows=0, http="off",
        queue_lines=10_000, epoch_store=es_dir,
    )
    with faults.armed(faults.FaultPlan.parse("epochstore.spill@2")):
        drv, th, out = start_serve(prefix, AnalysisConfig(**RUN_CFG), scfg)
        addr = tuple(drv.listeners.listeners[0].address)
        for w in range(3):
            send_tcp(addr, lines[w * WL:(w + 1) * WL])
        summary = finish(th, out)
    # publication survived the full schedule; the history plane (and
    # ONLY it) degraded at window 1 and stayed off — dense numbering
    assert summary["windows_published"] == 3
    assert "epoch_store" in summary["degraded"]
    assert summary["epoch_store"]["epochs"] == 1
    store = epochstore.EpochStore(es_dir)
    agg, m = store.range_agg(0, 0)
    assert m is None and agg.summary["windows"] == 1
    _, m = store.range_agg(0, 2)
    assert m["range_incomplete"] and m["reason"] == "beyond_frontier"
    store.close()


def test_chaos_compact_crash_loses_zero_epochs(tmp_path):
    """SIGKILL-equivalent (os._exit) after the pair is chosen, before
    the merged node lands: reopen must see every epoch whose spill
    started, and repair must rebuild the unwritten summary node."""
    es_dir = str(tmp_path / "estore")
    child = (
        "import sys\n"
        "sys.path.insert(0, sys.argv[2])\n"
        "from test_epochstore import _mk_epoch\n"
        "from ruleset_analysis_tpu.runtime import epochstore\n"
        "store = epochstore.EpochStore(sys.argv[1])\n"
        "store.bind_base(0)\n"
        "for w in range(12):\n"
        "    store.spill(_mk_epoch(w))\n"
        "    print(w, flush=True)\n"
    )
    env = dict(
        os.environ, JAX_PLATFORMS="cpu",
        RA_FAULT_PLAN="epochstore.compact@2",
    )
    proc = subprocess.run(
        [sys.executable, "-c", child, es_dir, os.path.dirname(__file__)],
        capture_output=True, text=True, timeout=300,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        env=env,
    )
    assert proc.returncode != 0, "crash plan never fired"
    acked = [int(x) for x in proc.stdout.split()]
    assert acked, f"no spill acked before crash: {proc.stderr[-500:]}"
    store = epochstore.EpochStore(es_dir)
    st = store.stats()
    # the victim spill's level-0 append landed before the crash point
    assert st["epochs"] == len(acked) + 1, (st, acked)
    agg, m = store.range_agg(0, st["epochs"] - 1)
    ref, mn = store.naive_range_agg(0, st["epochs"] - 1)
    assert m is None and mn is None
    assert _agg_equal(agg, ref)
    assert agg.summary["windows"] == st["epochs"]
    store.close()
