"""Pipelined ingest engine (runtime/ingest.py).

The prefetch driver must be BIT-IDENTICAL to the synchronous driver on
the full report — per-rule hits, unused set, talkers, totals — across
flat/stacked x text/wire x v4/v6, because batches commit in source order
and the side effects of producing a batch (counters, staged v6 rows,
elastic cursors) only land when the driver consumes it.  Failure modes
must be typed and prompt: a dead feed worker or a producer exception
surfaces at the consumer's next pull, never as a hang.
"""

import json
import random

import numpy as np
import pytest

pytest.importorskip("jax")

from ruleset_analysis_tpu.config import AnalysisConfig, SketchConfig
from ruleset_analysis_tpu.errors import FeedWorkerError, ResumeInputMismatch
from ruleset_analysis_tpu.hostside import aclparse, fastparse, pack, synth
from ruleset_analysis_tpu.hostside import wire as wire_mod
from ruleset_analysis_tpu.runtime import stream
from ruleset_analysis_tpu.runtime.ingest import PrefetchingSource
from ruleset_analysis_tpu.runtime.stream import (
    _TextSource,
    run_stream_file,
    run_stream_packed,
    run_stream_wire,
)

#: totals keys that legitimately differ run to run (timings); everything
#: else in the report must match bit for bit
# ONE volatile-keys list (runtime/report.py): the registry auditor
# (verify/registry.py) flags any module keeping a private copy.
from ruleset_analysis_tpu.runtime.report import VOLATILE_TOTALS as VOLATILE


def report_image(rep) -> dict:
    j = json.loads(rep.to_json())
    for k in VOLATILE:
        j["totals"].pop(k, None)
    return j


CFG6 = """\
hostname fw1
access-list A extended permit tcp any host 10.0.0.5 eq 443
access-list A extended permit tcp any6 2001:db8:1::/48 eq 443
access-list A extended permit udp 2001:db8:2::/64 any6 eq 53
access-list A extended deny tcp any6 host 2001:db8::bad
access-list A extended permit ip any any
access-list B extended permit tcp any6 any6 range 8000 8100
access-group A in interface outside
"""


def _mixed_lines(n, seed=0, v6_share=0.35):
    rng = random.Random(seed)
    out = []
    for i in range(n):
        acl = "A" if rng.random() < 0.8 else "B"
        if rng.random() < v6_share:
            src = f"2001:db8:2::{rng.randrange(1, 40):x}"
            dst = f"2001:db8:{rng.randrange(0, 4):x}:1::{rng.randrange(1, 99):x}"
            proto = rng.choice(["tcp", "udp"])
        else:
            src = f"10.1.{rng.randrange(256)}.{rng.randrange(1, 255)}"
            dst = "10.0.0.5" if rng.random() < 0.5 else "10.9.9.9"
            proto = "tcp"
        out.append(
            f"Jul 29 07:48:{i % 60:02d} fw1 : %ASA-6-106100: access-list {acl} "
            f"permitted {proto} inside/{src}({rng.randrange(1024, 60000)}) -> "
            f"outside/{dst}({rng.choice([443, 53, 8050, 80])}) "
            f"hit-cnt 1 first hit [0x0, 0x0]"
        )
    return out


@pytest.fixture(scope="module")
def corpus4(tmp_path_factory):
    """v4-only synth corpus, one text file."""
    td = tmp_path_factory.mktemp("ingest4")
    cfg_text = synth.synth_config(n_acls=3, rules_per_acl=8, seed=41)
    rs = aclparse.parse_asa_config(cfg_text, "fw1")
    packed = pack.pack_rulesets([rs])
    tuples = synth.synth_tuples(packed, 5000, seed=42)
    lines = synth.render_syslog(packed, tuples, seed=43)
    p = td / "v4.log"
    p.write_text("\n".join(lines) + "\n", encoding="utf-8")
    return packed, str(p)


@pytest.fixture(scope="module")
def corpus6(tmp_path_factory):
    """Mixed v4+v6 corpus against a unified ruleset."""
    td = tmp_path_factory.mktemp("ingest6")
    rs = aclparse.parse_asa_config(CFG6, "fw1")
    packed = pack.pack_rulesets([rs])
    p = td / "v6.log"
    p.write_text("\n".join(_mixed_lines(4000, seed=7)) + "\n", encoding="utf-8")
    return packed, str(p)


def _cfg(depth, layout="flat", **kw):
    return AnalysisConfig(
        batch_size=512,
        sketch=SketchConfig(cms_width=1 << 10, cms_depth=2, hll_p=6),
        prefetch_depth=depth,
        layout=layout,
        **kw,
    )


@pytest.mark.parametrize("layout", ["flat", "stacked"])
@pytest.mark.parametrize("family", ["v4", "v6"])
def test_text_prefetch_bit_identical(corpus4, corpus6, layout, family):
    packed, path = corpus4 if family == "v4" else corpus6
    sync = run_stream_file(packed, path, _cfg(0, layout), topk=5)
    pre = run_stream_file(packed, path, _cfg(3, layout), topk=5)
    assert report_image(sync) == report_image(pre)


@pytest.mark.parametrize("layout", ["flat", "stacked"])
@pytest.mark.parametrize("family", ["v4", "v6"])
def test_wire_prefetch_bit_identical(
    corpus4, corpus6, layout, family, tmp_path
):
    packed, path = corpus4 if family == "v4" else corpus6
    wp = str(tmp_path / "c.rawire")
    wire_mod.convert_logs(packed, [path], wp, batch_size=512, block_rows=512)
    sync = run_stream_wire(packed, wp, _cfg(0, layout), topk=5)
    pre = run_stream_wire(packed, wp, _cfg(2, layout), topk=5)
    assert report_image(sync) == report_image(pre)


@pytest.mark.parametrize("family", ["v4", "v6"])
def test_text_prefetch_coalesced_bit_identical(corpus4, corpus6, family):
    """Prefetch + flow coalescing still commits in source order: the
    coalesced pack stage runs on the producer thread, and the report is
    bit-identical to the synchronous UNcoalesced driver (ISSUE 5)."""
    packed, path = corpus4 if family == "v4" else corpus6
    sync = run_stream_file(packed, path, _cfg(0), topk=5)
    co = run_stream_file(packed, path, _cfg(3, coalesce="on"), topk=5)
    assert report_image(sync) == report_image(co)


def test_python_parser_prefetch_bit_identical(corpus4):
    packed, path = corpus4
    sync = run_stream_file(packed, path, _cfg(0), topk=5, native=False)
    pre = run_stream_file(packed, path, _cfg(2), topk=5, native=False)
    assert report_image(sync) == report_image(pre)


def test_packed_source_prefetch_bit_identical(corpus4):
    packed, _path = corpus4
    feeds = [
        np.ascontiguousarray(synth.synth_tuples(packed, 700, seed=i).T)
        for i in range(5)
    ]
    sync = run_stream_packed(packed, iter(feeds), _cfg(0), topk=5)
    pre = run_stream_packed(packed, iter(feeds), _cfg(3), topk=5)
    assert report_image(sync) == report_image(pre)


def test_ingest_stats_reported(corpus4):
    packed, path = corpus4
    rep = run_stream_file(packed, path, _cfg(3), topk=5)
    ing = rep.totals["ingest"]
    assert ing["prefetch_depth"] == 3
    assert ing["batches"] == rep.totals["chunks"]
    assert "compile_sec" in rep.totals
    assert "sustained_lines_per_sec" in rep.totals
    # synchronous runs report no ingest section at all
    rep0 = run_stream_file(packed, path, _cfg(0), topk=5)
    assert "ingest" not in rep0.totals


def test_crash_at_chunk_k_resume_under_prefetch(corpus6, tmp_path):
    """Crash simulation + resume with prefetch == uninterrupted sync run.

    The snapshot taken at a chunk boundary must cover exactly the
    committed batches — lines the producer prefetched past the crash
    point must NOT be claimed — or the resumed registers would double- or
    skip-count them.
    """
    packed, path = corpus6
    # same checkpoint cadence as the crashed run: checkpoint flushes step
    # partial v6 chunks, so cadence is part of the chunk structure
    ref = run_stream_file(
        packed,
        path,
        _cfg(0).replace(
            checkpoint_every_chunks=2, checkpoint_dir=str(tmp_path / "ref")
        ),
        topk=5,
    )

    ck = str(tmp_path / "ck")
    cfg = _cfg(3).replace(checkpoint_every_chunks=2, checkpoint_dir=ck)
    crashed = run_stream_file(packed, path, cfg, topk=5, max_chunks=3)
    assert crashed.totals["lines_total"] < ref.totals["lines_total"]
    resumed = run_stream_file(packed, path, cfg.replace(resume=True), topk=5)
    assert report_image(resumed) == report_image(ref)


def test_producer_exception_typed_not_hung(corpus4):
    """A producer-side failure re-raises, typed, at the consumer."""
    packed, path = corpus4
    src = PrefetchingSource(
        _TextSource(packed, stream._iter_files([path])), depth=2
    )
    it = src.batches(10_000_000, 512)  # skip past EOF -> ResumeInputMismatch
    with pytest.raises(ResumeInputMismatch):
        next(it)
    src.close()


@pytest.mark.skipif(
    not fastparse.available(), reason="native parser not buildable here"
)
def test_killed_feed_worker_mid_prefetch_typed_error(corpus4):
    """Killing feeder workers under the prefetch wrapper: typed, no hang."""
    from ruleset_analysis_tpu.hostside.feeder import ParallelFeeder

    packed, path = corpus4
    feeder = ParallelFeeder(packed, [path], n_workers=2)
    src = PrefetchingSource(feeder, depth=1)
    it = src.batches(0, 256)
    assert next(it) is not None  # workers are up and parsing
    for w in feeder._workers:
        w.terminate()
    with pytest.raises(FeedWorkerError):
        # bounded by the feeder's 5s liveness timeout; drain what the
        # producer managed to queue before the kill landed
        for _ in range(64):
            next(it)
    src.close()


def test_prefetch_source_commits_in_order(corpus4):
    """Counters visible on the wrapper track committed batches only."""
    packed, path = corpus4
    from ruleset_analysis_tpu.runtime.stream import _FileSource

    inner_cls = _FileSource if fastparse.available() else _TextSource
    if inner_cls is _TextSource:
        inner = _TextSource(packed, stream._iter_files([path]))
    else:
        inner = _FileSource(packed, [path])
    src = PrefetchingSource(inner, depth=4)
    it = src.batches(0, 512)
    assert src.packer.parsed == 0
    seen = 0
    parsed_after = []
    for _batch, n_raw in it:
        seen += n_raw
        parsed_after.append(src.packer.parsed)
        # committed counters never exceed what a synchronous parse of the
        # consumed batches would have produced (2x bound: dual-eval rows)
        assert src.packer.parsed <= 2 * seen
    assert seen == 5000
    assert parsed_after == sorted(parsed_after)
    src.close()
