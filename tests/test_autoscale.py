"""Metrics-driven elastic autoscaling + the hybrid DCN x ICI mesh.

Four surfaces (ISSUE 7; DESIGN §13):

- **Policy engine** (fast, no subprocesses): the decision table on
  synthetic samples — sustain windows, cooldown, budget, observe-only,
  scripted plans, flap accounting — plus the metrics-stream adapters
  (tail of a torn JSONL, signal differentiation) and the Prometheus
  text rendering of the one-source-of-truth gauges.
- **Reshard law** (property): the epoch cursor manifest round-trips
  across world sizes 1 -> 8 -> 3 -> 8 (grow AND shrink), registers
  bit-identical to a single-world replay — the law the autoscaler
  leans on for every planned scale event.
- **Hybrid mesh**: the 2x4 two-level DCN x ICI mesh produces reports
  bit-identical to the flat 8-way mesh (text + wire).
- **Serve / elastic actuation**: bursty load into ``serve --autoscale``
  scales out on the burst and in after it, every published window
  bit-identical to an offline replay; chaos schedules land injected
  faults at the decide->actuate seam (typed abort or intact service,
  never a half-applied scale event).  The subprocess elastic drills
  (scale events through real re-formations) are ``slow``-marked.
"""

import json
import os
import socket
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

pytest.importorskip("jax")

from ruleset_analysis_tpu.config import (
    AnalysisConfig, AutoscaleConfig, ServeConfig, SketchConfig,
)
from ruleset_analysis_tpu.errors import AnalysisError
from ruleset_analysis_tpu.hostside import aclparse, pack, synth
from ruleset_analysis_tpu.runtime import faults
from ruleset_analysis_tpu.runtime.autoscale import (
    AutoscaleController, MetricsTail, PolicyEngine, flap_count,
    ingest_signals, parse_plan, read_decision_log, render_prom, world_ladder,
)
from ruleset_analysis_tpu.runtime.stream import run_stream, run_stream_wire

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: report-totals keys excluded from bit-identity images (the chaos
#: harness list + the autoscale/world blocks this PR adds: scale
#: timings are wall-clock, never part of the answer)
# ONE volatile-keys list (runtime/report.py): the registry auditor
# (verify/registry.py) flags any module keeping a private copy.
from ruleset_analysis_tpu.runtime.report import VOLATILE_TOTALS as VOLATILE


def report_image(rep) -> dict:
    j = rep if isinstance(rep, dict) else json.loads(rep.to_json())
    j = json.loads(json.dumps(j))
    for k in VOLATILE:
        j["totals"].pop(k, None)
    j["totals"].pop("window", None)
    return j


def acfg(**kw) -> AutoscaleConfig:
    base = dict(
        min_world=1, max_world=8, out_threshold=0.5, in_threshold=0.8,
        sustain_sec=1.0, cooldown_sec=2.0, reform_budget=4, poll_sec=0.1,
    )
    base.update(kw)
    return AutoscaleConfig(**base)


# ---------------------------------------------------------------------------
# Policy engine decision table (pure, synthetic samples — the fast tier)
# ---------------------------------------------------------------------------


def feed(eng, t0, t1, *, p=0.0, s=0.0, dt=0.25):
    """Feed constant-signal samples over [t0, t1); first decision wins."""
    t = t0
    while t < t1:
        d = eng.observe(now=t, pressure=p, starvation=s)
        if d is not None:
            return d, t
        t += dt
    return None, t


def test_engine_scales_out_on_sustained_pressure_only():
    eng = PolicyEngine(acfg(), world=2, ladder=[1, 2, 4, 8])
    # below-threshold pressure never decides, no matter how long
    d, t = feed(eng, 0.0, 10.0, p=0.45)
    assert d is None
    # sustained above-threshold pressure decides after >= sustain_sec
    d, t = feed(eng, 10.0, 20.0, p=0.9)
    assert d is not None and (d.direction, d.from_world, d.to_world) == (
        "out", 2, 4,
    )
    assert t - 10.0 >= eng.acfg.sustain_sec
    assert d.reason == "backpressure" and d.actuate
    # evidence rides the decision: window stats + thresholds
    assert d.evidence["pressure"]["min"] >= 0.9
    assert d.evidence["pressure"]["threshold"] == eng.acfg.out_threshold


def test_engine_one_dip_resets_the_sustain_window():
    eng = PolicyEngine(acfg(sustain_sec=2.0), world=1, ladder=[1, 2])
    t = 0.0
    for i in range(40):
        # a dip every ~1.5s keeps min(window) below threshold forever
        p = 0.1 if i % 6 == 5 else 0.95
        assert eng.observe(now=t, pressure=p, starvation=0.0) is None
        t += 0.25
    assert eng.decisions == []


def test_engine_cooldown_and_ladder_edges():
    eng = PolicyEngine(acfg(cooldown_sec=5.0), world=4, ladder=[2, 4, 8])
    d, t = feed(eng, 0.0, 30.0, p=1.0)
    assert (d.from_world, d.to_world) == (4, 8)
    # within cooldown: silent hold even under saturated pressure
    d2, _ = feed(eng, t, t + 4.9, p=1.0)
    assert d2 is None
    # at the top rung: pressure can never push past the ladder
    d3, _ = feed(eng, t + 5.0, t + 30.0, p=1.0)
    assert d3 is None
    # starvation brings it back down a rung
    d4, _ = feed(eng, t + 31.0, t + 60.0, s=1.0)
    assert (d4.direction, d4.to_world) == ("in", 4)


def test_engine_budget_exhaustion_and_observe_only():
    eng = PolicyEngine(acfg(reform_budget=1, cooldown_sec=1.0),
                       world=1, ladder=[1, 2, 4])
    d, t = feed(eng, 0.0, 30.0, p=1.0)
    assert d is not None and eng.budget_left == 0
    d2, _ = feed(eng, t + 1.5, t + 30.0, p=1.0)
    assert d2 is None  # budget gone: hold forever
    assert eng.suppressed_budget > 0
    assert eng.summary()["suppressed_by_budget"] == eng.suppressed_budget

    obs_only = PolicyEngine(acfg(reform_budget=0), world=1, ladder=[1, 2])
    d, _ = feed(obs_only, 0.0, 30.0, p=1.0)
    assert d is not None and not d.actuate
    assert obs_only.world == 1  # decisions recorded, never actuated
    assert obs_only.summary()["observe_only"]


def test_engine_flap_accounting_and_damping_window():
    a = acfg(sustain_sec=1.0, cooldown_sec=1.0)  # damping window = 4s
    eng = PolicyEngine(a, world=2, ladder=[2, 4])
    d1, t = feed(eng, 0.0, 30.0, p=1.0)
    # immediate reversal right after cooldown: inside 2*(cd+sus) = flap
    d2, t2 = feed(eng, t + 1.1, t + 30.0, s=1.0)
    assert d1.direction == "out" and d2.direction == "in"
    assert eng.flaps == 1
    # the cross-generation flap counter sees the same thing on t_wall
    log = [{"direction": "out", "t_wall": 100.0},
           {"direction": "in", "t_wall": 102.5}]
    assert flap_count(log, cooldown_sec=1.0, sustain_sec=1.0) == 1
    # reversal OUTSIDE the window is a legitimate load response, not a flap
    log[1]["t_wall"] = 104.5
    assert flap_count(log, cooldown_sec=1.0, sustain_sec=1.0) == 0
    # same direction twice is never a flap, whatever the spacing
    log[1] = {"direction": "out", "t_wall": 100.2}
    assert flap_count(log, cooldown_sec=1.0, sustain_sec=1.0) == 0


def test_engine_scripted_plan_bypasses_thresholds():
    eng = PolicyEngine(acfg(plan="out@1,out@2,in@5"),
                       world=2, ladder=[1, 2, 4, 8])
    decs = []
    t = 0.0
    while t < 10.0:
        d = eng.observe(now=t, pressure=0.0, starvation=0.0)
        if d:
            decs.append((round(t, 2), d.direction, d.to_world, d.reason))
        t += 0.25
    assert [(d, w) for _, d, w, _ in decs] == [("out", 4), ("out", 8), ("in", 4)]
    assert all(r == "plan" for *_, r in decs)
    # entries fire at (not before) their offsets
    assert [x[0] for x in decs] == [1.0, 2.0, 5.0]


def test_engine_window_resets_after_decision():
    """Post-reform signals describe new capacity: no instant double-fire."""
    eng = PolicyEngine(acfg(cooldown_sec=0.0), world=1, ladder=[1, 2, 4])
    d, t = feed(eng, 0.0, 30.0, p=1.0)
    assert d is not None
    # zero cooldown, but the window restarted: the next decision still
    # needs a FULL fresh sustain window
    d2, t2 = feed(eng, t + 0.25, t + 30.0, p=1.0)
    assert d2 is not None
    assert t2 - t >= eng.acfg.sustain_sec


def test_world_ladder_and_plan_parsing():
    assert world_ladder(1, 8) == [1, 2, 3, 4, 5, 6, 7, 8]
    assert world_ladder(2, 8, divisors_of=8) == [2, 4, 8]
    assert world_ladder(1, 6, divisors_of=12) == [1, 2, 3, 4, 6]
    with pytest.raises(AnalysisError, match="empty"):
        world_ladder(5, 7, divisors_of=8)
    assert parse_plan("out@1.5, in@3") == [("out", 1.5), ("in", 3.0)]
    with pytest.raises(AnalysisError):
        parse_plan("sideways@1")
    # config validation refuses malformed knobs eagerly
    with pytest.raises(ValueError):
        AutoscaleConfig(plan="out@nope")
    with pytest.raises(ValueError):
        AutoscaleConfig(min_world=0)
    with pytest.raises(ValueError):
        AutoscaleConfig(min_world=4, max_world=2)
    with pytest.raises(ValueError):
        AutoscaleConfig(out_threshold=1.5)
    rt = AutoscaleConfig.from_dict(acfg(plan="out@2").to_dict())
    assert rt == acfg(plan="out@2")


def test_engine_off_ladder_world_refused():
    with pytest.raises(AnalysisError, match="ladder"):
        PolicyEngine(acfg(), world=3, ladder=[2, 4, 8])


# ---------------------------------------------------------------------------
# Metrics adapters: JSONL tail, signal differentiation, Prometheus text
# ---------------------------------------------------------------------------


def test_metrics_tail_tolerates_torn_and_missing(tmp_path):
    p = str(tmp_path / "m.jsonl")
    tail = MetricsTail(p)
    assert tail.poll() == []  # not created yet: worker still starting
    with open(p, "w") as f:
        f.write('{"kind":"snapshot","t":1}\n{"kind":"snap')
        f.flush()
    recs = tail.poll()
    assert [r["t"] for r in recs] == [1]
    with open(p, "a") as f:
        f.write('shot","t":2}\n')
    assert [r["t"] for r in tail.poll()] == [2]  # torn line completed


def test_controller_tails_metrics_and_publishes_once(tmp_path):
    """The elastic leader's controller: differentiates the cumulative
    ingest counters over a smoothing stride, decides, publishes exactly
    ONE scale request, then stops (the re-formation replaces it)."""
    mpath = str(tmp_path / "metrics.jsonl")
    published = []
    ctrl = AutoscaleController(
        acfg(out_threshold=0.4, sustain_sec=0.4, cooldown_sec=0.1,
             poll_sec=0.05),
        world=2, ladder=[1, 2, 4],
        metrics_path=mpath, publish=published.append, budget_left=4,
    )
    ctrl.start()
    try:
        t0 = time.time()
        bp = 0.0
        with open(mpath, "w") as f:
            # ~8s of device-bound snapshots, written faster than real
            # time; the >=1s differentiation stride sees bp/dt ~= 0.9
            for i in range(28):
                bp += 0.27
                f.write(json.dumps({
                    "kind": "snapshot", "t": t0 + 0.3 * (i + 1),
                    "lines": 64 * i, "lines_per_sec_inst": 200.0,
                    "ingest": {
                        "backpressure_sec": round(bp, 3),
                        "starved_sec": 0.01 * i, "queue_depth": 2,
                    },
                }) + "\n")
                f.flush()
                time.sleep(0.05)
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline and not published:
            time.sleep(0.05)
    finally:
        ctrl.stop()
        ctrl.join(timeout=10)
    assert ctrl.error is None
    assert len(published) == 1  # one request per controller, ever
    dec = published[0]
    assert (dec.direction, dec.from_world, dec.to_world) == ("out", 2, 4)
    assert dec.evidence["pressure"]["min"] >= 0.4
    assert not ctrl.is_alive()  # returned after publishing


def test_controller_observe_only_logs_without_publishing(tmp_path):
    """Budget 0 (the rollout drill): decisions land in the log with
    their evidence, but nothing is ever published/actuated."""
    mpath = str(tmp_path / "metrics.jsonl")
    published, logged = [], []
    ctrl = AutoscaleController(
        acfg(out_threshold=0.4, sustain_sec=0.4, cooldown_sec=0.2,
             poll_sec=0.05, reform_budget=0),
        world=2, ladder=[1, 2, 4],
        metrics_path=mpath, publish=published.append,
        log=logged.append, budget_left=0,
    )
    ctrl.start()
    try:
        t0 = time.time()
        bp = 0.0
        with open(mpath, "w") as f:
            for i in range(28):
                bp += 0.27
                f.write(json.dumps({
                    "kind": "snapshot", "t": t0 + 0.3 * (i + 1),
                    "ingest": {"backpressure_sec": round(bp, 3),
                               "starved_sec": 0.0},
                }) + "\n")
                f.flush()
                time.sleep(0.05)
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline and not logged:
            time.sleep(0.05)
    finally:
        ctrl.stop()
        ctrl.join(timeout=10)
    assert ctrl.error is None
    assert published == []  # never actuated
    assert logged and not logged[0].actuate
    assert logged[0].evidence["pressure"]["min"] >= 0.4


def test_ingest_signals_differentiate_cumulative_counters():
    mk = lambda t, bp, st: {  # noqa: E731
        "t": t, "ingest": {"backpressure_sec": bp, "starved_sec": st},
    }
    assert ingest_signals(None, mk(0, 0, 0)) is None  # nothing to diff yet
    p, s = ingest_signals(mk(0, 0, 0), mk(2.0, 1.0, 0.5))
    assert (p, s) == (0.5, 0.25)
    # clamped to [0, 1] even when counters jump a whole blocked burst
    p, s = ingest_signals(mk(0, 0, 0), mk(1.0, 5.0, 0.0))
    assert (p, s) == (1.0, 0.0)
    assert ingest_signals(mk(5, 0, 0), mk(5, 1, 1)) is None  # dt <= 0
    assert ingest_signals(mk(0, 0, 0), {"t": 1}) is None  # no ingest gauge


def test_render_prom_exposition_format():
    text = render_prom(
        {"queue_depth": 12, "rate": 1.5, "name": "skipme", "ok": True},
        prefix="ra_serve_",
    )
    lines = text.strip().split("\n")
    assert "# TYPE ra_serve_queue_depth gauge" in lines
    assert "ra_serve_queue_depth 12" in lines
    assert "ra_serve_rate 1.5" in lines
    assert "ra_serve_ok 1" in lines  # booleans export as 0/1
    assert not any("skipme" in ln for ln in lines)  # non-numeric skipped
    assert text.endswith("\n")


def test_trace_summary_autoscale_block(tmp_path):
    """The trace alone answers what/why/how-fast for every scale event."""
    sys.path.insert(0, os.path.join(_REPO, "tools"))
    import trace_summary

    def decide(ts, seq, direction, frm, to, reason):
        return {
            "ph": "i", "name": "autoscale.decide", "ts": ts, "pid": 1,
            "args": {
                "seq": seq, "direction": direction, "from_world": frm,
                "to_world": to, "reason": reason, "actuate": True,
                "damping_window_sec": 3.0,
                "evidence": {
                    "window_sec": 0.6,
                    "pressure": {"min": 0.4, "threshold": 0.25},
                },
            },
        }

    events = [
        {"ph": "X", "name": "step.dispatch", "ts": 0, "dur": 1000, "pid": 1},
        decide(1_000_000, 1, "out", 2, 4, "backpressure"),
        {"ph": "X", "name": "autoscale.apply", "ts": 1_000_100,
         "dur": 25_000, "pid": 1},
        # a reversal INSIDE the 3s damping window: one flap
        decide(3_000_000, 2, "in", 4, 2, "starvation"),
        {"ph": "X", "name": "autoscale.apply", "ts": 3_000_100,
         "dur": 15_000, "pid": 1},
        # and one far outside it: a legitimate load response
        decide(9_000_000, 3, "out", 2, 4, "backpressure"),
        {"ph": "i", "name": "autoscale.retire", "ts": 9_100_000, "pid": 2},
        {"ph": "i", "name": "autoscale.standby", "ts": 9_200_000, "pid": 3},
    ]
    p = str(tmp_path / "trace.json")
    with open(p, "w") as f:
        json.dump({"traceEvents": events}, f)
    s = trace_summary.summarize(p)
    a = s["autoscale"]
    assert a["scale_out"] == 2 and a["scale_in"] == 1
    assert a["flaps"] == 1
    assert a["applies"] == 2
    assert a["time_to_effect_max_ms"] == 25.0
    assert a["retirements"] == 1 and a["standby_parks"] == 1
    assert [d["seq"] for d in a["decisions"]] == [1, 2, 3]
    assert a["decisions"][0]["evidence"]["pressure"]["min"] == 0.4
    text = trace_summary.render(s)
    assert "autoscale: 2 out / 1 in, 1 flap(s)" in text
    assert "#1 out 2->4 (backpressure)" in text
    assert "[min 0.4 >= thr 0.25" in text


# ---------------------------------------------------------------------------
# The reshard law: epoch cursors round-trip across world sizes
# 1 -> 8 -> 3 -> 8, registers bit-identical to a single-world replay.
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def reshard_corpus(tmp_path_factory):
    td = tmp_path_factory.mktemp("reshard")
    cfg_text = synth.synth_config(n_acls=3, rules_per_acl=6, seed=51)
    rs = aclparse.parse_asa_config(cfg_text, "fw1")
    packed = pack.pack_rulesets([rs])
    tuples = synth.synth_tuples(packed, 960, seed=52)
    lines = synth.render_syslog(packed, tuples, seed=53, variety=0.3)
    shards = []
    for i in range(8):
        p = td / f"s{i}.log"
        p.write_text(
            "".join(ln + "\n" for ln in lines[i * 120:(i + 1) * 120]),
            encoding="utf-8",
        )
        shards.append(str(p))
    return packed, shards


def test_reshard_grow_and_shrink_registers_bit_identical(reshard_corpus):
    """Worlds 1 -> 8 -> 3 -> 8 over the same corpus == one world-1 pass.

    Each generation re-splits the REMAINING work from the merged cursor
    manifest (`assign_shards`, exactly what a planned scale re-formation
    does), consumes a bounded slice through the real device step, and
    merges its register contribution under the epoch-ring laws.  Any
    lost, duplicated, or re-ordered line shows up as a register diff.
    """
    import jax

    from ruleset_analysis_tpu.models import pipeline
    from ruleset_analysis_tpu.parallel import mesh as mesh_lib
    from ruleset_analysis_tpu.parallel.step import make_parallel_step
    from ruleset_analysis_tpu.runtime.elastic import assign_shards
    from ruleset_analysis_tpu.runtime.serve import (
        merge_register_arrays, zero_arrays,
    )
    from ruleset_analysis_tpu.runtime.stream import _ShardCursorSource

    packed, shards = reshard_corpus
    cfg = AnalysisConfig(
        batch_size=64,
        sketch=SketchConfig(cms_width=1 << 10, cms_depth=2, hll_p=6),
    )
    mesh = mesh_lib.make_mesh(list(jax.devices())[:1], axis=cfg.mesh_axis)
    step = make_parallel_step(mesh, cfg, packed.n_keys)
    rules = pipeline.ship_ruleset(packed)

    def consume(assignment, max_batches):
        """One rank of one generation: (register image, cursors, done)."""
        src = _ShardCursorSource(packed, assignment, native=False)
        state = pipeline.init_state(packed.n_keys, cfg)
        n = 0
        for batch, _n_raw in src.batches(0, 64):
            wire = pack.compact_batch(batch)
            state, _ = step(
                state, rules, mesh_lib.shard_batch(mesh, wire, cfg.mesh_axis)
            )
            n += 1
            if max_batches is not None and n >= max_batches:
                break
        return dict(pipeline.state_to_host(state)), src.cursors, src.done

    def staged(worlds_and_quota):
        cursors: dict[int, int] = {}
        done: set[int] = set()
        total = zero_arrays(packed.n_keys, cfg)
        consumed_epochs = []
        for world, quota in worlds_and_quota:
            parts = assign_shards(shards, cursors, done, world)
            images = []
            for assignment in parts:
                if not assignment:
                    continue  # more ranks than remaining shards
                img, cur, sub_done = consume(assignment, quota)
                images.append(img)
                # the epoch manifest merge: every rank's cursors union
                cursors.update(cur)
                done |= sub_done
            if images:
                total = merge_register_arrays([total] + images)
            consumed_epochs.append(
                (world, sum(cursors.values()), sorted(done))
            )
        # the final generation must have drained everything
        assert set(range(len(shards))) == done, consumed_epochs
        assert sum(cursors.values()) == 960
        return total

    # grow AND shrink: 1 -> 8 -> 3 -> 8 (bounded slices force mid-shard
    # cursors at every boundary), vs. one uninterrupted world-1 pass
    got = staged([(1, 3), (8, 1), (3, 2), (8, None)])
    want = staged([(1, None)])
    for name in sorted(want):
        np.testing.assert_array_equal(got[name], want[name], err_msg=name)


# ---------------------------------------------------------------------------
# Hybrid DCN x ICI mesh: bit-identical to the flat mesh over the same
# devices (the acceptance pin for `--mesh hybrid`).
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def hybrid_corpus(tmp_path_factory):
    td = tmp_path_factory.mktemp("hybrid")
    cfg_text = synth.synth_config(
        n_acls=2, rules_per_acl=8, seed=7, v6_fraction=0.25
    )
    rs = aclparse.parse_asa_config(cfg_text, "fw1")
    packed = pack.pack_rulesets([rs])
    t = synth.synth_tuples(packed, 700, seed=1)
    lines = synth.render_syslog(packed, t, seed=1)
    t6 = synth.synth_tuples6(packed, 150, seed=2)
    lines += synth.render_syslog6(packed, t6, seed=3)
    text = str(td / "mix.log")
    with open(text, "w", encoding="utf-8") as f:
        f.write("\n".join(lines) + "\n")
    from ruleset_analysis_tpu.hostside import wire as wire_mod

    wirep = str(td / "mix.rawire")
    wire_mod.convert_logs(packed, [text], wirep, block_rows=256)
    return packed, lines, wirep


def test_mesh_constructors_and_axes():
    import jax

    from ruleset_analysis_tpu.parallel import mesh as mesh_lib

    devs = list(jax.devices())
    flat = mesh_lib.make_mesh(devs, "data")
    assert mesh_lib.data_axes(flat) == "data"
    assert mesh_lib.data_extent(flat) == len(devs)
    hyb = mesh_lib.make_mesh(devs, "data", topology="hybrid", dcn=2)
    assert hyb.axis_names == ("dcn", "data")
    assert dict(hyb.shape) == {"dcn": 2, "data": len(devs) // 2}
    assert mesh_lib.data_axes(hyb) == ("dcn", "data")
    assert mesh_lib.data_extent(hyb) == len(devs)
    # device ORDER is preserved: slice placement identical to flat
    assert [d.id for d in hyb.devices.flat] == [d.id for d in flat.devices.flat]
    with pytest.raises(AnalysisError, match="divide"):
        mesh_lib.make_mesh(devs, "data", topology="hybrid", dcn=3)
    with pytest.raises(AnalysisError, match=">= 2"):
        mesh_lib.make_mesh(devs, "data", topology="hybrid", dcn=1)
    with pytest.raises(AnalysisError, match="topology"):
        mesh_lib.make_mesh(devs, "data", topology="weird")
    # the padded batch covers the PRODUCT of both axes
    assert mesh_lib.pad_batch_size(9, hyb, "data") == 16


@pytest.mark.parametrize("kind", ["text", "wire"])
def test_hybrid_mesh_report_bit_identical_to_flat(hybrid_corpus, kind):
    packed, lines, wirep = hybrid_corpus

    def run(shape, dcn=0):
        cfg = AnalysisConfig(batch_size=128, mesh_shape=shape, mesh_dcn=dcn)
        rep = (
            run_stream_wire(packed, wirep, cfg, topk=5)
            if kind == "wire"
            else run_stream(packed, iter(lines), cfg, topk=5)
        )
        return report_image(rep)

    flat = run("flat")
    assert run("hybrid") == flat          # auto dcn: 2 x 4
    assert run("hybrid", dcn=4) == flat   # 4 x 2: grouping-invariant


def test_hybrid_mesh_config_validation():
    with pytest.raises(ValueError, match="mesh_shape"):
        AnalysisConfig(mesh_shape="ring")
    with pytest.raises(ValueError, match="mesh_dcn"):
        AnalysisConfig(mesh_dcn=2)  # only applies to hybrid
    AnalysisConfig(mesh_shape="hybrid", mesh_dcn=2)  # ok


# ---------------------------------------------------------------------------
# Serve actuation: the e2e acceptance (bursty load -> out -> in, windows
# bit-identical) and the chaos seams.
# ---------------------------------------------------------------------------

SERVE_CFG = dict(
    batch_size=64,
    sketch=SketchConfig(cms_width=1 << 10, cms_depth=2, hll_p=6),
)


@pytest.fixture(scope="module")
def serve_corpus(tmp_path_factory):
    td = tmp_path_factory.mktemp("as_serve")
    cfg_text = synth.synth_config(n_acls=2, rules_per_acl=8, seed=0)
    rs = aclparse.parse_asa_config(cfg_text, "fw1")
    packed = pack.pack_rulesets([rs])
    prefix = str(td / "rules")
    pack.save_packed(packed, prefix)
    t = synth.synth_tuples(packed, 3000, seed=1)
    lines = synth.render_syslog(packed, t, seed=1)
    return packed, prefix, lines


def _start(drv):
    out: dict = {}

    def runner():
        try:
            out["summary"] = drv.run()
        except BaseException as e:
            out["error"] = e

    th = threading.Thread(target=runner)
    th.start()
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline and not (
        "error" in out or (drv.listeners.listeners and drv.listeners.alive())
    ):
        time.sleep(0.05)
    return th, out


def _wait(pred, timeout, msg):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.1)
    raise AssertionError(f"timed out waiting for {msg}")


def test_serve_autoscale_e2e_burst_out_idle_in(serve_corpus, tmp_path):
    """The acceptance run: a traffic burst scales the serve mesh OUT
    (threshold-driven, evidence attached), the post-burst idle scales it
    back IN after cooldown, zero flaps, zero drops — and every published
    window is bit-identical to an offline replay of exactly its lines.
    """
    import urllib.request

    from ruleset_analysis_tpu.runtime.serve import ServeDriver

    packed, prefix, lines = serve_corpus
    cfg = AnalysisConfig(**SERVE_CFG)
    a = acfg(
        min_world=2, max_world=8, initial_world=2,
        out_threshold=0.3, in_threshold=0.8,
        sustain_sec=0.5, cooldown_sec=1.0, reform_budget=4, poll_sec=0.1,
    )
    scfg = ServeConfig(
        listen=("tcp:127.0.0.1:0",), window_lines=1000, ring=4,
        serve_dir=str(tmp_path / "serve"), http="127.0.0.1:0",
        # queue bigger than the burst: pressure rises, nothing drops
        queue_lines=4096, checkpoint_every_windows=0, reload_watch=False,
        stop_after_sec=240,
    )
    drv = ServeDriver(prefix, cfg, scfg, topk=5, ascfg=a)
    th, out = _start(drv)
    assert "error" not in out, out.get("error")
    assert drv.world == 2  # starts at the configured initial rung

    # burst: the whole corpus at once — the device tier falls behind,
    # queue occupancy (the pressure signal) sustains above threshold
    s = socket.create_connection(drv.listeners.listeners[0].address)
    s.sendall(("\n".join(lines) + "\n").encode())
    s.close()
    _wait(
        lambda: any(d.direction == "out" for d in drv._engine.decisions),
        90, "scale-out under burst",
    )
    # idle: the queue drains, starvation sustains, the mesh comes back
    _wait(
        lambda: any(d.direction == "in" for d in drv._engine.decisions),
        120, "scale-in after the burst",
    )

    # the one-source-of-truth metrics surface, Prometheus variant
    host, port = drv.http_address
    with urllib.request.urlopen(
        f"http://{host}:{port}/metrics?format=prom", timeout=10
    ) as r:
        assert "version=0.0.4" in r.headers["Content-Type"]
        prom = r.read().decode()
    assert "ra_serve_queue_depth " in prom
    assert "ra_serve_world " in prom
    assert "ra_serve_autoscale_scale_out_total" in prom
    with urllib.request.urlopen(
        f"http://{host}:{port}/metrics", timeout=10
    ) as r:
        gauges = json.load(r)
    assert gauges["world"] == drv.world  # JSON variant: same gauges

    _wait(lambda: drv.windows_published >= 3, 120, "3 windows")
    drv.stop()
    th.join(timeout=120)
    assert not th.is_alive(), "serve hung after stop"
    assert "error" not in out, out.get("error")
    summary = out["summary"]

    # decisions carried their evidence and stayed within budget
    asum = summary["autoscale"]
    assert asum["scale_out"] >= 1 and asum["scale_in"] >= 1
    assert asum["flaps"] == 0
    assert asum["budget_left"] >= 0
    outs = [d for d in asum["decisions"] if d["direction"] == "out"]
    assert outs[0]["reason"] == "backpressure"
    assert outs[0]["evidence"]["pressure"]["min"] >= a.out_threshold
    assert outs[0]["evidence"]["time_to_effect_sec"] >= 0
    ins = [d for d in asum["decisions"] if d["direction"] == "in"]
    assert ins[0]["reason"] == "starvation"
    # every decision stays on the divisor ladder
    ladder = world_ladder(2, 8, divisors_of=8)
    assert all(d["to_world"] in ladder for d in asum["decisions"])
    assert summary["drops"] == 0

    # window fidelity ACROSS scale events: each published window is
    # bit-identical to an offline fixed-world replay of its lines
    for i in range(3):
        with open(
            os.path.join(scfg.serve_dir, f"window-{i:06d}.json"),
            encoding="utf-8",
        ) as f:
            got = json.load(f)
        seg = lines[i * 1000:(i + 1) * 1000]
        want = run_stream(packed, iter(seg), cfg, topk=5)
        assert report_image(got) == report_image(want), f"window {i}"


def test_serve_autoscale_rejects_bad_geometry(serve_corpus, tmp_path):
    from ruleset_analysis_tpu.runtime.serve import ServeDriver

    _packed, prefix, _lines = serve_corpus
    scfg = ServeConfig(
        listen=("tcp:127.0.0.1:0",), window_lines=100,
        serve_dir=str(tmp_path / "s"), http="off", reload_watch=False,
    )
    # the hybrid topology is the multi-host direction; serve autoscale
    # resizes a flat mesh — combining them is a config error
    with pytest.raises(AnalysisError, match="hybrid"):
        ServeDriver(
            prefix, AnalysisConfig(**SERVE_CFG, mesh_shape="hybrid"),
            scfg, ascfg=acfg(),
        )
    # off-ladder initial world (3 does not divide 8)
    drv = None
    with pytest.raises(AnalysisError, match="ladder"):
        drv = ServeDriver(
            prefix, AnalysisConfig(**SERVE_CFG), scfg,
            ascfg=acfg(min_world=1, max_world=8, initial_world=3),
        )
        drv.run()
    # more worlds than devices
    with pytest.raises(AnalysisError, match="devices"):
        drv = ServeDriver(
            prefix, AnalysisConfig(**SERVE_CFG), scfg,
            ascfg=acfg(max_world=64),
        )
        drv.run()


# ---------------------------------------------------------------------------
# Chaos: the decide->actuate seam.  Injected faults at autoscale.decide /
# autoscale.spawn — during rotation, checkpointing, and mid-stream — must
# end in a typed abort or an intact, bit-identical service.  Scripted
# plans make the decision times deterministic.
# ---------------------------------------------------------------------------

CHAOS_W = 100
CHAOS_LINES = 300


def chaos_schedule(seed: int):
    site = ["autoscale.decide", "autoscale.spawn"][seed % 2]
    # hit 1-2: land on the 1st/2nd scale decision (which interleave
    # with rotations and, for odd seeds, ring checkpoints); 99: a
    # never-fires schedule (the clean-run branch)
    at = [1, 2, 99][(seed // 2) % 3]
    return site, at, faults.FaultPlan([faults.FaultSpec(site, at)], seed=seed)


@pytest.mark.parametrize("seed", range(6))
def test_chaos_autoscale_seam(seed, serve_corpus, tmp_path):
    from ruleset_analysis_tpu.runtime.serve import ServeDriver

    packed, prefix, lines = serve_corpus
    lines = lines[:CHAOS_LINES]
    site, at, plan = chaos_schedule(seed)
    cfg = AnalysisConfig(**SERVE_CFG)
    a = acfg(
        min_world=2, max_world=8, initial_world=2,
        reform_budget=4, poll_sec=0.05,
        # scripted decisions, timed to interleave with the rotations the
        # 100-line windows force while the 300-line corpus drains
        plan="out@0.3,in@1.2,out@2.1",
    )
    scfg = ServeConfig(
        listen=("tcp:127.0.0.1:0",), window_lines=CHAOS_W, ring=4,
        serve_dir=str(tmp_path / "serve"), max_windows=3, http="off",
        queue_lines=10_000, reload_watch=False, stop_after_sec=90,
        # odd seeds checkpoint every rotation: scale events interleave
        # with ring checkpoint writes too
        checkpoint_every_windows=(1 if seed % 2 else 0),
        checkpoint_dir=str(tmp_path / "ck"),
    )
    out: dict = {}
    with faults.armed(plan):
        drv = ServeDriver(prefix, cfg, scfg, topk=5, ascfg=a)
        th, out = _start(drv)
        if "error" not in out:
            s = socket.create_connection(drv.listeners.listeners[0].address)
            # paced feed so decision offsets interleave mid-stream
            for i in range(0, CHAOS_LINES, 50):
                s.sendall(("\n".join(lines[i:i + 50]) + "\n").encode())
                time.sleep(0.05)
            s.close()
        th.join(timeout=150)
        assert not th.is_alive(), f"seed {seed} ({site}@{at}): serve HUNG"

    if "error" in out:
        # the typed-abort branch: the injected failure at the seam
        # surfaced as a typed error, never a half-applied scale event
        assert isinstance(out["error"], AnalysisError), (
            f"seed {seed} ({site}@{at}): untyped {out['error']!r}"
        )
        assert at <= 3, f"seed {seed}: never-fire schedule aborted"
        return

    # the clean branch: all 3 windows published, each bit-identical to
    # an offline replay over exactly its lines, drops still zero
    summary = out["summary"]
    assert summary["windows_published"] == 3
    assert summary["drops"] == 0
    for i in range(3):
        with open(
            os.path.join(scfg.serve_dir, f"window-{i:06d}.json"),
            encoding="utf-8",
        ) as f:
            got = json.load(f)
        seg = lines[i * CHAOS_W:(i + 1) * CHAOS_W]
        want = run_stream(packed, iter(seg), cfg, topk=5)
        assert report_image(got) == report_image(want), (
            f"seed {seed} ({site}@{at}): window {i} diverged"
        )


# ---------------------------------------------------------------------------
# Elastic actuation (subprocess drills — slow tier): planned scale
# events drive REAL re-formations through the epoch checkpoints, with
# warm standbys parking and promoting, report always bit-identical.
# ---------------------------------------------------------------------------


def _launcher_env(n_local_devices: int) -> dict:
    sys.path.insert(0, _REPO)
    from __graft_entry__ import scrubbed_cpu_env

    env = scrubbed_cpu_env(n_local_devices)
    env["RA_TEST_REEXEC"] = "1"
    return env


@pytest.fixture(scope="module")
def elastic_corpus(tmp_path_factory):
    td = tmp_path_factory.mktemp("as_elastic")
    cfg_text = synth.synth_config(
        n_acls=3, rules_per_acl=8, seed=41, egress_acls=True
    )
    rs = aclparse.parse_asa_config(cfg_text, "fw1")
    packed = pack.pack_rulesets([rs])
    tuples = synth.synth_tuples(packed, 1600, seed=42)
    lines = synth.render_syslog(packed, tuples, seed=43, variety=0.4)
    prefix = str(td / "packed")
    pack.save_packed(packed, prefix)
    shards = []
    for i in range(4):
        p = td / f"shard{i}.log"
        p.write_text(
            "".join(ln + "\n" for ln in lines[i * 400:(i + 1) * 400]),
            encoding="utf-8",
        )
        shards.append(str(p))
    return td, prefix, shards


def _spawn_autoscale_launchers(
    td, prefix, shards, *, n, flags, pace="0.3", fault_plan=None, timeout=400,
):
    env = _launcher_env(2)
    env["RA_ELASTIC_PACE"] = pace  # slow the stream so policy can react
    eldir = str(td / "eldir")
    procs = [
        subprocess.Popen(
            [sys.executable, "-m", "ruleset_analysis_tpu.cli", "run",
             "--ruleset", prefix, "--logs", *shards, "--backend", "tpu",
             "--distributed", "--elastic", "--elastic-dir", eldir,
             "--num-processes", str(n), "--process-id", str(pid),
             "--batch-size", "64", "--checkpoint-every", "2",
             "--autoscale", *flags,
             *(["--fault-plan", fault_plan] if fault_plan else []),
             "--json", "--out", str(td / f"rep{pid}.json")],
            env=env, cwd=_REPO,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        )
        for pid in range(n)
    ]
    outs = []
    for p in procs:
        try:
            out, err = p.communicate(timeout=timeout)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise AssertionError("autoscale launcher HUNG")
        outs.append((p.returncode, out, err))
    return outs, eldir


def _reference_report(prefix, shards):
    from ruleset_analysis_tpu.runtime.stream import run_stream_file

    packed = pack.load_packed(prefix)
    rep = run_stream_file(packed, shards, AnalysisConfig(batch_size=64))
    return json.loads(rep.to_json())


@pytest.mark.slow
def test_elastic_autoscale_plan_drill_bit_identical(elastic_corpus):
    """Scripted out@2,in@6 against a 4-member pool starting at world 2:
    two planned re-formations (2->3->2) with standbys parking/promoting,
    final report bit-identical to an uninterrupted fixed-world run."""
    td, prefix, shards = elastic_corpus
    outs, eldir = _spawn_autoscale_launchers(
        td, prefix, shards, n=4,
        flags=["--autoscale-min", "2", "--autoscale-max", "4",
               "--autoscale-initial", "2", "--autoscale-budget", "3",
               "--autoscale-plan", "out@2,in@6", "--autoscale-poll", "0.1"],
        pace="0.4",
    )
    for pid, (rc, _out, err) in enumerate(outs):
        assert rc == 0, f"launcher {pid} rc={rc}\n{err[-3000:]}"

    rep = json.load(open(td / "rep0.json"))
    t = rep["totals"]
    asum = t["autoscale"]
    assert asum["scale_events"] == 2
    assert asum["scale_out"] == 1 and asum["scale_in"] == 1
    assert asum["final_world"] == 2
    # every applied event records its time-to-effect
    assert all(e["time_to_effect_sec"] >= 0 for e in asum["applied"])
    # the shared decision log survives for trace tooling
    log = read_decision_log(os.path.join(eldir, "scale-log.jsonl"))
    assert sum(1 for r in log if r.get("kind") == "applied") == 2

    ref = _reference_report(prefix, shards)
    hits = lambda r: {  # noqa: E731
        (e["firewall"], e["acl"], e["index"]): e["hits"] for e in r["per_rule"]
    }
    assert hits(rep) == hits(ref)
    assert rep["unused"] == ref["unused"]
    assert t["lines_total"] == 1600


@pytest.mark.slow
def test_elastic_autoscale_threshold_scale_out(tmp_path_factory):
    """Signal-driven: the CPU device tier is the bottleneck, so rank 0's
    metrics shard shows sustained producer backpressure — the policy
    scales 2 -> 3 from the LIVE signals, no script.  A corpus long
    enough that the decision lands with work left to re-form over."""
    td = tmp_path_factory.mktemp("as_elastic_thr")
    cfg_text = synth.synth_config(
        n_acls=3, rules_per_acl=8, seed=41, egress_acls=True
    )
    rs = aclparse.parse_asa_config(cfg_text, "fw1")
    packed = pack.pack_rulesets([rs])
    tuples = synth.synth_tuples(packed, 2400, seed=42)
    lines = synth.render_syslog(packed, tuples, seed=43, variety=0.4)
    prefix = str(td / "packed")
    pack.save_packed(packed, prefix)
    shards = []
    for i in range(4):
        p = td / f"shard{i}.log"
        p.write_text(
            "".join(ln + "\n" for ln in lines[i * 600:(i + 1) * 600]),
            encoding="utf-8",
        )
        shards.append(str(p))
    outs, _eldir = _spawn_autoscale_launchers(
        td, prefix, shards, n=3,
        flags=["--autoscale-min", "2", "--autoscale-max", "3",
               "--autoscale-initial", "2", "--autoscale-budget", "2",
               "--autoscale-out-threshold", "0.2",
               "--autoscale-sustain", "1.5", "--autoscale-cooldown", "5.0",
               "--autoscale-poll", "0.2"],
        pace="0.05",  # keep the producer fast: pressure, not starvation
    )
    for pid, (rc, _out, err) in enumerate(outs):
        assert rc == 0, f"launcher {pid} rc={rc}\n{err[-3000:]}"
    rep = json.load(open(td / "rep0.json"))
    asum = rep["totals"]["autoscale"]
    assert asum["scale_out"] >= 1
    assert asum["final_world"] == 3
    assert asum["flaps"] == 0
    d = next(x for x in asum["decisions"] if x["direction"] == "out")
    assert d["reason"] == "backpressure"
    assert d["evidence"]["pressure"]["min"] >= 0.2  # evidence attached

    ref = _reference_report(prefix, shards)
    hits = lambda r: {  # noqa: E731
        (e["firewall"], e["acl"], e["index"]): e["hits"] for e in r["per_rule"]
    }
    assert hits(rep) == hits(ref)
    assert rep["unused"] == ref["unused"]


@pytest.mark.slow
def test_elastic_autoscale_spawn_fault_aborts_typed(elastic_corpus, tmp_path_factory):
    """autoscale.spawn armed in every launcher: the first planned scale
    event's actuation fails — every member must exit with a TYPED error
    code in bounded time (no hang, no report), epoch checkpoint intact."""
    td = tmp_path_factory.mktemp("as_elastic_fault")
    _td, prefix, shards = elastic_corpus
    outs, eldir = _spawn_autoscale_launchers(
        td, prefix, shards, n=3,
        flags=["--autoscale-min", "2", "--autoscale-max", "3",
               "--autoscale-initial", "2", "--autoscale-budget", "2",
               "--autoscale-plan", "out@2", "--autoscale-poll", "0.1"],
        pace="0.4", fault_plan="autoscale.spawn@1",
    )
    # the members that processed the scale retirement hit the injected
    # actuation failure: the documented typed-abort exit (1, the
    # catch-all AnalysisError class InjectedFault maps to), with the
    # typed message on stderr — never a raw traceback or a hang
    rcs = sorted(rc for rc, _o, _e in outs)
    assert any(rc == 1 for rc in rcs), rcs
    assert all(rc in (0, 1, 6, 7) for rc in rcs), rcs
    assert not os.path.exists(td / "rep0.json"), "no report after abort"
    for rc, _out, err in outs:
        if rc == 1:
            assert "injected" in err.lower(), err[-1500:]
            assert "Traceback" not in err, err[-1500:]
    # the epoch checkpoint the abort left behind still loads cleanly
    from ruleset_analysis_tpu.runtime import checkpoint as ckpt

    snap = ckpt.load(os.path.join(eldir, "epoch"))
    assert snap is not None and snap.extra.get("elastic", {}).get("cursors")


@pytest.mark.slow
def test_elastic_autoscale_scale_plus_death_interleaving(elastic_corpus, tmp_path_factory):
    """A planned scale-out races a real node death (the re-formation
    interleaving): survivors still finish with a bit-identical report,
    both the scale event and the failure recovery accounted."""
    td = tmp_path_factory.mktemp("as_elastic_mix")
    _td, prefix, shards = elastic_corpus
    env_fault = "tag=3,after_batches=6"
    env = _launcher_env(2)
    env["RA_ELASTIC_PACE"] = "0.4"
    env["RA_ELASTIC_FAULT"] = env_fault
    eldir = str(td / "eldir")
    procs = [
        subprocess.Popen(
            [sys.executable, "-m", "ruleset_analysis_tpu.cli", "run",
             "--ruleset", prefix, "--logs", *shards, "--backend", "tpu",
             "--distributed", "--elastic", "--elastic-dir", eldir,
             "--num-processes", "4", "--process-id", str(pid),
             "--batch-size", "64", "--checkpoint-every", "2",
             "--max-reforms", "2",
             "--autoscale", "--autoscale-min", "2", "--autoscale-max", "4",
             "--autoscale-initial", "3", "--autoscale-budget", "2",
             "--autoscale-plan", "out@2", "--autoscale-poll", "0.1",
             "--json", "--out", str(td / f"rep{pid}.json")],
            env=env, cwd=_REPO,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        )
        for pid in range(4)
    ]
    outs = []
    for p in procs:
        try:
            out, err = p.communicate(timeout=400)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise AssertionError("scale+death drill HUNG")
        outs.append((p.returncode, out, err))

    from ruleset_analysis_tpu.runtime.elastic import DIE_RC

    # tag 3 dies by injection (it may have been promoted into the world
    # by the scale-out, or die as a standby — both legal interleavings);
    # everyone else must complete
    for pid, (rc, _out, err) in enumerate(outs):
        if pid == 3:
            assert rc in (DIE_RC, 0), f"victim rc={rc}\n{err[-2000:]}"
        else:
            assert rc == 0, f"survivor {pid} rc={rc}\n{err[-3000:]}"
    rep = json.load(open(td / "rep0.json"))
    ref = _reference_report(prefix, shards)
    hits = lambda r: {  # noqa: E731
        (e["firewall"], e["acl"], e["index"]): e["hits"] for e in r["per_rule"]
    }
    assert hits(rep) == hits(ref)
    assert rep["unused"] == ref["unused"]
    assert rep["totals"]["lines_total"] == 1600
    asum = rep["totals"]["autoscale"]
    assert asum["scale_events"] >= 1


# ---------------------------------------------------------------------------
# CLI flag surface
# ---------------------------------------------------------------------------


def test_cli_autoscale_flag_validation():
    from ruleset_analysis_tpu import cli

    parser = cli.make_parser()
    # knobs without --autoscale are a usage error
    args = parser.parse_args(
        ["run", "--ruleset", "r", "--logs", "l", "--autoscale-min", "2"]
    )
    with pytest.raises(AnalysisError, match="--autoscale"):
        cli._autoscale_config(args)
    # armed: the flag family maps onto the frozen config
    args = parser.parse_args(
        ["run", "--ruleset", "r", "--logs", "l", "--autoscale",
         "--autoscale-min", "2", "--autoscale-max", "8",
         "--autoscale-plan", "out@1"]
    )
    a = cli._autoscale_config(args)
    assert (a.min_world, a.max_world, a.plan) == (2, 8, "out@1")
    assert cli._autoscale_config(
        parser.parse_args(["run", "--ruleset", "r", "--logs", "l"])
    ) is None
