"""Direct unit tests for the report layer (SURVEY.md L5)."""

import json

from ruleset_analysis_tpu.hostside import aclparse, pack
from ruleset_analysis_tpu.runtime.report import build_report

CFG = """
hostname fwr
access-list A extended permit tcp any host 10.0.0.5 eq 443
access-list A extended deny ip any any
access-list B extended permit udp any any eq 53
access-group A in interface outside
"""


def _packed():
    rs = aclparse.parse_asa_config(CFG, "fwr")
    return pack.pack_rulesets([rs])


def test_per_rule_order_and_unused():
    packed = _packed()
    rep = build_report(
        packed,
        {("fwr", "A", 1): 10, ("fwr", "A", 0): 3},
        backend="tpu",
    )
    # config order: A/1, A/2, B/1, then implicit denies
    keys = [(e["firewall"], e["acl"], e["index"]) for e in rep.per_rule]
    assert keys[:3] == [("fwr", "A", 1), ("fwr", "A", 2), ("fwr", "B", 1)]
    assert ("fwr", "A", 0) in keys and ("fwr", "B", 0) in keys
    # unused = configured rules with zero hits (implicit denies excluded)
    assert rep.unused == [("fwr", "A", 2), ("fwr", "B", 1)]
    assert rep.totals["n_unused"] == 2
    assert rep.totals["n_rules"] == 3


def test_text_report_tags_and_talkers():
    packed = _packed()
    rep = build_report(
        packed,
        {("fwr", "A", 1): 7},
        backend="tpu",
        unique_sources={("fwr", "A", 1): 4},
        talkers={("fwr", "A"): [(0x0A000001, 5), (0x0A000002, 2)]},
    )
    text = rep.to_text()
    assert "rule 1" in text and "implicit-deny" in text
    assert "uniq_src~4" in text
    assert rep.talkers == {"fwr A": [["10.0.0.1", 5], ["10.0.0.2", 2]]}


def test_json_roundtrip_shape():
    packed = _packed()
    rep = build_report(packed, {}, backend="oracle", totals={"lines_total": 9})
    d = json.loads(rep.to_json())
    assert set(d) == {"totals", "per_rule", "unused", "talkers"}
    assert d["totals"]["backend"] == "oracle"
    assert d["totals"]["lines_total"] == 9
    assert all(len(k) == 3 for k in d["unused"])
