"""Window provenance & lineage plane + SLO burn-rate alerting (ISSUE 19).

Pins the lineage-plane invariants (DESIGN §24):

- **Sealed records**: every published window carries a ``totals.lineage``
  record whose CRC covers only the deterministic core — term/path/
  published_unix stay outside it, so replay-identical windows carry
  identical CRCs even across supervisor terms.
- **Ledger durability**: the ``lineage.jsonl`` append is a CORE
  publication step (single-write O_APPEND); the ``lineage.append`` chaos
  site proves a failed append aborts typed BEFORE the window file exists
  — never a torn record, never a window without provenance.
- **Burn-rate hysteresis**: ``--slo`` breach/recovery fire only on
  multi-window burn-rate transitions (fast+slow pair), never per-window,
  and the JSON gauges agree with the labeled prom exposition.
- **Trend hysteresis**: ``rule_burst``/``rule_quiet`` fire once per
  label transition with a minimum-hits floor — steady load emits nothing.
"""

import json
import os
import socket
import threading
import time
import urllib.request

import pytest

pytest.importorskip("jax")

from ruleset_analysis_tpu.config import AnalysisConfig, ServeConfig
from ruleset_analysis_tpu.errors import AnalysisError, InjectedFault
from ruleset_analysis_tpu.hostside import aclparse, pack, synth
from ruleset_analysis_tpu.runtime import faults
from ruleset_analysis_tpu.runtime.metrics import (
    LatencyHistogram,
    SloBurnEngine,
    SloPolicy,
    build_info,
    render_build_info_prom,
    window_slo_stats,
)
from ruleset_analysis_tpu.runtime.report import (
    LINEAGE_VOLATILE,
    lineage_core,
    lineage_frontier,
    seal_lineage,
    trend_events,
)
from ruleset_analysis_tpu.runtime.serve import ServeDriver
from ruleset_analysis_tpu.runtime.wal import LineageLog


# ---------------------------------------------------------------------------
# Record sealing + frontier (pure host-side).
# ---------------------------------------------------------------------------

def _rec(window, *, term=1, path="live", incomplete=None, kind="window"):
    rec = {
        "window": window,
        "kind": kind,
        "hosts": [{
            "rank": 0, "wal_seq_lo": window * 10, "wal_seq_hi": window * 10 + 10,
            "drops": 0, "quarantine_hits": 0,
        }],
        "generation": 0,
        "term": term,
        "path": path,
        "published_unix": 123.0 + window,
    }
    if incomplete:
        rec["incomplete"] = incomplete
    return seal_lineage(rec)


def test_seal_lineage_crc_covers_only_the_core():
    a = _rec(3, term=1, path="live")
    b = _rec(3, term=7, path="replay")
    # the replay-identity law: volatile fields differ, cores agree, and
    # because the CRC covers only the core the two CRCs are EQUAL
    assert lineage_core(a) == lineage_core(b)
    assert a["crc"] == b["crc"]
    assert a["term"] != b["term"] and a["path"] != b["path"]
    for k in LINEAGE_VOLATILE:
        assert k not in lineage_core(a)
    # any core mutation moves the CRC
    c = _rec(3)
    c["hosts"][0]["drops"] = 1
    assert seal_lineage(c)["crc"] != a["crc"]
    # resealing an untouched record is a no-op (the audit idiom)
    assert seal_lineage(dict(a))["crc"] == a["crc"]


def test_lineage_frontier_complete_incomplete_gaps():
    assert lineage_frontier([]) == {
        "windows": 0, "last_complete": None, "first_incomplete": None,
        "gaps": [],
    }
    recs = [_rec(0), _rec(1), _rec(3, incomplete={"reasons": ["drops"]}),
            _rec(4)]
    fr = lineage_frontier(recs)
    assert fr["windows"] == 4
    assert fr["gaps"] == [2]
    assert fr["first_incomplete"] == 2  # the gap precedes the marked one
    assert fr["last_complete"] == 4
    # merged-K records never join the per-window frontier
    fr2 = lineage_frontier(recs + [{"window": 9, "kind": "merged", "k": 2}])
    assert fr2["windows"] == 4
    # last write wins: a replay republish of window 3 heals the marker
    fr3 = lineage_frontier(recs + [_rec(2), _rec(3, path="replay")])
    assert fr3["gaps"] == [] and fr3["first_incomplete"] is None
    assert fr3["last_complete"] == 4


# ---------------------------------------------------------------------------
# SLO policy grammar + burn-rate engine.
# ---------------------------------------------------------------------------

def test_slo_policy_parse_grammar_and_refusals():
    pol = SloPolicy.parse(" p99_publish_ms <= 500 , drop_rate<=0.001 ")
    assert pol.objectives == [("p99_publish_ms", 500.0), ("drop_rate", 0.001)]
    for bad in ("p99_publish_ms>=500", "p99_publish_ms<500", "nonsense",
                "no_such_metric<=1", "drop_rate<=0.1,drop_rate<=0.2", ""):
        with pytest.raises(ValueError):
            SloPolicy.parse(bad)
    # config validation surfaces the same error at construction time
    with pytest.raises(ValueError):
        ServeConfig(window_lines=10, slo="no_such_metric<=1")


def test_slo_burn_engine_breach_and_recovery_transitions():
    eng = SloBurnEngine(
        SloPolicy.parse("drop_rate<=0.001"), fast=3, slow=12, budget=0.01,
    )
    events = []
    # clean windows: no events, burn stays 0
    for w in range(4):
        events += eng.observe({"drop_rate": 0.0, "window": w})
    assert events == []
    assert eng.gauges()["slo_breached"] == 0
    # sustained violation: exactly ONE breach at the transition, then
    # hysteresis swallows the steady-bad windows
    for w in range(4, 10):
        events += eng.observe({"drop_rate": 0.5, "window": w})
    breaches = [e for e in events if e["event"] == "slo.breach"]
    assert len(breaches) == 1
    assert breaches[0]["objective"] == "drop_rate"
    assert breaches[0]["burn_fast"] >= eng.fast_burn
    assert eng.gauges()["slo_breached"] == 1
    assert eng.gauges()["slo_breaches_total"] == 1
    # recovery needs the whole fast window clean: exactly one event
    events = []
    for w in range(10, 16):
        events += eng.observe({"drop_rate": 0.0, "window": w})
    recs = [e for e in events if e["event"] == "slo.recovered"]
    assert len(recs) == 1 and len(events) == 1
    g = eng.gauges()
    assert g["slo_breached"] == 0 and g["slo_recoveries_total"] == 1
    # a window with no latency samples cannot violate a latency bound
    eng2 = SloBurnEngine(SloPolicy.parse("p99_publish_ms<=1"))
    for w in range(12):
        assert eng2.observe({"window": w}) == []
    # labeled gauges carry the per-objective view
    lab = eng.labeled_gauges()
    assert set(lab) == {"drop_rate"}
    assert lab["drop_rate"]["slo_bound"] == 0.001
    assert lab["drop_rate"]["slo_objective_breached"] == 0


def test_window_slo_stats_shape():
    hist = LatencyHistogram()
    for s in (0.001, 0.002, 0.004):
        hist.record(s)
    st = window_slo_stats(
        hist, lines=90, drops=10, incomplete=True, degraded=2, window=7,
    )
    assert st["drop_rate"] == 10 / 100  # drops over OFFERED lines
    assert st["incomplete_rate"] == 1.0
    assert st["degraded_subsystems"] == 2 and st["window"] == 7
    assert st["p99_publish_ms"] > st["p50_publish_ms"] > 0
    empty = window_slo_stats(None, lines=0, drops=0, incomplete=False,
                             degraded=0)
    assert empty["drop_rate"] == 0.0
    assert "p99_publish_ms" not in empty


def test_build_info_prom_is_one_value_1_gauge():
    info = build_info({"mesh": "hybrid/2"})
    assert info["mesh"] == "hybrid/2" and info["version"]
    prom = render_build_info_prom(info)
    assert prom.count("ra_build_info{") == 1
    assert prom.rstrip().endswith("} 1")
    for k, v in info.items():
        assert f'{k}="{v}"' in prom


# ---------------------------------------------------------------------------
# Per-rule trend hysteresis (pure host-side).
# ---------------------------------------------------------------------------

def _trend_rep(hits_by_idx, lines):
    return {
        "per_rule": [
            {"firewall": "fw1", "acl": "a", "index": i, "hits": h}
            for i, h in hits_by_idx.items()
        ],
        "totals": {"lines_total": lines},
    }


def test_trend_events_hysteresis_no_storm():
    state: dict = {}
    steady = _trend_rep({0: 100, 1: 50}, 1000)
    # steady load: nothing, ever
    for _ in range(5):
        assert trend_events(steady, steady, threshold=4.0, state=state) == []
    # a 10x burst: ONE event at the transition...
    burst = _trend_rep({0: 1000, 1: 50}, 1000)
    evs = trend_events(steady, burst, threshold=4.0, state=state)
    assert [e["event"] for e in evs] == ["rule_burst"]
    assert evs[0]["rule"] == "fw1 a 0"
    # ...and the sustained burst emits nothing (hysteresis, no storm)
    assert trend_events(burst, burst, threshold=4.0, state=state) == []
    # collapse back: the rate fell under old/threshold -> one quiet event
    evs = trend_events(burst, steady, threshold=4.0, state=state)
    assert [e["event"] for e in evs] == ["rule_quiet"]
    # back in band clears the state silently; a LATER burst re-fires
    trend_events(steady, steady, threshold=4.0, state=state)
    assert state == {}
    evs = trend_events(steady, burst, threshold=4.0, state=state)
    assert [e["event"] for e in evs] == ["rule_burst"]
    # the min-hits floor: a 2 -> 20 jump on a cold rule is noise
    cold_a = _trend_rep({0: 2}, 1000)
    cold_b = _trend_rep({0: 20}, 1000)
    assert trend_events(cold_a, cold_b, threshold=4.0, state={}) == []
    # an ingest lull is NOT every rule going quiet: rates normalise
    lull = _trend_rep({0: 10, 1: 5}, 100)
    assert trend_events(steady, lull, threshold=4.0, state={}) == []


# ---------------------------------------------------------------------------
# LineageLog durability + the lineage.append chaos site.
# ---------------------------------------------------------------------------

def test_lineage_log_roundtrip_and_torn_tail(tmp_path):
    path = str(tmp_path / "lineage.jsonl")
    log = LineageLog(path)
    a, b = _rec(0), _rec(1)
    log.append(a)
    log.append(b)
    log.sync()
    log.close()
    assert LineageLog.read(path) == [a, b]
    # a SIGKILL can tear at most the final line: read skips exactly it
    with open(path, "ab") as f:
        f.write(b'{"window": 2, "torn')
    assert LineageLog.read(path) == [a, b]
    assert LineageLog.read(str(tmp_path / "absent.jsonl")) == []


def test_lineage_append_fault_site_aborts_typed_never_torn(tmp_path):
    path = str(tmp_path / "lineage.jsonl")
    log = LineageLog(path)
    with faults.armed(faults.FaultPlan.parse("lineage.append@1")):
        with pytest.raises(InjectedFault):
            log.append(_rec(0))
    # the abort happened BEFORE the write: the ledger is empty and
    # readable, never torn — and the next append works
    assert LineageLog.read(path) == []
    log.append(_rec(0))
    log.close()
    assert [r["window"] for r in LineageLog.read(path)] == [0]


# ---------------------------------------------------------------------------
# Serve e2e: sealed records on every surface + chaos + SLO gauges.
# ---------------------------------------------------------------------------

RUN_CFG = dict(batch_size=128, prefetch_depth=0)
WL = 150


@pytest.fixture(scope="module")
def corpus(tmp_path_factory):
    """Same geometry as the serve suite so the jit caches stay warm."""
    td = tmp_path_factory.mktemp("lineage")
    cfg_text = synth.synth_config(
        n_acls=2, rules_per_acl=8, seed=0, v6_fraction=0.25
    )
    rs = aclparse.parse_asa_config(cfg_text, "fw1")
    packed = pack.pack_rulesets([rs])
    prefix = str(td / "rules")
    pack.save_packed(packed, prefix)
    t = synth.synth_tuples(packed, 500, seed=1)
    lines = synth.render_syslog(packed, t, seed=1)
    return packed, prefix, lines, str(td)


def start_serve(prefix, cfg, scfg):
    drv = ServeDriver(prefix, cfg, scfg)
    out: dict = {}

    def runner():
        try:
            out["summary"] = drv.run()
        except BaseException as e:  # surfaced by finish()
            out["error"] = e

    th = threading.Thread(target=runner)
    th.start()
    deadline = time.monotonic() + 20
    while time.monotonic() < deadline:
        if out.get("error"):
            break
        if drv.listeners.listeners and drv.listeners.alive() and (
            scfg.http == "off" or drv.http_address
        ):
            break
        time.sleep(0.05)
    return drv, th, out


def finish(th, out, timeout=120):
    th.join(timeout=timeout)
    assert not th.is_alive(), "serve hung"
    if "error" in out:
        raise out["error"]
    return out["summary"]


def send_tcp(addr, lines):
    s = socket.create_connection(addr)
    s.sendall(("\n".join(lines) + "\n").encode())
    s.close()


def get_json(http, path):
    host, port = http
    with urllib.request.urlopen(
        f"http://{host}:{port}{path}", timeout=10
    ) as r:
        return json.load(r)


def wait_for(pred, timeout=60, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.05)
    raise AssertionError(f"timed out waiting for {msg}")


def test_serve_lineage_e2e_ledger_routes_and_slo_gauges(corpus, tmp_path):
    packed, prefix, lines, td = corpus
    scfg = ServeConfig(
        listen=("tcp:127.0.0.1:0",), window_lines=WL, ring=4,
        serve_dir=str(tmp_path / "serve"), max_windows=0, stop_after_sec=90,
        reload_watch=False, checkpoint_every_windows=0, http="127.0.0.1:0",
        queue_lines=10_000, slo="p99_publish_ms<=60000,drop_rate<=0.5",
    )
    drv, th, out = start_serve(prefix, AnalysisConfig(**RUN_CFG), scfg)
    try:
        addr = tuple(drv.listeners.listeners[0].address)
        # the SAME lines twice: two windows of identical traffic, so the
        # per-rule trend plane must stay silent (the no-storm pin)
        send_tcp(addr, lines[:WL])
        wait_for(lambda: out.get("error") or drv.windows_published >= 1,
                 msg="window 0")
        send_tcp(addr, lines[:WL])
        wait_for(lambda: out.get("error") or drv.windows_published >= 2,
                 msg="window 1")
        # windows_published increments BEFORE the publish + SLO-observe
        # phase of the rotation — wait for both planes to catch up
        wait_for(lambda: out.get("error") or (
            drv.lineage_records_total >= 2
            and drv.slo.windows_observed >= 2
        ), msg="window 1 lineage + SLO observation")
        if "error" in out:
            raise out["error"]

        http = drv.http_address
        tail = get_json(http, "/lineage")
        assert tail["records_total"] == 2
        assert [r["window"] for r in tail["records"]] == [0, 1]
        for r in tail["records"]:
            assert r["kind"] == "window" and r["path"] == "live"
            assert r["term"] == 0 and r["generation"] == 0
            h = r["hosts"][0]
            assert h["wal_seq_hi"] >= h["wal_seq_lo"] >= 0
            assert h["drops"] == 0
            # the seal verifies: reseal of the served record is a no-op
            assert seal_lineage(dict(r))["crc"] == r["crc"]
        one = get_json(http, "/lineage/window/1")
        assert one == tail["records"][1]

        m = get_json(http, "/metrics")
        assert m["lineage_records_total"] == 2
        assert m["trend_events_total"] == 0
        assert m["slo_objectives"] == 2
        assert m["slo_windows_observed"] == 2
        assert m["slo_breached"] == 0 and m["slo_breaches_total"] == 0
        assert m["build_info"]["version"]
        host, port = http
        with urllib.request.urlopen(
            f"http://{host}:{port}/metrics?format=prom", timeout=10
        ) as r:
            prom = r.read().decode()
        assert "ra_build_info{" in prom
        assert f'version="{m["build_info"]["version"]}"' in prom
        assert "ra_serve_lineage_records_total 2" in prom
        assert 'ra_serve_slo_bound{objective="drop_rate"} 0.5' in prom
        assert 'ra_serve_slo_burn_fast{objective="p99_publish_ms"}' in prom
    finally:
        drv.stop()
    finish(th, out)

    # disk surfaces: window files, the ledger, and the frontier agree
    sd = scfg.serve_dir
    ledger = LineageLog.read(os.path.join(sd, LineageLog.NAME))
    assert len(ledger) == 2
    for w in range(2):
        with open(os.path.join(sd, f"window-{w:06d}.json")) as f:
            rep = json.load(f)
        lin = rep["totals"]["lineage"]
        assert lin == ledger[w]
        assert lin["window"] == w and "incomplete" not in lin
    fr = lineage_frontier(ledger)
    assert fr == {"windows": 2, "last_complete": 1,
                  "first_incomplete": None, "gaps": []}
    # identical traffic in both windows: zero trend events in the diff
    with open(os.path.join(sd, "diff-000001.json")) as f:
        diff = json.load(f)
    assert "trend_events" not in diff


def test_serve_lineage_disarmed_has_no_plane(corpus, tmp_path):
    packed, prefix, lines, td = corpus
    scfg = ServeConfig(
        listen=("tcp:127.0.0.1:0",), window_lines=WL, ring=4,
        serve_dir=str(tmp_path / "serve"), max_windows=1, stop_after_sec=60,
        reload_watch=False, checkpoint_every_windows=0, http="off",
        queue_lines=10_000, lineage=False,
    )
    drv, th, out = start_serve(prefix, AnalysisConfig(**RUN_CFG), scfg)
    send_tcp(tuple(drv.listeners.listeners[0].address), lines[:WL])
    finish(th, out)
    sd = scfg.serve_dir
    assert not os.path.exists(os.path.join(sd, LineageLog.NAME))
    with open(os.path.join(sd, "window-000000.json")) as f:
        rep = json.load(f)
    assert "lineage" not in rep["totals"]
    assert "lineage_records_total" not in drv.metrics_gauges()


def test_tenant_lineage_shared_ledger_and_routes(tmp_path):
    """Two tenants through one process: the shared ledger carries
    tenant-keyed records (the WAL record-v2 idiom applied to
    provenance), and /lineage + /t/<name>/lineage serve them."""
    from ruleset_analysis_tpu.runtime.tenantserve import TenantServeDriver

    tenants = {}
    for i in range(2):
        cfg_text = synth.synth_config(
            n_acls=2, rules_per_acl=6 + i, seed=10 + i, v6_fraction=0.0
        )
        rs = aclparse.parse_asa_config(cfg_text, f"fw{i}")
        packed = pack.pack_rulesets([rs])
        prefix = os.path.join(str(tmp_path), f"rules{i}")
        pack.save_packed(packed, prefix)
        t = synth.synth_tuples(packed, 100, seed=20 + i)
        tenants[f"t{i}"] = (prefix, synth.render_syslog(packed, t, seed=30 + i))
    manifest = os.path.join(str(tmp_path), "manifest.json")
    with open(manifest, "w", encoding="utf-8") as f:
        json.dump({"tenants": [
            {"name": n, "ruleset": p, "listen": ["tcp:127.0.0.1:0"]}
            for n, (p, _) in sorted(tenants.items())
        ]}, f)
    scfg = ServeConfig(
        listen=(), window_lines=100, ring=8,
        serve_dir=os.path.join(str(tmp_path), "serve"),
        http="127.0.0.1:0", checkpoint_every_windows=0,
        slo="drop_rate<=0.5",
    )
    drv = TenantServeDriver(manifest, AnalysisConfig(**RUN_CFG), scfg)
    out: dict = {}

    def runner():
        try:
            out["summary"] = drv.run()
        except BaseException as e:
            out["error"] = e

    th = threading.Thread(target=runner)
    th.start()
    wait_for(
        lambda: out.get("error") or (
            drv.listeners.alive() == 2 and drv.http_address
        ),
        msg="tenant listeners",
    )
    try:
        if "error" in out:
            raise out["error"]
        by_tenant = {
            ln.q.tenant: tuple(ln.address) for ln in drv.listeners.listeners
        }
        for n, (_, tlines) in tenants.items():
            send_tcp(by_tenant[n], tlines)
        wait_for(
            lambda: out.get("error") or drv.windows_published >= 2,
            timeout=120, msg="one window per tenant",
        )
        # windows_published increments before the publish phase ends —
        # wait for both lanes' lineage appends to land
        wait_for(
            lambda: out.get("error") or drv.lineage_records_total >= 2,
            msg="tenant lineage records",
        )
        if "error" in out:
            raise out["error"]
        http = drv.http_address
        tail = get_json(http, "/lineage")
        assert tail["records_total"] == 2
        assert set(tail["tenants"]) == {"t0", "t1"}
        for n in ("t0", "t1"):
            recs = tail["tenants"][n]
            assert [r["window"] for r in recs] == [0]
            assert recs[0]["kind"] == "tenant" and recs[0]["tenant"] == n
            assert seal_lineage(dict(recs[0]))["crc"] == recs[0]["crc"]
            sub = get_json(http, f"/t/{n}/lineage")
            assert sub["records"] == recs
        m = get_json(http, "/metrics")
        assert m["lineage_records_total"] == 2
        assert m["slo_objectives"] == 1 and m["slo_breached"] == 0
        assert m["build_info"]["version"]
        host, port = http
        with urllib.request.urlopen(
            f"http://{host}:{port}/metrics?format=prom", timeout=10
        ) as r:
            prom = r.read().decode()
        assert "ra_build_info{" in prom
        assert 'ra_serve_slo_bound{objective="drop_rate"} 0.5' in prom
    finally:
        drv.stop()
    finish(th, out)
    # one shared ledger, tenant-keyed, beside the per-tenant report trees
    ledger = LineageLog.read(os.path.join(scfg.serve_dir, LineageLog.NAME))
    assert sorted(r["tenant"] for r in ledger) == ["t0", "t1"]
    for n in ("t0", "t1"):
        with open(os.path.join(
            scfg.serve_dir, "t", n, "window-000000.json"
        )) as f:
            rep = json.load(f)
        lin = rep["totals"]["lineage"]
        assert lin == next(r for r in ledger if r["tenant"] == n)


def test_serve_lineage_append_chaos_never_publishes_without_record(
    corpus, tmp_path
):
    packed, prefix, lines, td = corpus
    scfg = ServeConfig(
        listen=("tcp:127.0.0.1:0",), window_lines=WL, ring=4,
        serve_dir=str(tmp_path / "serve"), max_windows=2, stop_after_sec=60,
        reload_watch=False, checkpoint_every_windows=0, http="off",
        queue_lines=10_000,
    )
    with faults.armed(faults.FaultPlan.parse("lineage.append@1")):
        drv, th, out = start_serve(prefix, AnalysisConfig(**RUN_CFG), scfg)
        send_tcp(tuple(drv.listeners.listeners[0].address), lines[:WL])
        with pytest.raises(AnalysisError):
            finish(th, out)
    sd = scfg.serve_dir
    # typed abort BEFORE the window file: no window published without
    # its provenance record, and the ledger is readable (never torn)
    assert not os.path.exists(os.path.join(sd, "window-000000.json"))
    assert not os.path.exists(os.path.join(sd, "latest.json"))
    assert LineageLog.read(os.path.join(sd, LineageLog.NAME)) == []
