"""ACL parser unit tests: address/port/protocol normalization and expansion."""

import pytest

from ruleset_analysis_tpu.hostside import aclparse as A


def parse(text, fw="fw1"):
    return A.parse_asa_config(text, fw)


def test_ip_roundtrip():
    assert A.ip_to_u32("0.0.0.0") == 0
    assert A.ip_to_u32("255.255.255.255") == 0xFFFFFFFF
    assert A.ip_to_u32("10.0.0.1") == (10 << 24) | 1
    assert A.u32_to_ip(A.ip_to_u32("192.168.4.77")) == "192.168.4.77"
    with pytest.raises(A.AclParseError):
        A.ip_to_u32("300.1.1.1")
    with pytest.raises(A.AclParseError):
        A.ip_to_u32("1.2.3")


def test_subnet_range():
    lo, hi = A.subnet_range("10.1.2.0", "255.255.255.0")
    assert A.u32_to_ip(lo) == "10.1.2.0"
    assert A.u32_to_ip(hi) == "10.1.2.255"
    lo, hi = A.subnet_range("10.1.2.99", "255.255.255.0")  # host bits set in net
    assert A.u32_to_ip(lo) == "10.1.2.0"


def test_simple_ace():
    rs = parse("access-list OUT extended permit tcp any host 10.0.0.5 eq 443\n")
    [rule] = rs.acls["OUT"]
    assert rule.index == 1
    [ace] = rule.aces
    assert ace.action == A.PERMIT
    assert (ace.proto_lo, ace.proto_hi) == (6, 6)
    assert (ace.src_lo, ace.src_hi) == (0, 0xFFFFFFFF)
    assert ace.dst_lo == ace.dst_hi == A.ip_to_u32("10.0.0.5")
    assert (ace.dport_lo, ace.dport_hi) == (443, 443)
    assert (ace.sport_lo, ace.sport_hi) == (0, 65535)


def test_ip_proto_is_any_proto():
    rs = parse("access-list X extended deny ip 10.0.0.0 255.0.0.0 any\n")
    [ace] = rs.acls["X"][0].aces
    assert (ace.proto_lo, ace.proto_hi) == (0, 255)
    assert ace.action == A.DENY
    assert A.u32_to_ip(ace.src_lo) == "10.0.0.0"
    assert A.u32_to_ip(ace.src_hi) == "10.255.255.255"


def test_port_operators():
    rs = parse(
        "access-list P extended permit tcp any any gt 1023\n"
        "access-list P extended permit tcp any any lt 1024\n"
        "access-list P extended permit tcp any any range 8000 9000\n"
        "access-list P extended permit udp any any neq 53\n"
        "access-list P extended permit tcp any any eq https\n"
    )
    rules = rs.acls["P"]
    assert (rules[0].aces[0].dport_lo, rules[0].aces[0].dport_hi) == (1024, 65535)
    assert (rules[1].aces[0].dport_lo, rules[1].aces[0].dport_hi) == (0, 1023)
    assert (rules[2].aces[0].dport_lo, rules[2].aces[0].dport_hi) == (8000, 9000)
    # neq expands to two rows under one configured rule
    neq = rules[3]
    assert len(neq.aces) == 2
    assert {(a.dport_lo, a.dport_hi) for a in neq.aces} == {(0, 52), (54, 65535)}
    assert (rules[4].aces[0].dport_lo, rules[4].aces[0].dport_hi) == (443, 443)


def test_source_port_spec():
    rs = parse("access-list S extended permit tcp any eq 1024 any eq 80\n")
    [ace] = rs.acls["S"][0].aces
    assert (ace.sport_lo, ace.sport_hi) == (1024, 1024)
    assert (ace.dport_lo, ace.dport_hi) == (80, 80)


def test_network_object_group_expansion():
    rs = parse(
        "object-group network SRV\n"
        " network-object host 10.0.0.1\n"
        " network-object 10.1.0.0 255.255.0.0\n"
        "access-list G extended permit tcp object-group SRV any eq 22\n"
    )
    [rule] = rs.acls["G"]
    assert len(rule.aces) == 2
    assert {(a.src_lo, a.src_hi) for a in rule.aces} == {
        (A.ip_to_u32("10.0.0.1"),) * 2,
        (A.ip_to_u32("10.1.0.0"), A.ip_to_u32("10.1.255.255")),
    }


def test_nested_group_and_cycle_detection():
    rs = parse(
        "object-group network INNER\n"
        " network-object host 1.1.1.1\n"
        "object-group network OUTER\n"
        " group-object INNER\n"
        " network-object host 2.2.2.2\n"
        "access-list N extended permit ip object-group OUTER any\n"
    )
    assert len(rs.acls["N"][0].aces) == 2
    with pytest.raises(A.AclParseError, match="cycle"):
        parse(
            "object-group network A1\n"
            " group-object B1\n"
            "object-group network B1\n"
            " group-object A1\n"
            "access-list C extended permit ip object-group A1 any\n"
        )


def test_service_group_ports():
    rs = parse(
        "object-group service WEB tcp\n"
        " port-object eq 80\n"
        " port-object range 8000 8010\n"
        "access-list W extended permit tcp any any object-group WEB\n"
    )
    [rule] = rs.acls["W"]
    assert {(a.dport_lo, a.dport_hi) for a in rule.aces} == {(80, 80), (8000, 8010)}


def test_generic_service_group_bundles_proto_and_port():
    rs = parse(
        "object-group service MIXED\n"
        " service-object tcp destination eq 443\n"
        " service-object udp destination eq 53\n"
        " service-object icmp\n"
        "access-list M extended permit object-group MIXED any any\n"
    )
    [rule] = rs.acls["M"]
    combos = {(a.proto_lo, a.dport_lo, a.dport_hi) for a in rule.aces}
    assert (6, 443, 443) in combos
    assert (17, 53, 53) in combos
    assert (1, 0, 65535) in combos


def test_protocol_object_group():
    rs = parse(
        "object-group protocol TUNNEL\n"
        " protocol-object esp\n"
        " protocol-object gre\n"
        "access-list T extended permit object-group TUNNEL any any\n"
    )
    [rule] = rs.acls["T"]
    assert {(a.proto_lo, a.proto_hi) for a in rule.aces} == {(50, 50), (47, 47)}


def test_object_network():
    rs = parse(
        "object network WEB1\n"
        " host 10.9.9.9\n"
        "object network NET1\n"
        " subnet 10.8.0.0 255.255.0.0\n"
        "object network RANGE1\n"
        " range 10.7.0.5 10.7.0.9\n"
        "access-list O extended permit tcp object NET1 object WEB1 eq 80\n"
        "access-list O extended permit tcp object RANGE1 any\n"
    )
    r0, r1 = rs.acls["O"]
    assert r0.aces[0].dst_lo == A.ip_to_u32("10.9.9.9")
    assert (r1.aces[0].src_lo, r1.aces[0].src_hi) == (
        A.ip_to_u32("10.7.0.5"),
        A.ip_to_u32("10.7.0.9"),
    )


def test_icmp_type_in_dport_column():
    rs = parse(
        "access-list I extended permit icmp any any echo\n"
        "access-list I extended permit icmp any any 11\n"
        "access-list I extended permit icmp any any\n"
    )
    rules = rs.acls["I"]
    assert (rules[0].aces[0].dport_lo, rules[0].aces[0].dport_hi) == (8, 8)
    assert (rules[1].aces[0].dport_lo, rules[1].aces[0].dport_hi) == (11, 11)
    assert (rules[2].aces[0].dport_lo, rules[2].aces[0].dport_hi) == (0, 65535)


def test_inactive_rule_has_no_rows_but_is_reported():
    rs = parse("access-list D extended permit tcp any any eq 80 inactive\n")
    [rule] = rs.acls["D"]
    assert rule.aces == []
    assert rule.index == 1


def test_remarks_skipped_and_indices_stable():
    rs = parse(
        "access-list R remark allow web\n"
        "access-list R extended permit tcp any any eq 80\n"
        "access-list R remark block rest\n"
        "access-list R extended deny ip any any\n"
    )
    rules = rs.acls["R"]
    assert [r.index for r in rules] == [1, 2]


def test_standard_acl_matches_source():
    rs = parse("access-list STD standard permit 10.0.0.0 255.0.0.0\n")
    [ace] = rs.acls["STD"][0].aces
    assert A.u32_to_ip(ace.src_lo) == "10.0.0.0"
    assert (ace.dst_lo, ace.dst_hi) == (0, 0xFFFFFFFF)
    assert (ace.proto_lo, ace.proto_hi) == (0, 255)


def test_access_group_binding():
    rs = parse(
        "access-list OUT extended permit ip any any\n"
        "access-group OUT in interface outside\n"
    )
    assert rs.bindings[("outside", "in")] == "OUT"


def test_hostname_detection(tmp_path):
    p = tmp_path / "fw.cfg"
    p.write_text("hostname edge-fw-1\naccess-list A extended permit ip any any\n")
    rs = A.parse_config_file(str(p))
    assert rs.firewall == "edge-fw-1"


def test_impossible_port_spec_matches_nothing():
    # "gt 65535" can never match — must NOT degrade to match-all (review finding)
    rs = parse("access-list T extended permit tcp any gt 65535 any\n")
    [rule] = rs.acls["T"]
    assert rule.aces == []
    rs = parse("access-list T2 extended permit tcp any any lt 0\n")
    assert rs.acls["T2"][0].aces == []


def test_icmp_type_group_cycle_and_bad_name():
    with pytest.raises(A.AclParseError, match="cycle"):
        parse(
            "object-group icmp-type IA\n"
            " group-object IB\n"
            "object-group icmp-type IB\n"
            " group-object IA\n"
            "access-list C extended permit icmp any any object-group IA\n"
        )
    with pytest.raises(A.AclParseError, match="unknown icmp type"):
        parse(
            "object-group icmp-type IT\n"
            " icmp-object bogus-name\n"
            "access-list C extended permit icmp any any object-group IT\n"
        )


def test_inverted_ranges_rejected():
    """Real ASA rejects inverted ranges; so must the parser — the device
    kernel's wraparound range check relies on lo <= hi, and silently
    packing an inverted range would make it match almost everything."""
    from ruleset_analysis_tpu.hostside.aclparse import AclParseError, parse_asa_config

    bad_port = """hostname fw1
access-list A extended permit tcp any any range 100 50
access-group A in interface outside
"""
    with pytest.raises(AclParseError, match="inverted port range"):
        parse_asa_config(bad_port, "fw1")

    bad_addr = """hostname fw1
object network SRV
 range 10.0.0.9 10.0.0.1
access-list A extended permit tcp object SRV any
access-group A in interface outside
"""
    with pytest.raises(AclParseError, match="inverted address range"):
        parse_asa_config(bad_addr, "fw1")

    ok = """hostname fw1
access-list A extended permit tcp any any range 50 100
access-group A in interface outside
"""
    rs = parse_asa_config(ok, "fw1")
    assert rs.rule_count() == 1
