"""Observability plane: tracing + metrics under normal and chaos runs.

Three tiers of assertion (ISSUE 4 acceptance):

- **plumbing units** — disarmed no-ops, env-inherited lazy arming, merge
  tolerance of torn shards, Profiler/RecoveryMeter/ThroughputMeter
  satellite behavior;
- **end-to-end trace shape** — a prefetch + multi-worker-feeder run
  through the production CLI produces ONE merged Chrome trace holding
  spans from the main process, the prefetch producer thread, and a
  spawned feeder worker process, plus instant events where armed fault
  sites fired;
- **chaos x observability** — for seeded fault schedules the merged
  trace and the metrics JSONL stay well-formed (parseable, monotonic
  timestamps, no orphan open spans) even when the run ends in a typed
  abort.
"""

import json
import os
import random
import sys

import pytest

pytest.importorskip("jax")

from ruleset_analysis_tpu.config import AnalysisConfig, SketchConfig
from ruleset_analysis_tpu.errors import AnalysisError
from ruleset_analysis_tpu.hostside import aclparse, fastparse, pack, synth
from ruleset_analysis_tpu.hostside import wire as wire_mod
from ruleset_analysis_tpu.runtime import faults, obs
from ruleset_analysis_tpu.runtime.stream import run_stream_file, run_stream_wire

sys.path.insert(
    0,
    os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tools"),
)
import trace_summary  # noqa: E402


@pytest.fixture(autouse=True)
def _obs_clean():
    """Every test starts and ends fully disarmed (env check included)."""
    obs._reset_for_tests()
    yield
    obs._reset_for_tests()


@pytest.fixture(scope="module")
def corpus(tmp_path_factory):
    td = tmp_path_factory.mktemp("obs")
    cfg_text = synth.synth_config(n_acls=3, rules_per_acl=8, seed=7)
    rs = aclparse.parse_asa_config(cfg_text, "fw1")
    packed = pack.pack_rulesets([rs])
    tuples = synth.synth_tuples(packed, 2400, seed=8)
    lines = synth.render_syslog(packed, tuples, seed=9)
    log = str(td / "obs.log")
    with open(log, "w", encoding="utf-8") as f:
        f.write("\n".join(lines) + "\n")
    wirep = str(td / "obs.rawire")
    wire_mod.convert_logs(packed, [log], wirep, block_rows=512)
    prefix = str(td / "packed")
    pack.save_packed(packed, prefix)
    return packed, prefix, log, wirep


def _cfg(depth=2, cadence=0, ckpt_dir="", **kw):
    return AnalysisConfig(
        batch_size=512,
        sketch=SketchConfig(cms_width=1 << 10, cms_depth=2, hll_p=6),
        prefetch_depth=depth,
        checkpoint_every_chunks=cadence,
        **({"checkpoint_dir": ckpt_dir} if ckpt_dir else {}),
        stall_timeout_sec=3.0,
        **kw,
    )


def _load_trace(path: str) -> list[dict]:
    with open(path, "r", encoding="utf-8") as f:
        data = json.load(f)
    return data["traceEvents"]


def _assert_well_formed(events: list[dict]) -> None:
    """The merge contract: parseable, sane stamps, no orphan open spans."""
    assert events, "merged trace is empty"
    for e in events:
        assert e["ph"] in ("X", "i", "M"), f"unexpected phase {e}"
        if e["ph"] == "X":
            assert e["ts"] > 0 and e["dur"] >= 0
    # the recorder only ever writes complete spans, so duration-style
    # begin/end events (which CAN orphan) must never appear
    assert not [e for e in events if e.get("ph") in ("B", "E")]
    # per-track monotonicity: the merge sorts by ts, so each (pid, tid)
    # track must read in nondecreasing time order
    last: dict = {}
    for e in events:
        if e["ph"] != "X":
            continue
        key = (e["pid"], e["tid"])
        assert e["ts"] >= last.get(key, 0), f"time went backwards on {key}"
        last[key] = e["ts"]


def _assert_metrics_well_formed(path: str) -> list[dict]:
    recs = []
    with open(path, "r", encoding="utf-8") as f:
        for ln in f:
            ln = ln.strip()
            if ln:
                recs.append(json.loads(ln))
    assert recs, "metrics file is empty"
    stamps = [r["t"] for r in recs]
    assert stamps == sorted(stamps), "metrics timestamps not monotonic"
    finals = [r for r in recs if r.get("kind") == "final"]
    assert finals, "shutdown never wrote the final snapshot"
    assert all("lines" in r for r in recs if r["kind"] in ("snapshot", "final"))
    return recs


# ---------------------------------------------------------------------------
# Plumbing units
# ---------------------------------------------------------------------------


def test_disarmed_everything_is_noop(tmp_path):
    assert obs.active_tracer() is None
    obs.complete("x", 0.0, 1.0)
    obs.instant("x")
    obs.add_lines(10)
    obs.metric_event("x", a=1)
    obs.register_sampler("x", lambda: {})
    with obs.span("x"):
        pass
    assert obs.timed("x", lambda a: a + 1, 41) == 42
    assert obs.shutdown() is None
    assert not list(tmp_path.iterdir())


def test_trace_env_export_and_lazy_child_arm(tmp_path):
    d = str(tmp_path / "tr")
    obs.start_trace(d, role="main")
    assert os.environ[obs.ENV_VAR] == os.path.abspath(d)
    obs.complete("unit.span", 0.0, 0.001)
    merged = obs.shutdown()
    assert os.environ.get(obs.ENV_VAR) is None
    # simulate a freshly spawned child: module disarmed, env present
    obs._reset_for_tests()
    os.environ[obs.ENV_VAR] = os.path.abspath(d)
    try:
        obs.instant("child.mark")
        assert obs.active_tracer() is not None
    finally:
        obs.shutdown(merge=False)
        os.environ.pop(obs.ENV_VAR, None)
    merged = obs.merge_trace(d)
    names = [e["name"] for e in _load_trace(merged)]
    assert "unit.span" in names and "child.mark" in names


def test_owner_arm_prunes_previous_runs_shards(tmp_path):
    """Re-using a --trace-out dir must not merge last run's events in.

    Covers the immediate abort-and-retry loop: a dead writer's shard is
    pruned even with a FRESH mtime (liveness probe on the PID in the
    shard name), alongside an old-mtime shard and the stale merged
    file.  A live sibling rank's shard must survive.
    """
    d = str(tmp_path)
    # a crashed previous run, seconds ago: writer pid is dead, mtime fresh
    import subprocess

    proc = subprocess.Popen(["true"])
    proc.wait()
    dead_pid = proc.pid
    (tmp_path / f"trace-{dead_pid}.jsonl").write_text(
        f'{{"ph":"X","name":"crashed.run","pid":{dead_pid},"tid":1,"ts":5,"dur":1}}\n'
    )
    # an hour-old leftover (recycled-PID backstop clause)
    stale = tmp_path / "trace-99999.jsonl"
    stale.write_text(
        '{"ph":"X","name":"old.run","pid":99999,"tid":1,"ts":5,"dur":1}\n'
    )
    old = os.path.getmtime(stale) - 2 * obs.STALE_SHARD_SEC
    os.utime(stale, (old, old))
    (tmp_path / "trace.json").write_text("{}")
    # a LIVE sibling rank's shard must survive the prune; pid 1 (init:
    # alive, not ours — the probe's PermissionError branch) stands in
    live = tmp_path / "trace-1.jsonl"
    live.write_text(
        '{"ph":"X","name":"sibling.rank","pid":1,"tid":1,"ts":9,"dur":1}\n'
    )
    obs.start_trace(d, role="main")
    obs.complete("new.span", 0.0, 0.001)
    merged = obs.shutdown()
    names = {e["name"] for e in _load_trace(merged) if e["ph"] == "X"}
    assert names == {"new.span", "sibling.rank"}, (
        "prune kept a dead run's shard or dropped a live sibling's"
    )


def test_cli_unwritable_trace_out_is_usage_error(corpus, tmp_path):
    from ruleset_analysis_tpu import cli

    _packed, prefix, log, _wirep = corpus
    blocker = tmp_path / "not-a-dir"
    blocker.write_text("")  # a FILE where a directory is required
    rc = cli.main([
        "run", "--ruleset", prefix, "--logs", log,
        "--trace-out", str(blocker / "sub"),
    ])
    assert rc == 2  # typed usage error, not a raw traceback


def test_merge_skips_torn_shard_tail(tmp_path):
    d = str(tmp_path)
    tr = obs.start_trace(d, export_env=False)
    obs.complete("good.span", 0.0, 0.001)
    obs.shutdown(merge=False)
    # a worker killed mid-write leaves a torn final line in its shard
    with open(tr.path, "a", encoding="utf-8") as f:
        f.write('{"ph":"X","name":"torn...')
    events = _load_trace(obs.merge_trace(d))
    assert [e["name"] for e in events if e["ph"] == "X"] == ["good.span"]


def test_metrics_snapshot_and_samplers(tmp_path):
    path = str(tmp_path / "m.jsonl")
    obs.start_metrics(path, every_sec=60.0)  # snapshots forced, not timed
    obs.add_lines(1000)
    obs.register_sampler("gauge", lambda: {"depth": 3})
    obs.register_sampler("broken", lambda: 1 / 0)  # must not kill the run
    rec = obs.metrics_snapshot()
    assert rec["lines"] == 1000 and rec["gauge"] == {"depth": 3}
    assert "broken" not in rec
    assert rec["rss_bytes"] > 0
    obs.metric_event("checkpoint", bytes=123)
    obs.shutdown()
    recs = _assert_metrics_well_formed(path)
    kinds = [r["kind"] for r in recs]
    assert "checkpoint" in kinds and "final" in kinds


def test_recovery_meter_out_of_order_recovered():
    """recovered() with no prior detect() must not depend on attribute
    luck (satellite: _reason now initialized in __init__)."""
    from ruleset_analysis_tpu.runtime.metrics import RecoveryMeter

    m = RecoveryMeter()
    m.recovered(world=3)  # no detect() first — the out-of-order path
    assert m.events[0]["reason"] == ""
    assert m.events[0]["time_to_recover_sec"] == 0.0
    s = m.summary()
    assert s["recovery_events"] == 1


def test_throughput_meter_feeds_metrics_and_summary(tmp_path, capsys):
    from ruleset_analysis_tpu.runtime.metrics import ThroughputMeter

    path = str(tmp_path / "m.jsonl")
    obs.start_metrics(path, every_sec=60.0)
    meter = ThroughputMeter(report_every_chunks=2)
    for _ in range(4):
        meter.tick(100)
    obs.shutdown()
    s = meter.summary()
    assert s["lines"] == 400 and s["chunks_ticked"] == 4
    assert s["lines_per_sec_cum"] > 0
    recs = _assert_metrics_well_formed(path)
    through = [r for r in recs if r["kind"] == "throughput"]
    assert len(through) == 2  # chunk 2 and chunk 4 report lines
    assert through[-1]["lines"] == 400
    final = [r for r in recs if r["kind"] == "final"][0]
    assert final["lines"] == 400  # tick() fed the cumulative counter


# ---------------------------------------------------------------------------
# Profiler hardening (satellite)
# ---------------------------------------------------------------------------


class _FakeProfiler:
    def __init__(self, fail_stop=False):
        self.starts = 0
        self.stops = 0
        self._fail_stop = fail_stop

    def start_trace(self, d):
        self.starts += 1

    def stop_trace(self):
        self.stops += 1
        if self._fail_stop:
            raise RuntimeError("profiler teardown broke")


def _patched_profiler(monkeypatch, fake):
    import jax

    monkeypatch.setattr(jax, "profiler", fake)


def test_profiler_noop_without_dir():
    from ruleset_analysis_tpu.runtime.metrics import Profiler

    with Profiler(None):
        pass  # no jax.profiler calls, no output, no error


def test_profiler_double_start_is_typed(monkeypatch, tmp_path):
    from ruleset_analysis_tpu.runtime.metrics import Profiler

    fake = _FakeProfiler()
    _patched_profiler(monkeypatch, fake)
    p = Profiler(str(tmp_path))
    with p:
        with pytest.raises(AnalysisError, match="already started"):
            p.__enter__()
    assert fake.starts == 1 and fake.stops == 1


def test_profiler_stops_trace_on_body_exception(monkeypatch, tmp_path, capsys):
    from ruleset_analysis_tpu.runtime.metrics import Profiler

    fake = _FakeProfiler()
    _patched_profiler(monkeypatch, fake)
    with pytest.raises(ValueError, match="body failed"):
        with Profiler(str(tmp_path), out=sys.stderr):
            raise ValueError("body failed")
    assert fake.stops == 1  # the trace ALWAYS stops
    assert "tensorboard" not in capsys.readouterr().err.lower()


def test_profiler_stop_failure_does_not_mask_body_error(monkeypatch, tmp_path):
    from ruleset_analysis_tpu.runtime.metrics import Profiler

    fake = _FakeProfiler(fail_stop=True)
    _patched_profiler(monkeypatch, fake)
    with pytest.raises(ValueError, match="the real error"):
        with Profiler(str(tmp_path)):
            raise ValueError("the real error")
    # ... but a clean-exit stop failure is real and propagates
    fake2 = _FakeProfiler(fail_stop=True)
    _patched_profiler(monkeypatch, fake2)
    with pytest.raises(RuntimeError, match="teardown broke"):
        with Profiler(str(tmp_path)):
            pass


def test_profiler_prints_tensorboard_hint_on_clean_exit(monkeypatch, tmp_path, capsys):
    from ruleset_analysis_tpu.runtime.metrics import Profiler

    _patched_profiler(monkeypatch, _FakeProfiler())
    with Profiler(str(tmp_path), out=sys.stderr):
        pass
    err = capsys.readouterr().err
    assert str(tmp_path) in err and "tensorboard" in err.lower()


# ---------------------------------------------------------------------------
# End-to-end trace shape (acceptance: main + producer thread + feeder
# worker in ONE merged trace; fault instants; trace_summary occupancy)
# ---------------------------------------------------------------------------


def test_report_totals_carry_throughput(corpus):
    packed, _prefix, log, _wirep = corpus
    rep = run_stream_file(packed, log, _cfg(), topk=5)
    t = rep.totals["throughput"]
    assert t["lines"] == rep.totals["lines_total"]
    assert t["chunks_ticked"] >= 1 and t["elapsed_sec"] > 0


@pytest.mark.skipif(
    not fastparse.available(), reason="native parser not buildable here"
)
def test_merged_trace_spans_main_producer_and_feeder_worker(corpus, tmp_path):
    """The acceptance artifact: one merged timeline, three span origins."""
    from ruleset_analysis_tpu import cli

    packed, prefix, log, _wirep = corpus
    td = str(tmp_path / "trace")
    mf = str(tmp_path / "metrics.jsonl")
    rc = cli.main([
        "run", "--ruleset", prefix, "--logs", log, "--batch-size", "256",
        "--feed-workers", "2", "--feed-mode", "process",
        "--prefetch-depth", "2", "--trace-out", td, "--metrics-out", mf,
        "--metrics-every", "0.2", "--json",
        "--out", str(tmp_path / "rep.json"),
    ])
    assert rc == 0
    merged = os.path.join(td, "trace.json")
    assert os.path.exists(merged), "CLI did not merge the trace at exit"
    events = _load_trace(merged)
    _assert_well_formed(events)
    main_pid = os.getpid()
    spans = [e for e in events if e["ph"] == "X"]
    # device dispatches happen on the main thread of the main process
    steps = [e for e in spans if e["name"] == "step.dispatch"]
    assert steps and all(e["pid"] == main_pid for e in steps)
    # the prefetch producer runs on a different thread of the SAME process
    produce = [e for e in spans if e["name"] == "ingest.produce"]
    assert produce and all(e["pid"] == main_pid for e in produce)
    assert {e["tid"] for e in produce} != {e["tid"] for e in steps}
    # spawned feeder workers write their own per-PID shards
    feed = [e for e in spans if e["name"] == "feeder.parse"]
    assert feed and all(e["pid"] != main_pid for e in feed)
    assert len({e["pid"] for e in feed}) >= 1
    # worker tracks carry their role label
    roles = [
        e["args"]["name"] for e in events
        if e["ph"] == "M" and e.get("name") == "process_name"
    ]
    assert any(r.startswith("feeder-worker") for r in roles)
    assert any(r.startswith("main") for r in roles)
    # metrics: well-formed, and the ingest queue gauges made it in
    recs = _assert_metrics_well_formed(mf)
    assert any("ingest" in r for r in recs)
    # trace_summary attributes per-stage occupancy from the same file
    s = trace_summary.summarize(merged)
    assert s["processes"] >= 2
    assert "step.dispatch" in s["stages"] and "feeder.parse" in s["stages"]
    assert all(st["occupancy_pct"] >= 0 for st in s["stages"].values())


def test_fault_instants_land_in_merged_trace(corpus, tmp_path):
    """An armed site's firing is an instant event, and the typed abort
    still produces a merged, well-formed trace (CLI finally path)."""
    from ruleset_analysis_tpu import cli

    packed, prefix, log, _wirep = corpus
    td = str(tmp_path / "trace")
    rc = cli.main([
        "run", "--ruleset", prefix, "--logs", log, "--batch-size", "256",
        "--prefetch-depth", "2", "--trace-out", td,
        "--fault-plan", "ingest.producer.raise@2", "--json",
        "--out", str(tmp_path / "rep.json"),
    ])
    assert rc != 0  # typed abort (InjectedFault -> IngestError class 5)
    events = _load_trace(os.path.join(td, "trace.json"))
    _assert_well_formed(events)
    fires = [e for e in events if e["name"] == "fault.ingest.producer.raise"]
    assert len(fires) == 1 and fires[0]["ph"] == "i"
    assert fires[0]["args"]["hit"] == 2


# ---------------------------------------------------------------------------
# Chaos x observability: seeded schedules with the plane armed
# ---------------------------------------------------------------------------


def _chaos_schedule(seed: int):
    rng = random.Random(seed)
    inp = rng.choice(["text", "wire"])
    sites = ["stream.device_put.fail", "checkpoint.torn_state",
             "checkpoint.torn_manifest", "ingest.producer.raise"]
    if inp == "wire":
        sites.append("stream.wire.corrupt")
    site = rng.choice(sites)
    cadence = 2 if site.startswith("checkpoint.") else rng.choice([0, 2])
    plan = faults.FaultPlan([faults.FaultSpec(site, rng.randint(1, 3))], seed=seed)
    return inp, cadence, plan


@pytest.mark.parametrize("seed", [201, 202, 203, 204, 205])
def test_chaos_trace_and_metrics_stay_well_formed(seed, corpus, tmp_path):
    """Seeded fault schedules with tracing + metrics armed: whether the
    run aborts typed or completes, the merged trace and metrics JSONL
    parse, stamps are monotonic, and no open span is orphaned."""
    packed, _prefix, log, wirep = corpus
    inp, cadence, plan = _chaos_schedule(seed)
    td = str(tmp_path / "trace")
    mf = str(tmp_path / "metrics.jsonl")
    cfg = _cfg(depth=2, cadence=cadence, ckpt_dir=str(tmp_path / "ck"))
    obs.start_trace(td, role="main")
    obs.start_metrics(mf, every_sec=0.2)
    aborted = False
    try:
        with faults.armed(plan):
            try:
                if inp == "wire":
                    run_stream_wire(packed, wirep, cfg, topk=5)
                else:
                    run_stream_file(packed, log, cfg, topk=5)
            except AnalysisError:
                aborted = True  # the allowed chaos outcome
    finally:
        merged = obs.shutdown()
    assert merged and os.path.exists(merged)
    events = _load_trace(merged)
    _assert_well_formed(events)
    if aborted:
        # the armed site fired: its instant must be on the timeline
        site = next(iter(plan.specs))
        assert any(
            e["name"] == f"fault.{site}" for e in events if e["ph"] == "i"
        ), f"seed {seed}: fired site left no instant"
    _assert_metrics_well_formed(mf)
    # and the merged artifact stays summarizable after any outcome
    s = trace_summary.summarize(merged)
    assert s["wall_sec"] >= 0 and isinstance(s["stages"], dict)
