"""Multi-host distributed serve: per-host ingest, cross-host merge.

Pins the DESIGN §22 invariants (ISSUE 17):

- **Merge fidelity**: the window report rank 0 publishes from N hosts'
  epochs is bit-identical — registers, per-rule hits, unique-source
  counts, unused-rule deletion candidates, AND talkers — to a
  single-host replay of the union of the hosts' delivered lines (per
  window: host 0's slice then host 1's), at a geometry where candidate
  coverage is complete.
- **Typed degradation**: a host that dies mid-window yields a published
  window carrying ``host_died:<rank>`` in its incomplete marker — never
  a hang, never a silent zero-hit window — and the service keeps
  serving from the survivors.
- **Elastic resume**: the ring-checkpoint fingerprint pins the host
  LADDER MAXIMUM, so a checkpoint taken at any world size resumes at
  any other on the same ladder, and a changed ceiling is a typed
  refusal.
- **Host-tier wire**: epochs cross the merge plane as CRC'd RAEP1
  payloads; corruption is a typed refusal at the merge tier, never
  silently-wrong published counters.

Everything here runs thread-mode workers (in-process, shared jit
caches); the process-mode SIGKILL chaos and WAL-rejoin side is
``slow``-marked (spawned interpreters recompile from scratch, which
does not fit the tier-1 wall budget).
"""

import json
import os
import socket
import threading
import time

import numpy as np
import pytest

pytest.importorskip("jax")

from ruleset_analysis_tpu.config import (
    AnalysisConfig,
    DistServeConfig,
    ServeConfig,
)
from ruleset_analysis_tpu.errors import AnalysisError
from ruleset_analysis_tpu.hostside import aclparse, pack, synth
from ruleset_analysis_tpu.hostside.listener import offset_listen_spec
from ruleset_analysis_tpu.parallel.distributed import (
    pack_epoch_payload,
    unpack_epoch_payload,
)
from ruleset_analysis_tpu.runtime import flightrec
from ruleset_analysis_tpu.runtime.autoscale import host_ladder
from ruleset_analysis_tpu.runtime.distserve import DistServeDriver
from ruleset_analysis_tpu.runtime.report import VOLATILE_TOTALS as VOLATILE
from ruleset_analysis_tpu.runtime.serve import ServeDriver


def image(obj) -> dict:
    if not isinstance(obj, dict):
        obj = json.loads(obj.to_json())
    obj = json.loads(json.dumps(obj))
    for k in VOLATILE:
        obj["totals"].pop(k, None)
    # host fan-out changes batch segmentation and the window meta
    # (per-host blocks), not analysis content
    obj["totals"].pop("window", None)
    obj["totals"].pop("chunks", None)
    return obj


@pytest.fixture(scope="module")
def corpus(tmp_path_factory):
    """v4+v6 packed ruleset + 800 mixed lines, host-sliced."""
    td = tmp_path_factory.mktemp("distserve")
    cfg_text = synth.synth_config(
        n_acls=2, rules_per_acl=8, seed=0, v6_fraction=0.25
    )
    rs = aclparse.parse_asa_config(cfg_text, "fw1")
    packed = pack.pack_rulesets([rs])
    prefix = str(td / "rules")
    pack.save_packed(packed, prefix)
    t = synth.synth_tuples(packed, 600, seed=1)
    lines = synth.render_syslog(packed, t, seed=1)
    t6 = synth.synth_tuples6(packed, 200, seed=2)
    lines += synth.render_syslog6(packed, t6, seed=3)
    return packed, prefix, lines, str(td)


RUN_CFG = dict(batch_size=128, prefetch_depth=0)

#: per-host window length; the solo replay uses N_HOSTS * WL
WL = 200


def dist_cfg(**kw) -> AnalysisConfig:
    return AnalysisConfig(**{**RUN_CFG, "mesh_shape": "hybrid", **kw})


def dist_scfg(serve_dir, **kw) -> ServeConfig:
    return ServeConfig(**{
        "listen": ("tcp:127.0.0.1:0",), "window_lines": WL,
        "serve_dir": str(serve_dir), "http": "off",
        "checkpoint_every_windows": 0, "reload_watch": False,
        **kw,
    })


def host_slices(lines, n_hosts, windows, wl=WL):
    """Union order -> per-host streams (window w: host 0's slice, then
    host 1's, ...), so merged window w and solo window w cover the same
    lines."""
    return {
        r: [
            ln
            for w in range(windows)
            for ln in lines[(w * n_hosts + r) * wl:(w * n_hosts + r + 1) * wl]
        ]
        for r in range(n_hosts)
    }


def start_dist(prefix, cfg, scfg, dscfg, **kw):
    drv = DistServeDriver(prefix, cfg, scfg, dscfg, **kw)
    out: dict = {}

    def runner():
        try:
            out["summary"] = drv.run()
        except BaseException as e:  # surfaced by finish_dist()
            out["error"] = e

    th = threading.Thread(target=runner, daemon=True)
    th.start()
    return drv, th, out


def finish_dist(th, out, timeout=240):
    th.join(timeout=timeout)
    assert not th.is_alive(), "distributed serve hung"
    if "error" in out:
        raise out["error"]
    return out["summary"]


def host_tcp(drv, rank):
    with drv._lock:
        h = drv.hosts.get(rank)
        addrs = dict(h.addresses) if h else {}
    for lbl, ad in addrs.items():
        if lbl.startswith("tcp"):
            return tuple(ad)
    return None


def wait_for(pred, timeout=120, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.05)
    raise AssertionError(f"timed out waiting for {msg}")


def wait_hosts_up(drv, out, n_hosts, timeout=120):
    wait_for(
        lambda: out.get("error")
        or all(host_tcp(drv, r) for r in range(n_hosts)),
        timeout, "host listeners",
    )
    if "error" in out:
        raise out["error"]


def send_tcp(addr, lines):
    s = socket.create_connection(addr)
    s.sendall(("\n".join(lines) + "\n").encode())
    s.close()


def read_json(path):
    with open(path, "r", encoding="utf-8") as f:
        return json.load(f)


# ---------------------------------------------------------------------------
# Config + wire plumbing (no device work).
# ---------------------------------------------------------------------------

def test_distserve_config_validation():
    assert DistServeConfig().ladder_max == 2
    assert DistServeConfig(hosts=2, max_hosts=5).ladder_max == 5
    with pytest.raises(ValueError):
        DistServeConfig(hosts=0)
    with pytest.raises(ValueError):
        DistServeConfig(workers="fiber")
    with pytest.raises(ValueError):
        DistServeConfig(merge_bind="nocolon")
    with pytest.raises(ValueError):
        DistServeConfig(merge_timeout_sec=0)
    with pytest.raises(ValueError):
        DistServeConfig(hosts=4, max_hosts=3)
    with pytest.raises(ValueError):
        DistServeConfig(hosts=1, min_hosts=2, max_hosts=4)


def test_host_ladder_contiguous():
    assert host_ladder(1, 4) == [1, 2, 3, 4]
    assert host_ladder(3, 3) == [3]
    with pytest.raises(AnalysisError):
        host_ladder(5, 2)


def test_offset_listen_spec():
    assert offset_listen_spec("tcp:0.0.0.0:6514", 2) == "tcp:0.0.0.0:6516"
    assert offset_listen_spec("udp:127.0.0.1:514", 1) == "udp:127.0.0.1:515"
    # ephemeral stays ephemeral: every host binds its own port
    assert offset_listen_spec("tcp:127.0.0.1:0", 3) == "tcp:127.0.0.1:0"
    assert offset_listen_spec("tail:/var/log/fw.log", 0) == "tail:/var/log/fw.log"
    assert offset_listen_spec("tail:/var/log/fw.log", 2).endswith(".host2")
    with pytest.raises(AnalysisError):
        offset_listen_spec("tcp:127.0.0.1:6514", -1)


def test_epoch_payload_roundtrip_and_corruption():
    arrays = {
        "counts_lo": np.arange(8, dtype=np.uint32),
        "counts_hi": np.zeros(8, dtype=np.uint32),
    }
    extra = {"rank": 1, "meta": {"id": 4, "lines": 99}, "wal_next": 7}
    payload = pack_epoch_payload(arrays, extra)
    arr2, extra2 = unpack_epoch_payload(payload)
    assert extra2 == extra
    assert set(arr2) == set(arrays)
    for k in arrays:
        assert np.array_equal(arr2[k], arrays[k])
    # a flipped body byte must be a typed refusal (CRC), never silence
    torn = bytearray(payload)
    torn[-1] ^= 0xFF
    with pytest.raises(AnalysisError):
        unpack_epoch_payload(bytes(torn))
    with pytest.raises(AnalysisError):
        unpack_epoch_payload(payload[:-3])
    with pytest.raises(AnalysisError):
        unpack_epoch_payload(b"NOPE" + payload[4:])


# ---------------------------------------------------------------------------
# Typed refusals at the composition boundaries.
# ---------------------------------------------------------------------------

def test_distributed_requires_hybrid_mesh(corpus):
    _, prefix, _, td = corpus
    with pytest.raises(AnalysisError, match="hybrid"):
        DistServeDriver(
            prefix, AnalysisConfig(**RUN_CFG),
            dist_scfg(os.path.join(td, "refuse-mesh")),
            DistServeConfig(workers="thread"),
        )


def test_distributed_refuses_static_analysis(corpus):
    _, prefix, _, td = corpus
    with pytest.raises(AnalysisError, match="static"):
        DistServeDriver(
            prefix, dist_cfg(),
            dist_scfg(os.path.join(td, "refuse-static"), static_analysis=True),
            DistServeConfig(workers="thread"),
        )


def test_tenants_distributed_refusal(corpus):
    from ruleset_analysis_tpu.runtime.tenantserve import TenantServeDriver

    _, _, _, td = corpus
    with pytest.raises(AnalysisError, match="do not compose"):
        TenantServeDriver(
            os.path.join(td, "nonexistent-manifest.json"),
            AnalysisConfig(**RUN_CFG),
            dist_scfg(os.path.join(td, "refuse-tenants")),
            distributed=DistServeConfig(),
        )


def test_cli_dist_flags_require_distributed(corpus, capsys):
    from ruleset_analysis_tpu import cli

    _, prefix, _, td = corpus
    rc = cli.main([
        "serve", "--ruleset", prefix, "--listen", "tcp:127.0.0.1:0",
        "--window", "lines:100", "--serve-dir", os.path.join(td, "cli-no-dist"),
        "--dist-hosts", "3",
    ])
    assert rc == 2
    assert "--distributed" in capsys.readouterr().err


def test_cli_distributed_requires_hybrid(corpus, capsys):
    from ruleset_analysis_tpu import cli

    _, prefix, _, td = corpus
    rc = cli.main([
        "serve", "--ruleset", prefix, "--listen", "tcp:127.0.0.1:0",
        "--window", "lines:100", "--serve-dir", os.path.join(td, "cli-flat"),
        "--distributed",
    ])
    assert rc == 2


# ---------------------------------------------------------------------------
# The merge law: N-host published windows == single-host union replay.
# ---------------------------------------------------------------------------

def test_two_host_bit_identity(corpus):
    packed, prefix, lines, td = corpus
    n_hosts, windows = 2, 2
    union = lines[:n_hosts * windows * WL]
    streams = host_slices(union, n_hosts, windows)

    # solo reference: one driver replays the union at 2x window length
    solo_dir = os.path.join(td, "solo")
    solo = ServeDriver(
        prefix, AnalysisConfig(**RUN_CFG),
        dist_scfg(solo_dir, window_lines=n_hosts * WL, max_windows=windows),
    )
    out: dict = {}
    th = threading.Thread(
        target=lambda: out.update(summary=solo.run())
    )
    th.start()
    ep = os.path.join(solo_dir, "endpoint.json")
    wait_for(lambda: os.path.exists(ep), msg="solo endpoint")
    time.sleep(0.2)
    (addr,) = read_json(ep)["listeners"].values()
    send_tcp(tuple(addr), union)
    th.join(timeout=240)
    assert not th.is_alive(), "solo serve hung"
    assert out["summary"]["drops"] == 0

    dist_dir = os.path.join(td, "dist")
    drv, th, dout = start_dist(
        prefix, dist_cfg(), dist_scfg(dist_dir, max_windows=windows),
        DistServeConfig(hosts=n_hosts, workers="thread"),
    )
    wait_hosts_up(drv, dout, n_hosts)
    for r in range(n_hosts):
        send_tcp(host_tcp(drv, r), streams[r])
    summary = finish_dist(th, dout)
    assert summary["windows_published"] == windows
    assert summary["lines_total"] == len(union)
    assert summary["drops"] == 0
    assert summary["dead_hosts"] == []

    # merged window w == solo window w, talkers INCLUDED: at this
    # geometry candidate coverage is complete, so even the sampled
    # talker section reproduces exactly under the sum-merge law
    for w in range(windows):
        a = read_json(os.path.join(dist_dir, f"window-{w:06d}.json"))
        b = read_json(os.path.join(solo_dir, f"window-{w:06d}.json"))
        assert a.get("talkers") == b.get("talkers"), f"window {w} talkers"
        assert image(a) == image(b), f"window {w} diverged"
    ca = read_json(os.path.join(dist_dir, "cumulative.json"))
    cb = read_json(os.path.join(solo_dir, "cumulative.json"))
    assert ca.get("talkers") == cb.get("talkers")
    assert image(ca) == image(cb)
    # per-host accounting rides the merged window meta
    w0 = read_json(os.path.join(dist_dir, "window-000000.json"))
    meta = w0["totals"]["window"]
    assert meta["merged_hosts"] == [0, 1]
    assert set(meta["hosts"]) == {"0", "1"}
    assert sum(h["lines"] for h in meta["hosts"].values()) == n_hosts * WL


# ---------------------------------------------------------------------------
# Whole-host death: typed WindowIncomplete, no hang, no silent loss.
# ---------------------------------------------------------------------------

def test_killed_host_names_window_incomplete(corpus):
    packed, prefix, lines, td = corpus
    n_hosts, windows = 2, 2
    union = lines[:n_hosts * windows * WL]
    streams = host_slices(union, n_hosts, windows)

    dist_dir = os.path.join(td, "chaos")
    drv, th, out = start_dist(
        prefix, dist_cfg(),
        dist_scfg(dist_dir, max_windows=windows),
        DistServeConfig(hosts=n_hosts, workers="thread", merge_timeout_sec=60),
    )
    wait_hosts_up(drv, out, n_hosts)

    # live per-host gauges reach rank 0 over the merge plane, and the
    # JSON and labeled-prom views agree (the registry auditor pins the
    # formatting law; this pins the LIVE path)
    wait_for(
        lambda: all(
            "queue_depth" in drv.host_gauges().get(str(r), {})
            for r in range(n_hosts)
        ),
        msg="per-host gauges",
    )
    gj = drv.host_gauges()
    prom = drv.render_labeled_prom()
    assert set(gj) == {"0", "1"}
    assert 'host="0"' in prom and 'host="1"' in prom
    assert "ra_serve_host_" in prom

    send_tcp(host_tcp(drv, 0), streams[0])
    send_tcp(host_tcp(drv, 1), streams[1][:WL])  # window 0 only
    # wait until host 1's window-0 epoch merged, then kill it mid-window-1
    wait_for(
        lambda: out.get("error") or drv.hosts[1].last_wid >= 0,
        msg="host 1 epoch 0",
    )
    drv.kill_host(1)
    wait_for(
        lambda: out.get("error") or 1 not in drv.live_hosts(),
        msg="host 1 marked dead",
    )
    summary = finish_dist(th, out)

    assert summary["dead_hosts"] == [1]
    assert summary["windows_published"] == windows
    # host 0's full stream + host 1's completed window 0: nothing a
    # survivor delivered is lost, and nothing is silently absorbed
    assert summary["lines_total"] == windows * WL + WL
    w1 = read_json(os.path.join(dist_dir, "window-000001.json"))
    inc = w1["totals"]["window"]["incomplete"]
    assert any(r == "host_died:1" for r in inc["reasons"]), inc
    assert inc["dead_hosts"] == [1]
    w0 = read_json(os.path.join(dist_dir, "window-000000.json"))
    assert "incomplete" not in w0["totals"]["window"]


# ---------------------------------------------------------------------------
# Elastic resume: ladder-max fingerprint, any world resumes any world.
# ---------------------------------------------------------------------------

def test_resume_across_world_sizes(corpus):
    packed, prefix, lines, td = corpus
    union = lines[:800]

    base = os.path.join(td, "elastic")
    ck = os.path.join(base, "ckpt")
    scfg_kw = dict(
        checkpoint_every_windows=1, checkpoint_dir=ck, max_windows=2,
    )
    # first world: 2 hosts, 2 windows
    drv, th, out = start_dist(
        prefix, dist_cfg(), dist_scfg(base, **scfg_kw),
        DistServeConfig(hosts=2, max_hosts=3, workers="thread"),
    )
    wait_hosts_up(drv, out, 2)
    streams = host_slices(union[:800], 2, 2)
    for r in range(2):
        send_tcp(host_tcp(drv, r), streams[r])
    s1 = finish_dist(th, out)
    assert s1["windows_published"] == 2
    assert s1["drops"] == 0

    # resume at a DIFFERENT world (3 hosts) on the same ladder: the
    # fingerprint pins the ladder max, not the live host count
    drv, th, out = start_dist(
        prefix, dist_cfg(resume=True),
        dist_scfg(base, **{**scfg_kw, "max_windows": 3}),
        DistServeConfig(hosts=3, max_hosts=3, workers="thread"),
    )
    wait_hosts_up(drv, out, 3)
    # cumulative state restored before any new traffic
    assert drv.windows_published == 2
    assert drv.total_lines == 800
    for r in range(3):
        send_tcp(host_tcp(drv, r), lines[800 + r * 66:800 + (r + 1) * 66])
    # 3 hosts x 66 lines < WL each: stopping publishes the partial tail
    wait_for(
        lambda: out.get("error")
        or all(drv.hosts[r].gauges.get("lines_total", 0) >= 66
               or drv.hosts[r].last_wid >= 2
               for r in range(3))
        or drv.total_lines >= 800,
        timeout=60, msg="resumed ingest",
    )
    time.sleep(1.0)
    drv.stop()
    s2 = finish_dist(th, out)
    assert s2["windows_published"] >= 2  # restored count carries over

    # a CHANGED ladder maximum is a typed refusal, not silent reuse
    with pytest.raises(ckpt_mismatch_types()):
        drv, th, out = start_dist(
            prefix, dist_cfg(resume=True), dist_scfg(base, **scfg_kw),
            DistServeConfig(hosts=2, max_hosts=4, workers="thread"),
        )
        finish_dist(th, out, timeout=60)


def ckpt_mismatch_types():
    from ruleset_analysis_tpu.runtime import checkpoint as ckpt

    return (ckpt.CheckpointMismatch,)


# ---------------------------------------------------------------------------
# Forensics: per-host shards merge, the doctor names the dead host.
# ---------------------------------------------------------------------------

def _shard(role, pid, events=(), cursors=None):
    return {
        "kind": "ra-blackbox-shard",
        "role": role,
        "pid": pid,
        "trigger": "abort",
        "ring_events": list(events),
        "cursors": cursors or {},
    }


def test_blackbox_merge_names_dead_host(tmp_path):
    bb = tmp_path / "blackbox"
    bb.mkdir()
    sup = _shard(
        "serve-sup", 100,
        events=[
            {"name": "serve.host.spawn", "ph": "i", "args": {"host": 1}},
            {"name": "serve.host.died", "ph": "i", "args": {"host": 1}},
        ],
        cursors={"dead_hosts": [1], "windows_published": 3},
    )
    worker = _shard(
        "serve-host0", 101,
        events=[{"name": "serve.rotate", "ph": "i", "args": {"host": 0}}],
    )
    (bb / "blackbox-100.json").write_text(json.dumps(sup))
    (bb / "blackbox-101.json").write_text(json.dumps(worker))
    out_path = flightrec.merge(str(bb), trigger="abort", exit_code=7)
    bundle = read_json(out_path)
    a = bundle["analysis"]
    assert a["dead_hosts"] == ["1"]
    assert a["host_events"].get("1", 0) >= 2
    assert a["host_events"].get("0", 0) >= 1
    diags = flightrec.diagnose(bundle, exit_code=7)
    dead = [d for d in diags if "ingest host died" in d["cause"]]
    assert dead, diags
    assert "host 1" in dead[0]["cause"]
    assert "host_died:<rank>" in dead[0]["advice"]


def test_worker_arm_does_not_reexport_env(tmp_path, monkeypatch):
    # the spawned worker arms its own shard ring from the inherited
    # RA_BLACKBOX_DIR but must NOT re-export/prune the shared dir (the
    # supervisor owns the bundle lifecycle)
    bb = tmp_path / "bb"
    monkeypatch.delenv(flightrec.ENV_VAR, raising=False)
    flightrec.arm(str(bb), role="serve-host7", export_env=False)
    try:
        assert flightrec.ENV_VAR not in os.environ
    finally:
        flightrec.disarm()


def test_registry_distserve_audit_clean():
    from ruleset_analysis_tpu.verify.registry import audit_distserve

    assert audit_distserve() == []


# ---------------------------------------------------------------------------
# Process isolation + SIGKILL chaos + WAL rejoin (spawned interpreters
# recompile XLA from scratch — minutes on one core, so slow-marked).
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_process_mode_sigkill_rejoin(corpus):
    """SIGKILL a whole host process; `--dist-respawn` absorbs it.

    With a merge wait that outlives the replacement's cold start, the
    respawned rank rejoins at the merge frontier and delivers the
    windows its predecessor never saw — every published window ends up
    COMPLETE (no incomplete markers, no drops), lines_total covers
    every delivered line once, and the supervisor shuts down cleanly
    even though the replacement came up long after its siblings (the
    per-generation stop-delivery path).  The typed host_died naming of
    an UNREPLACED death is pinned in-tier by
    test_killed_host_names_window_incomplete; this is the other half
    of the failure model: replacement means seamless, not renamed.
    """
    packed, prefix, lines, td = corpus
    n_hosts, windows = 2, 3
    wl = 100
    union = lines[:n_hosts * windows * wl]
    streams = host_slices(union, n_hosts, windows, wl=wl)

    dist_dir = os.path.join(td, "proc-chaos")
    drv, th, out = start_dist(
        prefix, dist_cfg(),
        dist_scfg(dist_dir, window_lines=wl, max_windows=windows, wal=True),
        DistServeConfig(
            hosts=n_hosts, workers="process", respawn=True,
            merge_timeout_sec=600,
        ),
    )
    wait_hosts_up(drv, out, n_hosts, timeout=300)
    send_tcp(host_tcp(drv, 0), streams[0])
    send_tcp(host_tcp(drv, 1), streams[1][:wl])
    wait_for(
        lambda: out.get("error") or drv.hosts[1].last_wid >= 0,
        timeout=300, msg="host 1 epoch 0",
    )
    pid = drv.hosts[1].proc.pid
    os.kill(pid, 9)  # whole-host SIGKILL, not a polite stop
    # respawn brings a NEW process up on the same rank; its WAL replay
    # seq starts past the merged windows, so nothing double-counts
    wait_for(
        lambda: out.get("error")
        or (1 in drv.live_hosts() and drv.hosts[1].generation >= 1),
        timeout=300, msg="host 1 respawn",
    )
    wait_for(
        lambda: out.get("error")
        or (drv.hosts[1].addresses and drv.hosts[1].generation >= 1
            and host_tcp(drv, 1)),
        timeout=300, msg="replacement listener",
    )
    send_tcp(host_tcp(drv, 1), streams[1][wl:])
    summary = finish_dist(th, out, timeout=900)
    assert summary["windows_published"] == windows
    assert summary["lines_total"] == len(union)
    assert summary["drops"] == 0
    assert summary["hosts_spawned"] == n_hosts + 1
    assert summary["hosts"]["1"]["generation"] >= 1
    assert summary["dead_hosts"] == []  # rejoined, no longer dead
    for w in range(windows):
        meta = read_json(
            os.path.join(dist_dir, f"window-{w:06d}.json")
        )["totals"]["window"]
        assert "incomplete" not in meta, (w, meta)
        assert meta["lines"] == n_hosts * wl
