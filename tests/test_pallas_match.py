"""Pallas first-match kernel: bit-equality with the XLA-fused path.

Runs in pallas interpret mode on the CPU test mesh (the kernel compiles
natively on TPU; bench_suite.py compares the two there).
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from ruleset_analysis_tpu.hostside import aclparse, pack, synth  # noqa: E402
from ruleset_analysis_tpu.ops import pallas_match  # noqa: E402
from ruleset_analysis_tpu.ops.match import first_match_rows, match_keys  # noqa: E402


def _case(n_acls=3, rules_per_acl=24, n=2048, seed=0):
    cfg_text = synth.synth_config(n_acls=n_acls, rules_per_acl=rules_per_acl, seed=seed)
    rs = aclparse.parse_asa_config(cfg_text, "fw1")
    packed = pack.pack_rulesets([rs])
    tuples = synth.synth_tuples(packed, n, seed=seed + 1)
    cols = {
        k: jnp.asarray(tuples[:, i])
        for k, i in zip(["acl", "proto", "src", "sport", "dst", "dport"], range(6))
    }
    return packed, cols, tuples


@pytest.mark.parametrize("seed", [0, 1])
def test_rows_match_xla_path(seed):
    packed, cols, _ = _case(seed=seed)
    rules = jnp.asarray(packed.rules)
    want = np.asarray(first_match_rows(cols, rules))
    got = np.asarray(
        pallas_match.first_match_rows_pallas(
            cols, pallas_match.prep_rules(rules), block_lines=512, interpret=True
        )
    )
    np.testing.assert_array_equal(got, want)


def test_keys_match_xla_path():
    packed, cols, _ = _case(n_acls=2, rules_per_acl=40, n=1024, seed=3)
    rules = jnp.asarray(packed.rules)
    deny = jnp.asarray(packed.deny_key.astype(np.uint32))
    want = np.asarray(match_keys(cols, rules, deny))
    got = np.asarray(
        pallas_match.match_keys_pallas(
            cols, rules, pallas_match.prep_rules(rules), deny,
            block_lines=256, interpret=True,
        )
    )
    np.testing.assert_array_equal(got, want)


def test_no_match_lines_get_no_match():
    packed, cols, tuples = _case(n=512, seed=5)
    # lines pointing at a non-existent ACL gid never match any rule
    cols = dict(cols)
    cols["acl"] = jnp.full_like(cols["acl"], 0xFFFF)
    got = np.asarray(
        pallas_match.first_match_rows_pallas(
            cols, pallas_match.prep_rules(jnp.asarray(packed.rules)),
            block_lines=512, interpret=True,
        )
    )
    assert (got == 0xFFFFFFFF).all()


def test_stream_report_identical_across_match_impls(tmp_path):
    """The full driver with match_impl=pallas must produce the exact
    report of the XLA path (pallas runs in its compiled form on TPU; on
    the CPU test backend pallas_call executes via the interpreter-backed
    lowering, exercising the same wiring)."""
    from ruleset_analysis_tpu.config import AnalysisConfig, SketchConfig
    from ruleset_analysis_tpu.runtime.stream import run_stream

    packed, _, tuples = _case(n=600, seed=9)
    lines = synth.render_syslog(packed, tuples, seed=10)

    def run(impl):
        cfg = AnalysisConfig(
            batch_size=256,
            sketch=SketchConfig(cms_width=1 << 10, cms_depth=2, hll_p=6),
            match_impl=impl,
        )
        return run_stream(packed, iter(lines), cfg)

    a, b = run("xla"), run("pallas")
    assert a.per_rule == b.per_rule
    assert a.unused == b.unused
    assert a.talkers == b.talkers


def test_rule_padding_to_lane_tile():
    # 442-ish expanded rows -> padded to a multiple of 128; padding columns
    # must never match even all-zero lines
    packed, cols, _ = _case(n_acls=4, rules_per_acl=64, n=512, seed=7)
    fm = pallas_match.prep_rules(jnp.asarray(packed.rules))
    assert fm.shape[1] % pallas_match.RULE_TILE == 0
    zero_cols = {k: jnp.zeros(512, dtype=jnp.uint32) for k in cols}
    got = np.asarray(
        pallas_match.first_match_rows_pallas(
            zero_cols, fm, block_lines=512, interpret=True
        )
    )
    want = np.asarray(
        first_match_rows(zero_cols, jnp.asarray(packed.rules))
    )
    np.testing.assert_array_equal(got, want)
