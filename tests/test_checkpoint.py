"""Checkpoint/resume + fault injection (SURVEY.md §6: "kill between chunks
in tests").  The contract: kill a run anywhere, resume from the snapshot,
and the final registers/report are bit-identical to an uninterrupted run."""

import numpy as np
import pytest

pytest.importorskip("jax")

from ruleset_analysis_tpu.config import AnalysisConfig, SketchConfig
from ruleset_analysis_tpu.hostside import aclparse, pack, synth
from ruleset_analysis_tpu.runtime import checkpoint as ckpt
from ruleset_analysis_tpu.runtime.stream import run_stream


@pytest.fixture(scope="module")
def corpus():
    cfg_text = synth.synth_config(n_acls=2, rules_per_acl=10, seed=41)
    rs = aclparse.parse_asa_config(cfg_text, "fw1")
    packed = pack.pack_rulesets([rs])
    tuples = synth.synth_tuples(packed, 2000, seed=41)
    lines = synth.render_syslog(packed, tuples, seed=41)
    return packed, lines


def make_cfg(tmp, every=2, resume=False):
    return AnalysisConfig(
        batch_size=256,
        sketch=SketchConfig(cms_width=1 << 10, cms_depth=4, hll_p=6),
        checkpoint_every_chunks=every,
        checkpoint_dir=str(tmp),
        resume=resume,
    )


def hits_of(rep):
    return {(e["firewall"], e["acl"], e["index"]): e["hits"] for e in rep.per_rule}


def test_kill_and_resume_bit_identical(corpus, tmp_path):
    packed, lines = corpus
    # uninterrupted reference run (no checkpointing at all)
    ref = run_stream(packed, iter(lines), make_cfg(tmp_path / "none", every=0))

    # "crash" after 3 chunks (max_chunks cuts the run mid-stream)
    run_stream(packed, iter(lines), make_cfg(tmp_path / "ck"), max_chunks=3)
    snap = ckpt.load(str(tmp_path / "ck"))
    assert snap is not None
    assert snap.n_chunks == 2  # snapshots at chunk 2; chunk 3 was "lost"

    # resume from the snapshot over the same stream
    rep = run_stream(packed, iter(lines), make_cfg(tmp_path / "ck", resume=True))
    assert hits_of(rep) == hits_of(ref)
    assert rep.unused == ref.unused
    assert rep.talkers == ref.talkers
    assert rep.totals["lines_matched"] == ref.totals["lines_matched"]


def test_resume_without_snapshot_starts_fresh(corpus, tmp_path):
    packed, lines = corpus
    rep = run_stream(packed, iter(lines), make_cfg(tmp_path / "empty", resume=True))
    ref = run_stream(packed, iter(lines), make_cfg(tmp_path / "none2", every=0))
    assert hits_of(rep) == hits_of(ref)


def test_fingerprint_mismatch_refused(corpus, tmp_path):
    packed, lines = corpus
    run_stream(packed, iter(lines), make_cfg(tmp_path / "fp"), max_chunks=3)
    bad = AnalysisConfig(
        batch_size=256,
        sketch=SketchConfig(cms_width=1 << 11, cms_depth=4, hll_p=6),  # different geometry
        checkpoint_every_chunks=2,
        checkpoint_dir=str(tmp_path / "fp"),
        resume=True,
    )
    with pytest.raises(ckpt.CheckpointMismatch):
        run_stream(packed, iter(lines), bad)


def test_snapshot_roundtrip(tmp_path):
    snap = ckpt.Snapshot(
        arrays={"a": np.arange(5, dtype=np.uint32), "b": np.ones((2, 3), np.uint32)},
        lines_consumed=123,
        n_chunks=4,
        parsed=100,
        skipped=23,
        tracker_tables={7: {111: 9, 222: 3}},
        fingerprint="abc",
    )
    ckpt.save(str(tmp_path), snap)
    got = ckpt.load(str(tmp_path))
    assert got.lines_consumed == 123 and got.n_chunks == 4
    assert got.tracker_tables == {7: {111: 9, 222: 3}}
    np.testing.assert_array_equal(got.arrays["b"], snap.arrays["b"])
    # overwrite is atomic: save again and reload
    snap.lines_consumed = 456
    ckpt.save(str(tmp_path), snap)
    assert ckpt.load(str(tmp_path)).lines_consumed == 456


def test_epoch_snapshot_roundtrip_on_fake_mesh(corpus, tmp_path):
    """Elastic epoch schema: a snapshot carrying the world-size-independent
    cursor manifest (Snapshot.extra) round-trips exactly, old snapshots
    load with extra=None, and the restored registers place onto the fake
    8-device mesh bit-identically (the re-formed-cluster restore path)."""
    import jax

    from ruleset_analysis_tpu.config import AnalysisConfig
    from ruleset_analysis_tpu.models import pipeline
    from ruleset_analysis_tpu.parallel import mesh as mesh_lib
    from ruleset_analysis_tpu.runtime.elastic import manifest_of

    packed, lines = corpus
    cfg = AnalysisConfig(batch_size=256, sketch=SketchConfig(hll_p=6))
    state = pipeline.init_state_host(packed.n_keys, cfg)
    manifest = {
        "epoch": 2,
        "world": 3,
        "shards": ["/logs/a.log", "/logs/b.log", "/logs/c.log"],
        "cursors": {"0": 400, "1": 2**33 + 7, "2": 0},  # >32-bit cursor too
        "done": [0],
    }
    snap = ckpt.Snapshot(
        arrays=dict(state._asdict()),
        lines_consumed=400,
        n_chunks=6,
        parsed=400,
        skipped=0,
        tracker_tables={1: {10: 5}},
        fingerprint="fp-elastic",
        extra={"elastic": manifest},
    )
    ckpt.save(str(tmp_path), snap)
    got = ckpt.load(str(tmp_path))
    assert got.extra == {"elastic": manifest}
    shards, cursors, done = manifest_of(got)
    assert shards == manifest["shards"]
    assert cursors == {0: 400, 1: 2**33 + 7, 2: 0}
    assert done == {0}
    # registers restore onto the fake 8-device mesh bit-identically
    mesh = mesh_lib.make_mesh()
    assert mesh.devices.size == 8
    dev_state = ckpt.state_of(
        got, lambda v: jax.device_put(v, mesh_lib.replicated(mesh))
    )
    for k, v in snap.arrays.items():
        np.testing.assert_array_equal(np.asarray(getattr(dev_state, k)), v)
    # a manifest-less snapshot (the pre-elastic schema) loads as extra=None
    snap2 = ckpt.Snapshot(
        arrays={"a": np.arange(3, dtype=np.uint32)}, lines_consumed=1,
        n_chunks=1, parsed=1, skipped=0, tracker_tables={}, fingerprint="x",
    )
    ckpt.save(str(tmp_path / "plain"), snap2)
    plain = ckpt.load(str(tmp_path / "plain"))
    assert plain.extra is None
    assert manifest_of(plain) == (None, {}, set())


def test_same_chunk_resave_never_deletes_live_snapshot(tmp_path):
    """Re-saving at the same chunk count (e.g. end-of-run save right after
    a periodic one) must not delete the dir LATEST points at — the re-save
    lands under a fresh name and the old dir is pruned only after the
    pointer moves."""
    import os

    snap = ckpt.Snapshot(
        arrays={"a": np.arange(3, dtype=np.uint32)},
        lines_consumed=10,
        n_chunks=2,
        parsed=10,
        skipped=0,
        tracker_tables={},
        fingerprint="fp",
    )
    ckpt.save(str(tmp_path), snap)
    first = (tmp_path / "LATEST").read_text().strip()
    snap.lines_consumed = 20
    ckpt.save(str(tmp_path), snap)
    second = (tmp_path / "LATEST").read_text().strip()
    assert first != second  # fresh name, not an in-place overwrite
    assert not (tmp_path / first).exists()  # pruned after the pointer moved
    assert ckpt.load(str(tmp_path)).lines_consumed == 20


def test_load_missing_dir_returns_none(tmp_path):
    assert ckpt.load(str(tmp_path / "nothing")) is None


def test_orphans_swept_after_pointer_commit(tmp_path):
    """A crash between snapshot rename and pointer commit leaves an
    unreferenced snap dir (and possibly tmp litter); the next successful
    save must sweep everything the new pointer does not reference."""
    import os

    snap = ckpt.Snapshot(
        arrays={"a": np.arange(3, dtype=np.uint32)},
        lines_consumed=10,
        n_chunks=2,
        parsed=10,
        skipped=0,
        tracker_tables={},
        fingerprint="fp",
    )
    # simulate crash leftovers
    (tmp_path / "snap-99").mkdir()
    (tmp_path / "snap-99" / "state.npz").write_bytes(b"x")
    (tmp_path / ".tmp-dead").mkdir()
    (tmp_path / "dead.ptr.tmp").write_text("snap-99")
    ckpt.save(str(tmp_path), snap)
    live = (tmp_path / "LATEST").read_text().strip()
    entries = set(os.listdir(tmp_path))
    assert entries == {live, "LATEST"}
    assert ckpt.load(str(tmp_path)).lines_consumed == 10


def test_save_is_crash_atomic_pairwise(corpus, tmp_path):
    """A torn save (snapshot dir written, pointer not moved) must resume
    from the PREVIOUS consistent (offset, registers) pair."""
    import os

    packed, lines = corpus
    d = tmp_path / "atomic"
    run_stream(packed, iter(lines), make_cfg(d), max_chunks=3)
    before = ckpt.load(str(d))
    # simulate a crash between snapshot-dir creation and pointer rename:
    # drop a newer snapshot dir WITHOUT updating LATEST
    os.makedirs(d / "snap-99")
    (d / "snap-99" / "state.npz").write_bytes(b"garbage")
    (d / "snap-99" / "manifest.json").write_text("{broken")
    after = ckpt.load(str(d))
    assert after is not None
    assert after.n_chunks == before.n_chunks
    assert after.lines_consumed == before.lines_consumed


def test_crc_catches_valid_json_manifest_flip(tmp_path):
    """A flipped digit in the manifest decodes as perfectly valid JSON —
    pre-CRC this silently resumed from the wrong cursors.  The manifest
    self-CRC (which covers the elastic per-shard cursor manifest in
    ``extra``) must refuse it as CheckpointCorrupt."""
    snap = ckpt.Snapshot(
        arrays={"a": np.arange(8, dtype=np.uint32)},
        lines_consumed=1000,
        n_chunks=4,
        parsed=1000,
        skipped=0,
        tracker_tables={},
        fingerprint="fp",
        extra={"elastic": {"epoch": 1, "world": 4, "shards": ["s0"],
                           "cursors": {"0": 700}, "done": []}},
    )
    ckpt.save(str(tmp_path), snap)
    name = (tmp_path / "LATEST").read_text().strip()
    mp = tmp_path / name / "manifest.json"
    text = mp.read_text(encoding="utf-8")
    flipped = text.replace('"0": 700', '"0": 300')
    assert flipped != text
    mp.write_text(flipped, encoding="utf-8")
    with pytest.raises(ckpt.CheckpointCorrupt, match="CRC32"):
        ckpt.load(str(tmp_path))
    # restore -> loads again (the CRC is over canonical content)
    mp.write_text(text, encoding="utf-8")
    assert ckpt.load(str(tmp_path)).extra["elastic"]["cursors"]["0"] == 700


def test_crc_catches_state_payload_substitution(tmp_path):
    """Swapping the register payload for a different VALID npz (storage
    returning the wrong object) passes zipfile's member CRCs; the
    manifest's whole-file state CRC must still refuse it."""
    snap = ckpt.Snapshot(
        arrays={"a": np.arange(8, dtype=np.uint32)},
        lines_consumed=10,
        n_chunks=2,
        parsed=10,
        skipped=0,
        tracker_tables={},
        fingerprint="fp",
    )
    ckpt.save(str(tmp_path), snap)
    name = (tmp_path / "LATEST").read_text().strip()
    with open(tmp_path / name / ckpt.STATE_FILE, "wb") as f:
        np.savez(f, a=np.zeros(8, dtype=np.uint32))  # valid npz, wrong data
    with pytest.raises(ckpt.CheckpointCorrupt, match="CRC32"):
        ckpt.load(str(tmp_path))


def test_torn_state_write_truncation_refused(corpus, tmp_path):
    """Truncate the pointed-to snapshot's register file mid-payload: load
    must refuse with CheckpointCorrupt, never silently start fresh."""
    import os

    packed, lines = corpus
    d = tmp_path / "torn"
    run_stream(packed, iter(lines), make_cfg(d), max_chunks=3)
    name = (d / "LATEST").read_text().strip()
    state = d / name / ckpt.STATE_FILE
    size = os.path.getsize(state)
    with open(state, "r+b") as f:
        f.truncate(size // 2)
    with pytest.raises(ckpt.CheckpointCorrupt):
        ckpt.load(str(d))
    with pytest.raises(ckpt.CheckpointCorrupt):
        run_stream(packed, iter(lines), make_cfg(d, resume=True))


def test_torn_save_fault_recovers_from_prior_epoch(corpus, tmp_path):
    """PERSISTENTLY torn ``checkpoint.torn_state`` (``@2:99`` — past any
    retry budget): the save escalates typed after the policy's bounded
    attempts.  The pointer protocol must keep serving the FIRST epoch,
    and the resumed run must be bit-identical to an uninterrupted
    cadence-matched reference.  (A single-fire torn write no longer
    aborts at all — the retry engine absorbs it; see the transient
    sibling test below.)"""
    from ruleset_analysis_tpu.errors import InjectedFault
    from ruleset_analysis_tpu.runtime import faults

    packed, lines = corpus
    ref = run_stream(packed, iter(lines), make_cfg(tmp_path / "ref"))
    d = tmp_path / "ck"
    with faults.armed(faults.FaultPlan.parse("checkpoint.torn_state@2:99")):
        with pytest.raises(InjectedFault):
            run_stream(packed, iter(lines), make_cfg(d))
    before = ckpt.load(str(d))  # the prior epoch survived the torn saves
    assert before is not None and before.n_chunks == 2
    rep = run_stream(packed, iter(lines), make_cfg(d, resume=True))
    assert hits_of(rep) == hits_of(ref)
    assert rep.unused == ref.unused
    assert rep.totals["lines_matched"] == ref.totals["lines_matched"]


def test_torn_save_transient_burst_recovers_in_place(corpus, tmp_path):
    """TRANSIENT torn writes (``@2:2`` — two consecutive, below the
    checkpoint.save attempt bound): the retry engine re-writes into a
    fresh tmp dir, the run completes WITHOUT an abort, the report is
    bit-identical to the fault-free reference, and no .tmp- litter from
    the failed attempts survives (DESIGN §19)."""
    import os

    from ruleset_analysis_tpu.runtime import faults, retrypolicy

    packed, lines = corpus
    ref = run_stream(packed, iter(lines), make_cfg(tmp_path / "ref"))
    d = tmp_path / "ck"
    with faults.armed(faults.FaultPlan.parse("checkpoint.torn_state@2:2")):
        rep = run_stream(packed, iter(lines), make_cfg(d))
    assert hits_of(rep) == hits_of(ref)
    assert rep.unused == ref.unused
    c = retrypolicy.counters().get("checkpoint.save", {})
    assert c.get("recoveries", 0) >= 1, c
    leftovers = [e for e in os.listdir(d) if e.startswith(".tmp-")]
    assert not leftovers, leftovers
    # and the latest checkpoint is intact despite the torn attempts
    assert ckpt.load(str(d)) is not None


def test_resume_input_too_short_is_refused(corpus, tmp_path):
    from ruleset_analysis_tpu.errors import ResumeInputMismatch

    packed, lines = corpus
    d = tmp_path / "short"
    run_stream(packed, iter(lines), make_cfg(d), max_chunks=3)
    snap = ckpt.load(str(d))
    too_short = lines[: snap.lines_consumed - 10]
    with pytest.raises(ResumeInputMismatch, match="truncated"):
        run_stream(packed, iter(too_short), make_cfg(d, resume=True))


def test_corrupt_snapshot_refused_loudly(corpus, tmp_path):
    """Random corruption of any snapshot file (pointer, manifest, npz):
    load must raise the typed CheckpointCorrupt — never silently start
    fresh (losing the resume intent) nor leak BadZipFile/JSONDecodeError/
    UnicodeDecodeError (r5 fuzz: 231/300 trials crashed raw)."""
    import glob
    import random

    from ruleset_analysis_tpu.errors import AnalysisError

    packed, lines = corpus
    cfg = make_cfg(tmp_path / "ck", every=1)
    run_stream(packed, iter(lines), cfg, topk=5)
    files = [
        f
        for f in glob.glob(str(tmp_path / "ck" / "**" / "*"), recursive=True)
        if not __import__("os").path.isdir(f)
    ]
    assert files, "checkpoint must have been written"
    refused = silent_none = loaded = 0
    for trial in range(120):
        rng = random.Random(trial)
        target = rng.choice(files)
        with open(target, "rb") as f:
            orig = f.read()
        blob = bytearray(orig)
        if not blob:
            continue
        for _ in range(rng.randint(1, 6)):
            if rng.randrange(2) == 0:
                blob[rng.randrange(len(blob))] = rng.randrange(256)
            else:
                blob = bytearray(blob[: rng.randrange(len(blob))]) or bytearray(b"x")
        if bytes(blob) == orig:
            continue
        with open(target, "wb") as f:
            f.write(bytes(blob))
        try:
            snap = ckpt.load(str(tmp_path / "ck"))
            if snap is None:
                silent_none += 1  # the forbidden outcome
            else:
                loaded += 1  # benign (e.g. whitespace-only pointer change)
        except AnalysisError:
            refused += 1  # typed refusal: the contract
        finally:
            with open(target, "wb") as f:
                f.write(orig)
    assert refused > 0, "corruption must be detectable at least once"
    # the docstring's actual contract: corruption may refuse loudly or
    # (rarely) decode benignly, but NEVER silently report "no checkpoint"
    assert silent_none == 0, f"{silent_none} corruptions silently restarted"
