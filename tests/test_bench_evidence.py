"""bench.py fallback output must carry the newest banked TPU evidence.

VERDICT r4 missing #4: four rounds of driver artifacts were evidence-free
whenever the axon tunnel was down at bench time even though a committed
real-TPU capture existed in the repo.  The orchestrator now embeds that
capture as ``last_tpu``."""

import importlib.util
import os

HERE = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_bench():
    spec = importlib.util.spec_from_file_location("bench", os.path.join(HERE, "bench.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_last_tpu_evidence_reads_banked_headline():
    bench = _load_bench()
    ev = bench._last_tpu_evidence()
    assert ev is not None, "a committed TPU headline exists in this repo"
    assert ev["source"].startswith("BENCH_r0")
    assert ev["value"] > 1e6 and ev["unit"] == "lines/sec/chip"
    assert ev["vs_baseline"] > 1.0
    assert ev["checks"], "validation block must ride along"
    assert ev["commit"] and len(ev["commit"]["sha"]) == 40
