"""Convert fleet (ISSUE 11): sharded parallel convert == w=1 reference.

The fleet chops the corpus into exact-raw-line descriptors, assigns
contiguous descriptor ranges to worker processes, and coalesces
per-descriptor-batch — so the concatenated row stream, the manifest
accounting, and every downstream report are byte-identical for ANY
worker count.  w=1 is the pinned reference.
"""

import json
import os

import numpy as np
import pytest

pytest.importorskip("jax")

from ruleset_analysis_tpu.config import AnalysisConfig, SketchConfig
from ruleset_analysis_tpu.errors import FeedWorkerError
from ruleset_analysis_tpu.hostside import aclparse, fastparse, pack, synth, wire
from ruleset_analysis_tpu.hostside.convertfleet import (
    convert_logs_fleet,
    expand_wire_inputs,
    is_manifest_file,
    read_manifest,
)

pytestmark = pytest.mark.skipif(
    not fastparse.available(), reason="native parser not buildable here"
)


@pytest.fixture(scope="module")
def corpus(tmp_path_factory):
    td = tmp_path_factory.mktemp("fleet")
    cfg_text = synth.synth_config(
        n_acls=3, rules_per_acl=10, seed=61, egress_acls=True, v6_fraction=0.3
    )
    rs = aclparse.parse_asa_config(cfg_text, "fw1")
    packed = pack.pack_rulesets([rs])
    lines = synth.render_syslog(
        packed, synth.synth_tuples(packed, 3000, seed=62), seed=63, variety=0.4
    )
    lines += synth.render_syslog6(
        packed, synth.synth_tuples6(packed, 800, seed=64), seed=65, variety=0.3
    )
    import random

    random.Random(7).shuffle(lines)
    p1 = td / "a.log"
    p1.write_text("\n".join(lines[:2300]) + "\n", encoding="utf-8")
    p2 = td / "b.log"
    p2.write_text("\n".join(lines[2300:]) + "\n", encoding="utf-8")
    return packed, [str(p1), str(p2)], td


@pytest.fixture(scope="module")
def manifests(corpus):
    packed, paths, td = corpus
    s1 = convert_logs_fleet(
        packed, paths, str(td / "w1.rawire"), workers=1, batch_size=256
    )
    s3 = convert_logs_fleet(
        packed, paths, str(td / "w3.rawire"), workers=3, batch_size=256
    )
    return s1, s3, str(td / "w1.rawire"), str(td / "w3.rawire")


def _row_streams(packed, shard_paths):
    r = wire.WireReader(shard_paths, packed)
    v4 = [b[:, :n].copy() for b, n in r.iter_batches(0, 256)]
    v6 = [b[:, :n].copy() for b, n in r.iter_batches6(0, 256)]
    n_rows = (r.n_rows, r.n6_rows, r.raw_lines, r.n_evals, r.n_skipped)
    r.close()
    c4 = np.concatenate(v4, axis=1) if v4 else np.zeros((5, 0), np.uint32)
    c6 = np.concatenate(v6, axis=1) if v6 else np.zeros((11, 0), np.uint32)
    return c4, c6, n_rows


def test_fleet_row_stream_byte_identical_w1_vs_w3(corpus, manifests):
    packed, paths, td = corpus
    s1, s3, m1p, m3p = manifests
    assert is_manifest_file(m1p) and is_manifest_file(m3p)
    m1, m3 = read_manifest(m1p), read_manifest(m3p)
    a4, a6, atot = _row_streams(packed, m1["shard_paths"])
    b4, b6, btot = _row_streams(packed, m3["shard_paths"])
    assert np.array_equal(a4, b4)
    assert np.array_equal(a6, b6)
    assert atot == btot
    assert a6.shape[1] > 0  # the v6 plane is genuinely exercised
    # aggregate accounting identical, and the stream is pre-coalesced:
    # true evaluations exceed stored rows on this repetitive corpus
    for k in ("rows", "rows6", "raw_lines", "evals", "skipped"):
        assert s1[k] == s3[k], k
    assert m3["weighted"] and len(m3["shards"]) == 3
    assert s1["evals"] >= s1["rows"] + s1["rows6"]


def test_fleet_report_bit_identical_w1_vs_w3(corpus, manifests):
    from ruleset_analysis_tpu.runtime.stream import run_stream_wire

    packed, paths, td = corpus
    _s1, _s3, m1p, m3p = manifests
    cfg = AnalysisConfig(
        batch_size=256,
        sketch=SketchConfig(cms_width=1 << 11, cms_depth=4, hll_p=6),
    )
    reps = {}
    for name, mp in (("w1", m1p), ("w3", m3p)):
        rep = run_stream_wire(
            packed, read_manifest(mp)["shard_paths"], cfg
        )
        j = json.loads(rep.to_json())
        from ruleset_analysis_tpu.runtime.report import VOLATILE_TOTALS

        for k in VOLATILE_TOTALS:
            j["totals"].pop(k, None)
        reps[name] = j
    assert reps["w1"] == reps["w3"]


def test_fleet_registers_equal_text_run(corpus, manifests):
    """Registers from the pre-coalesced fleet output must equal the
    direct text parse (weight-linear/idempotent updates; the top-K
    candidate pool is chunk-boundary-sensitive and excluded, as
    documented for every re-chunked tier)."""
    from ruleset_analysis_tpu.runtime.stream import (
        run_stream_file,
        run_stream_wire,
    )

    packed, paths, td = corpus
    _s1, _s3, m1p, _m3p = manifests
    cfg = AnalysisConfig(
        batch_size=256,
        sketch=SketchConfig(cms_width=1 << 11, cms_depth=4, hll_p=6),
    )
    text = run_stream_file(packed, paths, cfg)
    fleet = run_stream_wire(packed, read_manifest(m1p)["shard_paths"], cfg)
    ht = {(e["firewall"], e["acl"], e["index"]): (e["hits"], e.get("unique_sources"))
          for e in text.per_rule}
    hf = {(e["firewall"], e["acl"], e["index"]): (e["hits"], e.get("unique_sources"))
          for e in fleet.per_rule}
    assert ht == hf
    assert text.unused == fleet.unused
    assert fleet.totals["lines_total"] == text.totals["lines_total"]
    assert fleet.totals["lines_matched"] == text.totals["lines_matched"]


def test_fleet_resume_in_stored_row_units(corpus, manifests, tmp_path):
    """Crash-at-K resume over the multi-shard fleet output: the resume
    cursor counts STORED (coalesced) rows across the shard list, and the
    finished resume equals an uninterrupted run bit-for-bit."""
    from ruleset_analysis_tpu.runtime.stream import run_stream_wire

    packed, paths, td = corpus
    _s1, _s3, _m1p, m3p = manifests
    shards = read_manifest(m3p)["shard_paths"]
    ck = str(tmp_path / "ck")
    cfg = AnalysisConfig(
        batch_size=256,
        sketch=SketchConfig(cms_width=1 << 11, cms_depth=4, hll_p=6),
        checkpoint_every_chunks=3,
        checkpoint_dir=ck,
    )
    run_stream_wire(packed, shards, cfg, max_chunks=5)
    rep = run_stream_wire(packed, shards, cfg.replace(resume=True))
    full = run_stream_wire(
        packed, shards, cfg.replace(checkpoint_every_chunks=0)
    )
    jr, jf = json.loads(rep.to_json()), json.loads(full.to_json())
    from ruleset_analysis_tpu.runtime.report import VOLATILE_TOTALS

    for j in (jr, jf):
        for k in VOLATILE_TOTALS:
            j["totals"].pop(k, None)
    assert jr == jf


def test_expand_wire_inputs_resolves_manifests(corpus, manifests):
    packed, paths, td = corpus
    _s1, _s3, m1p, m3p = manifests
    out = expand_wire_inputs([m3p, paths[0]])
    assert len(out) == 4  # 3 shards + the untouched text path
    assert out[3] == paths[0]
    assert all(wire.is_wire_file(p) for p in out[:3])


def test_fleet_worker_failure_leaves_no_manifest(corpus, tmp_path):
    """A failing worker aborts the whole convert: shards are removed (a
    torn one would carry the partial magic anyway) and the manifest is
    never written — no silently short corpus."""
    packed, paths, td = corpus
    out = str(tmp_path / "missing-dir" / "x.rawire")  # unwritable target
    with pytest.raises((FeedWorkerError, OSError)):
        convert_logs_fleet(packed, paths, out, workers=2, batch_size=256)
    assert not os.path.exists(out)
    assert not any(
        f.startswith("x.rawire") for f in (
            os.listdir(tmp_path / "missing-dir")
            if os.path.isdir(tmp_path / "missing-dir") else []
        )
    )


def test_fleet_single_worker_output_is_complete_wire(corpus, manifests):
    """Each shard is a complete, self-validating RAWIREv3 file — a
    shard list survives wire-info style inspection one file at a time."""
    packed, paths, td = corpus
    _s1, _s3, m1p, _m3p = manifests
    m1 = read_manifest(m1p)
    for sp in m1["shard_paths"]:
        r = wire.WireReader([sp], packed)
        assert r.weighted
        r.close()
